"""Benchmark rig: Nexmark pipelines on the real chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ...,
"vs_baseline": N, "q7": {...}, "q8": {...}, "q3": {...}, "q5": {...},
"q1": {...}} — the driver records it in BENCH_r{N}.json. All five
queries ride the single captured line; the headline value/vs_baseline
is q7 (the stateful device-kernel path, measured in steady state with
watermark window retirement ON). `--quick` runs q7 only.

Baseline (BASELINE.md): ≥1M events/sec/chip on Nexmark q7/q8 (one v5e).
Pipelines come from risingwave_tpu.models.nexmark — the benchmarked
plan is exactly the tested plan (tests/test_e2e_q*.py).
"""

from __future__ import annotations

import asyncio
import json
import sys

BASELINE_EVENTS_PER_SEC = 1_000_000.0
IN_FLIGHT = 2          # barrier pipelining window used by every bench


def _result(metric, elapsed, rows, loop):
    return {
        "metric": metric,
        "value": round(rows / elapsed, 1),
        "unit": "events/s",
        # inject→commit INCLUDING queueing behind in-flight barriers
        # (compare like with like across rounds)
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "barrier_in_flight": IN_FLIGHT,
        "events": rows,
    }


def bench_q1(total_events: int = 50 * 4000, chunk_size: int = 4096):
    """q1: source → project → materialize (stateless reference path)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q1, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size)
    p = build_q1(MemoryStateStore(), cfg, rate_limit=16, min_chunks=16)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q1_events_per_sec", elapsed, rows, p.loop)


def bench_q7(total_events: int = 50 * 40_000, chunk_size: int = 8192):
    """q7 core: tumble-window MAX(price) on the device hash-agg kernel.

    The stateful baseline config (BASELINE.md: HashAgg on TPU, ≥1M
    events/s/chip). Measured in STEADY STATE: watermark-driven window
    retirement is ON, so the number reflects bounded state, not a
    forever-growing table (VERDICT r2 weak #2)."""
    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=32, min_chunks=32,
                 watermark_delay=Interval(usecs=0))
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q7_events_per_sec", elapsed, rows, p.loop)


def bench_q5(total_events: int = 50 * 8_000, chunk_size: int = 4096):
    """q5 (hot items): hop windows + per-window group top-n."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q5, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    p = build_q5(MemoryStateStore(), cfg, rate_limit=16, min_chunks=16)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q5_events_per_sec", elapsed, rows, p.loop)


def bench_q8(total_events: int = 50 * 40_000, chunk_size: int = 4096):
    """q8: windowed person⋈auction inner join on the device matcher.

    Throughput counts rows entering the pipeline (persons + auctions)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q8, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    base = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                         generate_strings=False)
    cfg_p = NexmarkConfig(**{**base.__dict__, "table_type": "person"})
    cfg_a = NexmarkConfig(**{**base.__dict__, "table_type": "auction"})
    p = build_q8(MemoryStateStore(), cfg_p, cfg_a, rate_limit=16,
                 min_chunks=16)
    targets = {1: total_events // 50, 2: total_events * 3 // 50}
    elapsed, rows = asyncio.run(drive_to_completion(
        p, targets, in_flight=IN_FLIGHT))
    return _result("nexmark_q8_events_per_sec", elapsed, rows, p.loop)


def bench_q3(customers: int = 1500, orders: int = 15000):
    """TPC-H q3 streaming: 3-way join → agg → top-10 (BASELINE config).

    Throughput counts rows entering across all three tables."""
    from risingwave_tpu.connectors.tpch import LINES_PER_ORDER
    from risingwave_tpu.models.nexmark import drive_to_completion
    from risingwave_tpu.models.tpch import build_q3
    from risingwave_tpu.state.store import MemoryStateStore

    p = build_q3(MemoryStateStore(), customers=customers, orders=orders,
                 rate_limit=16, min_chunks=16)
    targets = {1: customers, 2: orders, 3: orders * LINES_PER_ORDER}
    elapsed, rows = asyncio.run(drive_to_completion(
        p, targets, in_flight=IN_FLIGHT))
    return _result("tpch_q3_events_per_sec", elapsed, rows, p.loop)


def _probe_device(timeout_s: int = 180, attempts: int = 2) -> None:
    """Fail over to CPU if the TPU backend cannot initialize.

    The axon tunnel can wedge (a killed client's remote claim takes
    time to expire); jax backend init then blocks with no timeout and
    the whole bench run would hang. Probe in a subprocess first with
    retries (a wedged claim usually expires within minutes — VERDICT r2
    lost the round's TPU number to a single-shot probe); only after all
    attempts fail, force this process onto the CPU backend so the bench
    still reports a (clearly-labeled) number instead of nothing."""
    import os
    import subprocess
    import time
    for i in range(attempts):
        try:
            subprocess.run(
                [sys.executable, "-c", "import jax; jax.devices()"],
                timeout=timeout_s, capture_output=True, check=True)
            return
        except (subprocess.SubprocessError, OSError):
            print(f"WARNING: device probe {i + 1}/{attempts} failed",
                  file=sys.stderr)
            if i + 1 < attempts:
                time.sleep(30)
    print("WARNING: device backend unreachable — benching on CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")


def main(argv):
    import contextlib
    import os
    from risingwave_tpu.utils.tpulock import ChipBusy, chip_lock
    # Chip discipline (VERDICT r3): hold the exclusive chip lock for
    # the WHOLE run (probe included — the probe subprocess is itself a
    # TPU client). Two concurrent clients wedge the tunnel for minutes.
    lock = contextlib.nullcontext() \
        if os.environ.get("JAX_PLATFORMS") == "cpu" else chip_lock()
    try:
        lock.__enter__()
    except ChipBusy as e:
        print(f"WARNING: {e} — benching on CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        lock = contextlib.nullcontext()
        lock.__enter__()
    try:
        _main_locked(argv)
    finally:
        lock.__exit__(None, None, None)


def _main_locked(argv):
    from risingwave_tpu.utils.jaxtools import enable_compilation_cache
    _probe_device()
    enable_compilation_cache()
    import jax
    platform = jax.devices()[0].platform
    quick = "--quick" in argv
    # Every query lands in the ONE captured headline line (VERDICT r2:
    # stderr tables are not recorded by the driver). Per-query isolation:
    # one query failing must not cost the others their numbers.
    # Each query runs a small WARMUP first (criterion-style): the first
    # run traces/compiles every (shape) program — on a fresh process
    # that fixed cost would otherwise be reported as throughput.
    benches = [("q7", bench_q7, {"total_events": 50 * 4000}),
               ("q8", bench_q8, {"total_events": 50 * 4000}),
               ("q3", bench_q3, {"orders": 1500}),
               ("q5", bench_q5, {"total_events": 50 * 1000}),
               ("q1", bench_q1, {"total_events": 50 * 400})]
    if quick:
        benches = benches[:1]
    headline = {}
    for name, fn, warm_kw in benches:
        try:
            fn(**warm_kw)                            # warmup (traced)
            r = fn()
            headline[name] = {k: r[k] for k in
                              ("value", "p99_barrier_latency_s",
                               "barrier_in_flight", "events")}
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: {name} failed: {e!r}", file=sys.stderr)
            headline[name] = {"error": repr(e)[:200]}
    q7 = headline.get("q7", {})
    ok = "value" in q7
    headline.update({
        "metric": "nexmark_q7_events_per_sec",
        # null, not 0.0, when q7 failed: a fabricated zero reads as a
        # measured catastrophic regression in round-over-round diffs
        "value": q7["value"] if ok else None,
        "unit": "events/s",
        "vs_baseline": round(q7["value"] / BASELINE_EVENTS_PER_SEC, 4)
        if ok else None,
        "platform": platform,
    })
    print(json.dumps(headline))


if __name__ == "__main__":
    main(sys.argv[1:])
