"""Benchmark rig: Nexmark pipelines on the real chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ...,
"vs_baseline": N, "q7": {...}, "q8": {...}, "q3": {...}, "q5": {...},
"q1": {...}} — the driver records it in BENCH_r{N}.json. All five
queries ride the single captured line; the headline value/vs_baseline
is q7 (the stateful device-kernel path, measured in steady state with
watermark window retirement ON). `--quick` runs q7 only.

Baseline (BASELINE.md): ≥1M events/sec/chip on Nexmark q7/q8 (one v5e).
Pipelines come from risingwave_tpu.models.nexmark — the benchmarked
plan is exactly the tested plan (tests/test_e2e_q*.py).
"""

from __future__ import annotations

import asyncio
import json
import os.path
import sys

BASELINE_EVENTS_PER_SEC = 1_000_000.0
IN_FLIGHT = 2          # barrier pipelining window used by every bench

# Device-probe outcome log, persisted ACROSS rounds (VERDICT r5 weak
# #1): when a round's numbers collapse, this file distinguishes
# "tunnel wedged" (probe failures with timestamps) from "kernels
# broken" (probe fine, smoke/bench failed).
PROBE_LOG_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_probe_log.json")
PROBE_LOG_KEEP = 200


def _log_probe(entry: dict) -> None:
    """Append one probe/smoke outcome to BENCH_probe_log.json (bounded
    to the last PROBE_LOG_KEEP entries; best-effort — logging must
    never fail a bench run)."""
    import datetime
    import os
    entry = {"ts": datetime.datetime.now(
        datetime.timezone.utc).isoformat(timespec="seconds"), **entry}
    try:
        log = []
        if os.path.exists(PROBE_LOG_PATH):
            with open(PROBE_LOG_PATH) as f:
                log = json.load(f)
        log.append(entry)
        log = log[-PROBE_LOG_KEEP:]
        tmp = PROBE_LOG_PATH + ".tmp"
        with open(tmp, "w") as f:
            json.dump(log, f, indent=1)
        os.replace(tmp, PROBE_LOG_PATH)
    except Exception as e:                           # noqa: BLE001
        print(f"WARNING: probe log write failed: {e!r}",
              file=sys.stderr)


def _metrics_snapshot(loop) -> dict:
    """Registry snapshot riding along in every bench line: p99 barrier
    breakdown, back-pressure and throughput totals, block-cache
    traffic — BENCH_*.json carries the observability trajectory."""
    from risingwave_tpu.utils.metrics import STORAGE, STREAMING
    b = loop.profiler.p99_breakdown()
    # device-dispatch amortization (stream/coalesce.py): dispatch
    # counts and rows-per-dispatch sit NEXT TO events/sec so a round
    # diff shows the batching effect directly
    dispatches = int(sum(v for _l, v in
                         STREAMING.device_dispatch.series()))
    disp_rows = sum(s for _l, _n, s in
                    STREAMING.rows_per_dispatch.series())
    co_in = int(sum(v for _l, v in
                    STREAMING.coalesce_chunks_in.series()))
    co_out = int(sum(v for _l, v in
                     STREAMING.coalesce_chunks_out.series()))
    rewrites = int(sum(v for _l, v in
                       STREAMING.rewrite_rule_fired.series()))
    tier_evicted = int(sum(v for _l, v in
                           STREAMING.state_tier_evicted.series()))
    tier_reloads = int(sum(v for _l, v in
                           STREAMING.state_tier_reloads.series()))
    return {
        # state-tiering activity (state/tier.py): nonzero here with a
        # cap above the working set would explain a throughput diff
        "state_tier_evicted": tier_evicted,
        "state_tier_reloads": tier_reloads,
        # jitted-kernel (re)traces over the WHOLE run (warmup compiles
        # included); a steady-state-only growth between rounds is a
        # shape-churn regression — the conftest guard's bench-side twin
        "kernel_recompiles": int(sum(
            v for _l, v in STREAMING.kernel_recompile.series())),
        "device_dispatches": dispatches,
        "rows_per_dispatch_avg": round(disp_rows / dispatches, 1)
        if dispatches else 0.0,
        # plan-rewrite engine (frontend/opt): what the optimizer did
        # to this run's plans, next to what the run then measured
        "rewrite_rules_fired": rewrites,
        "plan_columns_pruned": int(sum(
            v for _l, v in STREAMING.plan_columns_pruned.series())),
        "plan_exchanges_elided": int(sum(
            v for _l, v in
            STREAMING.plan_exchanges_elided.series())),
        # join payload residency (ISSUE 9): which half of the join's
        # stored rows lives in HBM lanes vs the host arena — the
        # auditable half of "ship refs, not rows"
        "join_payload_device_bytes": int(sum(
            v for _l, v in STREAMING.join_device_bytes.series())),
        "join_payload_host_bytes": int(sum(
            v for _l, v in STREAMING.join_host_bytes.series())),
        "coalesce_chunks_in": co_in,
        "coalesce_chunks_out": co_out,
        "compaction_rows_saved": int(sum(
            v for _l, v in
            STREAMING.compaction_rows_saved.series())),
        # epoch phase ledger transfer totals (exact payload bytes over
        # the run — the auditable halves of h2d/d2h)
        "transfer_h2d_bytes": int(sum(
            v for l, v in STREAMING.transfer_bytes.series()
            if l.get("dir") == "h2d")),
        "transfer_d2h_bytes": int(sum(
            v for l, v in STREAMING.transfer_bytes.series()
            if l.get("dir") == "d2h")),
        "p99_inject_to_collect_s": round(b["inject_to_collect_s"], 5),
        "p99_collect_to_commit_s": round(b["collect_to_commit_s"], 5),
        # the async checkpoint tail (seal→durable commit), overlapped
        # with younger barriers — NOT part of barrier latency
        "p99_upload_s": round(b["upload_s"], 5),
        "exchange_backpressure_s": round(
            sum(v for _l, v in
                STREAMING.exchange_backpressure.series()), 5),
        # sender-side credit park time (ISSUE 14): the half of
        # exchange backpressure now subtracted from executor busy
        "backpressure_wait_s": round(
            sum(v for _l, v in
                STREAMING.backpressure_wait.series()), 5),
        "executor_rows": int(
            sum(v for _l, v in STREAMING.executor_rows.series())),
        "executor_busy_s": round(
            sum(v for _l, v in STREAMING.executor_busy.series()), 4),
        "block_cache_hits": int(STORAGE.block_cache_hits.get()),
        "block_cache_misses": int(STORAGE.block_cache_misses.get()),
        "sst_upload_bytes": int(
            sum(v for _l, v in STORAGE.sst_upload_bytes.series())),
    }


def _result(metric, elapsed, rows, loop, plan=None):
    from risingwave_tpu.stream.bottleneck import BOTTLENECKS
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.utils.ledger import LEDGER

    # per-lane platform from the LIVE backend (never a literal): a
    # future GPU/TPU lane can't accidentally report "cpu", and a
    # CPU-fallback lane can't masquerade as the device
    import jax
    out = {
        "metric": metric,
        "value": round(rows / elapsed, 1),
        "unit": "events/s",
        "platform": jax.devices()[0].platform,
        # inject→commit INCLUDING queueing behind in-flight barriers
        # (compare like with like across rounds)
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "barrier_in_flight": IN_FLIGHT,
        "events": rows,
        "observability": _metrics_snapshot(loop),
        # epoch phase ledger: how the run's barrier intervals split
        # across host/device phases (steady epochs only — warmup
        # compiles are marked and excluded), with conservation
        # coverage and exact transfer bytes
        "phase_breakdown": LEDGER.phase_breakdown(),
        # per-MV event-time freshness (ISSUE 14): per-barrier lag
        # percentiles over the measured run — what a reader of the MV
        # experienced, next to what the pipeline cost
        "freshness": FRESHNESS.summary(),
        # bottleneck walker verdict at end of run: the operator each
        # domain's capacity change should target, with its streak and
        # the ledger cross-check baked into the diagnosis
        "bottleneck": BOTTLENECKS.summary(),
    }
    if plan is not None:
        out["plan"] = plan
    return out


def _session_plan_stats(fe) -> dict:
    """Deployed-plan stats of a Frontend session: executor count and
    carried lane widths summed over every live actor chain (the
    rewrite engine's narrowing shows up here, next to events/sec)."""
    from risingwave_tpu.frontend.opt import plan_lane_stats
    agg = {"executors": 0, "total_lanes": 0, "max_lane_width": 0}
    for actor in fe.actors.values():
        s = plan_lane_stats(actor.consumer)
        agg["executors"] += s["executors"]
        agg["total_lanes"] += s["total_lanes"]
        agg["max_lane_width"] = max(agg["max_lane_width"],
                                    s["max_lane_width"])
    agg["avg_lane_width"] = round(
        agg["total_lanes"] / agg["executors"], 2) \
        if agg["executors"] else 0.0
    # in-process exchange hops = MV-on-MV chain edges (distributed
    # graphs report theirs via DistFrontend.last_plan_stats)
    agg["exchange_hops"] = sum(len(v) for v in fe.chain_edges.values())
    return agg


def bench_q1(total_events: int = 50 * 4000, chunk_size: int = 4096):
    """q1: source → project → materialize (stateless reference path)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q1, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size)
    p = build_q1(MemoryStateStore(), cfg, rate_limit=16, min_chunks=16)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q1_events_per_sec", elapsed, rows, p.loop)


def bench_q7(total_events: int = 50 * 40_000, chunk_size: int = 8192,
             fusion: bool = False, ledger: bool = True,
             tricolor: bool = True, costs: bool = True):
    """q7 core: tumble-window MAX(price) on the device hash-agg kernel.

    The stateful baseline config (BASELINE.md: HashAgg on TPU, ≥1M
    events/s/chip). Measured in STEADY STATE: watermark-driven window
    retirement is ON, so the number reflects bounded state, not a
    forever-growing table (VERDICT r2 weak #2). ``ledger=False`` is
    the phase-ledger-off arm (ISSUE 11 acceptance: ledger-on
    throughput within 5% of ledger-off on q7 CPU); ``tricolor=False``
    is the utilization-tricolor/freshness-off arm (ISSUE 14: on-vs-off
    within 5%); ``costs=False`` is the cost/skew-attribution-off arm
    (ISSUE 16: per-MV rollup, topology upkeep and hot-key sketches
    reduced to predicate checks) — each query runs in its own
    subprocess, so the toggles never leak across lanes."""
    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream import costs as costs_mod
    from risingwave_tpu.stream import freshness as freshness_mod
    from risingwave_tpu.stream import monitor as monitor_mod
    from risingwave_tpu.utils import ledger as ledger_mod

    ledger_mod.set_enabled(ledger)
    monitor_mod.set_tricolor(tricolor)
    freshness_mod.set_enabled(tricolor)
    costs_mod.set_enabled(costs)
    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=32, min_chunks=32,
                 watermark_delay=Interval(usecs=0), fusion=fusion)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q7_events_per_sec", elapsed, rows, p.loop)


def bench_q5(total_events: int = 50 * 8_000, chunk_size: int = 4096,
             fusion: bool = False):
    """q5 (hot items): hop windows + per-window group top-n."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q5, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    p = build_q5(MemoryStateStore(), cfg, rate_limit=16, min_chunks=16,
                 fusion=fusion)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(
        p, {1: n_bids}, in_flight=IN_FLIGHT))
    return _result("nexmark_q5_events_per_sec", elapsed, rows, p.loop)


def bench_q8(total_events: int = 50 * 40_000, chunk_size: int = 4096,
             fusion: bool = False):
    """q8: windowed person⋈auction inner join on the device matcher.

    Throughput counts rows entering the pipeline (persons + auctions)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q8, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    base = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                         generate_strings=False)
    cfg_p = NexmarkConfig(**{**base.__dict__, "table_type": "person"})
    cfg_a = NexmarkConfig(**{**base.__dict__, "table_type": "auction"})
    p = build_q8(MemoryStateStore(), cfg_p, cfg_a, rate_limit=16,
                 min_chunks=16, fusion=fusion)
    targets = {1: total_events // 50, 2: total_events * 3 // 50}
    elapsed, rows = asyncio.run(drive_to_completion(
        p, targets, in_flight=IN_FLIGHT))
    return _result("nexmark_q8_events_per_sec", elapsed, rows, p.loop)


def bench_q3(customers: int = 1500, orders: int = 15000,
             fusion: bool = False):
    """TPC-H q3 streaming: 3-way join → agg → top-10 (BASELINE config).

    Throughput counts rows entering across all three tables."""
    from risingwave_tpu.connectors.tpch import LINES_PER_ORDER
    from risingwave_tpu.models.nexmark import drive_to_completion
    from risingwave_tpu.models.tpch import build_q3
    from risingwave_tpu.state.store import MemoryStateStore

    p = build_q3(MemoryStateStore(), customers=customers, orders=orders,
                 rate_limit=16, min_chunks=16, fusion=fusion)
    targets = {1: customers, 2: orders, 3: orders * LINES_PER_ORDER}
    elapsed, rows = asyncio.run(drive_to_completion(
        p, targets, in_flight=IN_FLIGHT))
    return _result("tpch_q3_events_per_sec", elapsed, rows, p.loop)


async def _drive_frontend(fe, expected_total: int, in_flight: int,
                          max_epochs: int = 500):
    """Pipelined barrier driver over a Frontend session (same
    in-flight discipline as drive_to_completion, measured after a
    one-epoch warmup). Returns (elapsed_s, rows)."""
    import time

    await fe.step(1)                         # warmup (traces compile)
    readers = [r for d in fe.readers.values() for r in d.values()]

    def rows_seen() -> int:
        # filelog readers count rows explicitly (offset is bytes);
        # generator readers' offset IS the row ordinal
        return sum(r.rows_read if hasattr(r, "rows_read") else r.offset
                   for r in readers)

    warm = rows_seen()
    if warm >= expected_total:
        raise ValueError(
            f"bench scale too small: warmup consumed all "
            f"{expected_total} rows — raise total_events")
    warm_epochs = len(fe.loop.stats.latencies_s)
    loop = fe.loop
    t0 = time.perf_counter()
    injected = 0
    while rows_seen() < expected_total:
        if injected >= max_epochs:
            raise RuntimeError(
                f"sources stalled at {rows_seen()}/{expected_total}")
        while loop.in_flight_count < in_flight:
            await loop.inject()
            injected += 1
        await loop.collect_next()
    while loop.in_flight_count:
        await loop.collect_next()
    elapsed = time.perf_counter() - t0
    rows = rows_seen() - warm
    loop.stats.latencies_s = loop.stats.latencies_s[warm_epochs:]
    loop.profiler.drop_first(warm_epochs)
    return elapsed, rows


def bench_q4(total_events: int = 50 * 4000, chunk_size: int = 4096):
    """Nexmark q4 (named baseline config): AVG of per-auction MAX bid
    price per category — agg over join over a FROM-subquery, the full
    SQL front-door path (e2e_test/streaming/nexmark/views/q4.slt.part).
    Throughput counts rows entering (auctions + bids)."""
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(rate_limit=16, min_chunks=16)
        for t in ("auction", "bid"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', "
                f"nexmark.event.num={total_events}, "
                f"nexmark.max.chunk.size={chunk_size}, "
                f"nexmark.generate.strings='false')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q4 AS "
            "SELECT category, AVG(final) AS avg_final FROM ("
            "  SELECT a.category AS category, MAX(b.price) AS final"
            "  FROM auction AS a JOIN bid AS b ON a.id = b.auction"
            "  WHERE b.date_time BETWEEN a.date_time AND a.expires"
            "  GROUP BY a.id, a.category) AS q "
            "GROUP BY category")
        expected = total_events * 3 // 50 + total_events * 46 // 50
        plan = _session_plan_stats(fe)
        elapsed, rows = await _drive_frontend(fe, expected, IN_FLIGHT)
        stats = fe.loop
        await fe.close()
        return elapsed, rows, stats, plan

    elapsed, rows, loop, plan = asyncio.run(run())
    return _result("nexmark_q4_events_per_sec", elapsed, rows, loop,
                   plan=plan)


def _adctr_produce(path: str, n_impressions: int, n_ads: int = 100):
    """Filelog topics standing in for the ad-ctr demo's Kafka topics."""
    import json as _json
    import os

    import numpy as np
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(42)
    ads = rng.integers(0, n_ads, n_impressions)
    base = 1_700_000_000_000_000
    with open(os.path.join(path, "impressions-0.log"), "wb") as f:
        for i in range(n_impressions):
            f.write(_json.dumps({
                "bid_id": i, "ad_id": int(ads[i]),
                "its": base + i * 10_000}).encode() + b"\n")
    with open(os.path.join(path, "clicks-0.log"), "wb") as f:
        for i in range(0, n_impressions, 3):
            f.write(_json.dumps({
                "cbid": i, "cts": base + i * 10_000 + 500}).encode()
                + b"\n")


def _adctr_ddl(path: str) -> list:
    """The ad-ctr pipeline's DDL (shared by the adctr and multimv
    lanes — one source of truth for the 3-MV shape)."""
    return [
        f"CREATE SOURCE impression (bid_id BIGINT, ad_id BIGINT, "
        f"its TIMESTAMP) WITH (connector='filelog', "
        f"path='{path}', topic='impressions', "
        f"max.chunk.size=4096)",
        f"CREATE SOURCE click (cbid BIGINT, cts TIMESTAMP) WITH "
        f"(connector='filelog', path='{path}', topic='clicks', "
        f"max.chunk.size=4096)",
        "CREATE MATERIALIZED VIEW ad_dim AS SELECT ad_id, "
        "count(*) AS seen FROM impression GROUP BY ad_id",
        "CREATE MATERIALIZED VIEW ad_ctr AS SELECT i.ad_id, "
        "i.window_start, count(*) AS clicked "
        "FROM HOP(impression, its, INTERVAL '2' SECOND, "
        "INTERVAL '10' SECOND) AS i "
        "JOIN click AS c ON i.bid_id = c.cbid "
        "JOIN ad_dim AS d FOR SYSTEM_TIME AS OF PROCTIME() "
        "ON i.ad_id = d.ad_id "
        "GROUP BY i.ad_id, i.window_start",
    ]


def bench_adctr(n_impressions: int = 200_000, parallelism: int = 4):
    """ad-ctr (named baseline config #5): sources → HOP windows →
    2-way join + temporal dim join → sliding-window agg at actor
    parallelism 4 (integration_tests/ad-ctr analog). Runs on whatever
    mesh the current process exposes — the driver launches this in a
    4-device virtual-mesh subprocess when the chip count is 1."""
    import tempfile

    from risingwave_tpu.frontend.session import Frontend

    async def run(path):
        fe = Frontend(rate_limit=8, min_chunks=8,
                      parallelism=parallelism)
        for sql in _adctr_ddl(path):
            await fe.execute(sql)
        # ad_dim consumes impressions too: expected totals count every
        # reader the session drives
        expected = 2 * n_impressions + (n_impressions + 2) // 3
        plan = _session_plan_stats(fe)
        elapsed, rows = await _drive_frontend(fe, expected, IN_FLIGHT)
        stats = fe.loop
        await fe.close()
        return elapsed, rows, stats, plan

    with tempfile.TemporaryDirectory() as path:
        _adctr_produce(path, n_impressions)
        elapsed, rows, loop, plan = asyncio.run(run(path))
    r = _result("adctr_events_per_sec", elapsed, rows, loop,
                plan=plan)
    import jax
    r["parallelism"] = min(parallelism, len(jax.devices()))
    return r


def bench_multimv(n_impressions: int = 120_000,
                  neighbor_events: int = 50 * 8_000) -> dict:
    """Multi-MV barrier-domain lane (ISSUE 13): the ad-ctr pipeline
    (impression/click sources → dim MV → hop/join/agg MV — ONE
    connected domain via the shared impression source) next to a
    q7-shaped neighbor MV on its own nexmark source, in ONE session.
    With stream_epoch_pipeline=on each domain's barriers flow
    independently: the neighbor's p99 stays sub-second while the
    ad-ctr domain alone carries the tail — the per-domain breakdown
    IS the measurement. Driven by the plane's per-domain pump (every
    domain keeps its own in-flight window full)."""
    import tempfile
    import time as _time

    from risingwave_tpu.frontend.session import Frontend

    async def run(path):
        fe = Frontend(rate_limit=8, min_chunks=8)
        for sql in _adctr_ddl(path):
            await fe.execute(sql)
        await fe.execute(
            f"CREATE SOURCE bid WITH (connector='nexmark', "
            f"nexmark.table.type='bid', "
            f"nexmark.event.num={neighbor_events}, "
            f"nexmark.max.chunk.size=4096, "
            f"nexmark.generate.strings='false')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q7_neighbor AS "
            "SELECT window_start, MAX(price) AS max_price, "
            "COUNT(*) AS cnt "
            "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
            "GROUP BY window_start")
        expected = (2 * n_impressions + (n_impressions + 2) // 3
                    + neighbor_events * 46 // 50)
        await fe.step(1)                   # warmup (traces compile)
        readers = [r for d in fe.readers.values()
                   for r in d.values()]

        def rows_seen() -> int:
            return sum(r.rows_read if hasattr(r, "rows_read")
                       else r.offset for r in readers)

        warm = rows_seen()
        warm_epochs = len(fe.loop.stats.latencies_s)
        t0 = _time.perf_counter()
        await fe.loop.drive(lambda: rows_seen() >= expected,
                            in_flight=IN_FLIGHT,
                            progress_fn=rows_seen)
        elapsed = _time.perf_counter() - t0
        rows = rows_seen() - warm
        fe.loop.stats.latencies_s = \
            fe.loop.stats.latencies_s[warm_epochs:]
        fe.loop.profiler.drop_first(warm_epochs)
        by_domain = fe.loop.p99_by_domain()
        domains = fe.loop.describe()
        # marginal-cost snapshot (ISSUE 16): captured BEFORE close —
        # close purges each dropped MV's cost/topology series, which
        # is exactly the lifecycle the attribution surface promises
        from risingwave_tpu.state.topology import TOPOLOGY
        from risingwave_tpu.stream.costs import COSTS
        marginal = COSTS.summary()
        imbalance = TOPOLOGY.imbalance_by_mv()
        topo_by_mv = TOPOLOGY.bytes_by_mv()
        att_dev, led_dev = COSTS.coverage()
        await fe.close()
        return (elapsed, rows, fe.loop, by_domain, domains,
                marginal, imbalance, topo_by_mv, att_dev, led_dev)

    with tempfile.TemporaryDirectory() as path:
        _adctr_produce(path, n_impressions)
        (elapsed, rows, loop, by_domain, domains, marginal,
         imbalance, topo_by_mv, att_dev, led_dev) = \
            asyncio.run(run(path))
    r = _result("multimv_events_per_sec", elapsed, rows, loop)
    from risingwave_tpu.utils.ledger import LEDGER
    r["by_domain"] = {
        dom: {"p99_s": round(p99, 4),
              "phase_breakdown": LEDGER.phase_breakdown(domain=dom)}
        for dom, p99 in sorted(by_domain.items())}
    r["domains"] = domains
    # per-MV serving-cost rollup + attribution coverage: the split
    # must account (nearly) all ledgered device time and all state
    # bytes to a NAMED MV — unattributed cost is the failure mode
    mv_state = sum(b for mv, b in topo_by_mv.items() if mv)
    topo_state = sum(topo_by_mv.values())
    r["marginal_cost"] = {
        "by_mv": {mv: {"device_s": round(d.get("device_s", 0.0), 6),
                       "state_bytes": int(d.get("state_bytes", 0)),
                       "h2d_bytes": int(d.get("h2d_bytes", 0)),
                       "d2h_bytes": int(d.get("d2h_bytes", 0)),
                       "compile_hits": int(d.get("compile_hits", 0)),
                       "compile_misses":
                           int(d.get("compile_misses", 0)),
                       "shared_compile_hits":
                           int(d.get("shared_hits", 0)),
                       "hot_vnode_imbalance":
                           round(imbalance.get(mv, 1.0), 3)}
                 for mv, d in sorted(marginal.items())},
        # both sides summed over the SAME sealed-epoch window
        # (COSTS.coverage) — cumulative totals vs the ledger's bounded
        # record deque would inflate past 1.0 as records age out
        "ledgered_device_compute_s": round(led_dev, 6),
        "attributed_device_s": round(att_dev, 6),
        "device_coverage": round(att_dev / led_dev, 4)
        if led_dev > 0 else None,
        "attributed_state_bytes": int(mv_state),
        # acceptance: >= 95% of ledgered device_compute and state
        # bytes land on a named MV
        "coverage_ok": (led_dev > 0
                        and att_dev >= 0.95 * led_dev
                        and mv_state >= 0.95 * topo_state),
    }
    # the acceptance proof: every domain EXCEPT the ad-ctr one keeps
    # a sub-second p99 — a slow fragment holds only its own domain
    fast = {d: v["p99_s"] for d, v in r["by_domain"].items()
            if d not in ("ad_dim", "ad_ctr")}
    r["fast_domains_p99_max_s"] = max(fast.values(), default=None)
    r["fast_domains_sub_second"] = all(v <= 1.0
                                       for v in fast.values())
    return r


def _bench_multimv_subprocess() -> dict:
    """Multi-MV domain lane in a CPU-pinned subprocess (domain
    isolation is the subject; the virtual mesh lives in the adctr
    lane)."""
    return _run_bench_subprocess(
        ["--multimv-sub"],
        {"JAX_PLATFORMS": "cpu"}, timeout=1500)


def _bench_adctr_subprocess() -> dict:
    """Run the ad-ctr config in a 4-virtual-device CPU-mesh subprocess
    (BASELINE config #5 is 4-chip; with one real chip the mesh is
    virtual — the result is labeled accordingly)."""
    return _run_bench_subprocess(
        ["--adctr-sub"],
        {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=4"},
        timeout=1200)


def bench_q7_mesh(total_events: int = 50 * 8_000,
                  parallelism: int = 8):
    """Sharded mesh lane (ISSUE 10 satellite): nexmark q7 through the
    SQL front door at parallelism 8 — the GROUP BY runs on the
    vnode-sharded SPMD kernel with per-EPOCH batched dispatches, so
    BENCH_r*.json carries mesh-parallel throughput and p99 in the
    trajectory, not just the multichip dry-run's correctness gate
    (ROADMAP item 2 tail)."""
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(rate_limit=16, min_chunks=16,
                      parallelism=parallelism)
        await fe.execute(
            f"CREATE SOURCE bid WITH (connector='nexmark', "
            f"nexmark.table.type='bid', "
            f"nexmark.event.num={total_events}, "
            f"nexmark.max.chunk.size=4096, "
            f"nexmark.generate.strings='false')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q7_mesh AS "
            "SELECT window_start, MAX(price) AS max_price, "
            "COUNT(*) AS cnt "
            "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
            "GROUP BY window_start")
        expected = total_events * 46 // 50
        plan = _session_plan_stats(fe)
        elapsed, rows = await _drive_frontend(fe, expected, IN_FLIGHT)
        stats = fe.loop
        await fe.close()
        return elapsed, rows, stats, plan

    elapsed, rows, loop, plan = asyncio.run(run())
    r = _result("nexmark_q7_mesh_events_per_sec", elapsed, rows, loop,
                plan=plan)
    import jax
    r["parallelism"] = min(parallelism, len(jax.devices()))
    return r


def _bench_q7_mesh_subprocess() -> dict:
    """q7 on the 8-virtual-device CPU mesh in a subprocess (clearly
    labeled: one real chip ⇒ the mesh is virtual)."""
    return _run_bench_subprocess(
        ["--mesh-sub"],
        {"JAX_PLATFORMS": "cpu",
         "XLA_FLAGS": "--xla_force_host_platform_device_count=8"},
        timeout=1500)


def _probe_device(timeout_s: int = 180, attempts: int = 2) -> str:
    """Probe the device backend IN A SUBPROCESS and return the platform.

    The axon tunnel can wedge (a killed client's remote claim takes
    time to expire); jax backend init then blocks with no timeout and
    the whole bench run would hang. The PARENT never initializes a
    device client itself — each per-query child owns the chip in turn
    (a parent client alive alongside a child client is exactly the
    two-concurrent-clients condition that wedges the tunnel). On probe
    failure, force CPU in the env so every child inherits it."""
    import os
    import subprocess
    import time
    for i in range(attempts):
        try:
            out = subprocess.run(
                [sys.executable, "-c",
                 "import jax; print(jax.devices()[0].platform)"],
                timeout=timeout_s, capture_output=True, check=True)
            lines = out.stdout.decode().strip().splitlines()
            if lines:
                _log_probe({"event": "probe", "attempt": i + 1,
                            "ok": True, "platform": lines[-1]})
                return lines[-1]
            raise OSError("probe printed no platform")
        except (subprocess.SubprocessError, OSError) as e:
            _log_probe({"event": "probe", "attempt": i + 1,
                        "ok": False, "error": repr(e)[:300]})
            print(f"WARNING: device probe {i + 1}/{attempts} failed",
                  file=sys.stderr)
            if i + 1 < attempts:
                time.sleep(30)
    _log_probe({"event": "probe_exhausted", "attempt": attempts,
                "ok": False, "error": "all attempts failed — CPU "
                "fallback"})
    print("WARNING: device backend unreachable — benching on CPU",
          file=sys.stderr)
    os.environ["JAX_PLATFORMS"] = "cpu"
    return "cpu"


def _smoke_device() -> dict:
    """`--smoke-device`: compile ONE hash-agg kernel and time ONE
    chunk+flush round — a minutes-cheaper signal than a full bench.
    Probe ok + smoke ok + bench dead ⇒ pipeline bug; probe ok + smoke
    dead ⇒ kernels/XLA broken; probe dead ⇒ tunnel wedged. The result
    also lands in BENCH_probe_log.json."""
    import time

    import numpy as np

    import jax
    from risingwave_tpu.ops.hash_agg import (
        AggKind, AggSpec, GroupedAggKernel,
    )
    platform = jax.devices()[0].platform
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT, None)]
    n, n_groups = 4096, 64
    keys = np.zeros((n, 3), dtype=np.int32)
    keys[:, 0] = np.arange(n) % n_groups
    keys[:, 2] = 1
    signs = np.ones(n, dtype=np.int64)
    vis = np.ones(n, dtype=bool)
    vals = np.arange(n, dtype=np.int64)
    inputs = ((specs[0].encode_input(vals), np.ones(n, dtype=bool)),
              ((), None))
    t0 = time.perf_counter()
    k = GroupedAggKernel(key_width=3, specs=specs, capacity=1 << 12)
    k.apply(keys, signs, vis, inputs)
    fr = k.flush()
    k.advance()
    compile_s = time.perf_counter() - t0
    assert fr.n == n_groups, f"expected {n_groups} groups, got {fr.n}"
    t1 = time.perf_counter()
    k.apply(keys, signs, vis, inputs)
    fr2 = k.flush()
    k.advance()
    chunk_s = time.perf_counter() - t1
    assert fr2.n == n_groups
    out = {"metric": "smoke_device", "ok": True, "platform": platform,
           "compile_and_first_chunk_s": round(compile_s, 4),
           "warm_chunk_s": round(chunk_s, 4),
           "rows": n, "groups": n_groups}
    _log_probe({"event": "smoke", "ok": True, "platform": platform,
                "compile_s": round(compile_s, 4),
                "chunk_s": round(chunk_s, 4)})
    return out


def _elastic_produce(path: str, topic: str, parts: int, start: int,
                     n: int, n_ads: int = 20_000) -> None:
    """Append `n` JSON records round-robin across `parts` partition
    files (the stepped-load generator: call again mid-run to step the
    offered load — filelog readers tail the appends)."""
    import json as _json
    import os

    import numpy as np
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(start + 17)
    ads = rng.integers(0, n_ads, n)
    fhs = [open(os.path.join(path, f"{topic}-{p}.log"), "ab")
           for p in range(parts)]
    try:
        # bulk-format per partition (a dumps-per-record loop is ~10x
        # the wall cost at millions of records; the mid-run step
        # append must be quick)
        for p_i, f in enumerate(fhs):
            f.write(b"".join(
                b'{"k": %d, "v": %d, "b": %d}\n'
                % (ads[i], start + i, (start + i) % 23)
                for i in range(p_i, n, parts)))
    finally:
        for f in fhs:
            f.close()


def bench_elastic(autoscale: bool = True, n_records: int = 1_600_000,
                  neighbor_events: int = 50 * 2000,
                  step_after_s: float = 8.0,
                  deadline_s: float = 600.0) -> dict:
    """Elastic stepped-load lane (ISSUE 15): a hot filelog → GROUP BY
    pipeline at parallelism 1 next to a healthy q7-shaped nexmark
    neighbor, on a real 2-worker cluster under the serving heartbeat.
    A quarter of the load is present at start; the rest appends after
    ``step_after_s`` (the step). With ``stream_autoscale=on`` the
    worker-side bottleneck walker names the hot fragment sustained and
    the control loop rescales it — zero human ALTERs — while the
    neighbor domain must record ZERO decisions (hysteresis holds).
    The off arm is the control: same load, parallelism pinned at 1.
    Recorded per arm: events/s, per-domain p99, decisions, rollbacks,
    and the wall stall each rescale cost (p99-during-rescale)."""
    import tempfile
    import time as _time

    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.meta.autoscaler import (
        autoscaler_rows, clear_autoscale_log,
    )

    clear_autoscale_log()

    async def run(data, root):
        # parallelism 2 cuts at the hash exchange (the rescalable
        # topology; at 1 the whole plan is one fragment) and 3 workers
        # give the loop headroom to scale 2 -> 3;
        # approx_count_distinct keeps the agg single-phase so the
        # source fragment stays split-rescalable (a two-phase LOCAL
        # agg's durable partials ride the source fragment)
        fe = DistFrontend(root, n_workers=3, parallelism=2,
                          barrier_timeout_s=180.0)
        await fe.start()
        try:
            await fe.execute(
                f"SET stream_autoscale = "
                f"'{'on' if autoscale else 'off'}'")
            if fe.autoscaler is not None:
                # bench cadence: decisions may re-observe quickly (the
                # verify window is the real gate at this scale)
                fe.autoscaler.cfg.cooldown_s = 6.0
                fe.autoscaler.cfg.verify_barriers = 2
            # offered load per barrier: 32 chunks x 4096 — the step
            # must hold MULTI-SECOND epochs at parallelism 1 (the
            # pressure the loop exists to relieve), not drain inside
            # the default trickle
            await fe.execute("SET streaming_rate_limit = 32")
            # bounded chunks cap per-barrier ingest (~32K records at
            # the default rate limit): the load step then holds a
            # MULTI-BARRIER backlog of ~1s epochs — the sustained
            # streak the walker needs, not one giant catch-up epoch
            await fe.execute(
                f"CREATE SOURCE imp (k BIGINT, v BIGINT, b BIGINT) "
                f"WITH (connector='filelog', path='{data}', "
                f"topic='imps', max.chunk.size=4096)")
            # count(DISTINCT b) keeps the agg single-phase (the
            # source fragment stays split-rescalable) with SMALL
            # per-group dedup state — the rescale handoff moves the
            # agg tables, so state size is part of the lane's design
            await fe.execute(
                "CREATE MATERIALIZED VIEW hot AS SELECT k, "
                "count(*) AS c, sum(v) AS s, count(DISTINCT b) AS d "
                "FROM imp GROUP BY k")
            await fe.execute(
                f"CREATE SOURCE bid WITH (connector='nexmark', "
                f"nexmark.table.type='bid', "
                f"nexmark.event.num={neighbor_events}, "
                f"nexmark.max.chunk.size=4096, "
                f"nexmark.generate.strings='false')")
            await fe.execute(
                "CREATE MATERIALIZED VIEW q7n AS "
                "SELECT window_start, MAX(price) AS max_price, "
                "COUNT(*) AS cnt "
                "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
                "GROUP BY window_start")
            # warmup: compile every kernel and trim those barriers
            # from the profiler — a neighbor whose p99 is its own
            # first-compile outlier would read as an unhealthy domain
            await fe.step(3)
            fe.cluster.loop.profiler.drop_first(
                len(fe.cluster.loop.profiler.profiles))
            hb = asyncio.ensure_future(fe.run_heartbeat(0.05))
            t0 = _time.perf_counter()
            stepped = False
            seen = 0
            try:
                while _time.perf_counter() - t0 < deadline_s:
                    await asyncio.sleep(2.0)
                    if not stepped and (_time.perf_counter() - t0
                                        >= step_after_s):
                        # the load step: 3x more records land at once
                        # (in a thread — a synchronous multi-MB append
                        # would stall the coordinator loop)
                        await asyncio.to_thread(
                            _elastic_produce, data, "imps", 2,
                            n_records // 4,
                            n_records - n_records // 4)
                        stepped = True
                    rows = await fe.execute("SELECT * FROM hot")
                    seen = sum(r[1] for r in rows)
                    if stepped and seen >= n_records:
                        break
                    if hb.done():
                        hb.result()      # surface a dead heartbeat
            finally:
                if not hb.done():
                    hb.cancel()
                    with __import__("contextlib").suppress(
                            asyncio.CancelledError):
                        await hb
            elapsed = _time.perf_counter() - t0
            job = fe.cluster.jobs["hot"]
            parallelism = {
                f"f{fi}": len(p)
                for fi, p in enumerate(job.placements)}
            by_domain = fe.cluster.loop.p99_by_domain()
            stalls = (fe.autoscaler.action_durations_s
                      if fe.autoscaler is not None else [])
            return (elapsed, seen, by_domain, parallelism,
                    list(stalls))
        finally:
            await fe.close()

    with tempfile.TemporaryDirectory() as data, \
            tempfile.TemporaryDirectory() as root:
        _elastic_produce(data, "imps", 2, 0, n_records // 4)
        elapsed, seen, by_domain, parallelism, stalls = \
            asyncio.run(run(data, root))
    from risingwave_tpu.utils.metrics import exact_quantile
    rows = autoscaler_rows()
    hot = [r for r in rows if r[1] == "hot"]
    neighbor = [r for r in rows if r[1] == "q7n"]
    hot_dom = max((d for d in by_domain if "hot" in d or "imp" in d),
                  default=None, key=lambda d: by_domain[d])
    return {
        "metric": "elastic_events_per_sec",
        "unit": "events/s",
        "autoscale": autoscale,
        "value": round((seen + neighbor_events * 46 // 50)
                       / elapsed, 1) if elapsed else None,
        "hot_events": seen,
        "drained_all": seen >= n_records,
        "elapsed_s": round(elapsed, 2),
        "p99_barrier_latency_s": round(
            max(by_domain.values(), default=0.0), 4),
        "hot_domain_p99_s": round(by_domain.get(hot_dom, 0.0), 4)
        if hot_dom else None,
        "by_domain_p99_s": {d: round(v, 4)
                            for d, v in sorted(by_domain.items())},
        "final_parallelism": parallelism,
        "decisions": len([r for r in hot if r[7] == "applied"]),
        "rollbacks": len([r for r in hot
                          if r[7] in ("rolled_back",
                                      "rollback_failed")]),
        "neighbor_decisions": len(neighbor),
        "decision_log": [list(r) for r in rows],
        # the serving stall each guarded rescale cost (stop + handoff
        # + redeploy + verify) — the p99-during-rescale record
        "rescale_stall_p99_s": round(
            exact_quantile(stalls, 0.99), 4) if stalls else None,
        "rescale_stall_max_s": round(max(stalls), 4)
        if stalls else None,
    }


def _bench_elastic_subprocess(autoscale: bool) -> dict:
    return _run_bench_subprocess(
        ["--elastic-sub", "on" if autoscale else "off"],
        {"JAX_PLATFORMS": "cpu"}, timeout=1800)


def bench_q7_compact(dedicated: bool = True,
                     total_events: int = 48_000,
                     obj_delay_s: float = 0.2) -> dict:
    """Compaction-pressure lane (ISSUE 19): q7 through the SQL front
    door over HummockLite with forced heavy state churn — small epochs
    (min_chunks=4) land one L0 run per checkpoint, so the L0 trigger
    fires repeatedly over the run — behind a latency-injecting object
    store (every SST upload sleeps ``obj_delay_s``). The INLINE arm
    runs ``compact()`` synchronously on the commit path: its merge
    uploads stall the barrier loop and show up in serving p99 + the
    barrier_wait share. The DEDICATED arm moves the same merges to the
    off-path compactor (pinned inputs, version-delta commit), so its
    p99 stays flat under identical churn. Recorded per arm: events/s,
    serving p99, barrier_wait share, off-path tasks applied and the
    per-arm compaction byte counters (the white-box evidence that
    ZERO inline compactions ran on the dedicated arm)."""
    import time as _time

    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.meta.compaction import compaction_rows
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import (
        DelayedObjectStore, MemObjectStore,
    )
    from risingwave_tpu.utils.ledger import LEDGER
    from risingwave_tpu.utils.metrics import STORAGE

    arm = "dedicated" if dedicated else "inline"

    def _bytes_by_arm() -> dict:
        out = {"inline": 0, "dedicated": 0}
        for labels, v in STORAGE.compaction_bytes_written.series():
            a = labels.get("arm", "inline")
            out[a] = out.get(a, 0) + int(v)
        return out

    # counters and the task log are process-global: baseline-diff so
    # a same-process back-to-back arm (dev runs; the real bench
    # isolates arms in subprocesses) reads only ITS run
    base_bytes = _bytes_by_arm()
    base_tasks = len(compaction_rows())

    async def run():
        store = HummockLite(DelayedObjectStore(
            MemObjectStore(), delay_s=obj_delay_s))
        fe = Frontend(store, rate_limit=8, min_chunks=4)
        try:
            await fe.execute(f"SET storage_compaction = '{arm}'")
            await fe.execute(
                f"CREATE SOURCE bid WITH (connector='nexmark', "
                f"nexmark.table.type='bid', "
                f"nexmark.event.num={total_events}, "
                f"nexmark.max.chunk.size=512, "
                f"nexmark.generate.strings='false')")
            await fe.execute(
                "CREATE MATERIALIZED VIEW q7c AS "
                "SELECT window_start, MAX(price) AS max_price, "
                "COUNT(*) AS cnt "
                "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
                "GROUP BY window_start")
            expected = total_events * 46 // 50
            # pipelined drive (same in-flight discipline as
            # _drive_frontend) with the session's CompactionManager
            # ticked per collected barrier: serial fe.step() would
            # let the async uploader's commits — where the inline
            # arm's compact() stalls the loop — land between barriers
            # while the loop is idle, hiding exactly the stall the
            # lane measures
            await fe.step(1)                # warmup (traces compile)
            warm_epochs = len(fe.loop.stats.latencies_s)
            readers = [r for d in fe.readers.values()
                       for r in d.values()]

            def rows_seen() -> int:
                return sum(r.rows_read if hasattr(r, "rows_read")
                           else r.offset for r in readers)

            if rows_seen() >= expected:
                raise ValueError(
                    "bench scale too small: warmup consumed all "
                    f"{expected} rows — raise total_events")
            loop = fe.loop
            t0 = _time.perf_counter()
            base = rows_seen()
            injected = 0
            while rows_seen() < expected:
                if injected >= 500:
                    raise RuntimeError(
                        f"sources stalled at "
                        f"{rows_seen()}/{expected}")
                while loop.in_flight_count < IN_FLIGHT:
                    await loop.inject()
                    injected += 1
                await loop.collect_next()
                if fe._compaction_mgr is not None:
                    await fe._compaction_mgr.tick()
            while loop.in_flight_count:
                await loop.collect_next()
            elapsed = _time.perf_counter() - t0
            rows = rows_seen() - base
            loop.stats.latencies_s = \
                loop.stats.latencies_s[warm_epochs:]
            loop.profiler.drop_first(warm_epochs)
            snap = store.level_snapshot()
            return elapsed, rows, fe.loop, snap
        finally:
            await fe.close()

    t0 = _time.perf_counter()
    elapsed, rows, loop, snap = asyncio.run(run())
    wall = _time.perf_counter() - t0
    pb = LEDGER.phase_breakdown()
    now_bytes = _bytes_by_arm()
    by_arm = {a: now_bytes.get(a, 0) - base_bytes.get(a, 0)
              for a in ("inline", "dedicated")}
    led = compaction_rows()[base_tasks:]
    import jax
    return {
        "metric": "nexmark_q7_compact_events_per_sec",
        "arm": arm,
        "value": round(rows / elapsed, 1) if elapsed else None,
        "unit": "events/s",
        "platform": jax.devices()[0].platform,
        "events": rows,
        "elapsed_s": round(elapsed, 2),
        "wall_s": round(wall, 2),
        "obj_delay_s": obj_delay_s,
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "barrier_wait_share": pb.get("phases", {}).get(
            "barrier_wait", {}).get("share"),
        "phase_breakdown": pb,
        # off-path ledger: tasks the dedicated manager applied (the
        # inline arm must show zero — compact() never queues tasks)
        "offpath_tasks_applied": len(
            [r for r in led if r[3] == "applied"]),
        "offpath_tasks_failed": len(
            [r for r in led if r[3] in ("failed", "aborted")]),
        # per-arm byte counters: on the dedicated arm
        # inline_compaction_bytes MUST be 0 (zero compact() frames on
        # the commit path — the acceptance's white-box form)
        "inline_compaction_bytes": by_arm.get("inline", 0),
        "dedicated_compaction_bytes": by_arm.get("dedicated", 0),
        "l0_runs_final": len(snap["l0"]),
        "l1_runs_final": len(snap["l1"]),
        "space_amp": round(STORAGE.storage_space_amp.get(), 3),
    }


def _bench_q7_compact_subprocess(dedicated: bool) -> dict:
    return _run_bench_subprocess(
        ["--compact-sub", "dedicated" if dedicated else "inline"],
        {"JAX_PLATFORMS": "cpu"}, timeout=1800)


def bench_q7_sink(sink_on: bool = True,
                  total_events: int = 48_000) -> dict:
    """Exactly-once sink lane (ISSUE 20): q7 through the SQL front
    door over HummockLite with an epochlog sink attached to the MV
    (vs the identical pipeline with the sink OFF — the control arm).
    The sink's per-epoch staging is part of each checkpoint's
    durability set but rides the uploader's ASYNC tail (upload_s)
    exactly like the SST uploads — the lane's acceptance is that the
    sink arm's p99 barrier latency stays at the control arm's level
    while p99_upload_s carries the staging cost. (barrier_wait_share
    is NOT comparable across the arms: the sink's chained
    BackfillExecutor reader parks on the barrier channel while the
    upstream agg computes, and the ledger attributes that idle as
    source barrier_wait — reader idle, not commit-path stall.) After
    the run the committed log is verified against the MV's own
    content: the folded key→row state must match row for row (zero
    duplicated, zero lost)."""
    import tempfile
    import time as _time

    from risingwave_tpu.connectors.sink import make_sink_target
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.utils.ledger import LEDGER
    from risingwave_tpu.utils.metrics import STREAMING

    arm = "sink" if sink_on else "control"
    sink_dir = tempfile.mkdtemp(prefix="bench_q7_sink_")

    async def run():
        store = HummockLite(MemObjectStore())
        fe = Frontend(store, rate_limit=8, min_chunks=4)
        try:
            await fe.execute(
                f"CREATE SOURCE bid WITH (connector='nexmark', "
                f"nexmark.table.type='bid', "
                f"nexmark.event.num={total_events}, "
                f"nexmark.max.chunk.size=512, "
                f"nexmark.min.event.gap.in.ns=10000000, "
                f"nexmark.generate.strings='false')")
            await fe.execute(
                "CREATE MATERIALIZED VIEW q7s AS "
                "SELECT window_start, MAX(price) AS max_price, "
                "COUNT(*) AS cnt "
                "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
                "GROUP BY window_start")
            if sink_on:
                await fe.execute(
                    f"CREATE SINK s16 FROM q7s WITH "
                    f"(connector='epochlog', path='{sink_dir}')")
            expected = total_events * 46 // 50
            await fe.step(1)                # warmup (traces compile)
            warm_epochs = len(fe.loop.stats.latencies_s)
            readers = [r for d in fe.readers.values()
                       for r in d.values()]

            def rows_seen() -> int:
                return sum(r.rows_read if hasattr(r, "rows_read")
                           else r.offset for r in readers)

            if rows_seen() >= expected:
                raise ValueError(
                    "bench scale too small: warmup consumed all "
                    f"{expected} rows — raise total_events")
            loop = fe.loop
            t0 = _time.perf_counter()
            base = rows_seen()
            injected = 0
            while rows_seen() < expected:
                if injected >= 500:
                    raise RuntimeError(
                        f"sources stalled at "
                        f"{rows_seen()}/{expected}")
                while loop.in_flight_count < IN_FLIGHT:
                    await loop.inject()
                    injected += 1
                await loop.collect_next()
            while loop.in_flight_count:
                await loop.collect_next()
            elapsed = _time.perf_counter() - t0
            rows = rows_seen() - base
            loop.stats.latencies_s = \
                loop.stats.latencies_s[warm_epochs:]
            loop.profiler.drop_first(warm_epochs)
            # drain the source to completion OUTSIDE the timed window:
            # close() finishes the stream anyway, so the verification
            # below must compare final log vs final MV content
            prev = -1
            while rows_seen() != prev:
                prev = rows_seen()
                await fe.step(2)
            mv_rows = [tuple(int(v) for v in r)
                       for r in await fe.execute("SELECT * FROM q7s")]
            return elapsed, rows, fe.loop, mv_rows
        finally:
            await fe.close()            # drains staging + final commit

    t0 = _time.perf_counter()
    elapsed, rows, loop, mv_rows = asyncio.run(run())
    wall = _time.perf_counter() - t0
    pb = LEDGER.phase_breakdown()
    obs = _metrics_snapshot(loop)
    out = {
        "metric": "nexmark_q7_sink_events_per_sec",
        "arm": arm,
        "value": round(rows / elapsed, 1) if elapsed else None,
        "unit": "events/s",
        "events": rows,
        "elapsed_s": round(elapsed, 2),
        "wall_s": round(wall, 2),
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "barrier_wait_share": pb.get("phases", {}).get(
            "barrier_wait", {}).get("share"),
        # the async checkpoint tail — where the staging cost must land
        "p99_upload_s": obs["p99_upload_s"],
        "phase_breakdown": pb,
    }
    import jax
    out["platform"] = jax.devices()[0].platform
    if not sink_on:
        return out
    # end-to-end verification off the committed log: the folded
    # key→row state must equal the MV's final content exactly
    target = make_sink_target({"path": sink_dir}, "upsert", [])
    state = {}
    for line in target.canonical_rows():
        r = json.loads(line)
        state[tuple(r["__k"])] = (int(r["max_price"]), int(r["cnt"]))
    expect = {(r[0],): (r[1], r[2]) for r in mv_rows}
    out.update({
        "sink_committed_epoch": target.committed_epoch(),
        "sink_uncommitted_epochs": len(target.uncommitted_epochs()),
        "sink_rows_total": int(sum(
            v for _l, v in STREAMING.sink_rows_total.series())),
        "sink_staged_bytes": int(sum(
            v for _l, v in STREAMING.sink_staged_bytes.series())),
        "sink_state_rows": len(state),
        "mv_rows": len(mv_rows),
        "sink_matches_mv": state == expect,
    })
    return out


def _bench_q7_sink_subprocess(sink_on: bool) -> dict:
    return _run_bench_subprocess(
        ["--sink-sub", "on" if sink_on else "off"],
        {"JAX_PLATFORMS": "cpu"}, timeout=1800)


def bench_chaos(seed: int = 7, events: int = 6000) -> dict:
    """Deterministic chaos round (``bench.py --chaos``): replay the
    seeded fault schedule — worker SIGKILL mid-epoch, object-store
    flake (absorbed), upload fault past retries, straggler past the
    barrier timeout — against distributed nexmark q7 and q4 pipelines
    and assert each MV converges to its fault-free in-process oracle
    bit-identically. The snapshot records recovery counts, causes and
    MTTR: tail behavior under faults is a bench trajectory, not an
    anecdote (Hazelcast Jet's stance, arxiv 2103.10169)."""
    import tempfile

    from risingwave_tpu.cluster.chaos import run_chaos
    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.frontend.session import Frontend

    q7_srcs = [
        ("CREATE SOURCE bid WITH (connector='nexmark', "
         "nexmark.table.type='bid', nexmark.event.num={n}, "
         "nexmark.max.chunk.size=256, "
         "nexmark.min.event.gap.in.ns=50000000)")]
    q7_mv = ("CREATE MATERIALIZED VIEW q7 AS SELECT window_start, "
             "MAX(price) AS max_price, COUNT(*) AS cnt "
             "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
             "GROUP BY window_start")
    q4_srcs = [
        ("CREATE SOURCE auction WITH (connector='nexmark', "
         "nexmark.table.type='auction', nexmark.event.num={n}, "
         "nexmark.max.chunk.size=256)"),
        ("CREATE SOURCE bid WITH (connector='nexmark', "
         "nexmark.table.type='bid', nexmark.event.num={n}, "
         "nexmark.max.chunk.size=256)")]
    q4_mv = ("CREATE MATERIALIZED VIEW q4 AS "
             "SELECT category, AVG(final) AS avg_final FROM ("
             "  SELECT a.category AS category, MAX(b.price) AS final"
             "  FROM auction AS a JOIN bid AS b ON a.id = b.auction"
             "  WHERE b.date_time BETWEEN a.date_time AND a.expires"
             "  GROUP BY a.id, a.category) AS q GROUP BY category")

    def oracle(srcs, mv, select):
        async def run():
            fe = Frontend(min_chunks=8)
            for s in srcs:
                await fe.execute(s.format(n=events))
            await fe.execute(mv)
            await fe.step(40)
            rows = await fe.execute(select)
            await fe.close()
            return {tuple(r) for r in rows}
        return asyncio.run(run())

    def chaos_run(srcs, mv, select, kinds=None, rescale_mv=None,
                  autoscale=False):
        async def run():
            with tempfile.TemporaryDirectory() as tmp:
                # wedge timeout with headroom over the natural worst
                # post-recovery barrier (~2-4s on CPU): a spurious
                # wedge would break the seeded schedule's determinism
                fe = DistFrontend(tmp, n_workers=2, parallelism=2,
                                  barrier_timeout_s=8.0)
                await fe.start()
                try:
                    if autoscale:
                        await fe.execute("SET stream_autoscale = 'on'")
                    for s in srcs:
                        await fe.execute(s.format(n=events))
                    await fe.execute(mv)
                    report = await run_chaos(fe, seed,
                                             settle_steps=50,
                                             kinds=kinds,
                                             rescale_mv=rescale_mv)
                    rows = {tuple(r)
                            for r in await fe.execute(select)}
                    return report, rows
                finally:
                    await fe.close()
        return asyncio.run(run())

    out = {"metric": "chaos_mttr_s", "unit": "s", "seed": seed,
           "events": events}
    mttrs = []
    all_ok = True
    # lane 3 (ISSUE 15): the SAME q7 pipeline with faults injected
    # MID-RESCALE — SIGKILL at cohort redeploy, storage fault during
    # the state handoff, straggler across the rescale's stop barrier —
    # each fired while a guarded ALTER is in flight and the autoscaler
    # is enabled. Convergence bar is identical: oracle-bit-identical.
    rescale_kinds = ["kill_mid_rescale", "fault_mid_handoff",
                     "straggler_mid_rescale", "flake_object_store"]
    for name, srcs, mv, kinds, rmv, asc in (
            ("q7", q7_srcs, q7_mv, None, None, False),
            ("q4", q4_srcs, q4_mv, None, None, False),
            ("q7_rescale", q7_srcs, q7_mv, rescale_kinds, "q7",
             True)):
        select = "SELECT * FROM q7" if name.startswith("q7") \
            else f"SELECT * FROM {name}"
        expect = oracle(srcs, mv, select)
        report, rows = chaos_run(srcs, mv, select, kinds=kinds,
                                 rescale_mv=rmv, autoscale=asc)
        ok = rows == expect
        all_ok = all_ok and ok
        mttrs += report.mttr_s
        out[name] = dict(report.summary(), oracle_ok=ok,
                         oracle_rows=len(expect))
    out["value"] = (round(sum(mttrs) / len(mttrs), 4)
                    if mttrs else None)
    out["recovery_count"] = len(mttrs)
    out["oracle_ok"] = all_ok
    return out


# Default latency-bounded mode (ISSUE 9 satellite): every round runs
# against these p99 ceilings unless --latency-budget overrides them —
# the adctr regression (12.9s in r05 → 23.1s in r08) sailed through
# three rounds because only explicitly-budgeted runs were gated. The
# bare float covers every measured query INCLUDING the *_fused twins;
# adctr/q5 get explicit headroom (slowest pipelines at CPU scale).
# Pass --latency-budget '' to disable.
#
# adctr: 30 → 8 after sharded epoch batching (ISSUE 10), 8 → 5 after
# the columnar host path (ISSUE 12: batch JSON parse, staged state
# writes, single-chunk hop expansion + the barrier_wait attribution
# fix) — host_ingest+host_emit dropped 1.7× (9.0s → 5.3s per round)
# and measured p99 is 4.3-4.6s. The ISSUE-12 target of 2s is NOT
# reachable on the 4-virtual-device CPU mesh: device_compute is now
# the dominant phase (~0.9s per epoch of serialized virtual-mesh
# SPMD), so the 5 → 2 ratchet rides ROADMAP item 1 (real
# accelerator). q5_fused: 4 → 5 — the fused arm now absorbs the HOP
# into the one trace (the dispatch-count win the fused twins exist to
# measure) at ~0.7× CPU throughput vs the host-side hop, the same
# tunneled-device trade q3_fused has carried since r09 (0.68× CPU at
# -82 dispatches); the unfused arm keeps the host hop and q5=4.
# Escape hatch if CI hardware is slower:
# --latency-budget '2.0,q5=4,q5_fused=8,adctr=8' (or '')
# overrides per run without a code change.
#
# multimv (ISSUE 13): the AGGREGATE p99 of the multi-MV domain lane is
# dominated by the ad-ctr domain (single-chip, no mesh — slower than
# the 4-virtual-device adctr lane), so it takes generous headroom; the
# lane's own `fast_domains_sub_second` field carries the real
# acceptance claim (every non-ad-ctr domain p99 ≤ 1s).
#
# elastic (ISSUE 15): the stepped-load lane REPORTS the worst domain
# p99 as its headline latency — the hot domain under a 4x load step at
# parallelism 1 runs multi-second barriers BY DESIGN (that pressure is
# what the autoscaler resolves); the lane's own `vs_off.resolved`
# field carries the acceptance claim, so the budget here is a
# don't-hang bound, not a latency target. The off arm gets double (no
# loop to relieve it).
DEFAULT_LATENCY_BUDGET = ("2.0,q5=4,q5_fused=5,adctr=5,multimv=12,"
                          "elastic=60,elastic_off=120")


def _parse_budget_spec(argv, flag: str, default_spec: str) -> dict:
    """Shared budget-spec parser: `<flag> 'q7=0.5,adctr=15'` (per
    lane) or a bare float (every lane) → {lane: budget seconds}.
    Defaults to ``default_spec`` when the flag is absent; an empty
    spec turns the gate off."""
    if flag not in argv:
        spec = default_spec
    else:
        spec = argv[argv.index(flag) + 1]
    budgets = {}
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" in part:
            q, v = part.split("=", 1)
            budgets[q.strip()] = float(v)
        else:
            budgets["*"] = float(part)
    return budgets


def _parse_latency_budgets(argv) -> dict:
    return _parse_budget_spec(argv, "--latency-budget",
                              DEFAULT_LATENCY_BUDGET)


# Freshness-bounded mode (ISSUE 14): every round also gates each
# lane's per-MV WALL freshness lag p99 (the time from ingest to
# visible — the number an MV reader actually experiences; EVENT-time
# lag is recorded too but not gated: synthetic generators race through
# event time far faster than the wall clock, so event-lag magnitudes
# are workload constants, not regressions). Budgets are generous
# multiples of each lane's p99 barrier latency: wall lag spans a
# couple of epochs by construction. Pass --freshness-budget '' to
# disable, or override per lane like the latency budget.
DEFAULT_FRESHNESS_BUDGET = "20,adctr=45,multimv=120"


def _parse_freshness_budgets(argv) -> dict:
    """--freshness-budget 'q7=2,adctr=30' or a bare float (every lane
    reporting freshness) → {lane: wall-lag p99 budget seconds}."""
    return _parse_budget_spec(argv, "--freshness-budget",
                              DEFAULT_FRESHNESS_BUDGET)


def _freshness_verdict(headline: dict, budgets: dict) -> dict:
    """Per-lane freshness-vs-budget verdicts: the lane's WORST per-MV
    wall-lag p99 must fit its budget. Lanes without freshness blocks
    (chaos, failed lanes) are gated only when explicitly budgeted."""
    default = budgets.get("*")
    verdicts = {}
    ok = True
    for name, r in headline.items():
        if not isinstance(r, dict):
            continue
        budget = budgets.get(name, default)
        if budget is None:
            continue
        fresh = r.get("freshness") or {}
        worst = None
        for mv, block in fresh.items():
            w = block.get("wall_lag_p99_s")
            if w is not None and (worst is None or w > worst):
                worst = w
        if worst is None:
            if name in budgets:
                verdicts[name] = {"budget_s": budget,
                                  "verdict": "no-measurement"}
                ok = False
            continue
        over = worst > budget
        ok = ok and not over
        verdicts[name] = {"budget_s": budget,
                          "wall_lag_p99_s": worst,
                          "verdict": "over-budget" if over else "ok"}
    return {"budgets": budgets, "verdicts": verdicts, "ok": ok}


def _latency_verdict(headline: dict, budgets: dict) -> dict:
    """Per-query p99-vs-budget verdicts (ROADMAP item 3's
    latency-bounded bench mode). Recorded in the headline JSON the
    driver snapshots into BENCH_r*.json; `ok` False → exit 1."""
    default = budgets.get("*")
    verdicts = {}
    ok = True
    for name, r in headline.items():
        if not isinstance(r, dict):
            continue
        p99 = r.get("p99_barrier_latency_s")
        budget = budgets.get(name, default)
        if budget is None:
            continue
        if p99 is None:
            if name not in budgets:
                # the '*' default only gates entries that measure a
                # barrier p99 (the chaos round reports MTTR instead)
                continue
            verdicts[name] = {"budget_s": budget,
                              "verdict": "no-measurement"}
            ok = False
            continue
        over = p99 > budget
        ok = ok and not over
        verdicts[name] = {"budget_s": budget, "p99_s": p99,
                          "verdict": "over-budget" if over else "ok"}
    return {"budgets": budgets, "verdicts": verdicts, "ok": ok}


def main(argv):
    import contextlib
    import os
    from risingwave_tpu.utils.tpulock import ChipBusy, chip_lock
    # Chip discipline (VERDICT r3): hold the exclusive chip lock for
    # the WHOLE run (probe included — the probe subprocess is itself a
    # TPU client). Two concurrent clients wedge the tunnel for minutes.
    # Per-query child subprocesses inherit the parent's lock.
    lock = contextlib.nullcontext() \
        if (os.environ.get("JAX_PLATFORMS") == "cpu"
            or os.environ.get("RW_TPU_CHIP_LOCK_HELD")) else chip_lock()
    try:
        lock.__enter__()
    except ChipBusy as e:
        print(f"WARNING: {e} — benching on CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        lock = contextlib.nullcontext()
        lock.__enter__()
    try:
        _main_locked(argv)
    finally:
        lock.__exit__(None, None, None)


BENCH_FNS = {}


def _clear_attribution():
    """Reset the process-global attribution state between a lane's
    warmup and measured runs (records, freshness rings, bottleneck
    streaks are all process-global — a warmup's epochs must not
    dilute the measured run's blocks)."""
    from risingwave_tpu.stream.bottleneck import BOTTLENECKS
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.utils.ledger import LEDGER
    LEDGER.clear()
    FRESHNESS.clear()
    BOTTLENECKS.clear()


def _run_bench_subprocess(args: list, env_overrides: dict,
                          timeout: int = 1800) -> dict:
    """Spawn a bench child and parse its one JSON line (shared by the
    per-query and adctr runners — keep the scan/error shape in one
    place)."""
    import os
    import subprocess
    env = dict(os.environ)
    env.update(env_overrides)
    out = subprocess.run([sys.executable, __file__] + args,
                         capture_output=True, timeout=timeout, env=env)
    for line in reversed(out.stdout.decode().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise RuntimeError(
        f"bench {args} subprocess produced no JSON: "
        f"rc={out.returncode} stderr={out.stderr.decode()[-300:]!r}")


def _bench_one_subprocess(name: str) -> dict:
    """Run ONE query's warmup+measure in a fresh subprocess: queries
    measured back-to-back in one process interfere (q7 halves after
    q8's run — accumulated allocator/registry state), so isolation is
    part of the methodology. The child inherits the parent's platform
    env and skips the chip lock the parent already holds."""
    return _run_bench_subprocess(["--one", name],
                                 {"RW_TPU_CHIP_LOCK_HELD": "1"})


def _main_locked(argv):
    from risingwave_tpu.utils.jaxtools import enable_compilation_cache
    if "--smoke-device" in argv:
        # one kernel compile + one timed chunk, under the chip lock the
        # parent already took; failures log to BENCH_probe_log.json
        import os
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            import jax
            jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        try:
            print(json.dumps(_smoke_device()))
        except BaseException as e:
            _log_probe({"event": "smoke", "ok": False,
                        "error": repr(e)[:300]})
            print(json.dumps({"metric": "smoke_device", "ok": False,
                              "error": repr(e)[:300]}))
            raise
        return
    if "--chaos" in argv:
        # deterministic chaos round: seeded fault schedule against
        # distributed q7/q4, oracle-checked, MTTR in the snapshot.
        # CPU-pinned: the faults under test are control-plane, and a
        # killed worker must not wedge a shared accelerator tunnel
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        seed = (int(argv[argv.index("--chaos-seed") + 1])
                if "--chaos-seed" in argv else 7)
        out = bench_chaos(seed=seed)
        print(json.dumps(out))
        if not out["oracle_ok"]:
            print("FAIL: chaos run diverged from the fault-free "
                  "oracle", file=sys.stderr)
            sys.exit(1)
        return
    if "--one" in argv:
        # child mode: one query, full-scale warmup then measure
        import os
        if os.environ.get("JAX_PLATFORMS") == "cpu":
            import jax
            jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        name = argv[argv.index("--one") + 1]
        from risingwave_tpu.utils.ledger import LEDGER
        LEDGER.query = name     # stamps stream_epoch_phase_seconds
        fn = BENCH_FNS[name]
        fn()
        # the warmup run's epochs must not dilute the measured run's
        # phase_breakdown / freshness / bottleneck blocks (all are
        # process-global)
        _clear_attribution()
        print(json.dumps(fn()))
        return
    if "--mesh-sub" in argv:
        # child mode: timed sharded lane on the 8-virtual-device CPU
        # mesh (same sitecustomize override dance as --adctr-sub)
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        from risingwave_tpu.utils.ledger import LEDGER
        LEDGER.query = "q7_mesh"
        r = bench_q7_mesh()                            # full-scale warmup
        _clear_attribution()
        r = bench_q7_mesh()
        import jax
        r["platform"] = (f"{jax.devices()[0].platform}"
                         f"-mesh-{r['parallelism']}")
        print(json.dumps(r))
        return
    if "--elastic-sub" in argv:
        # child mode: elastic stepped-load lane (ISSUE 15), CPU-pinned
        # — the subject is the control loop, not the mesh
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        from risingwave_tpu.utils.ledger import LEDGER
        arm = argv[argv.index("--elastic-sub") + 1]
        LEDGER.query = f"elastic_{arm}"
        print(json.dumps(bench_elastic(autoscale=(arm == "on"))))
        return
    if "--compact-sub" in argv:
        # child mode: compaction-pressure lane (ISSUE 19), CPU-pinned
        # — the subject is the commit path, not the kernels
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        from risingwave_tpu.utils.ledger import LEDGER
        arm = argv[argv.index("--compact-sub") + 1]
        LEDGER.query = f"q7_compact_{arm}"
        print(json.dumps(bench_q7_compact(
            dedicated=(arm == "dedicated"))))
        return
    if "--sink-sub" in argv:
        # child mode: exactly-once sink lane (ISSUE 20), CPU-pinned
        # — the subject is the checkpoint/staging path, not kernels
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        from risingwave_tpu.utils.ledger import LEDGER
        arm = argv[argv.index("--sink-sub") + 1]
        LEDGER.query = f"q7_sink_{arm}"
        print(json.dumps(bench_q7_sink(sink_on=(arm == "on"))))
        return
    if "--multimv-sub" in argv:
        # child mode: multi-MV barrier-domain lane, CPU-pinned
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        from risingwave_tpu.utils.ledger import LEDGER
        LEDGER.query = "multimv"
        bench_multimv()                            # warmup
        _clear_attribution()
        print(json.dumps(bench_multimv()))
        return
    if "--adctr-sub" in argv:
        # child mode: env asks for the CPU virtual mesh, but the axon
        # sitecustomize overrides JAX_PLATFORMS at interpreter start —
        # override it back before any backend initializes (conftest.py
        # does the same for the test suite)
        import jax as _jax
        _jax.config.update("jax_platforms", "cpu")
        enable_compilation_cache()
        # FULL-scale warmup (the stated methodology): a half-scale
        # warmup left the bigger catch-up epochs' pow2 shapes — and
        # their XLA compiles — inside the timed window, which is
        # exactly the p99 tail the latency budget gates
        from risingwave_tpu.utils.ledger import LEDGER
        LEDGER.query = "adctr"
        r = bench_adctr()                          # warmup
        _clear_attribution()
        r = bench_adctr()
        import jax
        r["platform"] = (f"{jax.devices()[0].platform}"
                         f"-mesh-{r['parallelism']}")
        print(json.dumps(r))
        return
    import os
    if os.environ.get("JAX_PLATFORMS") == "cpu":
        # explicit CPU run: children pin past the axon sitecustomize
        # themselves; the parent touches no backend at all
        platform = "cpu"
    else:
        platform = _probe_device()
    quick = "--quick" in argv
    # Every query lands in the ONE captured headline line (VERDICT r2:
    # stderr tables are not recorded by the driver). Per-query isolation:
    # one query failing must not cost the others their numbers.
    # Each query runs a small WARMUP first (criterion-style): the first
    # run traces/compiles every (shape) program — on a fresh process
    # that fixed cost would otherwise be reported as throughput.
    # warmups run at FULL scale (warm_kw = {}): a smaller warmup
    # leaves capacity-growth XLA compiles inside the timed run — the
    # timed number then measures the compiler, not the pipeline
    # fused twins right after their interpretive baselines: the round
    # diff shows fragment fusion's before/after per query (ISSUE 6)
    names = ["q7", "q7_ledger_off", "q7_tricolor_off", "q7_costs_off",
             "q7_fused", "q8", "q8_fused", "q4", "q3", "q3_fused",
             "q5", "q5_fused", "q1"]
    if quick:
        names = names[:1]
    headline = {}
    for name in names:
        try:
            r = _bench_one_subprocess(name)
            headline[name] = {k: r[k] for k in
                              ("value", "p99_barrier_latency_s",
                               "barrier_in_flight", "events",
                               "platform", "phase_breakdown",
                               "observability", "freshness",
                               "bottleneck") if k in r}
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: {name} failed: {e!r}", file=sys.stderr)
            headline[name] = {"error": repr(e)[:200]}
    if not quick:
        # ad-ctr is the 4-chip baseline config: with one local chip it
        # measures on a 4-virtual-device CPU mesh in a subprocess
        # (clearly labeled) so the parallel path always has a number
        try:
            r = _bench_adctr_subprocess()
            headline["adctr"] = {
                k: r[k] for k in ("value", "p99_barrier_latency_s",
                                  "barrier_in_flight", "events",
                                  "parallelism", "platform",
                                  "phase_breakdown", "observability",
                                  "freshness", "bottleneck")
                if k in r}
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: adctr failed: {e!r}", file=sys.stderr)
            headline["adctr"] = {"error": repr(e)[:200]}
        # multi-MV barrier-domain lane (ISSUE 13): ad-ctr next to a
        # q7-shaped neighbor in one session — the per-domain p99
        # breakdown shows the slow domain carrying the tail alone
        try:
            r = _bench_multimv_subprocess()
            headline["multimv"] = {
                k: r[k] for k in ("value", "p99_barrier_latency_s",
                                  "barrier_in_flight", "events",
                                  "platform", "by_domain", "domains",
                                  "fast_domains_p99_max_s",
                                  "fast_domains_sub_second",
                                  "marginal_cost",
                                  "observability", "freshness",
                                  "bottleneck") if k in r}
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: multimv failed: {e!r}", file=sys.stderr)
            headline["multimv"] = {"error": repr(e)[:200]}
        # elastic stepped-load lane (ISSUE 15): the hot pipeline's
        # offered load steps 4x mid-run; the autoscale-on arm must
        # resolve the sustained bottleneck with ZERO human ALTERs
        # while the q7 neighbor domain records ZERO decisions; the
        # off arm is the pinned-parallelism control
        elastic_keys = ("value", "autoscale", "hot_events",
                        "drained_all", "elapsed_s",
                        "p99_barrier_latency_s", "hot_domain_p99_s",
                        "by_domain_p99_s", "final_parallelism",
                        "decisions", "rollbacks",
                        "neighbor_decisions", "decision_log",
                        "rescale_stall_p99_s", "rescale_stall_max_s")
        for lane, arm in (("elastic", True), ("elastic_off", False)):
            try:
                r = _bench_elastic_subprocess(arm)
                headline[lane] = {k: r[k] for k in elastic_keys
                                  if k in r}
            except Exception as e:                   # noqa: BLE001
                print(f"WARNING: {lane} failed: {e!r}",
                      file=sys.stderr)
                headline[lane] = {"error": repr(e)[:200]}
        el, eo = headline.get("elastic"), headline.get("elastic_off")
        if isinstance(el, dict) and isinstance(eo, dict) \
                and el.get("hot_domain_p99_s") \
                and eo.get("hot_domain_p99_s"):
            el["vs_off"] = {
                "hot_p99_ratio": round(el["hot_domain_p99_s"]
                                       / eo["hot_domain_p99_s"], 4),
                # the lane's acceptance: the loop acted (≥1 applied
                # decision), the hot domain's p99 improved vs the
                # pinned arm, and the healthy neighbor was untouched
                "resolved": bool(
                    el.get("decisions", 0) >= 1
                    and el.get("neighbor_decisions", 0) == 0
                    and el["hot_domain_p99_s"]
                    < eo["hot_domain_p99_s"]),
            }
        # compaction-pressure lane (ISSUE 19): q7 under forced heavy
        # state churn behind a latency-injecting object store; the
        # dedicated arm must hold serving p99 flat while the inline
        # arm pays its merges on the commit path
        compact_keys = ("value", "arm", "events", "elapsed_s",
                        "obj_delay_s", "p99_barrier_latency_s",
                        "barrier_wait_share", "offpath_tasks_applied",
                        "offpath_tasks_failed",
                        "inline_compaction_bytes",
                        "dedicated_compaction_bytes",
                        "l0_runs_final", "l1_runs_final", "space_amp",
                        "platform")
        for lane, arm in (("q7_compact", True),
                          ("q7_compact_inline", False)):
            try:
                r = _bench_q7_compact_subprocess(arm)
                headline[lane] = {k: r[k] for k in compact_keys
                                  if k in r}
            except Exception as e:                   # noqa: BLE001
                print(f"WARNING: {lane} failed: {e!r}",
                      file=sys.stderr)
                headline[lane] = {"error": repr(e)[:200]}
        cd = headline.get("q7_compact")
        ci = headline.get("q7_compact_inline")
        if isinstance(cd, dict) and isinstance(ci, dict) \
                and cd.get("p99_barrier_latency_s") \
                and ci.get("p99_barrier_latency_s"):
            cd["vs_inline"] = {
                "p99_ratio": round(cd["p99_barrier_latency_s"]
                                   / ci["p99_barrier_latency_s"], 4),
                # the lane's acceptance: the dedicated arm did its
                # merges OFF the commit path (≥1 applied task, zero
                # inline bytes) and held p99 at-or-under the inline
                # arm that paid the same merges on-path
                "resolved": bool(
                    cd.get("offpath_tasks_applied", 0) >= 1
                    and cd.get("inline_compaction_bytes", 1) == 0
                    and ci.get("inline_compaction_bytes", 0) > 0
                    and cd["p99_barrier_latency_s"]
                    <= ci["p99_barrier_latency_s"]),
            }
        # exactly-once sink lane (ISSUE 20): q7 with an epochlog sink
        # attached vs the identical sink-off control — the staging
        # cost must ride the async upload tail (p99 parity with the
        # control, upload_s carries the staging; barrier_wait_share
        # is reader-idle attribution, not comparable across arms),
        # and the committed log must match the MV row for row
        sink_keys = ("value", "arm", "events", "elapsed_s",
                     "p99_barrier_latency_s", "barrier_wait_share",
                     "p99_upload_s", "sink_committed_epoch",
                     "sink_uncommitted_epochs", "sink_rows_total",
                     "sink_staged_bytes", "sink_state_rows",
                     "mv_rows", "sink_matches_mv", "platform")
        for lane, on in (("q7_sink", True), ("q7_sink_off", False)):
            try:
                r = _bench_q7_sink_subprocess(on)
                headline[lane] = {k: r[k] for k in sink_keys
                                  if k in r}
            except Exception as e:                   # noqa: BLE001
                print(f"WARNING: {lane} failed: {e!r}",
                      file=sys.stderr)
                headline[lane] = {"error": repr(e)[:200]}
        sk = headline.get("q7_sink")
        so = headline.get("q7_sink_off")
        if isinstance(sk, dict) and isinstance(so, dict) \
                and sk.get("p99_barrier_latency_s") \
                and so.get("p99_barrier_latency_s"):
            sk["vs_control"] = {
                "p99_ratio": round(sk["p99_barrier_latency_s"]
                                   / so["p99_barrier_latency_s"], 4),
                # the lane's acceptance: the committed log equals the
                # MV exactly (zero dup/lost), nothing left staged,
                # and the sink arm's p99 stays within 25% of the
                # sink-off control (staging rode the async tail)
                "resolved": bool(
                    sk.get("sink_matches_mv")
                    and sk.get("sink_uncommitted_epochs", 1) == 0
                    and sk["p99_barrier_latency_s"]
                    <= 1.25 * so["p99_barrier_latency_s"]),
            }
        # sharded mesh lane (ISSUE 10): q7 at parallelism 8 — the
        # epoch-batched SPMD kernels timed, not just dry-run-checked
        try:
            r = _bench_q7_mesh_subprocess()
            headline["q7_mesh"] = {
                k: r[k] for k in ("value", "p99_barrier_latency_s",
                                  "barrier_in_flight", "events",
                                  "parallelism", "platform",
                                  "phase_breakdown", "observability",
                                  "freshness", "bottleneck")
                if k in r}
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: q7_mesh failed: {e!r}", file=sys.stderr)
            headline["q7_mesh"] = {"error": repr(e)[:200]}
    # Bench honesty (ISSUE 9): each *_fused twin carries its p99 delta
    # NEXT TO its dispatch delta vs the interpretive baseline. Fused
    # runs trade host interpretation for device dispatches — on CPU
    # the p99 may go the wrong way while dispatches drop (the win is
    # a tunneled-device cost); recording both per round keeps that
    # argument auditable instead of implied.
    for name in [n for n in list(headline) if n.endswith("_fused")]:
        r, base = headline[name], headline.get(name[:-len("_fused")])
        if not (isinstance(r, dict) and isinstance(base, dict)
                and "value" in r and "value" in base):
            continue
        p99_f = r.get("p99_barrier_latency_s")
        p99_u = base.get("p99_barrier_latency_s")
        d_f = (r.get("observability") or {}).get("device_dispatches")
        d_u = (base.get("observability") or {}).get("device_dispatches")
        r["vs_unfused"] = {
            "p99_delta_s": (None if None in (p99_f, p99_u)
                            else round(p99_f - p99_u, 5)),
            "dispatch_delta": (None if None in (d_f, d_u)
                               else d_f - d_u),
            "throughput_ratio": round(r["value"] / base["value"], 4)
            if base["value"] else None,
        }
    # ledger-overhead verdict (ISSUE 11 acceptance: ledger-on q7
    # throughput within 5% of ledger-off on CPU) — recorded per round
    # so the observability tax stays auditable
    off, on_ = headline.get("q7_ledger_off"), headline.get("q7")
    if isinstance(off, dict) and isinstance(on_, dict) \
            and off.get("value") and on_.get("value"):
        off["ledger_overhead"] = {
            "on_vs_off_throughput_ratio": round(
                on_["value"] / off["value"], 4),
            "within_5pct": on_["value"] >= 0.95 * off["value"],
        }
    # tricolor-overhead verdict (ISSUE 14 acceptance: utilization
    # tricolor + freshness sampling on-vs-off q7 throughput within 5%)
    toff = headline.get("q7_tricolor_off")
    if isinstance(toff, dict) and isinstance(on_, dict) \
            and toff.get("value") and on_.get("value"):
        toff["tricolor_overhead"] = {
            "on_vs_off_throughput_ratio": round(
                on_["value"] / toff["value"], 4),
            "within_5pct": on_["value"] >= 0.95 * toff["value"],
        }
    # cost/skew-attribution-overhead verdict (ISSUE 16 acceptance:
    # per-MV cost rollup + state topology + hot-key sketches on-vs-off
    # q7 throughput within 5%)
    coff = headline.get("q7_costs_off")
    if isinstance(coff, dict) and isinstance(on_, dict) \
            and coff.get("value") and on_.get("value"):
        coff["costs_overhead"] = {
            "on_vs_off_throughput_ratio": round(
                on_["value"] / coff["value"], 4),
            "within_5pct": on_["value"] >= 0.95 * coff["value"],
        }
    q7 = headline.get("q7", {})
    ok = "value" in q7
    headline.update({
        "metric": "nexmark_q7_events_per_sec",
        # null, not 0.0, when q7 failed: a fabricated zero reads as a
        # measured catastrophic regression in round-over-round diffs
        "value": q7["value"] if ok else None,
        "unit": "events/s",
        "vs_baseline": round(q7["value"] / BASELINE_EVENTS_PER_SEC, 4)
        if ok else None,
        # the target is events/sec per TPU CHIP; a cpu-platform number
        # is a fallback measurement, not a claim against that target
        "vs_baseline_platform": platform,
        "platform": platform,
    })
    if "--with-chaos" in argv:
        # the chaos round rides the headline snapshot: recovery counts
        # and MTTR become part of the bench trajectory. Run it through
        # the --chaos child so it gets that branch's CPU pinning — the
        # in-process oracle must share the CPU workers' float
        # semantics, and a killed worker must not touch a shared
        # accelerator tunnel
        try:
            headline["chaos"] = _run_bench_subprocess(
                ["--chaos"], {"JAX_PLATFORMS": "cpu",
                              "RW_TPU_CHIP_LOCK_HELD": "1"})
        except Exception as e:                       # noqa: BLE001
            print(f"WARNING: chaos failed: {e!r}", file=sys.stderr)
            headline["chaos"] = {"error": repr(e)[:200]}
    budgets = _parse_latency_budgets(argv)
    verdict = None
    if budgets:
        verdict = _latency_verdict(headline, budgets)
        headline["latency_budget"] = verdict
    fresh_budgets = _parse_freshness_budgets(argv)
    fresh_verdict = None
    if fresh_budgets:
        fresh_verdict = _freshness_verdict(headline, fresh_budgets)
        headline["freshness_budget"] = fresh_verdict
    print(json.dumps(headline))
    failed = []
    if verdict is not None and not verdict["ok"]:
        # latency-bounded mode: a query past its p99 budget fails the
        # round AFTER the JSON line lands (the driver still records it)
        over = [q for q, v in verdict["verdicts"].items()
                if v["verdict"] != "ok"]
        print(f"FAIL: p99 barrier latency budget exceeded: {over}",
              file=sys.stderr)
        failed += over
    if fresh_verdict is not None and not fresh_verdict["ok"]:
        over = [q for q, v in fresh_verdict["verdicts"].items()
                if v["verdict"] != "ok"]
        print(f"FAIL: freshness wall-lag budget exceeded: {over}",
              file=sys.stderr)
        failed += over
    if failed:
        sys.exit(1)


import functools as _functools

BENCH_FNS.update({"q7": bench_q7, "q8": bench_q8, "q4": bench_q4,
                  "q3": bench_q3, "q5": bench_q5, "q1": bench_q1,
                  # phase-ledger-off arm (ISSUE 11): same q7 config
                  # with every ledger hook reduced to a predicate
                  # check — the observability-tax control
                  "q7_ledger_off": _functools.partial(bench_q7,
                                                      ledger=False),
                  # tricolor/freshness-off arm (ISSUE 14): same q7
                  # config with the utilization bookkeeping and
                  # freshness sampling reduced to predicate checks —
                  # the attribution-tax control (on-vs-off < 5%)
                  "q7_tricolor_off": _functools.partial(
                      bench_q7, tricolor=False),
                  # cost/skew-attribution-off arm (ISSUE 16): same q7
                  # config with the per-MV rollup, topology upkeep and
                  # hot-key sketches reduced to predicate checks —
                  # the serving-cost-attribution tax control (< 5%)
                  "q7_costs_off": _functools.partial(
                      bench_q7, costs=False),
                  # fragment fusion on (SET stream_fusion equivalent
                  # for the hand-built pipelines)
                  # compaction-pressure arms (ISSUE 19): q7 under
                  # forced churn behind a delayed object store —
                  # merges off-path vs paid on the commit path
                  "q7_compact": _functools.partial(
                      bench_q7_compact, dedicated=True),
                  "q7_compact_inline": _functools.partial(
                      bench_q7_compact, dedicated=False),
                  # exactly-once sink arms (ISSUE 20): q7 with the
                  # epochlog sink attached vs the sink-off control
                  "q7_sink": _functools.partial(
                      bench_q7_sink, sink_on=True),
                  "q7_sink_off": _functools.partial(
                      bench_q7_sink, sink_on=False),
                  "q7_fused": _functools.partial(bench_q7, fusion=True),
                  "q8_fused": _functools.partial(bench_q8, fusion=True),
                  "q3_fused": _functools.partial(bench_q3, fusion=True),
                  "q5_fused": _functools.partial(bench_q5,
                                                 fusion=True)})


if __name__ == "__main__":
    main(sys.argv[1:])
