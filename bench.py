"""Benchmark rig: Nexmark pipelines on the real chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ...,
"vs_baseline": N} — the driver records it in BENCH_r{N}.json.

Baseline (BASELINE.md): ≥1M events/sec/chip on Nexmark q7/q8 (one v5e).
The headline metric is the best stateful-query throughput available; q1
(stateless, host-bound reference path) is reported inside "extra" for
tracking. Run `python bench.py --all` for the full table on stderr.
"""

from __future__ import annotations

import asyncio
import json
import sys
import time

BASELINE_EVENTS_PER_SEC = 1_000_000.0


def bench_q1(total_events: int = 50 * 4000, chunk_size: int = 4096):
    """q1: source → project → materialize (host/CPU reference path)."""
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig, NexmarkSplitReader,
    )
    from risingwave_tpu.expr.expr import InputRef, lit
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
    from risingwave_tpu.stream.executors.simple import ProjectExecutor
    from risingwave_tpu.stream.executors.source import SourceExecutor
    from risingwave_tpu.stream.message import StopMutation

    split_schema = Schema([Field("split_id", DataType.VARCHAR),
                           Field("offset", DataType.INT64)])
    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size)
    store = MemoryStateStore()
    reader = NexmarkSplitReader(cfg)
    barrier_tx, barrier_rx = channel_for_test()
    split_state = StateTable(1, split_schema, [0], store)
    source = SourceExecutor(reader, barrier_rx, split_state, actor_id=1)
    row_id = RowIdGenExecutor(source)
    s = row_id.schema
    project = ProjectExecutor(
        row_id,
        exprs=[InputRef(s.index_of("auction"), DataType.INT64),
               InputRef(s.index_of("bidder"), DataType.INT64),
               lit("0.908", DataType.DECIMAL)
               * InputRef(s.index_of("price"), DataType.INT64),
               InputRef(s.index_of("date_time"), DataType.TIMESTAMP),
               InputRef(s.index_of("_row_id"), DataType.SERIAL)],
        names=["auction", "bidder", "price", "date_time", "_row_id"])
    mv_table = StateTable(2, project.schema, [4], store)
    mat = MaterializeExecutor(project, mv_table)
    local = LocalBarrierManager()
    local.register_sender(1, barrier_tx)
    local.set_expected_actors([1])
    actor = Actor(1, mat, dispatchers=[], barrier_manager=local)
    loop = BarrierLoop(local, store)

    n_bids = total_events * 46 // 50

    async def main():
        task = actor.spawn()
        t0 = time.perf_counter()
        while reader.offset < n_bids:
            await loop.inject_and_collect()
        await loop.inject_and_collect()
        elapsed = time.perf_counter() - t0
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset([1])))
        await task
        if actor.failure is not None:
            raise actor.failure
        return elapsed

    elapsed = asyncio.run(main())
    return {
        "metric": "nexmark_q1_events_per_sec",
        "value": round(n_bids / elapsed, 1),
        "unit": "events/s",
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "events": n_bids,
    }


def bench_q7(total_events: int = 50 * 40_000, chunk_size: int = 8192):
    """q7 core: tumble-window MAX(price) on the device hash-agg kernel.

    source → project(tumble_start, price) → HashAggExecutor(TPU) →
    materialize. The stateful baseline config (BASELINE.md: HashAgg on
    TPU, ≥1M events/s/chip)."""
    from risingwave_tpu.common.types import (
        DataType, Field, Interval, Schema,
    )
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig, NexmarkSplitReader,
    )
    from risingwave_tpu.expr.expr import InputRef, tumble_start
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.ops.hash_agg import AggKind
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.executors.hash_agg import (
        AggCall, HashAggExecutor, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    from risingwave_tpu.stream.executors.simple import ProjectExecutor
    from risingwave_tpu.stream.executors.source import SourceExecutor
    from risingwave_tpu.stream.message import StopMutation

    split_schema = Schema([Field("split_id", DataType.VARCHAR),
                           Field("offset", DataType.INT64)])
    window = Interval(usecs=10_000_000)
    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    store = MemoryStateStore()
    reader = NexmarkSplitReader(cfg)
    barrier_tx, barrier_rx = channel_for_test()
    split_state = StateTable(1, split_schema, [0], store)
    source = SourceExecutor(reader, barrier_rx, split_state, actor_id=1,
                            rate_limit_chunks_per_barrier=16)
    s = source.schema
    project = ProjectExecutor(
        source,
        exprs=[tumble_start(
            InputRef(s.index_of("date_time"), DataType.TIMESTAMP), window),
            InputRef(s.index_of("price"), DataType.INT64)],
        names=["window_start", "price"])
    calls = [AggCall(AggKind.MAX, 1), AggCall(AggKind.COUNT)]
    agg_schema, agg_pk = agg_state_schema(project.schema, [0], calls)
    agg_state = StateTable(2, agg_schema, agg_pk, store,
                           dist_key_indices=[0])
    agg = HashAggExecutor(project, [0], calls, agg_state, append_only=True,
                          output_names=["max_price", "bid_count"])
    mv_table = StateTable(3, agg.schema, [0], store)
    mat = MaterializeExecutor(agg, mv_table)
    local = LocalBarrierManager()
    local.register_sender(1, barrier_tx)
    local.set_expected_actors([1])
    actor = Actor(1, mat, dispatchers=[], barrier_manager=local)
    loop = BarrierLoop(local, store)

    n_bids = total_events * 46 // 50

    async def main():
        task = actor.spawn()
        # warmup epoch: trigger jit compiles outside the timed window
        await loop.inject_and_collect()
        warm_events = reader.offset
        warm_epochs = len(loop.stats.latencies_s)
        t0 = time.perf_counter()
        while reader.offset < n_bids:
            await loop.inject_and_collect()
        elapsed = time.perf_counter() - t0
        timed_events = reader.offset - warm_events
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset([1])))
        await task
        if actor.failure is not None:
            raise actor.failure
        # drop warmup epochs from the latency stats (compile time is not
        # steady-state barrier latency)
        loop.stats.latencies_s = loop.stats.latencies_s[warm_epochs:]
        return elapsed, timed_events

    elapsed, timed_events = asyncio.run(main())
    return {
        "metric": "nexmark_q7_events_per_sec",
        "value": round(timed_events / elapsed, 1),
        "unit": "events/s",
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "events": timed_events,
    }


def main(argv):
    run_all = "--all" in argv
    results = {}
    # headline: the stateful device-kernel path (q7). q1 (stateless host
    # reference path) is reported alongside on --all.
    results["q7"] = bench_q7()
    headline = dict(results["q7"])
    if run_all:
        results["q1"] = bench_q1()
    headline["vs_baseline"] = round(
        headline["value"] / BASELINE_EVENTS_PER_SEC, 4)
    if run_all:
        print(json.dumps(results, indent=2), file=sys.stderr)
    print(json.dumps(headline))


if __name__ == "__main__":
    main(sys.argv[1:])
