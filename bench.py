"""Benchmark rig: Nexmark pipelines on the real chip.

Prints ONE JSON line: {"metric": ..., "value": N, "unit": ...,
"vs_baseline": N} — the driver records it in BENCH_r{N}.json.

Baseline (BASELINE.md): ≥1M events/sec/chip on Nexmark q7/q8 (one v5e).
The headline metric is the stateful device-kernel path (q7: HashAgg on
TPU). Run `python bench.py --all` for the full table (q1, q7, q8 and
TPC-H q3) on stderr. Pipelines come from risingwave_tpu.models.nexmark — the
benchmarked plan is exactly the tested plan (tests/test_e2e_q*.py).
"""

from __future__ import annotations

import asyncio
import json
import sys

BASELINE_EVENTS_PER_SEC = 1_000_000.0


def _result(metric, elapsed, rows, loop):
    return {
        "metric": metric,
        "value": round(rows / elapsed, 1),
        "unit": "events/s",
        "p99_barrier_latency_s": round(loop.stats.p99_latency_s(), 4),
        "events": rows,
    }


def bench_q1(total_events: int = 50 * 4000, chunk_size: int = 4096):
    """q1: source → project → materialize (stateless reference path)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q1, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size)
    p = build_q1(MemoryStateStore(), cfg, rate_limit=16, min_chunks=16)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(p, {1: n_bids}))
    return _result("nexmark_q1_events_per_sec", elapsed, rows, p.loop)


def bench_q7(total_events: int = 50 * 40_000, chunk_size: int = 8192):
    """q7 core: tumble-window MAX(price) on the device hash-agg kernel.

    The stateful baseline config (BASELINE.md: HashAgg on TPU, ≥1M
    events/s/chip)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size,
                        generate_strings=False)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=32, min_chunks=32)
    n_bids = total_events * 46 // 50
    elapsed, rows = asyncio.run(drive_to_completion(p, {1: n_bids}))
    return _result("nexmark_q7_events_per_sec", elapsed, rows, p.loop)


def bench_q8(total_events: int = 50 * 40_000, chunk_size: int = 4096):
    """q8: windowed person⋈auction inner join on the device matcher.

    Throughput counts rows entering the pipeline (persons + auctions)."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q8, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore

    base = NexmarkConfig(event_num=total_events, max_chunk_size=chunk_size)
    cfg_p = NexmarkConfig(**{**base.__dict__, "table_type": "person"})
    cfg_a = NexmarkConfig(**{**base.__dict__, "table_type": "auction"})
    p = build_q8(MemoryStateStore(), cfg_p, cfg_a, rate_limit=16,
                 min_chunks=16)
    targets = {1: total_events // 50, 2: total_events * 3 // 50}
    elapsed, rows = asyncio.run(drive_to_completion(p, targets))
    return _result("nexmark_q8_events_per_sec", elapsed, rows, p.loop)


def bench_q3(customers: int = 1500, orders: int = 15000):
    """TPC-H q3 streaming: 3-way join → agg → top-10 (BASELINE config).

    Throughput counts rows entering across all three tables."""
    from risingwave_tpu.connectors.tpch import LINES_PER_ORDER
    from risingwave_tpu.models.nexmark import drive_to_completion
    from risingwave_tpu.models.tpch import build_q3
    from risingwave_tpu.state.store import MemoryStateStore

    p = build_q3(MemoryStateStore(), customers=customers, orders=orders,
                 rate_limit=16, min_chunks=16)
    targets = {1: customers, 2: orders, 3: orders * LINES_PER_ORDER}
    elapsed, rows = asyncio.run(drive_to_completion(p, targets))
    return _result("tpch_q3_events_per_sec", elapsed, rows, p.loop)


def _probe_device(timeout_s: int = 180) -> None:
    """Fail over to CPU if the TPU backend cannot initialize.

    The axon tunnel can wedge (a killed client's remote claim takes
    time to expire); jax backend init then blocks with no timeout and
    the whole bench run would hang. Probe in a subprocess first; on
    timeout, force this process onto the CPU backend so the bench still
    reports a (clearly-labeled) number instead of nothing."""
    import os
    import subprocess
    try:
        subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s, capture_output=True, check=True)
        return
    except (subprocess.SubprocessError, OSError):
        print("WARNING: device backend unreachable — benching on CPU",
              file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")


def main(argv):
    from risingwave_tpu.utils.jaxtools import enable_compilation_cache
    _probe_device()
    enable_compilation_cache()
    import jax
    platform = jax.devices()[0].platform
    run_all = "--all" in argv
    results = {}
    # headline: the stateful device-kernel path (q7). q1 (stateless host
    # reference path), q8 (device join) and tpch q3 on --all.
    results["q7"] = bench_q7()
    headline = dict(results["q7"])
    if run_all:
        results["q1"] = bench_q1()
        results["q8"] = bench_q8()
        results["q3"] = bench_q3()
    headline["vs_baseline"] = round(
        headline["value"] / BASELINE_EVENTS_PER_SEC, 4)
    headline["platform"] = platform
    if run_all:
        print(json.dumps(results, indent=2), file=sys.stderr)
    print(json.dumps(headline))


if __name__ == "__main__":
    main(sys.argv[1:])
