"""Two-process deployment: coordinator + worker over the remote
exchange (VERDICT r3 #7).

The worker process hosts q8's source fragments INCLUDING the stateful
auction-side dedup agg (its kernel + value-state table live there); the
coordinator hosts the join + materialize and drives barriers through
its own BarrierLoop, with the worker participating as a pseudo-actor
(InjectBarrier/BarrierComplete over a JSON control channel). Both roles
checkpoint their own hummock namespaces at the same epochs.

Includes the kill-the-worker chaos case: SIGKILL mid-stream, restart
over the same stores, resume from the coordinator's committed epoch,
finish with exactly the oracle result.
"""

import asyncio

import pytest

from risingwave_tpu.cluster.coordinator import (
    WorkerBarrierSender, WorkerHandle,
)
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.connectors.nexmark import NexmarkConfig
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import LocalFsObjectStore
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.executors.hash_join import HashJoinExecutor
from risingwave_tpu.stream.executors.materialize import (
    MaterializeExecutor,
)
from risingwave_tpu.stream.message import StopMutation
from risingwave_tpu.stream.remote import RemoteInput
from tests.test_e2e_q8 import q8_oracle

PERSON_ACTOR, AUCTION_ACTOR, JOIN_ACTOR, WORKER_PSEUDO = 11, 12, 20, 999
P_SCHEMA = Schema.of(id=DataType.INT64, name=DataType.VARCHAR,
                     starttime=DataType.TIMESTAMP)
A_SCHEMA = Schema.of(seller=DataType.INT64,
                     starttime=DataType.TIMESTAMP)
EVENTS = 6000


def _q8_side_plans(event_num: int) -> tuple:
    """The two q8 source fragments as plan IR (the shipped-plan path
    replaced the old named-fragment registry): person → project, and
    auction → project → device dedup agg → project."""
    from risingwave_tpu.common.types import DataType, Interval
    from risingwave_tpu.connectors.nexmark import TABLE_SCHEMAS
    from risingwave_tpu.expr.expr import InputRef, tumble_start
    from risingwave_tpu.stream.plan_ir import expr_to_ir, schema_to_ir

    window = Interval(usecs=10_000_000)
    p = TABLE_SCHEMAS["person"]
    a = TABLE_SCHEMAS["auction"]

    def src(table, actor_id, split_tid):
        return {"op": "source", "name": table,
                "connector": {"connector": "nexmark",
                              "nexmark.table.type": table,
                              "nexmark.event.num": str(event_num),
                              "nexmark.max.chunk.size": "256"},
                "schema": schema_to_ir(TABLE_SCHEMAS[table]),
                "actor_id": actor_id, "split_table_id": split_tid,
                "rate_limit": 2}

    person_plan = [
        src("person", PERSON_ACTOR, 101),
        {"op": "project", "input": 0,
         "exprs": [
             expr_to_ir(InputRef(p.index_of("id"), DataType.INT64)),
             expr_to_ir(InputRef(p.index_of("name"), DataType.VARCHAR)),
             expr_to_ir(tumble_start(
                 InputRef(p.index_of("date_time"), DataType.TIMESTAMP),
                 window))],
         "names": ["id", "name", "starttime"]},
    ]
    auction_plan = [
        src("auction", AUCTION_ACTOR, 102),
        {"op": "project", "input": 0,
         "exprs": [
             expr_to_ir(InputRef(a.index_of("seller"), DataType.INT64)),
             expr_to_ir(tumble_start(
                 InputRef(a.index_of("date_time"), DataType.TIMESTAMP),
                 window))],
         "names": ["seller", "starttime"]},
        {"op": "hash_agg", "input": 1, "group": [0, 1],
         "calls": [{"kind": "count"}], "table_id": 103,
         "append_only": True,
         "output_names": ["seller", "starttime", "_cnt"]},
        {"op": "project", "input": 2,
         "exprs": [expr_to_ir(InputRef(0, DataType.INT64)),
                   expr_to_ir(InputRef(1, DataType.TIMESTAMP))],
         "names": ["seller", "starttime"]},
    ]
    return person_plan, auction_plan


async def _deploy_fragments(client, event_num: int) -> None:
    person_plan, auction_plan = _q8_side_plans(event_num)
    await client.deploy_plan(person_plan, down_actor=JOIN_ACTOR)
    await client.deploy_plan(auction_plan, down_actor=JOIN_ACTOR)


class _Coordinator:
    """Join + materialize side, barriers driven cross-process."""

    def __init__(self, client, coord_root: str):
        self.store = HummockLite(LocalFsObjectStore(coord_root))
        self.local = LocalBarrierManager()
        left = RemoteInput("127.0.0.1", client.exchange_port,
                           PERSON_ACTOR, JOIN_ACTOR, P_SCHEMA)
        right = RemoteInput("127.0.0.1", client.exchange_port,
                            AUCTION_ACTOR, JOIN_ACTOR, A_SCHEMA)
        lt = StateTable(4, P_SCHEMA, [0, 2], self.store,
                        dist_key_indices=[0])
        rt = StateTable(5, A_SCHEMA, [0, 1], self.store,
                        dist_key_indices=[0])
        join = HashJoinExecutor(left, right, left_keys=[0, 2],
                                right_keys=[0, 1], left_table=lt,
                                right_table=rt)
        self.mv = StateTable(6, join.schema, [0, 2], self.store)
        mat = MaterializeExecutor(join, self.mv)
        self.actor = Actor(JOIN_ACTOR, mat, dispatchers=[],
                           barrier_manager=self.local)
        self.loop = BarrierLoop(self.local, self.store)
        self.local.register_sender(
            WORKER_PSEUDO,
            WorkerBarrierSender(client, self.local, WORKER_PSEUDO))
        self.local.set_expected_actors([JOIN_ACTOR, WORKER_PSEUDO])

    async def run_epochs(self, n: int) -> None:
        for _ in range(n):
            await self.loop.inject_and_collect(force_checkpoint=True)

    async def stop(self) -> None:
        await self.loop.inject_and_collect(
            force_checkpoint=True,
            mutation=StopMutation(frozenset(
                {PERSON_ACTOR, AUCTION_ACTOR, JOIN_ACTOR,
                 WORKER_PSEUDO})))


def _mv_rows(coord: _Coordinator) -> set:
    # join output = left(id, name, starttime) ++ right(seller, start):
    # compare the q8 projection (id, name, starttime)
    return {(row[0], row[1], row[2])
            for _pk, row in coord.mv.iter_rows()}


def test_two_node_q8(tmp_path):
    worker_root = str(tmp_path / "worker")
    coord_root = str(tmp_path / "coord")

    async def main():
        handle = WorkerHandle(worker_root)
        client = await handle.start()
        try:
            await _deploy_fragments(client, EVENTS)
            coord = _Coordinator(client, coord_root)
            task = coord.actor.spawn()
            await coord.run_epochs(25)
            await coord.stop()
            await task
            assert coord.actor.failure is None
            return _mv_rows(coord)
        finally:
            await handle.stop()

    got = asyncio.run(main())
    cfg = NexmarkConfig(event_num=EVENTS)
    expect = q8_oracle(cfg, EVENTS // 50, EVENTS * 3 // 50)
    assert got == expect
    assert len(got) > 5


def test_two_node_q8_kill_worker_recovers(tmp_path):
    worker_root = str(tmp_path / "worker")
    coord_root = str(tmp_path / "coord")

    async def phase1():
        handle = WorkerHandle(worker_root)
        client = await handle.start()
        await _deploy_fragments(client, EVENTS)
        coord = _Coordinator(client, coord_root)
        task = coord.actor.spawn()
        await coord.run_epochs(6)
        # SIGKILL mid-stream: no goodbye, no flush
        handle.kill()
        with pytest.raises(Exception):
            await coord.run_epochs(3)
        task.cancel()

    async def phase2():
        handle = WorkerHandle(worker_root)
        client = await handle.start()
        try:
            await _deploy_fragments(client, EVENTS)
            coord = _Coordinator(client, coord_root)
            task = coord.actor.spawn()
            await coord.run_epochs(40)
            await coord.stop()
            await task
            assert coord.actor.failure is None
            return _mv_rows(coord)
        finally:
            await handle.stop()

    asyncio.run(phase1())
    got = asyncio.run(phase2())
    cfg = NexmarkConfig(event_num=EVENTS)
    expect = q8_oracle(cfg, EVENTS // 50, EVENTS * 3 // 50)
    assert got == expect
    assert len(got) > 5
