"""Sink executor tests: epoch framing, changelog delivery, file-sink
replay idempotence (sink.rs + log-store semantics)."""

import asyncio
import json

from risingwave_tpu.common.chunk import Op
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.executors.sink import (
    BlackholeSink, CollectSink, FileSink, SinkExecutor,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from tests.test_operators import barrier, chunk

S2 = Schema.of(k=DataType.INT64, v=DataType.INT64)


def test_sink_commits_per_epoch():
    sink = CollectSink()
    src = MockSource(S2, [
        barrier(1),
        chunk([1, 2], [10, 20]),
        barrier(2),
        chunk([3], [30], ops=[2]),
        barrier(3),
    ])
    ex = SinkExecutor(src, sink)
    asyncio.run(collect_until_n_barriers(ex, 3))
    assert len(sink.committed) == 2
    e1, recs1 = sink.committed[0]
    assert [r for _op, r in recs1] == [(1, 10), (2, 20)]
    _e2, recs2 = sink.committed[1]
    assert recs2 == [(Op.DELETE, (3, 30))]


def test_file_sink_replay_is_idempotent(tmp_path):
    path = str(tmp_path / "sink.jsonl")

    def run(script):
        src = MockSource(S2, script)
        ex = SinkExecutor(src, FileSink(path))
        asyncio.run(collect_until_n_barriers(
            ex, sum(1 for m in script if not hasattr(m, "ops"))))

    script = [barrier(1), chunk([1], [10]), barrier(2),
              chunk([2], [20]), barrier(3)]
    run(script)
    # crash + replay from the beginning: already-committed epochs skip
    run(script)
    with open(path) as f:
        lines = [json.loads(x) for x in f]
    rows = [tuple(x["row"]) for x in lines if "row" in x]
    assert rows == [(1, 10), (2, 20)]          # no duplicates
    epochs = [x["epoch"] for x in lines if "epoch" in x]
    assert epochs == sorted(set(epochs))


def test_sink_buffers_across_non_checkpoint_barriers():
    """sink.rs flush_current_epoch(.., is_checkpoint): only checkpoint
    barriers commit to the external system (ADVICE r2)."""
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.stream.message import Barrier, BarrierKind

    def plain(n):
        return Barrier(
            EpochPair(Epoch.from_physical(n), Epoch.from_physical(n - 1)),
            BarrierKind.BARRIER)

    sink = CollectSink()
    src = MockSource(S2, [
        barrier(1),
        chunk([1], [10]),
        plain(2),                  # non-checkpoint: must NOT commit
        chunk([2], [20]),
        barrier(3),                # checkpoint: commits epochs 1+2 data
    ])
    asyncio.run(collect_until_n_barriers(SinkExecutor(src, sink), 3))
    assert len(sink.committed) == 1
    _e, recs = sink.committed[0]
    assert [r for _op, r in recs] == [(1, 10), (2, 20)]


def test_blackhole_counts():
    sink = BlackholeSink()
    src = MockSource(S2, [barrier(1), chunk([1, 2, 3], [1, 2, 3]),
                          barrier(2)])
    asyncio.run(collect_until_n_barriers(SinkExecutor(src, sink), 2))
    assert sink.rows == 3 and sink.epochs == 1
