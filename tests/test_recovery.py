"""Whole-system recovery + chaos: kill the process mid-stream, reboot
from the object store, finish, and match an uninterrupted oracle run.

Mirrors the reference's deterministic-simulation stance (SURVEY §4:
madsim Cluster::kill_node + nexmark_recovery.rs) in one process: a
"kill" abandons the session without close() — unsynced shared-buffer
state and unpersisted offsets are genuinely lost — and a reboot
replays the DDL log and resumes from the committed epoch.
"""

import asyncio

from risingwave_tpu.frontend import Frontend
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import MemObjectStore

DDL = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num=12000, "
       "nexmark.max.chunk.size=512, "
       "nexmark.min.event.gap.in.ns=100000000); "
       "CREATE MATERIALIZED VIEW q7 AS SELECT window_start, "
       "MAX(price) AS max_price, COUNT(*) AS cnt "
       "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
       "GROUP BY window_start")

QUERY = "SELECT window_start, max_price, cnt FROM q7 ORDER BY window_start"

N_BIDS = 12000 * 46 // 50


def _exhausted(fe: Frontend) -> bool:
    return all(r.offset >= N_BIDS
               for rs in fe.readers.values() for r in rs.values())


async def _drive_until_done(fe: Frontend, max_steps: int = 200) -> None:
    for _ in range(max_steps):
        if _exhausted(fe):
            break
        await fe.step(1)
    else:
        raise RuntimeError("sources never exhausted")
    await fe.step(1)          # final checkpoint past the last chunk


def _oracle():
    async def run():
        fe = Frontend(HummockLite(MemObjectStore()), min_chunks=4)
        await fe.execute(DDL)
        await _drive_until_done(fe)
        rows = await fe.execute(QUERY)
        await fe.close()
        return rows

    return asyncio.run(run())


def test_sql_session_kill_restart_resumes():
    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.execute(DDL)
        await fe.step(5)
        # KILL: no close(), no stop barrier — tasks die with the loop;
        # anything not checkpointed is lost
        return sum(r.offset for rs in fe.readers.values()
                   for r in rs.values())

    async def phase2():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        replayed = await fe.recover()
        assert replayed == 2
        # offsets resumed from committed state, not from zero
        resumed = sum(r.offset for rs in fe.readers.values()
                      for r in rs.values())
        await _drive_until_done(fe)
        rows = await fe.execute(QUERY)
        names = await fe.execute("SHOW MATERIALIZED VIEWS")
        await fe.close()
        return resumed, rows, names

    offset1 = asyncio.run(phase1())
    assert offset1 > 0
    resumed, rows, names = asyncio.run(phase2())
    assert resumed > 0                    # did not restart from scratch
    assert names == [("q7",)]
    assert rows == _oracle()


def test_kill_with_uploads_in_flight_recovers_and_matches_oracle():
    """Pipelined (run-mode) barrier driving over a SLOW object store:
    kill the session while checkpoint uploads are still in flight —
    recovery resumes from the last FULLY committed epoch (the async
    pipeline's ordered-commit invariant) and the finished result
    equals the uninterrupted oracle."""
    from risingwave_tpu.storage.object_store import DelayedObjectStore

    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(HummockLite(DelayedObjectStore(obj, delay_s=0.2)),
                      min_chunks=4)
        await fe.execute(DDL)
        loop = fe.loop
        # bench-style pipelined driving: no uploader drain between
        # barriers, so uploads pile up behind the slow store
        for _ in range(6):
            while loop.in_flight_count < 2:
                await loop.inject(force_checkpoint=True)
            await loop.collect_next()
        assert loop.uploading_count > 0    # in flight at the kill
        # KILL: no close(), no drain — the in-flight epochs' commits
        # never land; only fully committed epochs may survive

    asyncio.run(phase1())

    async def phase2():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        assert await fe.recover() == 2
        # recover() already vacuumed the dead generation's residue
        # (uploaded-but-uncommitted SSTs + its deferred-compaction
        # garbage): nothing unreferenced is left behind
        assert fe.store.vacuum_orphans() == 0
        await _drive_until_done(fe)
        rows = await fe.execute(QUERY)
        await fe.close()
        return rows

    assert asyncio.run(phase2()) == _oracle()


def test_chaos_repeated_kills_match_oracle():
    """Three generations, each killed after a few epochs; the final
    result must still equal the uninterrupted run (nexmark_recovery.rs
    analog)."""
    obj = MemObjectStore()

    async def gen(steps):
        fe = Frontend(HummockLite(obj), min_chunks=4)
        replayed = await fe.recover()
        if replayed == 0:
            await fe.execute(DDL)
        for _ in range(steps):
            if _exhausted(fe):
                break
            await fe.step(1)
        return fe

    async def run_all():
        for steps in (3, 4, 5):
            await gen(steps)              # killed: no close, no stop
        fe = await gen(10**6)
        await _drive_until_done(fe)
        rows = await fe.execute(QUERY)
        await fe.close()
        return rows

    assert asyncio.run(run_all()) == _oracle()


def test_ddl_after_recovery_preserves_log():
    """DDL executed after a recovery must extend — not overwrite — the
    persisted DDL log, or the next recovery loses the catalog."""
    obj = MemObjectStore()

    async def gen1():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.execute(DDL)                       # source + q7

    async def gen2():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        assert await fe.recover() == 2
        await fe.execute("CREATE MATERIALIZED VIEW extra AS "
                         "SELECT auction FROM bid")
        await fe.step(1)

    async def gen3():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        assert await fe.recover() == 3
        names = await fe.execute("SHOW MATERIALIZED VIEWS")
        await fe.close()
        return names

    asyncio.run(gen1())
    asyncio.run(gen2())
    assert asyncio.run(gen3()) == [("extra",), ("q7",)]


def test_backup_restore_fresh_cluster():
    """meta/backup: a consistent snapshot (DDL log + hummock version +
    SST closure) restores into a FRESH root; a new session recovers
    the catalog, state, and source offsets and keeps streaming
    (backup_restore/ parity)."""
    from risingwave_tpu.meta.backup import (
        create_backup, delete_backup, list_backups, restore_backup,
    )
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def phase1():
        f = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        await f.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=4000, "
            "nexmark.max.chunk.size=256)")
        await f.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        for _ in range(4):
            await f.step()
        rows = await f.execute("SELECT * FROM v")
        await f.close()
        return rows

    asyncio.run(phase1())
    bid = create_backup(obj)
    assert list_backups(obj) == [bid]

    # what the source-of-truth says AS OF the backup (recover, no
    # steps), then keep running PAST the backup point
    async def as_of_then_go():
        f = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        await f.recover()
        rows = await f.execute("SELECT * FROM v")
        for _ in range(4):
            await f.step()
        await f.close()
        return rows

    mid_rows = asyncio.run(as_of_then_go())

    # restore the backup into a fresh root: state is AS OF the backup
    fresh = MemObjectStore()
    restore_backup(obj, bid, fresh)

    async def phase2():
        f = Frontend(HummockLite(fresh), rate_limit=2, min_chunks=2)
        n = await f.recover()
        assert n >= 2
        rows = await f.execute("SELECT * FROM v")
        # and the restored cluster streams on from the backed-up offset
        for _ in range(20):
            await f.step()
        final = await f.execute("SELECT * FROM v")
        await f.close()
        return rows, final

    restored, final = asyncio.run(phase2())
    assert sorted(restored) == sorted(mid_rows)    # exact as-of state
    n_bids = 4000 * 46 // 50
    assert sum(c for _a, c in final) == n_bids     # streams to the end

    # refuse restoring over a non-empty root
    import pytest
    with pytest.raises(ValueError, match="empty"):
        restore_backup(obj, bid, obj)
    assert delete_backup(obj, bid) > 0
    assert list_backups(obj) == []
