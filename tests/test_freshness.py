"""Per-MV event-time freshness tests (ISSUE 14): barrier-lineage lag
accounting, the cross-process merge, and the SQL/system-table/history
surfaces."""

import asyncio

import pytest

from risingwave_tpu.stream.freshness import (
    FRESHNESS, FreshnessTracker, chunk_event_hwm, event_time_index,
)


def test_tracker_lag_math():
    t = FreshnessTracker()
    t.register_mv("mv1", ["src"], domain="d1")
    # ingest up to event-time 1_000_000us, then the barrier frontier
    t.note_ingest("src", 1_000_000, wall_s=100.0)
    t.note_source_barrier("src", 7)
    # by visibility time the source is 5s of event time ahead
    t.note_ingest("src", 6_000_000, wall_s=101.0)
    t.note_visible("mv1", 7, wall_s=102.5)
    rows = {r[0]: r for r in t.rows()}
    mv, domain, n, epoch, lag, wall_lag, p50, p99, wp99 = rows["mv1"]
    assert domain == "d1"
    assert n == 1 and epoch == 7
    assert lag == pytest.approx(5.0, abs=1e-6)
    assert wall_lag > 0
    assert p99 == pytest.approx(5.0, abs=1e-6)
    assert not t.gate_violations()


def test_tracker_multi_source_takes_worst_lag():
    t = FreshnessTracker()
    t.register_mv("mv", ["a", "b"])
    t.note_ingest("a", 1_000_000)
    t.note_ingest("b", 1_000_000)
    t.note_source_barrier("a", 3)
    t.note_source_barrier("b", 3)
    t.note_ingest("a", 2_000_000)   # a: 1s ahead
    t.note_ingest("b", 9_000_000)   # b: 8s ahead — the worst source
    t.note_visible("mv", 3)
    lag = t.rows()[0][4]
    assert lag == pytest.approx(8.0, abs=1e-6)


def test_pending_visibility_resolves_on_ingest_merge():
    """Cross-process shape: the materialize fragment's tracker has no
    source frontier — its visibility event parks pending and resolves
    when the source worker's parts merge in."""
    src_worker = FreshnessTracker()
    src_worker.note_ingest("s", 500_000, wall_s=10.0)
    src_worker.note_source_barrier("s", 11)
    src_worker.note_ingest("s", 1_500_000, wall_s=11.0)

    coord = FreshnessTracker()
    coord.register_mv("mv", ["s"])
    coord.note_visible("mv", 11, wall_s=12.0)       # frontier unknown
    assert coord.rows()[0][2] == 0                  # no sample yet
    n = coord.ingest(src_worker.drain_dict())
    assert n == 1
    mv_row = coord.rows()[0]
    assert mv_row[2] == 1
    assert mv_row[4] == pytest.approx(1.0, abs=1e-6)
    # repeated drains never double-count: the source worker's pendings
    # left with the first drain
    assert coord.ingest(src_worker.drain_dict()) == 0
    assert coord.rows()[0][2] == 1


def test_worker_unregistered_visibility_ships_to_coordinator():
    """The real cluster shape: registration lives ONLY on the
    coordinator. A worker's materialize fragment (tracker with no
    _mvs entry) must PARK its visibility event so drain_dict ships it
    — dropping it would make the whole drain_freshness chain a
    no-op."""
    src_worker = FreshnessTracker()
    src_worker.note_ingest("s", 500_000, wall_s=10.0)
    src_worker.note_source_barrier("s", 21)
    src_worker.note_ingest("s", 2_500_000, wall_s=11.0)

    mat_worker = FreshnessTracker()          # no registration here
    mat_worker.note_visible("mv", 21, wall_s=12.0)
    parts = mat_worker.drain_dict()
    assert parts["visible"], "worker must ship the visibility event"

    coord = FreshnessTracker()
    coord.register_mv("mv", ["s"])
    coord.ingest(src_worker.drain_dict())
    assert coord.ingest(parts) == 1
    row = coord.rows()[0]
    assert row[2] == 1
    assert row[4] == pytest.approx(2.0, abs=1e-6)


def test_empty_frontier_never_mints_negative_lag():
    """A source passing a barrier BEFORE ingesting anything records an
    empty frontier marker — later historical event times must yield
    lag 0 (nothing was visible), never a negative wall-vs-event-time
    artifact."""
    t = FreshnessTracker()
    t.register_mv("mv", ["s"])
    t.note_source_barrier("s", 9)            # nothing ingested yet
    # historical event times (a 2015-style dataset), far below any
    # wall-clock microsecond value
    t.note_ingest("s", 1_436_918_400_000_000)
    t.note_visible("mv", 9)
    row = t.rows()[0]
    assert row[2] == 1
    assert row[4] == 0.0                     # lag_s: empty frontier
    assert row[5] >= 0.0                     # wall_lag_s
    assert not t.gate_violations()


def test_duplicate_slice_visibility_dedupes():
    t = FreshnessTracker()
    t.register_mv("mv", ["s"])
    t.note_ingest("s", 1_000_000)
    t.note_source_barrier("s", 5)
    t.note_visible("mv", 5)
    t.note_visible("mv", 5)      # a second slice of the same MV
    assert t.rows()[0][2] == 1


def test_event_time_index_and_chunk_hwm():
    import numpy as np
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.common.types import DataType, Field, Schema
    sch = Schema([Field("id", DataType.INT64),
                  Field("ts", DataType.TIMESTAMP)])
    assert event_time_index(sch) == 1
    assert event_time_index(
        Schema([Field("id", DataType.INT64)])) is None
    chunk = StreamChunk.from_pydict(
        sch, {"id": [1, 2, 3], "ts": [100, 900, 300]})
    assert chunk_event_hwm(chunk, 1) == 900
    assert chunk_event_hwm(chunk, None) is None
    # invisible rows don't count
    vis = np.asarray(chunk.visibility).copy()
    vis[:] = False
    masked = StreamChunk(chunk.schema, chunk.columns, vis, chunk.ops)
    assert chunk_event_hwm(masked, 1) is None


def test_session_freshness_end_to_end():
    """SQL front door: per-MV samples land with finite non-negative
    lags, rw_mv_freshness serves them, rw_metrics_history carries the
    per-barrier freshness rows, and DROP unregisters."""
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=4000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW fresh_mv AS SELECT "
            "window_start, COUNT(*) AS c FROM TUMBLE(bid, date_time, "
            "INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.step(4)
        fresh = await fe.execute("SELECT * FROM rw_mv_freshness")
        hist = await fe.execute(
            "SELECT * FROM rw_metrics_history")
        gauge_rows = FRESHNESS.summary()
        await fe.execute("DROP MATERIALIZED VIEW fresh_mv")
        after_drop = FRESHNESS.summary()
        await fe.close()
        return fresh, hist, gauge_rows, after_drop

    fresh, hist, summary, after_drop = asyncio.run(run())
    row = next(r for r in fresh if r[0] == "fresh_mv")
    assert row[2] > 0                       # samples recorded
    assert row[4] is not None and row[4] >= 0.0   # lag_s
    assert row[5] is not None and row[5] >= 0.0   # wall_lag_s
    assert "fresh_mv" in summary
    assert summary["fresh_mv"]["wall_lag_p99_s"] >= 0.0
    # per-barrier history rows carry the freshness series
    names = {r[4] for r in hist}
    assert "freshness_lag_s.fresh_mv" in names
    assert "freshness_wall_lag_s.fresh_mv" in names
    assert "fresh_mv" not in after_drop


def test_table_dml_freshness():
    """CREATE TABLE jobs sample freshness through their DML source."""
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE t1 (v BIGINT, ts TIMESTAMP)")
        await fe.execute(
            "INSERT INTO t1 VALUES (1, '2024-01-01 00:00:00')")
        await fe.step(2)
        rows = await fe.execute(
            "SELECT mv, samples FROM rw_mv_freshness")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    row = next(r for r in rows if r[0] == "t1")
    assert row[1] > 0
