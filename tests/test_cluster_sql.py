"""SQL → N-worker cluster deployment (VERDICT r4 #1).

CREATE MATERIALIZED VIEW on the DistFrontend plans with the ordinary
StreamPlanner, fragments the executor tree at hash exchanges, and lands
the fragments on 2 worker processes with worker↔worker remote exchange.
The in-process Frontend over identical sources is the oracle: the
distributed cluster must produce exactly the same MV rows.

Covers: q8-shaped windowed join across 2 workers (hash exchange on the
join keys), parallel GROUP BY agg (hash exchange on group keys),
SIGKILL-one-worker full recovery to the committed epoch, and a
reschedule that moves a fragment's actor between workers with state
handoff.
"""

import asyncio

import pytest

from risingwave_tpu.cluster.session import DistFrontend
from risingwave_tpu.frontend.session import Frontend

EVENTS = 6000

Q8_SOURCES = (
    "CREATE SOURCE person WITH (connector='nexmark', "
    "nexmark.table.type='person', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.min.event.gap.in.ns=50000000)",
    "CREATE SOURCE auction WITH (connector='nexmark', "
    "nexmark.table.type='auction', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.min.event.gap.in.ns=50000000)",
)

Q8_MV = (
    "CREATE MATERIALIZED VIEW q8 AS "
    "SELECT p.id, p.name, p.window_start "
    "FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) AS p "
    "JOIN TUMBLE(auction, date_time, INTERVAL '10' SECOND) AS a "
    "ON p.id = a.seller AND p.window_start = a.window_start")

Q7ISH_SOURCES = (
    "CREATE SOURCE bid WITH (connector='nexmark', "
    "nexmark.table.type='bid', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.min.event.gap.in.ns=50000000)",
)

Q7ISH_MV = (
    "CREATE MATERIALIZED VIEW q7 AS "
    "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
    "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    "GROUP BY window_start")


def _inprocess_oracle(sources, mv_sql, select_sql, events=EVENTS,
                      steps=30):
    """Run the same job on the single-process session → row set."""
    async def run():
        fe = Frontend(min_chunks=8)
        for s in sources:
            await fe.execute(s.format(n=events))
        await fe.execute(mv_sql)
        await fe.step(steps)
        rows = await fe.execute(select_sql)
        await fe.close()
        return rows

    return {tuple(r) for r in asyncio.run(run())}


def test_dist_q8_two_workers(tmp_path):
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q8_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q8_MV)
            await fe.step(30)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q8")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q8_SOURCES, Q8_MV, "SELECT * FROM q8")
    assert got == expect
    assert len(got) > 5


def test_dist_parallel_agg_two_workers(tmp_path):
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q7ISH_MV)
            await fe.step(30)
            job = fe.cluster.jobs["q7"]
            # the GLOBAL agg fragment (exchange-fed; the local phase
            # colocates with the source) is parallel over both workers
            agg_frag = [fi for fi, f in
                        enumerate(job.graph.fragments)
                        if f.inputs and any(n["op"] == "hash_agg"
                                            for n in f.nodes)][0]
            slots = {s for _a, s in job.placements[agg_frag]}
            assert slots == {0, 1}, slots
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q7ISH_SOURCES, Q7ISH_MV,
                               "SELECT * FROM q7")
    assert got == expect
    assert len(got) > 2


def test_dist_kill_worker_recovers(tmp_path):
    """SIGKILL one worker mid-stream: the next barrier fails, recovery
    restarts every slot over its namespace, discards the uncommitted
    staged epoch, redeploys, and the job finishes oracle-exact."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q8_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q8_MV)
            await fe.step(5)
            fe.cluster.kill_slot(1)      # no goodbye, no flush
            with pytest.raises(Exception):
                await fe.step(3)
            await fe.recover()
            await fe.step(40)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q8")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q8_SOURCES, Q8_MV, "SELECT * FROM q8")
    assert got == expect
    assert len(got) > 5


def test_dist_move_fragment_between_workers(tmp_path):
    """Reschedule: move the agg fragment's actors between workers at a
    stopped barrier (scan+ingest state handoff), finish, stay exact."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=1)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q7ISH_MV)
            await fe.step(4)
            job = fe.cluster.jobs["q7"]
            # parallelism=1 → agg colocated with the source chain in
            # one fragment; move that single actor to the other slot
            frag_idx = len(job.graph.fragments) - 1
            old_slot = job.placements[frag_idx][0][1]
            new_slot = 1 - old_slot
            await fe.cluster.move_fragment("q7", frag_idx, [new_slot])
            assert job.placements[frag_idx][0][1] == new_slot
            await fe.step(30)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q7ISH_SOURCES, Q7ISH_MV,
                               "SELECT * FROM q7")
    assert got == expect
    assert len(got) > 2


def test_dist_topn_overwindow_projectset(tmp_path):
    """The rest of the executor set ships through plan IR (VERDICT r4
    #7): ORDER BY/LIMIT (singleton TopN fragment behind the parallel
    agg), ROW_NUMBER() OVER a derived table, and generate_series —
    each deployed across 2 workers and checked against the in-process
    session."""
    sqls = [
        ("q105",
         "CREATE MATERIALIZED VIEW q105 AS SELECT auction, count(*) "
         "AS num FROM bid GROUP BY auction ORDER BY num DESC LIMIT 5",
         "SELECT * FROM q105"),
        ("q9",
         "CREATE MATERIALIZED VIEW q9 AS SELECT auction, price "
         "FROM (SELECT auction, price, row_number() OVER ("
         "PARTITION BY auction ORDER BY price DESC) AS rn FROM bid) "
         "AS t WHERE rn = 1",
         "SELECT * FROM q9"),
        ("ps",
         "CREATE MATERIALIZED VIEW ps AS SELECT auction, "
         "generate_series(1, 3) AS s FROM bid WHERE auction = 1001",
         "SELECT * FROM ps"),
    ]

    async def run_dist():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            out = {}
            for name, mv, sel in sqls:
                await fe.execute(mv)
            await fe.step(25)
            for name, _mv, sel in sqls:
                out[name] = {tuple(r) for r in await fe.execute(sel)}
            return out
        finally:
            await fe.close()

    got = asyncio.run(run_dist())

    def orc(name):
        mv = next(m for n, m, _s in sqls if n == name)
        sel = next(s for n, _m, s in sqls if n == name)
        return _inprocess_oracle(Q7ISH_SOURCES, mv, sel)

    for name in ("q105", "q9", "ps"):
        assert got[name] == orc(name), name
        assert len(got[name]) > 0, name


def test_dist_two_phase_agg(tmp_path):
    """Two-phase aggregation (VERDICT r4 #4): the local partial agg
    colocates with the source fragment, the global merge agg sits
    behind the hash exchange, EXPLAIN shows the split, and results
    match the single-phase in-process session exactly."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            plan = await fe.execute(
                "EXPLAIN " + Q7ISH_MV.split(" AS ", 1)[1])
            text = "\n".join(r[0] for r in plan)
            assert "phase=local" in text and "phase=global" in text, \
                text
            await fe.execute(Q7ISH_MV)
            job = fe.cluster.jobs["q7"]
            # fragment 0 = source + LOCAL agg; the exchange feeds the
            # global agg fragment
            ops0 = [n["op"] for n in job.graph.fragments[0].nodes]
            assert "hash_agg" in ops0, ops0
            n_aggs = sum(n["op"] == "hash_agg"
                         for f in job.graph.fragments
                         for n in f.nodes)
            assert n_aggs == 2, n_aggs
            await fe.step(30)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q7ISH_SOURCES, Q7ISH_MV,
                               "SELECT * FROM q7")
    assert got == expect
    assert len(got) > 2


def test_dist_adctr_two_workers(tmp_path):
    """ad-ctr (BASELINE config #5) across 2 workers: filelog sources →
    HOP windows → hash join → TEMPORAL dim join (arrangement broadcast
    to every join actor; the dim view distributes by inlining its
    definition) → two-phase agg. Oracle = the in-process session."""
    import json as _json
    import os

    import numpy as np

    n_impressions, n_ads, click_every = 900, 12, 3
    base_ts = 1_700_000_000_000_000
    data = str(tmp_path / "logs")
    os.makedirs(data)
    rng = np.random.default_rng(7)
    ads = rng.integers(0, n_ads, n_impressions)
    with open(os.path.join(data, "impressions-0.log"), "wb") as f:
        for i in range(n_impressions):
            f.write(_json.dumps({
                "bid_id": i, "ad_id": int(ads[i]),
                "its": base_ts + i * 10_000}).encode() + b"\n")
    with open(os.path.join(data, "clicks-0.log"), "wb") as f:
        for i in range(0, n_impressions, click_every):
            f.write(_json.dumps({
                "cbid": i, "cts": base_ts + i * 10_000 + 500}).encode()
                + b"\n")

    sqls = [
        f"CREATE SOURCE impression (bid_id BIGINT, ad_id BIGINT, "
        f"its TIMESTAMP) WITH (connector='filelog', path='{data}', "
        f"topic='impressions')",
        f"CREATE SOURCE click (cbid BIGINT, cts TIMESTAMP) WITH "
        f"(connector='filelog', path='{data}', topic='clicks')",
        "CREATE MATERIALIZED VIEW ad_dim AS SELECT ad_id, count(*) "
        "AS seen FROM impression GROUP BY ad_id",
        "CREATE MATERIALIZED VIEW ad_ctr AS SELECT i.ad_id, "
        "i.window_start, count(*) AS clicked "
        "FROM HOP(impression, its, INTERVAL '2' SECOND, "
        "INTERVAL '10' SECOND) AS i "
        "JOIN click AS c ON i.bid_id = c.cbid "
        "JOIN ad_dim AS d FOR SYSTEM_TIME AS OF PROCTIME() "
        "ON i.ad_id = d.ad_id "
        "GROUP BY i.ad_id, i.window_start",
    ]

    async def run_dist():
        fe = DistFrontend(str(tmp_path / "cluster"), n_workers=2,
                          parallelism=2)
        await fe.start()
        try:
            for s in sqls:
                await fe.execute(s)
            await fe.step(30)
            ctr = {tuple(r)
                   for r in await fe.execute("SELECT * FROM ad_ctr")}
            dim = {tuple(r)
                   for r in await fe.execute("SELECT * FROM ad_dim")}
            return ctr, dim
        finally:
            await fe.close()

    async def run_local():
        fe = Frontend(min_chunks=8)
        for s in sqls:
            await fe.execute(s)
        await fe.step(30)
        ctr = {tuple(r)
               for r in await fe.execute("SELECT * FROM ad_ctr")}
        dim = {tuple(r)
               for r in await fe.execute("SELECT * FROM ad_dim")}
        await fe.close()
        return ctr, dim

    got_ctr, got_dim = asyncio.run(run_dist())
    exp_ctr, exp_dim = asyncio.run(run_local())
    assert got_dim == exp_dim
    assert got_ctr == exp_ctr
    assert len(got_ctr) > 5


def test_dist_rescale_parallelism_sql(tmp_path):
    """True elastic rescale across workers: ALTER … SET PARALLELISM
    changes the agg fragment's actor count mid-stream; every state row
    moves to its vnode's new owner (vnode-sliced handoff) and the
    final result stays oracle-exact. 2 → 1 → 3 actors."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q7ISH_MV)
            await fe.step(6)
            await fe.execute(
                "ALTER MATERIALIZED VIEW q7 SET PARALLELISM = 1")
            await fe.step(6)
            await fe.execute(
                "ALTER MATERIALIZED VIEW q7 SET PARALLELISM = 3")
            job = fe.cluster.jobs["q7"]
            agg_frag = [fi for fi, f in
                        enumerate(job.graph.fragments)
                        if f.inputs][0]
            assert len(job.placements[agg_frag]) == 3
            await fe.step(30)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    got = asyncio.run(run())
    expect = _inprocess_oracle(Q7ISH_SOURCES, Q7ISH_MV,
                               "SELECT * FROM q7")
    assert got == expect
    assert len(got) > 2


def test_dist_rescale_in_shared_domain(tmp_path):
    """ISSUE 13 regression: rescaling a job that SHARES its barrier
    domain with another live job (two MVs on one source) must not
    abort — the redeployed job rejoins the live domain, whose cursor
    re-anchors monotonely past the handoff epochs, and BOTH MVs stay
    oracle-exact."""
    MV2 = ("CREATE MATERIALIZED VIEW q7cnt AS "
           "SELECT auction, COUNT(*) AS cnt FROM bid "
           "GROUP BY auction")

    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in Q7ISH_SOURCES:
                await fe.execute(s.format(n=EVENTS))
            await fe.execute(Q7ISH_MV)
            await fe.execute(MV2)
            plane = fe.cluster._plane
            assert plane is not None
            # shared source ⇒ one live domain holding both jobs
            dom = plane.domain_of_job("q7")
            assert plane.domain_of_job("q7cnt") == dom
            await fe.step(6)
            await fe.execute(
                "ALTER MATERIALIZED VIEW q7 SET PARALLELISM = 1")
            assert plane.domain_of_job("q7") == \
                plane.domain_of_job("q7cnt")
            await fe.step(30)
            a = {tuple(r)
                 for r in await fe.execute("SELECT * FROM q7")}
            b = {tuple(r)
                 for r in await fe.execute("SELECT * FROM q7cnt")}
            return a, b
        finally:
            await fe.close()

    a, b = asyncio.run(run())
    assert a == _inprocess_oracle(Q7ISH_SOURCES, Q7ISH_MV,
                                  "SELECT * FROM q7")
    assert b == _inprocess_oracle(Q7ISH_SOURCES, MV2,
                                  "SELECT * FROM q7cnt")
    assert len(a) > 2 and len(b) > 2
