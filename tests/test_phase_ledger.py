"""Epoch phase ledger (ISSUE 11): scoped-phase accounting units, the
conservation gate, transfer-bytes exactness, worker-merge on a real
2-worker cluster, the ledger-on-vs-off q7 oracle, and the
rw_metrics_history per-barrier feed over SQL.
"""

import asyncio
import json
import time

import numpy as np
import pytest

from risingwave_tpu.utils import ledger as ledger_mod
from risingwave_tpu.utils import spans as spans_mod
from risingwave_tpu.utils.ledger import (
    LEDGER, AttributionCell, PhaseLedger, UNATTRIBUTED,
)
from risingwave_tpu.utils.metrics import HISTORY, STREAMING

EVENTS = 4000

BID_SOURCE = (
    "CREATE SOURCE bid WITH (connector='nexmark', "
    "nexmark.table.type='bid', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.min.event.gap.in.ns=50000000)")

Q7ISH_MV = (
    "CREATE MATERIALIZED VIEW q7 AS "
    "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
    "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    "GROUP BY window_start")


@pytest.fixture(autouse=True)
def _fresh_ledger():
    """Each test starts with an empty ledger/history and the ledger ON
    (the conftest conservation gate also clears records around every
    test; this additionally resets the history ring and the epoch
    key)."""
    LEDGER.clear()
    HISTORY.clear()
    ledger_mod.set_enabled(True)
    spans_mod.set_current_epoch(0)
    yield
    LEDGER.clear()
    HISTORY.clear()
    ledger_mod.set_enabled(True)


# -- scoping / nesting units ----------------------------------------------


def test_phase_scopes_are_exclusive_under_nesting():
    """A nested scope's time is charged to the INNER phase only; phase
    totals never double-count a wall-clock second."""
    led = PhaseLedger()
    spans_mod.set_current_epoch(42)
    with led.phase("host_pack"):
        time.sleep(0.03)
        with led.phase("h2d"):
            time.sleep(0.03)
    rec = led.seal(42, 0.08)
    pack, h2d = rec.seconds["host_pack"], rec.seconds["h2d"]
    assert 0.02 <= pack <= 0.06, rec.seconds
    assert 0.02 <= h2d <= 0.06, rec.seconds
    # exclusivity: the two phases together cover ~the elapsed wall
    # clock once, not the outer scope twice
    assert pack + h2d <= 0.08 + 0.02


def test_cell_commit_routes_epoch_exactly_and_tracks_bytes():
    """Scopes fired under an executor cell land in the cell (not the
    newest injected epoch) and commit to the BARRIER's epoch — the
    pipelined-inject attribution fix."""
    led = PhaseLedger()
    spans_mod.set_current_epoch(99)     # newest injected
    cell = AttributionCell()
    tok = led.push_cell(cell)
    try:
        with led.phase("host_pack"):
            time.sleep(0.01)
        led.add_bytes("h2d", 1234, kernel="unit-cell")
    finally:
        led.pop_cell(tok)
    assert cell.seconds["host_pack"] > 0
    assert cell.h2d_bytes == 1234
    # nothing leaked into epoch 99's open accumulator
    led.commit_cell(7, cell)            # the barrier's CURR epoch
    rec99 = led.seal(99, 0.001)
    assert "host_pack" not in rec99.seconds
    rec7 = led.seal(7, 0.02)
    assert rec7.seconds["host_pack"] > 0
    assert rec7.h2d_bytes == 1234
    # the cell drained at commit
    assert cell.named_total() == 0 and cell.h2d_bytes == 0


def test_conservation_residual_and_gate_exemptions():
    led = PhaseLedger()
    led.attribute("device_compute", 0.1, epoch=1)
    rec = led.seal(1, 1.0)
    assert rec.seconds[UNATTRIBUTED] == pytest.approx(0.9)
    assert rec.coverage() == pytest.approx(0.1)
    assert len(led.gate_violations()) == 1
    # a compile-bearing (warmup) epoch is exempt
    spans_mod.set_current_epoch(2)
    led.note_compile()
    led.seal(2, 1.0)
    # a mutation/topology barrier is exempt via the warmup flag
    led.seal(3, 1.0, warmup=True)
    # an unmerged distributed record is exempt (conservation defers
    # to the worker-ledger merge)
    led.seal(4, 1.0, distributed=True)
    assert len(led.gate_violations()) == 1


def test_ledger_off_records_nothing():
    led = PhaseLedger()
    ledger_mod.set_enabled(False)
    with led.phase("host_pack"):
        time.sleep(0.005)
    led.add_bytes("h2d", 999, kernel="off-test")
    assert led.seal(5, 1.0) is None
    assert list(led.records) == []
    assert STREAMING.transfer_bytes.get(dir="h2d",
                                        kernel="off-test") == 0.0


def test_worker_merge_recomputes_residual():
    """ingest() folds a drained worker accumulator into the sealed
    record of the same epoch and re-derives `unattributed`."""
    led = PhaseLedger()
    rec = led.seal(11, 1.0, distributed=True)
    assert rec.unattributed_s == pytest.approx(1.0)
    n = led.ingest([{"epoch": 11,
                     "seconds": {"host_emit": 0.7},
                     "h2d_bytes": 10, "d2h_bytes": 20}],
                   worker="worker-0")
    assert n == 1
    assert rec.seconds["host_emit"] == pytest.approx(0.7)
    assert rec.unattributed_s == pytest.approx(0.3)
    assert rec.workers == ["worker-0"]
    assert not rec.distributed          # conservation now checkable
    assert rec.h2d_bytes == 10 and rec.d2h_bytes == 20


# -- transfer bytes exactness ----------------------------------------------


def test_transfer_bytes_exact_for_known_upload_and_fetch():
    from risingwave_tpu.utils import jaxtools

    arr = np.arange(512, dtype=np.int32).reshape(128, 4)   # 2048 B
    spans_mod.set_current_epoch(21)
    h0 = STREAMING.transfer_bytes.get(dir="h2d", kernel="unit-xfer")
    d0 = STREAMING.transfer_bytes.get(dir="d2h", kernel="unit-xfer")
    dev = jaxtools.upload(arr, kernel="unit-xfer")
    assert STREAMING.transfer_bytes.get(
        dir="h2d", kernel="unit-xfer") - h0 == arr.nbytes
    with LEDGER.kernel_scope("unit-xfer"):
        [back] = jaxtools.fetch(dev)
    assert np.array_equal(back, arr)
    assert STREAMING.transfer_bytes.get(
        dir="d2h", kernel="unit-xfer") - d0 == arr.nbytes
    # host numpy pass-throughs never count as transfers
    with LEDGER.kernel_scope("unit-xfer"):
        jaxtools.fetch(arr)
    assert STREAMING.transfer_bytes.get(
        dir="d2h", kernel="unit-xfer") - d0 == arr.nbytes
    # and the per-epoch accumulators carry the same exact bytes
    rec = LEDGER.seal(21, 1.0, warmup=True)
    assert rec.h2d_bytes == arr.nbytes
    assert rec.d2h_bytes == arr.nbytes


def test_kernel_cost_analysis_surfaces():
    """instrumented_jit captures call shapes; cost_analysis serves the
    compiled program's flops/bytes (the device_compute yardstick)."""
    import jax.numpy as jnp

    from risingwave_tpu.utils import jaxtools

    f = jaxtools.instrumented_jit(lambda x: x * 2 + 1,
                                  "unit.cost_kernel")
    f(jnp.arange(64))
    ca = f.cost_analysis()
    assert ca is not None and ca["flops"] > 0
    rows = jaxtools.kernel_cost_rows()
    assert any(label == "unit.cost_kernel" for label, _f, _b in rows)
    assert jaxtools.publish_kernel_costs() >= 1
    assert STREAMING.kernel_flops.get(kernel="unit.cost_kernel") > 0


# -- perfetto counter tracks ----------------------------------------------


def test_seal_emits_phase_lanes_and_counter_tracks():
    from risingwave_tpu.utils.spans import EPOCH_TRACER

    EPOCH_TRACER.clear()
    spans_mod.set_enabled(True)
    spans_mod.set_current_epoch(33)
    LEDGER.attribute("device_compute", 0.004, epoch=33)
    LEDGER.add_bytes("h2d", 4096, kernel="unit-track")
    LEDGER.seal(33, 0.01, warmup=True)
    out = json.loads(json.dumps(EPOCH_TRACER.export_chrome(
        epochs=[33])))
    cs = [e for e in out["traceEvents"] if e["ph"] == "C"]
    names = {e["name"] for e in cs}
    assert "transfer_h2d_bytes" in names, names
    assert "uploader_queue_depth" in names
    [h2d] = [e for e in cs if e["name"] == "transfer_h2d_bytes"]
    assert h2d["args"]["value"] == 4096.0
    # phase lanes ride as ordinary X spans under cat=phase
    xs = [e for e in out["traceEvents"]
          if e["ph"] == "X" and e["cat"] == "phase"]
    assert any(e["name"] == "phase.device_compute" for e in xs)
    EPOCH_TRACER.clear()


# -- conservation under an injected stall (end-to-end) ---------------------


def test_sleep_failpoint_surfaces_as_unattributed():
    """A sleep failpoint on the barrier's commit path is wall time NO
    phase can claim: the sealed epoch publishes it as `unattributed`
    and the strict gate flags it."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.utils.failpoint import failpoints

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(BID_SOURCE.format(n=EVENTS))
        await fe.execute(Q7ISH_MV)
        await fe.step(3)                 # warmup: compiles done
        with failpoints({"barrier.collect": {"sleep_s": 0.8,
                                             "times": 1}}):
            await fe.step(1)
        rows = await fe.execute("SELECT * FROM q7")
        await fe.close()
        return rows

    asyncio.run(run())
    stalled = [r for r in LEDGER.records
               if not r.warmup and r.unattributed_s > 0.5]
    assert stalled, [r.to_dict() for r in LEDGER.records]
    assert stalled[0].coverage() < 0.5
    # the gate catches exactly this rot
    assert LEDGER.gate_violations()
    # clear before the conftest strict gate reads the records — this
    # test INJECTED the violation on purpose
    LEDGER.clear()


# -- q7 oracle: ledger on vs off -------------------------------------------


def _run_q7(ledger_on: bool):
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            f"SET stream_ledger = '{'on' if ledger_on else 'off'}'")
        await fe.execute(BID_SOURCE.format(n=EVENTS))
        await fe.execute(Q7ISH_MV)
        await fe.step(2)                 # warmup (compiles)
        t0 = time.perf_counter()
        await fe.step(8)
        elapsed = time.perf_counter() - t0
        rows = await fe.execute("SELECT * FROM q7")
        await fe.close()
        return {tuple(r) for r in rows}, elapsed

    return asyncio.run(run())


def test_q7_ledger_on_off_oracle_and_overhead():
    rows_on, t_on = _run_q7(True)
    n_records = len(LEDGER.records)
    assert n_records >= 8                # epochs sealed while on
    steady = [r for r in LEDGER.records if not r.warmup]
    assert steady
    # the flagship kernel moved bytes BOTH directions while on
    kernels_h2d = {l.get("kernel") for l, _v in
                   STREAMING.transfer_bytes.series()
                   if l.get("dir") == "h2d"}
    kernels_d2h = {l.get("kernel") for l, _v in
                   STREAMING.transfer_bytes.series()
                   if l.get("dir") == "d2h"}
    assert any("HashAgg" in k for k in kernels_h2d), kernels_h2d
    assert any("HashAgg" in k for k in kernels_d2h), kernels_d2h
    LEDGER.clear()
    rows_off, t_off = _run_q7(False)
    assert len(LEDGER.records) == 0      # off: nothing sealed
    # oracle: bit-identical MV content either way
    assert rows_on == rows_off
    # throughput within the tracing noise budget (generous: CI jitter
    # dwarfs the per-scope cost; the 5% bench criterion is enforced on
    # the real bench rig, this guards pathological overhead only)
    assert t_on <= t_off * 1.6 + 0.3, (t_on, t_off)


# -- rw_metrics_history over SQL -------------------------------------------


def test_metrics_history_over_sql_32_barriers():
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(BID_SOURCE.format(n=EVENTS))
        await fe.execute(Q7ISH_MV)
        for _ in range(34):
            await fe.step(1)
        rows = await fe.execute("SELECT * FROM rw_metrics_history")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    # long format: (seq, epoch, ts, interval_s, name, value)
    seqs = {r[0] for r in rows}
    assert len(seqs) >= 32, len(seqs)
    names = {r[4] for r in rows}
    # tracked registry series + the ledger's phase extras ride along
    assert {"source_rows", "device_dispatches", "h2d_bytes",
            "d2h_bytes", "uploader_queue_depth",
            "coverage"} <= names, names
    assert any(n.startswith("phase.") for n in names)
    # per-barrier deltas: source rows moved on data-bearing barriers
    moved = [r[5] for r in rows if r[4] == "source_rows"]
    assert sum(moved) > 0
    # coverage per barrier is a fraction
    for r in rows:
        if r[4] == "coverage":
            assert 0.0 <= r[5] <= 1.0


# -- 2-worker cluster merge ------------------------------------------------


def test_cluster_two_worker_ledger_merge(tmp_path):
    """Worker-side phase time folds into the coordinator's sealed
    records: before the drain a distributed record is coordinator-only
    (conservation deferred); after, worker tags appear, attributed
    time grows, and the residual is recomputed."""
    from risingwave_tpu.cluster.session import DistFrontend

    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            await fe.execute(BID_SOURCE.format(n=EVENTS))
            await fe.execute(Q7ISH_MV)
            await fe.step(6)
            pre = {r.epoch: (r.attributed_s, r.distributed,
                             list(r.workers))
                   for r in LEDGER.records}
            n = await fe.drain_ledger()
            rows = await fe.execute("SELECT * FROM q7")
            return pre, n, rows
        finally:
            await fe.close()

    pre, n, rows = asyncio.run(run())
    assert rows, "q7 produced no rows on the cluster"
    assert n > 0, "workers shipped no ledger accumulators"
    assert all(dist for _a, dist, _w in pre.values()), \
        "pre-merge records must be marked distributed"
    merged = [r for r in LEDGER.records if r.workers]
    assert merged, "no record absorbed worker phase time"
    grew = [r for r in merged
            if r.attributed_s > pre[r.epoch][0] + 1e-9]
    assert grew, "merge did not add worker-side attributed time"
    assert all(not r.distributed for r in merged)
    # a second drain is a no-op (drained accumulators left the worker)
    # — checked implicitly: records/workers are stable because the
    # drain above popped everything; the conftest gate then audits the
    # merged records' conservation like any other test's.


# -- parallel-source idle dedup (ISSUE 12 satellite) -----------------------


def test_parallel_idle_sources_cannot_exceed_share_one():
    """Regression for the BENCH_r10 ad-ctr phase_breakdown: four
    sources each parked ~the whole epoch summed to barrier_wait share
    1.05. Idle is keyed per source and the seal folds the MAX (the
    parks are concurrent), capped at the interval — the share can
    never exceed 1.0."""
    led = PhaseLedger()
    epoch = 0x1000
    for actor in range(4):
        led.attribute_idle(0.95, epoch, source=f"actor-{actor}/src")
    rec = led.seal(epoch, 1.0)
    assert rec.seconds["barrier_wait"] == pytest.approx(0.95)
    share = rec.seconds["barrier_wait"] / rec.interval_s
    assert share <= 1.0
    # and a single source longer than the interval still caps
    led2 = PhaseLedger()
    led2.attribute_idle(3.0, epoch, source="a")
    rec2 = led2.seal(epoch, 1.0)
    assert rec2.seconds["barrier_wait"] == pytest.approx(1.0)


def test_worker_idle_merges_as_max_not_sum():
    """Cross-process merge: each worker ships its own idle_max; the
    sealed record folds max-then-cap, never the sum."""
    led = PhaseLedger()
    epoch = 0x2000
    led.attribute_idle(0.4, epoch, source="coord-src")
    rec = led.seal(epoch, 1.0, distributed=True)
    assert rec.seconds["barrier_wait"] == pytest.approx(0.4)
    led.ingest([{"epoch": epoch, "seconds": {}, "idle_max": 0.9}],
               worker="w0")
    led.ingest([{"epoch": epoch, "seconds": {}, "idle_max": 0.7}],
               worker="w1")
    assert rec.seconds["barrier_wait"] == pytest.approx(0.9)
    # a worker idling past the interval caps at the interval
    led.ingest([{"epoch": epoch, "seconds": {}, "idle_max": 5.0}],
               worker="w2")
    assert rec.seconds["barrier_wait"] == pytest.approx(1.0)
