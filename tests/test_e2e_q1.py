"""Nexmark q1 end-to-end: the first full pipeline.

q1 (currency conversion, stateless):
    SELECT auction, bidder, 0.908 * price AS price, date_time FROM bid;

Reference parity: e2e_test/streaming/nexmark/q1 semantics; pipeline shape
mirrors §3.1-3.2 of SURVEY.md — source → project → materialize driven by
the barrier loop, results read from the MV's committed snapshot. The plan
itself lives in risingwave_tpu.models.nexmark (shared with bench.py).
"""

import asyncio
import decimal

import numpy as np

from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
from risingwave_tpu.models.nexmark import build_q1, drive_to_completion
from risingwave_tpu.state.store import MemoryStateStore


def test_q1_end_to_end():
    n_epochs = 100
    cfg = NexmarkConfig(event_num=50 * n_epochs, max_chunk_size=512)
    pipeline = build_q1(MemoryStateStore(), cfg)
    n_bids = 46 * n_epochs
    asyncio.run(drive_to_completion(pipeline, {1: n_bids}))
    loop, mv_table = pipeline.loop, pipeline.mv_table
    assert len(loop.stats.completed_epochs) >= 2

    # read the MV snapshot, compare against a direct-computed oracle
    from risingwave_tpu.state.state_table import to_logical_row
    got = [to_logical_row(row, mv_table.schema)
           for _pk, row in mv_table.iter_rows()]
    k = np.arange(n_bids, dtype=np.int64)
    bids = gen_bids(k, cfg)
    rate = decimal.Decimal("0.908")
    expect = {
        (int(a), int(b), (rate * p).quantize(decimal.Decimal("0.0001")),
         int(t))
        for a, b, p, t in zip(bids["auction"], bids["bidder"],
                              map(decimal.Decimal, map(int, bids["price"])),
                              bids["date_time"])
    }
    got_set = {(r[0], r[1], r[2].quantize(decimal.Decimal("0.0001")), r[3])
               for r in got}
    assert len(got) == n_bids
    assert got_set == expect
