"""Nexmark q1 end-to-end: the first full pipeline.

q1 (currency conversion, stateless):
    SELECT auction, bidder, 0.908 * price AS price, date_time FROM bid;

Reference parity: e2e_test/streaming/nexmark/q1 semantics; pipeline shape
mirrors §3.1-3.2 of SURVEY.md — source → project → materialize driven by
the barrier loop, results read from the MV's committed snapshot.
"""

import asyncio
import decimal

import numpy as np

from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.connectors.nexmark import (
    NexmarkConfig, NexmarkSplitReader, gen_bids,
)
from risingwave_tpu.expr.expr import InputRef, lit
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.simple import ProjectExecutor
from risingwave_tpu.stream.executors.source import SourceExecutor
from risingwave_tpu.stream.message import StopMutation

SPLIT_STATE_SCHEMA = Schema([Field("split_id", DataType.VARCHAR),
                             Field("offset", DataType.INT64)])


def build_q1(store, cfg):
    """Hand-built q1 plan (the fragmenter arrives with the frontend)."""
    reader = NexmarkSplitReader(cfg)
    barrier_tx, barrier_rx = channel_for_test()
    split_state = StateTable(1, SPLIT_STATE_SCHEMA, [0], store)
    source = SourceExecutor(reader, barrier_rx, split_state, actor_id=1,
                            rate_limit_chunks_per_barrier=3)
    row_id = RowIdGenExecutor(source)
    s = row_id.schema
    project = ProjectExecutor(
        row_id,
        exprs=[InputRef(s.index_of("auction"), DataType.INT64),
               InputRef(s.index_of("bidder"), DataType.INT64),
               lit("0.908", DataType.DECIMAL)
               * InputRef(s.index_of("price"), DataType.INT64),
               InputRef(s.index_of("date_time"), DataType.TIMESTAMP),
               InputRef(s.index_of("_row_id"), DataType.SERIAL)],
        names=["auction", "bidder", "price", "date_time", "_row_id"])
    mv_table = StateTable(2, project.schema, [4], store)  # pk = _row_id
    mat = MaterializeExecutor(project, mv_table)
    local = LocalBarrierManager()
    local.register_sender(1, barrier_tx)
    local.set_expected_actors([1])
    actor = Actor(1, mat, dispatchers=[], barrier_manager=local)
    loop = BarrierLoop(local, store)
    return actor, loop, mv_table, reader


def test_q1_end_to_end():
    n_epochs = 100
    cfg = NexmarkConfig(event_num=50 * n_epochs, max_chunk_size=512)

    async def main():
        store = MemoryStateStore()
        actor, loop, mv_table, reader = build_q1(store, cfg)
        task = actor.spawn()
        # barrier-drive until the bounded source is fully drained (the
        # 3-chunks-per-barrier rate limit spreads it over ≥3 epochs), then
        # a final checkpoint covers the tail, then stop
        while reader.offset * 1 < 46 * n_epochs:
            await loop.inject_and_collect()
        await loop.inject_and_collect()
        await loop.inject_and_collect(mutation=StopMutation(frozenset([1])))
        await task
        assert actor.failure is None, actor.failure
        return store, mv_table, loop

    store, mv_table, loop = asyncio.run(main())
    assert len(loop.stats.completed_epochs) >= 4

    # read the MV snapshot, compare against a direct-computed oracle
    from risingwave_tpu.state.state_table import to_logical_row
    got = [to_logical_row(row, mv_table.schema)
           for _pk, row in mv_table.iter_rows()]
    n_bids = 46 * n_epochs
    k = np.arange(n_bids, dtype=np.int64)
    bids = gen_bids(k, cfg)
    rate = decimal.Decimal("0.908")
    expect = {
        (int(a), int(b), (rate * p).quantize(decimal.Decimal("0.0001")),
         int(t))
        for a, b, p, t in zip(bids["auction"], bids["bidder"],
                              map(decimal.Decimal, map(int, bids["price"])),
                              bids["date_time"])
    }
    got_set = {(r[0], r[1], r[2].quantize(decimal.Decimal("0.0001")), r[3])
               for r in got}
    assert len(got) == n_bids
    assert got_set == expect
