"""Native C++ codec vs pure-Python: byte-identical outputs."""

import os

import numpy as np
import pytest

from risingwave_tpu import native
from risingwave_tpu.storage import sst as sst_mod
from risingwave_tpu.storage.sst import (
    Sst, SstBuilder, _BloomBuilder, _iter_block_py, bloom_may_contain,
    full_key, iter_block,
)
from risingwave_tpu.storage.value_codec import encode_row

requires_native = pytest.mark.skipif(
    native.lib() is None, reason="no g++ toolchain")


def _build(n=5000):
    b = SstBuilder(1)
    for i in range(n):
        b.add(full_key(3, b"user%05d" % i, 7), i % 17 == 0,
              b"" if i % 17 == 0 else encode_row((i, "v%d" % i, None)))
    return b.finish()


@requires_native
def test_native_block_roundtrip_matches_python():
    data, info = _build()
    s = Sst(data, info)
    for _first, off, ln in s.index:
        blk = data[off:off + ln]
        assert list(iter_block(blk)) == list(_iter_block_py(blk))


@requires_native
def test_native_bloom_matches_python(monkeypatch):
    items = [b"item-%d" % i for i in range(2000)]
    bb = _BloomBuilder()
    for i in items:
        bb.add(i)
    native_bits = bb.finish()
    # force the python path for the same inputs
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    bb2 = _BloomBuilder()
    for i in items:
        bb2.add(i)
    py_bits = bb2.finish()
    assert native_bits == py_bits
    monkeypatch.undo()
    for i in items:
        assert bloom_may_contain(native_bits, i)


@requires_native
def test_python_reads_native_sst_and_vice_versa(monkeypatch):
    data_native, info = _build(2000)
    monkeypatch.setattr(native, "_lib", None)
    monkeypatch.setattr(native, "_tried", True)
    data_py, info_py = _build(2000)
    assert data_native == data_py        # byte-identical formats
    s = Sst(data_native, info)
    hit = s.get(3, b"user00123", 10)
    assert hit is not None and not hit[1]
