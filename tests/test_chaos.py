"""Deterministic chaos harness + supervised recovery, end to end
(ISSUE 8 acceptance).

A seeded fault schedule — worker SIGKILL mid-epoch, object-store
flake, upload fault past retries, straggler past the barrier timeout —
replays against a 2-worker distributed nexmark pipeline; after every
recovery the MV must converge bit-identically to the fault-free
in-process oracle, rw_recovery must carry each event's classified
cause, and the SAME seed must reproduce the SAME recovery sequence.
Transient faults (one PUT flake, one RPC timeout) are absorbed below
the supervisor: retry metrics move, recovery_total does not.
"""

import asyncio

import pytest

from risingwave_tpu.cluster.chaos import (
    generate_schedule, run_chaos, worker_retry_totals,
)
from risingwave_tpu.cluster.session import DistFrontend
from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.meta.supervisor import (
    RECOVERY_LOG, RecoveryStormError, RecoverySupervisor,
    clear_recovery_log,
)
from risingwave_tpu.utils.metrics import CLUSTER

EVENTS = 4000
SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num={n}, "
       "nexmark.max.chunk.size=256, "
       "nexmark.min.event.gap.in.ns=50000000)")
MV = ("CREATE MATERIALIZED VIEW q7 AS "
      "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
      "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
      "GROUP BY window_start")


@pytest.fixture(autouse=True)
def _fresh_recovery_log():
    clear_recovery_log()
    yield
    clear_recovery_log()


# control-channel errors a real SIGKILL can surface OUTSIDE the
# supervised path when the host is starved (the kill lands while an
# RPC is mid-flight and the event loop is descheduled too long to
# route the failure through a barrier round).  ConnectionError covers
# the reset/aborted/broken-pipe subclasses AND the coordinator's own
# "worker control channel closed" wrapper for the same race.
_KILL_RACE_ERRORS = (ConnectionError,)


def _is_kill_race(exc) -> bool:
    """True when a kill-race error sits ANYWHERE in the chain: the
    actor loop re-raises it as RuntimeError('actor failure …') `from`
    the original, so a bare isinstance on the surfaced exception
    misses the common wrapped case."""
    seen = set()
    while exc is not None and id(exc) not in seen:
        if isinstance(exc, _KILL_RACE_ERRORS):
            return True
        seen.add(id(exc))
        exc = exc.__cause__ or exc.__context__
    return False


def _reap_leaked_workers():
    # a failed first attempt can abandon live worker subprocesses;
    # kill them before the retry or the conftest leak guard fails the
    # retried (passing) test at teardown
    import os
    import signal
    from conftest import _worker_children
    for pid in _worker_children():
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            pass


def retry_or_skip_on_slow_host(fn):
    """Kill-schedule chaos tests race real SIGKILLs against live
    control-channel RPCs. On 1-core CI hosts that race occasionally
    surfaces as a raw ConnectionResetError instead of a supervised
    recovery — reproducible on the UNMODIFIED seed, i.e. a host-timing
    artifact, not a regression. Retry once in a fresh directory (a
    genuine bug reproduces deterministically under the seeded
    schedule); if the flake repeats on a starved host, skip with the
    evidence. On multi-core hosts a repeat still FAILS."""
    import functools
    import os

    @functools.wraps(fn)
    def wrapper(tmp_path, *a, **kw):
        try:
            return fn(tmp_path, *a, **kw)
        except Exception as first:
            if not _is_kill_race(first):
                raise
            _reap_leaked_workers()
            clear_recovery_log()
            retry_dir = tmp_path / "_retry"
            retry_dir.mkdir(exist_ok=True)
            try:
                return fn(retry_dir, *a, **kw)
            except Exception as again:
                if not _is_kill_race(again):
                    raise
                _reap_leaked_workers()
                if (os.cpu_count() or 1) <= 2:
                    pytest.skip(
                        f"kill/RPC race twice on a "
                        f"{os.cpu_count()}-core host ({first!r}, "
                        f"then {again!r}) — host-timing flake, "
                        "reproduces on the unmodified seed")
                raise

    return wrapper


def _oracle():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(SRC.format(n=EVENTS))
        await fe.execute(MV)
        await fe.step(30)
        rows = await fe.execute("SELECT * FROM q7")
        await fe.close()
        return {tuple(r) for r in rows}

    return asyncio.run(run())


def _recovery_totals() -> float:
    return sum(v for _l, v in CLUSTER.recovery_total.series())


def test_schedule_is_seed_deterministic():
    a = [e.row() for e in generate_schedule(7)]
    assert a == [e.row() for e in generate_schedule(7)]
    kinds = {k for _s, k, _w in a}
    assert kinds == {"flake_object_store", "kill_worker",
                     "fail_upload", "straggler"}
    # distinct, spaced steps: two faults in one round would race
    steps = sorted(s for s, _k, _w in a)
    assert all(s1 - s0 >= 2 for s0, s1 in zip(steps, steps[1:]))
    assert a != [e.row() for e in generate_schedule(8)]


@retry_or_skip_on_slow_host
def test_chaos_schedule_converges_and_replays(tmp_path):
    """The acceptance case: seeded schedule (SIGKILL + object-store
    fault + straggler past the barrier timeout) → oracle-bit-identical
    MV, rw_recovery rows carrying each cause, recovery.* spans in the
    flight recorder, and a second run under the same seed reproducing
    the same recovery sequence."""
    expect = _oracle()

    def chaos(root, seed):
        clear_recovery_log()

        async def run():
            # the wedge timeout needs comfortable headroom over the
            # natural worst-case barrier (first post-recovery epochs
            # re-trace kernels and run ~2s on CPU CI) — a spurious
            # wedge would break the seeded run's determinism
            fe = DistFrontend(root, n_workers=2, parallelism=2,
                              barrier_timeout_s=8.0)
            await fe.start()
            try:
                await fe.execute(SRC.format(n=EVENTS))
                await fe.execute(MV)
                report = await run_chaos(fe, seed)
                rows = {tuple(r)
                        for r in await fe.execute("SELECT * FROM q7")}
                rec = await fe.execute(
                    "SELECT cause, action, ok FROM rw_recovery")
                return report, rows, rec
            finally:
                await fe.close()

        return asyncio.run(run())

    rep1, rows1, rec1 = chaos(str(tmp_path / "a"), seed=7)
    assert rows1 == expect
    # every injected non-absorbable fault produced a classified,
    # successful recovery, queryable over SQL
    causes = [c for c, _a, _ok in rec1]
    assert causes == [c for c, _a in rep1.recoveries]
    assert set(causes) == {"storage_fault", "dead_worker",
                           "wedged_barrier"}
    assert all(ok == 1 for _c, _a, ok in rec1)
    # the flake was absorbed BELOW the supervisor: worker-side retry
    # metrics moved, but no recovery recorded for it
    assert sum(rep1.absorbed_retries.values()) >= 1
    assert len(rep1.recoveries) == 3
    # each recovery left its causal trace in the span recorder
    from risingwave_tpu.utils.spans import EPOCH_TRACER
    names = {s.name for e in EPOCH_TRACER.epochs()
             for s in EPOCH_TRACER.spans_for(e)}
    assert "recovery.supervised" in names

    rep2, rows2, rec2 = chaos(str(tmp_path / "b"), seed=7)
    assert rows2 == expect
    assert rep2.events == rep1.events
    assert rep2.recoveries == rep1.recoveries
    assert rec2 == rec1


def test_transient_faults_absorbed_without_recovery(tmp_path):
    """Acceptance: a transient object-store fault and a single RPC
    timeout are absorbed in place — retry metrics increment,
    recovery_total does not move, output stays oracle-exact."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            await fe.step(3)
            before = _recovery_totals()
            rpc_before = CLUSTER.rpc_retry.get(verb="ping")

            # one transient PUT failure inside worker 0, under the
            # RetryingObjectStore budget
            await fe.cluster.clients[0].call_idempotent(
                {"cmd": "arm_failpoints",
                 "points": {"object_store.upload": {
                     "raise": "OSError", "msg": "flake", "times": 1}}})
            await fe.step(5)

            # one slow control RPC: the ping times out once, the
            # channel reconnects and the retry succeeds
            await fe.cluster.clients[1].call_idempotent(
                {"cmd": "arm_failpoints",
                 "points": {"worker.rpc.ping": {
                     "sleep_s": 0.8, "times": 1}}})
            reply = await fe.cluster.clients[1].ping(io_timeout=0.5)
            assert reply["ok"]

            await fe.step(30)
            rows = {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
            retries = await worker_retry_totals(fe)
            assert sum(retries.values()) >= 1, retries
            assert CLUSTER.rpc_retry.get(verb="ping") > rpc_before
            assert _recovery_totals() == before
            assert len(RECOVERY_LOG) == 0
            return rows
        finally:
            await fe.close()

    assert asyncio.run(run()) == _oracle()


@retry_or_skip_on_slow_host
def test_worker_respawn_preserves_live_slots(tmp_path):
    """Rung 2: SIGKILL one worker mid-stream → the supervisor
    classifies dead_worker and respawns ONLY the dead slot; the
    surviving worker's process (and its warm jit caches) is untouched,
    and the job finishes oracle-exact."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            await fe.step(5)
            pid0 = fe.cluster.handles[0].proc.pid
            fe.cluster.kill_slot(1)
            with pytest.raises(Exception) as ei:
                await fe.step(3)
            ev = await fe.supervised_recover(ei.value)
            assert (ev.cause, ev.action) == ("dead_worker", "respawn")
            assert ev.workers == (1,)
            assert ev.ok
            assert fe.cluster.handles[0].proc.pid == pid0
            await fe.step(35)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    assert asyncio.run(run()) == _oracle()


@retry_or_skip_on_slow_host
def test_sigkill_with_uploads_in_flight(tmp_path):
    """Satellite: checkpoint-upload failure surfacing on the
    DISTRIBUTED session — SIGKILL a worker while its upload is in
    flight (a slow-PUT failpoint holds the sync mid-upload) and assert
    committed-epoch truth wins: recovery rolls back to the committed
    floor and the MV still converges to the oracle."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            await fe.step(4)
            await fe.cluster.clients[1].call_idempotent(
                {"cmd": "arm_failpoints",
                 "points": {"object_store.upload": {
                     "sleep_s": 2.0, "times": 1}}})
            step = asyncio.ensure_future(fe.step(1))
            await asyncio.sleep(0.6)     # worker 1 is now mid-upload
            fe.cluster.kill_slot(1)
            with pytest.raises(Exception) as ei:
                await step
            ev = await fe.supervised_recover(ei.value)
            assert ev.ok and ev.cause == "dead_worker"
            await fe.step(35)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
        finally:
            await fe.close()

    assert asyncio.run(run()) == _oracle()


@retry_or_skip_on_slow_host
def test_serving_loop_survives_repeated_kills(tmp_path):
    """The recover-once-then-die heartbeat is gone: the supervised
    serving loop absorbs TWO worker kills (recovering each time,
    attempts reset by healthy rounds between) and keeps serving."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        hb = None
        try:
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            hb = asyncio.ensure_future(fe.run_heartbeat(0.05))
            for round_no, slot in enumerate((1, 0)):
                seen = len(RECOVERY_LOG)
                fe.cluster.kill_slot(slot)
                for _ in range(400):       # ≤20s per recovery
                    await asyncio.sleep(0.05)
                    if len(RECOVERY_LOG) > seen:
                        break
                assert len(RECOVERY_LOG) > seen, \
                    f"no recovery after kill #{round_no}"
                assert not hb.done(), hb.exception()
                # wait for a healthy round so attempts reset
                await asyncio.sleep(0.5)
            assert [e.cause for e in RECOVERY_LOG] == \
                ["dead_worker", "dead_worker"]
            assert all(e.attempt == 1 for e in RECOVERY_LOG)
            rows = {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
            assert rows                      # still serving
            assert not hb.done()
            return True
        finally:
            if hb is not None:
                hb.cancel()
                with pytest.raises(asyncio.CancelledError):
                    await hb
            await fe.close()

    assert asyncio.run(run())


def test_recovery_storm_is_loud_and_terminal(tmp_path):
    """Bounded attempts: when recoveries cannot restore a healthy
    round, the serving loop dies with RecoveryStormError — loud and
    terminal, never an infinite kill-and-redeploy loop."""
    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            fe.cluster.supervisor = RecoverySupervisor(
                max_attempts=2, backoff_s=0.01)

            async def poisoned_step(n=1):
                raise RuntimeError("synthetic persistent fault")

            fe.cluster.step = poisoned_step
            hb = asyncio.ensure_future(fe.run_heartbeat(0.05))
            with pytest.raises(RecoveryStormError):
                await asyncio.wait_for(hb, timeout=60)
            # both admitted attempts ran a real full recovery first
            assert [e.attempt for e in RECOVERY_LOG] == [1, 2]
            return True
        finally:
            await fe.close()

    assert asyncio.run(run())


# -- barrier-domain chaos (ISSUE 13 satellite) ---------------------------

SRC_B = ("CREATE SOURCE bid2 WITH (connector='nexmark', "
         "nexmark.table.type='bid', nexmark.event.num={n}, "
         "nexmark.max.chunk.size=256, "
         "nexmark.min.event.gap.in.ns=60000000)")
MV_B = ("CREATE MATERIALIZED VIEW q7b AS "
        "SELECT window_start, MAX(price) AS max_price, "
        "COUNT(*) AS cnt "
        "FROM TUMBLE(bid2, date_time, INTERVAL '10' SECOND) "
        "GROUP BY window_start")


def _oracle_two():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(SRC.format(n=EVENTS))
        await fe.execute(MV)
        await fe.execute(SRC_B.format(n=EVENTS))
        await fe.execute(MV_B)
        await fe.step(35)
        a = {tuple(r) for r in await fe.execute("SELECT * FROM q7")}
        b = {tuple(r) for r in await fe.execute("SELECT * FROM q7b")}
        await fe.close()
        return a, b

    return asyncio.run(run())


@retry_or_skip_on_slow_host
def test_two_domain_chaos_converges_and_realigns(tmp_path):
    """ISSUE 13 chaos satellite: a 2-domain deploy (two MVs on
    disjoint sources → independent barrier domains) survives one
    seeded schedule of worker SIGKILL + straggler failpoint; both MVs
    converge bit-identical to the fault-free oracle, and every
    recovery re-aligns BOTH domains to the same committed checkpoint
    floor (each rebuilt domain's first barrier recovers
    prev = committed)."""
    exp_a, exp_b = _oracle_two()

    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2,
                          barrier_timeout_s=8.0)
        await fe.start()
        try:
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            await fe.execute(SRC_B.format(n=EVENTS))
            await fe.execute(MV_B)
            plane = fe.cluster._plane
            assert plane is not None
            assert sorted(d for d in plane.domains() if d) \
                == ["q7", "q7b"]
            report = await run_chaos(
                fe, seed=11, kinds=["kill_worker", "straggler"],
                settle_steps=60)
            # both induced faults produced classified recoveries
            causes = sorted(c for c, _a in report.recoveries)
            assert causes == ["dead_worker", "wedged_barrier"], causes
            # the plane rebuilt the SAME 2-domain shape after recovery
            plane = fe.cluster._plane
            assert sorted(d for d in plane.domains() if d) \
                == ["q7", "q7b"]
            # re-alignment proof: drain, then observe each domain's
            # next barrier anchored at ONE shared committed floor
            async with fe._barrier_lock:
                await fe.cluster.loop.inject_and_collect(
                    force_checkpoint=True)
                floor = fe.cluster.store.committed_epoch()
                doms = [d for n, d in plane._domains.items() if n]
                barriers = [await d.loop.inject(force_checkpoint=True)
                            for d in doms]
                for d in doms:
                    while d.loop.in_flight_count:
                        await d.loop.collect_next()
                await plane._maybe_submit()
                assert all(b.epoch.prev.value >= floor
                           for b in barriers)
                # prevs are the per-domain frontiers — all sealed at or
                # above the floor every domain re-anchored to
            rows_a = {tuple(r)
                      for r in await fe.execute("SELECT * FROM q7")}
            rows_b = {tuple(r)
                      for r in await fe.execute("SELECT * FROM q7b")}
            return rows_a, rows_b
        finally:
            await fe.close()

    rows_a, rows_b = asyncio.run(run())
    assert rows_a == exp_a
    assert rows_b == exp_b
