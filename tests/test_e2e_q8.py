"""Nexmark q8 end-to-end: windowed person⋈auction join on the device.

q8 (who has entered the system and created an auction in the same
10s window):

    SELECT P.id, P.name, P.starttime FROM
      (person, TUMBLE 10s) P JOIN
      (SELECT seller, starttime FROM auction TUMBLE 10s GROUP BY ...) A
      ON P.id = A.seller AND P.starttime = A.starttime

Pipeline: two sources → projects → auction-side HashAgg dedup → inner
HashJoin (device matcher) → materialize. Reference parity:
e2e_test/streaming/nexmark/q8 semantics; dedup via GROUP BY matches the
reference plan (agg update pairs degrade to Delete+Insert through the
join, leaving the match multiset unchanged). The plan itself lives in
risingwave_tpu.models.nexmark (shared with bench.py).
"""

import asyncio

import numpy as np

from risingwave_tpu.connectors.nexmark import (
    NexmarkConfig, gen_auctions, gen_persons,
)
from risingwave_tpu.models.nexmark import (
    DEFAULT_WINDOW, build_q8, drive_to_completion,
)
from risingwave_tpu.state.store import MemoryStateStore

WINDOW = DEFAULT_WINDOW


def q8_oracle(cfg, n_persons, n_auctions):
    kp = np.arange(n_persons, dtype=np.int64)
    persons = gen_persons(kp, cfg)
    ka = np.arange(n_auctions, dtype=np.int64)
    auctions = gen_auctions(ka, cfg)
    p_win = (persons["date_time"] // WINDOW.usecs) * WINDOW.usecs
    a_win = (auctions["date_time"] // WINDOW.usecs) * WINDOW.usecs
    sellers = {(int(s), int(w))
               for s, w in zip(auctions["seller"], a_win)}
    out = set()
    for pid, name, w in zip(persons["id"], persons["name"], p_win):
        if (int(pid), int(w)) in sellers:
            out.add((int(pid), str(name), int(w)))
    return out


def test_q8_end_to_end():
    n_events = 50 * 400
    cfg = NexmarkConfig(event_num=n_events, max_chunk_size=256,
                        min_event_gap_in_ns=50_000_000,  # several windows
                        active_people=40, hot_seller_ratio=2)
    cfg_p = NexmarkConfig(**{**cfg.__dict__, "table_type": "person"})
    cfg_a = NexmarkConfig(**{**cfg.__dict__, "table_type": "auction"})
    n_persons = n_events // 50
    n_auctions = n_events * 3 // 50

    pipeline = build_q8(MemoryStateStore(), cfg_p, cfg_a)
    asyncio.run(drive_to_completion(
        pipeline, {1: n_persons, 2: n_auctions}, max_epochs=200))
    got = {tuple(row) for _pk, row in pipeline.mv_table.iter_rows()}
    expect = q8_oracle(cfg, n_persons, n_auctions)
    assert len(expect) > 10
    assert got == expect
