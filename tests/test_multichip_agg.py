"""Vnode-sharded agg over the 8-device virtual mesh == single-chip result.

VERDICT round-1 item #4: the multi-chip axis must be exercised, not just
claimed — this test uses the eight_devices fixture and asserts the SPMD
all_to_all path agrees with the single-device kernel on a random stream.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggKind, AggSpec, GroupedAggKernel, decode_outputs,
)
from risingwave_tpu.parallel.agg import ShardedAggKernel


def _mk_inputs(spec, vals, valid):
    return (tuple(np.asarray(a) for a in spec.encode_input(vals)),
            valid)


def _single_chip_snapshot(kernel: GroupedAggKernel):
    st = jax.device_get(kernel.state)
    out = {}
    live = st.table.occ & (st.group_rows > 0)
    idx = np.flatnonzero(live)
    keys = st.table.keys[idx]
    accs = [a[idx] for a in st.accs]
    outs, nulls = decode_outputs(kernel.specs, accs)
    for r in range(len(idx)):
        out[tuple(keys[r].tolist())] = tuple(
            None if nulls[c][r] else outs[c][r].item()
            for c in range(len(kernel.specs)))
    return out


def test_sharded_agg_matches_single_chip(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.MAX, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    # keys: one int64 logical key → (hi, lo) int32 lanes
    sharded = ShardedAggKernel(mesh, key_width=2, specs=specs,
                               capacity=1 << 10)
    single = GroupedAggKernel(key_width=2, specs=specs)

    rng = np.random.default_rng(5)
    for _step in range(4):
        n = 256
        gk = rng.integers(0, 37, n).astype(np.int64) * 7_000_000_000
        hi, lo = lanes.split_i64(gk)
        key_lanes = np.stack([hi, lo], axis=1)
        vals = rng.integers(-(10**9), 10**9, n)
        signs = np.ones(n, dtype=np.int32)
        vis = rng.random(n) > 0.1
        valid = np.ones(n, dtype=bool)
        inputs = [_mk_inputs(specs[0], vals, valid),
                  _mk_inputs(specs[1], vals, valid),
                  ((), valid)]
        sharded.apply(key_lanes, signs, vis, inputs)
        single.apply(key_lanes, signs, vis, inputs)

    got = sharded.snapshot()
    want = _single_chip_snapshot(single)
    assert got == want
    assert len(got) == 37


def test_sharded_state_is_actually_sharded(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    k = ShardedAggKernel(mesh, key_width=2,
                         specs=[AggSpec(AggKind.COUNT)], capacity=1 << 10)
    shardings = {str(a.sharding.spec) for a in
                 [k.state.table.keys, k.state.group_rows]}
    assert all("'d'" in s for s in shardings), shardings


def test_reshard_moves_state_and_preserves_results(eight_devices):
    """Elastic scaling: device state migrates to a new vnode→shard map
    via all_to_all at a barrier; results stay exact across the move."""
    from risingwave_tpu.common.hash import VNODE_COUNT

    mesh = Mesh(np.asarray(eight_devices), ("d",))
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    sharded = ShardedAggKernel(mesh, key_width=2, specs=specs,
                               capacity=1 << 10)
    single = GroupedAggKernel(key_width=2, specs=specs)
    rng = np.random.default_rng(21)

    def feed(n=256):
        gk = rng.integers(0, 41, n).astype(np.int64) * 3_700_000_001
        hi, lo = lanes.split_i64(gk)
        kl = np.stack([hi, lo], axis=1)
        vals = rng.integers(-1000, 1000, n)
        inputs = [(specs[0].encode_input(vals), np.ones(n, dtype=bool)),
                  ((), None)]
        args = (kl, np.ones(n, dtype=np.int32), np.ones(n, dtype=bool),
                inputs)
        sharded.apply(*args)
        single.apply(*args)

    feed()
    occ_before = np.asarray(jnp.sum(sharded.state.table.occ, axis=1))
    # scale "down": pack all vnodes onto the first 2 shards
    new_map = np.arange(VNODE_COUNT, dtype=np.int32) % 2
    sharded.reshard(new_map)
    occ_after = np.asarray(jnp.sum(sharded.state.table.occ, axis=1))
    assert occ_after[2:].sum() == 0          # state actually moved
    # nothing lost in transit: results identical right after the move
    assert sharded.snapshot() == _single_chip_snapshot(single)
    feed()                                    # keep streaming after move
    # scale back "up" to all 8 shards
    sharded.reshard(np.arange(VNODE_COUNT, dtype=np.int32) % 8)
    feed()
    assert sharded.snapshot() == _single_chip_snapshot(single)
