"""Vnode-sharded agg over the 8-device virtual mesh == single-chip result.

VERDICT round-1 item #4: the multi-chip axis must be exercised, not just
claimed — this test uses the eight_devices fixture and asserts the SPMD
all_to_all path agrees with the single-device kernel on a random stream.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh

from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggKind, AggSpec, GroupedAggKernel, decode_outputs,
)
from risingwave_tpu.parallel.agg import ShardedAggKernel


def _mk_inputs(spec, vals, valid):
    return (tuple(np.asarray(a) for a in spec.encode_input(vals)),
            valid)


def _single_chip_snapshot(kernel: GroupedAggKernel):
    kernel._dispatch_backlog()   # applies batch host-side until flush
    st = jax.device_get(kernel.state)
    out = {}
    live = st.table.occ & (st.group_rows > 0)
    idx = np.flatnonzero(live)
    keys = st.table.keys[idx]
    accs = [a[idx] for a in st.accs]
    outs, nulls = decode_outputs(kernel.specs, accs)
    for r in range(len(idx)):
        out[tuple(keys[r].tolist())] = tuple(
            None if nulls[c][r] else outs[c][r].item()
            for c in range(len(kernel.specs)))
    return out


def test_sharded_agg_matches_single_chip(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.MAX, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    # keys: one int64 logical key → (hi, lo) int32 lanes
    sharded = ShardedAggKernel(mesh, key_width=2, specs=specs,
                               capacity=1 << 10)
    single = GroupedAggKernel(key_width=2, specs=specs)

    rng = np.random.default_rng(5)
    for _step in range(4):
        n = 256
        gk = rng.integers(0, 37, n).astype(np.int64) * 7_000_000_000
        hi, lo = lanes.split_i64(gk)
        key_lanes = np.stack([hi, lo], axis=1)
        vals = rng.integers(-(10**9), 10**9, n)
        signs = np.ones(n, dtype=np.int32)
        vis = rng.random(n) > 0.1
        valid = np.ones(n, dtype=bool)
        inputs = [_mk_inputs(specs[0], vals, valid),
                  _mk_inputs(specs[1], vals, valid),
                  ((), valid)]
        sharded.apply(key_lanes, signs, vis, inputs)
        single.apply(key_lanes, signs, vis, inputs)

    got = sharded.snapshot()
    want = _single_chip_snapshot(single)
    assert got == want
    assert len(got) == 37


def test_q7_pipeline_with_sharded_agg_matches_oracle(eight_devices):
    """VERDICT r2 #2: the sharded kernel must be reachable from the
    ACTUAL pipeline — source → project → HashAggExecutor(sharded) →
    materialize through the actor runtime, on the 8-device mesh, with
    oracle-identical results (including watermark state cleaning)."""
    import asyncio

    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore
    from tests.test_e2e_q7 import q7_oracle

    mesh = Mesh(np.asarray(eight_devices), ("d",))
    cfg = NexmarkConfig(event_num=50 * 30 * 20, max_chunk_size=512,
                        min_event_gap_in_ns=200_000_000)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=2, mesh=mesh,
                 watermark_delay=Interval(usecs=0))
    n_bids = 46 * 30 * 20
    asyncio.run(drive_to_completion(p, {1: n_bids}))
    got = {row[0]: (row[1], row[2]) for _pk, row in
           p.mv_table.iter_rows()}
    expect = q7_oracle(cfg, n_bids)
    assert len(expect) > 10
    assert got == expect


def test_q7_pipeline_sharded_recovery(eight_devices):
    """Kill-and-rebuild with the sharded kernel: recovery reloads the
    committed value state into every shard (host-routed), then resumes
    to the oracle result."""
    import asyncio

    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7, drive_to_completion
    from risingwave_tpu.state.store import MemoryStateStore
    from tests.test_e2e_q7 import q7_oracle

    mesh = Mesh(np.asarray(eight_devices), ("d",))
    cfg = NexmarkConfig(event_num=50 * 40, max_chunk_size=256,
                        min_event_gap_in_ns=100_000_000)
    n_bids = 46 * 40
    store = MemoryStateStore()
    p1 = build_q7(store, cfg, rate_limit=1, min_chunks=1, mesh=mesh)
    asyncio.run(drive_to_completion(p1, {1: n_bids // 2}))
    del p1
    # same durable store, fresh pipeline + fresh sharded kernel
    p2 = build_q7(store, cfg, rate_limit=1, min_chunks=1, mesh=mesh)
    asyncio.run(drive_to_completion(p2, {1: n_bids}))
    got = {row[0]: (row[1], row[2]) for _pk, row in
           p2.mv_table.iter_rows()}
    assert got == q7_oracle(cfg, n_bids)


def test_sql_group_by_runs_sharded(eight_devices):
    """The SQL path reaches the sharded kernel: a session with
    parallelism=8 plans GROUP BY onto ShardedAggKernel and the MV
    matches the single-session (parallelism=1) result exactly."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.parallel.agg import ShardedAggKernel

    sql = [
        "CREATE SOURCE bid WITH (connector='nexmark', "
        "nexmark.table.type='bid', nexmark.event.num=4000, "
        "nexmark.max.chunk.size=256)",
        "CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) AS c, "
        "max(price) AS m FROM bid GROUP BY auction",
    ]

    async def run(parallelism):
        f = Frontend(rate_limit=4, parallelism=parallelism)
        for s in sql:
            await f.execute(s)
        for _ in range(30):
            await f.step()
        rows = await f.execute("SELECT * FROM v")
        if parallelism > 1:
            agg_kernels = [
                a for actor in f.actors.values()
                for a in _walk_kernels(actor.consumer)]
            assert any(isinstance(k, ShardedAggKernel)
                       for k in agg_kernels), "plan was not sharded"
        await f.close()
        return sorted(rows)

    def _walk_kernels(ex):
        out = []
        if hasattr(ex, "kernel"):
            out.append(ex.kernel)
        for attr in ("input", "left_in", "right_in"):
            child = getattr(ex, attr, None)
            if child is not None:
                out.extend(_walk_kernels(child))
        return out

    got = asyncio.run(run(8))
    want = asyncio.run(run(1))
    assert got == want
    assert len(got) > 10


def test_sharded_agg_non_divisible_batch_pads(eight_devices):
    """A 3-device mesh never divides pow2 batches: the pad path must
    route pad rows nowhere and keep results exact."""
    mesh = Mesh(np.asarray(eight_devices[:3]), ("d",))
    specs = [AggSpec(AggKind.COUNT)]
    k = ShardedAggKernel(mesh, key_width=2, specs=specs,
                         capacity=1 << 10)
    rng = np.random.default_rng(9)
    gk = rng.integers(0, 5, 64).astype(np.int64)
    hi, lo = lanes.split_i64(gk)
    k.apply(np.stack([hi, lo], axis=1), np.ones(64, np.int32),
            np.ones(64, bool), [((), np.ones(64, bool))])
    snap = k.snapshot()
    import collections
    want = collections.Counter(gk.tolist())
    got = {lanes.merge_i64(np.asarray([kt[0]]), np.asarray([kt[1]]))[0]:
           v[0] for kt, v in snap.items()}
    assert got == dict(want)


def test_sharded_state_is_actually_sharded(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    k = ShardedAggKernel(mesh, key_width=2,
                         specs=[AggSpec(AggKind.COUNT)], capacity=1 << 10)
    shardings = {str(a.sharding.spec) for a in
                 [k.state.table.keys, k.state.group_rows]}
    assert all("'d'" in s for s in shardings), shardings


def test_reshard_moves_state_and_preserves_results(eight_devices):
    """Elastic scaling: device state migrates to a new vnode→shard map
    via all_to_all at a barrier; results stay exact across the move."""
    from risingwave_tpu.common.hash import VNODE_COUNT

    mesh = Mesh(np.asarray(eight_devices), ("d",))
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    sharded = ShardedAggKernel(mesh, key_width=2, specs=specs,
                               capacity=1 << 10)
    single = GroupedAggKernel(key_width=2, specs=specs)
    rng = np.random.default_rng(21)

    def feed(n=256):
        gk = rng.integers(0, 41, n).astype(np.int64) * 3_700_000_001
        hi, lo = lanes.split_i64(gk)
        kl = np.stack([hi, lo], axis=1)
        vals = rng.integers(-1000, 1000, n)
        inputs = [(specs[0].encode_input(vals), np.ones(n, dtype=bool)),
                  ((), None)]
        args = (kl, np.ones(n, dtype=np.int32), np.ones(n, dtype=bool),
                inputs)
        sharded.apply(*args)
        single.apply(*args)

    feed()
    occ_before = np.asarray(jnp.sum(sharded.state.table.occ, axis=1))
    # scale "down": pack all vnodes onto the first 2 shards
    new_map = np.arange(VNODE_COUNT, dtype=np.int32) % 2
    sharded.reshard(new_map)
    occ_after = np.asarray(jnp.sum(sharded.state.table.occ, axis=1))
    assert occ_after[2:].sum() == 0          # state actually moved
    # nothing lost in transit: results identical right after the move
    assert sharded.snapshot() == _single_chip_snapshot(single)
    feed()                                    # keep streaming after move
    # scale back "up" to all 8 shards
    sharded.reshard(np.arange(VNODE_COUNT, dtype=np.int32) % 8)
    feed()
    assert sharded.snapshot() == _single_chip_snapshot(single)


def test_sharded_agg_grows_past_initial_capacity(eight_devices):
    """State 10x the initial device capacity (VERDICT r3 #5): the
    fatal-on-overflow contract is gone — the kernel rehashes into
    larger per-shard tables mid-stream and stays exact."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    specs = [AggSpec(AggKind.COUNT), AggSpec(AggKind.MAX,
                                             np.dtype(np.int64))]
    k = ShardedAggKernel(mesh, key_width=2, specs=specs, capacity=256)
    import collections
    want_c = collections.Counter()
    want_m = {}
    rng = np.random.default_rng(3)
    n_keys = 2560                     # 10x the initial capacity
    for _round in range(10):
        gk = rng.integers(0, n_keys, 512).astype(np.int64) * 7_001
        vals = rng.integers(0, 1 << 40, 512)
        hi, lo = lanes.split_i64(gk)
        k.apply(np.stack([hi, lo], axis=1),
                np.ones(512, np.int32), np.ones(512, bool),
                [((), None),
                 (specs[1].encode_input(vals), np.ones(512, bool))])
        for g, v in zip(gk.tolist(), vals.tolist()):
            want_c[g] += 1
            want_m[g] = max(want_m.get(g, v), v)
    assert k.capacity > 256           # grew
    snap = k.snapshot()
    got = {int(lanes.merge_i64(np.asarray([kt[0]]),
                               np.asarray([kt[1]]))[0]): v
           for kt, v in snap.items()}
    assert len(got) == len(want_c)
    for g, (c, m) in got.items():
        assert (c, m) == (want_c[g], want_m[g])


def test_sql_retracting_agg_runs_sharded(eight_devices):
    """Retracting upstream + MIN/MAX at parallelism 8 now runs the
    SHARDED kernel (patch_accs shard-mapped — the last fixed-capacity
    v1 NotImplementedError) and matches parallelism 1 exactly."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.parallel.agg import ShardedAggKernel

    sql = [
        "CREATE SOURCE bid WITH (connector='nexmark', "
        "nexmark.table.type='bid', nexmark.event.num=6000, "
        "nexmark.max.chunk.size=256)",
        "CREATE MATERIALIZED VIEW m1 AS SELECT auction, count(*) AS c "
        "FROM bid GROUP BY auction",
        # GROUP BY over an UPDATING MV: members leave groups, so the
        # MIN of each c-group rises — stale extremes must repatch
        "CREATE MATERIALIZED VIEW m2 AS SELECT c, count(*) AS n, "
        "min(auction) AS mn FROM m1 GROUP BY c",
    ]

    def _kernels(f):
        out = []
        for actor in f.actors.values():
            ex = actor.consumer
            while ex is not None:
                if hasattr(ex, "kernel"):
                    out.append(ex.kernel)
                ex = getattr(ex, "input", None)
        return out

    async def run(par):
        f = Frontend(rate_limit=4, min_chunks=4, parallelism=par)
        for s in sql:
            await f.execute(s)
        for _ in range(30):
            await f.step()
        rows = await f.execute("SELECT * FROM m2")
        if par > 1:
            ks = _kernels(f)
            # EVERY agg kernel must be sharded — especially m2's
            # retracting MIN/MAX (the newly-enabled patch_accs path)
            assert ks and all(isinstance(k, ShardedAggKernel)
                              for k in ks), "not fully sharded"
        await f.close()
        return sorted(rows)

    got = asyncio.run(run(8))
    want = asyncio.run(run(1))
    assert got == want and len(got) > 5
