"""Unified state-tiering subsystem (state/tier.py).

The cold tier bounds resident keyed state for EVERY stateful executor
— agg groups, outer-join degree state, TopN group caches — by evicting
least-recently-touched keys to the durable state table and reloading
them on touch. "Dies at high cardinality" becomes "degrades to reload
traffic": every oracle here compares a hard-capped run bit-identically
against an uncapped one.

Reference parity: managed_state/join/mod.rs:379-420 (LRU over the
StateTable), cache/managed_lru.rs, memory_management/memory_manager.rs.
"""

import asyncio
import collections

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.state.tier import StateTier
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind

AGG_S = Schema.of(g=DataType.INT64, v=DataType.INT64)
L_S = Schema.of(k=DataType.INT64, lv=DataType.INT64, lid=DataType.INT64)
R_S = Schema.of(k=DataType.INT64, rv=DataType.INT64, rid=DataType.INT64)


def _barrier(n):
    curr = Epoch.from_physical(n)
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(curr, prev), BarrierKind.CHECKPOINT)


def _chunk(schema, rows, ops=None):
    names = [f.name for f in schema]
    return StreamChunk.from_pydict(
        schema, {nm: [r[i] for r in rows]
                 for i, nm in enumerate(names)}, ops=ops)


def _final_rows(outs):
    """Fold a change stream into pk→row (pk = first column)."""
    st = {}
    for m in outs:
        if isinstance(m, StreamChunk):
            for op, row in m.to_records():
                if op in (Op.INSERT, Op.UPDATE_INSERT):
                    st[row[0]] = row
                elif op == Op.DELETE:
                    st.pop(row[0], None)
    return st


# -- tier policy units ----------------------------------------------------

def test_tier_lru_and_cap():
    """Oldest-touched keys evict first; re-touch rescues a key."""
    evicted = []
    tier = StateTier(memory=type("M", (), {"soft_limit": None,
                                           "last_total": 0})())
    part = tier.register("p", lambda ks: evicted.extend(ks) or len(ks),
                         cap=4)
    tier.touch(part, ["a", "b", "c", "d"], 1)
    tier.touch(part, ["a"], 2)            # a is now the NEWEST
    tier.touch(part, ["e", "f"], 3)       # 6 resident > cap 4
    n = tier.sweep(part, 4)               # target = 4 * 0.75 = 3
    assert n == 3 and evicted == ["b", "c", "d"]
    assert list(part.keys) == ["a", "e", "f"]


def test_tier_pressure_watermark():
    """MemoryContext over its soft limit halves every participant at
    its next sweep, cap or no cap."""
    mem = type("M", (), {"soft_limit": 100, "last_total": 500})()
    evicted = []
    tier = StateTier(memory=mem)
    part = tier.register("p", lambda ks: evicted.extend(ks) or len(ks))
    tier.touch(part, list(range(10)), 1)
    assert tier.sweep(part, 2) == 5       # PRESSURE_KEEP_RATIO = 0.5
    assert evicted == [0, 1, 2, 3, 4]
    mem.last_total = 50                   # back under the limit
    assert tier.sweep(part, 3) == 0


def test_tier_insert_false_refreshes_only():
    tier = StateTier(memory=type("M", (), {"soft_limit": None,
                                           "last_total": 0})())
    part = tier.register("p", lambda ks: len(ks), cap=None)
    tier.touch(part, ["a"], 1)
    tier.touch(part, ["a", "b"], 2, insert=False)
    assert list(part.keys) == ["a"]       # b never minted


# -- hash-agg consumer ----------------------------------------------------

def _agg_calls():
    return [AggCall(AggKind.SUM, 1), AggCall(AggKind.COUNT)]


def _build_agg(store, msgs, tier_cap):
    sch, pk = agg_state_schema(AGG_S, [0], _agg_calls())
    t = StateTable(1, sch, pk, store, dist_key_indices=[0])
    return HashAggExecutor(MockSource(AGG_S, msgs), [0], _agg_calls(),
                           t, append_only=False, tier_cap=tier_cap,
                           kernel_capacity=1 << 10)


def _agg_script(n_keys=300, wave=100):
    """q7-shaped: waves of fresh groups (old ones go cold), then every
    group re-touched — including RETRACTIONS against evicted groups."""
    msgs = [_barrier(1)]
    epoch = 2
    for lo in range(0, n_keys, wave):
        msgs += [_chunk(AGG_S, [(g, g * 2)
                                for g in range(lo, lo + wave)]),
                 _barrier(epoch)]
        epoch += 1
    for lo in range(0, n_keys, wave):
        msgs += [_chunk(AGG_S, [(g, 5)
                                for g in range(lo, lo + wave)]),
                 _barrier(epoch)]
        epoch += 1
    retr = [(g, g * 2) for g in range(0, 50)]
    msgs += [_chunk(AGG_S, retr, ops=[Op.DELETE] * len(retr)),
             _barrier(epoch)]
    return msgs, epoch


def test_agg_high_cardinality_oracle():
    """Groups ≫ cap: the capped run (cap = 1/18th of cardinality) is
    bit-identical to the uncapped one, through evictions, reloads AND
    retractions of evicted groups — agg state is fully durable, so
    reload-on-touch is retraction-safe."""
    msgs, epoch = _agg_script()
    capped = _build_agg(MemoryStateStore(), msgs, 16)
    outs_c = asyncio.run(collect_until_n_barriers(capped, epoch - 1))
    uncapped = _build_agg(MemoryStateStore(), msgs, None)
    outs_u = asyncio.run(collect_until_n_barriers(uncapped, epoch - 1))
    assert _final_rows(outs_c) == _final_rows(outs_u)
    part = capped._tier_part
    assert part.evicted_total > 0 and part.reload_total > 0
    # the cap held at the last sweep
    assert len(part.keys) <= 16


def test_agg_crash_recovery_with_evicted_keys():
    """Crash with most groups evicted: a fresh executor over the same
    store recovers the COMMITTED durable state — evicted and resident
    alike — and further touches stay oracle-exact."""
    store = MemoryStateStore()
    msgs, epoch = [_barrier(1)], 2
    for lo in range(0, 300, 100):
        msgs += [_chunk(AGG_S, [(g, g) for g in range(lo, lo + 100)]),
                 _barrier(epoch)]
        epoch += 1
    first = _build_agg(store, msgs, 16)
    asyncio.run(collect_until_n_barriers(first, epoch - 1))
    assert len(first._cold_groups) > 200          # most groups cold

    # restart: touch every third group (evicted before the crash)
    touch = [(g, 1) for g in range(0, 300, 3)]
    msgs2 = [_barrier(epoch), _chunk(AGG_S, touch), _barrier(epoch + 1)]
    second = _build_agg(store, msgs2, 16)
    outs = asyncio.run(collect_until_n_barriers(second, 2))
    got = _final_rows(outs)
    # every touched group emits an UPDATE pair with sum = g + 1
    assert len(got) == len(touch)
    for g, _one in touch:
        assert got[g] == (g, g + 1, 2)


def test_agg_sql_front_door_with_rw_state_tier():
    """SET state_tier_cap on the session: a GROUP BY with cardinality
    ≫ cap stays bit-identical to the uncapped run, and rw_state_tier
    accounts residency/evictions under the cap-derived bound."""
    from risingwave_tpu.frontend.session import Frontend

    async def run(cap):
        fe = Frontend(min_chunks=8)
        if cap:
            await fe.execute(f"SET state_tier_cap = {cap}")
            await fe.execute("SET state_tier_soft_limit_mb = 256")
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=6000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c, max(price) AS mx FROM bid "
            "GROUP BY auction")
        await fe.step(10)
        rows = await fe.execute("SELECT * FROM agg")
        tier = await fe.execute("SELECT * FROM rw_state_tier")
        await fe.close()
        return collections.Counter(map(tuple, rows)), tier

    capped, tier = asyncio.run(run(16))
    uncapped, _ = asyncio.run(run(None))
    assert capped == uncapped
    assert len(capped) > 10 * 16          # cardinality ≫ cap
    agg_rows = [r for r in tier if r[0].startswith("HashAggExecutor")]
    assert agg_rows, tier
    _name, cap, resident, evicted, _reloads, _nb = agg_rows[0]
    assert cap == 16 and evicted > 0
    assert resident <= 16                 # post-sweep bound held


def test_tier_cap_rides_ddl_log():
    """SET state_tier_cap rides the DDL log: recovery replays the
    CREATE under the recorded cap (join state-table pk layouts depend
    on it), and the replayed session shows the value."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def first():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.execute("SET state_tier_cap = 8")
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(6)
        rows = await fe.execute("SELECT * FROM agg")
        await fe.close()
        return rows

    async def recovered():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.recover()
        shown = await fe.execute("SHOW state_tier_cap")
        await fe.step(4)
        rows = await fe.execute("SELECT * FROM agg")
        await fe.close()
        return shown, rows

    rows1 = asyncio.run(first())
    shown, rows2 = asyncio.run(recovered())
    assert shown == [("8",)]
    # recovery resumed the job (sources continue; counts only grow)
    c1 = dict(map(tuple, rows1))
    c2 = dict(map(tuple, rows2))
    assert set(c1) <= set(c2)
    assert all(c2[k] >= c1[k] for k in c1)


def test_alter_parallelism_agg_with_tier():
    """ALTER ... SET PARALLELISM on a tier-capped agg MV drives a full
    reschedule cycle — stop barrier, replan from the recorded id base
    UNDER THE CREATE-TIME TIER CAP (_mv_tier_caps), recovery from the
    kept state tables (evicted groups included — they are just rows
    there) — and the MV stays oracle-exact while the new executor
    re-caps residency at its next sweeps. (Parallelism 1→1 keeps the
    cycle on the single-chip kernel; the mesh path is exercised by
    test_reschedule.)"""
    from risingwave_tpu.frontend.session import Frontend

    src = ("CREATE SOURCE bid WITH (connector='nexmark', "
           "nexmark.table.type='bid', nexmark.event.num=4000, "
           "nexmark.max.chunk.size=256)")
    mv = ("CREATE MATERIALIZED VIEW v AS SELECT auction, "
          "count(*) AS c, max(price) AS m FROM bid GROUP BY auction")

    async def with_alter():
        fe = Frontend(rate_limit=4, min_chunks=4)
        await fe.execute("SET state_tier_cap = 16")
        await fe.execute(src)
        await fe.execute(mv)
        for _ in range(12):
            await fe.step()
        await fe.execute(
            "ALTER MATERIALIZED VIEW v SET PARALLELISM = 1")
        for _ in range(40):
            await fe.step()
        rows = await fe.execute("SELECT * FROM v")
        tier = await fe.execute("SELECT * FROM rw_state_tier")
        await fe.close()
        # the REPLANNED executor registered with the CREATE-time cap
        agg_rows = [r for r in tier
                    if r[0].startswith("HashAggExecutor")]
        assert agg_rows and agg_rows[0][1] == 16
        assert agg_rows[0][2] <= 16       # re-capped after recovery
        return sorted(rows)

    async def plain():
        fe = Frontend(rate_limit=4, min_chunks=4)
        await fe.execute(src)
        await fe.execute(mv)
        for _ in range(60):
            await fe.step()
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return sorted(rows)

    assert asyncio.run(with_alter()) == asyncio.run(plain())


# -- outer-join consumer --------------------------------------------------

def _join_outer(store, lmsgs, rmsgs, cap):
    lt = StateTable(11, L_S, [0, 2], store, dist_key_indices=[0])
    rt = StateTable(12, R_S, [0, 2], store, dist_key_indices=[0])
    return HashJoinExecutor(
        MockSource(L_S, lmsgs), MockSource(R_S, rmsgs),
        [0], [0], lt, rt, join_type=JoinType.LEFT_OUTER,
        state_cap=cap)


def test_outer_join_eviction_then_retraction_oracle():
    """LEFT OUTER: unmatched left rows evict (their padded emissions
    already downstream), then right rows match them — the reload
    recomputes degrees and the padded-row RETRACTIONS (Delete) emit
    exactly as in the uncapped run."""
    def script():
        lmsgs, rmsgs = [_barrier(1)], [_barrier(1)]
        epoch = 2
        for lo in range(0, 300, 100):
            rows = [(k, k * 2, k) for k in range(lo, lo + 100)]
            lmsgs += [_chunk(L_S, rows), _barrier(epoch)]
            rmsgs += [_barrier(epoch)]
            epoch += 1
        rrows = [(k, k * 7, 1000 + k) for k in range(0, 100)]
        rmsgs += [_chunk(R_S, rrows), _barrier(epoch)]
        lmsgs += [_barrier(epoch)]
        epoch += 1
        return lmsgs, rmsgs, epoch

    def run(cap):
        lm, rm, epoch = script()
        j = _join_outer(MemoryStateStore(), lm, rm, cap)
        outs = asyncio.run(collect_until_n_barriers(j, epoch - 1))
        got = collections.Counter()
        for m in outs:
            if isinstance(m, StreamChunk):
                for op, row in m.to_records():
                    got[(row, op in (Op.INSERT, Op.UPDATE_INSERT))] += 1
        return j, got

    jc, got_c = run(32)
    _ju, got_u = run(None)
    assert got_c == got_u
    evicted = sum(p.evicted_total for p in jc._tier_parts)
    reloads = sum(p.reload_total for p in jc._tier_parts)
    assert evicted > 0 and reloads > 0
    # the padded row for key 0 was emitted, then RETRACTED after its
    # (previously evicted) left row matched
    assert got_u[((0, 0, 0, None, None, None), True)] == 1
    assert got_u[((0, 0, 0, None, None, None), False)] == 1
    assert got_c[((0, 0, 0, None, None, None), False)] == 1


def test_outer_join_matched_then_evicted_no_spurious_flip():
    """A MATCHED left row (degree > 0) evicts ON BOTH SIDES; a second
    matching right row arrives later. The reload must recompute
    degree 1 — NOT 0 (the cross-cold-twin union reload in _reload_cold)
    — or a spurious padded Delete would emit for a padding that is not
    on. Oracle: bit-identical to the uncapped run."""
    def script():
        lmsgs, rmsgs = [_barrier(1)], [_barrier(1)]
        epoch = 2
        # key 0 matched immediately (left + right in epoch 2)
        lmsgs += [_chunk(L_S, [(0, 5, 0)]), _barrier(epoch)]
        rmsgs += [_chunk(R_S, [(0, 50, 100)]), _barrier(epoch)]
        epoch += 1
        # flood both sides so key 0 evicts everywhere
        for lo in range(1, 301, 100):
            lrows = [(k, k, k) for k in range(lo, lo + 100)]
            rrows = [(k, k, 500 + k) for k in range(lo, lo + 100)]
            lmsgs += [_chunk(L_S, lrows), _barrier(epoch)]
            rmsgs += [_chunk(R_S, rrows), _barrier(epoch)]
            epoch += 1
        # second right row for key 0
        rmsgs += [_chunk(R_S, [(0, 51, 101)]), _barrier(epoch)]
        lmsgs += [_barrier(epoch)]
        epoch += 1
        return lmsgs, rmsgs, epoch

    def run(cap):
        lm, rm, epoch = script()
        j = _join_outer(MemoryStateStore(), lm, rm, cap)
        outs = asyncio.run(collect_until_n_barriers(j, epoch - 1))
        got = collections.Counter()
        for m in outs:
            if isinstance(m, StreamChunk):
                for op, row in m.to_records():
                    got[(row, op in (Op.INSERT, Op.UPDATE_INSERT))] += 1
        return j, got

    jc, got_c = run(32)
    _ju, got_u = run(None)
    assert got_c == got_u
    assert sum(p.evicted_total for p in jc._tier_parts) > 0
    # both matched pairs present exactly once in the capped run
    assert got_c[((0, 5, 0, 0, 50, 100), True)] == 1
    assert got_c[((0, 5, 0, 0, 51, 101), True)] == 1
    # padded emissions for key 0 are BALANCED (insert count == delete
    # count): a degree-recompute bug would leave an extra Delete
    pad = (0, 5, 0, None, None, None)
    assert got_c[(pad, True)] == got_c[(pad, False)]


# -- GroupTopN consumer ---------------------------------------------------

def test_group_topn_tier_oracle_q5():
    """q5 pipeline (hop → agg → group top-n) with the tier capping
    BOTH stateful stages at a handful of resident groups: the
    materialized MV is bit-identical to the uncapped run."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import (
        build_q5, drive_to_completion,
    )

    def run(cap):
        cfg = NexmarkConfig(event_num=4000, max_chunk_size=512,
                            generate_strings=False)
        p = build_q5(MemoryStateStore(), cfg, rate_limit=8,
                     min_chunks=8, tier_cap=cap)
        asyncio.run(drive_to_completion(p, {1: 4000 * 46 // 50},
                                        in_flight=2))
        return sorted(r for _pk, r in p.mv_table.iter_rows())

    assert run(8) == run(None)


def test_group_topn_cold_touch_reloads_pre_chunk_state():
    """A COLD group touched by a later chunk must reload PRE-chunk
    state: the emitted delta replaces the old top with the new one
    (and a delete against a cold group retracts, not no-ops)."""
    from risingwave_tpu.stream.executors.top_n import GroupTopNExecutor

    sch = Schema.of(g=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    msgs = [_barrier(1),
            _chunk(sch, [(g, 10 + g) for g in range(8)]), _barrier(2)]
    # enough checkpoints for group 0 to age out of a 4-group cap
    for e in range(3, 8):
        msgs.append(_chunk(sch, [(7, 100 + e)]))
        msgs.append(_barrier(e))
    # touch cold group 0: a better row, then DELETE the original top
    msgs += [_chunk(sch, [(0, 99)]), _barrier(8)]
    msgs += [_chunk(sch, [(0, 10)], ops=[Op.DELETE]), _barrier(9)]
    msgs += [_chunk(sch, [(0, 99)], ops=[Op.DELETE]), _barrier(10)]

    def run(cap):
        state = StateTable(7, sch, [0, 1], store if cap else
                           MemoryStateStore())
        topn = GroupTopNExecutor(
            MockSource(sch, list(msgs)), [(1, True)], 0, 1, state,
            group_indices=[0], pk_indices=[0, 1], tier_cap=cap)
        outs = asyncio.run(collect_until_n_barriers(topn, 10))
        got = collections.Counter()
        for m in outs:
            if isinstance(m, StreamChunk):
                for op, row in m.to_records():
                    got[(row, op in (Op.INSERT,
                                     Op.UPDATE_INSERT))] += 1
        return topn, got

    tc, got_c = run(4)
    _tu, got_u = run(None)
    assert got_c == got_u, (
        sorted((k, got_c[k], got_u[k]) for k in set(got_c) | set(got_u)
               if got_c[k] != got_u[k]))
    assert tc._tier_part.evicted_total > 0
    assert tc._tier_part.reload_total > 0
    # final top for group 0: (0,99) arrived, then both rows deleted —
    # the window ends EMPTY, so the (0,99) insert must be retracted
    assert got_u[((0, 99), True)] == got_u[((0, 99), False)]


def test_group_topn_guards():
    from risingwave_tpu.stream.executors.top_n import GroupTopNExecutor

    store = MemoryStateStore()
    state = StateTable(5, AGG_S, [1], store)   # pk NOT group-prefixed
    with pytest.raises(ValueError, match="prefixed"):
        GroupTopNExecutor(MockSource(AGG_S, []), [(1, True)], 0, 1,
                          state, group_indices=[0], pk_indices=[0, 1],
                          tier_cap=4)
    with pytest.raises(ValueError, match="grouped"):
        GroupTopNExecutor(MockSource(AGG_S, []), [(1, True)], 0, 1,
                          StateTable(6, AGG_S, [0, 1], store),
                          tier_cap=4)


# -- ctl memory -----------------------------------------------------------

def test_ctl_memory_verb(tmp_path, capsys):
    """`ctl memory` dumps MemoryContext.sizes() + tier residency
    against a recovered data dir."""
    from risingwave_tpu.__main__ import main as cli_main
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    d = str(tmp_path / "rw")

    async def seed():
        fe = Frontend(HummockLite(LocalFsObjectStore(d)), min_chunks=4)
        await fe.execute("SET state_tier_cap = 8")
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(4)
        await fe.close()

    asyncio.run(seed())
    with pytest.raises(SystemExit) as e:
        cli_main(["ctl", "--data-dir", d, "memory", "--steps", "2"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "accounted host state:" in out
    assert "state tier" in out and "HashAggExecutor" in out
