"""Session config (SET/SHOW) + rw_* system catalogs (VERDICT r4
missing #9: src/common/src/session_config/ and
src/frontend/src/catalog/system_catalog/ analogs)."""

import asyncio

import pytest

from risingwave_tpu.frontend.planner import PlanError
from risingwave_tpu.frontend.session import Frontend


def _run(coro):
    return asyncio.run(coro)


def test_set_show_session_vars():
    async def run():
        fe = Frontend(min_chunks=4)
        assert await fe.execute("SET streaming_rate_limit = 4") == "SET"
        assert await fe.execute("SHOW streaming_rate_limit") == [("4",)]
        await fe.execute("SET application_name = 'psql-test'")
        assert await fe.execute("SHOW application_name") == \
            [("psql-test",)]
        rows = dict(await fe.execute("SHOW ALL"))
        assert rows["streaming_rate_limit"] == "4"
        assert rows["application_name"] == "psql-test"
        # TO DEFAULT restores the session's construction-time value
        await fe.execute("SET streaming_rate_limit TO default")
        assert await fe.execute("SHOW streaming_rate_limit") == [("8",)]
        with pytest.raises(PlanError, match="unrecognized"):
            await fe.execute("SET no_such_var = 1")
        with pytest.raises(PlanError, match="unrecognized"):
            await fe.execute("SHOW no_such_var")
        await fe.close()

    _run(run())


def test_set_vars_bind_to_new_jobs():
    """Typed knobs feed future CREATEs: join_state_cap set via SQL
    lands on the next join's executor sides."""
    async def run():
        fe = Frontend(min_chunks=4)
        for t in ("person", "auction"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', nexmark.event.num=2000)")
        await fe.execute("SET join_state_cap = 32")
        await fe.execute(
            "CREATE MATERIALIZED VIEW j AS SELECT p.id FROM person "
            "AS p JOIN auction AS a ON p.id = a.seller")
        await fe.step(3)
        join = None
        for a in fe.actors.values():
            ex = a.consumer
            while ex is not None and not hasattr(ex, "sides"):
                ex = getattr(ex, "input", None)
            if ex is not None:
                join = ex
        assert join is not None
        assert all(s.state_cap == 32 for s in join.sides)
        await fe.close()

    _run(run())


def test_system_catalog_tables():
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        await fe.step(2)
        mvs = await fe.execute(
            "SELECT name FROM rw_materialized_views")
        assert ("m",) in mvs
        srcs = await fe.execute(
            "SELECT name, connector FROM rw_sources")
        assert ("bid", "nexmark") in srcs
        # system tables compose with the batch surface
        cnt = await fe.execute(
            "SELECT count(*) AS n FROM rw_sources")
        assert cnt == [(1,)]
        await fe.close()

    _run(run())


def test_user_table_shadows_system_catalog():
    """A user table named rw_sources wins over the system view."""
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute("CREATE TABLE rw_sources (x INT)")
        await fe.execute("INSERT INTO rw_sources VALUES (7)")
        rows = await fe.execute("SELECT x FROM rw_sources")
        assert rows == [(7,)]
        await fe.close()

    _run(run())


def test_rw_tables_vs_mvs_split():
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute("CREATE TABLE t (x INT)")
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT auction FROM bid")
        await fe.step(2)
        tables = await fe.execute("SELECT name FROM rw_tables")
        mvs = await fe.execute(
            "SELECT name FROM rw_materialized_views")
        assert tables == [("t",)]
        assert mvs == [("m",)]
        await fe.close()

    _run(run())


def test_set_string_unescaping():
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute("SET application_name = 'it''s'")
        assert await fe.execute("SHOW application_name") == \
            [("it's",)]
        await fe.close()

    _run(run())


def test_scalar_args_must_be_constant():
    """Kernel-scalar argument positions reject non-literals at bind
    time (a column there would silently broadcast row 0)."""
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500)")
        with pytest.raises(Exception, match="constant"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW b AS SELECT "
                "substr(url, auction) AS s FROM bid")
        with pytest.raises(Exception, match="constant"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW b AS SELECT "
                "split_part(url, channel, 1) AS s FROM bid")
        await fe.close()

    _run(run())


def test_filter_on_non_aggregate_rejected():
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500)")
        with pytest.raises(Exception, match="not an aggregate"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW b AS SELECT channel, "
                "upper(channel) FILTER (WHERE price > 0) AS u "
                "FROM bid GROUP BY channel")
        await fe.close()

    _run(run())
