"""The streaming join-type matrix vs a recomputed oracle.

Mirrors the outer/semi/anti cases of the reference's hash_join tests
(src/stream/src/executor/hash_join.rs:61-71 const generics + test mod):
scripted inserts/deletes on both sides; the emitted changelog must
materialize to exactly the join recomputed over the final state, for
every join type, including NULL keys, N:M matches, retractions that
flip degree transitions, and recovery (degree recompute).
"""

import asyncio
from collections import Counter

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

L = Schema.of(lk=DataType.INT64, lv=DataType.INT64)
R = Schema.of(rk=DataType.INT64, rv=DataType.INT64)


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def lchunk(ks, vs, ops=None):
    return StreamChunk.from_pydict(L, {"lk": ks, "lv": vs}, ops=ops)


def rchunk(ks, vs, ops=None):
    return StreamChunk.from_pydict(R, {"rk": ks, "rv": vs}, ops=ops)


def oracle_view(jt: JoinType, left, right) -> Counter:
    """Recompute the join over final (multiset) state."""
    out = Counter()
    if jt in (JoinType.LEFT_SEMI, JoinType.LEFT_ANTI):
        for lk, lv in left:
            n = 0 if lk is None else sum(1 for rk, _ in right if rk == lk)
            if (n > 0) != jt.is_anti:
                out[(lk, lv)] += 1
        return out
    if jt in (JoinType.RIGHT_SEMI, JoinType.RIGHT_ANTI):
        for rk, rv in right:
            n = 0 if rk is None else sum(1 for lk, _ in left if lk == rk)
            if (n > 0) != jt.is_anti:
                out[(rk, rv)] += 1
        return out
    for lk, lv in left:
        n = 0 if lk is None else sum(1 for rk, _ in right if rk == lk)
        if n == 0:
            if jt in (JoinType.LEFT_OUTER, JoinType.FULL_OUTER):
                out[(lk, lv, None, None)] += 1
        else:
            for rk, rv in right:
                if rk == lk:
                    out[(lk, lv, rk, rv)] += 1
    if jt in (JoinType.RIGHT_OUTER, JoinType.FULL_OUTER):
        for rk, rv in right:
            n = 0 if rk is None else sum(1 for lk, _ in left if lk == rk)
            if n == 0:
                out[(None, None, rk, rv)] += 1
    return out


def materialize(msgs) -> Counter:
    view = Counter()
    for m in msgs:
        if not is_chunk(m):
            continue
        for op, row in m.to_records():
            if op.is_insert:
                view[row] += 1
            else:
                view[row] -= 1
                assert view[row] >= 0, f"negative count for {row}"
    return +view


def run(jt, script_l, script_r, n_barriers, store=None, ids=(61, 62)):
    store = store or MemoryStateStore()
    lt = StateTable(ids[0], L, [1], store, dist_key_indices=[])
    rt = StateTable(ids[1], R, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L, script_l), MockSource(R, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        join_type=jt)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, store


ALL_TYPES = list(JoinType)


@pytest.mark.parametrize("jt", ALL_TYPES, ids=[t.value for t in ALL_TYPES])
def test_join_type_scripted(jt):
    """Hand-scripted case exercising every transition: unmatched insert,
    match arriving later (0→1 flip), N:M growth, retraction back to
    unmatched (→0 flip), NULL keys on both sides."""
    script_l = [
        barrier(1),
        lchunk([1, 2, None], [10, 20, 30]),      # 1,2 unmatched; NULL
        barrier(2),
        lchunk([1], [11]),                       # 1 now matched (if r)
        barrier(3),
        lchunk([2], [20], ops=[Op.DELETE]),      # retract unmatched row
        barrier(4),
    ]
    script_r = [
        barrier(1),
        rchunk([3, None], [90, 91]),             # 3 unmatched; NULL
        barrier(2),
        rchunk([1, 1], [70, 71]),                # flips left 1: 0→2
        barrier(3),
        rchunk([1], [70], ops=[Op.DELETE]),      # degree 2→1 (no flip)
        barrier(4),
    ]
    msgs, _ = run(jt, script_l, script_r, 4)
    left = [(1, 10), (None, 30), (1, 11)]
    right = [(3, 90), (None, 91), (1, 71)]
    assert materialize(msgs) == oracle_view(jt, left, right), jt


@pytest.mark.parametrize("jt", ALL_TYPES, ids=[t.value for t in ALL_TYPES])
def test_join_type_random_stream(jt):
    rng = np.random.default_rng(hash(jt.value) % 2**32)
    left_rows, right_rows = [], []
    script_l, script_r = [barrier(1)], [barrier(1)]
    pk = [0, 0]
    for b in range(2, 7):
        for side, rows, script, mk in (
                (0, left_rows, script_l, lchunk),
                (1, right_rows, script_r, rchunk)):
            ks, vs, ops = [], [], []
            for _ in range(20):
                if rows and rng.random() < 0.3:
                    i = int(rng.integers(0, len(rows)))
                    k_, v_ = rows.pop(i)
                    ks.append(k_)
                    vs.append(v_)
                    ops.append(Op.DELETE)
                else:
                    k_ = int(rng.integers(0, 6))
                    if rng.random() < 0.1:
                        k_ = None
                    v_ = pk[side]
                    pk[side] += 1
                    rows.append((k_, v_))
                    ks.append(k_)
                    vs.append(v_)
                    ops.append(Op.INSERT)
            script.append(mk(ks, vs, ops=ops))
            script.append(barrier(b))
    n_b = 6
    msgs, _ = run(jt, script_l, script_r, n_b)
    assert materialize(msgs) == oracle_view(jt, left_rows, right_rows), jt


@pytest.mark.parametrize("jt", [JoinType.LEFT_OUTER, JoinType.FULL_OUTER,
                                JoinType.LEFT_ANTI, JoinType.LEFT_SEMI],
                         ids=lambda t: t.value)
def test_join_type_recovery_recomputes_degrees(jt):
    """Kill-and-rebuild mid-stream: degrees recompute from state, and
    the resumed changelog still materializes to the oracle."""
    store = MemoryStateStore()
    phase1_l = [barrier(1), lchunk([1, 2], [10, 20]), barrier(2)]
    phase1_r = [barrier(1), rchunk([1], [70]), barrier(2)]
    msgs1, _ = run(jt, phase1_l, phase1_r, 2, store=store)
    # fresh executor over same tables; continue the stream
    phase2_l = [barrier(3), lchunk([1], [10], ops=[Op.DELETE]),
                barrier(4)]
    phase2_r = [barrier(3), rchunk([2, 1], [80, 71]), barrier(4)]
    msgs2, _ = run(jt, phase2_l, phase2_r, 2, store=store)
    left = [(2, 20)]
    right = [(1, 70), (2, 80), (1, 71)]
    assert materialize(msgs1 + msgs2) == oracle_view(jt, left, right), jt
