"""Nexmark q7-shaped pipeline end-to-end: the first stateful TPU query.

q7 core (highest bid per 10s tumbling window — the HashAgg-on-TPU
baseline config; full q7's self-join lands with HashJoinExecutor):

    SELECT window_start, MAX(price), COUNT(*) FROM
      TUMBLE(bid, date_time, INTERVAL '10' SECOND)
    GROUP BY window_start;

Reference parity: e2e_test/streaming/nexmark/q7.slt.part semantics;
pipeline shape per SURVEY §3.2 — source → project(tumble) → hash-agg
(device kernel) → materialize, driven by the barrier loop. The plan
itself lives in risingwave_tpu.models.nexmark (shared with bench.py).
"""

import asyncio
from collections import defaultdict

import numpy as np

from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
from risingwave_tpu.models.nexmark import (
    DEFAULT_WINDOW, build_q7, drive_to_completion,
)
from risingwave_tpu.state.store import MemoryStateStore

WINDOW = DEFAULT_WINDOW


def q7_oracle(cfg, n_bids):
    k = np.arange(n_bids, dtype=np.int64)
    bids = gen_bids(k, cfg)
    w = (bids["date_time"] // WINDOW.usecs) * WINDOW.usecs
    out = defaultdict(lambda: (0, 0))
    for wi, p in zip(w.tolist(), bids["price"].tolist()):
        mx, c = out[wi]
        out[wi] = (max(mx, p), c + 1)
    return dict(out)


def test_q7_end_to_end():
    n_epochs = 40
    # ~3 windows over the whole run: gap 100µs ⇒ 10s window = 100K events
    cfg = NexmarkConfig(event_num=50 * 50 * n_epochs, max_chunk_size=1024,
                        min_event_gap_in_ns=100_000_000)  # 0.1s/event
    pipeline = build_q7(MemoryStateStore(), cfg)
    n_bids = 46 * 50 * n_epochs
    asyncio.run(drive_to_completion(pipeline, {1: n_bids}))
    loop, mv_table = pipeline.loop, pipeline.mv_table
    assert len(loop.stats.completed_epochs) >= 3

    got = {row[0]: (row[1], row[2]) for _pk, row in mv_table.iter_rows()}
    expect = q7_oracle(cfg, n_bids)
    assert len(got) > 3   # several windows
    assert got == expect


def test_q7_watermark_cleaning_bounded_state():
    """Watermark-driven state cleaning end to end (VERDICT r2 #3):
    with a WatermarkFilter generating event-time watermarks and the agg
    retiring closed tumble windows, (a) the MV still matches the oracle
    exactly — nexmark event time is monotone, so no rows are late and
    retirement never changes results — and (b) the agg value-state table
    holds only the open windows at the end, not every window ever seen."""
    from risingwave_tpu.common.types import Interval

    n_epochs = 60
    # gap 0.2s/event ⇒ a 10s window every 50 events: many windows
    cfg = NexmarkConfig(event_num=50 * 30 * n_epochs, max_chunk_size=512,
                        min_event_gap_in_ns=200_000_000)
    pipeline = build_q7(MemoryStateStore(), cfg, rate_limit=2,
                        watermark_delay=Interval(usecs=0))
    n_bids = 46 * 30 * n_epochs
    asyncio.run(drive_to_completion(pipeline, {1: n_bids}))

    got = {row[0]: (row[1], row[2]) for _pk, row in
           pipeline.mv_table.iter_rows()}
    expect = q7_oracle(cfg, n_bids)
    assert len(expect) > 10            # many windows closed over the run
    assert got == expect               # retirement never changed results

    # the agg's VALUE STATE kept only windows at/after the final
    # watermark — closed windows were deleted (mv keeps final results)
    agg_executor = pipeline.actor.consumer.input  # materialize ← agg
    state_rows = list(agg_executor.table.iter_rows())
    assert len(state_rows) < len(expect) / 2, \
        (len(state_rows), len(expect))
    final_wm = agg_executor._cleaned_wm
    assert final_wm is not None
    assert all(row[0] >= final_wm for _pk, row in state_rows)
    # device table occupancy bounded too (survivors only)
    occ = int(np.asarray(agg_executor.kernel.state.table.occ).sum())
    assert occ <= len(state_rows) + 1


def test_q7_on_hummock_with_restart(tmp_path):
    """The full stack: pipeline state checkpoints through HummockLite on
    a local-FS object store; a fresh process-equivalent (new store over
    the same objects, new pipeline) resumes from the committed epoch and
    finishes with exactly the oracle result (recovery.rs semantics)."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    root = str(tmp_path / "hummock")
    cfg = NexmarkConfig(event_num=50 * 40, max_chunk_size=256,
                        min_event_gap_in_ns=100_000_000)
    n_bids = 46 * 40

    # phase 1: run HALF the stream, checkpoint, drop everything
    store1 = HummockLite(LocalFsObjectStore(root))
    p1 = build_q7(store1, cfg, rate_limit=1, min_chunks=1)
    asyncio.run(drive_to_completion(p1, {1: n_bids // 2}))
    offset1 = p1.reader.offset
    assert offset1 >= n_bids // 2
    del p1, store1

    # phase 2: recover from the object store, run to completion
    store2 = HummockLite(LocalFsObjectStore(root))
    p2 = build_q7(store2, cfg, rate_limit=1, min_chunks=1)
    asyncio.run(drive_to_completion(p2, {1: n_bids}))
    # the source resumed at (or after) the committed offset, not zero
    assert p2.reader.offset == n_bids

    got = {row[0]: (row[1], row[2]) for _pk, row in p2.mv_table.iter_rows()}
    assert got == q7_oracle(cfg, n_bids)
