"""Nexmark q7-shaped pipeline end-to-end: the first stateful TPU query.

q7 core (highest bid per 10s tumbling window — the HashAgg-on-TPU
baseline config; full q7's self-join lands with HashJoinExecutor):

    SELECT window_start, MAX(price), COUNT(*) FROM
      TUMBLE(bid, date_time, INTERVAL '10' SECOND)
    GROUP BY window_start;

Reference parity: e2e_test/streaming/nexmark/q7.slt.part semantics;
pipeline shape per SURVEY §3.2 — source → project(tumble) → hash-agg
(device kernel) → materialize, driven by the barrier loop.
"""

import asyncio
from collections import defaultdict

import numpy as np

from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.connectors.nexmark import (
    NexmarkConfig, NexmarkSplitReader, gen_bids,
)
from risingwave_tpu.expr.expr import InputRef, tumble_start
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.stream.executors.materialize import MaterializeExecutor
from risingwave_tpu.stream.executors.simple import ProjectExecutor
from risingwave_tpu.stream.executors.source import SourceExecutor
from risingwave_tpu.stream.message import StopMutation

SPLIT_STATE_SCHEMA = Schema([Field("split_id", DataType.VARCHAR),
                             Field("offset", DataType.INT64)])
WINDOW = Interval(usecs=10_000_000)   # 10 seconds


def build_q7(store, cfg):
    """Hand-built q7-core plan (fragmenter arrives with the frontend)."""
    reader = NexmarkSplitReader(cfg)
    barrier_tx, barrier_rx = channel_for_test()
    split_state = StateTable(1, SPLIT_STATE_SCHEMA, [0], store)
    source = SourceExecutor(reader, barrier_rx, split_state, actor_id=1,
                            rate_limit_chunks_per_barrier=4)
    s = source.schema
    project = ProjectExecutor(
        source,
        exprs=[tumble_start(
            InputRef(s.index_of("date_time"), DataType.TIMESTAMP), WINDOW),
            InputRef(s.index_of("price"), DataType.INT64)],
        names=["window_start", "price"])
    calls = [AggCall(AggKind.MAX, 1), AggCall(AggKind.COUNT)]
    agg_schema, agg_pk = agg_state_schema(project.schema, [0], calls)
    agg_state = StateTable(2, agg_schema, agg_pk, store,
                           dist_key_indices=[0])
    agg = HashAggExecutor(project, [0], calls, agg_state,
                          append_only=True,
                          output_names=["max_price", "bid_count"])
    mv_table = StateTable(3, agg.schema, [0], store)  # pk = window_start
    mat = MaterializeExecutor(agg, mv_table)
    local = LocalBarrierManager()
    local.register_sender(1, barrier_tx)
    local.set_expected_actors([1])
    actor = Actor(1, mat, dispatchers=[], barrier_manager=local)
    loop = BarrierLoop(local, store)
    return actor, loop, mv_table, reader


def q7_oracle(cfg, n_bids):
    k = np.arange(n_bids, dtype=np.int64)
    bids = gen_bids(k, cfg)
    w = (bids["date_time"] // WINDOW.usecs) * WINDOW.usecs
    out = defaultdict(lambda: (0, 0))
    for wi, p in zip(w.tolist(), bids["price"].tolist()):
        mx, c = out[wi]
        out[wi] = (max(mx, p), c + 1)
    return dict(out)


def test_q7_end_to_end():
    n_epochs = 40
    # ~3 windows over the whole run: gap 100µs ⇒ 10s window = 100K events
    cfg = NexmarkConfig(event_num=50 * 50 * n_epochs, max_chunk_size=1024,
                        min_event_gap_in_ns=100_000_000)  # 0.1s/event

    async def main():
        store = MemoryStateStore()
        actor, loop, mv_table, reader = build_q7(store, cfg)
        task = actor.spawn()
        while reader.offset < 46 * 50 * n_epochs:
            await loop.inject_and_collect()
        await loop.inject_and_collect()
        await loop.inject_and_collect(mutation=StopMutation(frozenset([1])))
        await task
        assert actor.failure is None, actor.failure
        return store, mv_table, loop

    store, mv_table, loop = asyncio.run(main())
    assert len(loop.stats.completed_epochs) >= 3

    got = {row[0]: (row[1], row[2]) for _pk, row in mv_table.iter_rows()}
    expect = q7_oracle(cfg, 46 * 50 * n_epochs)
    assert len(got) > 3   # several windows
    assert got == expect
