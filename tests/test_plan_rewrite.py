"""Plan-rewrite engine (frontend/opt): rule units, checker fallback,
oracle equivalence with rewrites on vs off, and the distributed
exchange-elision path.

Covers the ISSUE-4 acceptance points: every rule has a unit test, a
deliberately-broken rule trips the plan-property checker (fallback in
production mode, loud assertion in strict/test mode), Nexmark
q1/q4/q7/q8 and TPC-H q3/q5 produce BIT-IDENTICAL MV contents with
rewrites on vs off while q5/q7 plans carry strictly fewer lanes, the
session var plumbs through both frontends, and rw_plan_rewrites +
the rewrite metrics record what fired.
"""

import asyncio

import pytest

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.frontend.opt import (
    CheckError, parse_rules, plan_lane_stats, rewrite_fragment_graph,
    rewrite_history_rows, rewrite_stream_plan, set_strict_checker,
)
from risingwave_tpu.frontend.planner import PlanError, explain_tree
from risingwave_tpu.frontend.session import Frontend

SCHEMA = Schema.of(k=DataType.INT64, v=DataType.INT64)


def run(coro):
    return asyncio.run(coro)


# -- rule units over hand-built chains ------------------------------------


def _mat(consumer_input):
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    table = StateTable(1, consumer_input.schema, [0],
                       MemoryStateStore())
    return MaterializeExecutor(consumer_input, table)


def test_noop_project_elision_unit():
    from risingwave_tpu.expr.expr import InputRef
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    src = MockSource(SCHEMA, [])
    noop = ProjectExecutor(
        src, [InputRef(0, DataType.INT64), InputRef(1, DataType.INT64)],
        ["k", "v"])
    root = _mat(noop)
    new_root, report = rewrite_stream_plan(
        root, "noop_project_elision", record=False)
    assert report.fired == {"noop_project_elision": 1}
    assert new_root.input is src


def test_noop_project_with_renamed_column_stays():
    from risingwave_tpu.expr.expr import InputRef
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    src = MockSource(SCHEMA, [])
    renamed = ProjectExecutor(
        src, [InputRef(0, DataType.INT64), InputRef(1, DataType.INT64)],
        ["k", "v2"])                 # renames a column: NOT a noop
    root = _mat(renamed)
    _new, report = rewrite_stream_plan(
        root, "noop_project_elision", record=False)
    assert not report.fired


def test_project_fusion_unit():
    from risingwave_tpu.expr.expr import BinaryOp, InputRef
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    src = MockSource(SCHEMA, [])
    p1 = ProjectExecutor(
        src, [InputRef(0, DataType.INT64),
              BinaryOp("+", InputRef(1, DataType.INT64),
                       InputRef(0, DataType.INT64))], ["k", "s"])
    p2 = ProjectExecutor(p1, [InputRef(1, DataType.INT64)], ["s"])
    root = _mat(p2)
    new_root, report = rewrite_stream_plan(
        root, "project_fusion", record=False)
    assert report.fired.get("project_fusion") == 1
    fused = new_root.input
    assert isinstance(fused, ProjectExecutor)
    assert fused.input is src        # one projection left
    assert [f.name for f in fused.schema] == ["s"]


def test_checker_fallback_and_strict_mode():
    """A rule that corrupts the plan must never reach deployment: in
    fallback mode the pre-rule plan survives, in strict mode the
    violation raises."""
    from risingwave_tpu.expr.expr import InputRef
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.simple import ProjectExecutor

    def broken_rule(root):
        # drops a column right under the materialize: root contract
        # violation the checker must catch
        bad = ProjectExecutor(root.input,
                              [InputRef(0, DataType.INT64)], ["k"])
        import copy
        new = copy.copy(root)
        new.input = bad
        return new, 1, "oops"

    src = MockSource(SCHEMA, [])
    root = _mat(src)
    set_strict_checker(False)
    try:
        new_root, report = rewrite_stream_plan(
            root, "none", record=False,
            extra_rules={"broken": broken_rule})
        assert new_root is root                 # fell back
        assert report.fallbacks and \
            report.fallbacks[0][0] == "broken"
    finally:
        set_strict_checker(True)   # conftest default for this suite
    with pytest.raises(AssertionError, match="broken"):
        rewrite_stream_plan(root, "none", record=False,
                            extra_rules={"broken": broken_rule})


def test_parse_rules_validation():
    assert parse_rules("all") == parse_rules(None)
    assert parse_rules("none") == frozenset()
    assert parse_rules("column_pruning, filter_pushdown") == \
        frozenset({"column_pruning", "filter_pushdown"})
    with pytest.raises(PlanError):
        parse_rules("no_such_rule")


# -- SQL-level rule behavior ----------------------------------------------


NEXMARK_SOURCES = [
    ("CREATE SOURCE {t} WITH (connector='nexmark', "
     "nexmark.table.type='{t}', nexmark.event.num=2000, "
     "nexmark.max.chunk.size=128, "
     "nexmark.generate.strings='false')").format(t=t)
    for t in ("bid", "auction", "person")
]

TPCH_SOURCES = [
    ("CREATE SOURCE {t} WITH (connector='tpch', tpch.table='{t}', "
     "tpch.customers=150, tpch.orders=1500)").format(t=t)
    for t in ("customer", "orders", "lineitem", "supplier", "nation",
              "region")
]

TPCH_Q5 = (
    "CREATE MATERIALIZED VIEW q AS SELECT n.n_name, "
    "sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue "
    "FROM customer AS c "
    "JOIN orders AS o ON c.c_custkey = o.o_custkey "
    "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
    "JOIN supplier AS s ON l.l_suppkey = s.s_suppkey "
    "AND c.c_nationkey = s.s_nationkey "
    "JOIN nation AS n ON s.s_nationkey = n.n_nationkey "
    "JOIN region AS r ON n.n_regionkey = r.r_regionkey "
    "WHERE r.r_name = 'ASIA' AND o.o_orderdate < 9500 "
    "GROUP BY n.n_name")

TPCH_Q3 = (
    "CREATE MATERIALIZED VIEW q AS SELECT "
    "o.o_orderkey, o.o_orderdate, o.o_shippriority, "
    "sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue "
    "FROM customer AS c "
    "JOIN orders AS o ON c.c_custkey = o.o_custkey "
    "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
    "WHERE c.c_mktsegment = 'BUILDING' "
    "AND o.o_orderdate < 9204 AND l.l_shipdate > 9204 "
    "GROUP BY o.o_orderkey, o.o_orderdate, o.o_shippriority "
    "ORDER BY revenue DESC, o_orderdate ASC LIMIT 10")

NEXMARK_Q1 = ("CREATE MATERIALIZED VIEW q AS SELECT auction, bidder, "
              "price * 89 AS price_dol, date_time FROM bid")

NEXMARK_Q4 = (
    "CREATE MATERIALIZED VIEW q AS "
    "SELECT category, AVG(final) AS avg_final FROM ("
    "  SELECT a.category AS category, MAX(b.price) AS final"
    "  FROM auction AS a JOIN bid AS b ON a.id = b.auction"
    "  WHERE b.date_time BETWEEN a.date_time AND a.expires"
    "  GROUP BY a.id, a.category) AS q4i "
    "GROUP BY category")

NEXMARK_Q7 = (
    "CREATE MATERIALIZED VIEW q AS "
    "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
    "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    "GROUP BY window_start")

NEXMARK_Q8 = (
    "CREATE MATERIALIZED VIEW q AS "
    "SELECT p.id, p.name, p.window_start "
    "FROM TUMBLE(person, date_time, INTERVAL '10' SECOND) AS p "
    "JOIN TUMBLE(auction, date_time, INTERVAL '10' SECOND) AS a "
    "ON p.id = a.seller AND p.window_start = a.window_start")


def _oracle_rows(sources, mv_sql, rules, steps=16):
    async def main():
        fe = Frontend(rate_limit=16, min_chunks=16)
        await fe.execute(f"SET stream_rewrite_rules = '{rules}'")
        for s in sources:
            await fe.execute(s)
        await fe.execute(mv_sql)
        await fe.step(steps)
        rows = await fe.execute("SELECT * FROM q")
        await fe.close()
        return sorted(tuple(r) for r in rows)
    return run(main())


@pytest.mark.parametrize("name,sources,mv", [
    ("nexmark_q1", NEXMARK_SOURCES, NEXMARK_Q1),
    ("nexmark_q4", NEXMARK_SOURCES, NEXMARK_Q4),
    ("nexmark_q7", NEXMARK_SOURCES, NEXMARK_Q7),
    ("nexmark_q8", NEXMARK_SOURCES, NEXMARK_Q8),
    ("tpch_q3", TPCH_SOURCES, TPCH_Q3),
    ("tpch_q5", TPCH_SOURCES, TPCH_Q5),
])
def test_oracle_equivalence_rewrites_on_vs_off(name, sources, mv):
    """The whole contract: rewrites may change HOW rows are computed,
    never WHICH rows the MV holds."""
    rows_off = _oracle_rows(sources, mv, "none")
    rows_on = _oracle_rows(sources, mv, "all")
    assert rows_on == rows_off, name
    assert rows_on, f"{name} produced no output at this scale"


def _planned_lane_stats(sources, mv_sql, rules):
    """Lane stats of the (rewritten) plan without deploying it."""
    from risingwave_tpu.frontend import ast as _ast
    from risingwave_tpu.frontend.parser import parse_many

    async def main():
        fe = Frontend(rate_limit=16, min_chunks=16)
        for s in sources:
            await fe.execute(s)
        from risingwave_tpu.frontend.planner import StreamPlanner
        from risingwave_tpu.stream.actor import LocalBarrierManager
        [(_text, stmt)] = parse_many(mv_sql)
        assert isinstance(stmt, _ast.CreateMaterializedView)
        planner = StreamPlanner(
            fe.catalog, fe.store, LocalBarrierManager(),
            definition="", actors={},
            chunk_target_rows=fe.chunk_target_rows)
        plan = planner.plan("__stats__", stmt.select, actor_id=0)
        consumer, _rep = rewrite_stream_plan(plan.consumer, rules,
                                             record=False)
        await fe.close()
        return plan_lane_stats(consumer)
    return run(main())


@pytest.mark.parametrize("sources,mv", [
    (TPCH_SOURCES, TPCH_Q5), (NEXMARK_SOURCES, NEXMARK_Q7),
])
def test_q5_q7_carry_strictly_fewer_lanes(sources, mv):
    """Acceptance: on q5 and q7 the rewritten plan carries strictly
    fewer column lanes than the planner's tree."""
    off = _planned_lane_stats(sources, mv, "none")
    on = _planned_lane_stats(sources, mv, "all")
    assert on["total_lanes"] < off["total_lanes"], (on, off)
    assert on["max_lane_width"] <= off["max_lane_width"]


def test_filter_pushdown_gated_by_join_kind():
    """INNER-side filters sink below the join; a filter on the
    null-padded side of a LEFT join must stay above it."""
    async def main():
        fe = Frontend()
        # the assertions read executor POSITIONS in the rewritten
        # tree; join-input fusion would absorb the pushed filter into
        # the join's identity instead (covered by test_fusion.py)
        await fe.execute("SET stream_fusion = 'off'")
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        inner = await fe.execute(
            "EXPLAIN SELECT p.id, a.seller FROM person AS p "
            "JOIN auction AS a ON p.id = a.seller "
            "WHERE a.seller > 0")
        left = await fe.execute(
            "EXPLAIN SELECT p.id, a.seller FROM person AS p "
            "LEFT OUTER JOIN auction AS a ON p.id = a.seller "
            "WHERE a.seller > 0")
        await fe.close()
        return ("\n".join(r[0] for r in inner),
                "\n".join(r[0] for r in left))

    inner, left = run(main())
    inner_post = inner.split("-- rewritten plan", 1)[1]
    left_post = left.split("-- rewritten plan", 1)[1]
    assert inner_post.index("FilterExecutor") > \
        inner_post.index("HashJoinExecutor")
    assert left_post.index("FilterExecutor") < \
        left_post.index("HashJoinExecutor")


def test_explain_shows_both_trees_and_annotations():
    async def main():
        fe = Frontend()
        for s in TPCH_SOURCES:
            await fe.execute(s)
        plan = await fe.execute(
            "EXPLAIN " + TPCH_Q5.split(" AS ", 1)[1])
        await fe.execute("SET stream_rewrite_rules = 'none'")
        # fusion has its own knob and fires on q5's join inputs even
        # with the rules csv empty — the 'no rewrites fired' arm must
        # silence it too
        await fe.execute("SET stream_fusion = 'off'")
        off = await fe.execute(
            "EXPLAIN " + TPCH_Q5.split(" AS ", 1)[1])
        await fe.close()
        return ([r[0] for r in plan], [r[0] for r in off])

    lines, off_lines = run(main())
    txt = "\n".join(lines)
    assert "-- streaming plan (pre-rewrite):" in txt
    assert "-- rewritten plan (" in txt
    assert "rule column_pruning" in txt
    assert "rule filter_pushdown" in txt
    # both trees render a full chain
    assert txt.count("MaterializeExecutor") == 2
    off_txt = "\n".join(off_lines)
    assert "no rewrites fired" in off_txt


def test_column_pruning_narrows_join_state_tables():
    """The lanes the rewrite removes are exactly the lanes join state
    would have carried: q5's lineitem side keeps keys + referenced
    columns instead of the full 9-column row."""
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor,
    )

    def join_state_widths(rules):
        async def main():
            fe = Frontend(rate_limit=16, min_chunks=16)
            await fe.execute(
                f"SET stream_rewrite_rules = '{rules}'")
            for s in TPCH_SOURCES:
                await fe.execute(s)
            await fe.execute(TPCH_Q5)
            actor = fe.actors[max(fe.actors)]
            widths = []

            def walk(ex):
                inner = getattr(ex, "inner", None) or ex  # monitored
                if isinstance(inner, HashJoinExecutor):
                    widths.append(len(inner.sides[0].table.schema)
                                  + len(inner.sides[1].table.schema))
                from risingwave_tpu.stream.executor import (
                    executor_children,
                )
                for _a, _i, c in executor_children(inner):
                    walk(c)

            walk(actor.consumer)
            await fe.close()
            return widths

        return run(main())

    on = join_state_widths("all")
    off = join_state_widths("none")
    assert len(on) == len(off) == 5          # 6-way q5 → 5 joins
    # every join's resident state is at most as wide, strictly
    # narrower in total
    assert all(a <= b for a, b in zip(sorted(on), sorted(off)))
    assert sum(on) < sum(off), (on, off)


def test_session_var_rides_ddl_log_through_recovery():
    """SET stream_rewrite_rules shapes state-table schemas, so it must
    replay with the DDL log: an MV created with rewrites off recovers
    with rewrites off (same table schemas), even though the session
    default is 'all'."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    async def main():
        obj = MemObjectStore()
        fe = Frontend(HummockLite(obj), rate_limit=16, min_chunks=16)
        await fe.execute("SET stream_rewrite_rules = 'none'")
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        await fe.execute(NEXMARK_Q4)
        await fe.step(10)
        before = sorted(await fe.execute("SELECT * FROM q"))
        await fe.close()

        fe2 = Frontend(HummockLite(obj), rate_limit=16, min_chunks=16)
        n = await fe2.recover()
        assert n >= 5                  # SET + 3 sources + MV
        assert fe2.session_vars.get("stream_rewrite_rules") == "none"
        await fe2.step(10)
        after = sorted(await fe2.execute("SELECT * FROM q"))
        await fe2.close()
        assert before and after[:len(before)] != []
        # recovered MV keeps serving consistent rows
        assert {r[0] for r in before} <= {r[0] for r in after}
    run(main())


def test_rw_plan_rewrites_and_metrics():
    from risingwave_tpu.utils.metrics import STREAMING

    async def main():
        fe = Frontend(rate_limit=16, min_chunks=16)
        for s in TPCH_SOURCES:
            await fe.execute(s)
        before = sum(v for _l, v in
                     STREAMING.rewrite_rule_fired.series())
        pruned0 = sum(v for _l, v in
                      STREAMING.plan_columns_pruned.series())
        await fe.execute(TPCH_Q5)
        after = sum(v for _l, v in
                    STREAMING.rewrite_rule_fired.series())
        pruned1 = sum(v for _l, v in
                      STREAMING.plan_columns_pruned.series())
        rows = await fe.execute(
            "SELECT job, rule, fired FROM rw_plan_rewrites")
        await fe.close()
        return after - before, pruned1 - pruned0, rows

    fired, pruned, rows = run(main())
    assert fired > 0 and pruned > 0
    assert any(r[0] == "q" and r[1] == "column_pruning" and r[2] > 0
               for r in rows), rows
    assert rewrite_history_rows()


# -- distributed: exchange elision ----------------------------------------


def _dist_plan_graph(mv_sql, parallelism=2):
    """Lower an MV through the DistFrontend planner + fragmenter
    WITHOUT starting workers (plan-only)."""
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.frontend.planner import StreamPlanner
    from risingwave_tpu.frontend import ast as _ast
    from risingwave_tpu.frontend.catalog import Catalog
    from risingwave_tpu.frontend.parser import parse_many
    from risingwave_tpu.frontend.planner import source_schema
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager

    catalog = Catalog()
    for s in NEXMARK_SOURCES:
        [(_t, stmt)] = parse_many(s)
        catalog.add_source(stmt.name,
                           source_schema(stmt.options, stmt.columns),
                           stmt.options)
    [(_t, stmt)] = parse_many(mv_sql)
    assert isinstance(stmt, _ast.CreateMaterializedView)
    planner = StreamPlanner(catalog, MemoryStateStore(),
                            LocalBarrierManager(), definition="",
                            dist_parallelism=parallelism)
    plan = planner.plan("q", stmt.select, actor_id=0)
    consumer, _rep = rewrite_stream_plan(plan.consumer, "all",
                                         record=False)
    return Fragmenter(parallelism).lower(consumer)


def test_exchange_elision_unit():
    """join → GROUP BY over a superset of the join key: the agg's
    exchange is provably satisfied by the join's distribution and the
    fragments fuse; the q7-ish two-phase split (parallelism 1 producer
    → parallelism 2 consumer) must NOT fuse."""
    from risingwave_tpu.frontend.opt import fragment_plan_stats

    g = _dist_plan_graph(
        "CREATE MATERIALIZED VIEW q AS SELECT p.id, count(*) AS cnt "
        "FROM person AS p JOIN auction AS a ON p.id = a.seller "
        "GROUP BY p.id")
    before = fragment_plan_stats(g)
    g2, elided = rewrite_fragment_graph(g, "all", record=False)
    after = fragment_plan_stats(g2)
    assert elided >= 1
    assert after["exchange_hops"] < before["exchange_hops"]
    assert after["fragments"] < before["fragments"]

    g3 = _dist_plan_graph(
        "CREATE MATERIALIZED VIEW q AS SELECT bidder, count(*) AS c "
        "FROM bid GROUP BY bidder")
    # two-phase agg: local phase (par 1) feeds global (par 2) — the
    # exchange is load-bearing and must survive
    _g4, elided2 = rewrite_fragment_graph(g3, "all", record=False)
    assert elided2 == 0
    # and an explicitly disabled rule never fires
    _g5, elided3 = rewrite_fragment_graph(g, "none", record=False)
    assert elided3 == 0


def test_exchange_elision_cluster_oracle(tmp_path):
    """2-worker cluster: elided plan serves bit-identical rows with
    one fewer exchange hop and far fewer exchanged lanes."""
    from risingwave_tpu.cluster.session import DistFrontend

    MV = ("CREATE MATERIALIZED VIEW q AS SELECT p.id, "
          "count(*) AS cnt FROM person AS p "
          "JOIN auction AS a ON p.id = a.seller GROUP BY p.id")

    def run_dist(rules, sub):
        async def main():
            fe = DistFrontend(str(tmp_path / sub), n_workers=2,
                              parallelism=2)
            await fe.start()
            try:
                await fe.execute(
                    f"SET stream_rewrite_rules = '{rules}'")
                for s in NEXMARK_SOURCES:
                    await fe.execute(s.replace("2000", "1200"))
                await fe.execute(MV)
                stats = fe.last_plan_stats
                await fe.step(20)
                rows = sorted(tuple(r) for r in
                              await fe.execute("SELECT * FROM q"))
                return rows, stats
            finally:
                await fe.close()
        return run(main())

    rows_off, st_off = run_dist("none", "off")
    rows_on, st_on = run_dist("all", "on")
    assert rows_on == rows_off and rows_on
    assert st_on["exchange_hops"] < st_off["exchange_hops"]
    assert st_on["exchanged_lanes"] < st_off["exchanged_lanes"]


def test_dist_frontend_accepts_rewrite_session_var(tmp_path):
    from risingwave_tpu.cluster.session import DistFrontend

    async def main():
        fe = DistFrontend(str(tmp_path))   # no cluster start needed
        assert await fe.execute(
            "SET stream_rewrite_rules = 'none'") == "SET"
        assert await fe.execute(
            "SHOW stream_rewrite_rules") == [("none",)]
        with pytest.raises(PlanError):
            await fe.execute("SET stream_rewrite_rules = 'bogus'")
        assert await fe.execute(
            "SET stream_rewrite_rules TO DEFAULT") == "SET"
        assert await fe.execute(
            "SHOW stream_rewrite_rules") == [("all",)]
    run(main())


def test_fragment_checker_rejects_broken_graph():
    from risingwave_tpu.frontend.fragmenter import (
        FragInput, Fragment, FragmentGraph,
    )
    from risingwave_tpu.frontend.opt.checker import (
        check_fragment_graph,
    )
    g = FragmentGraph(fragments=[
        Fragment(nodes=[{"op": "exchange_in", "port": 0}],
                 inputs=[FragInput(up_frag=0, keys=[0], schema=[],
                                   node_idx=0)]),
    ])
    with pytest.raises(CheckError):
        check_fragment_graph(g)     # self-referential upstream
