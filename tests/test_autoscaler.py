"""Elastic control loop (ISSUE 15): autoscaler policy units over a
fake cluster, and the distributed end-to-end — a real 2-worker session
where an injected sustained-bottleneck signal drives a guarded rescale
1→2 with zero human ALTERs, filelog splits rebalance on manual
scale-out/in with byte offsets handing off exactly, a mid-redeploy
fault rolls the topology back (visible in rw_recovery), and concurrent
topology changes serialize with a clear error.
"""

import asyncio
import json
import os
import types

import pytest

from risingwave_tpu.meta.autoscaler import (
    AUTOSCALE_LOG, Autoscaler, AutoscalerConfig, _AdmitGate,
    autoscaler_rows, clear_autoscale_log,
)
from risingwave_tpu.meta.supervisor import (
    RECOVERY_LOG, RecoverySupervisor, clear_recovery_log,
)
from risingwave_tpu.stream.bottleneck import BOTTLENECKS
from risingwave_tpu.stream.monitor import UTILIZATION


@pytest.fixture(autouse=True)
def _fresh_ledgers():
    clear_autoscale_log()
    clear_recovery_log()
    yield
    clear_autoscale_log()
    clear_recovery_log()


# -- fakes ---------------------------------------------------------------


class FakeCluster:
    """Mechanism stub: policy under test lives in the Autoscaler."""

    def __init__(self, n: int = 2):
        self.n = n
        self.supervisor = RecoverySupervisor()
        self.store = types.SimpleNamespace(committed_epoch=lambda: 7)
        frag = types.SimpleNamespace(parallelism=1, nodes=[],
                                     inputs=[{}])
        self.frag = frag
        job = types.SimpleNamespace(
            name="hot",
            graph=types.SimpleNamespace(fragments=[frag]),
            placements=[[(1001, 0)]],
            split_assignments={})
        self.jobs = {"hot": job}
        self.rescales = []          # (name, fi, to_slots)
        self.steps = 0
        self.fail_rescale = None    # exception to raise on rescale
        self.fail_step_at = None    # step index (1-based) to fail at

    def _rescalable(self, frag):
        return frag is self.frag

    def _source_rescalable(self, frag):
        return False

    def domain_of_job(self, name):
        return "dom"

    async def drain_signals(self, light=False):
        return 0

    async def drain_freshness(self):
        return 0

    async def step(self, n=1):
        self.steps += 1
        if self.fail_step_at is not None \
                and self.steps >= self.fail_step_at:
            raise ConnectionError("worker died during verify")

    async def rescale_fragment(self, name, fi, to_slots):
        if self.fail_rescale is not None:
            exc, self.fail_rescale = self.fail_rescale, None
            raise exc
        self.rescales.append((name, fi, list(to_slots)))
        # keep stable actor ids so injected signal rows stay resolvable
        self.jobs[name].placements[fi] = [
            (1001 + k, s) for k, s in enumerate(to_slots)]

    async def rescale_source_fragment(self, name, fi, to_slots):
        await self.rescale_fragment(name, fi, to_slots)


def _sustained_row(mv="hot", actor=1001, busy=0.9, streak=5,
                   sustained=1):
    return (("dom", "HashAggExecutor(...)", mv, actor, 2, busy, 0.0,
             streak, sustained, 99, "sustained diag"))


def _busy_util(mv="hot", actor=1001, busy=0.9):
    return [(actor, mv, 2, "HashAggExecutor(...)", 99, 1.0, busy,
             0.0, 0.05)]


def _mk(cluster, **cfg):
    # backoff_s=0: policy units tick back-to-back — the deferred
    # backoff window (its own tests below) would otherwise swallow
    # the tick after any failed action
    defaults = dict(cooldown_s=0.0, verify_barriers=2,
                    up_busy_mean=0.3, backoff_s=0.0)
    defaults.update(cfg)
    return Autoscaler(cluster, AutoscalerConfig(**defaults))


def _tick(a):
    return asyncio.run(a.tick())


# -- policy units --------------------------------------------------------


def test_non_sustained_rows_are_ignored():
    """One-barrier anecdotes (sustained=0) never trigger a decision."""
    c = FakeCluster()
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row(streak=1, sustained=0)], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a) is None
    assert c.rescales == []
    assert autoscaler_rows() == []


def test_sustained_bottleneck_scales_up_and_verifies():
    c = FakeCluster()
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    ev = _tick(a)
    assert ev is not None and ev.outcome == "applied"
    assert c.rescales == [("hot", 0, [0, 1])]
    assert c.steps == 2                 # post-rescale verify rounds
    (row,) = autoscaler_rows()
    assert row[1] == "hot" and row[4] == "up" \
        and row[5] == 1 and row[6] == 2 and row[7] == "applied"
    from risingwave_tpu.utils.metrics import CLUSTER
    assert CLUSTER.autoscaler_decision.get(mv="hot",
                                           direction="up") >= 1


def test_per_mv_cooldown_suppresses_refire():
    c = FakeCluster()
    a = _mk(c, cooldown_s=60.0)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a).outcome == "applied"
    # signal still sustained — the per-MV cooldown wins
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a) is None
    assert len(c.rescales) == 1


def test_tricolor_cross_check_blocks_idle_fragment():
    """A sustained row whose fragment's actors are NOT busy-dominated
    (stale walk, skew) does not scale."""
    c = FakeCluster()
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util(busy=0.1))
    assert _tick(a) is None
    assert c.rescales == []


def test_freshness_trend_cross_check():
    """A lag already clearly recovering vetoes the scale-up; a rising
    lag does not."""
    c = FakeCluster()
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    a._lag["hot"] = __import__("collections").deque(
        [10.0, 8.0, 6.0, 1.0], maxlen=32)       # recovering
    assert _tick(a) is None
    a._lag["hot"] = __import__("collections").deque(
        [1.0, 2.0, 4.0, 8.0], maxlen=32)        # rising
    assert _tick(a).outcome == "applied"


def test_load_step_jumps_two_rungs():
    """A ≥4x load step — saturated busy mean AND a steeply rising
    wall-lag trend — jumps parallelism +2 in ONE guarded rescale."""
    import collections
    c = FakeCluster(n=4)
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row(busy=0.95)], "sig")
    UTILIZATION.ingest_rows(_busy_util(busy=0.95))
    # last sample ≥ jump_lag_slope (2.0) × window median
    a._lag["hot"] = collections.deque([1.0, 1.0, 2.0, 8.0], maxlen=32)
    ev = _tick(a)
    assert ev is not None and ev.outcome == "applied"
    assert ev.from_parallelism == 1 and ev.to_parallelism == 3
    assert len(c.rescales) == 1          # one rescale, not two
    assert "jump +2" in ev.reason


def test_gentle_load_keeps_single_step():
    """Busy-but-not-saturated, or a flat lag trend, walks +1."""
    import collections
    c = FakeCluster(n=4)
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util(busy=0.5))    # < jump_busy_mean
    a._lag["hot"] = collections.deque([1.0, 1.0, 2.0, 8.0], maxlen=32)
    ev = _tick(a)
    assert ev is not None and ev.to_parallelism == 2
    # saturated but the lag trend is flat (rising enough to pass the
    # veto, nowhere near the jump slope) → still +1
    c2 = FakeCluster(n=4)
    a2 = _mk(c2)
    BOTTLENECKS.ingest([_sustained_row(busy=0.95)], "sig")
    UTILIZATION.ingest_rows(_busy_util(busy=0.95))
    a2._lag["hot"] = collections.deque([4.0, 4.1, 4.0, 4.2],
                                       maxlen=32)
    ev2 = _tick(a2)
    assert ev2 is not None and ev2.to_parallelism == 2


def test_jump_clamps_to_max_parallelism():
    """The jump is bounded: +2 from cur=1 on a 2-slot cluster lands
    on 2, never past the cap."""
    import collections
    c = FakeCluster(n=2)
    a = _mk(c)
    BOTTLENECKS.ingest([_sustained_row(busy=0.95)], "sig")
    UTILIZATION.ingest_rows(_busy_util(busy=0.95))
    a._lag["hot"] = collections.deque([1.0, 1.0, 2.0, 8.0], maxlen=32)
    ev = _tick(a)
    assert ev is not None and ev.to_parallelism == 2


def test_failed_rescale_rolls_back_and_records_both_ledgers():
    c = FakeCluster()
    a = _mk(c)
    c.fail_rescale = RuntimeError("deploy exploded")
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    ev = _tick(a)
    assert ev.outcome == "rolled_back"
    # the compensating rescale went back to the prior single slot
    assert c.rescales == [("hot", 0, [0])]
    # rw_recovery carries the rollback; the recovery STORM budget is
    # untouched (satellite: no double-count against the supervisor)
    assert [(e.cause, e.action) for e in RECOVERY_LOG] == \
        [("rescale_failed", "rollback")]
    assert c.supervisor.attempts == 0
    from risingwave_tpu.utils.metrics import CLUSTER
    assert CLUSTER.autoscaler_rollback.get(mv="hot") >= 1


def test_verify_failure_rolls_back_and_surfaces_fault():
    """A recovery-worthy fault during the verify window rolls the
    parallelism back; if even the rollback cannot complete, the error
    surfaces to the serving loop's supervised ladder."""
    c = FakeCluster()
    a = _mk(c)
    c.fail_step_at = 1                  # first verify barrier dies
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    ev = _tick(a)
    assert ev.outcome == "rolled_back"
    assert [r[2] for r in c.rescales] == [[0, 1], [0]]
    assert RECOVERY_LOG[-1].cause == "rescale_failed"


def test_note_healthy_closes_window_only_after_success():
    c = FakeCluster()
    a = _mk(c)
    c.fail_rescale = RuntimeError("boom")
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    _tick(a)
    assert a.gate.attempts == 1
    a.note_healthy()                    # clean round after a ROLLBACK
    assert a.gate.attempts == 1         # backoff stays armed
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a).outcome == "applied"
    a.note_healthy()                    # clean round after a SUCCESS
    assert a.gate.attempts == 0


def test_storm_gate_disables_loop_loudly():
    async def run():
        c = FakeCluster()
        a = _mk(c, max_attempts=2)
        a.gate.sleep = lambda _d: asyncio.sleep(0)
        for _ in range(2):
            c.fail_rescale = RuntimeError("persistent")
            BOTTLENECKS.ingest([_sustained_row()], "sig")
            UTILIZATION.ingest_rows(_busy_util())
            await a.tick()
        c.fail_rescale = RuntimeError("persistent")
        BOTTLENECKS.ingest([_sustained_row()], "sig")
        UTILIZATION.ingest_rows(_busy_util())
        ev = await a.tick()
        return a, ev

    a, ev = asyncio.run(run())
    assert ev.outcome == "storm_disabled"
    assert a.enabled is False
    assert asyncio.run(a.tick()) is None     # stays off until SET


def test_metric_families_have_help_lines():
    """The autoscaler counter families render HELP lines in the
    Prometheus exposition (`ctl metrics` dumps the same registry)."""
    from risingwave_tpu.utils.metrics import GLOBAL
    text = GLOBAL.render()
    assert "# HELP autoscaler_decision_total" in text
    assert "# HELP autoscaler_rollback_total" in text


def test_admit_gate_jitter_is_seeded():
    async def delays(seed):
        out = []

        async def sleep(d):
            out.append(d)

        g = _AdmitGate(8, 0.5, 16.0, seed, sleep=sleep)
        for _ in range(5):
            await g.admit()
        return out

    a = asyncio.run(delays(5))
    b = asyncio.run(delays(5))
    assert a == b and len(a) == 4          # attempt 1 is immediate
    assert a != asyncio.run(delays(6))


def test_failed_action_defers_backoff_between_ticks():
    """The storm-gate backoff never sleeps under the barrier lock:
    a failed action arms a not-before deadline and tick() no-ops
    until it passes — the delay runs between heartbeats."""
    clock = [100.0]
    c = FakeCluster()
    a = Autoscaler(c, AutoscalerConfig(cooldown_s=0.0,
                                       verify_barriers=1,
                                       up_busy_mean=0.3,
                                       backoff_s=0.5),
                   monotonic=lambda: clock[0])
    c.fail_rescale = RuntimeError("boom")
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a).outcome == "rolled_back"
    assert a._not_before > clock[0]        # window armed
    deadline = a._not_before
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a) is None                # inside the window: no-op
    assert not c.rescales[1:]              # ...and no rescale driven
    clock[0] = deadline + 0.01
    assert _tick(a).outcome == "applied"   # window passed: acts again


def test_reset_storm_reopens_the_gate():
    """SET stream_autoscale=on after a storm must clear the exhausted
    budget too — a still-maxed gate would re-raise the storm on the
    next decision without attempting a single rescale."""
    async def run():
        c = FakeCluster()
        a = _mk(c, max_attempts=1)
        c.fail_rescale = RuntimeError("persistent")
        BOTTLENECKS.ingest([_sustained_row()], "sig")
        UTILIZATION.ingest_rows(_busy_util())
        await a.tick()                         # burns the one attempt
        BOTTLENECKS.ingest([_sustained_row()], "sig")
        UTILIZATION.ingest_rows(_busy_util())
        ev = await a.tick()
        assert ev.outcome == "storm_disabled" and a.enabled is False
        a.reset_storm()                        # the SET handler's path
        assert a.enabled and a.gate.attempts == 0
        BOTTLENECKS.ingest([_sustained_row()], "sig")
        UTILIZATION.ingest_rows(_busy_util())
        return await a.tick()

    assert asyncio.run(run()).outcome == "applied"


def test_target_slots_derive_from_current_placement():
    """Scale-out extends the fragment's CURRENT placement (surviving
    actors stay put — the handoff moves only the rebalanced share);
    scale-in drops the tail. A formula-derived set would relocate the
    whole fragment when its placement doesn't match the formula."""
    c = FakeCluster(n=3)
    a = _mk(c)
    job = c.jobs["hot"]
    job.placements[0] = [(1001, 2)]        # round-robin put it on 2
    assert a._target_slots(job, 0, 2) == [2, 0]
    job.placements[0] = [(1001, 2), (1002, 0)]
    assert a._target_slots(job, 0, 3) == [2, 0, 1]
    assert a._target_slots(job, 0, 1) == [2]   # shrink drops the tail
    # parallelism past the worker count: slots repeat rather than wedge
    assert len(a._target_slots(job, 0, 5)) == 5


def test_cancelled_mid_action_reraises():
    """A heartbeat cancellation landing inside a guarded action must
    escape _act after the unwind — swallowing it would leave the
    serving task uncancellable (and hang anyone awaiting it)."""
    async def run():
        c = FakeCluster()
        a = _mk(c)

        async def cancelled_step(n=1):
            raise asyncio.CancelledError()

        c.step = cancelled_step                # cancel lands in verify
        BOTTLENECKS.ingest([_sustained_row()], "sig")
        UTILIZATION.ingest_rows(_busy_util())
        with pytest.raises(asyncio.CancelledError):
            await a.tick()
        return autoscaler_rows()

    rows = asyncio.run(run())
    # the unwind completed and was recorded before the re-raise
    assert [r[7] for r in rows] == ["rolled_back"]


def test_scale_down_after_quiet_window():
    c = FakeCluster()
    a = _mk(c, down_quiet_rounds=3, down_busy_max=0.2)
    BOTTLENECKS.ingest([_sustained_row()], "sig")
    UTILIZATION.ingest_rows(_busy_util())
    assert _tick(a).outcome == "applied"       # 1 -> 2, baseline 1
    # demand evaporates: no sustained row, actors idle
    BOTTLENECKS.ingest([("dom", None, "", 0, 0, 0.0, 0.0, 0, 0, 99,
                         "no sustained bottleneck")], "sig")
    UTILIZATION.ingest_rows([(1001, "hot", 2, "Hash", 99, 1.0, 0.01,
                              0.0, 0.9),
                             (1002, "hot", 2, "Hash", 99, 1.0, 0.01,
                              0.0, 0.9)])
    for _ in range(2):
        assert _tick(a) is None                # quiet rounds accrue
    ev = _tick(a)
    assert ev is not None and ev.direction == "down" \
        and ev.to_parallelism == 1
    assert c.rescales[-1] == ("hot", 0, [0])
    # never below the recorded baseline
    BOTTLENECKS.ingest([("dom", None, "", 0, 0, 0.0, 0.0, 0, 0, 99,
                         "")], "sig")
    for _ in range(5):
        assert _tick(a) is None


# -- distributed end-to-end ---------------------------------------------


def _produce(path, parts, start, n_per_part, keys=40):
    os.makedirs(path, exist_ok=True)
    for p in range(parts):
        with open(os.path.join(path, f"imps-{p}.log"), "ab") as f:
            for i in range(n_per_part):
                j = start + p * n_per_part + i
                f.write(json.dumps(
                    {"k": j % keys, "v": j}).encode() + b"\n")


def _topic_bytes(path, parts):
    return sum(os.path.getsize(os.path.join(path, f"imps-{p}.log"))
               for p in range(parts))


def _oracle(path, total_hint):
    """In-process single-reader oracle over ALL partitions."""
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            f"CREATE SOURCE imp (k BIGINT, v BIGINT) WITH "
            f"(connector='filelog', path='{path}', topic='imps', "
            f"partitions='0,1,2', max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW hot AS SELECT k, "
            "count(*) AS c, sum(v) AS s, approx_count_distinct(v) "
            "AS d FROM imp GROUP BY k")
        for _ in range(60):
            await fe.step(1)
            rows = await fe.execute("SELECT * FROM hot")
            if sum(r[1] for r in rows) >= total_hint:
                break
        rows = await fe.execute("SELECT * FROM hot")
        await fe.close()
        return sorted(tuple(r) for r in rows)

    return asyncio.run(run())


async def _drain_until(fe, total):
    for _ in range(80):
        await fe.step(1)
        rows = await fe.execute("SELECT * FROM hot")
        if sum(r[1] for r in rows) >= total:
            break
    return sorted(tuple(r) for r in await fe.execute(
        "SELECT * FROM hot"))


def test_autoscaler_and_split_rebalance_e2e(tmp_path):
    """The acceptance path on a real 2-worker cluster: an injected
    sustained signal makes the loop rescale the hot fragment 1→2
    (guarded, verified, ledgered; the healthy neighbor records zero
    decisions), filelog splits rebalance across actors on manual
    scale-out and back in with per-split byte offsets handing off
    exactly, a mid-redeploy fault rolls back to the prior topology
    with the cause in rw_recovery, and a concurrent topology change
    gets the clear serialization error — MV bit-identical to the
    single-reader oracle throughout."""
    from risingwave_tpu.cluster.scheduler import (
        RescaleError, RescaleInProgressError,
    )
    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.utils.failpoint import arm_specs

    data = str(tmp_path / "logs")
    _produce(data, 3, 0, 500)

    async def run():
        # parallelism 2 so the fragmenter cuts at the hash exchange
        # (the rescalable topology); 3 workers give the loop headroom
        # to scale 2 -> 3. approx_count_distinct keeps the agg
        # single-phase — a two-phase LOCAL agg rides the source
        # fragment, whose durable partials make it deliberately NOT
        # split-rescalable (the split handoff moves offset rows only).
        fe = DistFrontend(str(tmp_path / "root"), n_workers=3,
                          parallelism=2, barrier_timeout_s=60.0)
        await fe.start()
        out = {}
        try:
            await fe.execute("SET stream_autoscale = 'on'")
            fe.autoscaler.cfg.cooldown_s = 0.0
            fe.autoscaler.cfg.verify_barriers = 1
            fe.autoscaler.cfg.up_busy_mean = 0.0   # signal-injected
            await fe.execute(
                f"CREATE SOURCE imp (k BIGINT, v BIGINT) WITH "
                f"(connector='filelog', path='{data}', topic='imps', "
                f"max.chunk.size=256)")
            await fe.execute(
                "CREATE MATERIALIZED VIEW hot AS SELECT k, "
                "count(*) AS c, sum(v) AS s, "
                "approx_count_distinct(v) AS d "
                "FROM imp GROUP BY k")
            await fe.execute(
                f"CREATE SOURCE bid WITH (connector='nexmark', "
                f"nexmark.table.type='bid', nexmark.event.num=2000, "
                f"nexmark.max.chunk.size=512)")
            await fe.execute(
                "CREATE MATERIALIZED VIEW q7n AS SELECT auction, "
                "count(*) AS c FROM bid GROUP BY auction")
            out["phase1"] = await _drain_until(fe, 1500)

            job = fe.cluster.jobs["hot"]
            agg_fi = next(
                fi for fi, f in enumerate(job.graph.fragments)
                if fe.cluster._rescalable(f))
            src_fi = next(
                fi for fi, f in enumerate(job.graph.fragments)
                if fe.cluster._source_rescalable(f))
            out["src_assign0"] = list(
                job.split_assignments[src_fi])
            aid = job.placements[agg_fi][0][0]
            # inject the sustained signal under a synthetic worker
            # tag (real worker drains replace only their own rows)
            BOTTLENECKS.ingest(
                [("hot", "HashAggExecutor(...)", "hot", aid, 2,
                  0.9, 0.0, 5, 1, 99, "injected sustained")], "sig")
            ev = await fe.autoscaler.tick()
            out["tick"] = (ev.outcome, ev.mv, ev.from_parallelism,
                           ev.to_parallelism)
            out["agg_par"] = len(job.placements[agg_fi])
            out["ledger_sql"] = await fe.execute(
                "SELECT mv, direction, outcome FROM rw_autoscaler")
            # hot MV still exact after the autoscaler's rescale
            _produce(data, 3, 1500, 300)
            out["phase2"] = await _drain_until(fe, 2400)

            # manual scale-out rebalances the SOURCE splits too
            await fe.execute(
                "ALTER MATERIALIZED VIEW hot SET PARALLELISM = 2")
            assert len(job.placements[src_fi]) == 2
            out["src_assign2"] = list(job.split_assignments[src_fi])
            _produce(data, 3, 2400, 300)
            out["phase3"] = await _drain_until(fe, 3300)
            # ...and scale-in hands every split back to one actor
            await fe.execute(
                "ALTER MATERIALIZED VIEW hot SET PARALLELISM = 1")
            assert len(job.placements[src_fi]) == 1
            _produce(data, 3, 3300, 200)
            out["phase4"] = await _drain_until(fe, 3900)
            await fe.execute("FLUSH")

            # per-split byte offsets hand off exactly: 3 rows, and
            # their sum equals the topic's total byte size (every
            # record consumed once, none re-read)
            src_node = next(
                n for n in job.graph.fragments[src_fi].nodes
                if n["op"] == "source")
            srows = await fe.cluster.scan_table(
                int(src_node["split_table_id"]))
            offs = {v[0]: v[1] for _k, v in srows}
            out["split_offsets"] = offs
            out["topic_bytes"] = _topic_bytes(data, 3)

            # forced-failure rescale: the cohort redeploy explodes;
            # the guarded protocol must land back on parallelism 1
            arm_specs({"rescale.redeploy": {
                "raise": "RuntimeError", "msg": "chaos redeploy",
                "times": 1}})
            try:
                with pytest.raises(RescaleError) as ei:
                    await fe.execute(
                        "ALTER MATERIALIZED VIEW hot "
                        "SET PARALLELISM = 2")
            finally:
                arm_specs({"rescale.redeploy": None})
            out["rolled_back"] = ei.value.rolled_back
            out["post_rollback_par"] = (
                len(job.placements[src_fi]),
                len(job.placements[agg_fi]))
            out["recovery_sql"] = await fe.execute(
                "SELECT cause, action, ok FROM rw_recovery")

            # the AUTOSCALER-driven forced failure: its verify window
            # dies, the compensating rescale restores the prior
            # parallelism, and the rollback is queryable over SQL
            BOTTLENECKS.ingest(
                [("hot", "HashAggExecutor(...)", "hot",
                  job.placements[agg_fi][0][0], 2, 0.9, 0.0, 5, 1,
                  99, "injected again")], "sig")
            arm_specs({"rescale.redeploy": {
                "raise": "RuntimeError", "msg": "chaos redeploy 2",
                "times": 1}})
            try:
                ev2 = await fe.autoscaler.tick()
            finally:
                arm_specs({"rescale.redeploy": None})
            out["tick2"] = (ev2.outcome, ev2.from_parallelism,
                            ev2.to_parallelism)
            out["tick2_par"] = len(job.placements[agg_fi])
            out["rollback_sql"] = await fe.execute(
                "SELECT mv, outcome FROM rw_autoscaler")
            _produce(data, 3, 3900, 100)
            out["phase5"] = await _drain_until(fe, 4200)

            # concurrent topology changes serialize with a clear error
            fe.cluster._topology_busy = "test-held"
            with pytest.raises(RescaleInProgressError):
                await fe.execute(
                    "ALTER MATERIALIZED VIEW hot "
                    "SET PARALLELISM = 2")
            fe.cluster._topology_busy = None
            return out
        finally:
            await fe.close()

    out = asyncio.run(run())
    assert out["tick"] == ("applied", "hot", 2, 3)
    assert out["agg_par"] == 3
    assert ("hot", "up", "applied") in [tuple(r) for r
                                        in out["ledger_sql"]]
    # the healthy neighbor saw ZERO decisions
    assert not [r for r in autoscaler_rows() if r[1] == "q7n"]
    # split assignment: all 3 partitions on one actor, then split 2/1,
    # then back to one
    assert sorted(p for ps in out["src_assign0"] for p in ps) \
        == [0, 1, 2]
    assert sorted(len(ps) for ps in out["src_assign2"]) == [1, 2]
    # offsets: one row per split, summing to the topic's exact bytes
    # at snapshot time (every record consumed once, none re-read)
    data_dir = str(tmp_path / "logs")
    assert len(out["split_offsets"]) == 3
    assert sum(out["split_offsets"].values()) == out["topic_bytes"]
    assert out["rolled_back"] is True
    assert out["post_rollback_par"] == (1, 1)
    assert ("rescale_failed", "rollback", 1) in [
        tuple(r) for r in out["recovery_sql"]]
    # the autoscaler's own forced failure rolled back to the prior
    # parallelism and the event is visible in rw_autoscaler
    assert out["tick2"] == ("rolled_back", 1, 2)
    assert out["tick2_par"] == 1
    assert ("hot", "rolled_back") in [tuple(r)
                                      for r in out["rollback_sql"]]
    # bit-identity vs the single-reader oracle over the full topic
    # (the final state subsumes every phase: counts/sums per key)
    assert out["phase5"] == _oracle(data_dir, 4200)
    # and each phase's snapshot saw exactly the records produced so
    # far — no loss, no duplication across any rescale boundary
    for phase, hint in (("phase1", 1500), ("phase2", 2400),
                        ("phase3", 3300), ("phase4", 3900),
                        ("phase5", 4200)):
        assert sum(r[1] for r in out[phase]) == hint, phase


def test_mid_rescale_chaos_converges(tmp_path):
    """ISSUE 15 acceptance (the bench --with-chaos round also runs
    this continuously): a seeded schedule injecting faults
    MID-RESCALE — SIGKILL during cohort redeploy, storage fault during
    the state handoff, straggler across the rescale's stop barrier —
    with the autoscaler enabled converges oracle-bit-identical, and
    the rollbacks/recoveries land in rw_recovery."""
    from risingwave_tpu.cluster.chaos import run_chaos
    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.frontend.session import Frontend

    events = 3000
    src = ("CREATE SOURCE bid WITH (connector='nexmark', "
           f"nexmark.table.type='bid', nexmark.event.num={events}, "
           "nexmark.max.chunk.size=256, "
           "nexmark.min.event.gap.in.ns=50000000)")
    mv = ("CREATE MATERIALIZED VIEW q7 AS SELECT window_start, "
          "MAX(price) AS max_price, COUNT(*) AS cnt "
          "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
          "GROUP BY window_start")

    async def oracle():
        fe = Frontend(min_chunks=8)
        await fe.execute(src)
        await fe.execute(mv)
        await fe.step(30)
        rows = {tuple(r) for r in await fe.execute(
            "SELECT * FROM q7")}
        await fe.close()
        return rows

    async def chaos():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2,
                          barrier_timeout_s=8.0)
        await fe.start()
        try:
            await fe.execute("SET stream_autoscale = 'on'")
            await fe.execute(src)
            await fe.execute(mv)
            report = await run_chaos(
                fe, seed=11, settle_steps=50,
                kinds=["kill_mid_rescale", "fault_mid_handoff",
                       "straggler_mid_rescale"],
                rescale_mv="q7")
            rows = {tuple(r) for r in await fe.execute(
                "SELECT * FROM q7")}
            rec = await fe.execute(
                "SELECT cause, action FROM rw_recovery")
            return report, rows, rec
        finally:
            await fe.close()

    expect = asyncio.run(oracle())
    report, rows, rec = asyncio.run(chaos())
    assert rows == expect
    assert report.rescale_rollbacks        # at least one unwound
    causes = {c for c, _a in rec}
    assert "rescale_failed" in causes
