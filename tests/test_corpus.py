"""Query corpus: Nexmark + TPC-H streaming MVs, oracle-checked.

Reference parity: e2e_test/streaming/nexmark/*.slt.part and
e2e_test/streaming/tpch/ — each entry runs CREATE SOURCE + CREATE
MATERIALIZED VIEW + SELECT on the in-process session and compares
against a numpy oracle computed from the deterministic generators
(the .slt expected-rows discipline with computed snapshots).

Queries whose reference form needs surface we lack are listed at the
bottom with the blocking feature, so the corpus table stays honest.
Other corpus entries live in their own files: q1 (test_e2e_q1), q4
(test_subquery_having), q5-lite (test_e2e_q5), q7-core (test_e2e_q7),
q8 (test_e2e_q8, test_cluster_sql), TPC-H q3 (test_tpch).
"""

import asyncio
import collections

import numpy as np
import pytest

from risingwave_tpu.connectors.nexmark import (
    NexmarkConfig, gen_auctions, gen_bids, gen_persons,
)
from risingwave_tpu.frontend.session import Frontend

N_EVENTS = 4000
GAP_NS = 100_000_000
WINDOW_US = 10_000_000

NEXMARK_SOURCES = [
    "CREATE SOURCE {t} WITH (connector='nexmark', "
    "nexmark.table.type='{t}', nexmark.event.num={n}, "
    "nexmark.min.event.gap.in.ns={gap})".format(t=t, n=N_EVENTS,
                                                gap=GAP_NS)
    for t in ("bid", "auction", "person")
]


def _gen(n=N_EVENTS):
    cfg = NexmarkConfig(event_num=n, min_event_gap_in_ns=GAP_NS)
    bids = gen_bids(np.arange(n * 46 // 50, dtype=np.int64), cfg)
    aucs = gen_auctions(np.arange(n * 3 // 50, dtype=np.int64), cfg)
    pers = gen_persons(np.arange(n // 50, dtype=np.int64), cfg)
    return bids, aucs, pers


def _run(mv_sql, select_sql, sources=NEXMARK_SOURCES, steps=12):
    async def run():
        fe = Frontend(min_chunks=8)
        for s in sources:
            await fe.execute(s)
        await fe.execute(mv_sql)
        await fe.step(steps)
        rows = await fe.execute(select_sql)
        await fe.close()
        return rows

    return asyncio.run(run())


# -- Nexmark ---------------------------------------------------------------


def test_nexmark_q0_passthrough():
    rows = _run(
        "CREATE MATERIALIZED VIEW q0 AS SELECT auction, bidder, "
        "price, date_time FROM bid",
        "SELECT * FROM q0")
    bids, _a, _p = _gen()
    expect = collections.Counter(zip(
        bids["auction"].tolist(), bids["bidder"].tolist(),
        bids["price"].tolist(), bids["date_time"].tolist()))
    assert collections.Counter(map(tuple, rows)) == expect


def test_nexmark_q2_filtered_auctions():
    rows = _run(
        "CREATE MATERIALIZED VIEW q2 AS SELECT auction, price FROM bid "
        "WHERE auction = 1007 OR auction = 1020 OR auction = 1040 "
        "OR auction = 1087",
        "SELECT * FROM q2")
    bids, _a, _p = _gen()
    keep = {1007, 1020, 1040, 1087}
    expect = collections.Counter(
        (a, p) for a, p in zip(bids["auction"].tolist(),
                               bids["price"].tolist()) if a in keep)
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q3_local_item_suggestion():
    rows = _run(
        "CREATE MATERIALIZED VIEW q3 AS SELECT p.name, p.city, "
        "p.state, a.id FROM auction AS a JOIN person AS p "
        "ON a.seller = p.id WHERE a.category = 10 AND "
        "(p.state = 'OR' OR p.state = 'ID' OR p.state = 'CA')",
        "SELECT * FROM q3")
    _b, aucs, pers = _gen()
    pmap = {int(i): (nm, c, s) for i, nm, c, s in zip(
        pers["id"], pers["name"], pers["city"], pers["state"])}
    expect = collections.Counter(
        (pmap[int(s)][0], pmap[int(s)][1], pmap[int(s)][2], int(i))
        for i, s, cat in zip(aucs["id"], aucs["seller"],
                             aucs["category"])
        if cat == 10 and int(s) in pmap
        and pmap[int(s)][2] in ("OR", "ID", "CA"))
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q7_highest_bid_per_window():
    """Full q7 (not just the MAX core): bids matching their window's
    max price, via an equi-join against the windowed-max derived table
    — a join over a RETRACTING aggregate (the arrangement-keyed join
    the planner previously refused)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q7 AS "
        "SELECT b.auction, b.price, b.bidder, b.date_time "
        "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) AS b "
        "JOIN (SELECT MAX(price) AS maxprice, window_start AS ws "
        "      FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
        "      GROUP BY window_start) AS m "
        "ON b.window_start = m.ws AND b.price = m.maxprice",
        "SELECT * FROM q7")
    bids, _a, _p = _gen()
    win = (bids["date_time"] // WINDOW_US) * WINDOW_US
    wmax = collections.defaultdict(int)
    for w, p in zip(win.tolist(), bids["price"].tolist()):
        wmax[w] = max(wmax[w], p)
    expect = collections.Counter(
        (a, p, bd, t) for a, bd, p, t, w in zip(
            bids["auction"].tolist(), bids["bidder"].tolist(),
            bids["price"].tolist(), bids["date_time"].tolist(),
            win.tolist())
        if p == wmax[w])
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q9_auction_top_bid_row_number():
    """q9 shape: ROW_NUMBER() OVER (PARTITION BY auction ORDER BY
    price DESC, date_time ASC), filtered to rn = 1 in an outer query
    over the derived table."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q9 AS SELECT auction, price, "
        "date_time FROM ("
        "  SELECT auction, price, date_time, row_number() OVER ("
        "    PARTITION BY auction ORDER BY price DESC, date_time ASC"
        "  ) AS rn FROM bid) AS t WHERE rn = 1",
        "SELECT * FROM q9")
    bids, _a, _p = _gen()
    best = {}
    for a, p, t in zip(bids["auction"].tolist(), bids["price"].tolist(),
                       bids["date_time"].tolist()):
        cur = best.get(a)
        if cur is None or (-p, t) < cur:
            best[a] = (-p, t)
    assert len(rows) == len(best)
    for a, p, t in rows:
        assert best[a] == (-p, t), (a, p, t, best[a])


def test_nexmark_q20_bid_with_auction_details():
    rows = _run(
        "CREATE MATERIALIZED VIEW q20 AS SELECT b.auction, b.bidder, "
        "b.price, a.item_name, a.category FROM bid AS b "
        "JOIN auction AS a ON b.auction = a.id WHERE a.category = 12",
        "SELECT * FROM q20")
    bids, aucs, _p = _gen()
    amap = {int(i): (nm, int(c)) for i, nm, c in zip(
        aucs["id"], aucs["item_name"], aucs["category"])}
    expect = collections.Counter(
        (a, bd, p, amap[a][0], amap[a][1])
        for a, bd, p in zip(bids["auction"].tolist(),
                            bids["bidder"].tolist(),
                            bids["price"].tolist())
        if a in amap and amap[a][1] == 12)
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def _bid_counts(bids):
    return collections.Counter(bids["auction"].tolist())


def test_nexmark_q101_auction_max_bid():
    rows = _run(
        "CREATE MATERIALIZED VIEW q101 AS SELECT a.id, a.item_name, "
        "b.max_price FROM auction AS a JOIN ("
        "  SELECT auction, MAX(price) AS max_price FROM bid "
        "  GROUP BY auction) AS b ON a.id = b.auction",
        "SELECT * FROM q101")
    bids, aucs, _p = _gen()
    mx = collections.defaultdict(int)
    for a, p in zip(bids["auction"].tolist(), bids["price"].tolist()):
        mx[a] = max(mx[a], p)
    names = dict(zip(aucs["id"].tolist(), aucs["item_name"].tolist()))
    expect = {(i, names[i], mx[i]) for i in names if i in mx}
    assert set(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q103_popular_auctions_having():
    rows = _run(
        "CREATE MATERIALIZED VIEW q103 AS SELECT a.id, a.item_name "
        "FROM auction AS a JOIN ("
        "  SELECT auction FROM bid GROUP BY auction "
        "  HAVING count(*) >= 15) AS b ON a.id = b.auction",
        "SELECT * FROM q103")
    bids, aucs, _p = _gen()
    counts = _bid_counts(bids)
    names = dict(zip(aucs["id"].tolist(), aucs["item_name"].tolist()))
    expect = {(i, names[i]) for i in names if counts.get(i, 0) >= 15}
    assert set(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q105_top_auctions_by_bid_count():
    rows = _run(
        "CREATE MATERIALIZED VIEW q105 AS SELECT auction, count(*) "
        "AS num FROM bid GROUP BY auction ORDER BY num DESC LIMIT 10",
        "SELECT auction, num FROM q105 ORDER BY num DESC")
    bids, _a, _p = _gen()
    counts = _bid_counts(bids)
    top = sorted(counts.values(), reverse=True)[:10]
    assert len(rows) == 10
    assert sorted((n for _a2, n in rows), reverse=True) == top
    for a, n in rows:
        assert counts[a] == n


def test_nexmark_q106_min_final_price():
    rows = _run(
        "CREATE MATERIALIZED VIEW q106 AS SELECT MIN(final) AS m "
        "FROM ("
        "  SELECT a.id AS id, MAX(b.price) AS final "
        "  FROM auction AS a JOIN bid AS b ON a.id = b.auction "
        "  WHERE b.date_time BETWEEN a.date_time AND a.expires "
        "  GROUP BY a.id) AS q",
        "SELECT m FROM q106")
    bids, aucs, _p = _gen()
    window = {}
    for i, dt, exp in zip(aucs["id"].tolist(),
                          aucs["date_time"].tolist(),
                          aucs["expires"].tolist()):
        window[i] = (dt, exp)
    finals = {}
    for a, p, t in zip(bids["auction"].tolist(), bids["price"].tolist(),
                       bids["date_time"].tolist()):
        if a in window and window[a][0] <= t <= window[a][1]:
            finals[a] = max(finals.get(a, 0), p)
    assert len(rows) == 1
    assert rows[0][0] == min(finals.values())


# -- TPC-H -----------------------------------------------------------------

TPCH_CUSTOMERS, TPCH_ORDERS = 300, 2000

TPCH_SOURCES = [
    "CREATE SOURCE {t} WITH (connector='tpch', tpch.table='{t}', "
    "tpch.customers={c}, tpch.orders={o})".format(
        t=t, c=TPCH_CUSTOMERS, o=TPCH_ORDERS)
    for t in ("customer", "orders", "lineitem")
]


def _tpch_lineitem():
    from risingwave_tpu.connectors.tpch import (
        LINES_PER_ORDER, TpchConfig, gen_lineitem,
    )
    cfg = TpchConfig(customers=TPCH_CUSTOMERS, orders=TPCH_ORDERS)
    return gen_lineitem(
        np.arange(TPCH_ORDERS * LINES_PER_ORDER, dtype=np.int64), cfg)


def test_tpch_q1_pricing_summary():
    """q1: the pricing-summary aggregates per (returnflag, linestatus)
    (e2e_test/streaming/tpch/q1 shape; no date filter — the generator
    domain is fully in range)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q1 AS SELECT l_returnflag, "
        "l_linestatus, sum(l_quantity) AS sum_qty, "
        "sum(l_extendedprice) AS sum_base, count(*) AS cnt "
        "FROM lineitem GROUP BY l_returnflag, l_linestatus",
        "SELECT * FROM q1 ORDER BY l_returnflag, l_linestatus",
        sources=TPCH_SOURCES)
    li = _tpch_lineitem()
    import decimal
    agg = {}
    for rf, ls, q, ep in zip(li["l_returnflag"], li["l_linestatus"],
                             li["l_quantity"].tolist(),
                             li["l_extendedprice"].tolist()):
        k = (rf, ls)
        a = agg.setdefault(k, [0, 0, 0])
        a[0] += q
        a[1] += ep          # physical scaled int
        a[2] += 1
    expect = sorted(
        (rf, ls, q, decimal.Decimal(ep).scaleb(-4), c)
        for (rf, ls), (q, ep, c) in agg.items())
    got = [tuple(r) for r in rows]
    assert len(got) == len(expect)
    for g, e in zip(got, expect):
        assert g[0] == e[0] and g[1] == e[1] and g[2] == e[2] \
            and g[4] == e[4]
        assert decimal.Decimal(g[3]) == e[3], (g, e)


def test_tpch_q6_forecast_revenue():
    """q6: global revenue sum under discount/quantity filters
    (e2e_test/streaming/tpch/q6 shape)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q6 AS SELECT "
        "sum(l_extendedprice * l_discount) AS revenue FROM lineitem "
        "WHERE l_discount BETWEEN 0.03 AND 0.07 AND l_quantity < 24",
        "SELECT revenue FROM q6", sources=TPCH_SOURCES)
    li = _tpch_lineitem()
    import decimal
    rev = decimal.Decimal(0)
    for ep, d, q in zip(li["l_extendedprice"].tolist(),
                        li["l_discount"].tolist(),
                        li["l_quantity"].tolist()):
        dd = decimal.Decimal(d).scaleb(-4)
        if decimal.Decimal("0.03") <= dd <= decimal.Decimal("0.07") \
                and q < 24:
            rev += decimal.Decimal(ep).scaleb(-4) * dd
    assert len(rows) == 1
    got = decimal.Decimal(rows[0][0])
    assert got == rev.quantize(decimal.Decimal(10) ** -4), (got, rev)


# -- honest gaps -----------------------------------------------------------
# Reference queries NOT in this corpus and why (checked against
# /root/reference/e2e_test/streaming/nexmark/):
#   q5 (full)   needs a scalar subquery (num >= (SELECT MAX ...));
#               the hop-window top-1 core runs in test_e2e_q5
#   q6          per-seller average of last 10 prices: needs
#               group-top-n-then-agg chaining in one MV
#   q21         needs regexp_extract (split_part-only form runs as
#               part of q22's coverage)
#   q12         processing-time tumble (proctime())
#   q13         side-input (bounded table) join
#   (q19 runs above: rn <= 10 over the q18-style window)
#   q102/q104   scalar subquery over a grouped aggregate (avg of
#               counts) in WHERE/HAVING


def test_tpch_q10_returned_item_revenue():
    """q10 shape: revenue per customer over returned items — 3-way
    join + group + order/limit (e2e_test/streaming/tpch/q10)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q10 AS SELECT c.c_custkey, "
        "c.c_name, sum(l.l_extendedprice * (1.0 - l.l_discount)) "
        "AS revenue FROM customer AS c "
        "JOIN orders AS o ON c.c_custkey = o.o_custkey "
        "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
        "WHERE l.l_returnflag = 'R' "
        "GROUP BY c.c_custkey, c.c_name "
        "ORDER BY revenue DESC LIMIT 20",
        "SELECT * FROM q10 ORDER BY revenue DESC",
        sources=TPCH_SOURCES, steps=16)
    import decimal
    from risingwave_tpu.connectors.tpch import (
        TpchConfig, gen_customer, gen_orders,
    )
    cfg = TpchConfig(customers=TPCH_CUSTOMERS, orders=TPCH_ORDERS)
    cust = gen_customer(np.arange(TPCH_CUSTOMERS, dtype=np.int64), cfg)
    orders = gen_orders(np.arange(TPCH_ORDERS, dtype=np.int64), cfg)
    li = _tpch_lineitem()
    order_cust = dict(zip(orders["o_orderkey"].tolist(),
                          orders["o_custkey"].tolist()))
    rev = collections.defaultdict(decimal.Decimal)
    for ok, ep, d, rf in zip(li["l_orderkey"].tolist(),
                             li["l_extendedprice"].tolist(),
                             li["l_discount"].tolist(),
                             li["l_returnflag"]):
        if rf == "R":
            rev[order_cust[ok]] += (
                decimal.Decimal(ep).scaleb(-4)
                * (1 - decimal.Decimal(d).scaleb(-4)))
    names = dict(zip(cust["c_custkey"].tolist(),
                     cust["c_name"].tolist()))
    top = sorted(rev.items(), key=lambda kv: -kv[1])[:20]
    assert len(rows) == 20
    got_revs = [decimal.Decimal(r[2]) for r in rows]
    exp_revs = [v.quantize(decimal.Decimal(10) ** -8)
                for _k, v in top]
    assert sorted(got_revs, reverse=True) == sorted(
        (decimal.Decimal(x) for x in got_revs), reverse=True)
    for (ck, nm, rv) in rows:
        assert names[ck] == nm
        assert decimal.Decimal(rv) == rev[ck].quantize(
            decimal.Decimal(rv).as_tuple().exponent
            and decimal.Decimal(10)
            ** decimal.Decimal(rv).as_tuple().exponent
            or decimal.Decimal(1)), (ck, rv, rev[ck])


def test_tpch_q18_large_volume_orders():
    """q18 shape: orders whose total quantity exceeds a threshold,
    via a HAVING derived table joined back (the IN-subquery rewrite;
    e2e_test/streaming/tpch/q18)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q18 AS SELECT o.o_orderkey, "
        "o.o_orderdate, b.total FROM orders AS o JOIN ("
        "  SELECT l_orderkey, sum(l_quantity) AS total FROM lineitem "
        "  GROUP BY l_orderkey HAVING sum(l_quantity) > 140"
        ") AS b ON o.o_orderkey = b.l_orderkey",
        "SELECT * FROM q18", sources=TPCH_SOURCES, steps=16)
    from risingwave_tpu.connectors.tpch import TpchConfig, gen_orders
    cfg = TpchConfig(customers=TPCH_CUSTOMERS, orders=TPCH_ORDERS)
    orders = gen_orders(np.arange(TPCH_ORDERS, dtype=np.int64), cfg)
    li = _tpch_lineitem()
    total = collections.Counter()
    for ok, q in zip(li["l_orderkey"].tolist(),
                     li["l_quantity"].tolist()):
        total[ok] += q
    odate = dict(zip(orders["o_orderkey"].tolist(),
                     orders["o_orderdate"].tolist()))
    expect = {(ok, odate[ok], t) for ok, t in total.items() if t > 140}
    assert set(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q101_small_epochs_no_stale_rows():
    """q101 with MANY small epochs: per-epoch MAX updates retract
    through the join by the derived table's pk — a fresh-row-id wrap
    would leave stale max rows (regression: derived-table pk
    stamping)."""
    async def run():
        fe = Frontend(min_chunks=2, rate_limit=2)
        for t in ("bid", "auction"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', nexmark.event.num={N_EVENTS}, "
                f"nexmark.max.chunk.size=64, "
                f"nexmark.min.event.gap.in.ns={GAP_NS})")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q101s AS SELECT a.id, b.m "
            "FROM auction AS a JOIN ("
            "  SELECT auction, MAX(price) AS m FROM bid "
            "  GROUP BY auction) AS b ON a.id = b.auction")
        await fe.step(40)
        rows = await fe.execute("SELECT * FROM q101s")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    bids, aucs, _p = _gen()
    mx = {}
    for a, p in zip(bids["auction"].tolist(), bids["price"].tolist()):
        mx[a] = max(mx.get(a, 0), p)
    ids = set(aucs["id"].tolist())
    expect = {(a, m) for a, m in mx.items() if a in ids}
    assert set(map(tuple, rows)) == expect


def test_nexmark_q10_formatted_log():
    rows = _run(
        "CREATE MATERIALIZED VIEW q10 AS SELECT auction, bidder, "
        "price, date_time, to_char(date_time, 'YYYY-MM-DD') AS dt, "
        "to_char(date_time, 'HH24:MI') AS dm FROM bid",
        "SELECT * FROM q10")
    import datetime
    bids, _a, _p = _gen()
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)

    def fmt(us, f):
        return (epoch + datetime.timedelta(
            microseconds=int(us))).strftime(f)
    expect = collections.Counter(
        (a, b, p, t, fmt(t, "%Y-%m-%d"), fmt(t, "%H:%M"))
        for a, b, p, t in zip(
            bids["auction"].tolist(), bids["bidder"].tolist(),
            bids["price"].tolist(), bids["date_time"].tolist()))
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q14_calculated_fields():
    rows = _run(
        "CREATE MATERIALIZED VIEW q14 AS SELECT auction, bidder, "
        "0.908 * price AS price, CASE "
        "WHEN date_part('hour', date_time) >= 8 AND "
        "date_part('hour', date_time) <= 18 THEN 'dayTime' "
        "WHEN date_part('hour', date_time) <= 6 OR "
        "date_part('hour', date_time) >= 20 THEN 'nightTime' "
        "ELSE 'otherTime' END AS bid_time_type, date_time "
        "FROM bid WHERE 0.908 * price > 1000000",
        "SELECT auction, bidder, price, bid_time_type FROM q14")
    import decimal
    bids, _a, _p = _gen()
    rate = decimal.Decimal("0.908")

    def btype(us):
        h = (int(us) // 3_600_000_000) % 24
        if 8 <= h <= 18:
            return "dayTime"
        if h <= 6 or h >= 20:
            return "nightTime"
        return "otherTime"
    expect = collections.Counter()
    for a, b, p, t in zip(bids["auction"].tolist(),
                          bids["bidder"].tolist(),
                          bids["price"].tolist(),
                          bids["date_time"].tolist()):
        adj = (rate * p).quantize(decimal.Decimal("0.0001"))
        if adj > 1_000_000:
            expect[(a, b, adj, btype(t))] += 1
    got = collections.Counter(
        (a, b, decimal.Decimal(p), bt) for a, b, p, bt in rows)
    assert got == expect
    assert len(rows) > 0


def test_nexmark_q15_per_minute_stats():
    """q15 shape: per-bucket bid stats with COUNT(DISTINCT ...) over a
    to_char projection of the event time."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q15 AS SELECT "
        "to_char(date_time, 'HH24:MI') AS minute, count(*) AS bids, "
        "count(DISTINCT bidder) AS bidders, "
        "count(DISTINCT auction) AS auctions FROM bid "
        "GROUP BY to_char(date_time, 'HH24:MI')",
        "SELECT * FROM q15")
    import datetime
    bids, _a, _p = _gen()
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)
    per = {}
    for a, b, t in zip(bids["auction"].tolist(),
                       bids["bidder"].tolist(),
                       bids["date_time"].tolist()):
        m = (epoch + datetime.timedelta(
            microseconds=int(t))).strftime("%H:%M")
        e = per.setdefault(m, [0, set(), set()])
        e[0] += 1
        e[1].add(b)
        e[2].add(a)
    expect = {(m, c, len(bs), len(as_))
              for m, (c, bs, as_) in per.items()}
    assert set(map(tuple, rows)) == expect
    assert len(rows) > 1


def test_nexmark_q22_url_dirs():
    rows = _run(
        "CREATE MATERIALIZED VIEW q22 AS SELECT auction, bidder, "
        "price, channel, split_part(url, '/', 4) AS dir1, "
        "split_part(url, '/', 5) AS dir2, "
        "split_part(url, '/', 6) AS dir3 FROM bid",
        "SELECT * FROM q22")
    bids, _a, _p = _gen()

    def part(u, k):
        parts = u.split("/")
        return parts[k - 1] if 1 <= k <= len(parts) else ""
    expect = collections.Counter(
        (a, b, p, ch, part(u, 4), part(u, 5), part(u, 6))
        for a, b, p, ch, u in zip(
            bids["auction"].tolist(), bids["bidder"].tolist(),
            bids["price"].tolist(), bids["channel"].tolist(),
            bids["url"].tolist()))
    assert collections.Counter(map(tuple, rows)) == expect
    assert len(rows) > 0


def test_nexmark_q16_filtered_aggregates():
    """q16 shape: per-channel stats with FILTER (WHERE ...) aggregate
    clauses (rank buckets), rewritten to CASE at bind time."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q16 AS SELECT channel, "
        "count(*) AS total, "
        "count(*) FILTER (WHERE price < 10000) AS rank1, "
        "count(*) FILTER (WHERE price >= 10000 AND price < 1000000) "
        "AS rank2, "
        "count(*) FILTER (WHERE price >= 1000000) AS rank3, "
        "max(price) FILTER (WHERE price < 10000) AS max1, "
        "avg(price) FILTER (WHERE price < 150) AS avg_tiny "
        "FROM bid GROUP BY channel",
        "SELECT * FROM q16")
    bids, _a, _p = _gen()
    per = {}
    for ch, p in zip(bids["channel"].tolist(), bids["price"].tolist()):
        e = per.setdefault(ch, [0, 0, 0, 0, None, []])
        e[0] += 1
        if p < 10_000:
            e[1] += 1
            e[4] = p if e[4] is None else max(e[4], p)
        elif p < 1_000_000:
            e[2] += 1
        else:
            e[3] += 1
        if p < 150:
            e[5].append(p)
    got = {r[:6] for r in map(tuple, rows)}
    expect = {(ch, t, r1, r2, r3, m)
              for ch, (t, r1, r2, r3, m, _tiny) in per.items()}
    assert got == expect
    # avg FILTER: empty-match buckets must be NULL, not NaN/0
    import decimal
    for r in map(tuple, rows):
        tiny = per[r[0]][5]
        if not tiny:
            assert r[6] is None, r
        else:
            want = (decimal.Decimal(sum(tiny)) / len(tiny))
            assert abs(decimal.Decimal(r[6]) - want) < \
                decimal.Decimal("0.01"), (r, want)
    assert len(rows) > 2


def test_nexmark_q17_auction_day_stats():
    """q17: per-(auction, day) bid statistics — rank-bucket FILTER
    counts plus min/max/avg/sum."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q17 AS SELECT auction, "
        "to_char(date_time, 'YYYY-MM-DD') AS day, count(*) AS total, "
        "count(*) FILTER (WHERE price < 10000) AS r1, "
        "count(*) FILTER (WHERE price >= 10000 AND price < 1000000) "
        "AS r2, count(*) FILTER (WHERE price >= 1000000) AS r3, "
        "min(price) AS mn, max(price) AS mx, sum(price) AS sm "
        "FROM bid GROUP BY auction, to_char(date_time, 'YYYY-MM-DD')",
        "SELECT * FROM q17")
    import datetime
    bids, _a, _p = _gen()
    epoch = datetime.datetime(1970, 1, 1,
                              tzinfo=datetime.timezone.utc)
    per = {}
    for a, p, t in zip(bids["auction"].tolist(),
                       bids["price"].tolist(),
                       bids["date_time"].tolist()):
        day = (epoch + datetime.timedelta(
            microseconds=int(t))).strftime("%Y-%m-%d")
        e = per.setdefault((a, day), [0, 0, 0, 0, None, None, 0])
        e[0] += 1
        if p < 10_000:
            e[1] += 1
        elif p < 1_000_000:
            e[2] += 1
        else:
            e[3] += 1
        e[4] = p if e[4] is None else min(e[4], p)
        e[5] = p if e[5] is None else max(e[5], p)
        e[6] += p
    expect = {(a, d, t, r1, r2, r3, mn, mx, sm)
              for (a, d), (t, r1, r2, r3, mn, mx, sm) in per.items()}
    assert set(map(tuple, rows)) == expect
    assert len(rows) > 5


def test_nexmark_q18_last_bid_per_bidder_auction():
    """q18: each (bidder, auction)'s most recent bid via
    ROW_NUMBER() = 1 over a derived table."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q18 AS SELECT auction, bidder, "
        "price, date_time FROM (SELECT auction, bidder, price, "
        "date_time, row_number() OVER (PARTITION BY bidder, auction "
        "ORDER BY date_time DESC) AS rn FROM bid) AS t WHERE rn = 1",
        "SELECT * FROM q18")
    bids, _a, _p = _gen()
    last = {}
    for a, b, p, t in zip(bids["auction"].tolist(),
                          bids["bidder"].tolist(),
                          bids["price"].tolist(),
                          bids["date_time"].tolist()):
        cur = last.get((b, a))
        if cur is None or t > cur[3]:
            last[(b, a)] = (a, b, p, t)
    assert len(rows) == len(last)
    src = {(a, b, p, t) for a, b, p, t in zip(
        bids["auction"].tolist(), bids["bidder"].tolist(),
        bids["price"].tolist(), bids["date_time"].tolist())}
    for a, b, p, t in rows:
        assert last[(b, a)][3] == t, (a, b, t)
        # the whole ROW must be a real source bid (not just the time)
        assert (a, b, p, t) in src, (a, b, p, t)
    assert len(rows) > 10


def test_nexmark_q19_top10_bids_per_auction():
    """q19: the 10 highest bids per auction via ROW_NUMBER() <= 10
    over a derived table (per-group LIMIT)."""
    rows = _run(
        "CREATE MATERIALIZED VIEW q19 AS SELECT auction, bidder, "
        "price FROM (SELECT auction, bidder, price, row_number() "
        "OVER (PARTITION BY auction ORDER BY price DESC) AS rn "
        "FROM bid) AS t WHERE rn <= 10",
        "SELECT * FROM q19")
    bids, _a, _p = _gen()
    by_auction = collections.defaultdict(list)
    for a, b, p in zip(bids["auction"].tolist(),
                       bids["bidder"].tolist(),
                       bids["price"].tolist()):
        by_auction[a].append(p)
    # the returned price MULTISET per auction must equal the exact
    # top-10 multiset (counts + thresholds alone would accept a
    # duplicated rank-1 row)
    got_prices = collections.defaultdict(list)
    for a, _b, p in rows:
        got_prices[a].append(p)
    assert set(got_prices) == set(by_auction)
    for a, prices in by_auction.items():
        top = sorted(prices, reverse=True)[:10]
        assert sorted(got_prices[a], reverse=True) == top, a
    assert len(rows) > 20
