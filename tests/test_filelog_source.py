"""External ingestion: filelog connector + parser framework.

Reference parity targets: SplitEnumerator/SplitReader contract
(src/connector/src/source/base.rs:86,282), JSON/CSV parsers
(src/connector/src/parser/), Kafka-style offset recovery
(src/connector/src/source/kafka/). The system ingests bytes it did NOT
generate: records are appended to partition files by the test acting
as an external producer, and kill/restart resumes exactly-once.
"""

import asyncio
import json
import os

import numpy as np
import pytest

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.connectors.filelog import (
    FileLogEnumerator, FileLogSplitReader, partition_path,
)
from risingwave_tpu.connectors.parser import (
    CsvRowParser, JsonRowParser,
)

S = Schema.of(k=DataType.INT64, name=DataType.VARCHAR,
              price=DataType.FLOAT64, ts=DataType.TIMESTAMP)


def _produce(path, topic, part, records):
    os.makedirs(path, exist_ok=True)
    with open(partition_path(path, topic, part), "ab") as f:
        for r in records:
            f.write(json.dumps(r).encode() + b"\n")


def test_json_parser_types_and_errors():
    p = JsonRowParser(S)
    rows = p.parse_batch([
        b'{"k": 1, "name": "a", "price": 1.5, '
        b'"ts": "2026-01-02T03:04:05"}',
        b'{"k": 2, "name": null, "price": null}',   # missing ts → NULL
        b'not json',
        b'{"k": "3", "name": 7, "price": "2.5", "ts": 1700000000}',
    ])
    assert p.errors == 1
    assert rows[0][0] == 1 and rows[0][1] == "a"
    assert rows[0][3] == 1767323045000000
    assert rows[1] == (2, None, None, None)
    assert rows[2] == (3, "7", 2.5, 1700000000000000)


def test_csv_parser():
    p = CsvRowParser(Schema.of(a=DataType.INT64, b=DataType.VARCHAR))
    rows = p.parse_batch([b"1,x", b"2,", b"junk"])
    assert rows == [(1, "x"), (2, None)]
    assert p.errors == 1


def test_enumerator_and_reader_tailing(tmp_path):
    path = str(tmp_path)
    _produce(path, "t", 0, [{"k": i, "name": f"n{i}", "price": i * 1.0,
                             "ts": 1000 + i} for i in range(5)])
    _produce(path, "t", 1, [{"k": 100}])
    splits = FileLogEnumerator(path, "t").list_splits()
    assert [s.split_id for s in splits] == ["filelog-t-0",
                                            "filelog-t-1"]
    r = FileLogSplitReader(path, "t", 0, S, max_chunk_size=3)
    c1 = r.next_chunk()
    assert c1.cardinality() == 3
    c2 = r.next_chunk()
    assert c2.cardinality() == 2
    assert r.next_chunk() is None            # idle, not exhausted
    # torn trailing write stays unconsumed until completed
    with open(partition_path(path, "t", 0), "ab") as f:
        f.write(b'{"k": 7')
    assert r.next_chunk() is None
    with open(partition_path(path, "t", 0), "ab") as f:
        f.write(b', "name": "late"}\n')
    c3 = r.next_chunk()
    assert c3.cardinality() == 1
    rec = c3.to_records()
    assert rec[0][1][0] == 7 and rec[0][1][1] == "late"
    # byte-offset recovery: a fresh reader seeks and re-reads exactly
    r2 = FileLogSplitReader(path, "t", 0, S)
    r2.seek(r.offset)
    assert r2.next_chunk() is None


def test_sql_filelog_ingestion_and_exactly_once_recovery(tmp_path):
    """CREATE SOURCE ... WITH (connector='filelog') ingests external
    bytes; SIGKILL-style restart (fresh Frontend over the same store)
    resumes from the committed offset exactly-once."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    path = str(tmp_path)
    obj = MemObjectStore()
    _produce(path, "trades", 0,
             [{"k": i, "name": f"sym{i % 3}", "price": float(i),
               "ts": i} for i in range(40)])

    ddl = (f"CREATE SOURCE trades (k BIGINT, name VARCHAR, "
           f"price DOUBLE PRECISION, ts TIMESTAMP) "
           f"WITH (connector='filelog', path='{path}', "
           f"topic='trades', format='json', max.chunk.size=16)")

    async def phase1():
        fe = Frontend(store=HummockLite(obj), rate_limit=2)
        await fe.execute(ddl)
        await fe.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT name, count(*) AS c, "
            "sum(k) AS s FROM trades GROUP BY name")
        for _ in range(4):
            await fe.step()
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return rows

    rows1 = asyncio.run(phase1())
    assert sum(r[1] for r in rows1) > 0      # ingested something

    # external producer appends MORE while the session is down
    _produce(path, "trades", 0,
             [{"k": i, "name": f"sym{i % 3}", "price": float(i),
               "ts": i} for i in range(40, 60)])

    async def phase2():
        fe = Frontend(store=HummockLite(obj), rate_limit=2)
        await fe.recover()
        for _ in range(20):
            await fe.step()
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return rows

    rows2 = asyncio.run(phase2())
    got = {name: (c, s) for name, c, s in rows2}
    want = {}
    for i in range(60):
        name = f"sym{i % 3}"
        c, s = want.get(name, (0, 0))
        want[name] = (c + 1, s + i)
    assert got == want, (got, want)   # no loss, no duplication


def test_block_read_carries_partial_line_across_blocks(tmp_path):
    """Review regression: a record straddling the read-block boundary
    must carry over intact — dropping the partial tail corrupted the
    record AND re-read the suffix (duplicate rows)."""
    import io

    from risingwave_tpu.connectors import filelog as fl

    blob = b"aaa\nbbbbbb\nccc\n"
    old = fl._READ_BLOCK
    try:
        fl._READ_BLOCK = 8            # boundary lands inside 'bbbbbb'
        payloads = []
        consumed = fl._read_complete_records(io.BytesIO(blob),
                                             payloads, 100)
    finally:
        fl._READ_BLOCK = old
    assert payloads == [b"aaa", b"bbbbbb", b"ccc"]
    assert consumed == len(blob)
