"""Batch engine tests: scan over committed snapshots + executor tree
(mirrors the reference's batch executor unit-test stances)."""

import numpy as np

from risingwave_tpu.batch import (
    BatchFilter, BatchHashAgg, BatchHashJoin, BatchLimit, BatchOrderBy,
    BatchProject, BatchValues, RowSeqScan, StorageTable, collect,
)
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.expr.expr import InputRef, lit
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_agg import AggCall

S = Schema([Field("k", DataType.INT64), Field("v", DataType.INT64),
            Field("s", DataType.VARCHAR)])


def _pair(n):
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return EpochPair(Epoch.from_physical(n), prev)


def _seeded_store():
    store = MemoryStateStore()
    t = StateTable(9, S, [0], store)
    t.init_epoch(_pair(1))
    for i in range(10):
        t.insert((i, i * 10, None if i % 3 == 0 else f"s{i}"))
    t.commit(_pair(2))
    store.seal_epoch(_pair(2).prev.value, True)
    store.sync(_pair(2).prev.value)
    return store, _pair(2).prev.value


def test_row_seq_scan_snapshot():
    store, epoch = _seeded_store()
    scan = RowSeqScan(StorageTable(9, S, [0], store), epoch, chunk_size=3)
    rows = collect(scan)
    assert len(rows) == 10
    assert rows[0] == (0, 0, None)
    assert rows[4] == (4, 40, "s4")
    # snapshot isolation: nothing visible below the write epoch
    assert collect(RowSeqScan(StorageTable(9, S, [0], store), 1)) == []


def test_filter_project_limit():
    store, epoch = _seeded_store()
    scan = RowSeqScan(StorageTable(9, S, [0], store), epoch)
    f = BatchFilter(scan, InputRef(1, DataType.INT64) >= lit(50))
    p = BatchProject(f, [InputRef(0, DataType.INT64),
                         InputRef(1, DataType.INT64) * lit(2)],
                     names=["k", "v2"])
    rows = collect(BatchLimit(p, limit=3, offset=1))
    assert rows == [(6, 120), (7, 140), (8, 160)]


def test_hash_agg_and_order_by():
    rows = [(i % 3, i, None if i == 4 else i * 1.0) for i in range(9)]
    sch = Schema([Field("g", DataType.INT64), Field("v", DataType.INT64),
                  Field("f", DataType.FLOAT64)])
    agg = BatchHashAgg(
        BatchValues(sch, rows), [0],
        [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 1),
         AggCall(AggKind.MAX, 2), AggCall(AggKind.COUNT, 2)])
    out = collect(BatchOrderBy(agg, [(0, False)]))
    assert out == [
        (0, 3, 0 + 3 + 6, 6.0, 3),
        (1, 3, 1 + 4 + 7, 7.0, 2),      # f NULL at i=4 → count(f)=2
        (2, 3, 2 + 5 + 8, 8.0, 3),
    ]


def test_hash_join_inner():
    ls = Schema([Field("a", DataType.INT64), Field("x", DataType.VARCHAR)])
    rs = Schema([Field("b", DataType.INT64), Field("y", DataType.INT64)])
    left = BatchValues(ls, [(1, "l1"), (2, "l2"), (None, "l3"), (3, "l4")])
    right = BatchValues(rs, [(1, 100), (1, 101), (3, 300), (None, 999)])
    out = sorted(collect(BatchHashJoin(left, right, [0], [0])))
    assert out == [(1, "l1", 1, 100), (1, "l1", 1, 101), (3, "l4", 3, 300)]


def test_scan_over_hummock():
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    store = HummockLite(MemObjectStore())
    t = StateTable(5, S, [0], store)
    t.init_epoch(_pair(1))
    t.insert((1, 11, "a"))
    t.insert((2, 22, "b"))
    t.commit(_pair(2))
    store.seal_epoch(_pair(2).prev.value, True)
    store.sync(_pair(2).prev.value)
    rows = collect(RowSeqScan(StorageTable.of(t), store.committed_epoch()))
    assert rows == [(1, 11, "a"), (2, 22, "b")]


def test_generate_series_table_function():
    """FROM-clause table function (src/expr/src/table_function/
    generate_series parity), incl. alias-as-column and negative step."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend()
        r1 = await fe.execute("SELECT * FROM generate_series(1, 5)")
        r2 = await fe.execute(
            "SELECT g * 2 AS d FROM generate_series(10, 2, -3) AS g")
        r3 = await fe.execute(
            "SELECT count(*) FROM generate_series(1, 100)")
        await fe.close()
        return r1, r2, r3

    r1, r2, r3 = asyncio.run(run())
    assert [r[0] for r in r1] == [1, 2, 3, 4, 5]
    assert [r[0] for r in r2] == [20, 14, 8]     # 2 unreachable (pg)
    assert r3[0][0] == 100


def test_batch_task_manager_staged_agg():
    """Task-manager stage/exchange protocol (task_manager.rs +
    generic_exchange.rs parity): parallel vnode-range scans → hash
    exchange on group keys → per-partition agg → gather equals the
    single-task plan exactly."""
    import asyncio

    from risingwave_tpu.batch.executors import BatchHashAgg, RowSeqScan
    from risingwave_tpu.batch.storage_table import StorageTable
    from risingwave_tpu.batch.task import BatchTaskManager
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.ops.hash_agg import AggKind
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors.hash_agg import AggCall

    S = Schema.of(k=DataType.INT64, g=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(5, S, [0], store, dist_key_indices=[0])
    e1 = EpochPair(Epoch.from_physical(1), Epoch.INVALID)
    e2 = EpochPair(Epoch.from_physical(2), Epoch.from_physical(1))
    t.init_epoch(e1)
    import numpy as np
    rng = np.random.default_rng(7)
    for k in range(2000):
        t.insert((k, int(rng.integers(0, 37)), int(rng.integers(0, 100))))
    t.commit(e2)
    store.seal_epoch(e2.prev.value)
    store.sync(e2.prev.value)
    epoch = e2.prev.value
    st = StorageTable(5, S, [0], store, dist_key_indices=[0])
    calls = [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 2),
             AggCall(AggKind.MAX, 2)]

    # oracle: the existing single-task plan
    single = BatchHashAgg(RowSeqScan(st, epoch), [1], calls)
    want = sorted(r for c in single.execute() for r in c.to_pylist())

    got = asyncio.run(BatchTaskManager(parallelism=4).run_agg(
        st, epoch, [1], calls))
    assert sorted(got) == want
    assert len(want) == 37

    # degenerate parallelism=1 also matches
    got1 = asyncio.run(BatchTaskManager(parallelism=1).run_agg(
        st, epoch, [1], calls))
    assert sorted(got1) == want


def test_batch_task_manager_varchar_keys_and_global_agg():
    import asyncio

    from risingwave_tpu.batch.executors import BatchHashAgg, RowSeqScan
    from risingwave_tpu.batch.storage_table import StorageTable
    from risingwave_tpu.batch.task import BatchTaskManager
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.ops.hash_agg import AggKind
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors.hash_agg import AggCall

    S = Schema.of(k=DataType.INT64, name=DataType.VARCHAR,
                  v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(6, S, [0], store, dist_key_indices=[0])
    e1 = EpochPair(Epoch.from_physical(1), Epoch.INVALID)
    e2 = EpochPair(Epoch.from_physical(2), Epoch.from_physical(1))
    t.init_epoch(e1)
    for k in range(500):
        t.insert((k, f"n{k % 11}", k))
    t.commit(e2)
    store.seal_epoch(e2.prev.value)
    epoch = e2.prev.value
    st = StorageTable(6, S, [0], store, dist_key_indices=[0])
    calls = [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 2)]
    single = BatchHashAgg(RowSeqScan(st, epoch), [1], calls)
    want = sorted(r for c in single.execute() for r in c.to_pylist())
    got = asyncio.run(BatchTaskManager(parallelism=3).run_agg(
        st, epoch, [1], calls))
    assert sorted(got) == want and len(want) == 11
    # grouping-free global agg: one row, exact
    g = asyncio.run(BatchTaskManager(parallelism=4).run_agg(
        st, epoch, [], [AggCall(AggKind.COUNT)]))
    assert g == [(500,)]
