"""Sharded epoch batching (ISSUE 10): one mesh dispatch per epoch.

The sharded join/agg kernels buffer a whole epoch's chunks host-side
and ship ONE SPMD step per kernel at the barrier (parallel/join.py
apply_epoch/probe_epoch, parallel/agg.py backlog) — the oracle here is
the per-chunk dispatch path (epoch_batch=False), which must agree
bit-identically per epoch: update pairs, NULL keys, retractions and
mid-epoch growth included. Dispatch counts are asserted at the REAL
shard_map launch sites (kernel="sharded_*" series) against the
O(1)-per-epoch ceiling, and the RecompileGuard extends to steady-state
mesh runs. Fused-mesh plans (fusion_grouping no longer refuses mesh /
parallelism>1) ride along: prelude-in-SPMD oracle, fragmenter→plan_ir
round-trip at parallelism 2, and a chaos round (worker SIGKILL
mid-epoch-batch) converging oracle-bit-identical.
"""

import asyncio
from collections import Counter

import numpy as np
import pytest
from jax.sharding import Mesh

from risingwave_tpu.common.chunk import Op
from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_agg import (
    AggKind, AggSpec, GroupedAggKernel,
)
from risingwave_tpu.parallel.agg import ShardedAggKernel
from risingwave_tpu.parallel.join import ShardedJoinKernel
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import is_barrier, is_chunk

from test_hash_join import (  # noqa: F401  (reuse the harness)
    L_SCHEMA, R_SCHEMA, barrier, lchunk, materialize_join, rchunk,
)

ALL_JOIN_TYPES = list(JoinType)


@pytest.fixture(scope="module")
def four_mesh(eight_devices):
    """The ad-ctr shape: a 4-virtual-device mesh."""
    return Mesh(np.asarray(eight_devices[:4]), ("d",))


def run_join_mesh(mesh, script_l, script_r, n_barriers,
                  join_type=JoinType.INNER, epoch_batch=True,
                  shard_opts=None):
    store = MemoryStateStore()
    lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, script_l), MockSource(R_SCHEMA, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        join_type=join_type, mesh=mesh, epoch_batch=epoch_batch,
        shard_opts=shard_opts)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, ex


def per_epoch_multisets(msgs):
    """One Counter of (is_insert, row) per epoch — the emission
    contract epoch batching must preserve (within-epoch chunk
    boundaries are reconstructed host-side by offset, so the per-epoch
    record multiset is exactly what downstream state consumes)."""
    epochs, cur = [], Counter()
    for m in msgs:
        if is_chunk(m):
            for op, row in m.to_records():
                cur[(op.is_insert, row)] += 1
        elif is_barrier(m):
            epochs.append(cur)
            cur = Counter()
    return epochs


def _join_scripts(seed: int, epochs: int = 4, per_chunk: int = 12,
                  chunks_per_epoch: int = 3):
    """Random scripted streams with NULL keys, deletes of live rows
    and same-pk update pairs — several chunks per epoch so batching
    has something to batch."""
    rng = np.random.default_rng(seed)
    script_l, script_r = [barrier(1)], [barrier(1)]
    live_l, live_r = [], []          # (key, pk-value)
    lpk, rpk = 0, 0
    b = 2
    for _e in range(epochs):
        for _c in range(chunks_per_epoch):
            ks, vs, ops = [], [], []
            for _ in range(per_chunk):
                r = rng.random()
                if live_l and r < 0.2:
                    i = int(rng.integers(0, len(live_l)))
                    k_, v_ = live_l.pop(i)
                    ks.append(k_); vs.append(v_)
                    ops.append(Op.DELETE)
                elif live_l and r < 0.35:
                    # same-pk update pair: key moves, pk stays
                    i = int(rng.integers(0, len(live_l)))
                    k_, v_ = live_l.pop(i)
                    k2 = int(rng.integers(0, 7))
                    ks.extend([k_, k2]); vs.extend([v_, v_])
                    ops.extend([Op.UPDATE_DELETE, Op.UPDATE_INSERT])
                    live_l.append((k2, v_))
                else:
                    k_ = None if r > 0.9 else int(rng.integers(0, 7))
                    live_l.append((k_, lpk))
                    ks.append(k_); vs.append(lpk)
                    ops.append(Op.INSERT)
                    lpk += 1
            script_l.append(lchunk(ks, vs, ops=ops))
            ks, vs, ops = [], [], []
            for _ in range(per_chunk // 2):
                r = rng.random()
                if live_r and r < 0.25:
                    i = int(rng.integers(0, len(live_r)))
                    k_, v_ = live_r.pop(i)
                    ks.append(k_); vs.append(v_)
                    ops.append(Op.DELETE)
                else:
                    k_ = None if r > 0.9 else int(rng.integers(0, 7))
                    v_ = f"r{rpk}"
                    live_r.append((k_, v_))
                    ks.append(k_); vs.append(v_)
                    ops.append(Op.INSERT)
                    rpk += 1
            script_r.append(rchunk(ks, vs, ops=ops))
        script_l.append(barrier(b))
        script_r.append(barrier(b))
        b += 1
    return script_l, script_r, b - 1


@pytest.mark.parametrize("jt", ALL_JOIN_TYPES,
                         ids=[t.value for t in ALL_JOIN_TYPES])
def test_epoch_batch_oracle_all_join_types(four_mesh, jt):
    """Acceptance: batch-on vs per-chunk-off bit-identical per epoch
    through the mesh join — all 8 types, update pairs, NULL keys and
    retractions included."""
    script_l, script_r, nb = _join_scripts(seed=31 + hash(jt.value) % 7)
    on, ex_on = run_join_mesh(four_mesh, script_l, script_r, nb,
                              join_type=jt, epoch_batch=True)
    off, ex_off = run_join_mesh(four_mesh, script_l, script_r, nb,
                                join_type=jt, epoch_batch=False)
    assert isinstance(ex_on.sides[0].kernel, ShardedJoinKernel)
    assert per_epoch_multisets(on) == per_epoch_multisets(off)
    assert materialize_join(on) == materialize_join(off)


def test_epoch_batch_dispatch_ceiling(four_mesh, dispatch_budget):
    """The whole point: sharded SPMD dispatches drop from one per
    chunk to O(1) per kernel per epoch (≤ 2 uploads + 1 apply + 1
    probe per side), counted at the real shard_map launch sites
    (kernel="sharded_join")."""
    script_l, script_r, nb = _join_scripts(seed=5, epochs=4,
                                           chunks_per_epoch=4)
    _off, d_off, _rpd_off = dispatch_budget.measure_sharded(
        lambda: run_join_mesh(four_mesh, script_l, script_r, nb,
                              epoch_batch=False))
    (_on, d_on, rpd_on) = dispatch_budget.measure_sharded(
        lambda: run_join_mesh(four_mesh, script_l, script_r, nb,
                              epoch_batch=True))
    assert d_on > 0 and d_off > 0
    # 2 sides × (1 apply + 1 probe) = 4 dispatches per epoch max
    dispatch_budget.check_epoch_ceiling(d_on, nb, 4)
    # the off arm dispatches per chunk (4 chunks/epoch/side) — the
    # epoch arm must be strictly cheaper and denser
    dispatch_budget.check(d_off, 1.0, d_on, max(rpd_on, 1.0))


def _agg_stream(seed: int, epochs: int, rows: int, n_keys: int):
    rng = np.random.default_rng(seed)
    out = []
    for _e in range(epochs):
        chunks = []
        for _c in range(3):
            gk = rng.integers(0, n_keys, rows).astype(np.int64) * 7_001
            vals = rng.integers(-(10**6), 10**6, rows)
            signs = np.where(rng.random(rows) < 0.15, -1, 1) \
                .astype(np.int32)
            vis = rng.random(rows) > 0.1
            valid = rng.random(rows) > 0.05      # NULL values
            chunks.append((gk, vals, signs, vis, valid))
        out.append(chunks)
    return out


def _drive_agg(kernel, stream, specs):
    views = []
    for chunks in stream:
        for gk, vals, signs, vis, valid in chunks:
            hi, lo = lanes.split_i64(gk)
            key_lanes = np.stack([hi, lo], axis=1)
            inputs = [(specs[0].encode_input(vals), valid),
                      ((), None)]
            kernel.apply(key_lanes, signs, vis, inputs)
        views.append(dict(kernel.snapshot()))
    return views


def test_mesh_agg_epoch_vs_perchunk_oracle(four_mesh):
    """Mesh agg: epoch-buffered vs per-chunk dispatch bit-identical
    after every epoch (sign-linear adds commute across the epoch fold
    exactly — limb/count math), retractions and NULL inputs included,
    WITH mid-epoch growth (capacity 256 ≪ 2000 keys)."""
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    stream = _agg_stream(seed=11, epochs=4, rows=512, n_keys=2000)
    on = ShardedAggKernel(four_mesh, key_width=2, specs=specs,
                          capacity=256, epoch_batch=True)
    off = ShardedAggKernel(four_mesh, key_width=2, specs=specs,
                           capacity=256, epoch_batch=False)
    v_on = _drive_agg(on, stream, specs)
    v_off = _drive_agg(off, stream, specs)
    assert v_on == v_off
    assert on.capacity > 256      # grew mid-stream, exactly
    # and both agree with the single-chip kernel
    single = GroupedAggKernel(key_width=2, specs=specs)
    for chunks in stream:
        for gk, vals, signs, vis, valid in chunks:
            hi, lo = lanes.split_i64(gk)
            single.apply(np.stack([hi, lo], axis=1), signs, vis,
                         [(specs[0].encode_input(vals), valid),
                          ((), None)])
    single._dispatch_backlog()
    import jax
    from risingwave_tpu.ops.hash_agg import decode_outputs
    st = jax.device_get(single.state)
    live = st.table.occ & (st.group_rows > 0)
    idx = np.flatnonzero(live)
    outs, nulls = decode_outputs(specs, [a[idx] for a in st.accs])
    want = {}
    for r in range(len(idx)):
        want[tuple(st.table.keys[idx][r].tolist())] = tuple(
            None if nulls[c][r] else outs[c][r].item()
            for c in range(len(specs)))
    assert v_on[-1] == want


def test_mesh_agg_epoch_dispatch_count(four_mesh, dispatch_budget):
    """One routed SPMD step + one gather per epoch (vs one step per
    chunk on the off arm), at the kernel="sharded_agg" launch sites."""
    specs = [AggSpec(AggKind.SUM, np.dtype(np.int64)),
             AggSpec(AggKind.COUNT)]
    stream = _agg_stream(seed=3, epochs=3, rows=256, n_keys=64)

    def run(epoch_batch):
        k = ShardedAggKernel(four_mesh, key_width=2, specs=specs,
                             capacity=1 << 10,
                             epoch_batch=epoch_batch)
        for chunks in stream:
            for gk, vals, signs, vis, valid in chunks:
                hi, lo = lanes.split_i64(gk)
                k.apply(np.stack([hi, lo], axis=1), signs, vis,
                        [(specs[0].encode_input(vals), valid),
                         ((), None)])
            k.flush()
            k.advance()
        return k

    _k_off, d_off, _r = dispatch_budget.measure_sharded(
        lambda: run(False))
    _k_on, d_on, _r2 = dispatch_budget.measure_sharded(
        lambda: run(True))
    # on: (1 step + 1 gather) per epoch; off adds one step per chunk
    dispatch_budget.check_epoch_ceiling(d_on, 3, 2)
    assert d_off > d_on


def test_mesh_join_steady_state_recompile_guard(four_mesh,
                                                recompile_guard):
    """RecompileGuard extension (satellite): equal-shaped epochs on a
    steady-state mesh run retrace NOTHING after warmup — the
    module-level step cache plus pow2 epoch shapes hold."""
    def epochs(seed, n):
        rng = np.random.default_rng(seed)
        sl, sr = [barrier(1)], [barrier(1)]
        b = 2
        pk = 0
        for _ in range(n):
            for _c in range(2):
                ks = rng.integers(0, 6, 16).astype(np.int64)
                sl.append(lchunk(ks.tolist(),
                                 list(range(pk, pk + 16))))
                sr.append(rchunk(
                    rng.integers(0, 6, 16).astype(np.int64).tolist(),
                    [f"x{i}" for i in range(pk, pk + 16)]))
                pk += 16
            sl.append(barrier(b))
            sr.append(barrier(b))
            b += 1
        return sl, sr, b - 1

    sl, sr, nb = epochs(1, 6)
    store = MemoryStateStore()
    lt = StateTable(31, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(32, R_SCHEMA, [1], store, dist_key_indices=[])

    def run():
        ex = HashJoinExecutor(
            MockSource(L_SCHEMA, sl), MockSource(R_SCHEMA, sr),
            left_keys=[0], right_keys=[0], left_table=lt,
            right_table=rt, mesh=four_mesh)
        return asyncio.run(collect_until_n_barriers(ex, nb))

    # warmup compiles every shape bucket; pk churn across runs is fine
    # (fresh tables) — what matters is the SECOND run's zero retraces
    _out, _n_warm = recompile_guard.measure(run)
    store2 = MemoryStateStore()
    lt2 = StateTable(33, L_SCHEMA, [1], store2, dist_key_indices=[])
    rt2 = StateTable(34, R_SCHEMA, [1], store2, dist_key_indices=[])

    def run2():
        ex = HashJoinExecutor(
            MockSource(L_SCHEMA, sl), MockSource(R_SCHEMA, sr),
            left_keys=[0], right_keys=[0], left_table=lt2,
            right_table=rt2, mesh=four_mesh)
        return asyncio.run(collect_until_n_barriers(ex, nb))

    _out2, n_steady = recompile_guard.measure(run2)
    recompile_guard.check_steady(n_steady,
                                 what="steady-state mesh join run")


def test_fused_mesh_sql_oracle(eight_devices):
    """fusion_grouping no longer refuses mesh plans: a parallelism-4
    session absorbs the filter run into the SHARDED agg kernel's
    prelude (traced before vnode routing) and stays bit-identical to
    fusion off."""
    from risingwave_tpu.frontend.session import Frontend

    sql_src = ("CREATE SOURCE bid WITH (connector='nexmark', "
               "nexmark.table.type='bid', nexmark.event.num=3000, "
               "nexmark.max.chunk.size=256, "
               "nexmark.generate.strings='false')")
    mv = ("CREATE MATERIALIZED VIEW q AS SELECT auction, "
          "count(*) AS c, sum(price) AS s FROM bid "
          "WHERE price > 100 GROUP BY auction")

    def run(fusion):
        async def main():
            fe = Frontend(min_chunks=8, parallelism=4)
            await fe.execute(
                f"SET stream_fusion = '{'on' if fusion else 'off'}'")
            await fe.execute(sql_src)
            await fe.execute(mv)
            await fe.step(20)
            rows = sorted(tuple(r) for r in
                          await fe.execute("SELECT * FROM q"))
            kernels = [
                a for actor in fe.actors.values()
                for a in [actor.consumer]]
            await fe.close()
            return rows
        return asyncio.run(main())

    rows_off = run(False)
    rows_on = run(True)
    assert rows_on == rows_off and rows_on


def test_fused_mesh_agg_prelude_installed(eight_devices):
    """White-box: the mesh plan's HashAggExecutor carries fused_stages
    AND its injected ShardedAggKernel received the prelude (the
    absorbed run runs in-SPMD, not interpretively)."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.stream.executor import executor_children
    from risingwave_tpu.stream.executors.hash_agg import (
        HashAggExecutor,
    )

    async def main():
        fe = Frontend(min_chunks=8, parallelism=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1500, "
            "nexmark.max.chunk.size=256, "
            "nexmark.generate.strings='false')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q AS SELECT auction, "
            "count(*) AS c FROM bid WHERE price > 50 "
            "GROUP BY auction")

        def find(ex):
            ex = getattr(ex, "inner", ex)   # MonitoredExecutor wraps
            if isinstance(ex, HashAggExecutor):
                return ex
            for _a, _i, c in executor_children(ex):
                got = find(c)
                if got is not None:
                    return got
            return None

        aggs = [find(a.consumer) for a in fe.actors.values()]
        agg = next(a for a in aggs if a is not None)
        assert agg.fused_stages is not None, "mesh plan did not fuse"
        assert isinstance(agg._kernel, ShardedAggKernel)
        await fe.step(10)
        assert agg._kernel._prelude is not None, \
            "prelude never installed on the sharded kernel"
        rows = await fe.execute("SELECT * FROM q")
        await fe.close()
        return rows

    assert asyncio.run(main())


def test_fused_parallel_fragmenter_roundtrip():
    """Fragmenter→plan_ir round-trip at parallelism 2 (satellite): the
    fused cut carries RAW-mapped hash keys, ships left_fused/
    right_fused + fused_stages IR, and build_fragment reconstructs
    fused executors on the worker side."""
    from risingwave_tpu.frontend.catalog import Catalog
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.frontend.opt import rewrite_stream_plan
    from risingwave_tpu.frontend.parser import parse_many
    from risingwave_tpu.frontend.planner import (
        StreamPlanner, source_schema,
    )
    from risingwave_tpu.meta.barrier import LocalBarrierManager

    opts_p = {"connector": "nexmark", "nexmark.table.type": "person",
              "nexmark.event.num": "500",
              "nexmark.generate.strings": "false"}
    opts_a = {"connector": "nexmark", "nexmark.table.type": "auction",
              "nexmark.event.num": "500",
              "nexmark.generate.strings": "false"}
    catalog = Catalog()
    catalog.add_source("person", source_schema(opts_p, None), opts_p)
    catalog.add_source("auction", source_schema(opts_a, None), opts_a)
    [(_t, stmt)] = parse_many(
        "CREATE MATERIALIZED VIEW v AS SELECT p.id, count(*) AS c "
        "FROM person AS p JOIN auction AS a ON p.id = a.seller "
        "GROUP BY p.id")
    planner = StreamPlanner(catalog, MemoryStateStore(),
                            LocalBarrierManager(), definition="",
                            dist_parallelism=2)
    plan = planner.plan("v", stmt.select, 7, rate_limit=4)
    consumer, report = rewrite_stream_plan(
        plan.consumer, "all", record=False, fusion=True,
        dist_parallelism=2)
    assert report.fired.get("fusion_grouping")
    graph = Fragmenter(2).lower(consumer)
    join_fi, join_node = next(
        (fi, n) for fi, f in enumerate(graph.fragments)
        for n in f.nodes if n["op"] == "hash_join")
    assert join_node.get("left_fused") or join_node.get("right_fused")
    frag = graph.fragments[join_fi]
    # the fused cut carries RAW-space hash keys (mapped back through
    # the absorbed run — person.id is raw col 0, auction.seller raw 7)
    for inp, side_key in zip(frag.inputs, ("left", "right")):
        assert inp.keys, "parallel fused cut must carry hash keys"
    # worker-side rebuild: splice a schema-only exchange stub per port
    from risingwave_tpu.stream.plan_ir import schema_from_ir
    nodes = []
    remap = {}
    for i, node in enumerate(frag.nodes):
        if node["op"] == "exchange_in":
            inp = frag.inputs[node["port"]]
            nodes.append({"op": "source_stub",
                          "schema": inp.schema})
            remap[i] = len(nodes) - 1
            continue
        from risingwave_tpu.stream.plan_ir import remap_node_refs
        nodes.append(remap_node_refs(node, remap))
        remap[i] = len(nodes) - 1

    # build_fragment has no source_stub — swap in real MockSources by
    # pre-seeding `built` via a tiny shim node type is overkill; use
    # the documented path: replace stubs with "merge"-free mock via
    # monkeypatched builder is heavier than just checking IR fidelity
    # here and executor parity through the DistFrontend e2e below.
    from risingwave_tpu.stream.plan_ir import stages_from_ir
    l_fs = stages_from_ir(schema_from_ir(frag.inputs[0].schema),
                          join_node["left_fused"],
                          store=MemoryStateStore())
    assert l_fs.out_schema is not None
    assert l_fs.describe()


def test_fused_parallel2_cluster_oracle(tmp_path):
    """e2e: a 2-worker, parallelism-2 distributed deploy with fusion
    ON (fused join inputs + fused local agg crossing hash-exchange
    cuts on raw-mapped keys) serves rows bit-identical to fusion off."""
    from risingwave_tpu.cluster.session import DistFrontend

    srcs = [
        "CREATE SOURCE person WITH (connector='nexmark', "
        "nexmark.table.type='person', nexmark.event.num=1200, "
        "nexmark.generate.strings='false')",
        "CREATE SOURCE auction WITH (connector='nexmark', "
        "nexmark.table.type='auction', nexmark.event.num=1200, "
        "nexmark.generate.strings='false')"]
    mv = ("CREATE MATERIALIZED VIEW q AS SELECT p.id, "
          "count(*) AS cnt FROM person AS p "
          "JOIN auction AS a ON p.id = a.seller "
          "WHERE a.category >= 10 GROUP BY p.id")

    def run(sub, fusion):
        async def main():
            fe = DistFrontend(str(tmp_path / sub), n_workers=2,
                              parallelism=2)
            await fe.start()
            try:
                await fe.execute(
                    f"SET stream_fusion = "
                    f"'{'on' if fusion else 'off'}'")
                for s in srcs:
                    await fe.execute(s)
                await fe.execute(mv)
                await fe.step(25)
                return sorted(tuple(r) for r in
                              await fe.execute("SELECT * FROM q"))
            finally:
                await fe.close()
        return asyncio.run(main())

    rows_off = run("off", False)
    rows_on = run("on", True)
    assert rows_on == rows_off and rows_on


def test_chaos_sigkill_mid_epoch_batch(tmp_path):
    """Chaos satellite: SIGKILL a worker while its join epoch buffers
    hold un-dispatched chunks (mid-epoch-batch) on a FUSED
    parallelism-2 job; supervised recovery classifies dead_worker,
    respawns the slot, and the MV converges bit-identically to the
    fault-free in-process oracle."""
    from risingwave_tpu.cluster.session import DistFrontend
    from risingwave_tpu.frontend.session import Frontend

    srcs = [
        "CREATE SOURCE person WITH (connector='nexmark', "
        "nexmark.table.type='person', nexmark.event.num=1500, "
        "nexmark.max.chunk.size=128, "
        "nexmark.generate.strings='false')",
        "CREATE SOURCE auction WITH (connector='nexmark', "
        "nexmark.table.type='auction', nexmark.event.num=1500, "
        "nexmark.max.chunk.size=128, "
        "nexmark.generate.strings='false')"]
    mv = ("CREATE MATERIALIZED VIEW q AS SELECT p.id, "
          "count(*) AS cnt FROM person AS p "
          "JOIN auction AS a ON p.id = a.seller GROUP BY p.id")

    def oracle():
        async def main():
            fe = Frontend(min_chunks=8)
            for s in srcs:
                await fe.execute(s)
            await fe.execute(mv)
            await fe.step(40)
            rows = {tuple(r)
                    for r in await fe.execute("SELECT * FROM q")}
            await fe.close()
            return rows
        return asyncio.run(main())

    async def chaos():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        try:
            for s in srcs:
                await fe.execute(s)
            await fe.execute(mv)
            await fe.step(4)
            # kill between barriers: the surviving epoch state is the
            # committed floor; the dead worker's buffered epoch batch
            # dies with it and replays from the source offsets
            fe.cluster.kill_slot(1)
            try:
                await fe.step(3)
            except Exception as e:                   # noqa: BLE001
                ev = await fe.supervised_recover(e)
                assert (ev.cause, ev.action) == ("dead_worker",
                                                 "respawn")
                assert ev.ok
            await fe.step(45)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q")}
        finally:
            await fe.close()

    assert asyncio.run(chaos()) == oracle()
