"""Pipelined epochs (ISSUE 13): barrier domains, overlap attribution,
decoupled checkpoint cadence, and the off-arm oracle.

Covers the in-process session plane: domain derivation by dataflow
reachability (disjoint sources → own domains; shared sources / MV deps
→ joined; live merge via a bridging MV), per-domain latency surfaces
(rw_barrier_latency / rw_metrics_history domain columns), the
sleep-failpoint overlap oracle (a stalled device dispatch lands in the
slow DOMAIN's device_compute books only, with the conservation gate
green in both domains), checkpoint cadence decoupled from barrier
cadence, and bit-identical results between stream_epoch_pipeline on
and off. The distributed plane's chaos coverage lives in
tests/test_chaos.py.
"""

import asyncio

import pytest

from risingwave_tpu.frontend.session import Frontend

BID_SOURCE = (
    "CREATE SOURCE {name} WITH (connector='nexmark', "
    "nexmark.table.type='bid', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.generate.strings='false')")

AGG_MV = (
    "CREATE MATERIALIZED VIEW {mv} AS SELECT auction, "
    "COUNT(*) AS cnt, MAX(price) AS max_price FROM {src} "
    "GROUP BY auction")

EVENTS = 4000


def _run(coro):
    return asyncio.run(coro)


async def _two_domain_session(n=EVENTS, pipeline=True):
    fe = Frontend(rate_limit=8, min_chunks=8, epoch_pipeline=pipeline)
    await fe.execute(BID_SOURCE.format(name="bid_a", n=n))
    await fe.execute(BID_SOURCE.format(name="bid_b", n=n))
    await fe.execute(AGG_MV.format(mv="mv_a", src="bid_a"))
    await fe.execute(AGG_MV.format(mv="mv_b", src="bid_b"))
    return fe


def test_disjoint_mvs_get_their_own_domains():
    """Two MVs over disjoint sources align independently; a third MV
    over a shared source joins the existing domain; dropping it
    retires nothing while the domain still has a job."""
    async def run():
        fe = await _two_domain_session()
        domains = {d["domain"]: d for d in fe.loop.describe()}
        assert set(domains) == {"mv_a", "mv_b"}
        assert domains["mv_a"]["jobs"] == ["mv_a"]
        # shared-source fan-out stays joined
        await fe.execute(AGG_MV.format(mv="mv_a2", src="bid_a"))
        domains = {d["domain"]: sorted(d["jobs"])
                   for d in fe.loop.describe()}
        assert domains["mv_a"] == ["mv_a", "mv_a2"]
        assert domains["mv_b"] == ["mv_b"]
        await fe.execute("DROP MATERIALIZED VIEW mv_a2")
        domains = {d["domain"]: sorted(d["jobs"])
                   for d in fe.loop.describe()}
        assert domains["mv_a"] == ["mv_a"]
        await fe.step(3)
        rows_a = await fe.execute("SELECT COUNT(*) FROM mv_a")
        rows_b = await fe.execute("SELECT COUNT(*) FROM mv_b")
        await fe.close()
        return rows_a, rows_b

    rows_a, rows_b = _run(run())
    assert rows_a[0][0] > 0 and rows_b[0][0] > 0


def test_bridging_mv_merges_live_domains_and_stays_exact():
    """A new MV reading BOTH sources merges the two live domains (the
    monotone epoch re-anchor): results on every MV stay exact vs the
    off-arm oracle."""
    async def run(pipeline):
        fe = await _two_domain_session(pipeline=pipeline)
        await fe.step(3)         # both domains flow before the merge
        await fe.execute(
            "CREATE MATERIALIZED VIEW bridge AS SELECT a.auction, "
            "a.cnt AS ca, b.cnt AS cb FROM mv_a AS a "
            "JOIN mv_b AS b ON a.auction = b.auction")
        if pipeline:
            domains = {d["domain"]: sorted(d["jobs"])
                       for d in fe.loop.describe()}
            assert len(domains) == 1, domains
            only = next(iter(domains.values()))
            assert only == ["bridge", "mv_a", "mv_b"]
        await fe.step(6)
        out = {}
        for mv in ("mv_a", "mv_b", "bridge"):
            out[mv] = {tuple(r) for r in
                       await fe.execute(f"SELECT * FROM {mv}")}
        await fe.close()
        return out

    on = _run(run(True))
    off = _run(run(False))
    assert on == off
    assert len(on["bridge"]) > 0


def test_on_off_arms_bit_identical():
    """stream_epoch_pipeline=off reproduces the plane's results
    bit-identically on a disjoint 2-MV deploy."""
    async def run(pipeline):
        fe = await _two_domain_session(pipeline=pipeline)
        await fe.step(8)
        a = {tuple(r) for r in await fe.execute("SELECT * FROM mv_a")}
        b = {tuple(r) for r in await fe.execute("SELECT * FROM mv_b")}
        await fe.close()
        return a, b

    assert _run(run(True)) == _run(run(False))


def test_epoch_pipeline_set_var_guarded():
    """SET stream_epoch_pipeline flips the engine when idle and
    refuses with live jobs."""
    from risingwave_tpu.frontend.planner import PlanError
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.meta.domains import BarrierPlane

    async def run():
        fe = Frontend()
        assert isinstance(fe.loop, BarrierPlane)
        await fe.execute("SET stream_epoch_pipeline = 'off'")
        assert isinstance(fe.loop, BarrierLoop)
        await fe.execute("SET stream_epoch_pipeline = 'on'")
        assert isinstance(fe.loop, BarrierPlane)
        await fe.execute(BID_SOURCE.format(name="bid_a", n=256))
        await fe.execute(AGG_MV.format(mv="mv_a", src="bid_a"))
        with pytest.raises(PlanError):
            await fe.execute("SET stream_epoch_pipeline = 'off'")
        assert isinstance(fe.loop, BarrierPlane)
        await fe.close()

    _run(run())


def test_domain_latency_surfaces_over_sql():
    """rw_barrier_latency and rw_metrics_history carry the domain
    column; each domain's epochs appear under its own key."""
    async def run():
        fe = await _two_domain_session()
        await fe.step(4)
        lat = await fe.execute("SELECT * FROM rw_barrier_latency")
        hist = await fe.execute("SELECT * FROM rw_metrics_history")
        p99 = fe.loop.p99_by_domain()
        await fe.close()
        return lat, hist, p99

    lat, hist, p99 = _run(run())
    lat_domains = {r[10] for r in lat}
    assert {"mv_a", "mv_b"} <= lat_domains, lat_domains
    hist_domains = {r[6] for r in hist}
    assert {"mv_a", "mv_b"} <= hist_domains, hist_domains
    # per-domain barrier_wait/phase rows exist for the autoscaler
    names = {r[4] for r in hist if r[6] == "mv_a"}
    assert "phase.barrier_wait" in names
    assert set(p99) >= {"mv_a", "mv_b"}
    assert all(v >= 0 for v in p99.values())


def test_overlap_ledger_slow_dispatch_stays_in_its_domain():
    """The overlap oracle (ISSUE 13 satellite): a sleep failpoint
    INSIDE one domain's device dispatch lands in that domain's
    device_compute books only — the sibling domain's epochs stay
    short (its barrier_wait cannot absorb the stall), and the
    conservation gate stays green in both domains."""
    from risingwave_tpu.utils.failpoint import arm_specs
    from risingwave_tpu.utils.ledger import LEDGER

    SLEEP_S = 0.6

    async def run():
        fe = await _two_domain_session()
        await fe.step(2)          # warm: compiles land outside
        # both domains' fused agg steps share one dispatch identity
        # (the planner's node-actor label): arm ONE firing — exactly
        # one domain's dispatch absorbs the stall; detect which below
        slow_aid = fe.catalog.mvs["mv_a"].actor_id
        ident = None

        def find(ex):
            nonlocal ident
            if "HashAgg" in getattr(ex, "identity", ""):
                ident = ex.identity
            for child in getattr(ex, "children", []):
                find(child)
        find(fe.actors[slow_aid].consumer)
        assert ident is not None
        # small epochs dispatch at the barrier flush (the .flush
        # label) — ONE firing total, so exactly one domain stalls
        points = {f"ledger.dispatch.{ident}.flush": {
            "sleep_s": SLEEP_S, "times": 1}}
        arm_specs(points)
        try:
            await fe.step(2)
        finally:
            arm_specs({k: None for k in points})
        recs = list(LEDGER.records)
        await fe.close()
        return recs

    recs = _run(run())
    by_dom = {}
    for r in recs:
        if r.domain in ("mv_a", "mv_b") and not r.warmup:
            by_dom.setdefault(r.domain, []).append(r)
    assert set(by_dom) == {"mv_a", "mv_b"}
    # exactly ONE domain's epoch carries the stall AS device_compute
    # (≥ 80% of the sleep inside its own books)
    hit_doms = {d for d, rs in by_dom.items()
                if any(r.seconds.get("device_compute", 0.0)
                       >= SLEEP_S * 0.8 for r in rs)}
    assert len(hit_doms) == 1, {
        d: [(r.interval_s, r.seconds) for r in rs]
        for d, rs in by_dom.items()}
    fast_dom = ({"mv_a", "mv_b"} - hit_doms).pop()
    # the sibling's concurrent epoch shares the frozen wall clock (a
    # blocking CPU dispatch stalls the single event loop — the same
    # physics as a real slow CPU kernel), but its books NEVER claim
    # the stall as work: no phantom device_compute, no unattributed
    # rot — the shared wall shows up as barrier-parked sources only
    for r in by_dom[fast_dom]:
        assert r.seconds.get("device_compute", 0.0) < SLEEP_S * 0.2, \
            (r.interval_s, r.seconds)
        assert r.unattributed_s < max(
            0.1, 0.3 * r.interval_s), (r.interval_s, r.seconds)
    # conservation green in BOTH domains (the autouse gate re-checks
    # at teardown; assert explicitly for the record)
    assert LEDGER.gate_violations() == []


def test_checkpoint_cadence_decoupled_from_barriers():
    """stream_checkpoint_frequency=k: plain rounds advance per-domain
    without committing; every k-th round is an aligned checkpoint that
    advances the durable floor."""
    async def run():
        fe = await _two_domain_session(n=8000)
        await fe.execute("SET stream_checkpoint_frequency = 4")
        base = fe.store.committed_epoch()
        committed = []
        for _ in range(8):
            await fe.loop.inject_and_collect(drain_uploader=False)
            committed.append(fe.store.committed_epoch())
        await fe.close()
        return base, committed

    base, committed = _run(run())
    # rounds 1-3 plain (floor parked), round 4 commits, 5-7 plain,
    # round 8 commits again
    assert committed[0] == base
    assert committed[2] == base
    assert committed[3] > base
    assert committed[6] == committed[3]
    assert committed[7] > committed[3]


def test_plane_pipelined_driver_and_drive():
    """The plane's inject/collect facade pipelines per-domain windows
    and drive() pumps domains independently to completion."""
    async def run():
        fe = await _two_domain_session(n=6000)
        readers = [r for d in fe.readers.values()
                   for r in d.values()]
        expected = 2 * (6000 * 46 // 50)

        def rows_seen():
            return sum(r.offset for r in readers)

        await fe.loop.drive(lambda: rows_seen() >= expected,
                            in_flight=2, progress_fn=rows_seen)
        assert rows_seen() == expected
        # every domain drained its window
        assert fe.loop.in_flight_count == 0
        await fe.step(1)     # final aligned checkpoint
        a = await fe.execute("SELECT COUNT(*) FROM mv_a")
        b = await fe.execute("SELECT COUNT(*) FROM mv_b")
        await fe.close()
        return a, b

    a, b = _run(run())
    assert a[0][0] > 0 and b[0][0] > 0
