"""Serving cost & skew attribution tests (ISSUE 16): the per-MV
resource ledger, the per-(table, vnode) state topology, heavy-hitter
sketches, the skew verdict in the bottleneck walker's diagnosis, and
the series-lifecycle purge on DROP / failed CREATE."""

import asyncio
import json
import os
import time

import numpy as np
import pytest

from risingwave_tpu.state.topology import (
    TOPOLOGY, StateTopology, fixed_row_nbytes, row_nbytes,
)
from risingwave_tpu.stream.costs import (
    COSTS, CompileCache, MVCosts, purge_mv_series,
)
from risingwave_tpu.stream.hotkeys import HOTKEYS, K, HotKeys, _Sketch

NEXMARK_BID = (
    "CREATE SOURCE bid WITH (connector='nexmark', "
    "nexmark.table.type='bid', nexmark.event.num=4000)")


def _lanes(values):
    """(n, 3) int32 key lanes for a single-BIGINT-column key — the
    (hi, lo, valid) shape the codec emits."""
    v = np.asarray(values, dtype=np.int64)
    return np.stack([(v >> 32).astype(np.int32),
                     (v & 0xFFFFFFFF).astype(np.int32),
                     np.ones(len(v), dtype=np.int32)], axis=1)


# -- space-saving sketch ---------------------------------------------------

def test_sketch_90_10_share_within_5pp():
    """The acceptance bound: a seeded 90/10 stream's hot key surfaces
    with share error ≤ 5pp, even with more distinct cold keys than
    sketch counters (evictions churn only the cold tail)."""
    rng = np.random.default_rng(7)
    n = 20_000
    keys = np.where(rng.random(n) < 0.9, 777,
                    rng.integers(1000, 1000 + 4 * K, n))
    sk = _Sketch()
    for lo in range(0, n, 512):          # chunked like the hot path
        sk.observe(_lanes(keys[lo:lo + 512]), None, None)
    true_share = float(np.mean(keys == 777))
    h, est, err = sk.top(1)[0]
    assert abs(est / sk.total - true_share) <= 0.05
    # guaranteed (lower-bound) share also within the bound
    assert true_share - 0.05 <= (est - err) / sk.total <= true_share + 0.05


def test_sketch_estimates_bound_true_counts():
    """Space-saving invariants under forced eviction: est ≥ true and
    est − err ≤ true for every surviving counter."""
    n_keys = 3 * K
    per = 5
    hot_reps = 200
    seq = list(range(n_keys)) * per + [42] * hot_reps
    rng = np.random.default_rng(0)
    rng.shuffle(seq)
    sk = _Sketch()
    sk.observe(_lanes(seq), None, None)
    true = {k: per for k in range(n_keys)}
    true[42] += hot_reps
    for h, est, err in sk.top(K):
        # recover the original key via its stored representative lane
        lane = sk.lanes[h]
        key = (int(lane[0]) << 32) | int(lane[1])
        assert est >= true[key]
        assert est - err <= true[key]
    # the hot key is rank 1
    top_lane = sk.lanes[sk.top(1)[0][0]]
    assert (int(top_lane[0]) << 32) | int(top_lane[1]) == 42


def test_sketch_respects_visibility_and_display_fallback():
    sk = _Sketch()
    lanes = _lanes([5, 5, 9, 9])
    vis = np.array([True, True, True, False])
    sk.observe(lanes, vis, None)
    assert sk.total == 3
    h, est, _err = sk.top(1)[0]
    assert est == 2
    assert sk.display(h).startswith("#")   # no codec → hash fallback


def test_hotkeys_join_suffix_resolution_and_unregister():
    """Join inputs observe under 'identity/side' while the fragment
    binding is on the base identity: rows() must resolve the MV, and
    unregister_fragment must drop the suffixed sketches too."""
    hk = HotKeys()
    hk.bind_fragment("HashJoinExecutor-3", "mv_a")
    hk.observe("HashJoinExecutor-3/0", _lanes([1] * 9 + [2]), None,
               None)
    hk.observe("HashJoinExecutor-3/1", _lanes([4] * 10), None, None)
    rows = hk.rows()
    assert rows and all(r[0] == "mv_a" for r in rows)
    hot = hk.hot_share("HashJoinExecutor-3", min_share=0.25)
    assert hot is not None and hot[1] >= 0.25
    hk.unregister_fragment("mv_a")
    assert hk.rows() == []
    assert hk.hot_share("HashJoinExecutor-3") is None


# -- state topology --------------------------------------------------------

def test_topology_incremental_matches_recount():
    topo = StateTopology()
    keys = [bytes([0, i, 7]) for i in range(10)]
    vals = [(1, 2)] * 10
    topo.record(9, keys, vals, fixed_nbytes=18)      # append-fast
    # overwrite half (same unit), delete two, then a varchar batch
    topo.record(9, keys[:5], vals[:5], fixed_nbytes=18)
    topo.record(9, keys[:2], [None, None], fixed_nbytes=18)
    topo.record(9, [b"\x01\x00zz", b"\x01\x01w"],
                [("abc",), ("defgh",)])               # slow path
    assert topo.gate_violations() == []
    stats = {t: (nrows, nbytes) for t, _mv, nrows, nbytes, _v, _i
             in topo.table_stats()}
    assert stats[9][0] == 8 + 2
    # per-vnode split: first batch lands in vnodes (0,0..9); varchar
    # rows in vnodes 256 and 257
    vns = {vn for _t, _mv, vn, _r, _b in topo.rows()}
    assert {256, 257} <= vns
    assert topo.top_vnodes(9, 4)


def test_topology_mixed_batches_and_byte_model():
    topo = StateTopology()
    topo.record(3, [b"ab"], [("xy", 5)])
    assert row_nbytes(("xy", 5)) == 3 + 9
    _t, _mv, nrows, nbytes, _v, _i = topo.table_stats()[0]
    assert (nrows, nbytes) == (1, 2 + 12)
    # a delete mixed into a fixed-width batch falls to the slow path
    topo.record(3, [b"ab", b"cd"], [None, (1, 2)], fixed_nbytes=18)
    assert topo.gate_violations() == []
    _t, _mv, nrows, nbytes, _v, _i = topo.table_stats()[0]
    assert (nrows, nbytes) == (1, 2 + 18)


def test_topology_width_change_overwrite_stays_exact():
    """Regression: re-planning the same table id with a different row
    width (column pruning narrows a varchar table to all-fixed) must
    not ride the append-fast bulk merge — blind overwrites of entries
    that hold a DIFFERENT size would change the map without touching
    the delta totals, and the recount gate would fire."""
    topo = StateTopology()
    keys = [bytes([0, i]) for i in range(6)]
    # first plan: varchar rows via the slow path (variable widths)
    topo.record(5, keys, [("x" * (i + 1),) for i in range(6)])
    # re-planned: same keys, all-fixed schema → fast-path candidate
    topo.record(5, keys, [(1, 2)] * 6, fixed_nbytes=18)
    assert topo.gate_violations() == []
    _t, _mv, nrows, nbytes, _v, _i = topo.table_stats()[0]
    assert (nrows, nbytes) == (6, 6 * (2 + 18))
    # and the reverse order: fast-path first, then a different unit
    topo2 = StateTopology()
    topo2.record(7, keys, [(1,)] * 6, fixed_nbytes=9)
    topo2.record(7, keys, [(1, 2)] * 6, fixed_nbytes=18)
    assert topo2.gate_violations() == []
    _t, _mv, nrows, nbytes, _v, _i = topo2.table_stats()[0]
    assert (nrows, nbytes) == (6, 6 * (2 + 18))
    # once mixed, the table stays on the exact per-entry loop
    topo2.record(7, keys, [(3, 4)] * 6, fixed_nbytes=18)
    assert topo2.gate_violations() == []
    # a never-mixed table keeps riding the fast path across
    # same-unit overwrites (the steady-state upsert shape)
    topo3 = StateTopology()
    topo3.record(8, keys, [(1,)] * 6, fixed_nbytes=9)
    topo3.record(8, keys, [(2,)] * 6, fixed_nbytes=9)
    assert topo3._unit[8] == 11 and topo3.gate_violations() == []


def test_topology_checkpoint_verify_arming():
    topo = StateTopology()
    topo.record(1, [b"aa"], [(1,)], fixed_nbytes=9)
    topo.checkpoint_verify()                 # unarmed: no-op
    topo.arm_checkpoint_verify(True)
    # sabotage the delta book to prove the recount catches drift
    topo._totals[1][1] += 5
    topo.checkpoint_verify()
    assert topo.gate_violations()
    topo.clear()
    assert topo.gate_violations() == []


def test_topology_unbind_mv_drops_books_and_remote():
    topo = StateTopology()
    topo.bind(4, "mv_x")
    topo.record(4, [b"aa"], [(1,)], fixed_nbytes=9)
    topo.ingest([(8, "mv_x", 0, 2, 40), (9, "mv_y", 0, 1, 20)],
                worker="w1")
    topo.unbind_mv("mv_x")
    assert all(r[1] != "mv_x" for r in topo.rows())
    assert topo.bytes_by_mv().get("mv_y") == 20


def test_fixed_row_nbytes_gates_on_device_types():
    from risingwave_tpu.common.types import DataType, Field, Schema
    dev = Schema([Field("a", DataType.INT64),
                  Field("b", DataType.FLOAT64)])
    host = Schema([Field("a", DataType.INT64),
                   Field("s", DataType.VARCHAR)])
    assert fixed_row_nbytes(dev) == 18
    assert fixed_row_nbytes(host) is None


# -- per-MV resource ledger ------------------------------------------------

def _seal_rec(epoch, device_s, domain="", distributed=False):
    from risingwave_tpu.utils.ledger import LedgerRecord
    return LedgerRecord(epoch=epoch, kind="checkpoint", interval_s=1.0,
                        seconds={"device_compute": device_s},
                        h2d_bytes=0, d2h_bytes=0, warmup=False,
                        distributed=distributed, domain=domain)


def test_mvcosts_split_conserves_and_feeds_history():
    c = MVCosts()
    c.observe_cell("mv_a", 11, 0.03, 100, 10)
    c.observe_cell("mv_b", 11, 0.01, 0, 0)
    extra = c.history_extra(_seal_rec(11, 0.05, domain="d1"))
    assert extra == {"mv_device_s.mv_a": 0.03,
                     "mv_device_s.mv_b": 0.01}
    assert c.gate_violations() == []
    rows = {r[0]: r for r in c.rows()}
    assert rows["mv_a"][1] == "d1"
    assert rows["mv_a"][2] == pytest.approx(0.03)
    assert rows["mv_a"][3] == 100 and rows["mv_a"][4] == 10
    # a split that MINTS device time (sum > domain + 1%) trips the gate
    c.observe_cell("mv_a", 12, 0.08, 0, 0)
    c.history_extra(_seal_rec(12, 0.05))
    assert c.gate_violations()


def test_mvcosts_coverage_windows_both_sides():
    """coverage() sums attributed AND ledgered device time over the
    same sealed-epoch window — including epochs that sealed with NO
    attributed cells (their device time belongs in the denominator,
    or unattributed work would inflate the coverage claim)."""
    c = MVCosts()
    c.observe_cell("mv_a", 21, 0.04, 0, 0)
    c.history_extra(_seal_rec(21, 0.05))
    # a cell-less epoch still lands in the window with 0.0 attributed
    c.history_extra(_seal_rec(22, 0.05))
    att, led = c.coverage()
    assert att == pytest.approx(0.04)
    assert led == pytest.approx(0.10)
    # distributed epochs stay out of the window entirely (their books
    # merge later — the coordinator's own seal undercounts by design)
    c.history_extra(_seal_rec(23, 9.0, distributed=True))
    assert c.coverage() == (pytest.approx(0.04), pytest.approx(0.10))


def test_mvcosts_distributed_epochs_exempt_from_gate():
    c = MVCosts()
    c.observe_cell("mv_a", 5, 0.5, 0, 0)
    c.history_extra(_seal_rec(5, 0.01, distributed=True))
    assert c.gate_violations() == []
    assert c.summary()["mv_a"]["device_s"] == pytest.approx(0.5)


def test_mvcosts_worker_drain_ingest_merges():
    w = MVCosts()
    w.observe_cell("mv_a", 3, 0.2, 50, 0)
    w.history_extra(_seal_rec(3, 0.2, distributed=True))
    w.observe_cell("mv_a", 4, 0.1, 0, 0)     # still pending
    parts = w.drain_dict()
    assert w.summary() == {}                 # a true drain
    coord = MVCosts()
    assert coord.ingest(parts, worker="w0") >= 1
    s = coord.summary()["mv_a"]
    assert s["device_s"] == pytest.approx(0.3)
    assert s["h2d_bytes"] == 50
    # idempotent across rounds: the next drain ships nothing
    assert coord.ingest(w.drain_dict(), worker="w0") == 0


def test_compile_cache_bills_pulling_mv():
    from risingwave_tpu.stream import costs as costs_mod
    cache = CompileCache("test_kind")
    tok = costs_mod.push_mv("mv_first")
    assert cache.get(("k",)) is None
    cache[("k",)] = object()                 # mv_first pays the trace
    assert cache.get(("k",)) is not None     # own hit
    costs_mod.pop_mv(tok)
    tok = costs_mod.push_mv("mv_second")
    assert cache.get(("k",)) is not None     # shared hit
    costs_mod.pop_mv(tok)
    s = COSTS.summary()
    assert s["mv_first"]["compile_misses"] == 1
    assert s["mv_first"]["compile_hits"] == 1
    assert s["mv_first"]["shared_hits"] == 0
    assert s["mv_second"]["compile_hits"] == 1
    assert s["mv_second"]["shared_hits"] == 1


def test_purge_mv_series_clears_every_registry():
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.utils.metrics import STREAMING
    FRESHNESS.register_mv("doomed", ["src"])
    COSTS.observe_cell("doomed", 1, 0.01, 1, 1)
    COSTS.history_extra(_seal_rec(1, 0.01))
    HOTKEYS.bind_fragment("Agg-1", "doomed")
    HOTKEYS.observe("Agg-1", _lanes([1, 1, 2]), None, None)
    TOPOLOGY.bind(77, "doomed")
    TOPOLOGY.record(77, [b"aa"], [(1,)], fixed_nbytes=9)
    COSTS.publish_state_bytes()
    assert any(r[0] == "doomed" for r in COSTS.rows())
    purge_mv_series("doomed")
    assert all(r[0] != "doomed" for r in COSTS.rows())
    assert all(r[0] != "doomed" for r in HOTKEYS.rows())
    assert all(r[1] != "doomed" for r in TOPOLOGY.rows())
    assert "doomed" not in FRESHNESS.summary()
    for fam in (STREAMING.mv_device_seconds, STREAMING.mv_state_bytes,
                STREAMING.mv_transfer_bytes):
        assert all(l.get("mv") != "doomed" for l, *_ in fam.series())


# -- skew verdict in the walker --------------------------------------------

def test_skew_verdict_names_hot_key():
    """Synthetic 90%-one-key stream: the walked bottleneck's diagnosis
    gains a skew:<key> clause (the autoscaler's parallelism veto)."""
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.bottleneck import (
        BOTTLENECKS, SUSTAINED_STREAK,
    )
    from risingwave_tpu.stream.executor import Executor, ExecutorInfo
    from risingwave_tpu.stream.executors.keys import KeyCodec
    from risingwave_tpu.stream.executors.test_utils import MockSource
    from risingwave_tpu.stream.message import (
        StopMutation, is_chunk,
    )
    from risingwave_tpu.stream.monitor import install_monitoring

    sch = Schema([Field("a", DataType.INT64)])
    codec = KeyCodec([DataType.INT64])
    rng = np.random.default_rng(1)
    skewed = np.where(rng.random(256) < 0.9, 7,
                      rng.integers(100, 200, 256))

    class HotAgg(Executor):
        """Burns CPU and sketches its input keys — a hash agg whose
        group key is 90% one value."""

        def __init__(self, input_):
            super().__init__(ExecutorInfo(sch, [0], "HotAgg"))
            self.input = input_

        async def execute(self):
            async for msg in self.input.execute():
                if is_chunk(msg):
                    HOTKEYS.observe(self.identity, _lanes(skewed),
                                    None, codec)
                    t0 = time.perf_counter()
                    while time.perf_counter() - t0 < 0.3:
                        pass
                yield msg

    async def run():
        store = MemoryStateStore()
        local = LocalBarrierManager()
        tx, src = MockSource.channel(sch)
        local.register_sender(5, tx)
        consumer = install_monitoring(HotAgg(src),
                                      fragment="skew-mv", actor_id=5)
        local.set_expected_actors([5])
        actor = Actor(5, consumer, dispatchers=[],
                      barrier_manager=local, fragment="skew-mv")
        loop = BarrierLoop(local, store)
        task = actor.spawn()
        await loop.inject_and_collect(force_checkpoint=True)
        for _ in range(SUSTAINED_STREAK + 1):
            for _ in range(2):      # push each epoch past the walker's
                await src._tx.send(StreamChunk.from_pydict(
                    sch, {"a": [1, 2, 3, 4]}))   # SLOW_INTERVAL_S floor
            await loop.inject_and_collect(force_checkpoint=True)
        summary = BOTTLENECKS.summary().get("(global)", {})
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset({5})))
        await task
        assert actor.failure is None
        return summary

    summary = asyncio.run(run())
    assert summary.get("operator") == "HotAgg", summary
    diag = summary.get("diagnosis", "")
    assert "skew:7" in diag, diag
    assert "parallelism won't help" in diag
    # the surfaced share tracks the seeded 90% within 5pp
    share = HOTKEYS.hot_share("HotAgg", min_share=0.25)[1]
    true_share = float(np.mean(skewed == 7))
    assert abs(share - true_share) <= 0.05


def test_cold_keys_never_fire_skew():
    """A uniform key distribution must not earn a skew clause: the
    guaranteed-share test uses the sketch's LOWER bound."""
    hk = HotKeys()
    hk.observe("Even", _lanes(list(range(500)) * 4), None, None)
    assert hk.hot_share("Even", min_share=0.25) is None


# -- SQL surfaces end-to-end -----------------------------------------------

def test_session_costs_end_to_end():
    """Front door: rw_mv_costs attributes device time and state bytes
    to the MV, rw_state_topology serves per-vnode rows, per-barrier
    history carries mv_device_s.<mv>, the knob flips the hooks off,
    and DROP purges every surface."""
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE MATERIALIZED VIEW cost_mv AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(4)
        costs = await fe.execute("SELECT * FROM rw_mv_costs")
        topo = await fe.execute("SELECT * FROM rw_state_topology")
        hist = await fe.execute("SELECT * FROM rw_metrics_history")
        await fe.execute("SET stream_costs = off")
        from risingwave_tpu.state import topology as topo_mod
        from risingwave_tpu.stream import costs as costs_mod
        from risingwave_tpu.stream import hotkeys as hot_mod
        flags_off = (costs_mod.ENABLED, topo_mod.ENABLED,
                     hot_mod.ENABLED)
        await fe.execute("SET stream_costs = on")
        await fe.execute("DROP MATERIALIZED VIEW cost_mv")
        after = await fe.execute("SELECT * FROM rw_mv_costs")
        await fe.close()
        return costs, topo, hist, flags_off, after

    costs, topo, hist, flags_off, after = asyncio.run(run())
    row = next(r for r in costs if r[0] == "cost_mv")
    assert row[2] >= 0.0                       # device_seconds
    assert row[5] > 0                          # state_bytes
    # topology rows exist for the MV and their bytes reconcile with
    # the cost row's state_bytes column (same books)
    mv_topo = [r for r in topo if r[1] == "cost_mv"]
    assert mv_topo and sum(r[4] for r in mv_topo) == row[5]
    names = {r[4] for r in hist}
    assert "mv_device_s.cost_mv" in names
    assert flags_off == (False, False, False)
    assert all(r[0] != "cost_mv" for r in after)


def test_skewed_source_surfaces_hot_key_share(tmp_path):
    """The ad-ctr acceptance shape: a 90/10-skewed filelog stream's
    GROUP BY surfaces the hot ad in rw_hot_keys with share error
    ≤ 5pp."""
    from risingwave_tpu.frontend import Frontend

    path = str(tmp_path)
    n = 1200
    rng = np.random.default_rng(3)
    ads = np.where(rng.random(n) < 0.9, 7, rng.integers(100, 160, n))
    with open(os.path.join(path, "imp-0.log"), "wb") as f:
        for i in range(n):
            f.write(json.dumps({
                "bid_id": i, "ad_id": int(ads[i]),
                "its": 1_700_000_000_000_000 + i * 10_000,
            }).encode() + b"\n")

    async def run():
        fe = Frontend(rate_limit=8, min_chunks=2)
        await fe.execute(
            f"CREATE SOURCE imp (bid_id BIGINT, ad_id BIGINT, "
            f"its TIMESTAMP) WITH (connector='filelog', "
            f"path='{path}', topic='imp')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW ctr AS SELECT ad_id, "
            "count(*) AS c FROM imp GROUP BY ad_id")
        for _ in range(24):
            await fe.step()
            total = (await fe.execute(
                "SELECT sum(c) FROM ctr"))[0][0]
            if total is not None and int(total) >= n:
                break
        hot = await fe.execute("SELECT * FROM rw_hot_keys")
        await fe.close()
        return hot

    hot = asyncio.run(run())
    true_share = float(np.mean(ads == 7))
    agg_rows = [r for r in hot if r[0] == "ctr" and r[2] == 0]
    assert agg_rows, hot
    r = max(agg_rows, key=lambda r: r[5])
    assert r[3] == "7"                          # decoded key
    assert abs(r[5] - true_share) <= 0.05, r


def test_failed_create_purges_series():
    """A CREATE that deploys far enough to register {mv=...} series
    and THEN fails must purge them before surfacing the failure."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.stream.freshness import FRESHNESS
    from risingwave_tpu.utils.failpoint import failpoints
    from risingwave_tpu.utils.metrics import STREAMING

    async def run():
        fe = Frontend(rate_limit=2)
        await fe.execute(NEXMARK_BID)
        with failpoints({"trace.slow.MaterializeExecutor":
                         RuntimeError("deploy sabotaged")}):
            with pytest.raises(Exception):
                await fe.execute(
                    "CREATE MATERIALIZED VIEW doomed_mv AS SELECT "
                    "auction FROM bid")
        summary = FRESHNESS.summary()
        series = [l for l, *_ in
                  STREAMING.mv_device_seconds.series()]
        try:
            await fe.close()
        except Exception:
            # the sabotaged actor died mid-deploy and its channels are
            # closed — the stop barrier can't reach it. The purge
            # contract (asserted above) is what this test guards.
            pass
        return summary, series

    summary, series = asyncio.run(run())
    assert "doomed_mv" not in summary
    assert all(l.get("mv") != "doomed_mv" for l in series)


# -- ctl cost --------------------------------------------------------------

def test_ctl_cost_verb(tmp_path, capsys):
    """`ctl cost` prints the per-MV cost table and hot keys against a
    recovered data dir."""
    from risingwave_tpu.__main__ import main as cli_main
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    d = str(tmp_path / "rw")

    async def seed():
        fe = Frontend(HummockLite(LocalFsObjectStore(d)), min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW agg AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(4)
        await fe.close()

    asyncio.run(seed())
    with pytest.raises(SystemExit) as e:
        cli_main(["ctl", "--data-dir", d, "cost", "--steps", "2"])
    assert e.value.code == 0
    out = capsys.readouterr().out
    assert "per-MV serving cost" in out
    assert "agg" in out
