"""Remote exchange over a real TCP socket: serde round-trips, delivery
order, barrier/stop semantics, and credit backpressure."""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, StopMutation, Watermark, is_barrier, is_chunk,
)
from risingwave_tpu.stream.remote import (
    ExchangeServer, RemoteInput, decode_chunk, encode_chunk,
)

SCH = Schema.of(k=DataType.INT64, s=DataType.VARCHAR, f=DataType.FLOAT64)


def _chunk(ks, ss, fs, ops=None):
    return StreamChunk.from_pydict(
        SCH, {"k": ks, "s": ss, "f": fs}, ops=ops)


def _barrier(n, mutation=None):
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT, mutation)


def test_chunk_serde_roundtrip():
    c = _chunk([1, 2, 3], ["a", None, "ccc"], [1.5, 2.5, None],
               ops=[1, 2, 1])
    d = decode_chunk(encode_chunk(c), SCH)
    assert d.to_records() == c.to_records()
    assert np.array_equal(np.asarray(d.ops), np.asarray(c.ops))


def test_remote_edge_end_to_end_with_stop():
    async def run():
        srv = ExchangeServer()
        await srv.serve()
        out = srv.register_edge(up=7, down=9)
        rin = RemoteInput("127.0.0.1", srv.port, 7, 9, SCH)

        async def producer():
            await out.send(_barrier(1))
            await out.send(_chunk([1, 2], ["x", "y"], [0.5, 1.5]))
            await out.send(Watermark(0, DataType.INT64, 42))
            await out.send(_chunk([3], ["z"], [2.5]))
            await out.send(_barrier(2, StopMutation(frozenset({9}))))

        prod = asyncio.ensure_future(producer())
        msgs = [m async for m in rin.execute()]
        await prod
        await srv.close()
        return msgs

    msgs = asyncio.run(run())
    kinds = [type(m).__name__ for m in msgs]
    assert kinds == ["Barrier", "StreamChunk", "Watermark", "StreamChunk",
                     "Barrier"]
    assert msgs[1].to_records()[0][1][:2] == (1, "x")
    assert msgs[2].value == 42
    assert msgs[-1].is_stop(9)


def test_credit_backpressure_blocks_sender():
    async def run():
        srv = ExchangeServer()
        await srv.serve()
        out = srv.register_edge(up=1, down=2)
        # tiny credit window, consumer grants one credit per chunk
        rin = RemoteInput("127.0.0.1", srv.port, 1, 2, SCH,
                          initial_credits=2, credit_batch=1)
        sent = []

        async def producer():
            for i in range(6):
                await out.send(_chunk([i], ["v"], [0.0]))
                sent.append(i)
            await out.send(_barrier(1, StopMutation(frozenset({2}))))

        prod = asyncio.ensure_future(producer())
        await asyncio.sleep(0.1)
        # consumer hasn't started: sender must be stuck at the window
        assert len(sent) <= 3          # 2 credits + 1 queued in-flight
        got = []
        async for m in rin.execute():
            if is_chunk(m):
                got.append(m.to_records()[0][1][0])
                await asyncio.sleep(0)
        await prod
        await srv.close()
        return got

    got = asyncio.run(run())
    assert got == [0, 1, 2, 3, 4, 5]


def test_peer_disconnect_fails_sender_loudly():
    """A crashed downstream must error blocked senders, not wedge them
    (a silent stall would hang barrier collection cluster-wide)."""
    async def run():
        srv = ExchangeServer()
        await srv.serve()
        out = srv.register_edge(up=1, down=2)
        rin = RemoteInput("127.0.0.1", srv.port, 1, 2, SCH,
                          initial_credits=1, credit_batch=1)
        agen = rin.execute()
        first = asyncio.ensure_future(agen.__anext__())  # connects
        await out.send(_chunk([1], ["a"], [0.1]))
        await asyncio.wait_for(first, 5)
        await agen.aclose()              # peer "crashes"
        with pytest.raises(ConnectionError):
            for _ in range(10):          # credits are gone: must raise
                await asyncio.wait_for(
                    out.send(_chunk([2], ["b"], [0.2])), 5)
        await srv.close()

    asyncio.run(run())
