"""Chunk compaction + adaptive coalescing (stream/coalesce.py).

Covers the ISSUE-3 acceptance points: U-/U+ pair atomicity across
compaction and coalescer merges, flush-on-barrier (a barrier is never
delayed behind a lingering batch), coalescing across a remote-exchange
serde round-trip, dispatcher output compaction + empty suppression,
exchange credit by true cardinality, and q7 oracle equivalence with
coalescing on vs off (including the device-dispatch amortization the
layer exists for).
"""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream import (
    Barrier, BarrierKind, MergeExecutor, Watermark, channel_for_test,
    is_barrier, is_chunk,
)
from risingwave_tpu.stream.coalesce import (
    ChunkCoalescer, CoalesceExecutor, compact, merge_chunks,
)
from risingwave_tpu.stream.dispatch import HashDispatcher, Output
from risingwave_tpu.stream.executor import ExecutorInfo
from risingwave_tpu.stream.executors import MockSource
from risingwave_tpu.stream.executors.test_utils import (
    collect_until_n_barriers,
)
from risingwave_tpu.stream.remote import decode_chunk, encode_chunk

SCHEMA = Schema.of(k=DataType.INT64, v=DataType.INT64)


def run(coro):
    return asyncio.run(coro)


def barrier(n: int, mutation=None,
            kind=BarrierKind.CHECKPOINT) -> Barrier:
    curr, prev = Epoch.from_physical(n), (
        Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID)
    return Barrier(EpochPair(curr, prev), kind, mutation)


def chunk(ks, vs, ops=None, capacity=None) -> StreamChunk:
    return StreamChunk.from_pydict(SCHEMA, {"k": ks, "v": vs}, ops=ops,
                                   capacity=capacity)


# -- compact ---------------------------------------------------------------


def test_compact_drops_invisible_rows():
    c = chunk(list(range(6)), [10 * i for i in range(6)], capacity=64)
    vis = np.asarray(c.visibility).copy()
    vis[[1, 3, 4]] = False
    sparse = c.with_visibility(vis)
    d = compact(sparse)
    assert d.dense_rows == 3
    assert d.capacity == 8            # next pow2 bucket, not 64
    assert d.to_records() == [(Op.INSERT, (0, 0)), (Op.INSERT, (2, 20)),
                              (Op.INSERT, (5, 50))]


def test_compact_empty_returns_none():
    c = chunk([1, 2], [1, 2])
    empty = c.with_visibility(np.zeros(c.capacity, dtype=bool))
    assert compact(empty) is None


def test_compact_dense_prefix_is_identity():
    c = chunk([1, 2, 3], [1, 2, 3])
    d = compact(c)
    assert d is c
    assert d.dense_rows == 3


def test_compact_update_pair_atomicity():
    # rows: pair A (both visible), pair B (U- visible, U+ masked),
    # pair C (U- masked, U+ visible)
    c = chunk([1, 1, 2, 2, 3, 3], [10, 11, 20, 21, 30, 31],
              ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT,
                   Op.UPDATE_DELETE, Op.UPDATE_INSERT,
                   Op.UPDATE_DELETE, Op.UPDATE_INSERT])
    vis = np.asarray(c.visibility).copy()
    vis[3] = False                    # hide B's U+
    vis[4] = False                    # hide C's U-
    d = compact(c.with_visibility(vis))
    assert d.to_records() == [
        (Op.UPDATE_DELETE, (1, 10)), (Op.UPDATE_INSERT, (1, 11)),
        (Op.DELETE, (2, 20)),         # degraded: half a pair
        (Op.INSERT, (3, 31)),         # degraded: half a pair
    ]


def test_compact_pair_straddling_dense_prefix_boundary():
    """Regression: a dense-prefix chunk in a right-sized bucket whose
    LAST visible row is a U- with its U+ masked must still degrade —
    the identity fast path may not skip the boundary check."""
    c = chunk([1, 1], [10, 11],
              ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT])
    vis = np.asarray(c.visibility).copy()
    vis[1] = False
    d = compact(c.with_visibility(vis))
    assert d.to_records() == [(Op.DELETE, (1, 10))]


def test_merge_chunks_preserves_order_and_pairs():
    a = compact(chunk([1, 1], [10, 11],
                      ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]))
    b = compact(chunk([2], [20], ops=[Op.DELETE]))
    m = merge_chunks([a, b])
    assert m.dense_rows == 3
    assert m.to_records() == [
        (Op.UPDATE_DELETE, (1, 10)), (Op.UPDATE_INSERT, (1, 11)),
        (Op.DELETE, (2, 20))]


def test_merge_chunks_null_validity():
    sch = Schema.of(k=DataType.INT64, s=DataType.VARCHAR)
    a = compact(StreamChunk.from_pydict(sch, {"k": [1], "s": [None]}))
    b = compact(StreamChunk.from_pydict(sch, {"k": [2], "s": ["x"]}))
    m = merge_chunks([a, b])
    assert m.to_records() == [(Op.INSERT, (1, None)),
                              (Op.INSERT, (2, "x"))]


# -- coalescer -------------------------------------------------------------


def test_coalescer_merges_small_chunks_to_target():
    co = ChunkCoalescer(target_rows=8)
    out = []
    for i in range(4):                 # 4 chunks x 3 rows
        out += co.push(chunk([i] * 3, [i] * 3))
    # 3+3 <8, 3+3+3 >=8 → one merged chunk after the 3rd push
    merged = [c for c in out if c is not None]
    assert len(merged) == 1
    assert merged[0].dense_rows == 9
    assert co.buffered_rows == 3       # the 4th chunk lingers
    tail = co.flush()
    assert tail.dense_rows == 3


def test_coalescer_big_chunk_flushes_older_rows_first():
    co = ChunkCoalescer(target_rows=100)
    assert co.push(chunk([1], [1])) == []
    out = co.push(chunk(list(range(200)), list(range(200))))
    assert len(out) == 2
    assert out[0].to_records() == [(Op.INSERT, (1, 1))]   # older first
    assert out[1].dense_rows == 200


def test_coalescer_linger_bound():
    co = ChunkCoalescer(target_rows=1 << 20, max_chunks=4)
    out = []
    for i in range(4):
        out += co.push(chunk([i], [i]))
    assert len(out) == 1 and out[0].dense_rows == 4


def test_coalescer_drops_empty_chunks():
    co = ChunkCoalescer(target_rows=8)
    empty = chunk([1], [1]).with_visibility(np.zeros(8, dtype=bool))
    assert co.push(empty) == []
    assert co.flush() is None


# -- CoalesceExecutor: flush-on-barrier ordering ---------------------------


def test_barrier_never_delayed_behind_lingering_batch():
    """A barrier must flush the buffer and pass IMMEDIATELY — rows of
    epoch N precede barrier N, nothing lingers into epoch N+1."""
    async def go():
        msgs = [barrier(1),
                chunk([1], [10]), chunk([2], [20]),    # below target
                barrier(2),
                chunk([3], [30]),
                barrier(3)]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs),
                              target_rows=1 << 20)    # never self-flush
        out = await collect_until_n_barriers(co, 3)
        kinds = ["B" if is_barrier(m) else "C" for m in out]
        assert kinds == ["B", "C", "B", "C", "B"]
        # epoch-2 rows merged into ONE dense chunk, before barrier 2
        assert out[1].dense_rows == 2
        assert out[1].to_records() == [(Op.INSERT, (1, 10)),
                                       (Op.INSERT, (2, 20))]
        assert out[3].to_records() == [(Op.INSERT, (3, 30))]
    run(go())


def test_watermark_resequences_to_flush_never_past_barrier():
    """A watermark amid buffered rows re-sequences to the flush point
    (monotone bound: later rows already satisfy it) — it is emitted
    after the merged batch and ALWAYS before the next barrier."""
    async def go():
        msgs = [barrier(1), chunk([1], [10]),
                Watermark(0, DataType.INT64, 42),
                chunk([2], [20]), barrier(2)]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs),
                              target_rows=1 << 20)
        out = await collect_until_n_barriers(co, 2)
        types = [type(m).__name__ for m in out]
        assert types == ["Barrier", "StreamChunk", "Watermark",
                         "Barrier"]
        # both rows in one merged batch, then the held watermark
        assert out[1].to_records() == [(Op.INSERT, (1, 10)),
                                       (Op.INSERT, (2, 20))]
        assert out[2].value == 42
    run(go())


def test_watermark_passes_through_when_buffer_empty():
    async def go():
        msgs = [barrier(1), Watermark(0, DataType.INT64, 7),
                chunk([1], [10]), barrier(2)]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs),
                              target_rows=1 << 20)
        out = await collect_until_n_barriers(co, 2)
        types = [type(m).__name__ for m in out]
        assert types == ["Barrier", "Watermark", "StreamChunk",
                         "Barrier"]
    run(go())


def test_held_watermarks_keep_only_latest_per_column():
    async def go():
        msgs = [barrier(1), chunk([1], [10]),
                Watermark(0, DataType.INT64, 5),
                chunk([2], [20]),
                Watermark(0, DataType.INT64, 9),
                barrier(2)]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs),
                              target_rows=1 << 20)
        out = await collect_until_n_barriers(co, 2)
        wms = [m.value for m in out if isinstance(m, Watermark)]
        assert wms == [9]               # monotone: newest subsumes
    run(go())


def test_coalescer_pair_atomicity_across_merges():
    """Pairs never split across coalescer output chunks: merging is
    whole-chunk only, so a surviving pair stays adjacent."""
    async def go():
        msgs = [barrier(1),
                chunk([1, 1], [10, 11],
                      ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
                chunk([2, 2], [20, 21],
                      ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
                barrier(2)]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs), target_rows=4)
        out = await collect_until_n_barriers(co, 2)
        chunks = [m for m in out if is_chunk(m)]
        recs = [r for c in chunks for r in c.to_records()]
        assert recs == [
            (Op.UPDATE_DELETE, (1, 10)), (Op.UPDATE_INSERT, (1, 11)),
            (Op.UPDATE_DELETE, (2, 20)), (Op.UPDATE_INSERT, (2, 21))]
        for c in chunks:               # each pair intact within a chunk
            ops = [op for op, _ in c.to_records()]
            for i, op in enumerate(ops):
                if op == Op.UPDATE_DELETE:
                    assert ops[i + 1] == Op.UPDATE_INSERT
    run(go())


# -- MergeExecutor coalescing ---------------------------------------------


def test_merge_executor_coalesces_between_barriers():
    async def go():
        tx1, rx1 = channel_for_test()
        tx2, rx2 = channel_for_test()
        merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"),
                              [rx1, rx2], coalesce_rows=1 << 20)

        async def feed():
            await tx1.send(chunk([1], [1]))
            await tx2.send(chunk([2], [2]))
            await tx1.send(barrier(1))
            await tx2.send(barrier(1))
            tx1.close()
            tx2.close()

        feeder = asyncio.ensure_future(feed())
        out = await collect_until_n_barriers(merge, 1)
        await feeder
        kinds = ["B" if is_barrier(m) else "C" for m in out]
        assert kinds == ["C", "B"]       # both rows in ONE dense chunk
        assert out[0].dense_rows == 2
        assert sorted(r for _op, r in out[0].to_records()) == \
            [(1, 1), (2, 2)]
    run(go())


# -- wire path -------------------------------------------------------------


def test_encode_chunk_compacts_sparse_chunks():
    c = chunk(list(range(8)), list(range(8)), capacity=256)
    vis = np.asarray(c.visibility).copy()
    vis[2:] = False                     # 2 visible of 256 capacity
    sparse = c.with_visibility(vis)
    data = encode_chunk(sparse)
    full = encode_chunk(chunk(list(range(256)), list(range(256))))
    assert len(data) < len(full) / 8    # wire shrinks with the rows
    d = decode_chunk(data, SCHEMA)
    assert d.capacity == 8              # wire carries the pow2 bucket
    assert d.to_records() == [(Op.INSERT, (0, 0)), (Op.INSERT, (1, 1))]


def test_remote_roundtrip_of_coalesced_chunk():
    co = ChunkCoalescer(target_rows=4)
    outs = co.push(chunk([1, 1], [10, 11],
                         ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]))
    outs += co.push(chunk([2], [20], ops=[Op.DELETE]))
    outs += [co.flush()]
    merged = [c for c in outs if c is not None]
    assert len(merged) == 1
    d = decode_chunk(encode_chunk(merged[0]), SCHEMA)
    assert d.to_records() == merged[0].to_records()


# -- dispatcher compaction + suppression ----------------------------------


def test_hash_dispatch_slices_arrive_compacted():
    async def go():
        chans = [channel_for_test() for _ in range(4)]
        outputs = [Output(i, tx) for i, (tx, _) in enumerate(chans)]
        disp = HashDispatcher(outputs, dist_key_indices=[0])
        ks = list(range(64))
        c = chunk(ks, [i * 10 for i in ks], capacity=1024)
        await disp.dispatch_data(c)
        total = 0
        seen = {}
        for i, (_tx, rx) in enumerate(chans):
            sub = await rx.recv()
            # every slice is DENSE: known cardinality, pow2 capacity,
            # full-prefix visibility
            assert sub.dense_rows == sub.cardinality() > 0
            assert sub.capacity < 1024
            total += sub.dense_rows
            for _, (k, _v) in sub.to_records():
                assert seen.setdefault(k, i) == i
        assert total == 64
    run(go())


def test_hash_dispatch_suppresses_empty_slices():
    async def go():
        chans = [channel_for_test() for _ in range(2)]
        outputs = [Output(i, tx) for i, (tx, _) in enumerate(chans)]
        disp = HashDispatcher(outputs, dist_key_indices=[0])
        # ALL rows route to one output: pick keys owned by output 0
        probe = chunk(list(range(32)), [0] * 32)
        owner = disp._route(probe)
        mine = [k for k in range(32) if owner[k] == 0][:4]
        await disp.dispatch_data(chunk(mine, [1] * len(mine)))
        got = await chans[0][1].recv()
        assert got.dense_rows == len(mine)
        # output 1 received NOTHING (not an empty chunk)
        assert chans[1][1].try_recv() is None
    run(go())


def test_exchange_credit_charges_true_cardinality():
    """A compacted 4-row chunk costs 4 permits, not its capacity."""
    async def go():
        from risingwave_tpu.stream.exchange import channel
        tx, rx = channel(chunk_permits=16, barrier_permits=2,
                         max_chunk_cost=8)
        dense = compact(chunk([1, 2], [1, 2], capacity=64)
                        .with_visibility(
                            np.r_[np.ones(2, bool),
                                  np.zeros(62, bool)]))
        assert dense.dense_rows == 2
        # capacity-costed this would be 8 each (max_chunk_cost) and
        # block after 2 sends; true-cardinality costing fits 8 of them
        for _ in range(8):
            await asyncio.wait_for(tx.send(dense), 1.0)
        blocked = asyncio.ensure_future(tx.send(dense))
        await asyncio.sleep(0.01)
        assert not blocked.done()
        await rx.recv()
        await asyncio.wait_for(blocked, 1.0)
    run(go())


# -- monitor strict mode ---------------------------------------------------


def test_monitored_executor_rejects_empty_emission_in_strict_mode():
    from risingwave_tpu.stream.monitor import MonitoredExecutor

    async def go():
        empty = chunk([1], [1]).with_visibility(
            np.zeros(8, dtype=bool))
        src = MockSource(SCHEMA, [barrier(1), empty, barrier(2)])
        mon = MonitoredExecutor(src, "t", 1, 0)
        with pytest.raises(AssertionError):
            await collect_until_n_barriers(mon, 2)
    run(go())


# -- oracle equivalence: q7 with coalescing on vs off ----------------------


def _run_q7(coalesce_rows):
    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import (
        build_q7, drive_to_completion,
    )
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.utils.metrics import STREAMING

    cfg = NexmarkConfig(event_num=4000, max_chunk_size=128,
                        generate_strings=False)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=8, min_chunks=8,
                 watermark_delay=Interval(usecs=0),
                 coalesce_rows=coalesce_rows)
    before = sum(v for _l, v in STREAMING.device_dispatch.series())
    asyncio.run(drive_to_completion(p, {1: 4000 * 46 // 50},
                                    in_flight=1))
    after = sum(v for _l, v in STREAMING.device_dispatch.series())
    rows = sorted(tuple(r) for _pk, r in p.mv_table.iter_rows())
    return rows, after - before


def test_q7_oracle_identical_with_coalescing_on_vs_off():
    rows_off, disp_off = _run_q7(None)
    rows_on, disp_on = _run_q7(2048)
    assert rows_on == rows_off          # bit-identical MV state
    # the whole point: materially fewer device dispatches (128-row
    # source chunks coalesce toward 2048-row batches)
    assert disp_on < disp_off, (disp_on, disp_off)
    assert disp_on <= disp_off * 0.75, (disp_on, disp_off)


# -- oracle equivalence: q4 through the SQL front door ---------------------


def _run_q4(target_rows):
    from risingwave_tpu.frontend.session import Frontend

    async def go():
        fe = Frontend(rate_limit=16, min_chunks=16)
        await fe.execute(
            f"SET stream_chunk_target_rows = {target_rows}")
        for t in ("auction", "bid"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', nexmark.event.num=2000, "
                f"nexmark.max.chunk.size=128, "
                f"nexmark.generate.strings='false')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q4 AS "
            "SELECT category, AVG(final) AS avg_final FROM ("
            "  SELECT a.category AS category, MAX(b.price) AS final"
            "  FROM auction AS a JOIN bid AS b ON a.id = b.auction"
            "  WHERE b.date_time BETWEEN a.date_time AND a.expires"
            "  GROUP BY a.id, a.category) AS q "
            "GROUP BY category")
        await fe.step(8)
        rows = await fe.execute("SELECT * FROM q4")
        await fe.close()
        return sorted(rows)

    return asyncio.run(go())


def test_q4_oracle_identical_with_coalescing_on_vs_off():
    rows_off = _run_q4(0)               # coalescing disabled
    rows_on = _run_q4(4096)             # default-on path
    assert rows_on == rows_off
    assert rows_on, "q4 must produce output at this scale"


# -- knob plumbing: distributed path --------------------------------------


def test_fragmenter_cut_edges_carry_coalesce_knob():
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.stream.coalesce import DEFAULT_TARGET_ROWS

    f_off = Fragmenter(2, merge_coalesce_rows=0)
    f_off._new_fragment(1)
    fi, _ni = f_off._cut(0, [0], SCHEMA, 2)
    assert f_off.graph.fragments[fi].inputs[0].coalesce_rows == 0

    f_on = Fragmenter(2)                 # session default rides along
    f_on._new_fragment(1)
    fi, _ni = f_on._cut(0, [0], SCHEMA, 2)
    assert f_on.graph.fragments[fi].inputs[0].coalesce_rows == \
        DEFAULT_TARGET_ROWS


def test_dist_frontend_accepts_coalesce_session_vars():
    import tempfile

    from risingwave_tpu.cluster.session import DistFrontend

    async def go():
        with tempfile.TemporaryDirectory() as root:
            fe = DistFrontend(root)      # no cluster start needed
            assert await fe.execute(
                "SET stream_chunk_target_rows = 0") == "SET"
            assert await fe.execute(
                "SHOW stream_chunk_target_rows") == [("0",)]
            assert fe.chunk_target_rows == 0
    run(go())


def test_merge_executor_resequences_watermarks():
    """Aligned watermarks must not force a fan-in flush (a
    watermark-per-chunk upstream would otherwise re-fragment every
    batch); they re-sequence to the flush and precede the barrier."""
    async def go():
        tx1, rx1 = channel_for_test()
        tx2, rx2 = channel_for_test()
        merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"),
                              [rx1, rx2], coalesce_rows=1 << 20)

        async def feed():
            await tx1.send(chunk([1], [1]))
            await tx1.send(Watermark(0, DataType.INT64, 50))
            await tx2.send(chunk([2], [2]))
            await tx2.send(Watermark(0, DataType.INT64, 60))
            await tx1.send(barrier(1))
            await tx2.send(barrier(1))
            tx1.close()
            tx2.close()

        feeder = asyncio.ensure_future(feed())
        out = await collect_until_n_barriers(merge, 1)
        await feeder
        types = [type(m).__name__ for m in out]
        # ONE merged chunk, then the aligned (min) watermark, then
        # the barrier — no per-watermark flush fragmentation
        assert types == ["StreamChunk", "Watermark", "Barrier"], types
        assert out[0].dense_rows == 2
        assert out[1].value == 50
    run(go())


def test_merge_never_leaks_pre_barrier_data_past_the_barrier():
    """Regression (found while wiring coalescing): messages still in
    the merge queue when the last input parks must drain BEFORE the
    aligned barrier — with or without coalescing."""
    async def go():
        for rows in (None, 1 << 20):     # un-coalesced and coalesced
            tx1, rx1 = channel_for_test()
            tx2, rx2 = channel_for_test()
            merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"),
                                  [rx1, rx2], coalesce_rows=rows)
            # burst everything before the consumer runs at all
            for k in range(5):
                await tx1.send(chunk([k], [k]))
                await tx2.send(chunk([10 + k], [k]))
            await tx1.send(barrier(1))
            await tx2.send(barrier(1))
            tx1.close()
            tx2.close()
            out = await collect_until_n_barriers(merge, 1)
            data = [r for m in out if is_chunk(m)
                    for _op, r in m.to_records()]
            assert is_barrier(out[-1])
            assert len(data) == 10, (rows, data)
    run(go())


def test_coalesce_executor_flushes_on_end_of_stream():
    """A bounded upstream that ends without a trailing barrier must
    not lose the lingering buffer."""
    async def go():
        msgs = [barrier(1), chunk([1], [10]), chunk([2], [20])]
        co = CoalesceExecutor(MockSource(SCHEMA, msgs),
                              target_rows=1 << 20)
        out = [m async for m in co.execute()]
        chunks = [m for m in out if is_chunk(m)]
        assert len(chunks) == 1 and chunks[0].dense_rows == 2
    run(go())


def test_encode_zero_visible_chunk_is_tiny():
    big = chunk(list(range(100)), list(range(100)), capacity=4096)
    empty = big.with_visibility(np.zeros(4096, dtype=bool))
    data = encode_chunk(empty)
    d = decode_chunk(data, SCHEMA)
    assert d.capacity == 8 and d.to_records() == []
