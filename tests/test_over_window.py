"""OverWindowExecutor vs a per-row python oracle (pg default frame).

Mirrors the reference's over-window executor tests
(src/stream/src/executor/over_window/general.rs test mod): scripted and
random retractable streams, changelog materialized and compared against
a full recompute, plus recovery from the state table.
"""

import asyncio
from collections import Counter

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr.window import WindowCall, WindowFuncKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.over_window import OverWindowExecutor
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

S = Schema.of(p=DataType.INT64, o=DataType.INT64, v=DataType.INT64,
              k=DataType.INT64)   # partition, order, value, pk


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def chunk(ps, os_, vs, ks, ops=None):
    return StreamChunk.from_pydict(
        S, {"p": ps, "o": os_, "v": vs, "k": ks}, ops=ops)


CALLS = [WindowCall(WindowFuncKind.ROW_NUMBER),
         WindowCall(WindowFuncKind.RANK),
         WindowCall(WindowFuncKind.DENSE_RANK),
         WindowCall(WindowFuncKind.SUM, input_idx=2),
         WindowCall(WindowFuncKind.MAX, input_idx=2),
         WindowCall(WindowFuncKind.LAG, input_idx=2, offset=1),
         WindowCall(WindowFuncKind.LEAD, input_idx=2, offset=2),
         WindowCall(WindowFuncKind.COUNT, input_idx=2),
         WindowCall(WindowFuncKind.FIRST_VALUE, input_idx=2),
         WindowCall(WindowFuncKind.LAST_VALUE, input_idx=2)]


def oracle(rows, desc=False):
    """Full per-row recompute with pg default-frame semantics."""
    out = {}
    parts = {}
    for r in rows:
        parts.setdefault(r[0], []).append(r)
    for p, rs in parts.items():
        rs.sort(key=lambda r: (-r[1] if desc else r[1], r[3]))
        n = len(rs)
        okeys = [r[1] for r in rs]
        for i, r in enumerate(rs):
            peers_end = max(j for j in range(n)
                            if okeys[j] == okeys[i]
                            and all(okeys[t] == okeys[i]
                                    for t in range(min(i, j),
                                                   max(i, j) + 1))) + 1
            # simpler: last index with equal okey in the contiguous run
            j = i
            while j + 1 < n and okeys[j + 1] == okeys[i]:
                j += 1
            peers_end = j + 1
            frame = rs[:peers_end]
            vals = [x[2] for x in frame if x[2] is not None]
            rank = next(j for j in range(n) if okeys[j] == okeys[i]) + 1
            dr = len(set(okeys[:i])) + (0 if i and okeys[i] in
                                        okeys[:i] else 1)
            dense = len({okeys[j] for j in range(i + 1)})
            out[r[3]] = r + (
                i + 1, rank, dense,
                sum(vals) if vals else None,
                max(vals) if vals else None,
                rs[i - 1][2] if i >= 1 else None,
                rs[i + 2][2] if i + 2 < n else None,
                len(vals),
                rs[0][2],
                rs[peers_end - 1][2])
    return out


def materialize(msgs):
    view = {}
    for m in msgs:
        if not is_chunk(m):
            continue
        for op, row in m.to_records():
            k = row[3]
            if op.is_insert:
                view[k] = tuple(row)
            else:
                assert view.pop(k) == tuple(row)
    return view


def run_exec(script, n_barriers, store=None, table_id=31):
    store = store or MemoryStateStore()
    # state pk = partition | order | input pk
    st = StateTable(table_id, S, [0, 1, 3], store, dist_key_indices=[0])
    ex = OverWindowExecutor(MockSource(S, script), [0], [(1, False)],
                            CALLS, st)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, store


def test_over_window_basic_inserts():
    script = [barrier(1),
              chunk([1, 1, 2], [10, 20, 5], [100, 200, 7], [1, 2, 3]),
              barrier(2)]
    msgs, _ = run_exec(script, 2)
    got = materialize(msgs)
    want = oracle([(1, 10, 100, 1), (1, 20, 200, 2), (2, 5, 7, 3)])
    assert got == want


def test_over_window_insert_shifts_row_numbers():
    """A row inserted BEFORE existing rows must update their outputs
    (row_number/rank shift, cumulative sums grow)."""
    script = [barrier(1),
              chunk([1, 1], [20, 30], [200, 300], [1, 2]), barrier(2),
              chunk([1], [10], [100], [3]), barrier(3)]
    msgs, _ = run_exec(script, 3)
    got = materialize(msgs)
    want = oracle([(1, 20, 200, 1), (1, 30, 300, 2), (1, 10, 100, 3)])
    assert got == want


def test_over_window_delete_and_peers():
    """Deletes shift later rows; ORDER BY peers share rank and frame."""
    rows = [(1, 10, 1, 1), (1, 10, 2, 2), (1, 20, 3, 3),
            (1, 20, None, 4), (1, 30, 5, 5)]
    script = [barrier(1),
              chunk(*[list(c) for c in zip(*rows)]), barrier(2),
              chunk([1], [10], [1], [1], ops=[Op.DELETE]), barrier(3)]
    msgs, _ = run_exec(script, 3)
    got = materialize(msgs)
    want = oracle([r for r in rows if r[3] != 1])
    assert got == want


def test_over_window_random_stream_oracle():
    rng = np.random.default_rng(5)
    live = {}
    script = [barrier(1)]
    b = 2
    nk = 0
    for _ in range(6):
        ps, os_, vs, ks, ops = [], [], [], [], []
        for _ in range(20):
            if live and rng.random() < 0.3:
                k = int(rng.choice(list(live)))
                p, o, v = live.pop(k)
                ps.append(p); os_.append(o); vs.append(v); ks.append(k)
                ops.append(Op.DELETE)
            else:
                p = int(rng.integers(0, 4))
                o = int(rng.integers(0, 15))
                v = None if rng.random() < 0.1 else int(
                    rng.integers(0, 100))
                k = nk
                nk += 1
                live[k] = (p, o, v)
                ps.append(p); os_.append(o); vs.append(v); ks.append(k)
                ops.append(Op.INSERT)
        script.append(chunk(ps, os_, vs, ks, ops=ops))
        script.append(barrier(b))
        b += 1
    msgs, _ = run_exec(script, b - 1)
    got = materialize(msgs)
    want = oracle([(p, o, v, k) for k, (p, o, v) in live.items()])
    assert got == want


def test_over_window_desc_order():
    store = MemoryStateStore()
    st = StateTable(32, S, [0, 1, 3], store, dist_key_indices=[0])
    ex = OverWindowExecutor(
        MockSource(S, [barrier(1),
                       chunk([1, 1, 1], [10, 30, 20], [1, 3, 2],
                             [1, 2, 3]),
                       barrier(2)]),
        [0], [(1, True)], [WindowCall(WindowFuncKind.ROW_NUMBER),
                           WindowCall(WindowFuncKind.SUM, input_idx=2)],
        st)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    got = {}
    for m in msgs:
        if is_chunk(m):
            for op, r in m.to_records():
                if op.is_insert:
                    got[r[3]] = (r[4], r[5])
    # DESC: o=30 first (rn 1, sum 3), o=20 (rn 2, sum 5), o=10 (rn 3, 6)
    assert got == {2: (1, 3), 3: (2, 5), 1: (3, 6)}


def test_over_window_recovery_resumes():
    """Fresh executor over the same state table recomputes outputs and
    applies further deltas correctly."""
    store = MemoryStateStore()
    msgs1, _ = run_exec(
        [barrier(1), chunk([1, 1], [20, 30], [200, 300], [1, 2]),
         barrier(2)], 2, store=store)
    view = materialize(msgs1)
    msgs2, _ = run_exec(
        [barrier(3), chunk([1], [10], [100], [3]), barrier(4)],
        2, store=store)
    for m in msgs2:
        if is_chunk(m):
            for op, row in m.to_records():
                if op.is_insert:
                    view[row[3]] = tuple(row)
                else:
                    assert view.pop(row[3]) == tuple(row)
    want = oracle([(1, 20, 200, 1), (1, 30, 300, 2), (1, 10, 100, 3)])
    assert view == want


# -- SQL surface ----------------------------------------------------------


def test_sql_over_window_oracle():
    """row_number/rank/sum/lag OVER from SQL, checked against a full
    recompute (reference parity: e2e over-window slt tests)."""
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW w AS SELECT auction, price, "
            "row_number() OVER (PARTITION BY auction ORDER BY price "
            "DESC) AS rn, rank() OVER (PARTITION BY auction ORDER BY "
            "price DESC) AS rk, sum(price) OVER (PARTITION BY auction "
            "ORDER BY price DESC) AS s, lag(price) OVER (PARTITION BY "
            "auction ORDER BY price DESC) AS lg FROM bid")
        for _ in range(12):
            await fe.step()
        rows = await fe.execute("SELECT * FROM w")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    assert len(rows) > 1000
    parts = {}
    for a, p, rn, rk, s, lg, *_rid in rows:
        parts.setdefault(a, []).append((p, rn, rk, s, lg))
    for a, lst in parts.items():
        lst.sort(key=lambda t: t[1])
        prices = sorted((p for p, *_ in lst), reverse=True)
        for i, (p, rn, rk, s, lg) in enumerate(lst):
            assert p == prices[i] and rn == i + 1
            j = i
            while j + 1 < len(prices) and prices[j + 1] == prices[i]:
                j += 1
            first = i
            while first > 0 and prices[first - 1] == prices[i]:
                first -= 1
            assert rk == first + 1
            assert s == sum(prices[:j + 1])
            assert lg == (prices[i - 1] if i else None)


def test_sql_over_window_recovery():
    """DDL-log replay redeploys the window MV and resumes exactly."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(store=HummockLite(obj), min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW w AS SELECT auction, price, "
            "row_number() OVER (PARTITION BY auction ORDER BY price "
            "DESC) AS rn FROM bid")
        for _ in range(4):
            await fe.step()
        await fe.close()

    async def phase2():
        fe = Frontend(store=HummockLite(obj), min_chunks=2)
        await fe.recover()
        for _ in range(16):
            await fe.step()
        rows = await fe.execute("SELECT * FROM w")
        await fe.close()
        return rows

    asyncio.run(phase1())
    rows = asyncio.run(phase2())
    parts = {}
    for a, p, rn, *_rid in rows:
        parts.setdefault(a, []).append((p, rn))
    assert sum(len(v) for v in parts.values()) == len(rows)
    for a, lst in parts.items():
        lst.sort(key=lambda t: t[1])
        prices = sorted((p for p, _ in lst), reverse=True)
        assert [rn for _p, rn in lst] == list(range(1, len(lst) + 1))
        assert [p for p, _rn in lst] == prices


def test_over_window_partition_move_delete_before_insert():
    """A row whose PARTITION key changes within one epoch must emit
    its old-partition DELETE before its new-partition INSERT, or a
    pk-keyed downstream nets the row to deleted (review r4)."""
    script = [barrier(1),
              chunk([1, 2], [10, 10], [100, 200], [1, 2]), barrier(2),
              # pk 1 moves partition 1 -> 2 (update pair)
              chunk([1, 2], [10, 10], [100, 100], [1, 1],
                    ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
              barrier(3)]
    msgs, _ = run_exec(script, 3)
    got = materialize(msgs)     # materialize() asserts D-before-I
    want = oracle([(2, 10, 100, 1), (2, 10, 200, 2)])
    assert got == want


def test_over_window_null_order_keys_pg_defaults():
    """pg defaults: ASC = NULLS LAST, DESC = NULLS FIRST."""
    def run(desc):
        store = MemoryStateStore()
        st = StateTable(33, S, [0, 1, 3], store, dist_key_indices=[0])
        ex = OverWindowExecutor(
            MockSource(S, [barrier(1),
                           chunk([1, 1, 1], [10, None, 20], [1, 2, 3],
                                 [1, 2, 3]),
                           barrier(2)]),
            [0], [(1, desc)], [WindowCall(WindowFuncKind.ROW_NUMBER)],
            st)
        msgs = asyncio.run(collect_until_n_barriers(ex, 2))
        got = {}
        for m in msgs:
            if is_chunk(m):
                for op, r in m.to_records():
                    if op.is_insert:
                        got[r[3]] = r[4]
        return got

    assert run(False) == {1: 1, 3: 2, 2: 3}   # ASC: NULL last
    assert run(True) == {2: 1, 3: 2, 1: 3}    # DESC: NULL first


def test_filter_clause_on_window_function_rejected():
    """FILTER (WHERE ...) OVER must error, not silently ignore the
    predicate (regression: it used to compute the unfiltered window)."""
    import asyncio

    import pytest

    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500)")
        with pytest.raises(Exception, match="FILTER"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW w AS SELECT count(*) "
                "FILTER (WHERE price < 10000) OVER (PARTITION BY "
                "auction ORDER BY date_time) AS c FROM bid")
        await fe.close()

    asyncio.run(run())
