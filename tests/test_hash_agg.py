"""HashAggExecutor vs host oracles: retractions, nulls, recovery, q7 shape.

Mirrors the reference's hash_agg tests (src/stream/src/executor/
hash_agg.rs test mod): scripted chunks through MockSource, change-chunk
emission asserted per barrier, state table contents asserted at commit.
"""

import asyncio
from collections import defaultdict

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_agg import (
    AggCall, HashAggExecutor, agg_state_schema,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

SCHEMA = Schema.of(g=DataType.INT64, v=DataType.INT64)


def barrier(n: int) -> Barrier:
    curr = Epoch.from_physical(n)
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(curr, prev), BarrierKind.CHECKPOINT)


def chunk(gs, vs, ops=None) -> StreamChunk:
    return StreamChunk.from_pydict(SCHEMA, {"g": gs, "v": vs}, ops=ops)


def build(messages, agg_calls, append_only=False, store=None):
    store = store if store is not None else MemoryStateStore()
    src = MockSource(SCHEMA, messages)
    sschema, spk = agg_state_schema(SCHEMA, [0], agg_calls)
    table = StateTable(10, sschema, spk, store, dist_key_indices=[0])
    minput = {}
    if not append_only:
        from risingwave_tpu.stream.executors.hash_agg import (
            minput_state_schema,
        )
        from risingwave_tpu.ops.hash_agg import AggKind as _K
        for j, call in enumerate(agg_calls):
            if call.kind in (_K.MIN, _K.MAX):
                msch, mpk, mdk = minput_state_schema(SCHEMA, [0], call)
                minput[j] = StateTable(100 + j, msch, mpk, store,
                                       dist_key_indices=mdk)
    ex = HashAggExecutor(src, [0], agg_calls, table,
                         append_only=append_only, minput_tables=minput)
    return ex, table, store


class Oracle:
    """Reference semantics: per-group count/sum/min/max over a changelog."""

    def __init__(self):
        self.rows = defaultdict(list)   # group → multiset of values

    def apply(self, records):
        for op, (g, v) in records:
            if op.is_insert:
                self.rows[g].append(v)
            else:
                self.rows[g].remove(v)
                if not self.rows[g]:
                    del self.rows[g]

    def result(self, kinds):
        out = {}
        for g, vals in self.rows.items():
            nn = [v for v in vals if v is not None]
            row = []
            for k in kinds:
                if k == "count*":
                    row.append(len(vals))
                elif k == "count":
                    row.append(len(nn))
                elif k == "sum":
                    row.append(sum(nn) if nn else None)
                elif k == "min":
                    row.append(min(nn) if nn else None)
                elif k == "max":
                    row.append(max(nn) if nn else None)
            out[g] = tuple(row)
        return out


def materialized_view(messages):
    """Replay emitted agg chunks into a dict (group → outputs)."""
    view = {}
    for m in messages:
        if not is_chunk(m):
            continue
        for op, row in m.to_records():
            g, outs = row[0], tuple(row[1:])
            if op.is_insert:
                view[g] = outs
            else:
                assert view.get(g) == outs, \
                    f"delete of non-current row {g}: {outs} vs {view.get(g)}"
                if op == Op.DELETE:
                    del view[g]
    return view


def run_case(script, agg_calls, kinds, append_only=False, n_barriers=None):
    """Drive executor over the script; after each barrier the materialized
    emission must equal the oracle."""
    n_barriers = n_barriers or sum(
        1 for m in script if isinstance(m, Barrier))
    ex, table, store = build(script, agg_calls, append_only)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    oracle = Oracle()
    for m in script:
        if isinstance(m, StreamChunk):
            oracle.apply(m.to_records())
    assert materialized_view(msgs) == oracle.result(kinds)
    return msgs, table


def test_count_sum_insert_only():
    script = [barrier(1),
              chunk([1, 1, 2], [10, 20, 5]),
              barrier(2),
              chunk([2, 3], [7, 100]),
              barrier(3)]
    msgs, _ = run_case(script, [AggCall(AggKind.COUNT),
                                AggCall(AggKind.SUM, 1)],
                       ["count*", "sum"])
    # first barrier emits pure inserts
    chunks = [m for m in msgs if is_chunk(m)]
    assert {r[1][0] for r in chunks[0].to_records()} == {1, 2}
    assert all(op == Op.INSERT for op, _ in chunks[0].to_records())
    # second barrier: group 2 updates (pair), group 3 inserts
    recs = chunks[1].to_records()
    by_op = defaultdict(list)
    for op, row in recs:
        by_op[op].append(row)
    assert [r[0] for r in by_op[Op.INSERT]] == [3]
    assert [r[0] for r in by_op[Op.UPDATE_DELETE]] == [2]
    assert by_op[Op.UPDATE_DELETE][0][1:] == (1, 5)
    assert by_op[Op.UPDATE_INSERT][0][1:] == (2, 12)


def test_retraction_to_zero_emits_delete():
    script = [barrier(1),
              chunk([1, 1], [10, 20]),
              barrier(2),
              chunk([1, 1], [10, 20], ops=[Op.DELETE, Op.DELETE]),
              barrier(3)]
    msgs, table = run_case(script, [AggCall(AggKind.COUNT),
                                    AggCall(AggKind.SUM, 1)],
                           ["count*", "sum"])
    chunks = [m for m in msgs if is_chunk(m)]
    assert [op for op, _ in chunks[-1].to_records()] == [Op.DELETE]
    # state table row is gone too
    assert list(table.iter_rows()) == []


def test_group_create_delete_within_epoch_emits_nothing():
    script = [barrier(1),
              chunk([9], [1]),
              chunk([9], [1], ops=[Op.DELETE]),
              barrier(2)]
    msgs, _ = run_case(script, [AggCall(AggKind.COUNT)], ["count*"])
    assert [m for m in msgs if is_chunk(m)] == []


def test_null_inputs_and_null_group_key():
    script = [barrier(1),
              StreamChunk.from_pydict(
                  SCHEMA, {"g": [1, 1, None], "v": [None, 3, 8]}),
              barrier(2)]
    msgs, _ = run_case(script,
                       [AggCall(AggKind.COUNT),          # count(*)
                        AggCall(AggKind.COUNT, 1),       # count(v)
                        AggCall(AggKind.SUM, 1)],
                       ["count*", "count", "sum"])
    view = materialized_view(msgs)
    assert view[1] == (2, 1, 3)
    assert view[None] == (1, 1, 8)


def test_max_append_only_q7_shape():
    rng = np.random.default_rng(3)
    script = [barrier(1)]
    for e in range(5):
        for _ in range(3):
            g = rng.integers(0, 6, 64).tolist()
            v = rng.integers(0, 10_000, 64).tolist()
            script.append(chunk(g, v))
        script.append(barrier(e + 2))
    msgs, _ = run_case(script,
                       [AggCall(AggKind.MAX, 1), AggCall(AggKind.COUNT)],
                       ["max", "count*"], append_only=True)


def test_retractable_max_with_deletes_matches_oracle():
    """The minput path: deletes that remove the current extreme force a
    recompute from the materialized value multiset."""
    script = [
        barrier(1),
        chunk([1, 1, 1, 2], [5, 9, 7, 3]),
        barrier(2),
        # delete the max of group 1 (9) and the only row of group 2
        chunk([1, 2], [9, 3], ops=[2, 2]),
        barrier(3),
        # delete ANOTHER max (7) and add a smaller value
        chunk([1, 1], [7, 6], ops=[2, 1]),
        barrier(4),
    ]
    run_case(script, [AggCall(AggKind.MAX, 1), AggCall(AggKind.MIN, 1),
                      AggCall(AggKind.COUNT)],
             ["max", "min", "count*"])


def test_retractable_minmax_random_oracle():
    rng = np.random.default_rng(31)
    live = []
    script = [barrier(1)]
    for e in range(2, 8):
        gs, vs, ops = [], [], []
        for _ in range(40):
            if live and rng.random() < 0.4:
                i = rng.integers(0, len(live))
                g, v = live.pop(int(i))
                gs.append(g); vs.append(v); ops.append(2)
            else:
                g = int(rng.integers(0, 5))
                v = int(rng.integers(-50, 50))
                live.append((g, v))
                gs.append(g); vs.append(v); ops.append(1)
        script.append(chunk(gs, vs, ops=ops))
        script.append(barrier(e))
    run_case(script, [AggCall(AggKind.MAX, 1), AggCall(AggKind.MIN, 1),
                      AggCall(AggKind.SUM, 1)], ["max", "min", "sum"])


def test_retractable_max_recovers_from_state():
    """Recovery mid-stream: minput + value state rebuild, then a delete
    of the pre-recovery max must still recompute correctly."""
    store = MemoryStateStore()
    ex, table, store = build(
        [barrier(1), chunk([1, 1], [10, 20]), barrier(2)],
        [AggCall(AggKind.MAX, 1)], store=store)
    asyncio.run(collect_until_n_barriers(ex, 2))
    store.seal_epoch(Epoch.from_physical(1).value, True)
    store.sync(Epoch.from_physical(1).value)
    # "restart": fresh executor over the same store; delete the max
    ex2, table2, _ = build(
        [barrier(2), chunk([1], [20], ops=[2]),
         barrier(3)],
        [AggCall(AggKind.MAX, 1)], store=store)
    msgs = asyncio.run(collect_until_n_barriers(ex2, 2))
    # recovery marked the group emitted, so the delete emits an update
    # pair retracting the stale max; the corrected value persists
    from risingwave_tpu.common.chunk import Op as _Op
    recs = [(op, row) for m in msgs if is_chunk(m)
            for op, row in m.to_records()]
    assert (_Op.UPDATE_DELETE, (1, 20)) in recs
    assert (_Op.UPDATE_INSERT, (1, 10)) in recs
    rows = {pk[0]: row for pk, row in table2.iter_rows()}
    assert rows[1][2] == 10


def test_random_stream_oracle_sum_count():
    """Randomized insert/delete stream with duplicates across chunks."""
    rng = np.random.default_rng(11)
    live = []                  # (g, v) multiset for valid deletes
    script = [barrier(1)]
    b = 2
    for _ in range(8):
        for _ in range(2):
            gs, vs, ops = [], [], []
            for _ in range(32):
                if live and rng.random() < 0.4:
                    i = rng.integers(0, len(live))
                    g, v = live.pop(int(i))
                    gs.append(g)
                    vs.append(v)
                    ops.append(Op.DELETE)
                else:
                    g = int(rng.integers(0, 10))
                    v = int(rng.integers(-50, 50))
                    live.append((g, v))
                    gs.append(g)
                    vs.append(v)
                    ops.append(Op.INSERT)
            script.append(chunk(gs, vs, ops=ops))
        script.append(barrier(b))
        b += 1
    run_case(script, [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 1),
                      AggCall(AggKind.COUNT, 1)],
             ["count*", "sum", "count"])


def test_recovery_resumes_from_state_table():
    store = MemoryStateStore()
    calls = [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 1)]
    sschema, spk = agg_state_schema(SCHEMA, [0], calls)

    script1 = [barrier(1), chunk([1, 2], [10, 20]), barrier(2)]
    src1 = MockSource(SCHEMA, script1)
    t1 = StateTable(10, sschema, spk, store, dist_key_indices=[0])
    ex1 = HashAggExecutor(src1, [0], calls, t1)
    asyncio.run(collect_until_n_barriers(ex1, 2))

    # new executor over the same store: must see groups 1,2 and emit
    # UPDATE (not INSERT) when they change
    script2 = [barrier(3), chunk([1, 3], [5, 7]), barrier(4)]
    src2 = MockSource(SCHEMA, script2)
    t2 = StateTable(10, sschema, spk, store, dist_key_indices=[0])
    ex2 = HashAggExecutor(src2, [0], calls, t2)
    msgs = asyncio.run(collect_until_n_barriers(ex2, 2))
    chunks = [m for m in msgs if is_chunk(m)]
    assert len(chunks) == 1
    ops = defaultdict(list)
    for op, row in chunks[0].to_records():
        ops[op].append(row)
    assert [r[0] for r in ops[Op.INSERT]] == [3]
    assert [r[0] for r in ops[Op.UPDATE_DELETE]] == [1]
    assert ops[Op.UPDATE_INSERT][0][1:] == (2, 15)


def test_growth_under_many_groups():
    """More groups than MIN_CAPACITY*load forces rehash mid-stream."""
    from risingwave_tpu.ops.hash_table import MIN_CAPACITY
    n = MIN_CAPACITY  # > 0.7*cap ⇒ at least one growth
    script = [barrier(1)]
    for start in range(0, n, 256):
        gs = list(range(start, start + 256))
        script.append(chunk(gs, [1] * 256))
    script.append(barrier(2))
    ex, table, _ = build(script, [AggCall(AggKind.SUM, 1)])
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert ex.kernel.capacity > MIN_CAPACITY
    view = materialized_view(msgs)
    assert len(view) == n
    assert all(view[g] == (1,) for g in range(n))


def test_flush_buffer_overflow_retries():
    """flush_capacity=1 forces the header-compare/double/refetch path on
    every barrier with >1 dirty group."""
    from risingwave_tpu.ops import lanes
    from risingwave_tpu.ops.hash_agg import (
        AggKind as K, AggSpec, GroupedAggKernel,
    )
    specs = (AggSpec(K.SUM, np.dtype(np.int64)), AggSpec(K.COUNT))
    kern = GroupedAggKernel(key_width=2, specs=specs, flush_capacity=1)
    n = 64
    gk = (np.arange(n, dtype=np.int64) % 13) * 1_000_000
    hi, lo = lanes.split_i64(gk)
    vals = np.arange(n, dtype=np.int64)
    kern.apply(np.stack([hi, lo], axis=1),
               np.ones(n, dtype=np.int32), np.ones(n, dtype=bool),
               ((specs[0].encode_input(vals), np.ones(n, dtype=bool)),
                ((), None)))
    fr = kern.flush()
    assert fr.n == 13
    assert kern._flush_cap >= 13
    # decoded sums must match a host oracle despite the retry
    want = {g: int(vals[gk == g * 1_000_000].sum()) for g in range(13)}
    got = {int(lanes.merge_i64(fr.keys[r, 0:1], fr.keys[r, 1:2])[0])
           // 1_000_000: int(fr.outs[0][r]) for r in range(fr.n)}
    assert got == want
    kern.advance()
    assert not bool(np.asarray(kern.state.dirty).any())


def test_retractable_max_rejected_without_minput():
    src = MockSource(SCHEMA, [])
    sschema, spk = agg_state_schema(SCHEMA, [0], [AggCall(AggKind.MAX, 1)])
    t = StateTable(10, sschema, spk, MemoryStateStore(),
                   dist_key_indices=[0])
    with pytest.raises(ValueError):
        HashAggExecutor(src, [0], [AggCall(AggKind.MAX, 1)], t)


def test_varchar_group_keys_streaming_tpch_q1_shape():
    """Streaming TPC-H q1's GROUP BY l_returnflag, l_linestatus —
    varchar group keys through the interning KeyCodec (VERDICT r2 #5:
    previously rejected outright). Checked against a host oracle,
    including NULL keys as their own group."""
    import asyncio
    from collections import defaultdict

    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors.hash_agg import (
        AggCall, HashAggExecutor, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.test_utils import (
        MockSource, collect_until_n_barriers,
    )
    from risingwave_tpu.common.chunk import StreamChunk
    from tests.test_operators import barrier

    schema = Schema.of(flag=DataType.VARCHAR, status=DataType.VARCHAR,
                       qty=DataType.INT64)
    rng = np.random.default_rng(3)
    flags = ["A", "N", "R", None]
    statuses = ["F", "O"]
    rows = [(flags[rng.integers(0, 4)], statuses[rng.integers(0, 2)],
             int(rng.integers(1, 100))) for _ in range(500)]
    script = [barrier(1)]
    for lo in range(0, 500, 100):
        part = rows[lo:lo + 100]
        script.append(StreamChunk.from_pydict(schema, {
            "flag": [r[0] for r in part],
            "status": [r[1] for r in part],
            "qty": [r[2] for r in part]}))
        script.append(barrier(lo // 100 + 2))
    store = MemoryStateStore()
    calls = [AggCall(AggKind.SUM, 2), AggCall(AggKind.COUNT)]
    sch, pk = agg_state_schema(schema, [0, 1], calls)
    table = StateTable(31, sch, pk, store)
    ex = HashAggExecutor(MockSource(schema, script), [0, 1], calls,
                         table, append_only=True)
    msgs = asyncio.run(collect_until_n_barriers(ex, 6))
    # accumulate the changelog into final rows
    final = {}
    for m in msgs:
        if hasattr(m, "to_records"):
            for op, row in m.to_records():
                if op.is_insert:
                    final[row[:2]] = row[2:]
                elif row[:2] in final and final[row[:2]] == row[2:]:
                    del final[row[:2]]
    oracle = defaultdict(lambda: [0, 0])
    for f, s, q in rows:
        oracle[(f, s)][0] += q
        oracle[(f, s)][1] += 1
    assert final == {k: (v[0], v[1]) for k, v in oracle.items()}
    # the state table persisted the string keys durably
    assert len(list(table.iter_rows())) == len(oracle)
    assert {pk[:2] for pk, _r in table.iter_rows()} == set(oracle)


def test_bytea_group_keys_with_nulls():
    """BYTEA keys intern with a type-consistent fill (str fill would
    crash np.unique's sort)."""
    from risingwave_tpu.common.types import DataType
    from risingwave_tpu.stream.executors.keys import KeyCodec

    codec = KeyCodec([DataType.BYTEA])
    vals = np.asarray([b"a", None, b"b", b"a"], dtype=object)
    lanes_ = codec.build_arrays([(vals, None)])
    assert lanes_[0].tolist() == lanes_[3].tolist()   # b"a" == b"a"
    assert lanes_[1][2] == 0                          # NULL lane
    decoded = codec.decode(lanes_)
    v, ok = decoded[0]
    assert v[0] == b"a" and v[2] == b"b" and not ok[1]


def _run_distinct_case(script, n_barriers, store=None):
    from risingwave_tpu.stream.executors.hash_agg import (
        minput_state_schema,
    )
    store = store if store is not None else MemoryStateStore()
    calls = [AggCall(AggKind.COUNT, 1, distinct=True),
             AggCall(AggKind.SUM, 1, distinct=True),
             AggCall(AggKind.COUNT, 1)]
    sschema, spk = agg_state_schema(SCHEMA, [0], calls)
    table = StateTable(50, sschema, spk, store, dist_key_indices=[0])
    dsch, dpk, ddk = minput_state_schema(SCHEMA, [0], calls[0])
    dt_tables = {1: StateTable(51, dsch, dpk, store,
                               dist_key_indices=ddk)}
    ex = HashAggExecutor(MockSource(SCHEMA, script), [0], calls, table,
                         append_only=False, distinct_tables=dt_tables)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, store


def test_distinct_count_sum():
    """count(DISTINCT v), sum(DISTINCT v) vs plain count(v), with
    duplicates within and across chunks (distinct.rs semantics)."""
    script = [barrier(1),
              chunk([1, 1, 1, 2], [10, 10, 20, 10]),
              barrier(2),
              chunk([1, 2], [10, 10]),     # more duplicates
              barrier(3)]
    msgs, _ = _run_distinct_case(script, 3)
    view = materialized_view(msgs)
    assert view[1] == (2, 30, 4)    # distinct {10,20}; 4 raw rows
    assert view[2] == (1, 10, 2)


def test_distinct_retraction_and_recovery():
    """Retracting one duplicate keeps the distinct count; retracting
    the last occurrence drops it. A fresh executor over the same store
    reloads the dedup multiset."""
    store = MemoryStateStore()
    script = [barrier(1),
              chunk([1, 1, 1], [10, 10, 20]),
              barrier(2),
              chunk([1], [10], ops=[Op.DELETE]),     # dup remains
              barrier(3)]
    msgs, store = _run_distinct_case(script, 3, store=store)
    view = materialized_view(msgs)
    assert view[1] == (2, 30, 2)
    # restart: new executor, retract the last 10 — distinct drops to 1
    script2 = [barrier(4),
               chunk([1], [10], ops=[Op.DELETE]),
               barrier(5)]
    _msgs2, store = _run_distinct_case(script2, 2, store=store)
    # final value state: (g, rows, cnt_distinct, sum_distinct, nn, cnt)
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.common.types import DataType, Schema
    calls = [AggCall(AggKind.COUNT, 1, distinct=True),
             AggCall(AggKind.SUM, 1, distinct=True),
             AggCall(AggKind.COUNT, 1)]
    sschema, spk = agg_state_schema(SCHEMA, [0], calls)
    t = StateTable(50, sschema, spk, store, dist_key_indices=[0])
    rows = {pk[0]: row for pk, row in _state_rows_of(t)}
    assert rows[1][2] == 1 and rows[1][3] == 20   # distinct {20}


def _state_rows_of(table):
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    table.init_epoch(EpochPair(Epoch.from_physical(99),
                               Epoch.from_physical(98)))
    return list(table.iter_rows())


# -- approx_count_distinct (HyperLogLog) ----------------------------------


def test_hll_primitives_dense_accuracy():
    """Dense 2^14-register sketch (VERDICT r4 #8): error < 2% at 1M
    distinct keys (standard error 1.04/sqrt(2^14) ≈ 0.8%), and the
    small-range linear-counting correction stays tight."""
    from risingwave_tpu.ops.hash_agg import (
        HLL_M, _clz64, hll_estimate_dense, hll_lanes,
    )

    assert HLL_M >= 1 << 14
    assert _clz64(np.asarray([1], np.uint64))[0] == 63
    assert _clz64(np.asarray([0], np.uint64))[0] == 64
    assert _clz64(np.asarray([1 << 63], np.uint64))[0] == 0
    for n, tol in ((100, 0.05), (10_000, 0.03), (1_000_000, 0.02)):
        reg, rho = hll_lanes(np.arange(n, dtype=np.int64))
        arr = np.zeros(HLL_M, dtype=np.uint8)
        np.maximum.at(arr, reg, rho.astype(np.uint8))
        est = int(hll_estimate_dense(arr)[0])
        assert abs(est - n) / n < tol, (n, est)


def test_approx_count_distinct_sql_and_recovery():
    """ACD from SQL: per-group estimates near exact distincts, and the
    packed registers recover exactly across a restart."""
    import asyncio

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()
    n_events = 6000

    async def phase1():
        fe = Frontend(store=HummockLite(obj), min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            f"nexmark.table.type='bid', nexmark.event.num={n_events}, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT auction, "
            "approx_count_distinct(bidder) AS acd, count(*) AS c "
            "FROM bid GROUP BY auction")
        for _ in range(4):
            await fe.step()
        await fe.close()

    async def phase2():
        fe = Frontend(store=HummockLite(obj), min_chunks=4)
        await fe.recover()
        for _ in range(16):
            await fe.step()
        rows = await fe.execute("SELECT * FROM a")
        await fe.close()
        return rows

    asyncio.run(phase1())
    rows = asyncio.run(phase2())
    cfg = NexmarkConfig(event_num=n_events, max_chunk_size=256)
    bids = gen_bids(np.arange(n_events * 46 // 50, dtype=np.int64), cfg)
    import collections
    d = collections.defaultdict(set)
    c = collections.Counter()
    for a, b in zip(bids["auction"].tolist(), bids["bidder"].tolist()):
        d[a].add(b)
        c[a] += 1
    bad = 0
    for a, acd, cnt in rows:
        assert cnt == c[a]          # exact counts survive recovery
        exact = len(d[a])
        if abs(acd - exact) > max(3, 0.7 * exact):
            bad += 1
    assert len(rows) == len(d) and bad < 0.05 * len(rows)


def test_approx_count_distinct_rejects_retracting_upstream():
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m1 AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        with pytest.raises(Exception, match="append-only"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW m2 AS SELECT c, "
                "approx_count_distinct(auction) AS n FROM m1 "
                "GROUP BY c")
        await fe.close()

    asyncio.run(run())


# -- string_agg / array_agg (host-path aggs) ------------------------------


def test_string_agg_array_agg_sql_oracle_and_retraction():
    """Host aggs over the value multiset, from SQL, incl. a RETRACTING
    upstream (GROUP BY over an updating MV): the composed string/list
    must drop retracted members (VERDICT r3 #9: string_agg/array_agg
    were wholly missing)."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=4000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m1 AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        # string_agg over a RETRACTING upstream: auctions move between
        # c-groups as counts grow
        await fe.execute(
            "CREATE MATERIALIZED VIEW m2 AS SELECT c, "
            "array_agg(auction) AS members FROM m1 GROUP BY c")
        for _ in range(20):
            await fe.step()
        m1 = await fe.execute("SELECT * FROM m1")
        m2 = await fe.execute("SELECT * FROM m2")
        await fe.close()
        return m1, m2

    m1, m2 = asyncio.run(run())
    want = {}
    for a, c in m1:
        want.setdefault(c, []).append(a)
    got = {c: members for c, members in m2}
    assert got == {c: tuple(sorted(v)) for c, v in want.items()}


def test_string_agg_recovery():
    import asyncio

    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(store=HummockLite(obj), min_chunks=2)
        await fe.execute(
            "CREATE SOURCE p WITH (connector='nexmark', "
            "nexmark.table.type='person', nexmark.event.num=4000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW s AS SELECT state, "
            "string_agg(city, '|') AS cities FROM p GROUP BY state")
        for _ in range(3):
            await fe.step()
        await fe.close()

    async def phase2():
        fe = Frontend(store=HummockLite(obj), min_chunks=2)
        await fe.recover()
        for _ in range(12):
            await fe.step()
        rows = await fe.execute("SELECT * FROM s")
        await fe.close()
        return rows

    asyncio.run(phase1())
    rows = asyncio.run(phase2())
    from risingwave_tpu.connectors.nexmark import (
        NexmarkConfig, gen_persons,
    )
    cfg = NexmarkConfig(table_type="person", event_num=4000)
    ps = gen_persons(np.arange(4000 // 50, dtype=np.int64), cfg)
    want = {}
    for st, city in zip(ps["state"].tolist(), ps["city"].tolist()):
        want.setdefault(st, []).append(city)
    assert {st: c for st, c in rows} == {
        st: "|".join(sorted(v)) for st, v in want.items()}


def test_approx_count_distinct_varchar_group_key():
    """ACD grouped by an interned VARCHAR column — the flush path must
    handle decoded (plain python str) group keys."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT channel, "
            "approx_count_distinct(bidder) AS acd FROM bid "
            "GROUP BY channel")
        for _ in range(6):
            await fe.step()
        rows = await fe.execute("SELECT * FROM a")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    cfg = NexmarkConfig(event_num=3000)
    bids = gen_bids(np.arange(3000 * 46 // 50, dtype=np.int64), cfg)
    import collections
    d = collections.defaultdict(set)
    for ch, b in zip(bids["channel"], bids["bidder"].tolist()):
        d[ch].add(b)
    got = {ch: acd for ch, acd in rows}
    assert set(got) == set(d)
    for ch, exact in ((k, len(v)) for k, v in d.items()):
        assert abs(got[ch] - exact) <= max(2, 0.05 * exact), \
            (ch, got[ch], exact)
