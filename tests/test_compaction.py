"""Dedicated compactor subsystem (ISSUE 19), end to end.

The tentpole's acceptance, white-box and black-box: multi-level
pickers choose tasks off a level snapshot; ``reserve_task`` freezes a
task's inputs and burns a durable output-id block while serving
commits land concurrently; ``apply_version_delta`` is
compare-and-commit; pinned readers survive any number of compactions
landing mid-scan (pin-exact GC); a crash between the version delta
and the vacuum leaves no dangling manifest refs; and with
``storage_compaction = 'dedicated'`` the barrier/commit path carries
ZERO ``compact()`` frames while the MV stays bit-identical to the
inline oracle arm — including under the two compactor chaos schedules
(SIGKILL mid-task, storage fault during vacuum), which must converge
with zero SERVING-domain recoveries.
"""

import asyncio

import pytest

from risingwave_tpu.frontend.planner import PlanError
from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.meta.compaction import (
    clear_compaction_log, compaction_rows, parse_compaction, pick_l0,
    pick_size_ratio, pick_task, pick_tombstone,
)
from risingwave_tpu.meta.supervisor import clear_recovery_log
from risingwave_tpu.storage.compactor import execute_task
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import (
    LocalFsObjectStore, MemObjectStore,
)
from risingwave_tpu.utils.failpoint import failpoints


def E(n: int) -> int:
    return n << 16


def _checkpoint(store, epoch):
    store.seal_epoch(epoch, True)
    store.sync(epoch)


def _churn(h, epochs, keys=50, table=1):
    """One full-keyspace overwrite per epoch: each checkpoint lands
    one L0 run, the compaction pressure the pickers watch."""
    for e in epochs:
        h.ingest_batch(table, [(b"k%03d" % i, (e, i))
                               for i in range(keys)], E(e))
        _checkpoint(h, E(e))


@pytest.fixture(autouse=True)
def _fresh_logs():
    clear_compaction_log()
    clear_recovery_log()
    yield
    clear_compaction_log()
    clear_recovery_log()


# -- parse + pickers (pure units) ---------------------------------------


def test_parse_compaction_modes():
    assert parse_compaction("inline") == "inline"
    assert parse_compaction("DEDICATED") == "dedicated"
    with pytest.raises(PlanError):
        parse_compaction("sideways")


def _info(sid, lo, hi, size=100, count=10, tombstones=0):
    # smallest/largest are hex FULL keys: user key + 8-byte inverted
    # epoch suffix (the pickers strip the suffix before comparing)
    return {"id": sid, "smallest": (lo + bytes(8)).hex(),
            "largest": (hi + bytes(8)).hex(), "size": size,
            "count": count, "tombstones": tombstones}


def test_pick_l0_threshold_overlap_and_reservations():
    l0 = [_info(i, b"a", b"z") for i in range(1, 5)]
    l1 = [_info(9, b"a", b"m"), _info(10, b"n", b"z")]
    t = pick_l0({"l0": l0, "l1": l1, "reserved": []})
    assert t is not None and t["picker"] == "l0" and t["bottom"]
    assert [i["id"] for i in t["inputs_l0"]] == [1, 2, 3, 4]
    assert [i["id"] for i in t["inputs_l1"]] == [9, 10]
    # below threshold → no task; any frozen input → no task
    assert pick_l0({"l0": l0[:3], "l1": l1, "reserved": []}) is None
    assert pick_l0({"l0": l0, "l1": l1, "reserved": [9]}) is None
    # disjoint L1 runs outside the L0 key range stay untouched
    far = _info(11, b"zz", b"zzz")
    t = pick_l0({"l0": l0, "l1": l1 + [far], "reserved": []})
    assert far["id"] not in [i["id"] for i in t["inputs_l1"]]


def test_pick_size_ratio_and_tombstone_reclaim():
    l1 = [_info(5, b"a", b"z", size=1000)]
    big = [_info(1, b"a", b"m", size=300), _info(2, b"a", b"m",
                                                 size=200)]
    t = pick_size_ratio({"l0": big, "l1": l1, "reserved": []})
    assert t is not None and t["picker"] == "size_ratio"
    small = [_info(1, b"a", b"m", size=10), _info(2, b"a", b"m",
                                                  size=20)]
    assert pick_size_ratio({"l0": small, "l1": l1,
                            "reserved": []}) is None
    dense = _info(7, b"a", b"z", count=10, tombstones=4)
    t = pick_tombstone({"l0": [], "l1": [dense], "reserved": []})
    assert t is not None and t["picker"] == "tombstone"
    assert t["inputs_l1"] == [dense] and t["inputs_l0"] == []
    # a reserved run is never re-picked, by any picker
    assert pick_task({"l0": [], "l1": [dense], "reserved": [7]}) \
        is None


# -- reservation protocol against a live store --------------------------


def test_reserve_execute_apply_with_concurrent_commits():
    h = HummockLite(MemObjectStore())
    h.compaction_mode = "dedicated"
    _churn(h, range(1, 5))
    snap = h.level_snapshot()
    assert len(snap["l0"]) == 4 and not snap["l1"]
    picked = pick_task(snap)
    assert picked is not None
    ids = [i["id"] for i in picked["inputs_l0"] + picked["inputs_l1"]]
    grant = h.reserve_task(ids, id_block=8)
    # frozen inputs: an overlapping second reservation is refused
    with pytest.raises(ValueError):
        h.reserve_task(ids[:1], id_block=8)
    # a serving commit lands CONCURRENTLY — not in the frozen set
    h.ingest_batch(1, [(b"k000", (99, 0))], E(5))
    _checkpoint(h, E(5))
    result = execute_task(h.obj, {
        **picked, "safe_epoch": grant["safe_epoch"],
        "output_base": grant["output_base"],
        "output_cap": grant["output_cap"]})
    out_ids = [i["id"] for i in result["outputs"]]
    assert out_ids and all(
        grant["output_base"] <= i
        < grant["output_base"] + grant["output_cap"] for i in out_ids)
    h.apply_version_delta(ids, result["outputs"])
    snap2 = h.level_snapshot()
    assert [i["id"] for i in snap2["l1"]] == out_ids
    # only the concurrent commit's run remains in L0
    assert len(snap2["l0"]) == 1
    assert snap2["l0"][0]["id"] not in ids
    # reads see the merged history AND the concurrent write
    assert h.get(1, b"k000", E(5)) == (99, 0)
    assert h.get(1, b"k001", E(5)) == (4, 1)
    # compare-and-commit: replaying the same delta conflicts
    with pytest.raises(ValueError):
        h.apply_version_delta(ids, result["outputs"])


def test_abort_releases_reservation_and_burns_ids():
    h = HummockLite(MemObjectStore())
    h.compaction_mode = "dedicated"
    _churn(h, range(1, 5))
    ids = [i["id"] for i in h.level_snapshot()["l0"]]
    g1 = h.reserve_task(ids, id_block=8)
    h.abort_task(ids, [])
    assert h.level_snapshot()["reserved"] == []
    # the aborted grant's id block stays burned: a crashed compactor
    # that uploaded outputs can never race a later allocation
    g2 = h.reserve_task(ids, id_block=8)
    assert g2["output_base"] >= g1["output_base"] + 8
    h.abort_task(ids, [])


# -- pin-exact GC -------------------------------------------------------


def test_iterator_opened_before_compaction_reads_old_version():
    """The satellite's pin-safety case: a scan that starts before a
    compaction commits reads its snapshot to completion even after
    TWO further compactions, and the vacuum frees the replaced
    objects only once the scan closes."""
    h = HummockLite(MemObjectStore())
    _churn(h, (1, 2, 3), keys=20)
    expected = [(b"k%03d" % i, (3, i)) for i in range(20)]
    it = h.iter(1, E(3))
    assert next(it) == expected[0]          # pins the version here
    old_ids = [i["id"] for i in h.level_snapshot()["l0"]]
    # compaction #1 (4th L0 run trips the inline trigger) ...
    _churn(h, (4,), keys=20)
    assert h.level_snapshot()["l1"], "first compaction landed"
    # ... and #2 (four more runs over the new L1)
    _churn(h, (5, 6, 7, 8), keys=20)
    assert h._retired, "replaced objects await the pinned reader"
    assert all(h.obj.exists(f"data/{sid}.sst") for sid in old_ids)
    # the open scan still reads the OLD snapshot, bit-exactly
    assert list(it) == expected[1:]
    # exhaustion unpinned → the vacuum drains every retired object
    h.maybe_vacuum()
    assert h._retired == []
    assert not any(h.obj.exists(f"data/{sid}.sst") for sid in old_ids)
    # and the current version still serves the newest data
    assert h.get(1, b"k000", E(8)) == (8, 0)


def test_storage_fault_during_vacuum_only_delays_gc():
    h = HummockLite(MemObjectStore())
    _churn(h, (1, 2, 3), keys=20)
    with failpoints({"hummock.vacuum": OSError("chaos vacuum fault")}):
        _churn(h, (4,), keys=20)      # trips compact; vacuum faults
        snap = h.level_snapshot()
        assert snap["l1"], "the commit must never fail on GC"
        assert h._retired, "GC delayed, not lost"
        kept = [ent["id"] for ent in h._retired]
        assert all(h.obj.exists(f"data/{sid}.sst") for sid in kept)
    # the next unarmed pass drains the backlog
    assert h.maybe_vacuum() == len(kept)
    assert h._retired == []
    assert not any(h.obj.exists(f"data/{sid}.sst") for sid in kept)


def test_crash_between_delta_and_vacuum_no_dangling_refs(tmp_path):
    obj = LocalFsObjectStore(str(tmp_path))
    h = HummockLite(obj)
    h.compaction_mode = "dedicated"
    _churn(h, range(1, 5))
    picked = pick_task(h.level_snapshot())
    ids = [i["id"] for i in picked["inputs_l0"] + picked["inputs_l1"]]
    grant = h.reserve_task(ids, id_block=8)
    result = execute_task(obj, {
        **picked, "safe_epoch": grant["safe_epoch"],
        "output_base": grant["output_base"],
        "output_cap": grant["output_cap"]})
    # the delta commits; the generation dies before its vacuum runs
    with failpoints({"hummock.vacuum": OSError("crash window")}):
        h.apply_version_delta(ids, result["outputs"])
    assert h._retired
    # recover a FRESH store over the same objects (the crash survivor)
    h2 = HummockLite(obj)
    snap = h2.level_snapshot()
    assert [i["id"] for i in snap["l1"]] == \
        [i["id"] for i in result["outputs"]]
    for info in snap["l0"] + snap["l1"]:
        assert obj.exists(f"data/{info['id']}.sst"), \
            "manifest references a missing object"
    # recovery GC removes the dead generation's residue ONLY
    assert h2.vacuum_orphans() == len(ids)
    for info in snap["l0"] + snap["l1"]:
        assert obj.exists(f"data/{info['id']}.sst")
    assert h2.get(1, b"k001", E(4)) == (4, 1)


# -- the session arms: zero compact() frames, bit-identical MV ----------


EVENTS = 12000
SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num={n}, "
       "nexmark.max.chunk.size=512)")
MV = ("CREATE MATERIALIZED VIEW q7 AS "
      "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
      "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
      "GROUP BY window_start")


def _run_arm(mode: str):
    async def run():
        store = HummockLite(MemObjectStore())
        calls = {"n": 0}
        orig = store.compact

        def counted():
            calls["n"] += 1
            return orig()

        store.compact = counted
        fe = Frontend(store, min_chunks=4)
        try:
            await fe.execute(f"SET storage_compaction = '{mode}'")
            await fe.execute(SRC.format(n=EVENTS))
            await fe.execute(MV)
            await fe.step(30)
            rows = {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
            states = [s for (s,) in await fe.execute(
                "SELECT state FROM rw_compaction")]
            return rows, calls["n"], states, store.level_snapshot()
        finally:
            await fe.close()

    return asyncio.run(run())


def test_dedicated_arm_zero_compact_frames_bit_identical():
    """The tentpole acceptance, white-box: with
    ``storage_compaction='dedicated'`` the commit path carries ZERO
    ``compact()`` frames, the level topology still shrinks (merges
    land via ``apply_version_delta``), and the MV is bit-identical to
    the inline oracle arm."""
    rows_inline, calls_inline, _st, _snap = _run_arm("inline")
    assert calls_inline >= 1, "the oracle arm must actually compact"
    clear_compaction_log()
    rows_ded, calls_ded, states, snap = _run_arm("dedicated")
    assert rows_ded == rows_inline
    assert calls_ded == 0
    assert states.count("applied") >= 1, \
        "merges must land OFF-path through the task manager"
    # the applied deltas kept the read path shallow: L0 below the
    # trigger after an off-path merge absorbed the older runs
    assert snap["l1"], "off-path merge produced a leveled run"


def test_rw_compaction_rows_shape():
    """The system-table payload is the task ledger, column-stable."""
    h = HummockLite(MemObjectStore())
    h.compaction_mode = "dedicated"
    _churn(h, range(1, 5))
    picked = pick_task(h.level_snapshot())
    ids = [i["id"] for i in picked["inputs_l0"]]

    from risingwave_tpu.meta.compaction import (
        CompactionManager, CompactorHooks,
    )
    from risingwave_tpu.storage.compactor import InProcessCompactor

    comp = InProcessCompactor(h.obj)
    mgr = CompactionManager()
    mgr.add_namespace("local", CompactorHooks(
        snapshot=h.level_snapshot, reserve=h.reserve_task,
        apply=h.apply_version_delta, abort=h.abort_task,
        execute=comp.submit))

    async def drive():
        await mgr.tick()            # dispatch
        await mgr.drain()           # settle the in-flight merge
    asyncio.run(drive())
    comp.close()
    rows = compaction_rows()
    assert rows, "the dispatched task must appear in the ledger"
    tid, ns, picker, state, ins, outs, br, bw, att, dur, det = rows[-1]
    assert ns == "local" and picker == "l0" and state == "applied"
    assert sorted(int(i) for i in ins.split(",")) == sorted(ids)
    assert outs and br > 0 and bw > 0 and att == 1 and dur >= 0.0


# -- chaos: the compactor rides its own ladder --------------------------


def _oracle_rows(events: int):
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(SRC.format(n=events))
        await fe.execute(MV)
        await fe.step(40)
        rows = await fe.execute("SELECT * FROM q7")
        await fe.close()
        return {tuple(r) for r in rows}

    return asyncio.run(run())


def test_compactor_chaos_converges_zero_serving_recoveries(tmp_path):
    """Satellite 4 acceptance: the two compactor fault kinds —
    SIGKILL mid-task and a storage fault during vacuum — against a
    2-worker dedicated-compaction cluster. The MV converges
    bit-identical to the fault-free in-process oracle, the compactor
    respawns, and rw_recovery carries NO serving-domain entry (only
    ``compactor_dead`` rows are allowed)."""
    from risingwave_tpu.cluster.chaos import run_chaos
    from risingwave_tpu.cluster.session import DistFrontend

    events = 24000
    expect = _oracle_rows(events)

    async def run():
        fe = DistFrontend(str(tmp_path / "c"), n_workers=2,
                          parallelism=2, barrier_timeout_s=30.0)
        await fe.start()
        try:
            await fe.execute("SET storage_compaction = 'dedicated'")
            await fe.execute(SRC.format(n=events))
            await fe.execute(MV)
            report = await run_chaos(
                fe, seed=11, steps=12, settle_steps=48,
                kinds=["kill_compactor_mid_task",
                       "storage_fault_during_vacuum"])
            rows = {tuple(r)
                    for r in await fe.execute("SELECT * FROM q7")}
            rec = await fe.execute(
                "SELECT cause, action, ok FROM rw_recovery")
            states = [s for (s,) in await fe.execute(
                "SELECT state FROM rw_compaction")]
            return report, rows, rec, states, \
                fe.cluster.compactor_respawns
        finally:
            await fe.close()

    report, rows, rec, states, respawns = asyncio.run(run())
    assert rows == expect
    assert {k for _s, k, _w in report.events} == {
        "kill_compactor_mid_task", "storage_fault_during_vacuum"}
    # the SIGKILL forced a respawn of the compactor role
    assert respawns >= 1
    # compaction kept landing off-path despite both faults
    assert states.count("applied") >= 1
    # THE invariant: zero serving-domain recoveries — every recovery
    # row (if any) is a compactor-domain requeue
    serving = [r for r in rec if r[0] != "compactor_dead"]
    assert serving == []
