"""Plan IR: expression serde, the IR→executor factory, and SHIPPED
plans on a real worker process (the StreamNode-shipping path —
VERDICT r3 weak #7: the two-node deployment was a hand-wired demo)."""

import asyncio
import json

import pytest

from risingwave_tpu.common.types import DataType, Interval, Schema
from risingwave_tpu.expr.expr import (
    BinaryOp, Case, Cast, FuncCall, InputRef, Literal, UnaryOp,
    tumble_start,
)
from risingwave_tpu.stream.plan_ir import (
    build_fragment, expr_from_ir, expr_to_ir, schema_from_ir,
    schema_to_ir,
)


def test_expr_ir_roundtrip():
    exprs = [
        InputRef(3, DataType.INT64),
        Literal(42, DataType.INT64),
        Literal("x", DataType.VARCHAR),
        BinaryOp("+", InputRef(0, DataType.INT64),
                 Literal(1, DataType.INT64)),
        UnaryOp("not", BinaryOp(">", InputRef(1, DataType.INT64),
                                Literal(5, DataType.INT64))),
        Cast(InputRef(0, DataType.INT64), DataType.FLOAT64),
        tumble_start(InputRef(2, DataType.TIMESTAMP),
                     Interval(usecs=10_000_000)),
        Case([(BinaryOp("=", InputRef(0, DataType.INT64),
                        Literal(1, DataType.INT64)),
               Literal(10, DataType.INT64))],
             Literal(0, DataType.INT64)),
    ]
    for e in exprs:
        ir = json.loads(json.dumps(expr_to_ir(e)))   # through JSON
        back = expr_from_ir(ir)
        assert repr(back) == repr(e) or \
            expr_to_ir(back) == expr_to_ir(e)
    import decimal
    for v, dt in [(decimal.Decimal("12.34"), DataType.DECIMAL),
                  (b"\x00\xffbin", DataType.BYTEA)]:
        ir = json.loads(json.dumps(expr_to_ir(Literal(v, dt))))
        back = expr_from_ir(ir)
        assert back.value == v and type(back.value) is type(v)
    s = Schema.of(a=DataType.INT64, b=DataType.VARCHAR)
    assert schema_from_ir(json.loads(json.dumps(
        schema_to_ir(s))))[1].name == "b"


def _q7ish_plan(event_num: int, actor_id: int) -> list:
    """source(bid) → project(window_start, price) → hash_agg."""
    bid_schema = [
        {"name": n, "dt": d} for n, d in
        [("auction", "bigint"), ("bidder", "bigint"),
         ("price", "bigint"), ("channel", "varchar"),
         ("url", "varchar"), ("date_time", "timestamp"),
         ("extra", "varchar")]]
    ts = InputRef(5, DataType.TIMESTAMP)
    return [
        {"op": "source", "name": "bid",
         "connector": {"connector": "nexmark",
                       "nexmark.table.type": "bid",
                       "nexmark.event.num": str(event_num),
                       "nexmark.max.chunk.size": "256"},
         "schema": bid_schema, "actor_id": actor_id,
         "split_table_id": 201, "rate_limit": 2, "min_chunks": 2},
        {"op": "project", "input": 0,
         "exprs": [expr_to_ir(tumble_start(
             ts, Interval(usecs=10_000_000))),
             expr_to_ir(InputRef(2, DataType.INT64))],
         "names": ["window_start", "price"]},
        {"op": "hash_agg", "input": 1, "group": [0],
         "calls": [{"kind": "max", "input_idx": 1},
                   {"kind": "count"}],
         "table_id": 202, "append_only": True,
         "output_names": ["max_price", "bid_count"]},
    ]


def _q7_oracle(n: int) -> dict:
    """window_start → (max_price, count) over the same bid stream."""
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids

    bids = gen_bids(np.arange(n * 46 // 50, dtype=np.int64),
                    NexmarkConfig(event_num=n, max_chunk_size=256))
    want = {}
    for t, p in zip(bids["date_time"].tolist(),
                    bids["price"].tolist()):
        w = t // 10_000_000 * 10_000_000
        mx, c = want.get(w, (0, 0))
        want[w] = (max(mx, p), c + 1)
    return want


def test_build_fragment_runs_locally():
    """The IR factory builds a runnable chain equal to the q7 oracle."""
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )

    n = 4000
    store = MemoryStateStore()
    local = LocalBarrierManager()
    _src, consumer = build_fragment(
        _q7ish_plan(n, actor_id=1), store, local, channel_for_test)
    mv = StateTable(203, consumer.schema, [0], store)
    mat = MaterializeExecutor(consumer, mv)
    local.set_expected_actors([1])
    actor = Actor(1, mat, dispatchers=[], barrier_manager=local)
    loop = BarrierLoop(local, store)

    async def run():
        task = actor.spawn()
        for _ in range(30):
            await loop.inject_and_collect(force_checkpoint=True)
        from risingwave_tpu.stream.message import StopMutation
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset({1})))
        await task
        assert actor.failure is None

    asyncio.run(run())
    got = {r[0]: (r[1], r[2]) for _pk, r in mv.iter_rows()}
    assert got == _q7_oracle(n)


def test_shipped_plan_on_real_worker(tmp_path):
    """deploy_plan ships the SAME IR to a worker process; the
    coordinator consumes its remote exchange and materializes the
    oracle-exact result — plan shipping, not a named fragment."""
    from risingwave_tpu.cluster.coordinator import (
        WorkerBarrierSender, WorkerHandle,
    )
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    from risingwave_tpu.stream.message import StopMutation
    from risingwave_tpu.stream.remote import RemoteInput

    SRC, SINK, PSEUDO = 31, 40, 999
    n = 4000
    out_schema = Schema.of(window_start=DataType.TIMESTAMP,
                           max_price=DataType.INT64,
                           bid_count=DataType.INT64)

    async def main():
        handle = WorkerHandle(str(tmp_path / "w"))
        client = await handle.start()
        try:
            await client.deploy_plan(_q7ish_plan(n, actor_id=SRC),
                                     actor_id=SRC, down_actor=SINK)
            store = HummockLite(LocalFsObjectStore(
                str(tmp_path / "c")))
            local = LocalBarrierManager()
            up = RemoteInput("127.0.0.1", client.exchange_port,
                             SRC, SINK, out_schema)
            mv = StateTable(7, out_schema, [0], store)
            mat = MaterializeExecutor(up, mv)
            actor = Actor(SINK, mat, dispatchers=[],
                          barrier_manager=local)
            loop = BarrierLoop(local, store)
            local.register_sender(
                PSEUDO, WorkerBarrierSender(client, local, PSEUDO))
            local.set_expected_actors([SINK, PSEUDO])
            task = actor.spawn()
            for _ in range(30):
                await loop.inject_and_collect(force_checkpoint=True)
            await loop.inject_and_collect(
                force_checkpoint=True,
                mutation=StopMutation(frozenset({SRC, SINK, PSEUDO})))
            await task
            assert actor.failure is None
            return {r[0]: (r[1], r[2]) for _pk, r in mv.iter_rows()}
        finally:
            await handle.stop()

    got = asyncio.run(main())
    assert got == _q7_oracle(n)


def test_shipped_join_pipeline_on_worker(tmp_path):
    """Full q8 ships as THREE typed plans to one worker: two source
    fragments + a remote-fed join+materialize fragment (remote_input/
    hash_join/materialize IR nodes) whose join state AND the MV live
    in the worker's hummock namespace — the coordinator only drives
    barriers. The MV is read back from the worker's store AFTER
    shutdown: durable exactly-once state, not streamed output."""
    from risingwave_tpu.cluster.coordinator import (
        WorkerBarrierSender, WorkerHandle,
    )
    from risingwave_tpu.common.types import Interval
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.expr.expr import InputRef, tumble_start
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.message import StopMutation
    from tests.test_e2e_q8 import q8_oracle

    P_ACTOR, A_ACTOR, J_ACTOR, PSEUDO = 11, 12, 20, 999
    EVENTS = 6000
    W = Interval(usecs=10_000_000)
    ir = expr_to_ir

    def src(table, actor_id, split_tid):
        from risingwave_tpu.connectors.nexmark import TABLE_SCHEMAS
        return {"op": "source", "name": table,
                "connector": {"connector": "nexmark",
                              "nexmark.table.type": table,
                              "nexmark.event.num": str(EVENTS),
                              "nexmark.max.chunk.size": "256"},
                "schema": schema_to_ir(TABLE_SCHEMAS[table]),
                "actor_id": actor_id, "split_table_id": split_tid,
                "rate_limit": 2, "min_chunks": 2}

    TS, I64, VC = DataType.TIMESTAMP, DataType.INT64, DataType.VARCHAR
    person_plan = [
        src("person", P_ACTOR, 101),
        {"op": "project", "input": 0,
         "exprs": [ir(InputRef(0, I64)), ir(InputRef(1, VC)),
                   ir(tumble_start(InputRef(6, TS), W))],
         "names": ["id", "name", "starttime"]},
    ]
    auction_plan = [
        src("auction", A_ACTOR, 102),
        {"op": "project", "input": 0,
         "exprs": [ir(InputRef(7, I64)),
                   ir(tumble_start(InputRef(5, TS), W))],
         "names": ["seller", "starttime"]},
        {"op": "hash_agg", "input": 1, "group": [0, 1],
         "calls": [{"kind": "count"}], "table_id": 103,
         "append_only": True,
         "output_names": ["seller", "starttime", "_cnt"]},
        {"op": "project", "input": 2,
         "exprs": [ir(InputRef(0, I64)), ir(InputRef(1, TS))],
         "names": ["seller", "starttime"]},
    ]
    p_out = Schema.of(id=I64, name=VC, starttime=TS)
    a_out = Schema.of(seller=I64, starttime=TS)
    mv_schema = Schema.of(id=I64, name=VC, starttime=TS,
                          seller=I64, starttime_r=TS)

    async def main():
        handle = WorkerHandle(str(tmp_path / "w"))
        client = await handle.start()
        try:
            port = client.exchange_port
            join_plan = [
                {"op": "remote_input", "host": "127.0.0.1",
                 "port": port, "up_actor": P_ACTOR,
                 "schema": schema_to_ir(p_out)},
                {"op": "remote_input", "host": "127.0.0.1",
                 "port": port, "up_actor": A_ACTOR,
                 "schema": schema_to_ir(a_out)},
                {"op": "hash_join", "left": 0, "right": 1,
                 "left_keys": [0, 2], "right_keys": [0, 1],
                 "left_table_id": 4, "right_table_id": 5,
                 "left_pk": [0, 2], "right_pk": [0, 1],
                 "left_dist_key": [0], "right_dist_key": [0]},
                {"op": "materialize", "input": 2, "table_id": 6,
                 "pk": [0, 2]},
            ]
            await client.deploy_plan(person_plan, down_actor=J_ACTOR)
            await client.deploy_plan(auction_plan, down_actor=J_ACTOR)
            await client.deploy_plan(join_plan, actor_id=J_ACTOR,
                                     down_actor=None)
            local = LocalBarrierManager()
            loop = BarrierLoop(local, MemoryStateStore())
            local.register_sender(
                PSEUDO, WorkerBarrierSender(client, local, PSEUDO))
            local.set_expected_actors([PSEUDO])
            for _ in range(25):
                await loop.inject_and_collect(force_checkpoint=True)
            await loop.inject_and_collect(
                force_checkpoint=True,
                mutation=StopMutation(frozenset(
                    {P_ACTOR, A_ACTOR, J_ACTOR, PSEUDO})))
        finally:
            await handle.stop()

    asyncio.run(main())
    # the worker is gone; its durable namespace has the MV
    store = HummockLite(LocalFsObjectStore(str(tmp_path / "w")))
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    mv = StateTable(6, mv_schema, [0, 2], store)
    ce = store.committed_epoch()
    mv.init_epoch(EpochPair(Epoch(ce + 1), Epoch(ce)))
    got = {(r[0], r[1], r[2]) for _pk, r in mv.iter_rows()}
    cfg = NexmarkConfig(event_num=EVENTS)
    assert got == q8_oracle(cfg, EVENTS // 50, EVENTS * 3 // 50)
    assert len(got) > 5


def test_build_fragment_agg_aux_tables():
    """DISTINCT / retractable min-max calls build their dedup and
    minput state tables from the IR's shipped table ids, and a plan
    missing a required id fails loudly at build (not at runtime)."""
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test

    def plan(**agg_extra):
        node = {"op": "hash_agg", "input": 1, "group": [0],
                "calls": [
                    {"kind": "count", "input_idx": 1,
                     "distinct": True},
                    {"kind": "min", "input_idx": 1}],
                "table_id": 302, "append_only": False,
                "output_names": ["dcount", "mn"]}
        node.update(agg_extra)
        return _q7ish_plan(100, actor_id=9)[:2] + [node]

    store = MemoryStateStore()
    local = LocalBarrierManager()
    _src, agg = build_fragment(
        plan(dedup_table_ids={"1": 303}, minput_table_ids={"1": 304}),
        store, local, channel_for_test)
    assert set(agg.distinct_tables) == {1}
    assert agg.distinct_tables[1].table_id == 303
    assert agg.minput[1].table_id == 304
    for bad in [plan(minput_table_ids={"1": 304}),
                plan(dedup_table_ids={"1": 303})]:
        local2 = LocalBarrierManager()
        with pytest.raises(ValueError, match="table_ids"):
            build_fragment(bad, MemoryStateStore(), local2,
                           channel_for_test)


def test_build_fragment_dynamic_filter_and_dedup():
    """dynamic_filter + dedup: the executors run end-to-end, and the
    plan-IR factory constructs both node types (they ship via direct
    deploy_plan; the fragmenter does not emit them yet)."""
    import asyncio

    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.executors.dedup import (
        AppendOnlyDedupExecutor,
    )
    from risingwave_tpu.stream.executors.dynamic_filter import (
        DynamicFilterExecutor,
    )
    from risingwave_tpu.stream.executors.test_utils import (
        MockSource, collect_until_n_barriers,
    )
    from risingwave_tpu.stream.message import Barrier, BarrierKind
    from risingwave_tpu.stream.plan_ir import build_fragment

    sch = Schema.of(v=DataType.INT64)

    def b(n):
        curr = Epoch.from_physical(n)
        prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
        return Barrier(EpochPair(curr, prev), BarrierKind.CHECKPOINT)

    left = MockSource(sch, [
        b(1), StreamChunk.from_pydict(sch, {"v": [1, 5, 9, 7]}), b(2),
        b(3)])
    right = MockSource(sch, [
        b(1), StreamChunk.from_pydict(sch, {"v": [4]}), b(2), b(3)])
    store = MemoryStateStore()
    df = DynamicFilterExecutor(left, right, 0, ">",
                               StateTable(50, sch, [0], store))
    dd = AppendOnlyDedupExecutor(
        df, [0], StateTable(51, sch, [0], store))
    outs = asyncio.run(collect_until_n_barriers(dd, 3))
    rows = [row for m in outs if hasattr(m, "to_records")
            for _op, row in m.to_records()]
    assert sorted(r[0] for r in rows) == [5, 7, 9]   # v > 4, deduped

    # IR factory constructs the same node types
    src = {"op": "source",
           "connector": {"connector": "datagen", "datagen.rows": "8",
                         "fields.v.kind": "sequence",
                         "fields.v.start": "1", "fields.v.end": "8"},
           "schema": [{"name": "v", "dt": DataType.INT64.value}],
           "actor_id": 1, "split_table_id": 60}
    plan = [src,
            dict(src, actor_id=2, split_table_id=61),
            {"op": "dynamic_filter", "left": 0, "right": 1,
             "left_col": 0, "cmp": ">", "table_id": 62},
            {"op": "dedup", "input": 2, "keys": [0], "table_id": 63}]
    _sr, consumer = build_fragment(plan, MemoryStateStore(),
                                   LocalBarrierManager(),
                                   channel_for_test, actor_id=9)
    assert type(consumer).__name__ == "AppendOnlyDedupExecutor"
    assert type(consumer.input).__name__ == "DynamicFilterExecutor"


def test_fragmenter_ships_hll_sketch_tables():
    """approx_count_distinct's sketch tables ride minput_table_ids
    through the fragmenter (the executor popped them out of minput at
    construction), so a distributed CREATE MV rebuilds the agg with
    its HLL aux table instead of failing at build."""
    from risingwave_tpu.frontend.catalog import Catalog
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.frontend.parser import parse_many
    from risingwave_tpu.frontend.planner import (
        StreamPlanner, source_schema,
    )
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.executor import executor_children
    from risingwave_tpu.stream.executors.hash_agg import HashAggExecutor

    opts = {"connector": "nexmark", "nexmark.table.type": "bid",
            "nexmark.event.num": "1000"}
    catalog = Catalog()
    catalog.add_source("bid", source_schema(opts, None), opts)
    [(_text, stmt)] = parse_many(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, "
        "approx_count_distinct(bidder) AS d FROM bid GROUP BY auction")
    planner = StreamPlanner(catalog, MemoryStateStore(),
                            LocalBarrierManager(), definition="")
    plan = planner.plan("v", stmt.select, 7, rate_limit=4)
    graph = Fragmenter(1).lower(plan.consumer)
    nodes = [n for f in graph.fragments for n in f.nodes]
    agg_node = next(n for n in nodes if n["op"] == "hash_agg")
    assert agg_node["minput_table_ids"], \
        "sketch table id missing from the shipped IR"
    # and the shipped IR round-trips into a working executor
    _src, consumer = build_fragment(
        graph.fragments[-1].nodes, MemoryStateStore(),
        LocalBarrierManager(), channel_for_test)

    def find_agg(ex):
        if isinstance(ex, HashAggExecutor):
            return ex
        for _a, _i, child in executor_children(ex):
            got = find_agg(child)
            if got is not None:
                return got
        return None

    agg = find_agg(consumer)
    assert agg is not None
    assert set(agg.hll_tables) == {0}
