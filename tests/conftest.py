"""Test harness: force an 8-device virtual CPU mesh before JAX import.

Mirrors the reference's testing stance (SURVEY.md section 4): executor tests
run against in-memory fakes; multi-chip sharding is validated on virtual CPU
devices (`--xla_force_host_platform_device_count=8`) — JAX-on-CPU stands in
for the TPU mesh. Real-TPU benchmarking happens only in bench.py.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
