"""Test harness: force an 8-device virtual CPU mesh before backend init.

Mirrors the reference's testing stance (SURVEY.md section 4): executor tests
run against in-memory fakes; multi-chip sharding is validated on virtual CPU
devices (`--xla_force_host_platform_device_count=8`) — JAX-on-CPU stands in
for the TPU mesh. Real-TPU benchmarking happens only in bench.py.

The env var alone is NOT enough on axon machines: the axon sitecustomize
(/root/.axon_site) calls jax.config.update("jax_platforms", "axon,cpu")
at interpreter start, overriding JAX_PLATFORMS. We override it back via
jax.config before any backend initializes — this also keeps the suite
runnable when the TPU tunnel is down.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the kernel tests compile many
# (capacity, width, chunk) shape buckets; without a disk cache every
# pytest invocation recompiles all of them from scratch.
_CACHE_DIR = os.path.join(os.path.dirname(__file__), "..", ".jax_cache")
jax.config.update("jax_compilation_cache_dir",
                  os.path.abspath(_CACHE_DIR))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.25)

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _strict_plan_checker():
    """Assert-don't-fallback mode for the plan-rewrite checker
    (frontend/opt): a rewrite rule that breaks a plan invariant fails
    the suite loudly instead of silently falling back."""
    from risingwave_tpu.frontend.opt import set_strict_checker
    set_strict_checker(True)
    yield
    set_strict_checker(False)


@pytest.fixture(autouse=True)
def _strict_empty_chunks():
    """Assertion mode for the empty-message-suppression invariant: a
    MonitoredExecutor (i.e. any deployed chain) emitting a
    zero-visible-row chunk fails the test instead of just counting."""
    from risingwave_tpu.stream.monitor import set_strict_empty_chunks
    set_strict_empty_chunks(True)
    yield
    set_strict_empty_chunks(False)


@pytest.fixture(autouse=True)
def _strict_memory_accounting():
    """Tier-1 strict mode for the state-tier soft limit: a test that
    configures MemoryContext.soft_limit (directly or via SET
    state_tier_soft_limit_mb) fails if the accounted host-state bytes
    still exceed it at teardown — the tier's pressure sweeps must have
    brought the state back under the watermark. Tests that set no
    limit are untouched. The limit is process-global, so it always
    resets between tests."""
    from risingwave_tpu.utils import memory as _mem
    _mem.GLOBAL.soft_limit = None
    yield
    limit = _mem.GLOBAL.soft_limit
    if limit is None:
        return
    total = _mem.GLOBAL.total_bytes()
    _mem.GLOBAL.soft_limit = None
    assert total <= limit, (
        f"accounted host state {total}B exceeds the configured "
        f"state-tier soft limit {limit}B at teardown — pressure "
        f"eviction failed to bound it")


@pytest.fixture(autouse=True)
def _conservation_gate():
    """Tier-1 strict mode for the epoch phase ledger (utils/ledger.py):
    any steady-state epoch a test drives whose `unattributed` residual
    exceeds the conservation budget fails the test — the ledger can
    never silently rot. Warmup (compile-bearing), mutation and
    unmerged-distributed epochs are exempt; micro-epochs are below the
    gate's interval floor. Sits next to the RecompileGuard and
    DispatchBudget strict-mode guards."""
    from risingwave_tpu.utils import ledger as _ledger
    _ledger.set_enabled(True)
    _ledger.LEDGER.clear()
    yield
    violations = _ledger.LEDGER.gate_violations()
    _ledger.LEDGER.clear()
    _ledger.set_enabled(True)
    assert not violations, (
        "epoch phase ledger conservation gate (tier-1 strict mode): "
        "steady-state epochs carried unattributed wall-clock over "
        "budget — an uninstrumented stall crept into the barrier "
        "path. (epoch, interval_s, unattributed_s, coverage, domain): "
        f"{[(hex(e), round(i, 3), round(u, 3), c, d) for e, i, u, c, d in violations]}")


@pytest.fixture(autouse=True)
def _attribution_gate():
    """Tier-1 strict mode for serving-cost attribution (ISSUE 16):
    (a) the per-MV device-seconds split can redistribute the phase
    ledger's books but never mint time — Σ per-MV ≤ the domain's
    ledgered device_compute + ε for every sealed local epoch; (b) the
    per-(table, vnode) topology's incremental totals must agree with a
    full recount of the authoritative size map at every checkpoint
    (armed here; a no-op in production). Same arming pattern as the
    ledger conservation gate."""
    from risingwave_tpu.state import topology as _topology
    from risingwave_tpu.stream import costs as _costs
    from risingwave_tpu.stream import hotkeys as _hotkeys
    _costs.set_enabled(True)
    _costs.COSTS.clear()
    _topology.TOPOLOGY.clear()
    _hotkeys.HOTKEYS.clear()
    _topology.TOPOLOGY.arm_checkpoint_verify(True)
    yield
    split = _costs.COSTS.gate_violations()
    _topology.TOPOLOGY.checkpoint_verify()
    books = _topology.TOPOLOGY.gate_violations()
    _costs.COSTS.clear()
    _topology.TOPOLOGY.clear()
    _hotkeys.HOTKEYS.clear()
    _topology.TOPOLOGY.arm_checkpoint_verify(False)
    _costs.set_enabled(True)
    assert not split, (
        "per-MV attribution gate (tier-1 strict mode): the MV split "
        "claims more device time than the domain's phase ledger "
        "recorded — the owner split minted time. (epoch, domain, "
        "sum_mv_device_s, domain_device_s): "
        f"{[(hex(e), d, round(s, 4), round(g, 4)) for e, d, s, g in split[:5]]}")
    assert not books, (
        "state-topology recount gate (tier-1 strict mode): the "
        "incremental per-table totals disagree with a full recount of "
        "the authoritative size map — delta arithmetic drifted. "
        "(table_id, rows_inc, rows_true, bytes_inc, bytes_true): "
        f"{books[:5]}")


@pytest.fixture(autouse=True)
def _tricolor_freshness_gate():
    """Tier-1 strict mode for the utilization tricolor and per-MV
    freshness (stream/monitor.py + stream/freshness.py): every
    published busy/backpressure/idle triple must sum to ≤ 1.0 + ε
    (the three parts partition disjoint wall time by construction —
    an oversum is a double-count bug), and every resolved freshness
    sample must be finite and non-negative once the first frontier
    passes materialize. Same arming pattern as the ledger
    conservation gate."""
    from risingwave_tpu.stream import freshness as _fresh
    from risingwave_tpu.stream import monitor as _monitor
    from risingwave_tpu.stream.bottleneck import BOTTLENECKS
    _monitor.set_tricolor(True)
    _fresh.set_enabled(True)
    _monitor.UTILIZATION.clear()
    _fresh.FRESHNESS.clear()
    BOTTLENECKS.clear()
    yield
    tri = _monitor.UTILIZATION.gate_violations()
    lag = _fresh.FRESHNESS.gate_violations()
    _monitor.UTILIZATION.clear()
    _fresh.FRESHNESS.clear()
    BOTTLENECKS.clear()
    _monitor.set_tricolor(True)
    _fresh.set_enabled(True)
    assert not tri, (
        "utilization tricolor gate (tier-1 strict mode): published "
        "busy+backpressure+idle triples exceed 1.0 + ε — two states "
        "claim the same wall time. ((fragment, actor, node), "
        f"executor, epoch, busy, bp, idle): {tri[:5]}")
    assert not lag, (
        "freshness gate (tier-1 strict mode): per-MV lag samples "
        "must be finite and non-negative once the first frontier "
        f"passes materialize. (mv, epoch, lag, wall_lag): {lag[:5]}")


def _worker_children() -> list:
    """PIDs of live `risingwave_tpu.cluster.worker` subprocesses whose
    parent is this test process. Zombies (state Z) don't count — a
    corpse holds no ports; what this hunts is the LIVE leak that
    shadows a later test's exchange/control ports."""
    import os
    me = os.getpid()
    out = []
    if not os.path.isdir("/proc"):          # non-Linux: guard is off
        return out
    for pid in os.listdir("/proc"):
        if not pid.isdigit():
            continue
        try:
            with open(f"/proc/{pid}/stat") as f:
                tail = f.read().rsplit(")", 1)[1].split()
            state, ppid = tail[0], int(tail[1])
            if ppid != me or state == "Z":
                continue
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ")
            if b"risingwave_tpu.cluster.worker" in cmd:
                out.append(int(pid))
        except (OSError, ValueError, IndexError):
            continue
    return out


@pytest.fixture(autouse=True)
def _no_orphan_workers():
    """Tier-1 guard (ISSUE 8): a test that leaves worker subprocesses
    running fails loudly — a leaked `WorkerHandle` child keeps serving
    its old exchange/control ports and can shadow a later cluster
    test's connections with stale state. The guard also SIGKILLs the
    orphans so one broken test doesn't cascade."""
    import os
    import signal
    yield
    orphans = _worker_children()
    if orphans:
        for pid in orphans:
            try:
                os.kill(pid, signal.SIGKILL)
            except OSError:
                pass
        pytest.fail(
            f"test leaked live worker subprocess(es) {orphans} — "
            "every WorkerHandle/Cluster must be stopped (they were "
            "killed now to protect the rest of the suite)")


@pytest.fixture(autouse=True)
def _no_orphan_sink_staging():
    """Tier-1 guard (ISSUE 20): a test that leaves staged-but-
    unmanifested sink segments behind fails loudly — uncommitted
    staging outliving its test is exactly the leakage the exactly-once
    protocol forbids (a converged pipeline either commits an epoch's
    segments or recovery truncates them). The guard also SWEEPS the
    orphans so a later test reusing the path can't promote a dead
    generation's rows."""
    from risingwave_tpu.connectors import sink as _sink
    _sink.reset_touched_roots()
    yield
    import os
    leaked = {}
    for root in _sink.touched_roots():
        if not os.path.isdir(root):
            continue                 # tmp_path already torn down
        from risingwave_tpu.storage.object_store import (
            LocalFsObjectStore,
        )
        target = _sink.EpochSegmentTarget(LocalFsObjectStore(root))
        orphans = target.uncommitted_epochs()
        if orphans:
            # sweep before failing: floor=-1 truncates everything
            # unmanifested, protecting the rest of the suite
            target.recover(-1)
            leaked[root] = sorted(hex(e) for e in orphans)
    _sink.reset_touched_roots()
    if leaked:
        pytest.fail(
            f"test leaked uncommitted sink staging {leaked} — every "
            "epoch-segment sink must converge (commit or truncate) "
            "before the test ends (orphans were swept now to protect "
            "the rest of the suite)")


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


class DispatchBudget:
    """Tier-1 strict-mode guard for fragment fusion (ISSUE 6): a fused
    pipeline must be at least as dispatch-dense as its unfused
    baseline. Usage in fused-vs-unfused tests:

        out_off, d_off, rpd_off = dispatch_budget.measure(run_unfused)
        out_on,  d_on,  rpd_on  = dispatch_budget.measure(run_fused)
        dispatch_budget.check(d_off, rpd_off, d_on, rpd_on)

    check() fails the test if the fused run's rows-per-dispatch fell
    below the unfused baseline's, or its dispatch count did not drop.

    Granularity note (ARCHITECTURE.md "Metrics attribution"): the
    unfused arm counts per-chunk dispatch REQUESTS (kernel.apply
    enqueues) while the fused arm counts real backlogged launches, so
    this guards the executor-level dispatch pressure the fusion
    removes, not a launch-for-launch comparison.
    """

    @staticmethod
    def totals():
        from risingwave_tpu.utils.metrics import STREAMING
        d = sum(v for _l, v in STREAMING.device_dispatch.series())
        r = sum(s for _l, _n, s in
                STREAMING.rows_per_dispatch.series())
        return float(d), float(r)

    def measure(self, fn):
        """(fn result, dispatches, rows/dispatch) over fn's run."""
        d0, r0 = self.totals()
        out = fn()
        d1, r1 = self.totals()
        d = d1 - d0
        return out, d, (r1 - r0) / max(d, 1.0)

    @staticmethod
    def check(d_unfused, rpd_unfused, d_fused, rpd_fused):
        assert d_fused < d_unfused, (
            f"fused pipeline dispatched {d_fused} times, unfused "
            f"baseline {d_unfused} — fusion must strictly drop the "
            "device dispatch count")
        assert rpd_fused >= rpd_unfused, (
            f"fused rows-per-dispatch {rpd_fused:.1f} fell below the "
            f"unfused baseline {rpd_unfused:.1f} — dispatch-budget "
            "guard (tier-1 strict mode)")

    @staticmethod
    def check_ceiling(d_fused, d_baseline, what="baseline"):
        """Join-query extension (ISSUE 9): a fused join run must not
        exceed its comparison arm's dispatch count — the test-scale
        analog of BENCH acceptance 'fused q5-shape dispatches below
        the r08 unfused count'."""
        assert d_fused <= d_baseline, (
            f"fused join run dispatched {d_fused} times, {what} "
            f"{d_baseline} — dispatch-budget guard (tier-1 strict "
            "mode, join extension)")

    @staticmethod
    def sharded_totals():
        """(dispatches, rows) of the SHARDED kernels alone — counted
        at their real shard_map launch sites under kernel="sharded_*"
        labels (ISSUE 10 observability satellite)."""
        from risingwave_tpu.utils.metrics import STREAMING
        d = sum(v for l, v in STREAMING.device_dispatch.series()
                if l.get("kernel", "").startswith("sharded"))
        r = sum(s for l, _n, s in
                STREAMING.rows_per_dispatch.series()
                if l.get("kernel", "").startswith("sharded"))
        return float(d), float(r)

    def measure_sharded(self, fn):
        """(fn result, sharded dispatches, sharded rows/dispatch)."""
        d0, r0 = self.sharded_totals()
        out = fn()
        d1, r1 = self.sharded_totals()
        d = d1 - d0
        return out, d, (r1 - r0) / max(d, 1.0)

    @staticmethod
    def check_epoch_ceiling(dispatches, n_epochs, per_epoch,
                            what="sharded epoch batching"):
        """Distributed/sharded extension (ISSUE 10): SPMD dispatches
        per epoch must stay O(1) per kernel — `per_epoch` is the
        kernel count times its per-epoch dispatch budget (join: 2
        apply + 2 probe; agg: 1 step + 1 gather), NOT a per-chunk
        allowance. A regression back to per-chunk dispatch trips this
        immediately."""
        assert dispatches <= n_epochs * per_epoch, (
            f"{what}: {dispatches} sharded SPMD dispatches over "
            f"{n_epochs} epochs exceeds the O(1)-per-epoch ceiling "
            f"({per_epoch}/epoch) — the per-epoch discipline "
            "regressed to per-chunk dispatch (tier-1 strict mode)")


@pytest.fixture
def dispatch_budget():
    return DispatchBudget()


class RecompileGuard:
    """Tier-1 strict-mode guard for jitted-kernel shape stability
    (ISSUE 7): a steady-state run must not retrace kernels after
    warmup — a retrace on the hot path is a silent shape-churn
    regression (each costs ~0.5-1s of compiler on a tunneled device).
    Usage:

        out, n_warm = recompile_guard.measure(run_warmup)
        out, n_steady = recompile_guard.measure(run_steady_state)
        recompile_guard.check_steady(n_steady)

    measure() counts stream_kernel_recompile_count growth over fn;
    check_steady() fails the test on ANY steady-state retrace.
    """

    @staticmethod
    def total():
        from risingwave_tpu.utils.metrics import STREAMING
        return sum(v for _l, v in
                   STREAMING.kernel_recompile.series())

    def measure(self, fn):
        t0 = self.total()
        out = fn()
        return out, self.total() - t0

    @staticmethod
    def check_steady(n_recompiles, what="steady state"):
        assert n_recompiles == 0, (
            f"{n_recompiles} jitted-kernel retraces during {what} — "
            "warmup must have compiled every shape bucket; a "
            "steady-state retrace is a shape-churn regression "
            "(recompile-guard, tier-1 strict mode)")


@pytest.fixture
def recompile_guard():
    return RecompileGuard()
