"""Async checkpoint pipeline (storage/uploader.py + meta/barrier.py).

The barrier loop's collect path only SEALS an epoch and hands the
flush to the CheckpointUploader: SST build and object-store upload run
off the critical path, epochs commit strictly in order once their
uploads durably land, the sealed-but-uncommitted window is bounded
(back-pressure), and a crash with uploads in flight recovers to the
last FULLY committed epoch — no partial manifest (uploader.rs:567
semantics).
"""

import asyncio
import threading
import time

import pytest

from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import (
    DelayedObjectStore, MemObjectStore,
)
from risingwave_tpu.stream.actor import LocalBarrierManager


def _loop(store, **kw):
    # zero expected actors: every epoch completes trivially, so these
    # tests exercise exactly the seal→build→upload→commit pipeline
    return BarrierLoop(LocalBarrierManager(), store, **kw)


async def _checkpoint_epochs(loop, store, n, table=1, start=0):
    """Inject+collect n checkpoint barriers, writing one row at each
    barrier's curr epoch (sealed and flushed by the NEXT collect).
    Returns the written epochs."""
    written = []
    for i in range(start, start + n):
        b = await loop.inject(force_checkpoint=True)
        e = b.epoch.curr.value
        store.ingest_batch(table, [(i.to_bytes(4, "big"), (i,))], e)
        written.append(e)
        await loop.collect_next()
    return written


class _FirstSlow:
    """Delays only the FIRST data-SST upload (younger epochs' uploads
    finish first — the ordered commit must still not skip)."""

    def __init__(self, inner, delay_s):
        self.inner = inner
        self.delay_s = delay_s
        self._seen = 0

    def upload(self, path, data):
        if path.startswith("data/"):
            self._seen += 1
            if self._seen == 1:
                time.sleep(self.delay_s)
        self.inner.upload(path, data)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _Flaky:
    """Fails the first `fail_times` data uploads (transient outage)."""

    def __init__(self, inner, fail_times):
        self.inner = inner
        self.left = fail_times

    def upload(self, path, data):
        if path.startswith("data/") and self.left > 0:
            self.left -= 1
            raise OSError("transient upload failure")
        self.inner.upload(path, data)

    def __getattr__(self, name):
        return getattr(self.inner, name)


class _Gate:
    """Once gated, data uploads park until `cut()` makes them fail —
    a deterministic 'crash with uploads in flight'."""

    def __init__(self, inner):
        self.inner = inner
        self.gated = False
        self._cut = threading.Event()

    def upload(self, path, data):
        if self.gated and path.startswith("data/"):
            if not self._cut.wait(timeout=30):
                raise TimeoutError("gate never cut")
            raise OSError("power cut")
        self.inner.upload(path, data)

    def cut(self):
        self._cut.set()

    def __getattr__(self, name):
        return getattr(self.inner, name)


def test_collect_does_not_block_on_upload():
    """The acceptance shape: with a delay-injecting object store,
    injection/collection of later barriers proceeds while older
    checkpoints are still uploading."""
    obj = MemObjectStore()
    store = HummockLite(DelayedObjectStore(obj, delay_s=0.3))
    loop = _loop(store, max_uploading=8)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        t0 = time.perf_counter()
        await _checkpoint_epochs(loop, store, 4)
        t_collect = time.perf_counter() - t0
        depth_mid = loop.uploading_count
        await loop.uploader.drain()
        return t_collect, depth_mid

    t_collect, depth_mid = asyncio.run(run())
    assert depth_mid >= 1, "no upload was in flight after collections"
    # 3 sealed data epochs × 0.3s uploads; collections must not have
    # serialized on even ONE of them
    assert t_collect < 0.3, t_collect
    log = list(loop.uploader.commit_log)
    assert log == sorted(log) and len(set(log)) == len(log)
    assert store.committed_epoch() == loop.committed_epoch


def test_committed_epoch_never_skips_unfinished_older_epoch():
    obj = MemObjectStore()
    store = HummockLite(_FirstSlow(obj, 0.4))
    loop = _loop(store, max_uploading=8)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        written = await _checkpoint_epochs(loop, store, 3)
        # one more barrier so the last written epoch seals too
        await loop.inject(force_checkpoint=True)
        await loop.collect_next()
        # younger epochs' uploads are instant and land while the first
        # data epoch's upload still sleeps — committed must NOT move
        # past the unfinished older epoch
        await asyncio.sleep(0.1)
        stalled = loop.committed_epoch
        await loop.uploader.drain()
        return written, stalled

    written, stalled = asyncio.run(run())
    assert stalled < written[0], (stalled, written)
    assert loop.committed_epoch == written[-1]
    log = list(loop.uploader.commit_log)
    assert log == sorted(log) and len(set(log)) == len(log)


def test_backpressure_bounds_uploading_window():
    obj = MemObjectStore()
    store = HummockLite(DelayedObjectStore(obj, delay_s=0.15))
    loop = _loop(store, max_uploading=2)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        depths = []
        t0 = time.perf_counter()
        for i in range(5):
            b = await loop.inject(force_checkpoint=True)
            store.ingest_batch(1, [(i.to_bytes(4, "big"), (i,))],
                               b.epoch.curr.value)
            await loop.collect_next()
            depths.append(loop.uploading_count)
        elapsed = time.perf_counter() - t0
        await loop.uploader.drain()
        return depths, elapsed

    depths, elapsed = asyncio.run(run())
    assert max(depths) <= 2, depths          # window stayed bounded
    assert elapsed >= 0.15, elapsed          # i.e. submit back-pressured


def test_transient_upload_failure_retries_and_commits():
    from risingwave_tpu.utils.metrics import STORAGE

    obj = MemObjectStore()
    store = HummockLite(_Flaky(obj, fail_times=2))
    loop = _loop(store)
    before = STORAGE.sst_upload_retries.get()

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        b = await loop.inject(force_checkpoint=True)
        store.ingest_batch(1, [(b"k", (1,))], b.epoch.curr.value)
        await loop.collect_next()
        await loop.checkpoint()          # seals the write; drains

    asyncio.run(run())
    assert STORAGE.sst_upload_retries.get() - before >= 2
    assert loop.committed_epoch > 0
    assert store.committed_epoch() == loop.committed_epoch
    h2 = HummockLite(obj)                # reboot: the retry was durable
    assert h2.get(1, b"k", loop.committed_epoch) == (1,)


def test_terminal_upload_failure_fails_barrier_with_original_error():
    obj = MemObjectStore()
    store = HummockLite(_Flaky(obj, fail_times=100))
    loop = _loop(store)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        b = await loop.inject(force_checkpoint=True)
        store.ingest_batch(1, [(b"k", (1,))], b.epoch.curr.value)
        await loop.collect_next()
        with pytest.raises(OSError):
            for _ in range(10):
                await loop.inject_and_collect(force_checkpoint=True)

    asyncio.run(run())


def test_crash_with_uploads_in_flight_recovers_last_committed():
    """Tentpole recovery invariant: kill with uploads in flight →
    reboot at the last FULLY committed epoch; none of the in-flight
    epochs' data resurrects, no partial manifest."""
    obj = MemObjectStore()
    gate = _Gate(obj)
    store = HummockLite(gate)
    loop = _loop(store, max_uploading=8)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        await _checkpoint_epochs(loop, store, 3)
        await loop.checkpoint()          # rows 0..2 durably committed
        gate.gated = True                # uploads now hang
        gated = await _checkpoint_epochs(loop, store, 3, start=10)
        assert loop.uploading_count > 0  # in flight at the "crash"
        gate.cut()                       # power cut: they never commit
        with pytest.raises(OSError):
            await loop.uploader.drain()
        assert loop.committed_epoch < gated[0]
        return loop.committed_epoch

    durable = asyncio.run(run())
    h2 = HummockLite(obj)                # reboot from the object store
    assert h2.committed_epoch() == durable
    got = dict(h2.iter(1, 1 << 62))
    assert got == {i.to_bytes(4, "big"): (i,) for i in range(3)}
    assert not obj.exists("meta/STAGED.json")


def test_uploaded_but_uncommitted_sst_is_not_referenced():
    """Crash AFTER the object-store PUT but BEFORE the manifest
    commit: the orphan object exists but no version references it."""
    obj = MemObjectStore()
    h = HummockLite(obj)
    h.ingest_batch(1, [(b"a", (1,))], 100)
    h.seal_epoch(100, True)
    for p in h.build_ssts(100):
        h.upload_payload(p)              # durable object, no manifest
    h2 = HummockLite(obj)                # reboot before commit_ssts
    assert h2.committed_epoch() == 0
    assert h2.get(1, b"a", 100) is None
    assert obj.list("data/")             # the orphan is there, ignored


def test_run_stop_with_uploads_in_flight_commits_every_epoch_once():
    """Regression for the run()-drain hazard: stop() with uploads in
    flight must still commit every collected epoch exactly once, in
    order, before run() returns."""
    obj = MemObjectStore()
    store = HummockLite(DelayedObjectStore(obj, delay_s=0.05))
    loop = BarrierLoop(LocalBarrierManager(), store, interval_ms=1,
                       max_uploading=16)

    async def run():
        task = asyncio.ensure_future(loop.run())
        for i in range(10):
            await asyncio.sleep(0.004)
            if loop._epoch is not None:
                # the LATEST injected epoch cannot be sealed yet (only
                # a later barrier's collect seals it), so this write
                # always lands above the sealed watermark
                store.ingest_batch(1, [(i.to_bytes(4, "big"), (i,))],
                                   loop._epoch.value)
        await asyncio.sleep(0.03)        # successor barriers seal the
        loop.stop()                      # last write's epoch
        await task

    asyncio.run(run())
    assert loop.uploading_count == 0     # run() drained the uploader
    log = list(loop.uploader.commit_log)
    assert log == sorted(log) and len(set(log)) == len(log)
    # every collected barrier's prev committed exactly once (the first
    # barrier has prev=0: nothing to commit)
    assert len(log) == len(loop.stats.completed_epochs) - 1
    assert loop.committed_epoch == log[-1] == store.committed_epoch()
    h2 = HummockLite(obj)                # all rows durable after drain
    got = dict(h2.iter(1, 1 << 62))
    assert got == {i.to_bytes(4, "big"): (i,) for i in range(10)}


def test_memory_store_fallback_stays_synchronous():
    """Stores without the build/commit split (MemoryStateStore) take
    the inline sync fallback: committed_epoch advances at collect."""
    from risingwave_tpu.state.store import MemoryStateStore

    store = MemoryStateStore()
    loop = _loop(store)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        b = await loop.inject(force_checkpoint=True)
        store.ingest_batch(1, [(b"k", (1,))], b.epoch.curr.value)
        await loop.collect_next()        # no drain needed:
        assert loop.uploading_count == 0
        b2 = await loop.inject(force_checkpoint=True)
        await loop.collect_next()
        assert loop.committed_epoch == b2.epoch.prev.value

    asyncio.run(run())


def test_inject_and_collect_can_skip_drain_for_heartbeats():
    """The background heartbeat must not re-serialize the pipeline:
    drain_uploader=False returns without waiting on in-flight PUTs."""
    obj = MemObjectStore()
    store = HummockLite(DelayedObjectStore(obj, delay_s=0.2))
    loop = _loop(store, max_uploading=8)

    async def run():
        await loop.inject_and_collect(force_checkpoint=True)
        b = await loop.inject(force_checkpoint=True)
        store.ingest_batch(1, [(b"k", (1,))], b.epoch.curr.value)
        await loop.collect_next()
        t0 = time.perf_counter()
        await loop.inject_and_collect(force_checkpoint=True,
                                      drain_uploader=False)
        dt = time.perf_counter() - t0
        assert loop.uploading_count > 0    # the overlap survived
        assert dt < 0.2, dt                # did not wait on the PUT
        await loop.uploader.drain()

    asyncio.run(run())
    assert store.committed_epoch() == loop.committed_epoch


def test_vacuum_orphans_clears_crash_residue_keeps_live_data():
    obj = MemObjectStore()
    h = HummockLite(obj)
    h.ingest_batch(1, [(b"live", (1,))], 100)
    h.seal_epoch(100, True)
    h.sync(100)                          # committed: referenced SST
    h.ingest_batch(1, [(b"lost", (2,))], 200)
    h.seal_epoch(200, True)
    for p in h.build_ssts(200):
        h.upload_payload(p)              # crash before commit_ssts
    h2 = HummockLite(obj)                # next generation recovers
    assert h2.vacuum_orphans() == 1      # exactly the orphan
    assert h2.get(1, b"live", 200) == (1,)
    assert h2.get(1, b"lost", 200) is None
    assert len(obj.list("data/")) == 1   # only the referenced SST


def test_barrier_loop_reusable_across_event_loops():
    """One BarrierLoop driven by separate asyncio.run() calls (each a
    fresh event loop) — the uploader re-binds its idle loop-bound
    primitives instead of raising 'bound to a different event loop'
    (the pre-pipeline code supported this usage)."""
    obj = MemObjectStore()
    store = HummockLite(obj)
    loop = _loop(store)

    async def one_round(i):
        b = await loop.inject(force_checkpoint=True)
        store.ingest_batch(1, [(bytes([i]), (i,))], b.epoch.curr.value)
        while loop.in_flight_count:
            await loop.collect_next()
        await loop.uploader.drain()

    asyncio.run(one_round(1))
    asyncio.run(one_round(2))        # fresh loop: must not raise
    asyncio.run(loop.checkpoint())   # seals + commits the last write
    assert store.get(1, bytes([2]), loop.committed_epoch) == (2,)
