"""Nexmark q5 (hot items) end-to-end: hop windows + per-window top-1
vs an independent oracle."""

import asyncio
from collections import Counter, defaultdict

import numpy as np

from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
from risingwave_tpu.models.nexmark import build_q5, drive_to_completion
from risingwave_tpu.state.store import MemoryStateStore

SLIDE, SIZE = 2_000_000, 10_000_000
UNITS = SIZE // SLIDE


def q5_oracle(cfg, n_bids):
    bids = gen_bids(np.arange(n_bids, dtype=np.int64), cfg)
    counts = defaultdict(Counter)       # window_start → auction → bids
    for ts, a in zip(bids["date_time"].tolist(),
                     bids["auction"].tolist()):
        base = ts // SLIDE * SLIDE
        for i in range(UNITS):
            counts[base - i * SLIDE][a] += 1
    out = {}
    for w, c in counts.items():
        best = max(c.items(), key=lambda kv: (kv[1], -kv[0]))
        out[w] = best                   # ties: smallest auction id
    return out


def test_q5_end_to_end():
    n_epochs = 30
    cfg = NexmarkConfig(event_num=50 * 50 * n_epochs, max_chunk_size=1024,
                        min_event_gap_in_ns=100_000_000,
                        generate_strings=False)
    p = build_q5(MemoryStateStore(), cfg, rate_limit=8, min_chunks=8)
    n_bids = 46 * 50 * n_epochs
    asyncio.run(drive_to_completion(p, {1: n_bids}))
    got = {r[0]: (r[1], r[2]) for _pk, r in p.mv_table.iter_rows()}
    want = q5_oracle(cfg, n_bids)
    assert len(got) == len(want) > 50
    assert got == want
