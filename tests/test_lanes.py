"""32-bit lane codec properties (ops/lanes.py)."""

import numpy as np

from risingwave_tpu.ops import lanes


def test_split_merge_i64_roundtrip():
    rng = np.random.default_rng(0)
    v = rng.integers(-(2**62), 2**62, 1000, dtype=np.int64)
    v = np.concatenate([v, [0, 1, -1, 2**62, -(2**62), (1 << 63) - 1,
                            -(1 << 63)]]).astype(np.int64)
    hi, lo = lanes.split_i64(v)
    assert hi.dtype == np.int32 and lo.dtype == np.int32
    assert np.array_equal(lanes.merge_i64(hi, lo), v)


def test_sum_limbs_exact():
    rng = np.random.default_rng(1)
    v = rng.integers(-(2**55), 2**55, 500, dtype=np.int64)
    limbs = lanes.sum_limbs(v)
    assert len(limbs) == lanes.N_LIMBS
    assert np.array_equal(lanes.merge_limbs(*limbs), v)
    # simulated accumulation: per-limb int32 adds + carry normalization
    acc = [np.zeros(1, dtype=np.int32) for _ in range(lanes.N_LIMBS)]
    for chunk in np.array_split(v, 10):
        ls = lanes.sum_limbs(chunk)
        for i in range(lanes.N_LIMBS):
            acc[i] = (acc[i] + ls[i].sum(dtype=np.int64)).astype(np.int32)
        for i in range(lanes.N_LIMBS - 1):
            carry = acc[i] >> lanes.LIMB_BITS
            acc[i] = acc[i] - (carry << lanes.LIMB_BITS)
            acc[i + 1] = acc[i + 1] + carry
    assert lanes.merge_limbs(*acc)[0] == v.sum()


def test_order_lanes_int_lexicographic():
    rng = np.random.default_rng(2)
    v = np.concatenate([
        rng.integers(-(2**62), 2**62, 500, dtype=np.int64),
        np.asarray([0, 1, -1, 2**40, -(2**40), (1 << 63) - 1, -(1 << 63)],
                   dtype=np.int64)])
    hi, lo = lanes.order_lanes(v)
    # lexicographic (hi, lo) order == value order
    order_pairs = sorted(range(len(v)), key=lambda i: (hi[i], lo[i]))
    order_vals = np.argsort(v, kind="stable")
    assert np.array_equal(v[np.asarray(order_pairs)], v[order_vals])
    assert np.array_equal(lanes.inv_order_lanes(hi, lo, np.dtype(np.int64)),
                          v)


def test_order_lanes_float():
    v = np.asarray([-np.inf, -1e300, -1.5, -0.0, 0.0, 1e-300, 2.5, np.inf])
    hi, lo = lanes.order_lanes(v)
    keys = [(hi[i], lo[i]) for i in range(len(v))]
    assert keys == sorted(keys)
    back = lanes.inv_order_lanes(hi, lo, np.dtype(np.float64))
    assert np.array_equal(back[back != 0], v[v != 0])  # -0.0 folded to 0.0
    # float32 values survive the f64 round trip
    v32 = np.asarray([-3.5, 1.25, 7.0], dtype=np.float32)
    hi, lo = lanes.order_lanes(v32)
    assert np.array_equal(
        lanes.inv_order_lanes(hi, lo, np.dtype(np.float32)), v32)
