"""State layer tests.

Mirrors the core cases of the reference's
src/stream/src/common/table/test_state_table.rs (write-read across commit,
iteration order, update-pair atomicity) plus mem_table.rs op-merge rules and
keycodec ordering properties.
"""

import decimal

import numpy as np
import pytest

from risingwave_tpu.common import (
    DataType, Epoch, EpochPair, Op, Schema, StreamChunk,
)
from risingwave_tpu.common.hash import hash_columns, hash_columns_host
from risingwave_tpu.state import (
    KeyOp, MemTable, MemTableError, MemoryStateStore, StateTable,
    decode_memcomparable, encode_memcomparable,
)


# -- key codec ---------------------------------------------------------------

def test_memcomparable_ordering():
    types = [DataType.INT64]
    vals = [-(10**12), -5, -1, 0, 1, 7, 10**12]
    encs = [encode_memcomparable((v,), types) for v in vals]
    assert encs == sorted(encs)
    ftypes = [DataType.FLOAT64]
    fvals = [float("-inf"), -2.5, -0.0, 0.0, 1e-300, 3.7, float("inf")]
    fencs = [encode_memcomparable((v,), ftypes) for v in fvals]
    assert sorted(fencs) == fencs
    stypes = [DataType.VARCHAR]
    svals = ["", "a", "a\x00b", "ab", "b"]
    sencs = [encode_memcomparable((v,), stypes) for v in svals]
    assert sorted(sencs) == sencs


def test_memcomparable_roundtrip():
    types = [DataType.INT64, DataType.VARCHAR, DataType.FLOAT64,
             DataType.BOOLEAN, DataType.DECIMAL]
    # DECIMAL is physical: the scaled-int64 payload (9.5001 → 95001)
    row = (42, "hello\x00world", -3.25, True, 95001)
    enc = encode_memcomparable(row, types)
    assert decode_memcomparable(enc, types) == row
    nonerow = (None, None, None, None, None)
    assert decode_memcomparable(
        encode_memcomparable(nonerow, types), types) == nonerow
    # composite ordering: first column dominates
    a = encode_memcomparable((1, "z"), [DataType.INT64, DataType.VARCHAR])
    b = encode_memcomparable((2, "a"), [DataType.INT64, DataType.VARCHAR])
    assert a < b


def test_hash_host_device_consistency():
    """Host state partitioning must agree with device dispatch bit-for-bit."""
    import jax.numpy as jnp
    ints = np.arange(-500, 500, dtype=np.int64) * 997
    floats = np.linspace(-5, 5, 1000)
    small = np.arange(1000, dtype=np.int32)
    dev = np.asarray(hash_columns([jnp.asarray(ints), jnp.asarray(floats),
                                   jnp.asarray(small)]))
    host = hash_columns_host([ints, floats, small])
    assert np.array_equal(dev, host)


# -- mem table ---------------------------------------------------------------

def test_mem_table_merge_rules():
    mt = MemTable()
    mt.insert(b"k1", (1,))
    mt.delete(b"k1", (1,))          # insert+delete annihilate
    assert not mt.is_dirty()
    mt.insert(b"k2", (2,))
    with pytest.raises(MemTableError):
        mt.insert(b"k2", (2,))      # double insert
    mt.update(b"k2", (2,), (3,))    # update over buffered insert folds in
    assert mt.get(b"k2") == (True, (3,))
    mt.delete(b"k3", (9,))
    with pytest.raises(MemTableError):
        mt.delete(b"k3", (9,))      # double delete
    mt.insert(b"k3", (10,))         # delete+insert → update
    ops = dict(mt.iter_ops())
    assert ops[b"k3"][0] == KeyOp.UPDATE
    drained = dict(mt.drain())
    assert drained == {b"k2": (3,), b"k3": (10,)}
    assert not mt.is_dirty()


# -- state store MVCC --------------------------------------------------------

def test_memory_state_store_mvcc():
    st = MemoryStateStore()
    st.ingest_batch(1, [(b"a", (1,)), (b"b", (2,))], epoch=100)
    st.ingest_batch(1, [(b"a", (10,)), (b"b", None)], epoch=200)
    assert st.get(1, b"a", 100) == (1,)
    assert st.get(1, b"a", 150) == (1,)
    assert st.get(1, b"a", 200) == (10,)
    assert st.get(1, b"b", 100) == (2,)
    assert st.get(1, b"b", 200) is None          # tombstone
    assert st.get(1, b"a", 50) is None           # before first write
    assert [k for k, _ in st.iter(1, 200)] == [b"a"]
    assert [k for k, _ in st.iter(1, 100)] == [b"a", b"b"]
    st.seal_epoch(200)
    with pytest.raises(ValueError):
        st.ingest_batch(1, [(b"c", (3,))], epoch=150)  # write below seal


# -- state table -------------------------------------------------------------

def _table(sanity=True, dist=None):
    schema = Schema.of(k=DataType.INT64, s=DataType.VARCHAR, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(table_id=7, schema=schema, pk_indices=[0], store=store,
                   dist_key_indices=dist, sanity_check=sanity)
    e1 = Epoch.from_physical(1)
    t.init_epoch(EpochPair.new_initial(e1))
    return t, store


def _advance(t):
    new = EpochPair(curr=t.epoch.curr.next(), prev=t.epoch.curr)
    t.commit(new)
    return new


def test_state_table_write_read_across_commit():
    t, _ = _table()
    t.insert((1, "a", 10))
    t.insert((2, "b", 20))
    # uncommitted rows visible through the memtable
    assert t.get_row((1,)) == (1, "a", 10)
    _advance(t)
    assert t.get_row((1,)) == (1, "a", 10)       # now from committed store
    t.delete((1, "a", 10))
    assert t.get_row((1,)) is None               # buffered delete wins
    _advance(t)
    assert t.get_row((1,)) is None
    assert t.get_row((2,)) == (2, "b", 20)


def test_state_table_iteration_order_and_merge():
    t, _ = _table()
    for k in (5, 1, 9):
        t.insert((k, "x", k * 10))
    _advance(t)
    t.insert((3, "y", 30))          # buffered
    t.delete((9, "x", 90))          # buffered delete of committed row
    pks = [pk for pk, _ in t.iter_rows()]
    assert pks == [(1,), (3,), (5,)]
    rows = [r for _, r in t.iter_rows()]
    assert rows[1] == (3, "y", 30)


def test_state_table_update_pair_atomicity():
    t, _ = _table()
    t.insert((1, "a", 10))
    _advance(t)
    t.update((1, "a", 10), (1, "a", 11))
    assert t.get_row((1,)) == (1, "a", 11)
    _advance(t)
    assert t.get_row((1,)) == (1, "a", 11)
    # inconsistent update (wrong old row) caught by sanity check after insert
    t2, _ = _table()
    t2.insert((5, "q", 1))
    with pytest.raises(MemTableError):
        t2.update((5, "q", 999), (5, "q", 2))


def test_state_table_write_chunk_and_vnode_partitioning():
    t, store = _table(dist=[0])
    s = t.schema
    c = StreamChunk.from_pydict(
        s, {"k": [1, 2, 1], "s": ["a", "b", "a"], "v": [10, 20, 10]},
        ops=[Op.INSERT, Op.INSERT, Op.DELETE])
    t.write_chunk(c)
    assert t.get_row((2,)) == (2, "b", 20)
    assert t.get_row((1,)) is None               # insert+delete annihilated
    _advance(t)
    # row landed in the vnode derived from the dist key
    vnodes_with_data = {pk[0]: True for pk, _ in t.iter_rows()}
    assert vnodes_with_data == {2: True}
    from risingwave_tpu.common.hash import vnodes_of_host
    vn = int(vnodes_of_host([np.asarray([2], dtype=np.int64)])[0])
    assert [pk for pk, _ in t.iter_rows(vnode=vn)] == [(2,)]
    assert list(t.iter_rows(vnode=(vn + 1) % 256)) == []


def test_state_table_commit_epoch_progression():
    t, store = _table()
    t.insert((1, "a", 1))
    e_first = t.epoch.curr
    _advance(t)
    # data written at the sealed epoch
    assert store.get(7, t._encode_pk((1,)), e_first.value) == (1, "a", 1)
    assert store.get(7, t._encode_pk((1,)), e_first.value - 1) is None
    # commit with wrong epoch pair rejected
    with pytest.raises(AssertionError):
        t.commit(EpochPair(curr=t.epoch.curr.next(), prev=Epoch(1)))


def test_state_table_vnode_bitmap_swap():
    t, _ = _table()
    t.insert((1, "a", 1))
    with pytest.raises(AssertionError):
        t.update_vnode_bitmap(np.zeros(256, dtype=bool))
    _advance(t)
    prev = t.update_vnode_bitmap(np.arange(256) < 128)
    assert prev.all() and len(t.owned_vnodes()) == 128


def test_decimal_pk_physical_consistency():
    """StateTable rows/keys are physical: DECIMAL pk = scaled int64.

    Logical→physical normalization happens once at chunk ingest
    (types.decimal_to_scaled); the state layer never re-scales.
    """
    import decimal as _d
    from risingwave_tpu.common.types import decimal_to_scaled
    from risingwave_tpu.state.keycodec import encode_value
    phys = decimal_to_scaled(_d.Decimal("5"))
    assert phys == decimal_to_scaled(5) == decimal_to_scaled(5.0) == 50000
    assert encode_value(phys, DataType.DECIMAL) == \
        encode_value(50000, DataType.DECIMAL)

    schema = Schema.of(d=DataType.DECIMAL, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(9, schema, pk_indices=[0], store=store,
                   dist_key_indices=[0])
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    t.insert((phys, 1))
    assert t.get_row((phys,)) == (phys, 1)
    from risingwave_tpu.state.state_table import to_logical_row
    assert to_logical_row(t.get_row((phys,)), schema) == \
        (_d.Decimal("5"), 1)


def test_bulk_and_scalar_key_encoding_agree():
    """write_chunk's vectorized keys must equal the row-API's keys."""
    from risingwave_tpu.common.chunk import StreamChunk

    schema = Schema.of(a=DataType.INT64, f=DataType.FLOAT64,
                       b=DataType.BOOLEAN, d=DataType.DECIMAL,
                       v=DataType.VARCHAR)
    data = {
        "a": [-5, 0, 7, 2**40],
        "f": [-2.5, 0.0, 3.75, 1e300],
        "b": [True, False, True, False],
        "d": [decimal.Decimal("1.5"), decimal.Decimal("-2"),
              decimal.Decimal("0"), decimal.Decimal("99.9999")],
        "v": ["x", "y", "z", "w"],
    }
    chunk = StreamChunk.from_pydict(schema, data)
    store = MemoryStateStore()
    ta = StateTable(21, schema, pk_indices=[0, 1, 2, 3], store=store,
                    dist_key_indices=[0])
    tb = StateTable(22, schema, pk_indices=[0, 1, 2, 3], store=store,
                    dist_key_indices=[0])
    ta.write_chunk(chunk)
    _idx, rows, _ops = chunk.to_physical_records()
    for row in rows:
        tb.insert(row)
    keys_a = sorted(k for k, _ in ta.mem_table.iter_ops())
    keys_b = sorted(k for k, _ in tb.mem_table.iter_ops())
    assert keys_a == keys_b
    # and a varchar pk falls back to the scalar codec with the same result
    tc = StateTable(23, schema, pk_indices=[4, 0], store=store)
    td = StateTable(24, schema, pk_indices=[4, 0], store=store)
    tc.write_chunk(chunk)
    for row in rows:
        td.insert(row)
    assert sorted(k for k, _ in tc.mem_table.iter_ops()) == \
        sorted(k for k, _ in td.mem_table.iter_ops())


def test_negative_zero_and_null_distkey_key_consistency():
    """Code-review regressions: -0.0 pk and NULL dist-key rows must be
    addressable identically through write_chunk and the row API."""
    from risingwave_tpu.common.chunk import StreamChunk

    # -0.0 and 0.0 are one SQL value → one key on both paths
    schema = Schema.of(f=DataType.FLOAT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(31, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    chunk = StreamChunk.from_pydict(
        schema, {"f": np.asarray([-0.0]), "v": np.asarray([1])})
    t.write_chunk(chunk)
    assert t.get_row((-0.0,)) == (-0.0, 1) or t.get_row((0.0,)) == (-0.0, 1)
    t.delete((0.0, 1))          # scalar delete reaches the bulk-written row
    assert not t.mem_table.is_dirty()

    # NULL dist-key value: row lands in a vnode and stays addressable
    t2 = StateTable(32, schema, pk_indices=[0], store=store,
                    dist_key_indices=[0])
    t2.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    c2 = StreamChunk.from_pydict(
        schema, {"f": [None, 2.5], "v": [7, 8]})
    t2.write_chunk(c2)
    assert t2.get_row((None,)) == (None, 7)
    assert t2.get_row((2.5,)) == (2.5, 8)
    t2.delete((None, 7))
    assert t2.get_row((None,)) is None


def test_interner_gc_bounds_entries_to_live_state():
    """Interner entries retire with their last referencing value; ids
    stay stable for survivors and retired ids are reused only after GC
    proves them dead (VERDICT r3 weak #6)."""
    from risingwave_tpu.stream.executors.keys import Interner

    it = Interner()
    ids = {v: it.intern_one(v) for v in ("a", "b", "c", "d")}
    assert it.gc(["b", "d"]) == 2
    assert len(it) == 2
    # survivors keep their ids
    assert it.intern_one("b") == ids["b"]
    assert it.intern_one("d") == ids["d"]
    # dead ids are reused for NEW values
    new_id = it.intern_one("e")
    assert new_id in (ids["a"], ids["c"])
    # lookup of a retired id (defensive decode) yields None
    import numpy as np
    dead = [i for i in (ids["a"], ids["c"]) if i != new_id][0]
    assert it.lookup(np.asarray([dead]))[0] is None


def test_memory_context_accounting_and_eviction():
    from risingwave_tpu.utils.memory import MemoryContext

    m = MemoryContext(soft_limit_bytes=100)
    state = {"big": 200, "small": 10}
    m.register("big", lambda: state["big"],
               evict=lambda: state.__setitem__("big", 40) or 160)
    m.register("small", lambda: state["small"])
    assert m.total_bytes() == 210
    total = m.tick()
    assert state["big"] == 40          # evictor ran
    assert total <= 100


# -- staged all-insert writes (ISSUE 12 emit path) ---------------------------


def _epoch(n):
    return EpochPair(Epoch.from_physical(n + 1), Epoch.from_physical(n))


def test_deferred_write_chunk_skips_memtable_and_commits():
    schema = Schema.of(k=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(61, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    chunk = StreamChunk.from_pydict(schema, {"k": [1, 2], "v": [10, 20]})
    t.write_chunk(chunk, defer=True)
    # the fast path bypasses the memtable entirely…
    assert not t.mem_table.is_dirty() and t.is_dirty()
    t.commit(_epoch(1))
    # …and the rows are durable at commit
    assert t.get_row((1,)) == (1, 10) and t.get_row((2,)) == (2, 20)
    assert not t.is_dirty()


def test_deferred_stage_spills_on_interleaved_delete():
    """An insert staged this epoch then deleted this epoch must
    annihilate exactly as the memtable path would."""
    schema = Schema.of(k=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(62, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    t.write_chunk(StreamChunk.from_pydict(
        schema, {"k": [1, 2], "v": [10, 20]}), defer=True)
    t.delete((1, 10))              # spills the stage, then annihilates
    t.commit(_epoch(1))
    assert t.get_row((1,)) is None
    assert t.get_row((2,)) == (2, 20)


def test_deferred_stage_read_your_writes_mid_epoch():
    schema = Schema.of(k=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(63, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    t.write_chunk(StreamChunk.from_pydict(
        schema, {"k": [5], "v": [50]}), defer=True)
    # a read mid-epoch spills the stage and sees the buffered row
    assert t.get_row((5,)) == (5, 50)
    t.commit(_epoch(1))
    assert t.get_row((5,)) == (5, 50)


def test_deferred_mixed_op_chunk_falls_back():
    """A chunk carrying deletes never stages — it takes the memtable
    merge path even under defer=True."""
    schema = Schema.of(k=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(64, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    t.write_chunk(StreamChunk.from_pydict(
        schema, {"k": [1], "v": [10]}), defer=True)
    t.commit(_epoch(1))
    mixed = StreamChunk.from_pydict(
        schema, {"k": [1, 2], "v": [10, 20]},
        ops=[Op.DELETE, Op.INSERT])
    t.write_chunk(mixed, defer=True)
    assert t.mem_table.is_dirty()
    t.commit(_epoch(2))
    assert t.get_row((1,)) is None
    assert t.get_row((2,)) == (2, 20)


def test_deferred_multi_chunk_epoch_bit_identical_to_memtable_path():
    schema = Schema.of(k=DataType.INT64, v=DataType.FLOAT64)
    rng = np.random.default_rng(3)
    chunks = []
    k0 = 0
    for _ in range(4):
        n = int(rng.integers(3, 9))
        chunks.append(StreamChunk.from_pydict(
            schema, {"k": list(range(k0, k0 + n)),
                     "v": rng.normal(size=n).tolist()}))
        k0 += n
    stores = []
    for defer in (True, False):
        store = MemoryStateStore()
        t = StateTable(65, schema, pk_indices=[0], store=store)
        t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
        for c in chunks:
            t.write_chunk(c, defer=defer)
        t.commit(_epoch(1))
        stores.append(sorted(t.iter_rows()))
    assert stores[0] == stores[1]


def test_deferred_duplicate_pks_never_duplicate_scan_rows():
    """Review regression: duplicate pks staged in one epoch resolve
    last-wins in the store AND keep the key index unique — a scan must
    yield the pk once."""
    schema = Schema.of(k=DataType.INT64, v=DataType.INT64)
    store = MemoryStateStore()
    t = StateTable(66, schema, pk_indices=[0], store=store)
    t.init_epoch(EpochPair.new_initial(Epoch.from_physical(1)))
    t.write_chunk(StreamChunk.from_pydict(
        schema, {"k": [1, 1, 2], "v": [10, 11, 20]}), defer=True)
    t.commit(_epoch(1))
    rows = sorted(r for _pk, r in t.iter_rows())
    assert rows == [(1, 11), (2, 20)]
    assert store.table_size(66, 2 ** 40) == 2
