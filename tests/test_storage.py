"""Hummock-lite storage tests.

Mirrors the reference's storage test stances: SST round-trip +
prefix-compression (sstable tests), epoch-MVCC snapshot reads
(hummock_storage read-path tests), upload-at-sync + restart recovery
(uploader/manager tests), compaction correctness incl. tombstone GC
(compactor tests), and StateTable-over-Hummock parity with the
in-memory fake (test_state_table.rs shapes).
"""

import numpy as np
import pytest

from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import (
    LocalFsObjectStore, MemObjectStore,
)
from risingwave_tpu.storage.sst import (
    Sst, SstBuilder, full_key, split_full_key,
)
from risingwave_tpu.storage.value_codec import decode_row, encode_row


# -- value codec ---------------------------------------------------------


def test_value_codec_roundtrip():
    rows = [
        (),
        (1, -1, 0, 2**62, -(2**62)),
        (None, True, False, 3.5, -0.0, "héllo", b"\x00\xff"),
        (np.int64(7), np.float64(2.25), "",),
    ]
    for r in rows:
        got = decode_row(encode_row(r))
        want = tuple(
            v.item() if hasattr(v, "item") else v for v in r)
        assert got == want, (got, want)


def test_value_codec_rejects_out_of_range_and_keeps_np_bool():
    import pytest
    with pytest.raises(TypeError):
        encode_row((1 << 63,))
    with pytest.raises(TypeError):
        encode_row((-(1 << 63) - 1,))
    assert decode_row(encode_row((np.bool_(True), np.bool_(False)))) \
        == (True, False)


# -- full key ------------------------------------------------------------


def test_full_key_orders_epochs_descending():
    a = full_key(1, b"k", 5)
    b = full_key(1, b"k", 9)
    assert b < a                      # newer sorts first
    assert split_full_key(a) == (1, b"k", 5)
    assert full_key(1, b"k", 5) < full_key(1, b"l", 9)
    assert full_key(1, b"z", 1) < full_key(2, b"a", 1)


# -- SST -----------------------------------------------------------------


def _entries(n, table_id=7, epoch=3):
    out = []
    for i in range(n):
        key = b"key%06d" % i
        out.append((full_key(table_id, key, epoch), False,
                    encode_row((i, f"v{i}"))))
    return out


def test_sst_roundtrip_and_block_split():
    b = SstBuilder(1)
    entries = _entries(20000)         # forces multiple 64K blocks
    for fk, tomb, row in entries:
        b.add(fk, tomb, row)
    data, info = b.finish()
    assert info["count"] == 20000
    sst = Sst(data, info)
    assert len(sst.index) > 1
    got = list(sst.iter_from(b""))
    assert [g[0] for g in got] == [e[0] for e in entries]
    hit = sst.get(7, b"key013337", 10)
    assert hit is not None
    assert decode_row(hit[2]) == (13337, "v13337")
    # absent key: bloom or scan must both say no
    assert sst.get(7, b"nope", 10) is None
    # epoch below the version's epoch: invisible
    assert sst.get(7, b"key000001", 2) is None


def test_sst_bloom_prunes():
    b = SstBuilder(1)
    for fk, tomb, row in _entries(1000):
        b.add(fk, tomb, row)
    data, info = b.finish()
    sst = Sst(data, info)
    misses = sum(sst.may_contain(7, b"absent%d" % i) for i in range(1000))
    assert misses < 50                # ~1% false-positive target


# -- HummockLite ---------------------------------------------------------


E1, E2, E3, E4 = 1 << 16, 2 << 16, 3 << 16, 4 << 16


def _checkpoint(store, epoch):
    store.seal_epoch(epoch, True)
    store.sync(epoch)


def test_hummock_mvcc_snapshot_reads():
    h = HummockLite(MemObjectStore())
    h.ingest_batch(1, [(b"a", (1,)), (b"b", (2,))], E1)
    _checkpoint(h, E1)
    h.ingest_batch(1, [(b"a", (10,)), (b"b", None)], E2)
    _checkpoint(h, E2)
    assert h.get(1, b"a", E1) == (1,)
    assert h.get(1, b"a", E2) == (10,)
    assert h.get(1, b"b", E1) == (2,)
    assert h.get(1, b"b", E2) is None          # tombstone
    assert h.get(2, b"a", E2) is None          # table namespaces
    assert list(h.iter(1, E1)) == [(b"a", (1,)), (b"b", (2,))]
    assert list(h.iter(1, E2)) == [(b"a", (10,))]


def test_hummock_unsynced_reads_and_ranges():
    h = HummockLite(MemObjectStore())
    h.ingest_batch(1, [(b"a", (1,))], E1)
    # readable before seal/sync (shared buffer)
    assert h.get(1, b"a", E1) == (1,)
    h.seal_epoch(E1, True)
    # readable from imm before sync
    assert h.get(1, b"a", E1) == (1,)
    h.sync(E1)
    h.ingest_batch(1, [(b"c", (3,)), (b"b", (2,))], E2)
    assert [k for k, _ in h.iter(1, E2, start=b"b")] == [b"b", b"c"]
    assert [k for k, _ in h.iter(1, E2, end=b"b")] == [b"a"]


def test_hummock_restart_recovers_committed():
    obj = MemObjectStore()
    h = HummockLite(obj)
    h.ingest_batch(1, [(b"k%d" % i, (i,)) for i in range(100)], E1)
    _checkpoint(h, E1)
    h.ingest_batch(1, [(b"k0", (999,))], E2)   # never sealed/synced
    del h
    h2 = HummockLite(obj)
    assert h2.committed_epoch() == E1
    assert h2.get(1, b"k0", E1) == (0,)        # E2 write lost, as it must
    assert h2.table_size(1, E1) == 100


def test_hummock_restart_on_fs(tmp_path):
    obj = LocalFsObjectStore(str(tmp_path / "hummock"))
    h = HummockLite(obj)
    h.ingest_batch(3, [(b"x", ("s", 1.5, None))], E1)
    _checkpoint(h, E1)
    h2 = HummockLite(LocalFsObjectStore(str(tmp_path / "hummock")))
    assert h2.get(3, b"x", E1) == ("s", 1.5, None)


def test_hummock_compaction_merges_and_gcs():
    obj = MemObjectStore()
    h = HummockLite(obj)
    # 4 checkpoints → hits L0_COMPACT_THRESHOLD → compaction
    for j, e in enumerate([E1, E2, E3, E4]):
        h.ingest_batch(1, [(b"k%03d" % i, (j, i)) for i in range(50)], e)
        if j == 3:
            h.ingest_batch(1, [(b"k000", None)], e)   # delete k000
        _checkpoint(h, e)
    l0, l1 = h.levels
    assert l0 == 0 and l1 >= 1
    # shadowed versions dropped; newest state visible
    assert h.get(1, b"k001", E4) == (3, 1)
    assert h.get(1, b"k000", E4) is None
    assert h.table_size(1, E4) == 49
    # old epoch reads below committed are gone by design (history GC'd):
    # the committed snapshot is the recovery point, as in the reference.
    # Vacuum is DEFERRED one compaction cycle (lazy block readers get a
    # grace period): the replaced objects disappear at the NEXT compact.
    h.compact()
    data_objects = [p for p in obj.list("data/")
                    if int(p.split("/")[1].split(".")[0])
                    in {i["id"] for i in h._l1}]
    all_objects = obj.list("data/")
    live_ids = {i["id"] for i in h._l1}
    stale = [p for p in all_objects
             if int(p.split("/")[1].split(".")[0]) not in live_ids
             and int(p.split("/")[1].split(".")[0])
             not in {i["id"] for i in h._pending_vacuum}]
    assert stale == []          # nothing older than one cycle survives


def test_hummock_compaction_preserves_above_committed():
    """Versions newer than the committed epoch survive compaction."""
    h = HummockLite(MemObjectStore())
    for e in (E1, E2, E3, E4):
        h.ingest_batch(1, [(b"a", (e,))], e)
        h.seal_epoch(e, True)
        h.sync(E1)                      # commit only E1; E2.. stay newer
    h.compact()
    assert h.get(1, b"a", E1) == (E1,)
    assert h.get(1, b"a", E4) == (E4,)


# -- StateTable over HummockLite ----------------------------------------


SCHEMA = Schema([Field("k", DataType.INT64), Field("v", DataType.INT64),
                 Field("s", DataType.VARCHAR)])


def _drive_state_table(store):
    from risingwave_tpu.common.epoch import Epoch, EpochPair

    def pair(n):
        prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
        return EpochPair(Epoch.from_physical(n), prev)

    t = StateTable(11, SCHEMA, [0], store, dist_key_indices=[0])
    t.init_epoch(pair(1))
    t.insert((1, 10, "a"))
    t.insert((2, 20, None))
    t.commit(pair(2))
    store.seal_epoch(pair(2).prev.value, True)
    store.sync(pair(2).prev.value)
    t.update((1, 10, "a"), (1, 11, "a2"))
    t.delete((2, 20, None))
    t.insert((3, 30, "c"))
    t.commit(pair(3))
    store.seal_epoch(pair(3).prev.value, True)
    store.sync(pair(3).prev.value)
    return sorted(t.iter_rows())


def test_state_table_parity_memory_vs_hummock():
    mem = _drive_state_table(MemoryStateStore())
    hum = _drive_state_table(HummockLite(MemObjectStore()))
    assert mem == hum
    assert [r for _pk, r in hum] == [(1, 11, "a2"), (3, 30, "c")]


def test_hummock_prefix_related_keys():
    """User keys where one is a byte-prefix of another must order and
    shadow correctly (needs the prefix-free key escaping in sst.py)."""
    h = HummockLite(MemObjectStore())
    h.ingest_batch(1, [(b"ab", (1,)), (b"abc", (2,)), (b"a\x00b", (3,))],
                   E1)
    _checkpoint(h, E1)
    h.ingest_batch(1, [(b"ab", (10,))], E2)
    assert [kv for kv in h.iter(1, E2)] == \
        [(b"a\x00b", (3,)), (b"ab", (10,)), (b"abc", (2,))]
    _checkpoint(h, E2)
    h.compact()
    assert h.get(1, b"ab", E2) == (10,)
    assert h.get(1, b"abc", E2) == (2,)
    assert h.get(1, b"a\x00b", E2) == (3,)
    assert [kv for kv in h.iter(1, E2)] == \
        [(b"a\x00b", (3,)), (b"ab", (10,)), (b"abc", (2,))]


def test_hummock_empty_checkpoint_uploads_nothing():
    h = HummockLite(MemObjectStore())
    h.ingest_batch(1, [], E1)
    _checkpoint(h, E1)
    assert h.levels == (0, 0)
    assert h.obj.list("data/") == []
    assert h.committed_epoch() == E1


def test_storage_trace_record_replay(tmp_path):
    """hummock_trace parity: record a StateTable workload, replay it
    against a FRESH store with byte-identical read results; a
    corrupted replay is detected."""
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.storage.trace import (
        TracingStateStore, load_trace, replay_trace,
    )

    S = Schema.of(k=DataType.INT64, v=DataType.VARCHAR)
    store = TracingStateStore(MemoryStateStore())
    t = StateTable(7, S, [0], store)
    e1 = EpochPair(Epoch.from_physical(1), Epoch.INVALID)
    e2 = EpochPair(Epoch.from_physical(2), Epoch.from_physical(1))
    e3 = EpochPair(Epoch.from_physical(3), Epoch.from_physical(2))
    t.init_epoch(e1)
    t.insert((1, "a"))
    t.insert((2, None))
    t.commit(e2)
    store.seal_epoch(e2.prev.value)
    assert t.get_row((1,)) == (1, "a")
    t.update((1, "a"), (1, "a2"))
    t.delete((2, None))
    t.commit(e3)
    store.seal_epoch(e3.prev.value)
    assert t.get_row((1,)) == (1, "a2")
    assert t.get_row((2,)) is None
    assert [r for _pk, r in t.iter_rows()] == [(1, "a2")]
    path = str(tmp_path / "trace.jsonl")
    n = store.dump(path)
    assert n > 5

    records = load_trace(path)
    assert replay_trace(records, MemoryStateStore()) == []

    # corrupt one recorded read result: replay must flag it
    bad = [dict(r) for r in records]
    for r in bad:
        if r["op"] == "get" and r["result"] is not None:
            r["result"] = {"__t": ["poison"]}
            break
    assert replay_trace(bad, MemoryStateStore()) != []


def test_block_cache_and_lazy_sst_parity():
    """LazySst (ranged reads + block cache) returns byte-identical
    results to the whole-bytes reader; point gets touch ONE block;
    vacuumed SSTs drop their blocks (sstable_store.rs block_cache)."""
    from risingwave_tpu.storage.block_cache import BlockCache
    from risingwave_tpu.storage.object_store import MemObjectStore
    from risingwave_tpu.storage.sst import (
        LazySst, Sst, SstBuilder, full_key,
    )

    import risingwave_tpu.storage.sst as sstmod
    old_target = sstmod.BLOCK_TARGET
    sstmod.BLOCK_TARGET = 256             # many small blocks
    b = SstBuilder(1)
    for i in range(500):
        fk = full_key(7, f"k{i:05d}".encode(), 5)
        b.add(fk, False, f"v{i}".encode())
    data, info = b.finish()
    sstmod.BLOCK_TARGET = old_target
    obj = MemObjectStore()
    obj.upload("data/1.sst", data)
    cache = BlockCache(capacity_bytes=1 << 20)
    lazy = LazySst(obj, "data/1.sst", info, cache=cache)
    whole = Sst(data, info)
    assert len(lazy.index) == len(whole.index) > 4
    # full-scan parity
    assert list(lazy.iter_from(b"")) == list(whole.iter_from(b""))
    # point get: exactly one block loaded into a fresh cache
    cache2 = BlockCache()
    lazy2 = LazySst(obj, "data/1.sst", info, cache=cache2)
    hit = lazy2.get(7, b"k00250", 10)
    assert hit is not None and hit[2] == b"v250"
    assert cache2.misses == 1 and cache2.nbytes() > 0
    # reverse parity
    assert list(lazy.iter_rev()) == list(reversed(
        list(whole.iter_from(b""))))
    mid = full_key(7, b"k00100", 0)
    assert list(lazy.iter_rev(mid)) == list(reversed(
        [e for e in whole.iter_from(b"") if e[0] <= mid]))
    # eviction under byte budget
    tiny = BlockCache(capacity_bytes=600)
    lz = LazySst(obj, "data/1.sst", info, cache=tiny)
    list(lz.iter_from(b""))
    assert tiny.nbytes() <= 600
    # vacuum drop
    cache.drop_sst(1)
    assert cache.nbytes() == 0


def test_hummock_reverse_iteration_all_layers():
    """Backward iterator across mem + imm + L0 + compacted L1 equals
    the forward scan reversed, newest version per key."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    h = HummockLite(MemObjectStore())
    # epoch 1: keys 0..99 → SST (L0)
    h.ingest_batch(7, [(f"k{i:03d}".encode(), (i,)) for i in range(100)],
                   epoch=1)
    h.seal_epoch(1)
    h.sync(1)
    # epoch 2: overwrite evens, delete multiples of 10 → second SST,
    # then force a compaction into L1
    h.ingest_batch(7, [(f"k{i:03d}".encode(),
                        None if i % 10 == 0 else (i * 100,))
                       for i in range(0, 100, 2)], epoch=2)
    h.seal_epoch(2)
    h.sync(2)
    h.compact()
    # epoch 3: fresh keys still in MEM (unsealed)
    h.ingest_batch(7, [(b"k200", (200,)), (b"k201", (201,))], epoch=3)

    fwd = list(h.iter(7, epoch=3))
    rev = list(h.iter(7, epoch=3, reverse=True))
    assert rev == list(reversed(fwd))
    assert ("k200".encode(), (200,)) in fwd
    got = dict(fwd)
    assert got[b"k002"] == (200,) and b"k010" not in got
    assert got[b"k001"] == (1,)
    # bounded reverse range
    rev_rng = list(h.iter(7, epoch=3, start=b"k005", end=b"k011",
                          reverse=True))
    assert [k for k, _ in rev_rng] == [b"k009", b"k008", b"k007",
                                       b"k006", b"k005"]


def test_state_table_reverse_iter_with_memtable():
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore

    S = Schema.of(k=DataType.INT64, v=DataType.INT64)
    t = StateTable(9, S, [0], MemoryStateStore())
    e1 = EpochPair(Epoch.from_physical(1), Epoch.INVALID)
    e2 = EpochPair(Epoch.from_physical(2), Epoch.from_physical(1))
    t.init_epoch(e1)
    for k in (3, 1, 2):
        t.insert((k, k * 10))
    t.commit(e2)
    t.insert((0, 0))            # buffered (memtable) row merges too
    fwd = [r for _pk, r in t.iter_rows()]
    rev = [r for _pk, r in t.iter_rows(reverse=True)]
    assert fwd == [(0, 0), (1, 10), (2, 20), (3, 30)]
    assert rev == list(reversed(fwd))


def test_leveled_compaction_keeps_disjoint_runs():
    """Level picker: L0 merges with only the OVERLAPPING L1 runs;
    disjoint runs carry over untouched (same object ids), and reads
    over the spliced L1 stay exact."""
    import risingwave_tpu.storage.hummock as hm
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    h = HummockLite(MemObjectStore())
    old_target = hm.L1_TARGET_SST_BYTES
    hm.L1_TARGET_SST_BYTES = 2048       # force several small runs
    try:
        # build an L1 with several disjoint runs over keys a..z
        h.ingest_batch(1, [(f"{c}{i:03d}".encode(), (i,))
                           for c in "acegikmoqsuwy"
                           for i in range(40)], epoch=1)
        h.seal_epoch(1)
        h.sync(1)
        h.compact()                     # full: everything into L1
        runs_before = {i["id"] for i in h._l1}
        assert len(runs_before) > 3
        # L0 touching only the 'm'..'o' range
        h.ingest_batch(1, [(f"m{i:03d}".encode(), (i * 10,))
                           for i in range(40)], epoch=2)
        h.seal_epoch(2)
        h.sync(2)
        h.compact()
        runs_after = {i["id"] for i in h._l1}
        # untouched runs carried over by id; some new ids appeared
        carried = runs_before & runs_after
        assert carried, "picker rewrote disjoint runs"
        assert runs_after - runs_before, "no rewritten range?"
        # reads exact across the splice
        got = dict(h.iter(1, epoch=2))
        assert got[b"m005"] == (50,)      # updated range
        assert got[b"a005"] == (5,)       # untouched range
        assert got[b"y039"] == (39,)
        # L1 stays sorted + key-disjoint
        bounds = [(bytes.fromhex(i["smallest"]), bytes.fromhex(
            i["largest"])) for i in h._l1]
        for (s1, l1), (s2, _l2) in zip(bounds, bounds[1:]):
            assert l1 < s2
    finally:
        hm.L1_TARGET_SST_BYTES = old_target


def test_two_phase_staging_semantics(tmp_path):
    """Worker-mode HummockLite: sync() STAGES; the version advances
    only at commit_through; discard_staged_above drops uncommitted
    epochs; a restart before the FIRST commit neither reuses staged
    SST ids nor re-seals staged epochs (coordinator-owned commit,
    HummockManager::commit_epoch split)."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    root = str(tmp_path / "tp")
    s = HummockLite(LocalFsObjectStore(root), two_phase=True)
    s.ingest_batch(7, [(b"a", (1,)), (b"b", (2,))], epoch=100)
    s.seal_epoch(100)
    s.sync(100)
    # staged, not committed — but readable at its epoch
    assert s.committed_epoch() == 0
    assert s.get(7, b"a", 100) == (1,)
    # restart BEFORE any commit: staged survives, ids/epochs reserved
    s2 = HummockLite(LocalFsObjectStore(root), two_phase=True)
    assert s2.committed_epoch() == 0
    assert s2.get(7, b"a", 100) == (1,)
    assert s2._next_sst_id > s._staged[0]["sst"]["id"] if s._staged \
        else True
    s2.ingest_batch(7, [(b"c", (3,))], epoch=200)
    s2.seal_epoch(200)
    s2.sync(200)
    ids = {st["sst"]["id"] for st in s2._staged}
    assert len(ids) == len(s2._staged) == 2      # no id reuse
    # commit through 100: epoch 100 visible in the committed version
    s2.commit_through(100)
    assert s2.committed_epoch() == 100
    # discard the uncommitted 200 (crash recovery to floor 100)
    assert s2.discard_staged_above(100) == 1
    assert s2.get(7, b"c", 300) is None
    # fresh open sees exactly the committed state
    s3 = HummockLite(LocalFsObjectStore(root), two_phase=True)
    assert s3.committed_epoch() == 100
    assert s3.get(7, b"a", 100) == (1,)
    assert s3.get(7, b"c", 300) is None


# -- async checkpoint split (build → upload → commit) --------------------


def test_build_commit_split_keeps_data_readable():
    """Between build_ssts and commit_ssts the flushed data lives in the
    in-memory uploading layer: reads see it, the object store doesn't
    yet, and the manifest only advances at commit."""
    obj = MemObjectStore()
    h = HummockLite(obj)
    h.ingest_batch(1, [(b"a", (1,)), (b"b", (2,))], 100)
    h.seal_epoch(100, True)
    payloads = h.build_ssts(100)
    assert len(payloads) == 1
    # readable while the upload is "in flight"...
    assert h.get(1, b"a", 100) == (1,)
    assert dict(h.iter(1, 100)) == {b"a": (1,), b"b": (2,)}
    # ...but nothing uploaded or committed yet
    assert not obj.list("data/")
    assert h.committed_epoch() == 0
    for p in payloads:
        h.upload_payload(p)
    h.commit_ssts(100, payloads)
    assert h.committed_epoch() == 100
    assert h.get(1, b"a", 100) == (1,)
    # a reboot sees exactly the committed version
    h2 = HummockLite(obj)
    assert h2.committed_epoch() == 100
    assert dict(h2.iter(1, 100)) == {b"a": (1,), b"b": (2,)}


def test_build_commit_split_ordered_epochs():
    """Two epochs built back-to-back (the uploader's chained builds):
    each build drains only its own imms, reads merge both layers, and
    in-order commits publish both."""
    obj = MemObjectStore()
    h = HummockLite(obj)
    h.ingest_batch(1, [(b"k", (1,))], 100)
    h.seal_epoch(100, True)
    p1 = h.build_ssts(100)
    h.ingest_batch(1, [(b"k", (2,)), (b"l", (9,))], 200)
    h.seal_epoch(200, True)
    p2 = h.build_ssts(200)
    # snapshot semantics across the two uploading layers
    assert h.get(1, b"k", 100) == (1,)
    assert h.get(1, b"k", 200) == (2,)
    assert h.get(1, b"l", 100) is None
    for p in p1 + p2:
        h.upload_payload(p)
    h.commit_ssts(100, p1)
    assert h.committed_epoch() == 100
    h.commit_ssts(200, p2)
    assert h.committed_epoch() == 200
    h2 = HummockLite(obj)
    assert h2.get(1, b"k", 200) == (2,)
    assert h2.get(1, b"k", 100) == (1,)
