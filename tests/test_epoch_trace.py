"""Epoch-causal tracing (ISSUE 7): flight recorder, span propagation,
slow-barrier promotion + straggler diagnosis, Perfetto export, SQL/ctl
surfaces, and the steady-state recompile guard.

The acceptance case: a forced-slow barrier in a 2-worker cluster yields
ONE causally-linked trace — coordinator inject → worker actor spans →
cross-worker exchange edge → device dispatch → commit — exported as
valid Chrome trace-event JSON, with the straggler diagnosis naming the
injected laggard (a sleep-spec failpoint on the agg executor).
"""

import asyncio
import json
import os
import struct

import pytest

from risingwave_tpu.utils import spans as spans_mod
from risingwave_tpu.utils.spans import EPOCH_TRACER, EpochTracer

EVENTS = 4000

BID_SOURCE = (
    "CREATE SOURCE bid WITH (connector='nexmark', "
    "nexmark.table.type='bid', nexmark.event.num={n}, "
    "nexmark.max.chunk.size=256, nexmark.min.event.gap.in.ns=50000000)")

Q7ISH_MV = (
    "CREATE MATERIALIZED VIEW q7 AS "
    "SELECT window_start, MAX(price) AS max_price, COUNT(*) AS cnt "
    "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
    "GROUP BY window_start")


@pytest.fixture(autouse=True)
def _fresh_tracer():
    """Each test starts with an empty flight recorder and tracing ON
    (the always-on default), and leaves it that way."""
    EPOCH_TRACER.clear()
    spans_mod.set_enabled(True)
    yield
    EPOCH_TRACER.clear()
    spans_mod.set_enabled(True)


# -- span model / flight recorder -----------------------------------------


def test_flight_recorder_bounds_and_roots():
    t = EpochTracer(epoch_window=4, max_spans=8, retain_slots=2)
    root = t.record("barrier.inject", "barrier", epoch=1)
    t.set_root(1, root)
    child = t.record("HashAggExecutor", "actor", epoch=1, dur_s=0.5,
                     actor=7)
    [s] = [s for s in t.spans_for(1) if s.span_id == child]
    assert s.parent_id == root          # default parent = epoch root
    # per-epoch span cap: overflow is counted, not silently grown
    for i in range(20):
        t.record(f"s{i}", "dispatch", epoch=2)
    assert len(t.spans_for(2)) == 8
    assert t.dropped == 12
    # epoch window: only the newest 4 epochs stay
    for e in range(3, 9):
        t.record("x", "barrier", epoch=e)
    assert 1 not in t.epochs() and 8 in t.epochs()
    # promotion survives the ring rolling past the epoch
    t.record("slow", "actor", epoch=9, dur_s=1.0, actor=3)
    t.promote(9, "diag-line", total_s=1.0)
    for e in range(10, 20):
        t.record("x", "barrier", epoch=e)
    assert any(s.name == "slow" for s in t.spans_for(9))
    assert t.diagnosis_for(9) == "diag-line"
    # retain_slots bound
    t.promote(18, "a", 1.0)
    t.promote(19, "b", 1.0)
    assert 9 not in t.retained_epochs()


def test_diagnose_names_largest_actor_span():
    t = EpochTracer()
    r = t.record("barrier.inject", "barrier", epoch=5)
    t.set_root(5, r)
    t.record("FilterExecutor", "actor", epoch=5, dur_s=0.1, actor=1)
    t.record("HashAggExecutor(actor=2)", "actor", epoch=5, dur_s=1.6,
             actor=2)
    d = t.diagnose(5, 2.0)
    assert "HashAggExecutor(actor=2)" in d
    assert "actor 2" in d and "80%" in d
    # merged worker spans can retake the diagnosis after promotion
    t.promote(5, t.diagnose(5, 2.0), total_s=2.0)
    t.ingest([{"name": "SlowJoin", "cat": "actor", "epoch": 5,
               "start_s": 0.0, "dur_s": 1.9, "span_id": 999,
               "actor": 9}], worker="worker-1")
    t.refresh_diagnoses()
    assert "SlowJoin" in t.diagnosis_for(5)
    assert "@worker-1" in t.diagnosis_for(5)


def test_chrome_export_is_valid_and_causal():
    t = EpochTracer()
    r = t.record("barrier.inject", "barrier", epoch=3)
    t.set_root(3, r)
    t.record("MaterializeExecutor", "actor", epoch=3, dur_s=0.2,
             actor=4)
    out = json.loads(json.dumps(t.export_chrome()))
    evs = out["traceEvents"]
    xs = [e for e in evs if e["ph"] == "X"]
    assert len(xs) == 2
    for e in xs:
        assert {"name", "cat", "ph", "ts", "dur", "pid",
                "tid"} <= set(e)
    # the causal edge: one s/f flow pair sharing the CHILD's span id,
    # 's' leaving the parent's lane, 'f' landing on the child's lane
    # at its start, start never after finish (Perfetto drops flows
    # whose start postdates their finish)
    child = next(e for e in xs if e["name"] == "MaterializeExecutor")
    cid = child["args"]["span_id"]
    root = next(e for e in xs if e["name"] == "barrier.inject")
    [fs] = [e for e in evs if e["ph"] == "s" and e["id"] == cid]
    [ff] = [e for e in evs if e["ph"] == "f" and e["id"] == cid]
    assert (fs["pid"], fs["tid"]) == (root["pid"], root["tid"])
    assert (ff["pid"], ff["tid"]) == (child["pid"], child["tid"])
    assert fs["ts"] <= ff["ts"] == child["ts"]


def test_p99_breakdown_returns_zeros_on_empty_profiles():
    """Satellite: an empty/fully-warmup-trimmed profile deque yields
    all-zero phases, never a raise (bench snapshots run right after
    the warmup trim)."""
    from risingwave_tpu.meta.barrier import EpochProfiler
    p = EpochProfiler()
    zeros = {"inject_to_collect_s": 0.0, "collect_to_commit_s": 0.0,
             "upload_s": 0.0}
    assert p.p99_breakdown() == zeros
    p.record(1, "checkpoint", 0.5, 0.1, 1, {})
    assert p.p99_breakdown()["inject_to_collect_s"] == 0.5
    p.drop_first(10)               # trim past everything recorded
    assert p.p99_breakdown() == zeros


# -- remote-exchange span context ------------------------------------------


def _mk_barrier(mutation=None):
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    from risingwave_tpu.stream.message import Barrier, BarrierKind
    return Barrier(EpochPair(Epoch(0x30000), Epoch(0x20000)),
                   BarrierKind.CHECKPOINT, mutation)


def test_barrier_trailer_roundtrip_and_off_byte_identical():
    from risingwave_tpu.stream.message import StopMutation
    from risingwave_tpu.stream.remote import encode_barrier
    from risingwave_tpu.stream.trace_ctx import (
        barrier_trailer, decode_trailer,
    )
    b = _mk_barrier()
    root = EPOCH_TRACER.record("barrier.inject", "barrier",
                               epoch=0x30000)
    EPOCH_TRACER.set_root(0x30000, root)
    payload = encode_barrier(b) + barrier_trailer(b)
    epoch, parent, ts = decode_trailer(payload)
    assert epoch == 0x30000 and parent == root and ts > 0
    # the trailer must survive next to a stop mutation's actor list
    bs = _mk_barrier(StopMutation(frozenset({7, 9})))
    payload = encode_barrier(bs) + barrier_trailer(bs)
    from risingwave_tpu.stream.remote import decode_barrier
    decoded = decode_barrier(payload)
    assert decoded.mutation.actors == frozenset({7, 9})
    assert decode_trailer(payload)[0] == 0x30000
    # tracing off ⇒ byte-identical to the bare wire format of today
    spans_mod.set_enabled(False)
    payload_off = encode_barrier(b) + barrier_trailer(b)
    expected = struct.pack(">BQQB", 2, 0x30000, 0x20000, 0)
    assert payload_off == expected


def test_remote_exchange_propagates_span_context():
    """Round trip over a real TCP exchange edge: the receiver records
    an exchange-transfer span parented to the sender's inject span;
    with tracing off, no span and no trailer."""
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.stream.remote import ExchangeServer, RemoteInput

    schema = Schema.of(v=DataType.INT64)

    async def run():
        srv = ExchangeServer()
        await srv.serve()
        out = srv.register_edge(11, 22)
        inp = RemoteInput("127.0.0.1", srv.port, 11, 22, schema)
        b = _mk_barrier()
        root = EPOCH_TRACER.record("barrier.inject", "barrier",
                                   epoch=b.epoch.curr.value)
        EPOCH_TRACER.set_root(b.epoch.curr.value, root)

        async def pump():
            await out.send(b)
            out.close()

        task = asyncio.ensure_future(pump())
        got = [m async for m in inp.execute()]
        await task
        await srv.close()
        return got, root, b.epoch.curr.value

    got, root, epoch = asyncio.run(run())
    assert len(got) == 1
    edges = [s for s in EPOCH_TRACER.spans_for(epoch)
             if s.cat == "exchange"]
    assert len(edges) == 1
    assert edges[0].parent_id == root
    assert edges[0].args["edge"] == "11->22"


def test_remote_exchange_tracing_off_no_spans():
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.stream.remote import ExchangeServer, RemoteInput

    spans_mod.set_enabled(False)
    schema = Schema.of(v=DataType.INT64)

    async def run():
        srv = ExchangeServer()
        await srv.serve()
        out = srv.register_edge(1, 2)
        inp = RemoteInput("127.0.0.1", srv.port, 1, 2, schema)
        b = _mk_barrier()

        async def pump():
            await out.send(b)
            out.close()

        task = asyncio.ensure_future(pump())
        got = [m async for m in inp.execute()]
        await task
        await srv.close()
        return got

    got = asyncio.run(run())
    assert len(got) == 1
    assert EPOCH_TRACER.epochs() == []


# -- end-to-end: one process ----------------------------------------------


def _run_q7ish(trace_on: bool, slow_threshold: float = 1.0,
               failpoints_armed=None):
    """Frontend + q7-shaped MV; returns (mv rows, promoted epochs,
    diagnoses, trace rows via SQL)."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.utils.failpoint import failpoints

    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            f"SET stream_trace = '{'on' if trace_on else 'off'}'")
        await fe.execute(BID_SOURCE.format(n=EVENTS))
        await fe.execute(Q7ISH_MV)
        fe.loop.profiler.slow_threshold_s = slow_threshold
        await fe.step(8)
        rows = await fe.execute("SELECT * FROM q7")
        trace_rows = await fe.execute(
            "SELECT * FROM rw_epoch_trace")
        await fe.close()
        return rows, trace_rows

    if failpoints_armed:
        with failpoints(failpoints_armed):
            rows, trace_rows = asyncio.run(run())
    else:
        rows, trace_rows = asyncio.run(run())
    retained = list(EPOCH_TRACER.retained_epochs())
    diags = [EPOCH_TRACER.diagnosis_for(e) for e in retained]
    return {tuple(r) for r in rows}, retained, diags, trace_rows


def test_frontend_trace_end_to_end_and_oracle_unchanged():
    """Tracing on yields inject→actor→dispatch→commit spans reachable
    over SQL; tracing off records nothing; MV output is bit-identical
    either way."""
    rows_on, _retained, _d, trace_rows = _run_q7ish(True)
    cats = {r[4] for r in trace_rows}
    assert {"barrier", "actor", "dispatch", "commit"} <= cats, cats
    # warmup compiles are visible events
    assert "compile" in cats
    # causal linkage: every actor span parents to its epoch's root
    by_id = {r[1]: r for r in trace_rows if r[1] != 0}
    actor_rows = [r for r in trace_rows if r[4] == "actor"]
    assert actor_rows
    for r in actor_rows:
        parent = by_id.get(r[2])
        assert parent is not None and parent[0] == r[0], \
            (r, "actor span must parent into its own epoch")
    # dispatch spans carry kernel identity + rows
    disp = [r for r in trace_rows if r[4] == "dispatch"]
    assert any("HashAgg" in r[3] for r in disp)
    assert any(json.loads(r[10] or "{}").get("rows", 0) > 0
               for r in disp)

    EPOCH_TRACER.clear()
    rows_off, _r, _d, trace_rows_off = _run_q7ish(False)
    assert trace_rows_off == []
    assert rows_on == rows_off


def test_slow_barrier_promotes_trace_with_straggler_diagnosis(capfd):
    """A forced-slow agg (sleep failpoint) trips the watchdog: the
    epoch's full trace lands in the retained store and the one-line
    diagnosis names the laggard executor."""
    _rows, retained, diags, trace_rows = _run_q7ish(
        True, slow_threshold=0.05,
        failpoints_armed={"trace.slow.HashAggExecutor":
                          {"sleep_s": 0.12}})
    assert retained, "no slow barrier was promoted"
    assert any("HashAggExecutor" in d for d in diags), diags
    err = capfd.readouterr().err
    assert "slow barrier:" in err and "straggler" in err
    # the diagnosis also rides the system table
    assert any(r[4] == "diagnosis" and "HashAggExecutor" in r[3]
               for r in trace_rows)


def test_set_stream_trace_rides_ddl_log(tmp_path):
    """SET stream_trace persists in the DDL log like stream_fusion: a
    recovered frontend comes back with the operator's setting."""
    from risingwave_tpu.frontend.session import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    async def run():
        store = HummockLite(LocalFsObjectStore(str(tmp_path)))
        fe = Frontend(store)
        await fe.execute("SET stream_trace = 'off'")
        await fe.execute(BID_SOURCE.format(n=400))
        await fe.execute(Q7ISH_MV)
        await fe.step(2)
        await fe.close()
        assert not spans_mod.enabled()
        spans_mod.set_enabled(True)     # recovery must switch it back

        fe2 = Frontend(HummockLite(LocalFsObjectStore(str(tmp_path))))
        await fe2.recover()
        on_after_recover = spans_mod.enabled()
        shown = await fe2.execute("SHOW stream_trace")
        await fe2.close()
        return on_after_recover, shown

    on_after, shown = asyncio.run(run())
    assert on_after is False
    assert shown == [("off",)]


def test_set_stream_trace_validates():
    from risingwave_tpu.frontend.planner import PlanError
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend()
        with pytest.raises(PlanError):
            await fe.execute("SET stream_trace = 'sideways'")
        # TO DEFAULT restores on
        await fe.execute("SET stream_trace = 'off'")
        await fe.execute("SET stream_trace TO DEFAULT")
        return await fe.execute("SHOW stream_trace")

    assert asyncio.run(run()) == [("on",)]
    assert spans_mod.enabled()


# -- latency-budget bench mode (satellite) ---------------------------------


def test_bench_latency_budget_parse_and_verdict():
    """bench.py --latency-budget: spec parsing (per-query + bare-float
    default) and the per-query p99-vs-budget verdict, including the
    over-budget path that fails the round with a non-zero exit."""
    import bench

    budgets = bench._parse_latency_budgets(
        ["--latency-budget", "2.0, q5=4, adctr=30"])
    assert budgets == {"*": 2.0, "q5": 4.0, "adctr": 30.0}

    headline = {
        "q7": {"p99_barrier_latency_s": 1.1},
        "q5": {"p99_barrier_latency_s": 3.2},
        "adctr": {"error": "boom"},           # measured nothing
        "value": 1234.5,                      # non-dict headline keys
    }
    v = bench._latency_verdict(headline, budgets)
    assert v["verdicts"]["q7"]["verdict"] == "ok"
    assert v["verdicts"]["q5"]["verdict"] == "ok"        # 3.2 < 4
    assert v["verdicts"]["adctr"]["verdict"] == "no-measurement"
    assert v["ok"] is False                   # no-measurement fails

    # a query past its budget flips the round verdict
    v2 = bench._latency_verdict(
        {"q7": {"p99_barrier_latency_s": 2.5}}, {"*": 2.0})
    assert v2["verdicts"]["q7"]["verdict"] == "over-budget"
    assert v2["ok"] is False

    v3 = bench._latency_verdict(
        {"q7": {"p99_barrier_latency_s": 0.5}}, {"*": 2.0})
    assert v3["ok"] is True

    # flag absent -> the DEFAULT budget string arms (ISSUE 9: adctr
    # and the *_fused twins are gated every round — the bare-float
    # default covers the twins, adctr/q5 get explicit headroom)
    d = bench._parse_latency_budgets([])
    assert d == bench._parse_latency_budgets(
        ["--latency-budget", bench.DEFAULT_LATENCY_BUDGET])
    assert "*" in d and "adctr" in d and "q5_fused" in d
    # the '*' default must not gate entries with no p99 measurement
    # (the chaos round reports MTTR, not barrier latency)
    v4 = bench._latency_verdict(
        {"q7": {"p99_barrier_latency_s": 0.5}, "chaos": {"mttr": 1.3}},
        {"*": 2.0})
    assert v4["ok"] is True and "chaos" not in v4["verdicts"]
    # explicit empty spec -> mode off, nothing recorded
    assert bench._parse_latency_budgets(["--latency-budget", ""]) == {}


# -- steady-state recompile guard (satellite) ------------------------------


def test_q7_steady_state_never_retraces(recompile_guard):
    """Tier-1 shape-stability oracle: after the warmup epochs of a q7
    run have compiled every shape bucket, further steady-state epochs
    must not retrace a single jitted kernel."""
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7
    from risingwave_tpu.state.store import MemoryStateStore

    cfg = NexmarkConfig(event_num=6000, max_chunk_size=256,
                        generate_strings=False)
    p = build_q7(MemoryStateStore(), cfg, rate_limit=4, min_chunks=4)

    async def drive(epochs):
        for _ in range(epochs):
            await p.loop.inject_and_collect(force_checkpoint=True)

    async def run():
        from risingwave_tpu.stream.message import StopMutation
        task = p.actor.spawn()
        t0 = recompile_guard.total()
        await drive(6)                       # warmup: compiles land
        warm = recompile_guard.total() - t0
        t1 = recompile_guard.total()
        await drive(6)                       # steady state
        steady = recompile_guard.total() - t1
        await p.loop.inject_and_collect(
            mutation=StopMutation(frozenset({p.actor.actor_id})))
        await task
        return warm, steady

    warm, steady = asyncio.run(run())
    assert warm > 0, "warmup should have traced the agg kernels"
    recompile_guard.check_steady(steady)
    # the compile events are also visible in the trace
    assert any(s.cat == "compile"
               for e in EPOCH_TRACER.epochs()
               for s in EPOCH_TRACER.spans_for(e))


# -- the 2-worker acceptance case ------------------------------------------


def test_cluster_two_worker_slow_barrier_causal_trace(tmp_path):
    """Forced-slow barrier on a 2-worker cluster: one causally-linked
    trace (coordinator inject → worker actor spans → cross-worker
    exchange edge → device dispatch → commit), valid Chrome JSON, and
    a straggler diagnosis naming the injected laggard."""
    from risingwave_tpu.cluster.session import DistFrontend

    os.environ["RW_TPU_FAILPOINTS"] = json.dumps(
        {"trace.slow.HashAggExecutor": {"sleep_s": 0.4}})
    try:
        async def run():
            fe = DistFrontend(str(tmp_path), n_workers=2,
                              parallelism=2)
            await fe.start()
            try:
                fe.cluster.loop.profiler.slow_threshold_s = 0.1
                await fe.execute(BID_SOURCE.format(n=EVENTS))
                await fe.execute(Q7ISH_MV)
                await fe.step(6)
                n = await fe.drain_trace()
                rows = await fe.execute(
                    "SELECT * FROM rw_epoch_trace")
                # close() promotes one more (undrained) stop-barrier
                # epoch — snapshot the drained ones now
                return n, rows, EPOCH_TRACER.retained_epochs()
            finally:
                await fe.close()

        n_spans, trace_rows, retained = asyncio.run(run())
    finally:
        del os.environ["RW_TPU_FAILPOINTS"]

    assert n_spans > 0, "workers shipped no spans"
    assert retained, "the forced-slow barrier was not promoted"
    epoch = retained[-1]
    spans = EPOCH_TRACER.spans_for(epoch)
    by_cat = {}
    for s in spans:
        by_cat.setdefault(s.cat, []).append(s)
    # the full causal chain is present in ONE epoch's trace
    assert "barrier" in by_cat          # coordinator + worker inject
    assert "actor" in by_cat            # worker executor spans
    assert "exchange" in by_cat         # cross-worker edge
    assert "dispatch" in by_cat         # agg kernel dispatch
    assert "commit" in by_cat
    workers = {s.worker for s in spans}
    assert {"worker-0", "worker-1"} <= workers, workers
    # causal linkage, coordinator → worker: every worker inject span
    # parents to the coordinator's inject root for the same epoch
    root = next(s for s in by_cat["barrier"]
                if s.name == "barrier.inject")
    winjects = [s for s in by_cat["barrier"]
                if s.name == "barrier.inject.worker"]
    assert winjects
    assert all(s.parent_id == root.span_id for s in winjects)
    # exchange edges parent to a worker-side inject span
    winject_ids = {s.span_id for s in winjects}
    assert any(s.parent_id in winject_ids
               for s in by_cat["exchange"])
    # the diagnosis names the injected laggard
    diag = EPOCH_TRACER.diagnosis_for(epoch)
    assert "HashAggExecutor" in diag, diag
    assert any(r[4] == "diagnosis" and "HashAggExecutor" in r[3]
               for r in trace_rows)
    # and the whole thing exports as valid Chrome trace JSON
    out = json.loads(json.dumps(
        EPOCH_TRACER.export_chrome(epochs=[epoch])))
    xs = [e for e in out["traceEvents"] if e["ph"] == "X"]
    # counter-sample spans (phase-ledger byte/queue tracks) render as
    # 'C' counter events, not slices
    assert len(xs) == len([s for s in spans if s.cat != "counter"])
    assert {e["pid"] for e in xs} >= {"coordinator", "worker-0",
                                      "worker-1"}
