"""Device hash table kernel vs a host-dict oracle.

Mirrors the testing stance of the reference's hash-map-backed operators:
random batches incl. heavy duplicate keys, asserted slot-consistency
against a Python dict (SURVEY.md §4 — executor tests vs host oracles).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.ops.hash_table import (
    DeviceHashTable, MIN_CAPACITY, lookup, make_state, probe_insert,
)


def _oracle_slots(all_batches):
    """key tuple → first-seen order id (identity of the group)."""
    ids = {}
    for batch, valid in all_batches:
        for row, v in zip(batch, valid):
            if v and tuple(row) not in ids:
                ids[tuple(row)] = len(ids)
    return ids


def _assert_consistent(state, batches_and_slots):
    """Same key ⇒ same slot; different keys ⇒ different slots."""
    seen = {}
    for batch, valid, slots in batches_and_slots:
        slots = np.asarray(slots)
        for row, v, s in zip(batch, valid, slots):
            if not v:
                assert s == -1
                continue
            k = tuple(row)
            assert s >= 0, f"valid row {k} got slot -1"
            if k in seen:
                assert seen[k] == s, f"key {k}: slots {seen[k]} != {s}"
            else:
                assert s not in seen.values(), f"slot {s} reused across keys"
                seen[k] = s
        # table keys at those slots hold the batch keys
        tkeys = np.asarray(state.keys)
        for row, v, s in zip(batch, valid, slots):
            if v:
                assert tuple(tkeys[s]) == tuple(row)


def test_probe_insert_basic():
    state = make_state(64, 2)
    batch = jnp.asarray([[1, 10], [2, 20], [1, 10], [3, 30]], dtype=jnp.int32)
    valid = jnp.asarray([True, True, True, True])
    state, slots, ins = probe_insert(state, batch, valid)
    slots = np.asarray(slots)
    assert int(ins) == 3                      # one duplicate in the batch
    assert slots[0] == slots[2]               # duplicate keys share a slot
    assert len({slots[0], slots[1], slots[3]}) == 3
    # re-probing finds, not re-inserts
    state2, slots2, ins2 = probe_insert(state, batch, valid)
    assert int(ins2) == 0
    assert np.array_equal(np.asarray(slots2), slots)


def test_invalid_rows_untouched():
    state = make_state(64, 1)
    batch = jnp.asarray([[7], [8]], dtype=jnp.int32)
    valid = jnp.asarray([True, False])
    state, slots, ins = probe_insert(state, batch, valid)
    assert int(ins) == 1
    assert np.asarray(slots)[1] == -1
    assert int(np.sum(np.asarray(state.occ))) == 1


def test_lookup_absent_and_present():
    state = make_state(64, 1)
    ins_batch = jnp.asarray([[5], [6]], dtype=jnp.int32)
    state, slots, _ = probe_insert(state, ins_batch,
                                   jnp.ones(2, dtype=bool))
    q = jnp.asarray([[6], [42], [5]], dtype=jnp.int32)
    got = np.asarray(lookup(state, q, jnp.ones(3, dtype=bool)))
    assert got[0] == np.asarray(slots)[1]
    assert got[1] == -1
    assert got[2] == np.asarray(slots)[0]


def test_collision_heavy_random_oracle():
    """Tiny capacity + skewed keys: every batch collides hard."""
    rng = np.random.default_rng(7)
    state = make_state(128, 2)
    batches = []
    for _ in range(6):
        n = 32
        batch = np.stack([rng.integers(0, 10, n),      # heavy duplicates
                          rng.integers(0, 5, n)], axis=1).astype(np.int32)
        valid = rng.random(n) > 0.2
        state, slots, _ = probe_insert(
            state, jnp.asarray(batch), jnp.asarray(valid))
        batches.append((batch, valid, slots))
    _assert_consistent(state, batches)
    n_keys = len(_oracle_slots([(b, v) for b, v, _ in batches]))
    assert int(np.sum(np.asarray(state.occ))) == n_keys


def test_wrapper_growth_preserves_slots_mapping():
    t = DeviceHashTable(key_width=1, capacity=MIN_CAPACITY)
    moves = []
    t.on_grow(lambda old_to_new, old_cap: moves.append(
        (np.asarray(old_to_new), old_cap)))
    n = MIN_CAPACITY  # force at least one growth past MAX_LOAD
    keys = np.arange(n, dtype=np.int32).reshape(-1, 1)
    slots_before = {}
    for start in range(0, n, 256):
        b = jnp.asarray(keys[start:start + 256])
        s = np.asarray(t.probe_insert(b, jnp.ones(256, dtype=bool)))
        for k, sl in zip(range(start, start + 256), s):
            slots_before[k] = sl
    assert t.capacity > MIN_CAPACITY
    assert moves, "growth hooks must fire"
    assert t.sync_count() == n
    # every key still findable, exactly once
    got = np.asarray(t.lookup(jnp.asarray(keys), jnp.ones(n, dtype=bool)))
    assert (got >= 0).all()
    assert len(set(got.tolist())) == n


def test_full_table_contract():
    """reserve() grows before a batch could overflow MAX_LOAD."""
    t = DeviceHashTable(key_width=1)
    cap0 = t.capacity
    t.reserve(int(cap0 * 0.9))
    assert t.capacity >= cap0 * 2
