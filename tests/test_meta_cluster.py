"""ClusterManager heartbeats + NotificationService (meta plane).

Reference parity: src/meta/src/manager/cluster.rs:312-400 (heartbeat
lease + expiry check) and src/meta/src/manager/notification.rs
(versioned observer broadcast, snapshot-then-delta).
"""

import asyncio

import pytest

from risingwave_tpu.meta.cluster import ClusterManager
from risingwave_tpu.meta.notification import (
    Notification, NotificationService,
)


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_heartbeat_lease_and_expiry():
    clk = FakeClock()
    ns = NotificationService()
    obs = ns.subscribe()
    cm = ClusterManager(max_heartbeat_interval_s=5.0, clock=clk,
                        notifications=ns)
    w1 = cm.add_worker("h1", 1, {"parallelism": 4})
    w2 = cm.add_worker("h2", 2)
    assert {w.worker_id for w in cm.workers()} == {1, 2}
    clk.t = 4.0
    assert cm.heartbeat(w1.worker_id, {"actors": 3})
    assert cm.expire_stale() == []        # both within lease
    clk.t = 8.9    # w2 (last beat t=0) lapsed; w1 (t=4) not yet
    dead = cm.expire_stale()
    assert [w.worker_id for w in dead] == [w2.worker_id]
    assert cm.heartbeat(w2.worker_id) is False   # must re-register
    assert cm.workers()[0].info["actors"] == 3
    kinds = []
    while (n := obs.try_recv()) is not None:
        kinds.append(n.kind)
    assert kinds == ["worker_added", "worker_added", "worker_expired"]


def test_notification_versions_and_snapshot():
    state = [{"kind": "mv", "name": "v1"}]
    ns = NotificationService(snapshot_fn=lambda: list(state))
    v1 = ns.publish(Notification("mv_created", {"name": "v1"}))
    obs = ns.subscribe()
    # snapshot carries current state at the subscribe version
    assert [s.payload["name"] for s in obs.snapshot] == ["v1"]
    v2 = ns.publish(Notification("mv_created", {"name": "v2"}))
    assert v2 == v1 + 1
    n = obs.try_recv()
    assert n.kind == "mv_created" and n.version == v2
    ns.unsubscribe(obs.observer_id)
    ns.publish(Notification("mv_dropped", {"name": "v2"}))
    assert obs.try_recv() is None         # unsubscribed


def test_frontend_publishes_catalog_notifications():
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000)")
        obs = fe.notifications.subscribe()
        # snapshot sees the source created before subscribing
        assert any(p.payload.get("name") == "bid"
                   for p in obs.snapshot)
        await fe.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction FROM bid")
        n = obs.try_recv()
        await fe.close()
        return n

    n = asyncio.run(run())
    assert n.kind == "CreateMaterializedView"
    assert n.payload["name"] == "v"


def test_heartbeater_detects_killed_worker(tmp_path):
    """End-to-end failure DETECTION (VERDICT r3 §5 gap: 'no heartbeat-
    based detection'): a SIGKILLed worker stops answering pings and is
    evicted by lease expiry, with a notification."""
    from risingwave_tpu.cluster.coordinator import (
        Heartbeater, WorkerHandle,
    )
    from risingwave_tpu.meta.notification import NotificationService

    async def run():
        ns = NotificationService()
        obs = ns.subscribe()
        cm = ClusterManager(max_heartbeat_interval_s=1.5,
                            notifications=ns)
        hb = Heartbeater(cm, interval_s=0.2)
        h = WorkerHandle(str(tmp_path / "s"))
        client = await h.start()
        w = cm.add_worker("127.0.0.1", client.control_port)
        hb.register(w.worker_id, client)
        assert await hb.tick() == []
        assert cm.workers()[0].info.get("actors") == 0
        h.kill()                            # SIGKILL: no goodbye
        await asyncio.sleep(1.6)
        dead = await hb.tick()
        assert [x.worker_id for x in dead] == [w.worker_id]
        kinds = []
        while (n := obs.try_recv()) is not None:
            kinds.append(n.kind)
        assert kinds == ["worker_added", "worker_expired"]
        return True

    assert asyncio.run(run())
