"""Bottleneck walker tests (ISSUE 14): the walk names the
busy-dominated operator, streaks make it sustained, idle domains
report none, and the SQL surface serves the ranked table."""

import asyncio
import time

from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.stream.bottleneck import (
    BOTTLENECKS, BUSY_DOMINANT, SUSTAINED_STREAK,
)
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.message import StopMutation, is_chunk

SCH = Schema([Field("a", DataType.INT64)])


class HotPass(Executor):
    """Burns host CPU per chunk — the operator the walk must name."""

    def __init__(self, input_, busy_s: float = 0.05,
                 ident: str = "HotPass"):
        super().__init__(ExecutorInfo(
            input_.schema, list(input_.pk_indices), ident))
        self.input = input_
        self.busy_s = busy_s

    async def execute(self):
        async for msg in self.input.execute():
            if is_chunk(msg):
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < self.busy_s:
                    pass
            yield msg


def _run_pipeline(n_data_epochs: int, idle_epochs: int = 0,
                  busy_s: float = 0.35):
    """One actor: MockSource → HotPass → CheapPass root, driven by a
    real BarrierLoop (the walker hook runs at every seal). The
    default busy burn pushes each data epoch past SLOW_INTERVAL_S so
    the streak machine ticks."""
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.executors.test_utils import MockSource
    from risingwave_tpu.stream.monitor import install_monitoring

    class CheapRoot(Executor):
        def __init__(self, input_):
            super().__init__(ExecutorInfo(SCH, [], "CheapRoot"))
            self.input = input_

        async def execute(self):
            async for msg in self.input.execute():
                yield msg

    async def run():
        store = MemoryStateStore()
        local = LocalBarrierManager()
        tx, src = MockSource.channel(SCH)
        local.register_sender(5, tx)
        consumer = install_monitoring(
            CheapRoot(HotPass(src, busy_s=busy_s)),
            fragment="bn-test", actor_id=5)
        local.set_expected_actors([5])
        actor = Actor(5, consumer, dispatchers=[],
                      barrier_manager=local, fragment="bn-test")
        loop = BarrierLoop(local, store)
        task = actor.spawn()
        await loop.inject_and_collect(force_checkpoint=True)
        for _ in range(n_data_epochs):
            for _ in range(2):
                await src._tx.send(StreamChunk.from_pydict(
                    SCH, {"a": [1, 2, 3, 4]}))
            await loop.inject_and_collect(force_checkpoint=True)
        mid = BOTTLENECKS.summary().get("(global)", {})
        for _ in range(idle_epochs):
            await loop.inject_and_collect(force_checkpoint=True)
        end = BOTTLENECKS.summary().get("(global)", {})
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset({5})))
        await task
        assert actor.failure is None
        return mid, end

    return asyncio.run(run())


def test_walker_names_hot_operator_and_sustains():
    mid, _end = _run_pipeline(n_data_epochs=SUSTAINED_STREAK + 1)
    assert mid.get("operator") == "HotPass", mid
    assert mid["busy_ratio"] >= BUSY_DOMINANT
    assert mid["streak"] >= SUSTAINED_STREAK
    assert mid["sustained"] is True
    assert "scale this operator first" in mid["diagnosis"]
    # the Prometheus streak series named the same operator
    from risingwave_tpu.utils.metrics import STREAMING
    assert STREAMING.bottleneck_streak.get(
        domain="", operator="HotPass") >= SUSTAINED_STREAK


def test_fast_domain_never_sustains():
    """A domain holding fast barriers is healthy: its hottest operator
    never enters the streak machine — the q7-neighbor acceptance
    shape (no sustained bottleneck)."""
    mid, end = _run_pipeline(n_data_epochs=SUSTAINED_STREAK + 1,
                             busy_s=0.005)
    assert mid.get("operator") is None
    assert end.get("operator") is None
    assert end.get("sustained") is False
    assert "no sustained bottleneck" in end.get("diagnosis", "")


def test_idle_epochs_freeze_the_streak():
    """Empty trailing epochs (a drained domain) FREEZE the machine:
    the verdict its last slow barrier earned survives a drain — the
    multimv ad-ctr acceptance shape (the streak must not vanish just
    because the lane finished)."""
    _mid, end = _run_pipeline(n_data_epochs=SUSTAINED_STREAK + 1,
                              idle_epochs=3)
    assert end.get("operator") == "HotPass"
    assert end.get("sustained") is True


def test_rw_bottlenecks_system_table():
    from risingwave_tpu.frontend import Frontend

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=30000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW bn_mv AS SELECT window_start, "
            "COUNT(*) AS c FROM TUMBLE(bid, date_time, "
            "INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.step(5)
        rows = await fe.execute("SELECT * FROM rw_bottlenecks")
        util = await fe.execute(
            "SELECT * FROM rw_actor_utilization")
        await fe.close()
        return rows, util

    rows, util = asyncio.run(run())
    assert rows, "rw_bottlenecks must serve the walker state"
    # every triple the utilization table serves respects the identity
    for r in util:
        busy, bp, idle = r[6], r[7], r[8]
        assert busy + bp + idle <= 1.05, r
    # the MV's domain row exists (named bn_mv under the plane)
    domains = {r[0] for r in rows}
    assert "bn_mv" in domains or "" in domains


def test_walker_clear_drops_gauges():
    from risingwave_tpu.utils.metrics import STREAMING
    _mid, _end = _run_pipeline(n_data_epochs=SUSTAINED_STREAK)
    BOTTLENECKS.clear()
    assert not [l for l, v in STREAMING.bottleneck_streak.series()
                if l.get("domain") == ""]
