"""Expression framework tests (ref: src/expr/src/expr tests)."""

import decimal

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import DataChunk, DataType, Interval, Schema
from risingwave_tpu.expr import (
    Case, InputRef, and_, col, lit, or_, tumble_start, tumble_end,
)


def _chunk():
    s = Schema.of(a=DataType.INT64, b=DataType.INT64, f=DataType.FLOAT64,
                  d=DataType.DECIMAL)
    return DataChunk.from_pydict(s, {
        "a": [1, 2, None, 4],
        "b": [10, 0, 30, 40],
        "f": [0.5, 1.5, 2.5, 3.5],
        "d": ["1.10", "2.20", "3.30", "4.40"],
    })


def _vals(colmn, n=4):
    out = []
    v = np.asarray(colmn.values)
    val = None if colmn.validity is None else np.asarray(colmn.validity)
    for i in range(n):
        out.append(None if (val is not None and not val[i]) else v[i].item())
    return out


def test_arith_and_null_propagation():
    c = _chunk()
    s = c.schema
    e = col(s, "a") + col(s, "b")
    assert e.return_type == DataType.INT64
    assert _vals(e.eval(c)) == [11, 2, None, 44]
    e2 = col(s, "a") * lit(3)
    assert _vals(e2.eval(c)) == [3, 6, None, 12]


def test_comparison_and_logic():
    c = _chunk()
    s = c.schema
    e = (col(s, "b") > lit(5)) | (col(s, "a") == lit(2))
    r = _vals(e.eval(c))
    assert r == [True, True, True, True]
    e2 = and_(col(s, "b") >= lit(10), col(s, "f") < lit(3.0))
    assert _vals(e2.eval(c)) == [True, False, True, False]
    # Kleene: null AND false = false, null AND true = null
    e3 = (col(s, "a") > lit(0)) & (col(s, "b") > lit(100))
    assert _vals(e3.eval(c)) == [False, False, False, False]
    e4 = (col(s, "a") > lit(0)) & (col(s, "b") >= lit(0))
    assert _vals(e4.eval(c)) == [True, True, None, True]


def test_decimal_exact_math():
    c = _chunk()
    s = c.schema
    e = col(s, "d") * lit(decimal.Decimal("0.908"))
    out = e.eval(c)
    assert out.data_type == DataType.DECIMAL
    # 1.10 * 0.908 = 0.9988 exactly at scale 4
    assert _vals(out)[0] == 9988
    e2 = col(s, "d") + col(s, "d")
    assert _vals(e2.eval(c))[1] == 44000  # 2.20 + 2.20 = 4.40 → 44000 raw


def test_division_by_zero_is_null():
    c = _chunk()
    s = c.schema
    e = col(s, "a") / col(s, "b")
    out = _vals(e.eval(c))
    assert out[1] is None           # 2 / 0 → NULL
    assert out[0] == 1000           # 1/10 = 0.1 → decimal raw 1000
    e2 = col(s, "b") % lit(0)
    assert _vals(e2.eval(c)) == [None] * 4


def test_int_division_becomes_decimal():
    c = _chunk()
    s = c.schema
    e = col(s, "b") / lit(4)
    out = e.eval(c)
    assert out.data_type == DataType.DECIMAL
    assert _vals(out)[0] == 25000   # 10/4 = 2.5


def test_unary_and_is_null():
    from risingwave_tpu.expr.expr import UnaryOp
    c = _chunk()
    s = c.schema
    assert _vals(UnaryOp("is_null", col(s, "a")).eval(c)) == \
        [False, False, True, False]
    assert _vals(UnaryOp("neg", col(s, "b")).eval(c)) == [-10, 0, -30, -40]
    assert _vals(UnaryOp("not", col(s, "b") > lit(5)).eval(c)) == \
        [False, True, False, False]


def test_tumble_window():
    s = Schema.of(ts=DataType.TIMESTAMP)
    c = DataChunk.from_pydict(s, {"ts": [0, 5_000_000, 12_345_678, 59_999_999]})
    w = Interval.from_duration(seconds=10)  # 10s windows
    st = tumble_start(col(s, "ts"), w).eval(c)
    en = tumble_end(col(s, "ts"), w).eval(c)
    assert _vals(st) == [0, 0, 10_000_000, 50_000_000]
    assert _vals(en) == [10_000_000, 10_000_000, 20_000_000, 60_000_000]


def test_case_expression():
    c = _chunk()
    s = c.schema
    e = Case([(col(s, "b") < lit(15), lit(1)),
              (col(s, "b") < lit(35), lit(2))], lit(3))
    assert _vals(e.eval(c)) == [1, 1, 2, 3]


def test_literal_null_and_varchar():
    c = _chunk()
    out = lit(None).eval(c)
    assert _vals(out) == [None] * 4
    v = lit("hello").eval(c)
    assert np.asarray(v.values)[0] == "hello"


def test_float_promotion():
    c = _chunk()
    s = c.schema
    e = col(s, "a") + col(s, "f")
    assert e.return_type == DataType.FLOAT64
    r = _vals(e.eval(c))
    assert r[0] == 1.5 and r[2] is None


def test_varchar_comparison_host():
    s = Schema.of(name=DataType.VARCHAR, x=DataType.INT64)
    c = DataChunk.from_pydict(s, {"name": ["alice", "bob", None, "alice"],
                                  "x": [1, 2, 3, 4]})
    e = col(s, "name") == lit("alice")
    assert _vals(e.eval(c)) == [True, False, None, True]
    e2 = col(s, "name") < lit("b")
    assert _vals(e2.eval(c)) == [True, False, None, True]
    with pytest.raises(TypeError):
        (col(s, "name") + lit("x")).eval(c)


def test_decimal_mul_truncates_toward_zero():
    s = Schema.of(d=DataType.DECIMAL)
    c = DataChunk.from_pydict(s, {"d": ["-0.0001", "0.0001"]})
    e = col(s, "d") * lit(decimal.Decimal("0.5"))
    assert _vals(e.eval(c), 2) == [0, 0]   # both truncate to zero


def test_tumble_null_window():
    from risingwave_tpu.expr.expr import FuncCall, Literal
    s = Schema.of(ts=DataType.TIMESTAMP)
    c = DataChunk.from_pydict(s, {"ts": [100]})
    e = FuncCall("tumble_start",
                 [col(s, "ts"), Literal(None, DataType.INTERVAL)],
                 DataType.TIMESTAMP)
    assert _vals(e.eval(c), 1) == [None]


# -- round-2 review-fix regressions -----------------------------------------


def test_decimal_to_float_cast():
    import decimal as _d
    s = Schema.of(d=DataType.DECIMAL, f=DataType.FLOAT64)
    c = DataChunk.from_pydict(s, {"d": [_d.Decimal("1.5")], "f": [2.0]})
    out = (col(s, "d") + col(s, "f")).eval(c)
    assert out.data_type == DataType.FLOAT64
    assert abs(float(out.values[0]) - 3.5) < 1e-9


def test_modulo_truncated_sign():
    s = Schema.of(a=DataType.INT64, b=DataType.INT64)
    c = DataChunk.from_pydict(s, {"a": [-7, 7, -7, 7], "b": [3, 3, -3, -3]})
    out = (col(s, "a") % col(s, "b")).eval(c)
    assert [int(v) for v in out.values[:4]] == [-1, 1, -1, 1]


def test_host_cmp_interval_with_padding():
    from risingwave_tpu.common.types import Interval
    s = Schema.of(iv=DataType.INTERVAL)
    c = DataChunk.from_pydict(s, {"iv": [Interval(days=1)]})  # capacity 8
    out = (col(s, "iv") < lit(Interval(usecs=360_000_000_000),
                              DataType.INTERVAL)).eval(c)
    # 1 day < 100 hours under justified comparison
    assert bool(out.values[0])


def test_interval_justified_ordering():
    from risingwave_tpu.common.types import Interval
    assert Interval(days=1) < Interval(usecs=360_000_000_000)
    assert Interval(months=1) == Interval(days=30)
    assert Interval(months=1) > Interval(days=29)


def test_scalar_function_library_semantics():
    """pg semantics of the new string/date scalars: substr window
    clamping, split_part from-the-end, to_char, extract_epoch without
    int64 overflow."""
    import decimal

    import numpy as np

    from risingwave_tpu.common.chunk import DataChunk
    from risingwave_tpu.common.types import DataType, Schema
    from risingwave_tpu.expr.expr import FuncCall, InputRef, lit

    sch = Schema.of(s=DataType.VARCHAR, ts=DataType.TIMESTAMP)
    chunk = DataChunk.from_pydict(
        sch, {"s": ["hello", "a/b/c"],
              "ts": [1_436_918_400_000_000, 0]})
    sref = InputRef(0, DataType.VARCHAR)
    tref = InputRef(1, DataType.TIMESTAMP)

    def run(fc):
        col = fc.eval(chunk)
        return list(np.asarray(col.values)[:2])

    # substr clamps the WINDOW, not the length (pg)
    assert run(FuncCall("substr", [sref, lit(0, DataType.INT64),
                                   lit(3, DataType.INT64)],
                        DataType.VARCHAR))[0] == "he"
    assert run(FuncCall("substr", [sref, lit(-2, DataType.INT64),
                                   lit(5, DataType.INT64)],
                        DataType.VARCHAR))[0] == "he"
    # split_part counts negative positions from the end
    assert run(FuncCall("split_part",
                        [sref, lit("/", DataType.VARCHAR),
                         lit(-1, DataType.INT64)],
                        DataType.VARCHAR))[1] == "c"
    assert run(FuncCall("to_char",
                        [tref, lit("YYYY-MM-DD", DataType.VARCHAR)],
                        DataType.VARCHAR))[0] == "2015-07-15"
    ep = run(FuncCall("extract_epoch", [tref], DataType.DECIMAL))[0]
    assert int(ep) == 1_436_918_400 * 10_000   # scaled decimal seconds
