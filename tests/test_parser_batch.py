"""Columnar batch parser vs row-at-a-time oracle (ISSUE 12 tentpole a).

The batch path (one combined decode + vectorized per-column coercion)
must be bit-identical to the row path (the ``batch=False`` off arm)
under every shape the wire can carry: NULLs, ``__op`` envelopes,
malformed records interleaved (skip-and-count isolation), BOM and
non-UTF-8 payloads, and every physical type — plus the DECIMAL
single-scale regression (the old row path double-scaled parsed
decimals through from_pydict's logical-ingest contract).
"""

import decimal
import json

import numpy as np
import pytest

from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.connectors.parser import (
    CsvRowParser, JsonRowParser, make_parser,
)

ALL_TYPES = Schema.of(
    i16=DataType.INT16, i32=DataType.INT32, i64=DataType.INT64,
    f32=DataType.FLOAT32, f64=DataType.FLOAT64, b=DataType.BOOLEAN,
    d=DataType.DECIMAL, ts=DataType.TIMESTAMP, dt=DataType.DATE,
    s=DataType.VARCHAR, by=DataType.BYTEA)


def _chunk_records(chunk):
    return None if chunk is None else chunk.to_records()


def _oracle(schema, payloads):
    """Both arms parse the same payloads; chunks must agree exactly."""
    on = JsonRowParser(schema, batch=True)
    off = JsonRowParser(schema, batch=False)
    c_on = on.build_chunk(list(payloads))
    c_off = off.build_chunk(list(payloads))
    assert _chunk_records(c_on) == _chunk_records(c_off)
    assert on.errors == off.errors
    return c_on, on


def _rec(rng, i, malform=False):
    if malform:
        return rng.choice([b"not json", b"{broken", b"[1,2]", b"17",
                           b'"str"'])
    obj = {}
    if rng.random() > 0.2:
        obj["i16"] = int(rng.integers(-30000, 30000))
    if rng.random() > 0.2:
        obj["i32"] = int(rng.integers(-2**31, 2**31 - 1))
    if rng.random() > 0.2:
        obj["i64"] = int(rng.integers(-2**53, 2**53))
    if rng.random() > 0.2:
        obj["f32"] = float(rng.normal())
    if rng.random() > 0.2:
        obj["f64"] = rng.choice([float(rng.normal()), -0.0, 1e308])
    if rng.random() > 0.2:
        obj["b"] = bool(rng.random() > 0.5)
    if rng.random() > 0.2:
        obj["d"] = rng.choice(["1.5", "-2", "0.0001", "99.99"])
    if rng.random() > 0.2:
        obj["ts"] = rng.choice([
            1_700_000_000,                       # seconds heuristic
            1_700_000_000_000_000,               # already µs
            "2026-01-02T03:04:05",               # ISO
            "2026-01-02T03:04:05Z",              # ISO + Z
            int(rng.integers(0, 4_000_000_000)),
        ])
    if rng.random() > 0.2:
        obj["dt"] = rng.choice([12345, "2026-01-02"])
    if rng.random() > 0.2:
        obj["s"] = rng.choice(["plain", "", "unié", "7"])
    if rng.random() > 0.2:
        obj["by"] = rng.choice([{"__b": "deadbeef"}, "text-bytes"])
    if rng.random() > 0.7:
        obj["__op"] = rng.choice(["I", "D"])
    if rng.random() > 0.8:
        obj["unknown_key"] = i
    return json.dumps(obj).encode()


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_fuzzed_batch_vs_row_oracle_all_types(seed):
    rng = np.random.default_rng(seed)
    payloads = [_rec(rng, i, malform=rng.random() < 0.07)
                for i in range(300)]
    chunk, parser = _oracle(ALL_TYPES, payloads)
    assert chunk is not None and chunk.cardinality() > 0
    assert parser.errors > 0          # fuzz interleaves malformed recs


def test_bom_and_non_utf8_payloads():
    schema = Schema.of(a=DataType.INT64, s=DataType.VARCHAR)
    payloads = [
        b'{"a": 1, "s": "x"}',
        "﻿".encode("utf-8") + b'{"a": 2, "s": "bom"}',
        '{"a": 3, "s": "wide"}'.encode("utf-16"),
        b'\xff\xfe garbage that is not any json',
        b'{"a": 4, "s": "tail"}',
    ]
    c_on, parser = _oracle(schema, payloads)
    recs = [r for _op, r in c_on.to_records()]
    assert (1, "x") in recs and (2, "bom") in recs
    assert (3, "wide") in recs and (4, "tail") in recs
    assert parser.errors == 1


def test_op_envelope_maps_to_deletes_in_both_arms():
    schema = Schema.of(k=DataType.INT64)
    payloads = [b'{"k": 1}', b'{"k": 2, "__op": "D"}',
                b'{"k": 3, "__op": "I"}']
    c_on, _p = _oracle(schema, payloads)
    ops = [op for op, _r in c_on.to_records()]
    from risingwave_tpu.common.chunk import Op
    assert ops == [Op.INSERT, Op.DELETE, Op.INSERT]


def test_coercion_failure_isolates_single_record():
    """A record whose FIELD refuses to coerce (not malformed JSON)
    drops exactly that record in both arms."""
    schema = Schema.of(a=DataType.INT64, s=DataType.VARCHAR)
    payloads = [b'{"a": 1, "s": "x"}',
                b'{"a": "3.5", "s": "bad-int"}',   # int("3.5") raises
                b'{"a": "7", "s": "str-int-ok"}',
                b'{"a": 2, "s": "y"}']
    c_on, parser = _oracle(schema, payloads)
    recs = [r for _op, r in c_on.to_records()]
    assert recs == [(1, "x"), (7, "str-int-ok"), (2, "y")]
    assert parser.errors == 1


def test_decimal_parses_single_scaled():
    """Regression: parsed DECIMALs reached the chunk DOUBLE-scaled
    (physical scaled ints fed into from_pydict's logical ingest)."""
    schema = Schema.of(d=DataType.DECIMAL)
    for batch in (True, False):
        p = JsonRowParser(schema, batch=batch)
        c = p.build_chunk([b'{"d": 1.5}', b'{"d": "-2"}'])
        assert c.to_pylist() == [(decimal.Decimal("1.5"),),
                                 (decimal.Decimal("-2"),)]


def test_all_malformed_batch_returns_none():
    schema = Schema.of(a=DataType.INT64)
    for batch in (True, False):
        p = JsonRowParser(schema, batch=batch)
        assert p.build_chunk([b"nope", b"{broken"]) is None
        assert p.errors == 2


def test_csv_batch_vs_row_oracle():
    schema = Schema.of(a=DataType.INT64, f=DataType.FLOAT64,
                       s=DataType.VARCHAR)
    payloads = [b"1,1.5,x", b"2,,", b"junk", b"3,2.5,y,extra",
                b"bad-int,1.0,z"]
    on = CsvRowParser(schema, batch=True)
    off = CsvRowParser(schema, batch=False)
    c_on = on.build_chunk(list(payloads))
    c_off = off.build_chunk(list(payloads))
    assert _chunk_records(c_on) == _chunk_records(c_off)
    assert on.errors == off.errors == 2
    recs = [r for _op, r in c_on.to_records()]
    assert recs == [(1, 1.5, "x"), (2, None, None), (3, 2.5, "y")]


def test_csv_prebound_coercers_row_path():
    """Satellite: CsvRowParser's row path uses prebound per-column
    coercers (no per-field type dispatch) with unchanged semantics."""
    p = CsvRowParser(Schema.of(a=DataType.INT64, t=DataType.TIMESTAMP,
                               s=DataType.VARCHAR))
    assert p.parse_one(b"5,2026-01-02T00:00:00,hello") == \
        (5, 1767312000000000, "hello")
    # the prebound list exists and has one entry per column
    assert len(p._fields) == 3


def test_make_parser_batch_option():
    s = Schema.of(a=DataType.INT64)
    assert make_parser("json", s).batch is True
    assert make_parser("json", s,
                       {"parse.batch": "false"}).batch is False
    assert make_parser("csv", s, {"parse.batch": "off"}).batch is False


def test_comma_concatenated_payload_is_isolated_not_exploded():
    """Review regression: '{..},{..}' parses as TWO values inside the
    synthesized array — it must count as ONE malformed record (row-path
    parity), never mint phantom rows."""
    schema = Schema.of(a=DataType.INT64)
    payloads = [b'{"a": 1}', b'{"a": 2},{"a": 3}', b'{"a": 4}']
    c_on, parser = _oracle(schema, payloads)
    assert [r for _op, r in c_on.to_records()] == [(1,), (4,)]
    assert parser.errors == 1
