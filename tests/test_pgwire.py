"""pgwire protocol test: a hand-rolled v3 client (what psql sends)
against the in-process server."""

import asyncio
import struct

from risingwave_tpu.frontend import Frontend
from risingwave_tpu.frontend.pgwire import PgServer


class _Client:
    def __init__(self, reader, writer):
        self.r, self.w = reader, writer

    @staticmethod
    async def connect(port):
        r, w = await asyncio.open_connection("127.0.0.1", port)
        c = _Client(r, w)
        # SSL probe → expect 'N'
        c.w.write(struct.pack(">II", 8, 80877103))
        await c.w.drain()
        assert await c.r.readexactly(1) == b"N"
        params = b"user\x00tpu\x00database\x00dev\x00\x00"
        c.w.write(struct.pack(">II", 8 + len(params), 196608) + params)
        await c.w.drain()
        msgs = await c.read_until(b"Z")
        assert msgs[0][0] == b"R"        # AuthenticationOk
        return c

    async def read_msg(self):
        hdr = await self.r.readexactly(5)
        ln = struct.unpack(">I", hdr[1:5])[0]
        return hdr[0:1], await self.r.readexactly(ln - 4)

    async def read_until(self, tag):
        out = []
        while True:
            t, p = await self.read_msg()
            out.append((t, p))
            if t == tag:
                return out

    async def query(self, sql):
        body = sql.encode() + b"\x00"
        self.w.write(b"Q" + struct.pack(">I", len(body) + 4) + body)
        await self.w.drain()
        return await self.read_until(b"Z")

    def close(self):
        self.w.write(b"X" + struct.pack(">I", 4))
        self.w.close()


def _rows(msgs):
    out = []
    for t, p in msgs:
        if t != b"D":
            continue
        n = struct.unpack(">H", p[:2])[0]
        pos, row = 2, []
        for _ in range(n):
            ln = struct.unpack(">i", p[pos:pos + 4])[0]
            pos += 4
            if ln == -1:
                row.append(None)
            else:
                row.append(p[pos:pos + ln].decode())
                pos += ln
        out.append(tuple(row))
    return out


def test_pgwire_end_to_end():
    async def run():
        fe = Frontend(min_chunks=4)
        srv = PgServer(fe)
        await srv.serve(port=0)
        c = await _Client.connect(srv.port)
        msgs = await c.query(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=5000)")
        assert any(t == b"C" and b"CREATE SOURCE" in p for t, p in msgs)
        await c.query("CREATE MATERIALIZED VIEW m AS SELECT auction, "
                      "price FROM bid WHERE price > 1000")
        await fe.step(4)
        msgs = await c.query("SELECT COUNT(*) AS n FROM m")
        rd = [p for t, p in msgs if t == b"T"]
        assert rd and b"n\x00" in rd[0]
        rows = _rows(msgs)
        assert len(rows) == 1 and int(rows[0][0]) > 0
        # error path: bad SQL → ErrorResponse then ReadyForQuery
        msgs = await c.query("SELEKT 1")
        assert msgs[0][0] == b"E" and msgs[-1][0] == b"Z"
        # NULL and bool text encoding
        msgs = await c.query("SELECT true AS t, null AS x")
        assert _rows(msgs) == [("t", None)]
        c.close()
        await srv.close()
        await fe.close()

    asyncio.run(run())


def test_pgwire_extended_protocol():
    """Parse/Bind/Describe/Execute/Sync with $n text parameters — what
    psycopg-style drivers send (pg_protocol.rs extended surface)."""
    async def run():
        fe = Frontend(rate_limit=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, price "
            "FROM bid")
        await fe.step(6)
        srv = PgServer(fe)
        await srv.serve(port=0)
        c = await _Client.connect(srv.port)

        def ext(tag, body):
            c.w.write(tag + struct.pack(">I", len(body) + 4) + body)

        # Parse (named stmt, $1 parameter), Bind, Describe, Execute, Sync
        sql = ("SELECT auction, count(*) AS n FROM m "
               "WHERE auction = CAST($1 AS BIGINT) GROUP BY auction")
        ext(b"P", b"s1\x00" + sql.encode() + b"\x00" +
            struct.pack(">H", 0))
        param = b"1000"
        ext(b"B", b"\x00" + b"s1\x00" + struct.pack(">H", 0)
            + struct.pack(">H", 1)
            + struct.pack(">i", len(param)) + param
            + struct.pack(">H", 0))
        ext(b"D", b"P\x00")
        ext(b"E", b"\x00" + struct.pack(">I", 0))
        ext(b"S", b"")
        await c.w.drain()
        msgs = await c.read_until(b"Z")
        tags = [t for t, _ in msgs]
        assert b"1" in tags and b"2" in tags       # Parse/BindComplete
        assert b"T" in tags                        # RowDescription
        data = [p for t, p in msgs if t == b"D"]
        assert len(data) == 1
        # error inside extended mode skips to Sync, then recovers
        ext(b"P", b"bad\x00SELECT nope FROM m\x00"
            + struct.pack(">H", 0))
        ext(b"B", b"\x00bad\x00" + struct.pack(">HHH", 0, 0, 0))
        ext(b"E", b"\x00" + struct.pack(">I", 0))
        ext(b"S", b"")
        await c.w.drain()
        msgs = await c.read_until(b"Z")
        assert any(t == b"E" for t, _ in msgs)     # ErrorResponse
        # connection still usable via simple query
        rows = _rows(await c.query("SELECT count(*) AS n FROM m"))
        c.close()
        await srv.close()
        await fe.close()
        return rows

    rows = asyncio.run(run())
    assert int(rows[0][0]) > 0


def test_pgwire_param_substitution_is_token_aware():
    from risingwave_tpu.frontend.pgwire import PgServer

    sub = PgServer._sub_params_sql
    # $n inside a string literal is untouched; a value containing $1
    # is never re-scanned
    assert sub("SELECT 'price $1', $1", ["x"]) == \
        "SELECT 'price $1', 'x'"
    assert sub("SELECT $1, $2", ["a", "$1"]) == "SELECT 'a', '$1'"
    assert sub("SELECT $1", [None]) == "SELECT NULL"
    assert sub("SELECT $1", ["O'Brien"]) == "SELECT 'O''Brien'"
    assert PgServer._param_count("SELECT $2 + '$9'") == 2


def test_pgwire_cleartext_password_auth():
    """AuthenticationCleartextPassword round trip: wrong password is
    rejected, right password reaches ReadyForQuery (pg_protocol.rs
    startup auth parity)."""
    import struct

    from risingwave_tpu.frontend.pgwire import PgServer
    from risingwave_tpu.frontend.session import Frontend

    async def run():
        fe = Frontend()
        srv = PgServer(fe, password="sekrit")
        await srv.serve(port=0)
        port = srv.port

        async def attempt(pw):
            r, w = await asyncio.open_connection("127.0.0.1", port)
            payload = b"user\x00u\x00\x00"
            w.write(struct.pack(">II", 8 + len(payload), 196608)
                    + payload)
            await w.drain()
            hdr = await r.readexactly(5)
            assert hdr[0:1] == b"R"
            ln = struct.unpack(">I", hdr[1:5])[0]
            code = struct.unpack(
                ">I", await r.readexactly(ln - 4))[0]
            assert code == 3            # cleartext password request
            pwb = pw.encode() + b"\x00"
            w.write(b"p" + struct.pack(">I", len(pwb) + 4) + pwb)
            await w.drain()
            tags = []
            try:
                while True:
                    hdr = await r.readexactly(5)
                    ln = struct.unpack(">I", hdr[1:5])[0]
                    await r.readexactly(ln - 4)
                    tags.append(hdr[0:1])
                    if hdr[0:1] in (b"Z", b"E"):
                        break
            except asyncio.IncompleteReadError:
                pass
            w.close()
            return tags

        bad = await attempt("wrong")
        good = await attempt("sekrit")
        await srv.close()
        await fe.close()
        return bad, good

    bad, good = asyncio.run(run())
    assert b"E" in bad and b"Z" not in bad
    assert good[-1] == b"Z"


def test_pgwire_extended_protocol_dml():
    """Parameterized INSERT through Parse/Bind/Execute — the
    prepared-statement write path every ORM uses."""
    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE t (a bigint, b varchar)")
        srv = PgServer(fe)
        await srv.serve(port=0)
        c = await _Client.connect(srv.port)

        def ext(tag, body):
            c.w.write(tag + struct.pack(">I", len(body) + 4) + body)

        sql = "INSERT INTO t VALUES (CAST($1 AS BIGINT), $2)"
        ext(b"P", b"ins\x00" + sql.encode() + b"\x00"
            + struct.pack(">H", 0))
        for a, b in ((b"1", b"x"), (b"2", b"y")):
            ext(b"B", b"\x00ins\x00" + struct.pack(">H", 0)
                + struct.pack(">H", 2)
                + struct.pack(">i", len(a)) + a
                + struct.pack(">i", len(b)) + b
                + struct.pack(">H", 0))
            ext(b"E", b"\x00" + struct.pack(">I", 0))
        ext(b"S", b"")
        await c.w.drain()
        msgs = await c.read_until(b"Z")
        tags = [p for t, p in msgs if t == b"C"]
        assert len(tags) == 2 and all(b"INSERT 0 1" in p
                                      for p in tags), tags
        rows = sorted(_rows(await c.query("SELECT a, b FROM t")))
        assert rows == [("1", "x"), ("2", "y")], rows
        c.close()
        await srv.close()
        await fe.close()

    asyncio.run(run())


def test_pgwire_over_distributed_cluster(tmp_path):
    """psql-shaped traffic against the N-worker cluster session
    (`serve-cluster` shape): DDL deploys fragments across worker
    processes, SELECT gathers from their namespaces — all over the
    wire protocol."""
    from risingwave_tpu.cluster.session import DistFrontend

    async def run():
        fe = DistFrontend(str(tmp_path), n_workers=2, parallelism=2)
        await fe.start()
        srv = PgServer(fe)
        await srv.serve(port=0)
        try:
            c = await _Client.connect(srv.port)
            await c.query(
                "CREATE SOURCE bid WITH (connector='nexmark', "
                "nexmark.table.type='bid', nexmark.event.num=4000, "
                "nexmark.min.event.gap.in.ns=50000000)")
            msgs = await c.query(
                "CREATE MATERIALIZED VIEW m AS SELECT auction, "
                "count(*) AS c FROM bid GROUP BY auction")
            assert any(t == b"C" for t, _p in msgs)
            await fe.step(12)
            msgs = await c.query("SELECT count(*) AS n FROM m")
            rows = _rows(msgs)
            assert len(rows) == 1 and int(rows[0][0]) > 10
            msgs = await c.query("SHOW streaming_rate_limit")
            assert _rows(msgs) == [("8",)]
            c.close()
        finally:
            await srv.close()
            await fe.close()

    asyncio.run(run())
