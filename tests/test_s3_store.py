"""S3-API object store backend (VERDICT r4 #9).

A MinIO-style stub server (stdlib http.server, in-process thread)
implements the S3 subset the store uses — PUT/GET (with byte ranges)/
HEAD/DELETE + ListObjectsV2 — and checks SigV4 Authorization headers
when credentials are configured. hummock-lite then checkpoints a
state-table workload through it and recovers from a fresh handle
(object/s3.rs parity: whole-object uploads, ranged block reads).
"""

import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, unquote, urlparse

import pytest

from risingwave_tpu.storage.object_store import S3ObjectStore


class _S3Stub(BaseHTTPRequestHandler):
    objects = {}
    require_auth = False
    protocol_version = "HTTP/1.1"

    def log_message(self, *a):
        pass

    def _key(self):
        return unquote(urlparse(self.path).path.lstrip("/"))

    def _authorized(self) -> bool:
        if not self.require_auth:
            return True
        auth = self.headers.get("Authorization", "")
        return (auth.startswith("AWS4-HMAC-SHA256 Credential=minio/")
                and "SignedHeaders=" in auth
                and "Signature=" in auth
                and self.headers.get("x-amz-date") is not None
                and self.headers.get("x-amz-content-sha256")
                is not None)

    def _reply(self, code, body=b"", headers=()):
        self.send_response(code)
        sent = {k.lower() for k, _v in headers}
        for k, v in headers:
            self.send_header(k, v)
        if "content-length" not in sent:
            self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_PUT(self):
        if not self._authorized():
            return self._reply(403)
        n = int(self.headers.get("Content-Length", 0))
        self.objects[self._key()] = self.rfile.read(n)
        self._reply(200)

    def do_GET(self):
        if not self._authorized():
            return self._reply(403)
        url = urlparse(self.path)
        q = parse_qs(url.query)
        if "list-type" in q:
            prefix = q.get("prefix", [""])[0]
            bucket = url.path.lstrip("/")
            full = f"{bucket}/{prefix}"
            keys = sorted(k[len(bucket) + 1:] for k in self.objects
                          if k.startswith(full))
            body = ("<ListBucketResult>" + "".join(
                f"<Contents><Key>{k}</Key></Contents>" for k in keys)
                + "</ListBucketResult>").encode()
            return self._reply(200, body)
        data = self.objects.get(self._key())
        if data is None:
            return self._reply(404)
        rng = self.headers.get("Range")
        if rng:
            lo, hi = rng.split("=")[1].split("-")
            part = data[int(lo):int(hi) + 1]
            return self._reply(206, part)
        self._reply(200, data)

    def do_HEAD(self):
        data = self.objects.get(self._key())
        if data is None:
            return self._reply(404)
        self._reply(200, headers=[("Content-Length", str(len(data)))])

    def do_DELETE(self):
        self.objects.pop(self._key(), None)
        self._reply(204)


@pytest.fixture
def s3_stub():
    _S3Stub.objects = {}
    _S3Stub.require_auth = False
    srv = ThreadingHTTPServer(("127.0.0.1", 0), _S3Stub)
    t = threading.Thread(target=srv.serve_forever, daemon=True)
    t.start()
    yield f"http://127.0.0.1:{srv.server_port}"
    srv.shutdown()


def test_s3_object_store_roundtrip(s3_stub):
    s = S3ObjectStore(s3_stub, "bucket", prefix="env1")
    s.upload("data/1.sst", b"hello world" * 100)
    assert s.exists("data/1.sst")
    assert s.read("data/1.sst") == b"hello world" * 100
    assert s.read_range("data/1.sst", 6, 5) == b"world"
    assert s.size("data/1.sst") == 1100
    s.upload("meta/CURRENT", b"7")
    assert s.list("data/") == ["data/1.sst"]
    assert sorted(s.list("")) == ["data/1.sst", "meta/CURRENT"]
    s.delete("data/1.sst")
    assert not s.exists("data/1.sst")


def test_s3_sigv4_headers_required(s3_stub):
    _S3Stub.require_auth = True
    anon = S3ObjectStore(s3_stub, "bucket")
    with pytest.raises(IOError):
        anon.upload("x", b"1")
    signed = S3ObjectStore(s3_stub, "bucket", access_key="minio",
                           secret_key="minio123")
    signed.upload("x", b"1")
    assert signed.read("x") == b"1"


def test_hummock_checkpoints_to_s3(s3_stub):
    """hummock-lite over the S3 endpoint: write at epochs, seal, sync
    (SST + version manifest PUTs), then recover from a fresh handle
    and read the committed state back through ranged block GETs."""
    from risingwave_tpu.storage.hummock import HummockLite

    store = HummockLite(S3ObjectStore(s3_stub, "bucket", prefix="hum"))
    rows = {f"k{i:04d}".encode(): (i, f"v{i}") for i in range(500)}
    store.ingest_batch(7, rows.items(), epoch=100)
    store.seal_epoch(100)
    store.sync(100)
    store.ingest_batch(7, [(b"k0001", None)], epoch=200)  # tombstone
    store.seal_epoch(200)
    store.sync(200)
    assert any(k.startswith("bucket/hum/data/")
               for k in _S3Stub.objects)
    assert "bucket/hum/meta/CURRENT" in _S3Stub.objects

    fresh = HummockLite(S3ObjectStore(s3_stub, "bucket", prefix="hum"))
    assert fresh.committed_epoch() == 200
    assert fresh.get(7, b"k0002", 300) == (2, "v2")
    assert fresh.get(7, b"k0001", 300) is None
    got = dict(fresh.iter(7, 300))
    assert len(got) == 499
