"""FROM-subqueries, HAVING, BETWEEN, expressions over aggregates.

Reference parity: derived-table binding (src/frontend/src/binder/ bind
of Query in FROM), HAVING planning (logical_agg.rs filters over the
agg), and nexmark q4 — the named baseline config whose SQL needs all
three (e2e_test/streaming/nexmark/views/q4.slt.part:1-15).
"""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.frontend import Frontend
from risingwave_tpu.frontend.parser import ParseError, parse
from risingwave_tpu.frontend.planner import PlanError


# -- parser ---------------------------------------------------------------


def test_parser_subquery_having_between():
    s = parse("SELECT x, count(*) FROM (SELECT a AS x FROM t) q "
              "GROUP BY x HAVING count(*) > 5")
    from risingwave_tpu.frontend.ast import Bin, Subquery
    assert isinstance(s.from_item, Subquery)
    assert s.from_item.alias == "q"
    assert s.having is not None

    s = parse("SELECT * FROM t WHERE a BETWEEN 1 AND 5 AND b = 2")
    # BETWEEN desugars to (a>=1 AND a<=5), ANDed with b=2
    assert isinstance(s.where, Bin) and s.where.op == "and"

    with pytest.raises(ParseError):
        parse("SELECT * FROM (SELECT a FROM t)")   # missing alias


# -- streaming e2e --------------------------------------------------------


def _bid_source(n=20000, gap_ns=100_000_000):
    return ("CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', "
            f"nexmark.event.num={n}, nexmark.max.chunk.size=1024, "
            f"nexmark.min.event.gap.in.ns={gap_ns})")


def test_having_filters_groups():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        await fe.execute(
            "CREATE MATERIALIZED VIEW hot AS SELECT bidder, COUNT(*) "
            "AS cnt FROM bid GROUP BY bidder HAVING COUNT(*) > 10")
        await fe.execute(
            "CREATE MATERIALIZED VIEW all_b AS SELECT bidder, COUNT(*) "
            "AS cnt FROM bid GROUP BY bidder")
        await fe.step(8)
        hot = await fe.execute("SELECT bidder, cnt FROM hot")
        allb = await fe.execute("SELECT bidder, cnt FROM all_b")
        await fe.close()
        return hot, allb

    hot, allb = asyncio.run(run())
    expect = sorted(r for r in allb if r[1] > 10)
    assert 0 < len(hot) < len(allb)
    assert sorted(hot) == expect


def test_expression_over_aggregates():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, "
            "SUM(price) + COUNT(*) AS mix, MAX(price) - MIN(price) "
            "AS spread FROM bid GROUP BY auction")
        await fe.execute(
            "CREATE MATERIALIZED VIEW raw AS SELECT auction, price "
            "FROM bid")
        await fe.step(8)
        mix = await fe.execute(
            "SELECT auction, mix, spread FROM m ORDER BY auction")
        raw = await fe.execute("SELECT auction, price FROM raw")
        await fe.close()
        return mix, raw

    mix, raw = asyncio.run(run())
    by_auction = {}
    for a, p in raw:
        by_auction.setdefault(a, []).append(p)
    expect = sorted((a, sum(ps) + len(ps), max(ps) - min(ps))
                    for a, ps in by_auction.items())
    assert len(mix) > 10
    assert mix == expect


def test_nexmark_q4_subquery_avg():
    """q4: average final (=max) bid price per category, via a derived
    table — the baseline-config query the frontend previously could
    not express (VERDICT r4 item 4)."""
    async def run():
        fe = Frontend(min_chunks=8)
        n = 20000
        gap = 100_000_000
        for t in ("auction", "bid"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', nexmark.event.num={n}, "
                f"nexmark.min.event.gap.in.ns={gap})")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q4 AS "
            "SELECT category, AVG(final) AS avg_final FROM ("
            "  SELECT a.category AS category, MAX(b.price) AS final"
            "  FROM auction AS a JOIN bid AS b ON a.id = b.auction"
            "  WHERE b.date_time BETWEEN a.date_time AND a.expires"
            "  GROUP BY a.id, a.category) AS q "
            "GROUP BY category")
        await fe.step(10)
        rows = await fe.execute(
            "SELECT category, avg_final FROM q4 ORDER BY category")
        await fe.close()
        return rows

    rows = asyncio.run(run())

    # oracle: numpy recompute from the deterministic generators
    from risingwave_tpu.connectors.nexmark import (
        AUCTION_PROPORTION, BID_PROPORTION, NexmarkConfig,
        gen_auctions, gen_bids,
    )
    n = 20000
    cfg_a = NexmarkConfig(table_type="auction", event_num=n,
                          min_event_gap_in_ns=100_000_000)
    cfg_b = NexmarkConfig(table_type="bid", event_num=n,
                          min_event_gap_in_ns=100_000_000)
    n_auc = n * AUCTION_PROPORTION // 50
    n_bid = n * BID_PROPORTION // 50
    auctions = gen_auctions(np.arange(n_auc, dtype=np.int64), cfg_a)
    bids = gen_bids(np.arange(n_bid, dtype=np.int64), cfg_b)
    finals = {}            # (auction id) -> (category, max price)
    a_by_id = {int(i): k for k, i in enumerate(auctions["id"])}
    for auc, price, ts in zip(bids["auction"], bids["price"],
                              bids["date_time"]):
        k = a_by_id.get(int(auc))
        if k is None:
            continue
        if not (auctions["date_time"][k] <= ts
                <= auctions["expires"][k]):
            continue
        cat = int(auctions["category"][k])
        key = int(auc)
        if key not in finals or finals[key][1] < int(price):
            finals[key] = (cat, int(price))
    per_cat = {}
    for cat, price in finals.values():
        per_cat.setdefault(cat, []).append(price)
    expect = sorted((c, sum(ps) / len(ps))
                    for c, ps in per_cat.items())
    assert len(rows) >= 2
    got = [(c, v) for c, v in rows]
    assert [c for c, _ in got] == [c for c, _ in expect]
    for (_, gv), (_, ev) in zip(got, expect):
        assert abs(gv - ev) < 1e-9 * max(1.0, abs(ev))


def test_subquery_plain_projection():
    """Non-agg derived table: hidden pk carries through."""
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT p2, bidder FROM "
            "(SELECT price * 2 AS p2, bidder, auction FROM bid) q "
            "WHERE p2 > 2000")
        await fe.execute(
            "CREATE MATERIALIZED VIEW o AS SELECT price, bidder "
            "FROM bid WHERE price * 2 > 2000")
        await fe.step(6)
        m = await fe.execute("SELECT p2, bidder FROM m")
        o = await fe.execute("SELECT price, bidder FROM o")
        await fe.close()
        return m, o

    m, o = asyncio.run(run())
    assert len(m) > 0
    assert sorted(m) == sorted((p * 2, b) for p, b in o)


# -- batch ----------------------------------------------------------------


def test_batch_having_and_subquery():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        await fe.execute(
            "CREATE MATERIALIZED VIEW raw AS SELECT auction, bidder, "
            "price FROM bid")
        await fe.step(6)
        h = await fe.execute(
            "SELECT auction, COUNT(*) AS c FROM raw GROUP BY auction "
            "HAVING COUNT(*) > 3 ORDER BY auction")
        base = await fe.execute(
            "SELECT auction, COUNT(*) AS c FROM raw GROUP BY auction "
            "ORDER BY auction")
        sq = await fe.execute(
            "SELECT q.c + 1 AS c1 FROM (SELECT auction, COUNT(*) AS c "
            "FROM raw GROUP BY auction) q ORDER BY c1 LIMIT 3")
        await fe.close()
        return h, base, sq

    h, base, sq = asyncio.run(run())
    assert h == [r for r in base if r[1] > 3]
    assert sq == sorted([(r[1] + 1,) for r in base])[:3]


def test_having_without_group_key_projected():
    """Inner-q4 shape standalone: GROUP BY keys absent from SELECT."""
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT MAX(price) AS mp "
            "FROM bid GROUP BY auction")
        await fe.step(6)
        rows = await fe.execute("SELECT mp FROM m")
        star = await fe.execute("SELECT * FROM m")
        await fe.close()
        return rows, star

    rows, star = asyncio.run(run())
    assert len(rows) > 10
    # the hidden _g0 group key must NOT leak through SELECT *
    assert all(len(r) == 1 for r in star)


def test_eowc_over_subquery_rejected():
    """The inner query's EOWC watermark column is meaningless against
    the outer schema — gate on it and the MV never emits (code-review
    r5 finding); a clean PlanError is the correct behavior."""
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(_bid_source())
        with pytest.raises(PlanError):
            await fe.execute(
                "CREATE MATERIALIZED VIEW e AS SELECT c FROM ("
                "SELECT window_start, COUNT(*) AS c FROM TUMBLE(bid, "
                "date_time, INTERVAL '10' SECOND) GROUP BY "
                "window_start) q EMIT ON WINDOW CLOSE")
        await fe.close()

    asyncio.run(run())
