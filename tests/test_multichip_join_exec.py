"""HashJoinExecutor over the 8-device virtual mesh (ShardedJoinKernel)
must be indistinguishable from the single-chip kernel — the wiring
VERDICT r3 #3 required: sharded joins reachable from the executor (and
through planner.mesh from SQL), including retractions, outer degrees,
watermark expiry, and recovery.
"""

import asyncio
from collections import Counter

import numpy as np
import pytest
from jax.sharding import Mesh

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.parallel.join import ShardedJoinKernel
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import is_chunk

from test_hash_join import (  # noqa: F401  (reuse the harness)
    JoinOracle, L_SCHEMA, R_SCHEMA, barrier, lchunk, materialize_join,
    rchunk,
)


def run_join_mesh(mesh, script_l, script_r, n_barriers,
                  join_type=JoinType.INNER, store=None):
    store = store or MemoryStateStore()
    lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, script_l), MockSource(R_SCHEMA, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        join_type=join_type, mesh=mesh)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, (lt, rt, store), ex


def _random_scripts(seed):
    rng = np.random.default_rng(seed)
    oracle = JoinOracle()
    script_l, script_r = [barrier(1)], [barrier(1)]
    b = 2
    lpk, rpk = 0, 0
    for _ in range(5):
        ks, vs, ops = [], [], []
        for _ in range(24):
            if oracle.left and rng.random() < 0.3:
                i = int(rng.integers(0, len(oracle.left)))
                k_, v_ = oracle.left.pop(i)
                ks.append(k_); vs.append(v_); ops.append(Op.DELETE)
            else:
                k_, v_ = int(rng.integers(0, 8)), lpk
                lpk += 1
                oracle.left.append((k_, v_))
                ks.append(k_); vs.append(v_); ops.append(Op.INSERT)
        script_l.append(lchunk(ks, vs, ops=ops))
        ks, vs, ops = [], [], []
        for _ in range(16):
            if oracle.right and rng.random() < 0.3:
                i = int(rng.integers(0, len(oracle.right)))
                k_, v_ = oracle.right.pop(i)
                ks.append(k_); vs.append(v_); ops.append(Op.DELETE)
            else:
                k_, v_ = int(rng.integers(0, 8)), f"r{rpk}"
                rpk += 1
                oracle.right.append((k_, v_))
                ks.append(k_); vs.append(v_); ops.append(Op.INSERT)
        script_r.append(rchunk(ks, vs, ops=ops))
        script_l.append(barrier(b))
        script_r.append(barrier(b))
        b += 1
    return script_l, script_r, b - 1, oracle


def test_sharded_join_executor_random_oracle(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    script_l, script_r, nb, oracle = _random_scripts(23)
    msgs, _t, ex = run_join_mesh(mesh, script_l, script_r, nb)
    assert isinstance(ex.sides[0].kernel, ShardedJoinKernel)
    assert materialize_join(msgs) == oracle.view()


def test_sharded_left_outer_degrees(eight_devices):
    """Degree transitions (NULL-padding flips) through the sharded
    matcher: the deg block rides the same packed matrix."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    script_l = [barrier(1), lchunk([1, 2], [10, 20]), barrier(2),
                barrier(3)]
    script_r = [barrier(1), barrier(2), rchunk([1], ["a"]), barrier(3)]
    msgs, _t, _ex = run_join_mesh(mesh, script_l, script_r, 3,
                                  join_type=JoinType.LEFT_OUTER)
    got = materialize_join(msgs)
    assert got == Counter({(1, 10, 1, "a"): 1,
                           (2, 20, None, None): 1})


def test_sharded_join_watermark_expiry(eight_devices):
    """State expiry routes tombstones to the owning shard by key."""
    from risingwave_tpu.stream.message import Watermark

    mesh = Mesh(np.asarray(eight_devices), ("d",))
    wm = lambda v: Watermark(0, DataType.INT64, v)  # noqa: E731
    script_l = [barrier(1), lchunk([1, 5, 9], [10, 50, 90]), wm(6),
                barrier(2),
                lchunk([], []), barrier(3)]
    script_r = [barrier(1), rchunk([9], ["i"]), wm(8), barrier(2),
                rchunk([1, 5, 9], ["a2", "e2", "i2"]), barrier(3)]
    msgs, (lt, rt, _s), _ex = run_join_mesh(mesh, script_l, script_r, 3)
    got = materialize_join(msgs)
    # keys 1 and 5 expired at barrier 2 (combined wm=6): the epoch-3
    # right rows for them find nothing; key 9 still matches
    assert got == Counter({(9, 90, 9, "i"): 1, (9, 90, 9, "i2"): 1})
    assert sorted(r[0] for _pk, r in lt.iter_rows()) == [9]


def test_sharded_join_recovery_resumes(eight_devices):
    """Mirror of tests/test_multichip_agg recovery: kill the executor,
    rebuild from the state tables onto the SHARDED kernel, degrees
    recomputed by one routed batch probe."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    store = MemoryStateStore()

    def build(sl, sr, jt):
        lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
        rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
        return HashJoinExecutor(
            MockSource(L_SCHEMA, sl), MockSource(R_SCHEMA, sr),
            left_keys=[0], right_keys=[0], left_table=lt,
            right_table=rt, join_type=jt, mesh=mesh)

    ex1 = build([barrier(1), lchunk([1, 2], [10, 20]), barrier(2)],
                [barrier(1), rchunk([1], ["a"]), barrier(2)],
                JoinType.LEFT_OUTER)
    msgs1 = asyncio.run(collect_until_n_barriers(ex1, 2))
    view = materialize_join(msgs1)
    assert view == Counter({(1, 10, 1, "a"): 1, (2, 20, None, None): 1})
    # restart: new right rows — recovered left rows must match, and the
    # recovered DEGREE of row (1,10) must suppress a duplicate padded
    # retraction while (2,20) flips off its NULL padding
    ex2 = build([barrier(3), barrier(4)],
                [barrier(3), rchunk([2], ["b"]), barrier(4)],
                JoinType.LEFT_OUTER)
    assert isinstance(ex2.sides[0].kernel, ShardedJoinKernel)
    msgs2 = asyncio.run(collect_until_n_barriers(ex2, 2))
    for m in msgs2:
        if is_chunk(m):
            view.update({tuple(r): (1 if op.is_insert else -1)
                         for op, r in m.to_records()})
    view = +Counter({k: v for k, v in view.items() if v})
    assert view == Counter({(1, 10, 1, "a"): 1, (2, 20, 2, "b"): 1})


def test_sharded_probe_overflow_retries(eight_devices):
    """Tiny per-shard pair buffer forces the double/retry re-dispatch."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    k = ShardedJoinKernel(mesh, key_width=1, probe_capacity=1)
    other = ShardedJoinKernel(mesh, key_width=1, probe_capacity=1)
    lanes = np.asarray([[3]] * 9 + [[4]] * 7, dtype=np.int32)
    refs = np.arange(16, dtype=np.int32)
    h = k.apply_and_probe(other, lanes, np.zeros(16, dtype=bool),
                          refs, np.ones(16, dtype=bool),
                          np.zeros(16, dtype=np.int32),
                          np.zeros(16, dtype=bool), seq=1)
    h.collect()
    probe = np.asarray([[3], [4], [5], [6]], dtype=np.int32)
    deg, pidx, prefs = k.probe(probe, np.ones(4, dtype=bool))
    assert deg.tolist() == [9, 7, 0, 0]
    assert {int(r) for p, r in zip(pidx, prefs) if p == 0} == \
        set(range(9))
    assert {int(r) for p, r in zip(pidx, prefs) if p == 1} == \
        set(range(9, 16))


def test_sql_join_runs_sharded(eight_devices):
    """The SQL path reaches the sharded JOIN kernel (VERDICT r3 #3): a
    parallelism=8 session plans q8-shaped joins onto ShardedJoinKernel
    and the MV matches the parallelism=1 result exactly."""
    from risingwave_tpu.frontend.session import Frontend

    sql = [
        "CREATE SOURCE person WITH (connector='nexmark', "
        "nexmark.table.type='person', nexmark.event.num=20000, "
        "nexmark.min.event.gap.in.ns=100000000)",
        "CREATE SOURCE auction WITH (connector='nexmark', "
        "nexmark.table.type='auction', nexmark.event.num=20000, "
        "nexmark.min.event.gap.in.ns=100000000)",
        "CREATE MATERIALIZED VIEW q8 AS SELECT p.id, p.name, a.seller "
        "FROM person AS p JOIN auction AS a ON p.id = a.seller",
    ]

    def _walk(ex):
        out = []
        if hasattr(ex, "sides"):
            out.append(ex)
        for attr in ("input", "left_in", "right_in"):
            child = getattr(ex, attr, None)
            if child is not None:
                out.extend(_walk(child))
        return out

    async def run(parallelism):
        f = Frontend(rate_limit=4, min_chunks=8,
                     parallelism=parallelism)
        for s in sql:
            await f.execute(s)
        for _ in range(10):
            await f.step()
        rows = await f.execute("SELECT * FROM q8")
        if parallelism > 1:
            joins = [j for actor in f.actors.values()
                     for j in _walk(actor.consumer)]
            assert joins and all(
                isinstance(j.sides[0].kernel, ShardedJoinKernel)
                for j in joins), "join plan was not sharded"
        await f.close()
        return sorted({r[:3] for r in rows})

    got = asyncio.run(run(8))
    want = asyncio.run(run(1))
    assert got == want
    assert len(got) > 0


def test_sharded_join_grows_past_initial_capacity(eight_devices):
    """Join state 10x the initial sharded key capacity: barrier-time
    compact-with-growth replaces the fatal guard (VERDICT r3 #5)."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    store = MemoryStateStore()
    lt = StateTable(61, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(62, R_SCHEMA, [1], store, dist_key_indices=[])
    script_l, script_r = [barrier(1)], [barrier(1)]
    oracle = JoinOracle()
    b = 2
    n_keys = 1280                    # 10x key_capacity=128
    for r in range(10):
        ks = list(range(r * 128, (r + 1) * 128))
        vs = ks
        oracle.left += list(zip(ks, vs))
        script_l.append(lchunk(ks, vs))
        script_l.append(barrier(b))
        # right side joins a few of this round's keys
        rk = ks[:4]
        rv = [f"r{x}" for x in rk]
        oracle.right += list(zip(rk, rv))
        script_r.append(rchunk(rk, rv))
        script_r.append(barrier(b))
        b += 1
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, script_l), MockSource(R_SCHEMA, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        mesh=mesh,
        shard_opts=dict(key_capacity=128, row_capacity=1 << 12,
                        probe_capacity=256))
    msgs = asyncio.run(collect_until_n_barriers(ex, b - 1))
    assert ex.sides[0].kernel.key_capacity > 128      # grew
    assert materialize_join(msgs) == oracle.view()
