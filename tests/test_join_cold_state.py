"""Cold-state tier for join state (VERDICT r4 #6).

Join state exceeds the configured resident cap by >10x: old keys evict
from the arena + device into the (durable) state table, and probes of
evicted keys reload them first — results stay oracle-exact, including
probes that arrive MANY barriers after their key went cold, and
recovery across a restart.

Reference parity: src/stream/src/executor/managed_state/join/mod.rs
:228,379-420 (JoinHashMap as an LRU cache over the StateTable).
"""

import asyncio
import collections

import numpy as np
import pytest

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind
from risingwave_tpu.common.epoch import Epoch, EpochPair

L_SCHEMA = Schema.of(k=DataType.INT64, lv=DataType.INT64,
                     lid=DataType.INT64)
R_SCHEMA = Schema.of(k=DataType.INT64, rv=DataType.INT64,
                     rid=DataType.INT64)
CAP = 64


def _barrier(n):
    curr = Epoch.from_physical(n)
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(curr, prev), BarrierKind.CHECKPOINT)


def _chunk(schema, rows):
    names = [f.name for f in schema]
    return StreamChunk.from_pydict(
        schema, {nm: [r[i] for r in rows]
                 for i, nm in enumerate(names)})


def _build(store, left_msgs, right_msgs, cap=CAP):
    # state-table pk = (join key, row id): the key prefix is what the
    # cold tier prefix-scans on reload
    lt = StateTable(11, L_SCHEMA, [0, 2], store, dist_key_indices=[0])
    rt = StateTable(12, R_SCHEMA, [0, 2], store, dist_key_indices=[0])
    join = HashJoinExecutor(
        MockSource(L_SCHEMA, left_msgs),
        MockSource(R_SCHEMA, right_msgs),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        state_cap=cap)
    return join


def _oracle(left_rows, right_rows):
    by_key = collections.defaultdict(list)
    for r in right_rows:
        by_key[r[0]].append(r)
    out = collections.Counter()
    for l in left_rows:
        for r in by_key.get(l[0], ()):
            out[l + r] += 1
    return out


def test_cold_state_10x_over_cap_oracle_exact():
    """600 keys stream through a 64-key resident cap; every key's rows
    later probe again (long after eviction) — the reload path must
    produce the exact inner-join result."""
    n_keys = 600
    left_rows, right_rows = [], []
    lmsgs, rmsgs = [_barrier(1)], [_barrier(1)]
    epoch = 2
    # phase 1: rights arrive in key order (old keys go cold)
    for lo in range(0, n_keys, 100):
        rows = [(k, k * 10, k) for k in range(lo, lo + 100)]
        right_rows += rows
        rmsgs += [_chunk(R_SCHEMA, rows), _barrier(epoch)]
        lmsgs += [_barrier(epoch)]
        epoch += 1
    # phase 2: lefts probe EVERY key, oldest first — most are cold now
    lid = 10_000
    for lo in range(0, n_keys, 100):
        rows = [(k, k + 1, lid + k) for k in range(lo, lo + 100)]
        left_rows += rows
        lmsgs += [_chunk(L_SCHEMA, rows), _barrier(epoch)]
        rmsgs += [_barrier(epoch)]
        epoch += 1
    store = MemoryStateStore()
    join = _build(store, lmsgs, rmsgs)
    outs = asyncio.run(collect_until_n_barriers(join, epoch - 1))
    got = collections.Counter()
    for m in outs:
        if isinstance(m, StreamChunk):
            for op, row in m.to_records():
                assert op.is_insert
                got[tuple(row)] += 1
    assert got == _oracle(left_rows, right_rows)
    # the cap held: far fewer resident rows than total keys
    for side in join.sides:
        assert len(side.pk_to_ref) <= 2 * CAP, len(side.pk_to_ref)
    assert sum(len(s.cold_keys) for s in join.sides) > 0


def test_cold_state_survives_recovery():
    """Evicted state recovers: restart over the same store, then probe
    keys that were cold before the crash."""
    store = MemoryStateStore()
    n_keys = 400
    rmsgs = [_barrier(1)]
    epoch = 2
    right_rows = []
    for lo in range(0, n_keys, 100):
        rows = [(k, k * 7, k) for k in range(lo, lo + 100)]
        right_rows += rows
        rmsgs += [_chunk(R_SCHEMA, rows), _barrier(epoch)]
        epoch += 1
    lmsgs = [_barrier(e) for e in range(1, epoch)]
    join = _build(store, lmsgs, rmsgs)
    asyncio.run(collect_until_n_barriers(join, epoch - 1))

    # restart: a fresh executor over the same store (recovery loads
    # whatever the state table holds — resident and evicted alike)
    left_rows = [(k, 1, 10_000 + k) for k in range(0, n_keys, 3)]
    lmsgs2 = [_barrier(epoch), _chunk(L_SCHEMA, left_rows),
              _barrier(epoch + 1)]
    rmsgs2 = [_barrier(epoch), _barrier(epoch + 1)]
    join2 = _build(store, lmsgs2, rmsgs2)
    outs = asyncio.run(collect_until_n_barriers(join2, 2))
    got = collections.Counter()
    for m in outs:
        if isinstance(m, StreamChunk):
            for op, row in m.to_records():
                got[tuple(row)] += 1
    assert got == _oracle(left_rows, right_rows)


def test_cold_state_guards():
    store = MemoryStateStore()
    lt = StateTable(1, L_SCHEMA, [2], store)     # pk NOT key-prefixed
    rt = StateTable(2, R_SCHEMA, [0, 2], store, dist_key_indices=[0])
    with pytest.raises(ValueError, match="prefixed"):
        HashJoinExecutor(MockSource(L_SCHEMA, []),
                         MockSource(R_SCHEMA, []),
                         left_keys=[0], right_keys=[0],
                         left_table=lt, right_table=rt, state_cap=8)
    lt2 = StateTable(3, L_SCHEMA, [0, 2], store, dist_key_indices=[0])
    # semi/anti stay excluded (degree-transition HISTORY cannot be
    # evicted); outer joins are tier-eligible since the state-tiering
    # subsystem landed — their degrees recompute on reload
    with pytest.raises(ValueError, match="semi"):
        HashJoinExecutor(MockSource(L_SCHEMA, []),
                         MockSource(R_SCHEMA, []),
                         left_keys=[0], right_keys=[0],
                         left_table=lt2, right_table=rt,
                         join_type=JoinType.LEFT_SEMI, state_cap=8)


def test_cold_state_from_sql():
    """join_state_cap on the session: a q8-shaped SQL join with the
    resident cap 10x under the key count stays oracle-exact."""
    from risingwave_tpu.frontend.session import Frontend

    async def run(cap):
        fe = Frontend(min_chunks=8, join_state_cap=cap)
        n = 8000
        for t in ("person", "auction"):
            await fe.execute(
                f"CREATE SOURCE {t} WITH (connector='nexmark', "
                f"nexmark.table.type='{t}', nexmark.event.num={n}, "
                f"nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW j AS SELECT p.id, p.name, "
            "a.seller FROM person AS p JOIN auction AS a "
            "ON p.id = a.seller")
        await fe.step(12)
        rows = await fe.execute("SELECT * FROM j")
        await fe.close()
        return collections.Counter(map(tuple, rows))

    capped = asyncio.run(run(16))        # ~160 persons resident-capped
    uncapped = asyncio.run(run(None))
    assert capped == uncapped
    assert len(capped) > 50


def test_cold_state_insert_after_evict_no_duplicates():
    """A row arriving for an ALREADY-COLD key is resident; a later
    probe reloads the key — the resident row must not re-add (it would
    match twice and orphan a device ref)."""
    store = MemoryStateStore()
    rmsgs = [_barrier(1)]
    right_rows = []
    epoch = 2
    # fill way past cap so key 0 goes cold
    for lo in range(0, 300, 100):
        rows = [(k, k, k) for k in range(lo, lo + 100)]
        right_rows += rows
        rmsgs += [_chunk(R_SCHEMA, rows), _barrier(epoch)]
        epoch += 1
    # NEW row for (cold) key 0, then a probe of key 0
    late = (0, 999, 9000)
    right_rows.append(late)
    rmsgs += [_chunk(R_SCHEMA, [late]), _barrier(epoch)]
    lmsgs = [_barrier(e) for e in range(1, epoch + 1)]
    epoch += 1
    probe = (0, 5, 7777)
    lmsgs += [_chunk(L_SCHEMA, [probe]), _barrier(epoch)]
    rmsgs += [_barrier(epoch)]
    join = _build(store, lmsgs, rmsgs)
    outs = asyncio.run(collect_until_n_barriers(join, epoch))
    got = collections.Counter()
    for m in outs:
        if isinstance(m, StreamChunk):
            for op, row in m.to_records():
                got[tuple(row)] += 1
    assert got == _oracle([probe], right_rows)
    assert got[probe + late] == 1        # exactly once


def test_cold_state_retracting_input_fails_loud():
    """A retraction for an EVICTED key cannot be applied against
    device state (ADVICE r5 high): the executor refuses loudly instead
    of silently leaving already-emitted join outputs stale. (The
    planner never enables state_cap on inputs it cannot prove
    append-only — this guards direct executor users.)"""
    from risingwave_tpu.common.chunk import Op

    store = MemoryStateStore()
    rmsgs = [_barrier(1)]
    epoch = 2
    for lo in range(0, 300, 100):        # 300 keys >> cap: key 0 cold
        rows = [(k, k, k) for k in range(lo, lo + 100)]
        rmsgs += [_chunk(R_SCHEMA, rows), _barrier(epoch)]
        epoch += 1
    dead = StreamChunk.from_pydict(
        R_SCHEMA, {"k": [0], "rv": [0], "rid": [0]},
        ops=[Op.DELETE])
    rmsgs += [dead, _barrier(epoch)]
    lmsgs = [_barrier(e) for e in range(1, epoch + 1)]
    join = _build(store, lmsgs, rmsgs)
    with pytest.raises(RuntimeError, match="evicted"):
        asyncio.run(collect_until_n_barriers(join, epoch))


def test_join_state_cap_disabled_for_retracting_inputs():
    """join_state_cap set session-wide + a join over a RETRACTING
    input (a GROUP BY subquery): the planner must NOT enable the cold
    tier there — results stay exact, with no evicted-key retraction
    error, while append-only joins keep the cap."""
    from risingwave_tpu.frontend.session import Frontend

    async def run(cap):
        fe = Frontend(min_chunks=4, join_state_cap=cap)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=4000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW j AS SELECT b.auction, b.price, "
            "p.c FROM bid AS b JOIN (SELECT auction, count(*) AS c "
            "FROM bid GROUP BY auction) AS p "
            "ON b.auction = p.auction")
        await fe.step(10)
        rows = await fe.execute("SELECT * FROM j")
        await fe.close()
        return collections.Counter(map(tuple, rows))

    capped = asyncio.run(run(8))         # cap must be ignored here
    uncapped = asyncio.run(run(None))
    assert capped == uncapped
    assert len(capped) > 20
