"""TemporalJoin (stream ⋈ versioned table AS OF PROCTIME) + lookup
arrangement — reference temporal_join.rs:52 / lookup.rs:42 parity."""

import asyncio
from collections import Counter

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.executors.temporal_join import (
    TemporalJoinExecutor,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

L = Schema.of(k=DataType.INT64, v=DataType.INT64)
R = Schema.of(rk=DataType.INT64, rv=DataType.VARCHAR)


def barrier(n):
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def lc(ks, vs):
    return StreamChunk.from_pydict(L, {"k": ks, "v": vs})


def rc(ks, vs, ops=None):
    return StreamChunk.from_pydict(R, {"rk": ks, "rv": vs}, ops=ops)


def run(sl, sr, nb, outer=False):
    class _Keyed(MockSource):
        @property
        def pk_indices(self):
            return [0]

    ex = TemporalJoinExecutor(
        MockSource(L, sl), _Keyed(R, sr), [0], [0], outer=outer)
    msgs = asyncio.run(collect_until_n_barriers(ex, nb))
    return [tuple(r) for m in msgs if is_chunk(m)
            for op, r in m.to_records()]


def test_temporal_probe_sees_version_as_of_arrival():
    """A left row matches the right version current at its epoch;
    later right updates never revise emitted rows."""
    # each right change lands one epoch BEFORE its probe: intra-epoch
    # interleaving is unordered by design (process-time semantics),
    # but barrier alignment guarantees epoch N's arrangement updates
    # apply before any epoch N+1 message
    sl = [barrier(1), barrier(2), lc([1], [10]), barrier(3),
          barrier(4), lc([1], [11]), barrier(5)]
    sr = [barrier(1), rc([1], ["old"]), barrier(2), barrier(3),
          rc([1, 1], ["old", "new"],
             ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]), barrier(4),
          barrier(5)]
    rows = run(sl, sr, 5)
    # epoch-3 probe sees "old", epoch-5 probe sees "new"; emitted rows
    # are never retracted when the right side changes
    assert rows == [(1, 10, 1, "old"), (1, 11, 1, "new")]


def test_temporal_inner_drops_unmatched_left_outer_pads():
    sl = [barrier(1), barrier(2), lc([1, 2], [10, 20]), barrier(3)]
    sr = [barrier(1), rc([1], ["a"]), barrier(2), barrier(3)]
    assert Counter(run(sl, sr, 3)) == Counter({(1, 10, 1, "a"): 1})
    assert Counter(run(sl, sr, 3, outer=True)) == Counter(
        {(1, 10, 1, "a"): 1, (2, 20, None, None): 1})


def test_temporal_right_delete_unmatches():
    sl = [barrier(1), barrier(2), barrier(3), lc([1], [10]),
          barrier(4)]
    sr = [barrier(1), rc([1], ["a"]), barrier(2),
          rc([1], ["a"], ops=[Op.DELETE]), barrier(3), barrier(4)]
    assert run(sl, sr, 4, outer=True) == [(1, 10, None, None)]


def test_temporal_join_sql_end_to_end():
    """Dimension-table enrichment from SQL: bids against an auction
    count MV, LEFT temporal join (every bid emits exactly once, the
    enriched count frozen as-of probe time)."""
    from risingwave_tpu.frontend.session import Frontend

    async def go():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW dim AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.execute(
            "CREATE MATERIALIZED VIEW e AS SELECT b.price, d.c, "
            "b.auction FROM bid AS b LEFT JOIN dim AS d FOR "
            "SYSTEM_TIME AS OF PROCTIME() ON b.auction = d.auction")
        for _ in range(12):
            await fe.step()
        enriched = await fe.execute("SELECT * FROM e")
        final = dict(await fe.execute("SELECT auction, c FROM dim"))
        await fe.close()
        return enriched, final

    enriched, final = asyncio.run(go())
    n_bids = 3000 * 46 // 50
    assert len(enriched) == n_bids        # append-only, one per bid
    for _price, c, a, *_rid in enriched:
        if c is not None:
            assert 1 <= c <= final[a]     # a real as-of version


def test_temporal_join_rejects_non_mv_right():
    from risingwave_tpu.frontend.session import Frontend

    async def go():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000)")
        with pytest.raises(Exception, match="materialized view"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW x AS SELECT b.price FROM "
                "bid AS b JOIN bid AS b2 FOR SYSTEM_TIME AS OF "
                "PROCTIME() ON b.auction = b2.auction")
        await fe.close()

    asyncio.run(go())
