"""Utilization tricolor tests (ISSUE 14): exclusive-time/idle
subtraction with nested idle-exposing children, sender-side credit
park accounting, and the busy+backpressure+idle ≤ 1 identity."""

import asyncio
import time

import pytest

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.stream.exchange import (
    channel, note_backpressure, pop_park_cell, push_park_cell,
    set_actor_meter,
)
from risingwave_tpu.stream.executor import Executor, ExecutorInfo
from risingwave_tpu.stream.merge import barrier_align_n
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, is_barrier, is_chunk,
)
from risingwave_tpu.stream.monitor import (
    TOPOLOGY, UTILIZATION, install_monitoring,
)
from risingwave_tpu.utils.metrics import STREAMING

SCH = Schema([Field("a", DataType.INT64)])


def _barrier(e: int) -> Barrier:
    return Barrier(EpochPair(Epoch(e + 1), Epoch(e)),
                   BarrierKind.BARRIER)


def _chunk(n: int = 4) -> StreamChunk:
    return StreamChunk.from_pydict(SCH, {"a": list(range(n))})


class IdleFeed(Executor):
    """Source/RemoteInput-shaped node: parks (accruing idle_wait_s)
    before each scripted message — the input-starved shape whose park
    must NOT read as busy."""

    def __init__(self, msgs, idle_s: float, ident: str):
        super().__init__(ExecutorInfo(SCH, [], ident))
        self.msgs = list(msgs)
        self.idle_s = idle_s
        self.idle_wait_s = 0.0

    async def execute(self):
        for msg in self.msgs:
            t0 = time.monotonic()
            await asyncio.sleep(self.idle_s)
            self.idle_wait_s += time.monotonic() - t0
            yield msg


class BusyPass(Executor):
    """Burns host CPU per chunk — the chain's true straggler."""

    def __init__(self, input_, busy_s: float):
        super().__init__(ExecutorInfo(SCH, [], "BusyPass"))
        self.input = input_
        self.busy_s = busy_s

    async def execute(self):
        async for msg in self.input.execute():
            if is_chunk(msg):
                t0 = time.perf_counter()
                while time.perf_counter() - t0 < self.busy_s:
                    pass
            yield msg


class CheapPass(Executor):
    def __init__(self, input_):
        super().__init__(ExecutorInfo(SCH, [], "CheapPass"))
        self.input = input_

    async def execute(self):
        async for msg in self.input.execute():
            yield msg


class AlignTwo(Executor):
    """Minimal 2-input fan-in over barrier_align_n (the join shape)."""

    def __init__(self, left, right):
        super().__init__(ExecutorInfo(SCH, [], "AlignTwo"))
        self.inputs = [left, right]

    async def execute(self):
        async for tag, msg in barrier_align_n(
                [i.execute() for i in self.inputs]):
            yield msg


async def _drive(consumer, n_barriers: int) -> None:
    seen = 0
    async for msg in consumer.execute():
        if is_barrier(msg):
            seen += 1
            if seen >= n_barriers:
                return


def test_nested_idle_subtraction_source_and_remote_shape():
    """The PR-7 attribution path, directly: a chain whose SOURCE and a
    RemoteInput-shaped sibling both expose idle_wait_s, under a busy
    middle node and a cheap root. Exclusive busy must land on the busy
    node; the idle feeds must read idle, not busy; every triple sums
    to ≤ 1."""
    script = [_barrier(0), _chunk(), _barrier(2), _chunk(),
              _barrier(4), _chunk(), _barrier(6)]
    left = IdleFeed(script, idle_s=0.03, ident="MockSource")
    right = IdleFeed(list(script), idle_s=0.03,
                     ident="RemoteInput(1->2)")
    chain = CheapPass(BusyPass(AlignTwo(left, right), busy_s=0.05))
    consumer = install_monitoring(chain, fragment="tri-nested",
                                  actor_id=41)
    asyncio.run(_drive(consumer, 4))

    rows = {(node, ex): (busy, bp, idle)
            for a, frag, node, ex, _e, _i, busy, bp, idle
            in UTILIZATION.rows() if frag == "tri-nested"}
    assert rows, "no utilization rows published"
    # node ids: 0 CheapPass, 1 BusyPass, 2 AlignTwo, 3/4 the feeds
    busy_node = rows[(1, "BusyPass")]
    assert busy_node[0] > 0.3, busy_node
    for (node, ex), (busy, bp, idle) in rows.items():
        assert busy + bp + idle <= 1.0 + UTILIZATION.EPSILON, \
            (node, ex, busy, bp, idle)
        if ex in ("MockSource", "RemoteInput(1->2)"):
            assert idle > 0.2, (ex, busy, bp, idle)
            assert busy < idle, (ex, busy, bp, idle)
    # the cheap root's EXCLUSIVE busy excludes its whole subtree
    assert rows[(0, "CheapPass")][0] < 0.2, rows[(0, "CheapPass")]
    # cumulative counters agree: the busy node out-earns the feeds
    busy_mid = STREAMING.executor_busy.get(
        fragment="tri-nested", actor="41", executor="BusyPass",
        node="1")
    busy_src = STREAMING.executor_busy.get(
        fragment="tri-nested", actor="41", executor="MockSource",
        node="3")
    assert busy_mid > busy_src
    assert not UTILIZATION.gate_violations()
    TOPOLOGY.drop_actor(41)


def test_sender_park_charges_channel_and_context():
    """A sender blocked for credits records the park (a) in the
    per-channel counter and (b) in the innermost park cell when one is
    pushed, else the actor meter."""
    async def run():
        tx, rx = channel(chunk_permits=4, max_chunk_cost=4,
                         edge="tri:park")
        before = STREAMING.backpressure_wait.get(channel="tri:park")
        meter = [0.0]
        mtok = set_actor_meter(meter)

        async def consume_later():
            await asyncio.sleep(0.08)
            while True:
                try:
                    await asyncio.wait_for(rx.recv(), timeout=0.2)
                except (asyncio.TimeoutError, Exception):
                    return

        task = asyncio.ensure_future(consume_later())
        await tx.send(_chunk(4))          # fills the budget, no park
        await tx.send(_chunk(4))          # parks until the consumer
        meter_after_send = meter[0]
        # in-pull sends charge the pushed cell INSTEAD of the meter
        cell = [0.0]
        ptok = push_park_cell(cell)
        await tx.send(_chunk(4))
        pop_park_cell(ptok)
        set_actor_meter(None)
        await task
        parked = STREAMING.backpressure_wait.get(
            channel="tri:park") - before
        return meter_after_send, cell[0], parked

    meter_s, cell_s, parked = asyncio.run(run())
    assert meter_s > 0.04, meter_s          # the actor-meter park
    assert cell_s > 0.0, cell_s             # the in-pull park
    assert parked >= meter_s + cell_s - 1e-6


def test_actor_dispatch_park_lands_in_root_backpressure():
    """Full actor shape: the chain is fast, but its dispatcher feeds a
    credit-starved downstream — the park must surface as the ROOT
    node's backpressure share (and be absent from busy), so the
    straggler story names the slow consumer, not this actor."""
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.dispatch import Output, SimpleDispatcher
    from risingwave_tpu.stream.executors.test_utils import MockSource

    async def run():
        store = MemoryStateStore()
        local = LocalBarrierManager()
        tx, src = MockSource.channel(SCH)
        local.register_sender(9, tx)
        consumer = install_monitoring(CheapPass(src),
                                      fragment="tri-actor",
                                      actor_id=9)
        out_tx, out_rx = channel(chunk_permits=4, max_chunk_cost=4,
                                 barrier_permits=64,
                                 edge="tri:actor-out")
        actor = Actor(9, consumer,
                      dispatchers=[SimpleDispatcher(
                          Output(10, out_tx))],
                      barrier_manager=local, fragment="tri-actor")
        local.set_expected_actors([9])
        loop = BarrierLoop(local, store)
        task = actor.spawn()

        async def slow_drain():
            while True:
                try:
                    await asyncio.wait_for(out_rx.recv(), timeout=1.0)
                except asyncio.TimeoutError:
                    return
                await asyncio.sleep(0.02)

        drain = asyncio.ensure_future(slow_drain())
        await loop.inject_and_collect(force_checkpoint=True)
        for e in range(3):
            # 3 full chunks per epoch >> the 4-permit budget: the
            # dispatch send must park on the drainer's cadence
            for _ in range(3):
                await src._tx.send(_chunk(4))
            await loop.inject_and_collect(force_checkpoint=True)
        row = UTILIZATION.get("tri-actor", 9, 0)
        from risingwave_tpu.stream.message import StopMutation
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset({9})))
        await task
        drain.cancel()
        assert actor.failure is None
        return row

    row = asyncio.run(run())
    assert row is not None
    _ex, _e, _i, busy, bp, idle = row
    assert bp > 0.1, (busy, bp, idle)
    assert busy + bp + idle <= 1.0 + UTILIZATION.EPSILON
    parked = STREAMING.backpressure_wait.get(channel="tri:actor-out")
    assert parked > 0.0


def test_tricolor_off_publishes_nothing():
    from risingwave_tpu.stream import monitor as _monitor
    _monitor.set_tricolor(False)
    try:
        script = [_barrier(0), _chunk(), _barrier(2), _chunk(),
                  _barrier(4)]
        feed = IdleFeed(script, idle_s=0.0, ident="MockSource")
        consumer = install_monitoring(CheapPass(feed),
                                      fragment="tri-off", actor_id=43)
        asyncio.run(_drive(consumer, 3))
        assert not [r for r in UTILIZATION.rows() if r[1] == "tri-off"]
    finally:
        _monitor.set_tricolor(True)
        TOPOLOGY.drop_actor(43)


def test_metric_families_sorted_with_help():
    """ctl metrics exposition: families render in sorted order and
    every ISSUE-14 family carries a HELP line, so round-over-round
    dumps diff cleanly."""
    from risingwave_tpu.utils.metrics import GLOBAL
    # touch the new families so they render at least one series
    STREAMING.backpressure_wait.inc(0.001, channel="helptest")
    STREAMING.executor_utilization.set(
        0.5, state="busy", fragment="helptest", actor="1",
        executor="X", node="0")
    STREAMING.mv_freshness_lag.set(0.1, mv="helptest")
    STREAMING.mv_freshness_wall_lag.set(0.1, mv="helptest")
    STREAMING.bottleneck_streak.set(1, domain="helptest", operator="X")
    text = GLOBAL.render()
    fams = [ln.split()[2] for ln in text.splitlines()
            if ln.startswith("# TYPE ")]
    assert fams == sorted(fams), "families must render sorted"
    assert len(fams) == len(set(fams))
    for fam in ("stream_backpressure_wait_seconds",
                "stream_executor_utilization_ratio",
                "stream_mv_freshness_lag_seconds",
                "stream_mv_freshness_wall_lag_seconds",
                "stream_bottleneck_streak"):
        assert f"# HELP {fam} " in text, fam
        assert f"# TYPE {fam} " in text, fam
    # cleanup the touched series
    STREAMING.executor_utilization.remove(
        state="busy", fragment="helptest", actor="1", executor="X",
        node="0")
    STREAMING.mv_freshness_lag.remove(mv="helptest")
    STREAMING.mv_freshness_wall_lag.remove(mv="helptest")
    STREAMING.bottleneck_streak.remove(domain="helptest", operator="X")


def test_note_backpressure_without_context_is_safe():
    note_backpressure(0.01, channel=None)
    note_backpressure(0.0, channel="zero")
    assert STREAMING.backpressure_wait.get(channel="zero") == 0.0
