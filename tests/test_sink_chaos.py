"""Sink-domain chaos (ISSUE 20 acceptance): the seeded schedule —
writer SIGKILL mid-stage, storage fault during the manifest commit,
and a guarded rescale of the sink fragment — replays against a
2-worker cluster driving an N=2-writer upsert sink (hash-agg fragment,
vnode-rescalable) and a colocated append-only sink; both committed
logs must be BIT-identical to a fault-free in-process single-writer
oracle (zero duplicated, zero lost rows) and the staging areas must
hold zero uncommitted epochs when the dust settles.
"""

import asyncio

from risingwave_tpu.cluster.chaos import run_chaos
from risingwave_tpu.cluster.session import DistFrontend
from risingwave_tpu.connectors.sink import make_sink_target
from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.meta.supervisor import clear_recovery_log
from test_chaos import retry_or_skip_on_slow_host  # noqa: F401

EVENTS = 4000
SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num={n}, "
       "nexmark.max.chunk.size=256, "
       "nexmark.min.event.gap.in.ns=50000000)")
MV_APPEND = ("CREATE MATERIALIZED VIEW mb AS "
             "SELECT auction, price FROM bid WHERE price > 100")
MV_AGG = ("CREATE MATERIALIZED VIEW qa AS "
          "SELECT auction, COUNT(*) AS cnt, MAX(price) AS mx "
          "FROM bid GROUP BY auction")
SINK_KINDS = ["kill_writer_mid_stage", "fault_manifest_commit",
              "rescale_sink_fragment"]


async def _ddl(fe, base: str) -> None:
    await fe.execute(SRC.format(n=EVENTS))
    await fe.execute(MV_APPEND)
    await fe.execute(MV_AGG)
    # s7: GROUP BY plan → retractions → upsert mode; its hash-agg +
    # sink fragment runs at the session parallelism and is the
    # guarded-rescale target.  sa: provably append-only.
    await fe.execute(
        f"CREATE SINK s7 FROM qa "
        f"WITH (connector='epochlog', path='{base}/s7')")
    await fe.execute(
        f"CREATE SINK sa FROM mb AS APPEND-ONLY "
        f"WITH (connector='epochlog', path='{base}/sa')")


def _canon(base: str, name: str, mode: str):
    t = make_sink_target({"path": f"{base}/{name}"}, mode, [])
    return (t.canonical_rows(), t.canonical_bytes(),
            t.uncommitted_epochs())


def _oracle(base: str):
    """Fault-free in-process N=1 arm: the ground truth the chaos arm
    must reproduce byte for byte."""
    async def run():
        fe = Frontend(min_chunks=8)
        await _ddl(fe, base)
        await fe.step(30)
        await fe.close()

    asyncio.run(run())


@retry_or_skip_on_slow_host
def test_sink_chaos_converges_to_single_writer_oracle(tmp_path):
    """The acceptance case: SIGKILL a writer INSIDE stage() (torn
    segment truncated on recovery), fault the manifest PUT (commit
    re-derived from the object-store listing), rescale the sink
    fragment via the guarded protocol (writer ranks re-stamped) — and
    the committed logs still equal the no-fault single-writer oracle
    exactly."""
    clear_recovery_log()
    chaos_base = str(tmp_path / "chaos")

    async def run():
        fe = DistFrontend(str(tmp_path / "store"), n_workers=2,
                          parallelism=2)
        await fe.start()
        try:
            await _ddl(fe, chaos_base)
            report = await run_chaos(fe, seed=11, kinds=SINK_KINDS,
                                     rescale_mv="s7")
            view = await fe.execute("SELECT * FROM rw_sinks")
            return report, view
        finally:
            await fe.close()

    report, view = asyncio.run(run())

    # every scheduled sink fault actually fired, and the SIGKILL
    # mid-stage surfaced as a supervised dead_worker recovery
    assert {k for _s, k, _w in report.events} == set(SINK_KINDS)
    causes = {c for c, _a in report.recoveries}
    assert "dead_worker" in causes, report.recoveries

    oracle_base = str(tmp_path / "oracle")
    _oracle(oracle_base)
    for name, mode in (("s7", "upsert"), ("sa", "append")):
        rows, blob, uncommitted = _canon(chaos_base, name, mode)
        o_rows, o_blob, o_unc = _canon(oracle_base, name, mode)
        assert uncommitted == {}, (name, uncommitted)
        assert o_unc == {}, (name, o_unc)
        assert rows, f"chaos arm committed nothing for {name}"
        # zero dup / zero loss, byte for byte: append canonical_rows
        # keeps multiplicity (a duplicated replay fails equality);
        # upsert folds to key→state and a lost retraction diverges
        assert rows == o_rows, (name, len(rows), len(o_rows))
        assert blob == o_blob, name

    # the serving view agrees: fully drained, nothing staged
    by_name = {r[0]: r for r in view}
    for name, mode in (("s7", "upsert"), ("sa", "append")):
        _n, conn, m, epoch, staged, _nbytes, lag = by_name[name]
        assert (conn, m) == ("epochlog", mode)
        assert epoch > 0 and staged == 0 and lag == 0, view
