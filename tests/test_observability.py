"""Metrics + tracing tests (aux subsystems, SURVEY §5)."""

import asyncio

from risingwave_tpu.utils.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, STREAMING,
)
from risingwave_tpu.utils.trace import AwaitRegistry, Tracer


def test_counter_gauge_histogram_render():
    r = MetricsRegistry()
    c = r.counter("rows_total")
    c.inc(5, actor="1")
    c.inc(2, actor="1")
    c.inc(1, actor="2")
    assert c.get(actor="1") == 7
    g = r.gauge("cap")
    g.set(1024)
    h = r.histogram("lat_seconds", buckets=[0.01, 0.1, 1.0])
    for v in (0.005, 0.05, 0.05, 0.5, 2.0):
        h.observe(v)
    assert h.count() == 5
    assert h.quantile(0.5) == 0.05
    text = r.render()
    assert 'rows_total{actor="1"} 7' in text
    assert "cap 1024" in text
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text


def test_pipeline_populates_streaming_metrics():
    from risingwave_tpu.frontend import Frontend

    async def run():
        before_rows = STREAMING.source_rows.get(source="nexmark-0")
        before_cp = STREAMING.checkpoint_count.get()
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=5000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT window_start, "
            "COUNT(*) AS c FROM TUMBLE(bid, date_time, "
            "INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.step(3)
        await fe.close()
        return (STREAMING.source_rows.get(source="nexmark-0")
                - before_rows,
                STREAMING.checkpoint_count.get() - before_cp,
                STREAMING.barrier_latency.count())

    rows, cps, lat_n = asyncio.run(run())
    assert rows > 0
    assert cps >= 3
    assert lat_n > 0


def test_tracer_spans_and_await_registry():
    t = Tracer()
    with t.span("barrier", epoch=7):
        with t.span("flush"):
            pass
    spans = t.find("flush")
    assert len(spans) == 1 and spans[0].parent == "barrier"
    assert t.find("barrier")[0].attrs == {"epoch": 7}

    a = AwaitRegistry()
    a.enter("actor-1", "barrier_align(left)")
    a.enter("actor-2", "state_table.commit")
    dump = a.dump()
    assert "actor-1: barrier_align(left)" in dump
    a.exit("actor-1")
    assert "actor-1" not in a.dump()
