"""Metrics + tracing + barrier-aligned observability tests."""

import asyncio

from risingwave_tpu.utils.metrics import (
    Counter, Gauge, Histogram, MetricsRegistry, STREAMING,
)
from risingwave_tpu.utils.trace import AwaitRegistry, Tracer


def test_counter_gauge_histogram_render():
    r = MetricsRegistry()
    c = r.counter("rows_total")
    c.inc(5, actor="1")
    c.inc(2, actor="1")
    c.inc(1, actor="2")
    assert c.get(actor="1") == 7
    g = r.gauge("cap")
    g.set(1024)
    h = r.histogram("lat_seconds", buckets=[0.01, 0.1, 1.0])
    for v in (0.005, 0.05, 0.05, 0.5, 2.0):
        h.observe(v)
    assert h.count() == 5
    assert h.quantile(0.5) == 0.05
    text = r.render()
    assert 'rows_total{actor="1"} 7' in text
    assert "cap 1024" in text
    assert 'lat_seconds_bucket{le="0.01"} 1' in text
    assert 'lat_seconds_bucket{le="+Inf"} 5' in text
    assert "lat_seconds_count 5" in text


def test_pipeline_populates_streaming_metrics():
    from risingwave_tpu.frontend import Frontend

    async def run():
        before_rows = STREAMING.source_rows.get(source="nexmark-0")
        before_cp = STREAMING.checkpoint_count.get()
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=5000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT window_start, "
            "COUNT(*) AS c FROM TUMBLE(bid, date_time, "
            "INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.step(3)
        await fe.close()
        return (STREAMING.source_rows.get(source="nexmark-0")
                - before_rows,
                STREAMING.checkpoint_count.get() - before_cp,
                STREAMING.barrier_latency.count())

    rows, cps, lat_n = asyncio.run(run())
    assert rows > 0
    assert cps >= 3
    assert lat_n > 0


def test_help_lines_rendered():
    r = MetricsRegistry()
    r.counter("rows_total", "rows through the system").inc(3)
    r.gauge("cap", "capacity").set(7)
    r.histogram("lat", "latency").observe(0.2)
    r.counter("bare").inc()              # no help → no HELP line
    text = r.render()
    assert "# HELP rows_total rows through the system" in text
    assert "# HELP cap capacity" in text
    assert "# HELP lat latency" in text
    assert "# HELP bare" not in text
    # HELP precedes TYPE for each family
    assert text.index("# HELP cap") < text.index("# TYPE cap gauge")


def test_backpressure_on_throttled_edge():
    """A sender outpacing a slow receiver on a tiny permit budget must
    accumulate blocked-send time in the edge's back-pressure series."""
    from risingwave_tpu.common.chunk import StreamChunk
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.stream.exchange import channel

    edge = "test:throttled"
    sch = Schema([Field("a", DataType.INT64)])
    chunk = StreamChunk.from_pydict(sch, {"a": list(range(8))})

    async def run():
        tx, rx = channel(chunk_permits=8, barrier_permits=2, edge=edge)

        async def produce():
            for _ in range(5):
                await tx.send(chunk)

        async def consume():
            await asyncio.sleep(0.05)   # park the sender on permits
            for _ in range(5):
                await rx.recv()

        await asyncio.gather(produce(), consume())

    before = STREAMING.exchange_backpressure.get(edge=edge)
    asyncio.run(run())
    blocked = STREAMING.exchange_backpressure.get(edge=edge) - before
    assert blocked > 0.03, blocked
    assert STREAMING.exchange_send_count.get(edge=edge) >= 5


def test_epoch_profile_attributes_slow_executor():
    """A deliberately slow executor shows up in the epoch profile: the
    barrier exceeds the slow threshold, the profile carries the actor
    attribution + await dump, and the executor-level busy counters
    blame the right node."""
    from risingwave_tpu.common.types import DataType, Field, Schema
    from risingwave_tpu.meta.barrier import BarrierLoop
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
    from risingwave_tpu.stream.executor import Executor, ExecutorInfo
    from risingwave_tpu.stream.executors.test_utils import MockSource
    from risingwave_tpu.stream.message import StopMutation, is_barrier
    from risingwave_tpu.stream.monitor import install_monitoring

    class SlowPass(Executor):
        def __init__(self, input_):
            super().__init__(ExecutorInfo(
                input_.schema, list(input_.pk_indices), "SlowPass"))
            self.input = input_

        async def execute(self):
            async for msg in self.input.execute():
                if is_barrier(msg):
                    await asyncio.sleep(0.05)
                yield msg

    sch = Schema([Field("a", DataType.INT64)])

    async def run():
        store = MemoryStateStore()
        local = LocalBarrierManager()
        tx, src = MockSource.channel(sch)
        local.register_sender(7, tx)
        consumer = install_monitoring(SlowPass(src),
                                      fragment="slowtest", actor_id=7)
        local.set_expected_actors([7])
        actor = Actor(7, consumer, dispatchers=[],
                      barrier_manager=local, fragment="slowtest")
        loop = BarrierLoop(local, store,
                           slow_barrier_threshold_s=0.02)
        task = actor.spawn()
        await loop.inject_and_collect(force_checkpoint=True)
        await loop.inject_and_collect(force_checkpoint=True)
        prof = loop.profiler.profiles[-1]
        await loop.inject_and_collect(
            mutation=StopMutation(frozenset({7})))
        await task
        assert actor.failure is None
        return prof

    prof = asyncio.run(run())
    assert prof.inject_to_collect_s > 0.03
    assert prof.slowest_actor == 7
    assert prof.await_dump, "slow barrier must attach the await dump"
    assert "epoch" in prof.format()
    busy = STREAMING.executor_busy.get(
        fragment="slowtest", actor="7", executor="SlowPass", node="0")
    assert busy > 0.03, busy
    # teardown removed the live-actor series
    assert not any(labels.get("actor") == "7"
                   and labels.get("fragment") == "slowtest"
                   for labels, _v in STREAMING.actor_count.series())


def test_rw_metric_tables_over_pgwire():
    """The SQL query surface: rw_actor_metrics lists the live actors,
    rw_barrier_latency matches BarrierStats, rw_fragment_backpressure
    carries the labeled edges."""
    from test_pgwire import _Client, _rows

    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.frontend.pgwire import PgServer

    async def run():
        fe = Frontend(min_chunks=2)
        srv = PgServer(fe)
        await srv.serve(port=0)
        c = await _Client.connect(srv.port)
        await c.query(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000)")
        await c.query(
            "CREATE MATERIALIZED VIEW m AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(3)
        actors = _rows(await c.query("SELECT * FROM rw_actor_metrics"))
        barriers = _rows(await c.query(
            "SELECT * FROM rw_barrier_latency"))
        edges = _rows(await c.query(
            "SELECT * FROM rw_fragment_backpressure"))
        stats = list(fe.loop.stats.latencies_s)
        c.close()
        await srv.close()
        await fe.close()
        return actors, barriers, edges, stats

    actors, barriers, edges, stats = asyncio.run(run())
    # live actor rows, with nonzero executor throughput on the MV chain
    m_rows = [r for r in actors if r[1] == "m"]
    assert m_rows, actors
    assert any(int(r[4]) > 0 for r in m_rows), m_rows
    # per-epoch breakdown consistent with BarrierStats: same epochs,
    # and total ≈ the recorded latency (profiling adds only the time
    # between the two monotonic reads)
    assert len(barriers) == len(stats)
    for row, lat in zip(barriers, stats):
        assert abs(float(row[4]) - lat) < 0.05, (row, lat)
        assert float(row[4]) >= float(row[2])     # total ≥ i2c
    # the source's barrier channel is a labeled, metered edge
    assert any(r[0].startswith("barrier:bid") for r in edges), edges


def test_actor_count_series_track_deploy_and_drop():
    from risingwave_tpu.frontend import Frontend

    def live(fragment):
        return [labels for labels, _v in
                STREAMING.actor_count.series()
                if labels.get("fragment") == fragment]

    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW obs_mv AS SELECT auction, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.step(1)
        during = live("obs_mv")
        await fe.execute("DROP MATERIALIZED VIEW obs_mv")
        after_drop = live("obs_mv")
        await fe.close()
        return during, after_drop

    during, after_drop = asyncio.run(run())
    assert len(during) == 1
    assert after_drop == []


def test_tracer_spans_and_await_registry():
    t = Tracer()
    with t.span("barrier", epoch=7):
        with t.span("flush"):
            pass
    spans = t.find("flush")
    assert len(spans) == 1 and spans[0].parent == "barrier"
    assert t.find("barrier")[0].attrs == {"epoch": 7}

    a = AwaitRegistry()
    a.enter("actor-1", "barrier_align(left)")
    a.enter("actor-2", "state_table.commit")
    dump = a.dump()
    assert "actor-1: barrier_align(left)" in dump
    a.exit("actor-1")
    assert "actor-1" not in a.dump()
