"""Failpoint injection + virtual-time determinism (VERDICT r3 #10).

Reference parity: src/storage/src/storage_failpoints/ (fail_point! in
the storage IO path) and src/tests/simulation/ (madsim: deterministic
time + chaos). Faults here are seeded, so every run of a chaos case
executes the identical failure schedule.
"""

import asyncio

import pytest

from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.storage.hummock import HummockLite
from risingwave_tpu.storage.object_store import LocalFsObjectStore
from risingwave_tpu.utils.failpoint import fail_point, failpoints

SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num=2000, "
       "nexmark.max.chunk.size=128)")
MV = "CREATE MATERIALIZED VIEW m AS SELECT auction, price FROM bid"


def test_failpoint_registry_semantics():
    with failpoints({"x": RuntimeError("boom")}) as fired:
        with pytest.raises(RuntimeError):
            fail_point("x")
        fail_point("y")          # unarmed: no-op
        assert fired == {"x": 1}
    fail_point("x")              # disarmed after the with-block

    # probabilistic points are DETERMINISTIC per seed
    def run(seed):
        hits = 0
        with failpoints({"p": (0.5, RuntimeError)}, seed=seed):
            for _ in range(50):
                try:
                    fail_point("p")
                except RuntimeError:
                    hits += 1
        return hits

    assert run(7) == run(7)
    assert 5 < run(7) < 45


def test_exception_specs_and_times(monkeypatch):
    """ISSUE 8 satellite: dict specs now cover crash injection too —
    {"raise": <builtin name>} raises by name (the only exception form
    that round-trips through JSON across the subprocess boundary) and
    {"times": N} makes any dict spec a healing transient."""
    from risingwave_tpu.utils.failpoint import arm_from_env, arm_specs

    # raise spec through the context manager, with healing
    with failpoints({"x": {"raise": "OSError", "msg": "disk gone",
                           "times": 2}}) as fired:
        for _ in range(2):
            with pytest.raises(OSError, match="disk gone"):
                fail_point("x")
        fail_point("x")                    # healed: inert
        assert fired == {"x": 2}

    # the env boot path (worker subprocesses) takes the same specs
    monkeypatch.setenv(
        "RW_TPU_FAILPOINTS",
        '{"e1": {"raise": "ValueError"}, "e2": {"sleep_s": 0}}')
    assert arm_from_env() == 2
    try:
        with pytest.raises(ValueError):
            fail_point("e1")
        fail_point("e2")                   # sleep spec still works
    finally:
        arm_specs({"e1": None, "e2": None})   # disarm form
    fail_point("e1")

    # validation is eager — at arm time, not at the injection site
    with pytest.raises(ValueError, match="builtin"):
        arm_specs({"bad": {"raise": "NotARealException"}})
    with pytest.raises(ValueError, match="sleep or raise"):
        arm_specs({"bad": {"whatever": 1}})


def _oracle_total(store_root):
    async def main():
        f = Frontend(HummockLite(LocalFsObjectStore(store_root)),
                     rate_limit=2)
        await f.recover()
        for _ in range(40):
            await f.step()
        n = (await f.execute("SELECT count(*) FROM m"))[0][0]
        rows = sorted(await f.execute("SELECT auction, price FROM m"))
        await f.close()
        return n, rows
    return asyncio.run(main())


def test_sync_failpoint_crash_recovers_exactly(tmp_path):
    """A checkpoint sync that dies mid-run loses nothing: recovery
    resumes from the last committed epoch and the final MV equals the
    uninterrupted run's result."""
    root = str(tmp_path / "h")

    async def phase1():
        f = Frontend(HummockLite(LocalFsObjectStore(root)), rate_limit=2)
        await f.execute(SRC)
        await f.execute(MV)
        with failpoints({"hummock.sync": (0.3, OSError("sync died"))},
                        seed=11) as fired:
            for _ in range(20):
                try:
                    await f.step()
                except OSError:
                    break          # "process crash"
            assert fired.get("hummock.sync", 0) >= 1

    asyncio.run(phase1())
    n, rows = _oracle_total(root)
    # uninterrupted reference over a fresh store
    ref_root = str(tmp_path / "ref")

    async def ref():
        f = Frontend(HummockLite(LocalFsObjectStore(ref_root)),
                     rate_limit=2)
        await f.execute(SRC)
        await f.execute(MV)
        for _ in range(40):
            await f.step()
        rows = sorted(await f.execute("SELECT auction, price FROM m"))
        await f.close()
        return rows

    assert rows == asyncio.run(ref())
    assert n == len(rows) > 0


def test_upload_failpoint_barrier_fails_loud(tmp_path):
    """An object-store upload failure surfaces as a barrier failure —
    never a silent checkpoint gap."""
    root = str(tmp_path / "h")

    async def main():
        f = Frontend(HummockLite(LocalFsObjectStore(root)), rate_limit=2)
        await f.execute(SRC)
        await f.execute(MV)
        with failpoints({"object_store.upload": OSError("disk gone")}):
            with pytest.raises(OSError):
                for _ in range(10):
                    await f.step()

    asyncio.run(main())


def test_virtual_time_barrier_loop_is_deterministic(tmp_path):
    """BarrierLoop.run under a VirtualClock: the whole tick schedule
    executes at full speed with deterministic virtual timestamps."""
    from risingwave_tpu.meta.barrier import BarrierLoop, VirtualClock
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.models.nexmark import build_q7

    def run_once():
        clock = VirtualClock()
        cfg = NexmarkConfig(event_num=4000, max_chunk_size=256,
                            generate_strings=False)
        with clock.install():     # epochs come from virtual time too
            p = build_q7(MemoryStateStore(), cfg, rate_limit=2)
            loop = BarrierLoop(p.loop.local, p.loop.store,
                               interval_ms=250,
                               monotonic=clock.monotonic,
                               sleep=clock.sleep)

            async def main():
                task = p.actor.spawn()
                await loop.run(stop_after=12)
                from risingwave_tpu.stream.message import StopMutation
                loop.schedule_mutation(
                    StopMutation(frozenset(p.readers.keys())))
                await loop.inject_and_collect()
                await task
                return (clock.t, p.reader.offset,
                        loop.committed_epoch,
                        sorted(p.mv_table.iter_rows()))

            return asyncio.run(main())

    t1, off1, ep1, mv1 = run_once()
    t2, off2, ep2, mv2 = run_once()
    # FULLY deterministic: time, offsets, EPOCH VALUES, mv contents
    assert (t1, off1, ep1) == (t2, off2, ep2)
    assert mv1 == mv2
    # 12 ticks at 250ms, first immediate → ≥ 11 intervals of virtual time
    assert t1 >= 11 * 0.25
    assert off1 > 0
