"""Fragment fusion (ISSUE 6): traced-stage units, fused-vs-unfused
oracles, dispatch accounting, session plumbing, IR round-trips.

Covers the acceptance points: the composed filter/project chain traces
bit-identically to the sequential executors (including update-pair
degradation, noop-pair drops and NULL handling), fused nexmark
q1/q4/q7/q8 + TPC-H q3/q5 runs are bit-identical to unfused through the
SQL front door, a fused hand-built q7/q3/q8 run shows STRICTLY fewer
device dispatches at higher rows-per-dispatch (conftest dispatch-budget
guard), SET stream_fusion rides the DDL log and reschedule replay, the
checker falls back on a broken fusion, and the {"op":"fused"} /
hash_agg["fused_stages"] IR rebuilds on cluster workers.
"""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Column, Op, StreamChunk
from risingwave_tpu.common.types import DataType, Field, Interval, Schema
from risingwave_tpu.expr.expr import (
    BinaryOp, Cast, InputRef, lit, tumble_start,
)
from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.ops.fused import (
    FusedStage, FusedStages, encode_raw_chunk, key_lanes_traced,
    traceable_reason,
)


def run(coro):
    return asyncio.run(coro)


SCHEMA = Schema([Field("k", DataType.INT64),
                 Field("v", DataType.INT64),
                 Field("f", DataType.FLOAT64),
                 Field("s", DataType.VARCHAR)])


# -- eligibility walker ----------------------------------------------------


def test_traceable_reason_units():
    dev = BinaryOp("+", InputRef(0, DataType.INT64),
                   InputRef(1, DataType.INT64))
    assert traceable_reason(dev, SCHEMA) is None
    host_ref = InputRef(3, DataType.VARCHAR)
    assert "host-typed" in traceable_reason(host_ref, SCHEMA)
    host_cmp = BinaryOp("=", InputRef(3, DataType.VARCHAR),
                        lit("x"))
    assert traceable_reason(host_cmp, SCHEMA) is not None
    dec_cast = Cast(InputRef(2, DataType.FLOAT64), DataType.DECIMAL)
    assert "DECIMAL" in traceable_reason(dec_cast, SCHEMA)
    # tumble over a timestamp is the flagship traceable function
    ts = tumble_start(InputRef(0, DataType.INT64),
                      Interval(usecs=10))
    assert traceable_reason(ts, SCHEMA) is None


# -- composed chain vs sequential executors --------------------------------


def _chunk(n=32, seed=0, with_pairs=True):
    rng = np.random.default_rng(seed)
    cap = n
    k = rng.integers(-50, 50, size=cap).astype(np.int64)
    v = rng.integers(-1000, 1000, size=cap).astype(np.int64)
    f = rng.normal(size=cap)
    f[0] = 0.0
    if cap > 4:
        f[4] = -0.0
    s = np.empty(cap, dtype=object)
    s[:] = [f"s{int(x) % 5}" for x in k]
    vis = rng.random(cap) > 0.15
    ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
    if with_pairs:
        for i in range(0, cap - 1, 6):
            ops[i] = int(Op.UPDATE_DELETE)
            ops[i + 1] = int(Op.UPDATE_INSERT)
            vis[i] = vis[i + 1] = True
            k[i + 1] = k[i]              # same key, maybe same value
            if i % 12 == 0:
                v[i + 1] = v[i]          # noop pair after projection
    val = rng.random(cap) > 0.1
    cols = [Column(DataType.INT64, k, None),
            Column(DataType.INT64, v,
                   None if val.all() else val.copy()),
            Column(DataType.FLOAT64, f, None),
            Column(DataType.VARCHAR, s, None)]
    return StreamChunk(SCHEMA, cols, vis, ops)


def _sequential(chunk, pred, exprs, names):
    """Reference semantics: real FilterExecutor + ProjectExecutor math."""
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    c = chunk if pred is None \
        else FilterExecutor.apply_predicate(chunk, pred)
    cols = [e.eval(c) for e in exprs]
    vis = np.asarray(c.visibility)
    ops_np = np.asarray(c.ops)
    if (ops_np == int(Op.UPDATE_DELETE)).any():
        vis = ProjectExecutor._drop_noop_updates(cols, vis.copy(),
                                                 ops_np)
    out_schema = Schema([Field(nm, e.return_type)
                         for nm, e in zip(names, exprs)])
    return StreamChunk(out_schema, cols, vis, c.ops)


def _rows(schema, cols, vis, ops):
    out = []
    vis = np.asarray(vis)
    ops = np.asarray(ops)
    for i in np.flatnonzero(vis):
        row = [int(ops[i])]
        for c in cols:
            val = c.validity
            if val is not None and not np.asarray(val)[i]:
                row.append(None)
            else:
                x = np.asarray(c.values)[i]
                row.append(x.item() if hasattr(x, "item") else x)
        out.append(tuple(row))
    return out


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chain_step_bit_identical_to_sequential(seed):
    """filter→project composed into one trace == sequential executors,
    on visible rows (ops included), under numpy AND under jit."""
    pred = InputRef(0, DataType.INT64) > lit(-10)
    exprs = [InputRef(0, DataType.INT64),
             BinaryOp("+", InputRef(1, DataType.INT64), lit(7)),
             InputRef(2, DataType.FLOAT64)]
    names = ["k", "v7", "f"]
    fs = FusedStages(SCHEMA, [
        FusedStage("filter", "FilterExecutor", exprs=(pred,)),
        FusedStage("project", "ProjectExecutor", exprs=tuple(exprs),
                   names=tuple(names))])
    assert fs.fusable_reason() is None
    chunk = _chunk(seed=seed)
    ref = _sequential(chunk, pred, exprs, names)
    want = _rows(ref.schema, ref.columns, ref.visibility, ref.ops)

    # numpy path of the composed normal form
    out_cols, vis, ops, stage_rows = fs.chain_body(
        list(chunk.columns), np.asarray(chunk.visibility),
        np.asarray(chunk.ops), np)
    got = _rows(fs.out_schema, out_cols, vis, ops)
    assert got == want

    # traced path (the standalone executor's jitted step)
    from risingwave_tpu.ops.fused import build_chain_step
    step = build_chain_step(fs)
    vals = tuple(np.asarray(chunk.columns[i].values)
                 for i in fs.ref_cols)
    oks = tuple(np.ones(chunk.capacity, dtype=bool)
                if chunk.columns[i].validity is None
                else np.asarray(chunk.columns[i].validity)
                for i in fs.ref_cols)
    fv, fo, vis2, ops2, srows = step(vals, oks,
                                     np.asarray(chunk.visibility),
                                     np.asarray(chunk.ops),
                                     np.ones(chunk.capacity,
                                             dtype=bool))
    cols2 = [Column(f.data_type, np.asarray(a), np.asarray(o))
             for f, a, o in zip(fs.out_schema, fv, fo)]
    got2 = _rows(fs.out_schema, cols2, np.asarray(vis2),
                 np.asarray(ops2))
    assert got2 == want
    # per-stage attribution: filter rows ≤ input, project == final
    sr = np.asarray(srows)
    assert sr[1] == int(np.asarray(vis2).sum())


def test_noop_pair_drop_sees_host_passthrough_columns():
    """Regression (review finding): a U-/U+ pair whose ONLY change is
    in a varchar passthrough column must NOT be dropped — the host
    columns bypass the trace, so their adjacent equality rides in via
    host_noop_eq."""
    exprs = [InputRef(0, DataType.INT64),
             InputRef(3, DataType.VARCHAR)]
    fs = FusedStages(SCHEMA, [
        FusedStage("project", "ProjectExecutor", exprs=tuple(exprs),
                   names=("k", "s"))])
    assert fs.fusable_reason() is None and fs.host_out == {1: 3}
    k = np.array([7, 7, 5, 5], dtype=np.int64)
    s = np.empty(4, dtype=object)
    s[:] = ["old", "new", "same", "same"]   # pair 0-1 differs ONLY in s
    cols = [Column(DataType.INT64, k, None),
            Column(DataType.INT64, np.zeros(4, dtype=np.int64), None),
            Column(DataType.FLOAT64, np.zeros(4), None),
            Column(DataType.VARCHAR, s, None)]
    ops = np.array([int(Op.UPDATE_DELETE), int(Op.UPDATE_INSERT),
                    int(Op.UPDATE_DELETE), int(Op.UPDATE_INSERT)],
                   dtype=np.int8)
    chunk = StreamChunk(SCHEMA, cols, np.ones(4, dtype=bool), ops)
    out_cols, vis, _o, _sr = fs.chain_body(
        cols, np.asarray(chunk.visibility), ops, np,
        host_same=fs.host_noop_eq(chunk))
    vis = np.asarray(vis)
    assert vis[0] and vis[1], "varchar-only update pair was dropped"
    assert not vis[2] and not vis[3], "true noop pair survived"
    # and the sequential oracle agrees
    ref = _sequential(chunk, None, exprs, ["k", "s"])
    assert np.array_equal(vis, np.asarray(ref.visibility))


def test_filter_only_run_passes_all_columns_through():
    """Regression (review finding): a filter-only run has no output
    projection, so EVERY column passes through — device columns via
    the trace, host columns around it. Omitting them from ref_cols
    handed the consumer dummy zero columns."""
    pred = InputRef(0, DataType.INT64) > lit(0)
    fs = FusedStages(SCHEMA, [
        FusedStage("filter", "FilterExecutor", exprs=(pred,))])
    assert fs.fusable_reason() is None
    assert fs.ref_cols == [0, 1, 2]          # all device columns
    assert fs.host_out == {3: 3}             # varchar rides around
    chunk = _chunk(seed=5)
    out_cols, vis, ops, _sr = fs.chain_body(
        list(chunk.columns), np.asarray(chunk.visibility),
        np.asarray(chunk.ops), np)
    keep = np.asarray(vis)
    assert keep.any()
    # column 1 (never referenced by the predicate) keeps real values
    assert np.array_equal(np.asarray(out_cols[1].values)[keep],
                          np.asarray(chunk.columns[1].values)[keep])
    assert out_cols[3] is None               # host placeholder


def test_filter_only_fused_agg_front_door_oracle():
    """End-to-end shape of the same regression: the fused agg groups
    on a column the filter never references."""
    mv = ("CREATE MATERIALIZED VIEW q AS SELECT bidder, "
          "COUNT(*) AS c, SUM(price) AS s FROM bid "
          "WHERE price > 100 GROUP BY bidder")
    rows_off = _front_door_rows(NEXMARK_SOURCES, mv, False)
    rows_on = _front_door_rows(NEXMARK_SOURCES, mv, True)
    assert rows_on == rows_off and len(rows_on) > 1


def test_key_lanes_traced_match_keycodec():
    """Traced key-lane builder == KeyCodec.build_arrays, including
    float bitcast keys with -0.0 normalization and NULLs."""
    import jax
    from risingwave_tpu.stream.executors.keys import KeyCodec
    rng = np.random.default_rng(7)
    k = rng.integers(-9, 9, size=64).astype(np.int64)
    f = np.where(rng.random(64) < 0.2, 0.0, rng.normal(size=64))
    f[3] = -0.0
    ok = rng.random(64) > 0.3
    import jax.numpy as jnp
    codec = KeyCodec([DataType.INT64, DataType.FLOAT64])
    want = codec.build_arrays([(k, None), (f, ok)])
    got = jax.jit(lambda a, b, m: key_lanes_traced(
        [(a, None), (b, m)], jnp))(k, f, ok)
    assert np.array_equal(np.asarray(got), want)


def test_lane_codecs_trace_bit_identical():
    """ops/lanes.py order/sum codecs under jit == numpy (the fused
    prelude calls the SAME implementations)."""
    import jax
    from risingwave_tpu.ops import lanes
    v = np.array([0, 1, -1, 2**40, -(2**40), 2**62, -(2**62)],
                 dtype=np.int64)
    f = np.array([0.0, -0.0, 1.5, -3.25, 1e300, -1e-300, 7.0])
    for arr, fn in ((v, lanes.sum_limbs), (v, lanes.order_lanes),
                    (f, lanes.order_lanes)):
        want = fn(arr)
        got = jax.jit(fn)(arr)
        for a, b in zip(got, want):
            assert np.array_equal(np.asarray(a), b), fn.__name__


# -- fused agg oracle + dispatch budget (hand-built q7) --------------------


def _q7_rows(fusion: bool, steps=6):
    from risingwave_tpu.models.nexmark import build_q7
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.state.store import MemoryStateStore

    async def main():
        cfg = NexmarkConfig(event_num=40_000, max_chunk_size=256,
                            generate_strings=False)
        p = build_q7(MemoryStateStore(), cfg, rate_limit=24,
                     min_chunks=24, fusion=fusion)
        task = p.actor.spawn()
        for _ in range(steps):
            await p.loop.inject_and_collect(force_checkpoint=True)
        from risingwave_tpu.stream.message import StopMutation
        await p.loop.inject_and_collect(
            mutation=StopMutation(frozenset({1})))
        await task
        if p.actor.failure is not None:
            raise p.actor.failure
        return sorted(
            tuple(row) for _pk, row in _iter_mv(p.mv_table))

    return run(main())


def _iter_mv(table):
    from risingwave_tpu.common.epoch import Epoch, EpochPair
    ce = table.store.committed_epoch() if hasattr(
        table.store, "committed_epoch") else None
    t = type(table)(table.table_id, table.schema,
                    list(table.pk_indices), table.store,
                    sanity_check=False)
    ce = table.store.committed_epoch()
    t.init_epoch(EpochPair(Epoch(ce + 1), Epoch(ce)))
    return t.iter_rows()


def test_q7_fused_oracle_and_dispatch_budget(dispatch_budget):
    """THE acceptance test shape: bit-identical MV rows, strictly
    fewer device dispatches, rows-per-dispatch at least the unfused
    baseline's (conftest dispatch-budget guard)."""
    rows_off, d_off, rpd_off = dispatch_budget.measure(
        lambda: _q7_rows(False))
    rows_on, d_on, rpd_on = dispatch_budget.measure(
        lambda: _q7_rows(True))
    assert rows_on == rows_off and rows_on
    dispatch_budget.check(d_off, rpd_off, d_on, rpd_on)


def test_q3_fused_oracle(dispatch_budget):
    """TPC-H q3 (3-way join → DECIMAL-revenue project → agg → topn):
    the revenue projection fuses into the agg kernel."""
    from risingwave_tpu.models.nexmark import drive_to_completion
    from risingwave_tpu.models.tpch import build_q3
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.connectors.tpch import LINES_PER_ORDER

    def go(fusion):
        p = build_q3(MemoryStateStore(), customers=120, orders=1200,
                     rate_limit=4, min_chunks=8, fusion=fusion)
        targets = {1: 120, 2: 1200, 3: 1200 * LINES_PER_ORDER}
        run(drive_to_completion(p, targets, in_flight=1))
        return sorted(tuple(r) for _pk, r in _iter_mv(p.mv_table))

    rows_off, d_off, rpd_off = dispatch_budget.measure(
        lambda: go(False))
    rows_on, d_on, rpd_on = dispatch_budget.measure(lambda: go(True))
    assert rows_on == rows_off and rows_on
    dispatch_budget.check(d_off, rpd_off, d_on, rpd_on)


def test_q8_fused_oracle(dispatch_budget):
    """q8's auction-side dedup agg absorbs its tumble projection."""
    from risingwave_tpu.models.nexmark import (
        build_q8, drive_to_completion,
    )
    from risingwave_tpu.connectors.nexmark import NexmarkConfig
    from risingwave_tpu.state.store import MemoryStateStore

    def go(fusion):
        base = NexmarkConfig(event_num=40_000, max_chunk_size=256,
                             generate_strings=False)
        cfg_p = NexmarkConfig(**{**base.__dict__,
                                 "table_type": "person"})
        cfg_a = NexmarkConfig(**{**base.__dict__,
                                 "table_type": "auction"})
        p = build_q8(MemoryStateStore(), cfg_p, cfg_a, rate_limit=16,
                     min_chunks=16, fusion=fusion)
        targets = {1: 40_000 // 50, 2: 40_000 * 3 // 50}
        run(drive_to_completion(p, targets, in_flight=1))
        return sorted(tuple(r) for _pk, r in _iter_mv(p.mv_table))

    rows_off, d_off, rpd_off = dispatch_budget.measure(
        lambda: go(False))
    rows_on, d_on, rpd_on = dispatch_budget.measure(lambda: go(True))
    assert rows_on == rows_off and rows_on
    dispatch_budget.check(d_off, rpd_off, d_on, rpd_on)


# -- rewrite-rule units ----------------------------------------------------


def _mini_agg_chain(distinct=False):
    from risingwave_tpu.ops.hash_agg import AggKind
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.hash_agg import (
        AggCall, HashAggExecutor, agg_aux_tables, agg_state_schema,
    )
    from risingwave_tpu.stream.executors.materialize import (
        MaterializeExecutor,
    )
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    store = MemoryStateStore()
    src = MockSource(Schema.of(k=DataType.INT64, v=DataType.INT64), [])
    filt = FilterExecutor(src, InputRef(1, DataType.INT64) > lit(0))
    proj = ProjectExecutor(
        filt, [InputRef(0, DataType.INT64),
               BinaryOp("*", InputRef(1, DataType.INT64), lit(2))],
        ["k", "v2"])
    calls = [AggCall(AggKind.SUM, 1, distinct=distinct)]
    sch, pk = agg_state_schema(proj.schema, [0], calls)
    distinct_tables, minput = agg_aux_tables(
        proj.schema, [0], calls, False, store,
        dedup_table_id=lambda c: 90 + c,
        minput_table_id=lambda j: 95 + j)
    agg = HashAggExecutor(proj, [0], calls,
                          StateTable(2, sch, pk, store),
                          distinct_tables=distinct_tables,
                          minput_tables=minput)
    mv = StateTable(3, agg.schema, [0], store)
    return MaterializeExecutor(agg, mv)


def test_fusion_rule_absorbs_run_into_agg():
    from risingwave_tpu.frontend.opt import rewrite_stream_plan
    root = _mini_agg_chain()
    new_root, report = rewrite_stream_plan(root, "none", record=False,
                                           fusion=True)
    assert report.fired.get("fusion_grouping") == 1
    agg = new_root.input
    assert agg.fused_stages is not None
    assert agg.fused_stages.describe() == \
        "FilterExecutor→ProjectExecutor"
    from risingwave_tpu.stream.executors import MockSource
    assert isinstance(agg.input, MockSource)
    # without the fusion flag the rule never runs
    _root2, report2 = rewrite_stream_plan(_mini_agg_chain(), "all",
                                          record=False)
    assert "fusion_grouping" not in report2.fired


def test_fusion_rule_refuses_distinct_agg():
    """A DISTINCT agg cannot absorb the run (host dedup multisets read
    post-stage chunks) — the run still fuses as a STANDALONE block
    feeding the interpretive agg."""
    from risingwave_tpu.frontend.opt import rewrite_stream_plan
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )
    root = _mini_agg_chain(distinct=True)
    new, report = rewrite_stream_plan(root, "none", record=False,
                                      fusion=True)
    agg = new.input
    assert agg.fused_stages is None, \
        "DISTINCT agg must not absorb a prelude"
    assert isinstance(agg.input, FusedFragmentExecutor)
    assert report.fired.get("fusion_grouping") == 1


def test_checker_catches_broken_fused_block():
    """A fused run planned against the wrong input schema must trip
    the plan-property checker (fallback off-strict, raise in strict)."""
    from risingwave_tpu.frontend.opt import (
        rewrite_stream_plan, set_strict_checker,
    )
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )

    def broken_rule(root):
        import copy
        agg = root.input
        wrong = Schema.of(a=DataType.INT64)   # NOT the real base schema
        fs = FusedStages(wrong, [FusedStage(
            "filter", "FilterExecutor",
            exprs=(InputRef(0, DataType.INT64) > lit(0),))])
        bad = FusedFragmentExecutor.__new__(FusedFragmentExecutor)
        # hand-assemble to bypass the constructor's own assertion —
        # the checker must not depend on constructor diligence
        from risingwave_tpu.stream.executor import ExecutorInfo
        base = agg.input.input            # below the project
        bad.input = base
        bad.fused_stages = fs
        bad._info = ExecutorInfo(fs.out_schema, [], "FusedFragment")
        bad._step = None
        bad._ref = list(fs.ref_cols)
        new_agg = copy.copy(agg)
        new_agg.input = bad
        new_root = copy.copy(root)
        new_root.input = new_agg
        return new_root, 1, "broken"

    root = _mini_agg_chain()
    set_strict_checker(False)
    try:
        _new, report = rewrite_stream_plan(
            root, "none", record=False,
            extra_rules={"broken_fusion": broken_rule})
        assert any(r == "broken_fusion" for r, _ in report.fallbacks)
    finally:
        set_strict_checker(True)
    with pytest.raises(AssertionError):
        rewrite_stream_plan(root, "none", record=False,
                            extra_rules={"broken_fusion": broken_rule})


# -- SQL front door: oracle + plumbing -------------------------------------


NEXMARK_SOURCES = [
    ("CREATE SOURCE {t} WITH (connector='nexmark', "
     "nexmark.table.type='{t}', nexmark.event.num=2000, "
     "nexmark.max.chunk.size=128, "
     "nexmark.generate.strings='false')").format(t=t)
    for t in ("bid", "auction", "person")
]

TPCH_SOURCES = [
    ("CREATE SOURCE {t} WITH (connector='tpch', tpch.table='{t}', "
     "tpch.customers=150, tpch.orders=1500)").format(t=t)
    for t in ("customer", "orders", "lineitem", "supplier", "nation",
              "region")
]

QUERIES = {
    "nexmark_q1": (NEXMARK_SOURCES,
                   "CREATE MATERIALIZED VIEW q AS SELECT auction, "
                   "bidder, price * 89 AS price_dol, date_time "
                   "FROM bid"),
    "nexmark_q4": (NEXMARK_SOURCES,
                   "CREATE MATERIALIZED VIEW q AS "
                   "SELECT category, AVG(final) AS avg_final FROM ("
                   "  SELECT a.category AS category, "
                   "         MAX(b.price) AS final"
                   "  FROM auction AS a JOIN bid AS b "
                   "  ON a.id = b.auction"
                   "  WHERE b.date_time BETWEEN a.date_time "
                   "  AND a.expires"
                   "  GROUP BY a.id, a.category) AS q4i "
                   "GROUP BY category"),
    "nexmark_q7": (NEXMARK_SOURCES,
                   "CREATE MATERIALIZED VIEW q AS "
                   "SELECT window_start, MAX(price) AS max_price, "
                   "COUNT(*) AS cnt "
                   "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
                   "GROUP BY window_start"),
    "nexmark_q8": (NEXMARK_SOURCES,
                   "CREATE MATERIALIZED VIEW q AS "
                   "SELECT p.id, p.name, p.window_start "
                   "FROM TUMBLE(person, date_time, INTERVAL '10' "
                   "SECOND) AS p "
                   "JOIN TUMBLE(auction, date_time, INTERVAL '10' "
                   "SECOND) AS a "
                   "ON p.id = a.seller "
                   "AND p.window_start = a.window_start"),
    "tpch_q3": (TPCH_SOURCES,
                "CREATE MATERIALIZED VIEW q AS SELECT "
                "o.o_orderkey, o.o_orderdate, o.o_shippriority, "
                "sum(l.l_extendedprice * (1 - l.l_discount)) "
                "AS revenue "
                "FROM customer AS c "
                "JOIN orders AS o ON c.c_custkey = o.o_custkey "
                "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
                "WHERE c.c_mktsegment = 'BUILDING' "
                "AND o.o_orderdate < 9204 AND l.l_shipdate > 9204 "
                "GROUP BY o.o_orderkey, o.o_orderdate, "
                "o.o_shippriority "
                "ORDER BY revenue DESC, o_orderdate ASC LIMIT 10"),
    "tpch_q5": (TPCH_SOURCES,
                "CREATE MATERIALIZED VIEW q AS SELECT n.n_name, "
                "sum(l.l_extendedprice * (1 - l.l_discount)) "
                "AS revenue "
                "FROM customer AS c "
                "JOIN orders AS o ON c.c_custkey = o.o_custkey "
                "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
                "JOIN supplier AS s ON l.l_suppkey = s.s_suppkey "
                "AND c.c_nationkey = s.s_nationkey "
                "JOIN nation AS n ON s.s_nationkey = n.n_nationkey "
                "JOIN region AS r ON n.n_regionkey = r.r_regionkey "
                "WHERE r.r_name = 'ASIA' AND o.o_orderdate < 9500 "
                "GROUP BY n.n_name"),
}


def _front_door_rows(sources, mv_sql, fusion, steps=16):
    async def main():
        fe = Frontend(rate_limit=16, min_chunks=16)
        await fe.execute(
            f"SET stream_fusion = '{'on' if fusion else 'off'}'")
        for s in sources:
            await fe.execute(s)
        await fe.execute(mv_sql)
        await fe.step(steps)
        rows = await fe.execute("SELECT * FROM q")
        await fe.close()
        return sorted(tuple(r) for r in rows)
    return run(main())


@pytest.mark.parametrize("name", list(QUERIES))
def test_front_door_oracle_fusion_on_vs_off(name):
    sources, mv = QUERIES[name]
    rows_off = _front_door_rows(sources, mv, False)
    rows_on = _front_door_rows(sources, mv, True)
    assert rows_on == rows_off, name
    assert rows_on, f"{name} produced no output at this scale"


def test_set_stream_fusion_validates():
    from risingwave_tpu.frontend.planner import PlanError

    async def main():
        fe = Frontend()
        await fe.execute("SET stream_fusion = 'off'")
        assert (await fe.execute(
            "SHOW stream_fusion")) == [("off",)]
        with pytest.raises(PlanError):
            await fe.execute("SET stream_fusion = 'sideways'")
        await fe.close()
    run(main())


def test_explain_shows_fusion_group_annotation():
    async def main():
        fe = Frontend(rate_limit=4)
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        rows = await fe.execute(
            "EXPLAIN SELECT window_start, MAX(price) AS m, "
            "COUNT(*) AS c "
            "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
            "GROUP BY window_start")
        text = "\n".join(r[0] for r in rows)
        assert "fusion_grouping" in text
        assert "[fused:" in text
        await fe.close()
    run(main())


def test_ddl_log_replays_create_time_fusion_setting(tmp_path):
    """SET stream_fusion rides the DDL log: a recovery replays the
    CREATE under the recorded setting, not the current default."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore
    from risingwave_tpu.stream.executors.hash_agg import (
        HashAggExecutor,
    )
    from risingwave_tpu.stream.executor import executor_children

    def find_fused_agg(ex):
        ex = getattr(ex, "inner", ex)       # unwrap monitoring
        if isinstance(ex, HashAggExecutor) and \
                ex.fused_stages is not None:
            return True
        return any(find_fused_agg(c)
                   for _a, _i, c in executor_children(ex))

    async def main():
        store = HummockLite(LocalFsObjectStore(str(tmp_path)))
        fe = Frontend(store=store, rate_limit=4, min_chunks=4)
        await fe.execute("SET stream_fusion = 'off'")
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        await fe.execute(QUERIES["nexmark_q7"][1])
        await fe.step(4)
        rows1 = sorted(await fe.execute("SELECT * FROM q"))
        assert not any(find_fused_agg(a.consumer)
                       for a in fe.actors.values())
        await fe.close()

        store2 = HummockLite(LocalFsObjectStore(str(tmp_path)))
        fe2 = Frontend(store=store2, rate_limit=4, min_chunks=4)
        await fe2.recover()
        # the replayed CREATE ran under the RECORDED 'off', even
        # though a fresh session defaults to 'on'
        assert fe2.session_vars.get("stream_fusion") == "off"
        assert not any(find_fused_agg(a.consumer)
                       for a in fe2.actors.values())
        rows2 = sorted(await fe2.execute("SELECT * FROM q"))
        assert rows2 == rows1
        await fe2.step(3)
        await fe2.close()
    run(main())


def test_reschedule_replays_fusion(tmp_path):
    """ALTER SET PARALLELISM back to 1 re-fuses exactly as the CREATE
    did (the _mv_fusion replay map)."""
    from risingwave_tpu.stream.executors.hash_agg import (
        HashAggExecutor,
    )
    from risingwave_tpu.stream.executor import executor_children

    def fused_aggs(fe):
        out = []

        def walk(ex):
            ex = getattr(ex, "inner", ex)   # unwrap monitoring
            if isinstance(ex, HashAggExecutor):
                out.append(ex.fused_stages is not None)
            for _a, _i, c in executor_children(ex):
                walk(c)
        for a in fe.actors.values():
            walk(a.consumer)
        return out

    async def main():
        fe = Frontend(rate_limit=8, min_chunks=8)
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        await fe.execute(QUERIES["nexmark_q7"][1])
        await fe.step(4)
        assert any(fused_aggs(fe))
        rows1 = sorted(await fe.execute("SELECT * FROM q"))
        # flip the session default OFF: the replay must still fuse
        await fe.execute("SET stream_fusion = 'off'")
        await fe.execute(
            "ALTER MATERIALIZED VIEW q SET PARALLELISM = 1")
        await fe.step(4)
        assert any(fused_aggs(fe)), \
            "reschedule lost the CREATE-time fusion setting"
        rows2 = sorted(await fe.execute("SELECT * FROM q"))
        assert [r for r in rows1 if r in rows2]  # state survived
        await fe.close()
    run(main())


# -- IR / cluster ----------------------------------------------------------


def test_fragmenter_lowers_and_rebuilds_fused_agg():
    """plan → fuse → fragment → {hash_agg + fused_stages} IR →
    build_fragment reconstructs a fused executor (coordinator/worker
    parity)."""
    from risingwave_tpu.frontend.catalog import Catalog
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.frontend.parser import parse_many
    from risingwave_tpu.frontend.planner import (
        StreamPlanner, source_schema,
    )
    from risingwave_tpu.frontend.opt import rewrite_stream_plan
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.plan_ir import build_fragment
    from risingwave_tpu.stream.executor import executor_children
    from risingwave_tpu.stream.executors.hash_agg import (
        HashAggExecutor,
    )

    opts = {"connector": "nexmark", "nexmark.table.type": "bid",
            "nexmark.event.num": "1000",
            "nexmark.generate.strings": "false"}
    catalog = Catalog()
    catalog.add_source("bid", source_schema(opts, None), opts)
    [(_t, stmt)] = parse_many(
        "CREATE MATERIALIZED VIEW v AS SELECT auction, "
        "COUNT(*) AS c, SUM(price) AS s FROM bid "
        "WHERE price > 100 GROUP BY auction")
    planner = StreamPlanner(catalog, MemoryStateStore(),
                            LocalBarrierManager(), definition="")
    plan = planner.plan("v", stmt.select, 7, rate_limit=4)
    consumer, report = rewrite_stream_plan(plan.consumer, "all",
                                           record=False, fusion=True)
    assert report.fired.get("fusion_grouping")
    graph = Fragmenter(1).lower(consumer)
    nodes = [n for f in graph.fragments for n in f.nodes]
    agg_node = next(n for n in nodes if n["op"] == "hash_agg")
    assert agg_node.get("fused_stages"), \
        "fused run missing from the shipped IR"
    _src, rebuilt = build_fragment(
        graph.fragments[-1].nodes, MemoryStateStore(),
        LocalBarrierManager(), channel_for_test)

    def find_agg(ex):
        if isinstance(ex, HashAggExecutor):
            return ex
        for _a, _i, c in executor_children(ex):
            got = find_agg(c)
            if got is not None:
                return got
        return None

    agg = find_agg(rebuilt)
    assert agg is not None and agg.fused_stages is not None
    assert agg.fused_stages.describe() == \
        consumer.input.fused_stages.describe() \
        if hasattr(consumer.input, "fused_stages") else True


def test_cluster_session_fused_matches_inprocess(tmp_path):
    """DistFrontend at parallelism 1 ships fused IR to a worker; rows
    must equal the in-process unfused oracle."""
    from risingwave_tpu.cluster.session import DistFrontend

    sources = (
        "CREATE SOURCE bid WITH (connector='nexmark', "
        "nexmark.table.type='bid', nexmark.event.num=2000, "
        "nexmark.max.chunk.size=128, "
        "nexmark.generate.strings='false')",)
    mv = ("CREATE MATERIALIZED VIEW q AS "
          "SELECT window_start, MAX(price) AS max_price, "
          "COUNT(*) AS cnt "
          "FROM TUMBLE(bid, date_time, INTERVAL '10' SECOND) "
          "GROUP BY window_start")

    want = set(_front_door_rows(list(sources), mv, False, steps=20))

    async def main():
        fe = DistFrontend(str(tmp_path), n_workers=1, parallelism=1)
        await fe.start()
        try:
            assert (await fe.execute(
                "SHOW stream_fusion")) == [("on",)]
            for s in sources:
                await fe.execute(s)
            await fe.execute(mv)
            await fe.step(20)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q")}
        finally:
            await fe.close()

    got = asyncio.run(main())
    assert got == want and got


def test_cluster_session_approx_count_distinct(tmp_path):
    """Regression (ADVICE r5 medium): distributed
    approx_count_distinct MVs ship their HLL sketch-table ids in
    minput_table_ids — the worker-side rebuild must succeed and serve
    the same estimates as the in-process session."""
    from risingwave_tpu.cluster.session import DistFrontend

    sources = (
        "CREATE SOURCE bid WITH (connector='nexmark', "
        "nexmark.table.type='bid', nexmark.event.num=2000, "
        "nexmark.max.chunk.size=128, "
        "nexmark.generate.strings='false')",)
    mv = ("CREATE MATERIALIZED VIEW q AS SELECT auction, "
          "approx_count_distinct(bidder) AS d FROM bid "
          "GROUP BY auction")

    want = set(_front_door_rows(list(sources), mv, False, steps=16))

    async def main():
        fe = DistFrontend(str(tmp_path), n_workers=1, parallelism=1)
        await fe.start()
        try:
            for s in sources:
                await fe.execute(s)
            await fe.execute(mv)
            await fe.step(16)
            return {tuple(r)
                    for r in await fe.execute("SELECT * FROM q")}
        finally:
            await fe.close()

    got = asyncio.run(main())
    assert got == want and got


# -- monitor attribution ---------------------------------------------------


def test_fused_block_stage_metrics_attribution():
    """rw_actor_metrics keeps a row per LOGICAL executor inside a
    fused block: the absorbed filter/project stages stay observable."""
    from risingwave_tpu.utils.metrics import STREAMING

    async def main():
        fe = Frontend(rate_limit=8, min_chunks=8)
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        await fe.execute(
            "CREATE MATERIALIZED VIEW q AS SELECT auction, "
            "COUNT(*) AS c FROM bid WHERE price > 100 "
            "GROUP BY auction")
        await fe.step(6)
        await fe.close()

    run(main())
    stage_series = [(labels, v) for labels, v in
                    STREAMING.executor_rows.series()
                    if "::FilterExecutor" in labels.get("executor", "")]
    assert stage_series, \
        "no per-stage rows attributed inside the fused block"
    assert sum(v for _l, v in stage_series) > 0


# -- watermark_filter absorption (ISSUE 9 satellite) -----------------------


def test_watermark_filter_absorbed_oracle():
    """A wm→filter→project run fuses into ONE block whose traced late
    mask, runtime watermark advancement, persistence and post-chunk
    watermark emission are bit-identical to the sequential executors —
    including actually-late rows and the no-watermark-yet chunk."""
    from risingwave_tpu.frontend.opt.fusion import fuse_fragments
    from risingwave_tpu.state.state_table import StateTable
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.executors import MockSource
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )
    from risingwave_tpu.stream.executors.test_utils import (
        collect_until_n_barriers,
    )
    from risingwave_tpu.stream.executors.fused import (
        FusedFragmentExecutor,
    )
    from risingwave_tpu.stream.executors.watermark_filter import (
        WATERMARK_STATE_SCHEMA, WatermarkFilterExecutor,
    )
    from risingwave_tpu.stream.message import (
        Barrier, BarrierKind, Watermark, is_chunk,
    )
    from risingwave_tpu.common.epoch import Epoch, EpochPair

    S = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64,
                  s=DataType.VARCHAR)

    def barrier(n):
        prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
        return Barrier(EpochPair(Epoch.from_physical(n), prev),
                       BarrierKind.CHECKPOINT)

    def script():
        # chunk 1 has no watermark yet (nothing late by contract);
        # chunk 2's 5_000 and chunk 3's 2_000 are late once the
        # watermark passes them; NULL ts rows are never late
        data = [([10_000, 20_000, 15_000], [1, 2, 3]),
                ([5_000, 25_000, None], [4, 5, 6]),
                ([30_000, 2_000, 26_000], [7, 8, 9])]
        out = [barrier(1)]
        for b, (ts, v) in enumerate(data, start=2):
            out.append(StreamChunk.from_pydict(S, {
                "ts": ts, "v": v,
                "s": [f"x{x}" for x in v]}))
            out.append(barrier(b))
        return out, 4

    def arm(fused):
        msgs_script, nb = script()
        store = MemoryStateStore()
        wm_state = StateTable(191, WATERMARK_STATE_SCHEMA, [0], store)
        src = MockSource(S, msgs_script)
        wm = WatermarkFilterExecutor(src, 0, Interval(usecs=4_000),
                                     wm_state)
        filt = FilterExecutor(
            wm, InputRef(1, DataType.INT64) > lit(0))
        proj = ProjectExecutor(
            filt,
            exprs=[InputRef(0, DataType.TIMESTAMP),
                   InputRef(1, DataType.INT64) * lit(3),
                   InputRef(2, DataType.VARCHAR)],
            names=["ts", "v3", "s"],
            watermark_derivations={0: 0})
        top = proj
        if fused:
            top, fired, _details = fuse_fragments(proj)
            assert fired == 1
            assert isinstance(top, FusedFragmentExecutor)
            kinds = [st.kind for st in top.fused_stages.stages]
            assert kinds == ["watermark_filter", "filter", "project"]
        msgs = asyncio.run(collect_until_n_barriers(top, nb))
        out = []
        for m in msgs:
            if is_chunk(m):
                out.extend(("row", r) for r in m.to_records())
            elif isinstance(m, Watermark):
                out.append(("wm", m.col_idx, m.value))
        # the persisted watermark must round-trip identically too
        row = wm_state.get_row((0,))
        return out, None if row is None else tuple(row)

    on, wm_on = arm(True)
    off, wm_off = arm(False)
    assert on == off, "absorbed watermark_filter diverged"
    assert wm_on == wm_off and wm_on is not None
    assert any(t[0] == "wm" for t in on), "no watermarks observed"


def test_fragmenter_lowers_and_rebuilds_fused_join():
    """plan → fuse (join absorbs its input runs) → fragment →
    hash_join IR with left_fused/right_fused → build_fragment
    reconstructs the fused join (coordinator/worker parity), with
    row_id_gen stage runtimes rebuilt as bare counters."""
    from risingwave_tpu.frontend.catalog import Catalog
    from risingwave_tpu.frontend.fragmenter import Fragmenter
    from risingwave_tpu.frontend.parser import parse_many
    from risingwave_tpu.frontend.planner import (
        StreamPlanner, source_schema,
    )
    from risingwave_tpu.frontend.opt import rewrite_stream_plan
    from risingwave_tpu.state.store import MemoryStateStore
    from risingwave_tpu.stream.actor import LocalBarrierManager
    from risingwave_tpu.stream.exchange import channel_for_test
    from risingwave_tpu.stream.plan_ir import build_fragment
    from risingwave_tpu.stream.executor import executor_children
    from risingwave_tpu.stream.executors.hash_join import (
        HashJoinExecutor,
    )

    opts_p = {"connector": "nexmark", "nexmark.table.type": "person",
              "nexmark.event.num": "500",
              "nexmark.generate.strings": "false"}
    opts_a = {"connector": "nexmark", "nexmark.table.type": "auction",
              "nexmark.event.num": "500",
              "nexmark.generate.strings": "false"}
    catalog = Catalog()
    catalog.add_source("person", source_schema(opts_p, None), opts_p)
    catalog.add_source("auction", source_schema(opts_a, None), opts_a)
    [(_t, stmt)] = parse_many(
        "CREATE MATERIALIZED VIEW v AS SELECT p.id, a.seller "
        "FROM person AS p JOIN auction AS a ON p.id = a.seller "
        "WHERE a.seller > 0")
    planner = StreamPlanner(catalog, MemoryStateStore(),
                            LocalBarrierManager(), definition="")
    plan = planner.plan("v", stmt.select, 7, rate_limit=4)
    consumer, report = rewrite_stream_plan(plan.consumer, "all",
                                           record=False, fusion=True)
    assert report.fired.get("fusion_grouping")

    def find_join(ex):
        if isinstance(ex, HashJoinExecutor):
            return ex
        for _a, _i, c in executor_children(ex):
            got = find_join(c)
            if got is not None:
                return got
        return None

    j0 = find_join(consumer)
    fused_sides = [i for i, s in enumerate(j0.sides)
                   if s.fused_input is not None]
    assert fused_sides, "join fusion did not fire on the planned query"

    graph = Fragmenter(1).lower(consumer)
    nodes = [n for f in graph.fragments for n in f.nodes]
    join_node = next(n for n in nodes if n["op"] == "hash_join")
    assert any(join_node.get(k) for k in ("left_fused", "right_fused")), \
        "fused input runs missing from the shipped hash_join IR"
    join_fi = next(i for i, f in enumerate(graph.fragments)
                   if any(n["op"] == "hash_join" for n in f.nodes))
    frag = graph.fragments[join_fi]
    # splice the upstream source fragments over the exchange_in
    # placeholders (the scheduler's expansion, single-actor case)
    nodes: list = []
    up_tail = {}
    for inp in frag.inputs:
        up_nodes = graph.fragments[inp.up_frag].nodes
        base = len(nodes)
        from risingwave_tpu.stream.plan_ir import remap_node_refs
        for n in up_nodes:
            nodes.append(remap_node_refs(
                n, {i: base + i for i in range(len(up_nodes))}))
        up_tail[inp.node_idx] = len(nodes) - 1
    base = len(nodes)
    remap = {}
    for i, n in enumerate(frag.nodes):
        if n["op"] == "exchange_in":
            remap[i] = up_tail[i]
        else:
            remap[i] = base + len(
                [j for j in range(i) if frag.nodes[j]["op"]
                 != "exchange_in"])
    for i, n in enumerate(frag.nodes):
        if n["op"] == "exchange_in":
            continue
        from risingwave_tpu.stream.plan_ir import remap_node_refs
        nodes.append(remap_node_refs(n, remap))
    _src, rebuilt = build_fragment(
        nodes, MemoryStateStore(),
        LocalBarrierManager(), channel_for_test, actor_id=1)
    j1 = find_join(rebuilt)
    assert j1 is not None
    for i in fused_sides:
        fs0, fs1 = j0.sides[i].fused_input, j1.sides[i].fused_input
        assert fs1 is not None
        assert fs1.describe() == fs0.describe()
        assert [f.data_type for f in fs1.out_schema] == \
            [f.data_type for f in fs0.out_schema]
        for st in fs1.stages:
            if st.kind == "row_id_gen":
                assert st.runtime is not None and \
                    hasattr(st.runtime, "_rebase")


def test_watermark_sentinel_narrow_int_time_col():
    """Regression: the no-watermark-yet sentinel must be the time
    column's OWN dtype-min — np.full would silently wrap int64-min to
    0 on an INT32 column and late every negative timestamp."""
    from risingwave_tpu.stream.executors.watermark_filter import (
        WatermarkRuntime,
    )

    S32 = Schema.of(t=DataType.INT32, v=DataType.INT64)
    rt = WatermarkRuntime()
    st = FusedStage("watermark_filter", "WatermarkFilterExecutor",
                    time_col=0, delay_usecs=0, runtime=rt)
    fs = FusedStages(S32, [st, FusedStage(
        "filter", "FilterExecutor",
        exprs=(InputRef(1, DataType.INT64) >= lit(0),))])
    assert fs.fusable_reason() is None
    chunk = StreamChunk.from_pydict(
        S32, {"t": [-5, -1, 3], "v": [1, 2, 3]})
    aug = fs.augment(chunk)
    thr = np.asarray(aug.columns[2].values)
    assert thr.dtype == np.int32
    assert (thr == np.iinfo(np.int32).min).all(), thr
    # and the traced mask keeps every row (no watermark yet)
    out_cols, vis2, _ops, _sr = fs.chain_body(
        list(aug.columns), np.asarray(aug.visibility),
        np.asarray(aug.ops), np)
    assert (vis2 == np.asarray(aug.visibility)).all(), \
        "negative timestamps dropped with no watermark"


# -- hop-window absorption (ISSUE 12 tentpole c) ---------------------------


HOP_MV = ("CREATE MATERIALIZED VIEW q AS SELECT window_start, "
          "COUNT(*) AS c, MAX(price) AS m "
          "FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
          "INTERVAL '10' SECOND) GROUP BY window_start")


def test_hop_absorbed_vs_sequential_sql_oracle():
    """The agg's traced prelude replicates rows units× in-trace; the
    sequential HopWindowExecutor survives as the off arm — results
    must be bit-identical, and the fused plan must actually absorb
    the hop (EXPLAIN annotation)."""
    rows_off = _front_door_rows(NEXMARK_SOURCES, HOP_MV, False)
    rows_on = _front_door_rows(NEXMARK_SOURCES, HOP_MV, True)
    assert rows_on == rows_off and len(rows_on) > 1

    async def explain():
        fe = Frontend(rate_limit=4)
        for s in NEXMARK_SOURCES:
            await fe.execute(s)
        rows = await fe.execute(
            "EXPLAIN SELECT window_start, COUNT(*) AS c "
            "FROM HOP(bid, date_time, INTERVAL '2' SECOND, "
            "INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.close()
        return "\n".join(r[0] for r in rows)
    text = run(explain())
    assert "absorbed HopWindowExecutor" in text, text


def test_hop_chain_body_matches_sequential_executor():
    """Unit oracle: the composed hop+filter chain on numpy equals the
    sequential HopWindowExecutor + FilterExecutor over random chunks —
    NULL timestamps dropped, update pairs preserved per copy."""
    from risingwave_tpu.common.types import Interval as Iv
    from risingwave_tpu.stream.executors.hop_window import (
        HopWindowExecutor,
    )
    from risingwave_tpu.stream.executors.simple import FilterExecutor

    S = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    rng = np.random.default_rng(11)
    cap = 32
    ts = rng.integers(0, 40_000_000, size=cap).astype(np.int64)
    v = rng.integers(-10, 10, size=cap).astype(np.int64)
    ok = rng.random(cap) > 0.2           # NULL timestamps
    vis = rng.random(cap) > 0.1
    ops = np.full(cap, int(Op.INSERT), dtype=np.int8)
    ops[6] = int(Op.UPDATE_DELETE)
    ops[7] = int(Op.UPDATE_INSERT)
    chunk = StreamChunk(
        S, [Column(DataType.TIMESTAMP, ts, ok.copy()),
            Column(DataType.INT64, v, None)], vis, ops)

    hop_st = FusedStage("hop_window", "HopWindowExecutor",
                        time_col=0, slide_usecs=10_000_000,
                        size_usecs=30_000_000)
    pred = InputRef(1, DataType.INT64) >= lit(0)
    fs = FusedStages(S, [hop_st,
                         FusedStage("filter", "FilterExecutor",
                                    exprs=(pred,))])
    assert fs.fusable_reason() is None
    out_cols, vis2, ops2, _sr = fs.chain_body(
        list(chunk.columns), np.asarray(chunk.visibility),
        np.asarray(chunk.ops), np)
    got = StreamChunk(fs.out_schema,
                      [c for c in out_cols if c is not None],
                      np.asarray(vis2), np.asarray(ops2))

    class _Src:
        schema = S
        async def execute(self):
            from risingwave_tpu.common.epoch import Epoch, EpochPair
            from risingwave_tpu.stream.message import Barrier
            yield Barrier(EpochPair.new_initial(Epoch.from_physical(1)))
            yield chunk
            yield Barrier(EpochPair(
                Epoch.from_physical(2), Epoch.from_physical(1)))
        @property
        def pk_indices(self):
            return []
        identity = "mock"

    async def seq_records():
        hop = HopWindowExecutor(_Src(), 0, Iv(usecs=10_000_000),
                                Iv(usecs=30_000_000))
        filt = FilterExecutor(hop, pred)
        out = []
        async for m in filt.execute():
            from risingwave_tpu.stream.message import is_chunk
            if is_chunk(m):
                out.extend(m.to_records())
        return out

    want = run(seq_records())
    assert got.to_records() == want


def test_hop_watermark_rederivation_through_absorbed_stage():
    """A watermark on the time column re-derives to the window_start
    column (floor to slide, minus (units-1)*slide); all other
    watermarks are consumed — HopWindowExecutor's exact rule."""
    from risingwave_tpu.stream.message import Watermark
    S = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    fs = FusedStages(S, [FusedStage(
        "hop_window", "HopWindowExecutor", time_col=0,
        slide_usecs=10_000_000, size_usecs=30_000_000)])
    out = fs.derive_watermarks(
        Watermark(0, DataType.TIMESTAMP, 25_000_000))
    assert [(w.col_idx, w.value) for w in out] == [(2, 0)]
    assert fs.derive_watermarks(
        Watermark(1, DataType.INT64, 5)) == []


def test_hop_refuses_bad_shapes():
    S = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    hop = FusedStage("hop_window", "HopWindowExecutor", time_col=0,
                     slide_usecs=10, size_usecs=30)
    # non-head hop never composes
    with pytest.raises(ValueError):
        FusedStages(S, [FusedStage(
            "filter", "FilterExecutor",
            exprs=(InputRef(1, DataType.INT64) >= lit(0),)), hop])
    # float time column refuses
    SF = Schema.of(ts=DataType.FLOAT64, v=DataType.INT64)
    fsf = FusedStages(SF, [FusedStage(
        "hop_window", "HopWindowExecutor", time_col=0,
        slide_usecs=10, size_usecs=30)])
    assert "non-integer" in fsf.fusable_reason()
    # hop group keys (window_start) never map to raw input columns —
    # the parallel cut must refuse to dispatch on them
    fs = FusedStages(S, [hop])
    assert fs.input_positions([2]) is None
    assert fs.input_positions([1]) == [1]


def test_hop_executor_emits_pow2_copy_groups():
    """The rewritten HopWindowExecutor emits pow2 COPY-GROUP chunks
    (popcount(units) of them — e.g. 3 windows → a 2×-copy chunk + a
    1×-copy chunk), not `units` chunks, and every capacity stays a
    power of two so kernel backlogs pack tight."""
    from risingwave_tpu.common.types import Interval as Iv
    from risingwave_tpu.stream.executors.hop_window import (
        HopWindowExecutor,
    )
    from risingwave_tpu.stream.message import is_chunk
    S = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    chunk = StreamChunk.from_pydict(
        S, {"ts": [25_000_000, None], "v": [7, 8]})

    class _Src:
        schema = S
        async def execute(self):
            from risingwave_tpu.common.epoch import Epoch, EpochPair
            from risingwave_tpu.stream.message import Barrier
            yield Barrier(EpochPair.new_initial(Epoch.from_physical(1)))
            yield chunk
            yield Barrier(EpochPair(
                Epoch.from_physical(2), Epoch.from_physical(1)))
        @property
        def pk_indices(self):
            return []
        identity = "mock"

    async def main():
        hop = HopWindowExecutor(_Src(), 0, Iv(usecs=10_000_000),
                                Iv(usecs=30_000_000))
        chunks = []
        async for m in hop.execute():
            if is_chunk(m):
                chunks.append(m)
        return chunks

    chunks = run(main())
    assert len(chunks) == bin(3).count("1")     # 3 = 2 + 1 copies
    for c in chunks:
        cap = c.capacity
        assert cap & (cap - 1) == 0, "capacity must stay pow2"
    recs = [r for c in chunks for _op, r in c.to_records()]
    # NULL ts dropped; 3 windows for the valid row
    assert sorted(r[2] for r in recs) == [0, 10_000_000, 20_000_000]
    assert all(r[1] == 7 for r in recs)
