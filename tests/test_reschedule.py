"""Runtime reschedule: ALTER MATERIALIZED VIEW ... SET PARALLELISM.

Reference parity: src/meta/src/stream/scale.rs:717 (reschedule_actors)
+ :174 (vnode rebalance), collapsed to the TPU design — pause at a
stop barrier, replan the same definition over an n-device mesh from
the same table-id base, redeploy through recovery. The vnode-owner
routing of the sharded kernels re-balances state automatically on
rebuild.
"""

import asyncio

import pytest

from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.parallel.agg import ShardedAggKernel

SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num=6000, "
       "nexmark.max.chunk.size=256)")
MV = ("CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) AS c, "
      "max(price) AS m FROM bid GROUP BY auction")


def _agg_kernels(fe):
    out = []
    for actor in fe.actors.values():
        ex = actor.consumer
        while ex is not None:
            if hasattr(ex, "kernel"):
                out.append(ex.kernel)
            ex = getattr(ex, "input", None)
    return out


async def _drain(fe, steps):
    for _ in range(steps):
        await fe.step()


def _oracle_run():
    async def run():
        fe = Frontend(rate_limit=4, min_chunks=4)
        await fe.execute(SRC)
        await fe.execute(MV)
        await _drain(fe, 40)
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return sorted(rows)

    return asyncio.run(run())


def test_alter_parallelism_live_no_divergence(eight_devices):
    """A live job moves parallelism 2→4 mid-stream; the final MV is
    byte-equal to an uninterrupted single-chip run."""
    async def run():
        fe = Frontend(rate_limit=4, min_chunks=4, parallelism=2)
        await fe.execute(SRC)
        await fe.execute(MV)
        ks = _agg_kernels(fe)
        assert any(isinstance(k, ShardedAggKernel)
                   and k.n_dev == 2 for k in ks)
        await _drain(fe, 8)               # mid-stream
        mid = await fe.execute("SELECT * FROM v")
        assert len(mid) > 0 and any(r[1] > 1 for r in mid)
        await fe.execute(
            "ALTER MATERIALIZED VIEW v SET PARALLELISM = 4")
        ks = _agg_kernels(fe)
        assert any(isinstance(k, ShardedAggKernel)
                   and k.n_dev == 4 for k in ks), "not resharded"
        await _drain(fe, 40)
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return sorted(rows)

    assert asyncio.run(run()) == _oracle_run()


def test_alter_parallelism_chaos_recovery(eight_devices):
    """Kill the session right after the reschedule; the replayed DDL
    log (create + alter) redeploys at the NEW parallelism and the MV
    converges to the oracle."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(store=HummockLite(obj), rate_limit=4,
                      min_chunks=4, parallelism=2)
        await fe.execute(SRC)
        await fe.execute(MV)
        await _drain(fe, 6)
        await fe.execute(
            "ALTER MATERIALIZED VIEW v SET PARALLELISM = 4")
        await _drain(fe, 2)
        await fe.close()       # "SIGKILL": no clean shutdown needed —
        # recovery only reads committed state

    async def phase2():
        fe = Frontend(store=HummockLite(obj), rate_limit=4,
                      min_chunks=4, parallelism=2)
        await fe.recover()
        ks = _agg_kernels(fe)
        assert any(isinstance(k, ShardedAggKernel)
                   and k.n_dev == 4 for k in ks), \
            "replayed ALTER did not stick"
        await _drain(fe, 40)
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return sorted(rows)

    asyncio.run(phase1())
    assert asyncio.run(phase2()) == _oracle_run()


def test_alter_parallelism_down_to_single_chip(eight_devices):
    """Parallelism N→1 lands back on the single-chip kernel."""
    async def run():
        fe = Frontend(rate_limit=4, min_chunks=4, parallelism=4)
        await fe.execute(SRC)
        await fe.execute(MV)
        await _drain(fe, 8)
        await fe.execute(
            "ALTER MATERIALIZED VIEW v SET PARALLELISM = 1")
        ks = _agg_kernels(fe)
        assert not any(isinstance(k, ShardedAggKernel) for k in ks)
        await _drain(fe, 40)
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return sorted(rows)

    assert asyncio.run(run()) == _oracle_run()


def test_alter_unknown_mv_and_chained_rejected(eight_devices):
    async def run():
        fe = Frontend(rate_limit=4, min_chunks=4)
        await fe.execute(SRC)
        await fe.execute(MV)
        await fe.execute(
            "CREATE MATERIALIZED VIEW v2 AS SELECT c, count(*) AS n "
            "FROM v GROUP BY c")
        with pytest.raises(Exception, match="unknown"):
            await fe.execute(
                "ALTER MATERIALIZED VIEW nope SET PARALLELISM = 2")
        with pytest.raises(Exception, match="chained"):
            await fe.execute(
                "ALTER MATERIALIZED VIEW v SET PARALLELISM = 2")
        await fe.close()

    asyncio.run(run())
