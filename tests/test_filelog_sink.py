"""Exactly-once external sink (epoch segments + atomic rename) and
the segmented reader consuming its output — the coordinated-commit
sink contract (sink/mod.rs:156 + sink coordinator parity)."""

import asyncio
import json
import os

import pytest

from risingwave_tpu.connectors.filelog import (
    SegmentedFileLogReader, list_segments,
)
from risingwave_tpu.common.types import DataType, Schema


def _consume_all(path, topic, schema):
    r = SegmentedFileLogReader(path, topic, 0, schema,
                               max_chunk_size=10_000)
    rows = []
    while True:
        c = r.next_chunk()
        if c is None:
            return rows, r.offset
        for _op, row in c.to_records():
            rows.append(row)


def test_filelog_sink_sql_and_exactly_once_restart(tmp_path):
    """CREATE SINK ... connector='filelog' publishes epoch segments;
    a SIGKILL-style restart replays the last checkpoint window and the
    recommit is SKIPPED — consuming the topic yields each record
    exactly once."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    out = str(tmp_path / "out")
    obj = MemObjectStore()
    ddl = [
        "CREATE SOURCE bid WITH (connector='nexmark', "
        "nexmark.table.type='bid', nexmark.event.num=3000, "
        "nexmark.max.chunk.size=128)",
        f"CREATE SINK s AS SELECT auction, price FROM bid "
        f"WITH (connector='filelog', path='{out}', topic='enriched')",
    ]

    async def phase1():
        fe = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        for s in ddl:
            await fe.execute(s)
        for _ in range(4):
            await fe.step()
        await fe.close()

    async def phase2():
        fe = Frontend(HummockLite(obj), rate_limit=2, min_chunks=2)
        await fe.recover()
        for _ in range(20):
            await fe.step()
        await fe.close()

    asyncio.run(phase1())
    segs_mid = list_segments(out, "enriched", 0)
    assert segs_mid, "no segments published"
    asyncio.run(phase2())
    segs = list_segments(out, "enriched", 0)
    assert len(segs) > len(segs_mid)
    # epochs strictly increase; no duplicate segment names
    assert len(segs) == len(set(segs))

    S = Schema.of(auction=DataType.INT64, price=DataType.INT64)
    rows, _off = _consume_all(out, "enriched", S)
    # exactly-once: the sink output equals the source rows, no dupes
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    cfg = NexmarkConfig(event_num=3000, max_chunk_size=128)
    bids = gen_bids(np.arange(3000 * 46 // 50, dtype=np.int64), cfg)
    want = sorted(zip(bids["auction"].tolist(), bids["price"].tolist()))
    assert sorted(rows) == want

    # __op rides every record (retraction-capable wire format)
    first = open(segs[0], "rb").readline()
    assert json.loads(first)["__op"] == "I"


def test_filelog_sink_recommit_skips(tmp_path):
    """2PC contract, position-named segments: a crashed-and-replayed
    window re-sends records the segments already hold; reconciliation
    drops them and NO new segment appears. A fresh sink over a
    non-empty topic is refused, and two publishers on one partition
    fail loudly instead of overwriting."""
    from risingwave_tpu.common.chunk import Op
    from risingwave_tpu.stream.executors.sink import FilelogSink

    out = str(tmp_path)
    S = Schema.of(a=DataType.INT64)
    w = FilelogSink(out, "t", schema=S)
    w.reset_stream_position(0, claim="sink-A")
    w.begin_epoch(7)
    w.write_batch([(Op.INSERT, (1,))])
    w.commit(7)
    assert len(list_segments(out, "t", 0)) == 1
    # crash BEFORE the first counter checkpoint (C=0, P=1): the claim
    # proves this is the same sink, and reconciliation drops the
    # replayed record — no second segment, no duplicate line
    w2 = FilelogSink(out, "t", schema=S)
    w2.reset_stream_position(0, claim="sink-A")
    w2.begin_epoch(999)            # fresh epoch (recovery renumbers)
    w2.write_batch([(Op.INSERT, (1,))])
    w2.commit(999)
    segs = list_segments(out, "t", 0)
    assert len(segs) == 1
    assert open(segs[0]).read().count("\n") == 1
    # empty epochs publish nothing
    w2.begin_epoch(1000)
    w2.commit(1000)
    assert len(list_segments(out, "t", 0)) == 1
    # a DIFFERENT sink over the claimed topic: refused
    w3 = FilelogSink(out, "t", schema=S)
    with pytest.raises(ValueError, match="claimed"):
        w3.reset_stream_position(0, claim="sink-B")
    # no staging litter
    assert not [n for n in os.listdir(out) if "staging" in n]


def test_filelog_sink_crash_window_no_duplicates(tmp_path):
    """The hard crash window: a segment published but the META
    checkpoint lost. The replay re-sends the window's records under
    FRESH epochs; stream-position reconciliation drops exactly the
    already-published prefix (epoch-name dedup alone cannot)."""
    from risingwave_tpu.common.chunk import Op
    from risingwave_tpu.stream.executors.sink import FilelogSink

    out = str(tmp_path)
    S = Schema.of(a=DataType.INT64)
    w = FilelogSink(out, "t", schema=S)
    w.reset_stream_position(0, claim="A")
    w.begin_epoch(100)
    w.write_batch([(Op.INSERT, (i,)) for i in range(10)])
    w.commit(100)                       # published [0,10)
    w.begin_epoch(200)
    w.write_batch([(Op.INSERT, (i,)) for i in range(10, 15)])
    w.commit(200)                       # published [10,15) — but the
    # meta checkpoint for this window is LOST (crash): committed C=10
    w2 = FilelogSink(out, "t", schema=S)
    w2.reset_stream_position(10, claim="A")
    # replay re-sends [10,15) under a FRESH epoch + new data [15,18)
    w2.begin_epoch(777)
    w2.write_batch([(Op.INSERT, (i,)) for i in range(10, 18)])
    w2.commit(777)
    rows, _ = _consume_all(out, "t", S)
    assert [r[0] for r in rows] == list(range(18))   # exactly once


def test_segmented_source_sql_roundtrip(tmp_path):
    """Sink output consumed BACK through SQL: CREATE SOURCE over the
    segmented topic (segmented='true') — the full external loop."""
    from risingwave_tpu.frontend import Frontend
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    out = str(tmp_path / "topic")

    async def produce():
        fe = Frontend(HummockLite(MemObjectStore()), rate_limit=2,
                      min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            f"CREATE SINK s AS SELECT auction, price FROM bid "
            f"WITH (connector='filelog', path='{out}', topic='t')")
        for _ in range(10):
            await fe.step()
        await fe.close()

    async def consume():
        fe = Frontend(HummockLite(MemObjectStore()), rate_limit=4)
        await fe.execute(
            f"CREATE SOURCE t (auction BIGINT, price BIGINT) WITH "
            f"(connector='filelog', path='{out}', topic='t', "
            f"segmented='true', format='json')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, "
            "count(*) AS c FROM t GROUP BY auction")
        for _ in range(10):
            await fe.step()
        rows = await fe.execute("SELECT * FROM v")
        await fe.close()
        return rows

    asyncio.run(produce())
    rows = asyncio.run(consume())
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids
    cfg = NexmarkConfig(event_num=2000, max_chunk_size=128)
    bids = gen_bids(np.arange(2000 * 46 // 50, dtype=np.int64), cfg)
    from collections import Counter
    want = Counter(bids["auction"].tolist())
    assert {a: c for a, c in rows} == dict(want)


def test_segmented_reader_consumes_retractions_and_bytes(tmp_path):
    """__op envelope round-trips: DELETE records retract downstream;
    BYTEA values survive the hex wire format."""
    from risingwave_tpu.common.chunk import Op
    from risingwave_tpu.stream.executors.sink import FilelogSink

    out = str(tmp_path)
    S = Schema.of(a=DataType.INT64, b=DataType.BYTEA)
    w = FilelogSink(out, "t", schema=S)
    w.begin_epoch(1)
    w.write_batch([(Op.INSERT, (1, b"\x01\xff")),
                   (Op.INSERT, (2, b"zz")),
                   (Op.DELETE, (1, b"\x01\xff"))])
    w.commit(1)
    r = SegmentedFileLogReader(out, "t", 0, S)
    c = r.next_chunk()
    recs = c.to_records()
    assert [(op.is_insert, tuple(row)) for op, row in recs] == [
        (True, (1, b"\x01\xff")), (True, (2, b"zz")),
        (False, (1, b"\x01\xff"))]
