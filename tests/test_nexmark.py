"""Nexmark generator + SourceExecutor + barrier loop tests.

Mirrors the reference's source tests (src/connector nexmark tests +
source_executor.rs tests): determinism, seekability, split disjointness,
barrier-select protocol, split-state recovery.
"""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.common.types import DataType, Field, Schema
from risingwave_tpu.connectors.nexmark import (
    AUCTION_PROPORTION, BID_PROPORTION, FIRST_AUCTION_ID, FIRST_PERSON_ID,
    PERSON_PROPORTION, PROPORTION_DENOMINATOR,
    NexmarkConfig, NexmarkSplitReader,
    auction_event_index, bid_event_index, person_event_index,
    gen_auctions, gen_bids, gen_persons,
    _max_auction_base0, _max_person_base0,
)
from risingwave_tpu.meta.barrier import BarrierLoop
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.actor import Actor, LocalBarrierManager
from risingwave_tpu.stream.dispatch import SimpleDispatcher, Output
from risingwave_tpu.stream.exchange import channel_for_test
from risingwave_tpu.stream.executors.source import SourceExecutor
from risingwave_tpu.stream.message import is_barrier, is_chunk


# ---------------------------------------------------------------------------
# generator


def test_event_index_closed_forms():
    # the three per-type index sequences tile the global sequence exactly
    k = np.arange(0, 200, dtype=np.int64)
    p = person_event_index(k[:4])
    a = auction_event_index(k[:12])
    b = bid_event_index(k[:184])
    assert p.tolist() == [0, 50, 100, 150]
    assert a.tolist()[:6] == [1, 2, 3, 51, 52, 53]
    assert b.tolist()[:3] == [4, 5, 6]
    assert b.tolist()[45:48] == [49, 54, 55]
    merged = sorted(p.tolist() + a.tolist() + b.tolist())
    assert merged == list(range(200))


def test_id_watermarks_monotone():
    idx = np.arange(0, 5000, dtype=np.int64)
    mp = _max_person_base0(idx)
    ma = _max_auction_base0(idx)
    assert (np.diff(mp) >= 0).all() and (np.diff(ma) >= 0).all()
    # exactly 1 person and 3 auctions created per 50 events
    assert mp[4999] == 4999 // 50 * PERSON_PROPORTION
    assert ma[4999] == 4999 // 50 * AUCTION_PROPORTION + 2


def test_bids_reference_existing_entities():
    cfg = NexmarkConfig()
    k = np.arange(0, 10_000, dtype=np.int64)
    bids = gen_bids(k, cfg)
    idx = bid_event_index(k)
    max_a = _max_auction_base0(idx) + FIRST_AUCTION_ID
    max_p = _max_person_base0(idx) + FIRST_PERSON_ID
    assert (bids["auction"] <= max_a).all()
    assert (bids["auction"] >= FIRST_AUCTION_ID).all()
    assert (bids["bidder"] <= max_p).all()
    assert (bids["bidder"] >= FIRST_PERSON_ID).all()
    assert (bids["price"] >= 1).all()
    # hot-key skew exists: top auction gets way more than uniform share
    _, counts = np.unique(bids["auction"], return_counts=True)
    assert counts.max() > 10 * counts.mean()


def test_generator_deterministic_and_seekable():
    cfg = NexmarkConfig(max_chunk_size=256)
    r1 = NexmarkSplitReader(cfg)
    c1 = r1.next_chunk()
    c2 = r1.next_chunk()
    r2 = NexmarkSplitReader(cfg)
    r2.seek(256)  # skip first chunk
    c2b = r2.next_chunk()
    assert c2.to_pylist() == c2b.to_pylist()
    assert c1.to_pylist() != c2.to_pylist()


def test_splits_are_disjoint_and_complete():
    cfg = NexmarkConfig(event_num=50 * 100, max_chunk_size=10_000)
    whole = NexmarkSplitReader(cfg, 0, 1)
    rows_whole = whole.next_chunk().to_pylist()
    assert whole.next_chunk() is None  # event_num respected
    parts = []
    for i in range(3):
        r = NexmarkSplitReader(cfg, i, 3)
        ch = r.next_chunk()
        if ch is not None:
            parts.extend(ch.to_pylist())
    assert sorted(parts) == sorted(rows_whole)
    assert len(rows_whole) == 100 * BID_PROPORTION


def test_auction_and_person_tables():
    cfg_a = NexmarkConfig(table_type="auction", event_num=50 * 40)
    ra = NexmarkSplitReader(cfg_a)
    ca = ra.next_chunk()
    rows = ca.to_pylist()
    assert len(rows) == 40 * AUCTION_PROPORTION
    ids = [r[0] for r in rows]
    assert ids == list(range(FIRST_AUCTION_ID, FIRST_AUCTION_ID + 120))
    # expires strictly after date_time
    assert all(r[6] > r[5] for r in rows)

    cfg_p = NexmarkConfig(table_type="person", event_num=50 * 40)
    rp = NexmarkSplitReader(cfg_p)
    rows_p = rp.next_chunk().to_pylist()
    assert [r[0] for r in rows_p] == list(
        range(FIRST_PERSON_ID, FIRST_PERSON_ID + 40))
    assert all(" " in r[1] for r in rows_p)          # "First Last"
    assert all("@" in r[2] for r in rows_p)          # email


# ---------------------------------------------------------------------------
# source executor + barrier loop


SPLIT_STATE_SCHEMA = Schema([Field("split_id", DataType.VARCHAR),
                             Field("offset", DataType.INT64)])


def _source_setup(store, event_num=50 * 1000, max_chunk=512, table_id=77):
    cfg = NexmarkConfig(event_num=event_num, max_chunk_size=max_chunk)
    reader = NexmarkSplitReader(cfg)
    barrier_tx, barrier_rx = channel_for_test()
    split_state = StateTable(table_id, SPLIT_STATE_SCHEMA, [0], store)
    src = SourceExecutor(reader, barrier_rx, split_state, actor_id=1)
    return src, barrier_tx, reader


def test_source_barrier_protocol():
    async def main():
        store = MemoryStateStore()
        src, barrier_tx, reader = _source_setup(store)
        local = LocalBarrierManager()
        local.register_sender(1, barrier_tx)
        local.set_expected_actors([1])
        loop = BarrierLoop(local, store)

        out = []
        seen_barriers = 0

        async def drain():
            nonlocal seen_barriers
            async for msg in src.execute():
                out.append(msg)
                if is_barrier(msg):
                    local.collect(1, msg)
                    seen_barriers += 1
                    if seen_barriers >= 4:
                        return

        task = asyncio.ensure_future(drain())
        for _ in range(4):
            await loop.inject_and_collect()
        await task
        barriers = [m for m in out if is_barrier(m)]
        chunks = [m for m in out if is_chunk(m)]
        assert len(barriers) == 4
        assert chunks, "source produced no data between barriers"
        # first message is the init barrier
        assert is_barrier(out[0])
        # offsets persisted at each checkpoint
        assert loop.committed_epoch > 0
        return store, reader

    store, reader = asyncio.run(main())
    assert reader.offset > 0


def test_source_recovery_resumes_from_committed_offset():
    async def phase(store, n_barriers, collected_rows):
        src, barrier_tx, reader = _source_setup(store, max_chunk=128)
        local = LocalBarrierManager()
        local.register_sender(1, barrier_tx)
        local.set_expected_actors([1])
        loop = BarrierLoop(local, store)
        seen = 0

        async def drain():
            nonlocal seen
            async for msg in src.execute():
                if is_chunk(msg):
                    collected_rows.extend(msg.to_pylist())
                elif is_barrier(msg):
                    local.collect(1, msg)
                    seen += 1
                    if seen >= n_barriers:
                        return

        task = asyncio.ensure_future(drain())
        for _ in range(n_barriers):
            await loop.inject_and_collect()
        await task
        return reader.offset

    async def main():
        store = MemoryStateStore()
        rows1: list = []
        off1 = await phase(store, 3, rows1)
        # "crash": new executor on the same store resumes at the committed
        # offset — the replay produces no duplicates vs a straight-through run
        rows2: list = []
        await phase(store, 3, rows2)
        all_rows = rows1 + rows2
        cfg = NexmarkConfig(max_chunk_size=128)
        ref = NexmarkSplitReader(cfg)
        expect = []
        while len(expect) < len(all_rows):
            expect.extend(ref.next_chunk().to_pylist())
        assert all_rows == expect[:len(all_rows)]
        assert off1 > 0

    asyncio.run(main())


def test_barrier_loop_run_background():
    async def main():
        store = MemoryStateStore()
        src, barrier_tx, _ = _source_setup(store, max_chunk=64)
        local = LocalBarrierManager()
        local.register_sender(1, barrier_tx)
        local.set_expected_actors([1])
        loop = BarrierLoop(local, store, interval_ms=1,
                           checkpoint_frequency=2)

        async def drain():
            async for msg in src.execute():
                if is_barrier(msg):
                    local.collect(1, msg)
                    if msg.is_stop(1):
                        return

        drain_task = asyncio.ensure_future(drain())
        await loop.run(stop_after=6)
        drain_task.cancel()
        assert len(loop.stats.completed_epochs) == 6
        # checkpoint_frequency=2: initial checkpoint + every 2nd after
        assert loop.committed_epoch > 0
        assert loop.stats.p99_latency_s() >= 0
    asyncio.run(main())
