"""TPC-H streaming q3 end-to-end vs an independent host oracle
(e2e_test/streaming/tpch/q3 semantics: 3-way join, DECIMAL revenue,
group-by, top-10)."""

import asyncio
from collections import defaultdict
from decimal import Decimal

import numpy as np

from risingwave_tpu.common.types import scaled_to_decimal
from risingwave_tpu.connectors.tpch import (
    LINES_PER_ORDER, TpchConfig, gen_customer, gen_lineitem, gen_orders,
)
from risingwave_tpu.models.nexmark import drive_to_completion
from risingwave_tpu.models.tpch import CUTOFF, build_q3
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.state.state_table import to_logical_row

CUSTOMERS, ORDERS = 300, 3000


def q3_oracle(top_limit=10):
    cfg = TpchConfig(customers=CUSTOMERS, orders=ORDERS)
    cust = gen_customer(np.arange(CUSTOMERS, dtype=np.int64), cfg)
    ordr = gen_orders(np.arange(ORDERS, dtype=np.int64), cfg)
    line = gen_lineitem(
        np.arange(ORDERS * LINES_PER_ORDER, dtype=np.int64), cfg)
    building = {int(k) for k, seg in
                zip(cust["c_custkey"], cust["c_mktsegment"])
                if seg == "BUILDING"}
    okeys = {}
    for i in range(ORDERS):
        if (int(ordr["o_custkey"][i]) in building
                and int(ordr["o_orderdate"][i]) < CUTOFF):
            okeys[int(ordr["o_orderkey"][i])] = (
                int(ordr["o_orderdate"][i]),
                int(ordr["o_shippriority"][i]))
    groups = defaultdict(int)          # (okey, odate, prio) → scaled rev
    for i in range(ORDERS * LINES_PER_ORDER):
        ok = int(line["l_orderkey"][i])
        if ok in okeys and int(line["l_shipdate"][i]) > CUTOFF:
            price = int(line["l_extendedprice"][i])
            disc = int(line["l_discount"][i])
            # DECIMAL semantics: price * (1 - disc), scaled rescale
            rev = price * (10000 - disc) // 10000
            groups[(ok,) + okeys[ok]] += rev
    rows = [(k[0], k[1], k[2], scaled_to_decimal(v))
            for k, v in groups.items()]
    rows.sort(key=lambda r: (-r[3], r[1], r[0], r[2]))
    return rows[:top_limit]


def test_tpch_q3_end_to_end():
    store = MemoryStateStore()
    p = build_q3(store, customers=CUSTOMERS, orders=ORDERS,
                 rate_limit=8, min_chunks=8)
    targets = {1: CUSTOMERS, 2: ORDERS, 3: ORDERS * LINES_PER_ORDER}
    asyncio.run(drive_to_completion(p, targets))
    got = sorted(
        (to_logical_row(r, p.mv_table.schema)
         for _pk, r in p.mv_table.iter_rows()),
        key=lambda r: (-r[3], r[1], r[0], r[2]))
    want = q3_oracle()
    assert len(got) == len(want) == 10
    # revenue multiset must match exactly (ties can reorder rows whose
    # sort key collides; our topn breaks ties by pk deterministically)
    assert [r[3] for r in got] == [r[3] for r in want]
    assert {r[0] for r in got} == {r[0] for r in want}


def test_tpch_q3_via_sql_multiway_join():
    """TPC-H q3 expressed in SQL (VERDICT r3 optimizer v0): 3-way
    left-deep join with predicate pushdown, group-by revenue, ORDER BY
    + LIMIT — equals the independent oracle."""
    from risingwave_tpu.frontend.session import Frontend

    async def main():
        f = Frontend(rate_limit=8, min_chunks=8)
        for t in ("customer", "orders", "lineitem"):
            await f.execute(
                f"CREATE SOURCE {t} WITH (connector='tpch', "
                f"tpch.table='{t}', tpch.customers={CUSTOMERS}, "
                f"tpch.orders={ORDERS})")
        await f.execute(
            "CREATE MATERIALIZED VIEW q3 AS SELECT "
            "o.o_orderkey, o.o_orderdate, o.o_shippriority, "
            "sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue "
            "FROM customer AS c "
            "JOIN orders AS o ON c.c_custkey = o.o_custkey "
            "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
            f"WHERE c.c_mktsegment = 'BUILDING' "
            f"AND o.o_orderdate < {CUTOFF} AND l.l_shipdate > {CUTOFF} "
            "GROUP BY o.o_orderkey, o.o_orderdate, o.o_shippriority "
            "ORDER BY revenue DESC, o_orderdate ASC LIMIT 10")
        for _ in range(60):
            await f.step()
        rows = await f.execute(
            "SELECT o_orderkey, o_orderdate, o_shippriority, revenue "
            "FROM q3")
        plan = await f.execute(
            "EXPLAIN SELECT o.o_orderkey FROM customer AS c "
            "JOIN orders AS o ON c.c_custkey = o.o_custkey "
            "JOIN lineitem AS l ON o.o_orderkey = l.l_orderkey "
            "WHERE c.c_mktsegment = 'BUILDING'")
        await f.close()
        return rows, [l for (l,) in plan]

    rows, plan = asyncio.run(main())
    want = q3_oracle()
    got = sorted(rows, key=lambda r: (-r[3], r[1], r[0], r[2]))
    assert len(got) == len(want) == 10
    assert [r[3] for r in got] == [r[3] for r in want]
    assert {r[0] for r in got} == {r[0] for r in want}
    # plan snapshot: EXPLAIN shows the pre-rewrite tree (filter ABOVE
    # the joins) and the rewritten tree, where the filter_pushdown
    # rule sank the customer filter BELOW the joins
    txt = "\n".join(plan)
    pre, post = txt.split("-- rewritten plan", 1)
    assert pre.index("FilterExecutor") < pre.index("HashJoinExecutor")
    assert post.index("FilterExecutor") > post.index("HashJoinExecutor")
    assert post.count("MaterializeExecutor") == 1
