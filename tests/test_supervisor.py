"""RecoverySupervisor units: classification, the storm gate, the
rw_recovery event log, heartbeat-expiry surfacing, and the metric
families the supervisor feeds (ISSUE 8).

The chaos/e2e side lives in tests/test_chaos.py; everything here is
fast and in-process.
"""

import asyncio

import pytest

from risingwave_tpu.meta.barrier import BarrierWedgedError
from risingwave_tpu.meta.cluster import ClusterManager
from risingwave_tpu.meta.supervisor import (
    ACTION_FULL, ACTION_RESPAWN, CAUSE_DEAD_WORKER, CAUSE_STORAGE_FAULT,
    CAUSE_UNKNOWN, CAUSE_WEDGED_BARRIER, CAUSE_WORKER_DESYNC,
    CAUSE_WORKER_FAULT, RecoveryStormError, RecoverySupervisor,
    clear_recovery_log, recovery_rows,
)
from risingwave_tpu.utils.metrics import CLUSTER, GLOBAL


@pytest.fixture(autouse=True)
def _fresh_log():
    clear_recovery_log()
    yield
    clear_recovery_log()


def _chained(outer: BaseException, cause: BaseException):
    """exc raised FROM cause — the shape barrier failures surface in
    (RuntimeError('actor failure during epoch') from ConnectionError)."""
    try:
        raise outer from cause
    except BaseException as e:  # noqa: BLE001
        return e


def test_classify_matrix():
    s = RecoverySupervisor()
    # a dead worker explains every downstream symptom — it dominates
    assert s.classify(RuntimeError("x"),
                      dead_workers=[1]) == CAUSE_DEAD_WORKER
    # channel faults (incl. buried in the cause chain) → desync;
    # ConnectionError subclasses OSError, so order matters
    assert s.classify(ConnectionError("closed")) == CAUSE_WORKER_DESYNC
    assert s.classify(_chained(RuntimeError("actor failure"),
                               ConnectionError("torn"))) \
        == CAUSE_WORKER_DESYNC
    assert s.classify(TimeoutError("rpc")) == CAUSE_WORKER_DESYNC
    # storage faults: direct, chained, and sniffed from a worker-error
    # reply (the repr crosses the control channel as text)
    assert s.classify(OSError("disk gone")) == CAUSE_STORAGE_FAULT
    assert s.classify(_chained(RuntimeError("actor failure"),
                               OSError("disk"))) == CAUSE_STORAGE_FAULT
    assert s.classify(RuntimeError(
        "worker error: OSError('chaos upload fault')")) \
        == CAUSE_STORAGE_FAULT
    assert s.classify(BarrierWedgedError("late")) == CAUSE_WEDGED_BARRIER
    assert s.classify(RuntimeError("worker error: ValueError('plan')")) \
        == CAUSE_WORKER_FAULT
    assert s.classify(RuntimeError("???")) == CAUSE_UNKNOWN


def test_action_ladder():
    # only dead/desynced workers are repairable by respawn-in-place;
    # everything else escalates to full kill-and-redeploy
    assert RecoverySupervisor.action_for(
        CAUSE_DEAD_WORKER) == ACTION_RESPAWN
    assert RecoverySupervisor.action_for(
        CAUSE_WORKER_DESYNC) == ACTION_RESPAWN
    for cause in (CAUSE_STORAGE_FAULT, CAUSE_WEDGED_BARRIER,
                  CAUSE_WORKER_FAULT, CAUSE_UNKNOWN):
        assert RecoverySupervisor.action_for(cause) == ACTION_FULL


def test_storm_gate_bounds_and_backoff():
    delays = []

    async def fake_sleep(d):
        delays.append(d)

    async def run():
        s = RecoverySupervisor(max_attempts=4, backoff_s=0.1,
                               backoff_cap_s=0.3, seed=3,
                               sleep=fake_sleep)
        for i in range(4):
            assert await s.admit(CAUSE_DEAD_WORKER) == i + 1
        with pytest.raises(RecoveryStormError) as ei:
            await s.admit(CAUSE_DEAD_WORKER)
        assert "recovery storm" in str(ei.value)
        return s

    asyncio.run(run())
    # attempt 1 is immediate; later attempts back off exponentially
    # (jittered 0.5-1.5x) up to the cap
    assert len(delays) == 3
    assert 0.05 <= delays[0] <= 0.15          # ~0.1 jittered
    assert delays[1] >= delays[0] * 0.8       # growing (jitter aside)
    assert delays[2] <= 0.45                  # capped at 0.3 * 1.5

    # seeded jitter: the delay sequence is reproducible (madsim stance)
    async def seq(seed):
        out = []

        async def sleep(d):
            out.append(d)

        s = RecoverySupervisor(max_attempts=5, backoff_s=0.1, seed=seed,
                               sleep=sleep)
        for _ in range(5):
            await s.admit(CAUSE_UNKNOWN)
        return out

    assert asyncio.run(seq(11)) == asyncio.run(seq(11))


def test_note_healthy_resets_the_window():
    async def run():
        s = RecoverySupervisor(max_attempts=2, backoff_s=0.0)
        await s.admit(CAUSE_DEAD_WORKER)
        await s.admit(CAUSE_DEAD_WORKER)
        s.note_healthy()                    # a clean round closes it
        assert await s.admit(CAUSE_DEAD_WORKER) == 1

    asyncio.run(run())


def test_record_feeds_log_and_metrics():
    s = RecoverySupervisor()
    before = sum(v for _l, v in CLUSTER.recovery_total.series())
    ev = s.record(CAUSE_DEAD_WORKER, ACTION_RESPAWN, (1,), 42, 0.5,
                  True, 1, detail="x")
    rows = recovery_rows()
    assert rows == [(ev.seq, "dead_worker", "respawn", "1", 42, 0.5,
                     1, 1, "x")]
    assert CLUSTER.recovery_total.get(
        cause="dead_worker", action="respawn") >= 1
    after = sum(v for _l, v in CLUSTER.recovery_total.series())
    assert after == before + 1


def test_heartbeater_surfaces_expiry_to_owner():
    """Satellite: Heartbeater.tick no longer drops the dead set on the
    floor — cluster_worker_expired_total moves and the owner callback
    (the supervisor's detection input) fires."""
    from risingwave_tpu.cluster.coordinator import Heartbeater

    clock = [0.0]
    cm = ClusterManager(max_heartbeat_interval_s=1.0,
                        clock=lambda: clock[0])

    class DeadClient:
        async def ping(self, *a, **k):
            raise ConnectionError("no worker here")

        def abort(self):
            pass

    expired = []
    hb = Heartbeater(cm, on_expired=lambda dead: expired.extend(dead))
    w = cm.add_worker("127.0.0.1", 1)
    hb.register(w.worker_id, DeadClient())
    before = CLUSTER.worker_expired.get(worker=str(w.worker_id))

    async def run():
        assert await hb.tick() == []        # lease not yet lapsed
        clock[0] = 2.0
        dead = await hb.tick()
        assert [x.worker_id for x in dead] == [w.worker_id]

    asyncio.run(run())
    assert [x.worker_id for x in expired] == [w.worker_id]
    assert CLUSTER.worker_expired.get(
        worker=str(w.worker_id)) == before + 1


def test_recovery_metric_families_exposed():
    """Satellite: the supervisor's evidence trail renders through the
    same registry `ctl metrics` dumps."""
    text = GLOBAL.render()
    for name in ("recovery_total", "recovery_duration_seconds",
                 "rpc_retry_total", "cluster_worker_expired_total",
                 "object_store_retry_total"):
        assert f"# TYPE {name} " in text, name
