"""Executor tests for the wider operator set: Union, Expand, HopWindow,
WatermarkFilter, Wrapper, SimpleAgg, StatelessSimpleAgg, TopN family,
DynamicFilter — MockSource + MemoryStateStore, mirroring the reference's
per-executor test style (SURVEY §4)."""

import asyncio
from collections import Counter

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Interval, Schema
from risingwave_tpu.ops.hash_agg import AggKind
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.dynamic_filter import (
    DynamicFilterExecutor,
)
from risingwave_tpu.stream.executors.expand import ExpandExecutor
from risingwave_tpu.stream.executors.hash_agg import AggCall
from risingwave_tpu.stream.executors.hop_window import HopWindowExecutor
from risingwave_tpu.stream.executors.simple_agg import (
    SimpleAggExecutor, StatelessSimpleAggExecutor, simple_agg_state_schema,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.executors.top_n import (
    GroupTopNExecutor, TopNExecutor,
)
from risingwave_tpu.stream.executors.union import UnionExecutor
from risingwave_tpu.stream.executors.watermark_filter import (
    WATERMARK_STATE_SCHEMA, WatermarkFilterExecutor,
)
from risingwave_tpu.stream.executors.wrapper import (
    SanityError, WrapperExecutor,
)
from risingwave_tpu.stream.message import (
    Barrier, BarrierKind, Watermark, is_chunk, is_watermark,
)

S2 = Schema.of(k=DataType.INT64, v=DataType.INT64)


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def chunk(ks, vs, ops=None, schema=S2):
    names = [f.name for f in schema]
    return StreamChunk.from_pydict(
        schema, {names[0]: ks, names[1]: vs}, ops=ops)


def records(msgs) -> list:
    out = []
    for m in msgs:
        if is_chunk(m):
            out.extend(m.to_records())
    return out


def net_view(msgs) -> Counter:
    """Signed net counts per row (zeros dropped, negatives KEPT)."""
    view = Counter()
    for op, row in records(msgs):
        view[row] += 1 if op.is_insert else -1
    return Counter({k: v for k, v in view.items() if v != 0})


# -- Union ---------------------------------------------------------------


def test_union_merges_aligned_inputs():
    a = MockSource(S2, [barrier(1), chunk([1], [10]), barrier(2)])
    b = MockSource(S2, [barrier(1), chunk([2], [20]), barrier(2)])
    u = UnionExecutor([a, b])
    msgs = asyncio.run(collect_until_n_barriers(u, 2))
    assert net_view(msgs) == Counter({(1, 10): 1, (2, 20): 1})
    n_barriers = sum(1 for m in msgs if not is_chunk(m)
                     and not is_watermark(m))
    assert n_barriers == 2     # one aligned barrier per epoch


def test_union_watermark_is_min_across_inputs():
    a = MockSource(S2, [barrier(1), Watermark(0, DataType.INT64, 10),
                        barrier(2)])
    b = MockSource(S2, [barrier(1), Watermark(0, DataType.INT64, 5),
                        barrier(2)])
    u = UnionExecutor([a, b])
    msgs = asyncio.run(collect_until_n_barriers(u, 2))
    wms = [m for m in msgs if is_watermark(m)]
    assert [w.value for w in wms] == [5]


# -- Expand --------------------------------------------------------------


def test_expand_subsets_and_flag():
    src = MockSource(S2, [barrier(1), chunk([1], [10]), barrier(2)])
    ex = ExpandExecutor(src, column_subsets=[[0], [1]])
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    rows = records(msgs)
    # subset 0 keeps col k; subset 1 keeps col v; both append full copy
    assert (Op.INSERT, (1, None, 1, 10, 0)) in rows
    assert (Op.INSERT, (None, 10, 1, 10, 1)) in rows
    assert len(rows) == 2
    assert len(ex.schema) == 5 and ex.schema[4].name == "flag"


# -- HopWindow -----------------------------------------------------------


def test_hop_window_expands_each_row():
    sch = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    # slide 10s, size 30s → 3 windows per row
    src = MockSource(sch, [barrier(1),
                           chunk([25_000_000], [7], schema=sch),
                           barrier(2)])
    ex = HopWindowExecutor(src, time_col=0,
                           window_slide=Interval(usecs=10_000_000),
                           window_size=Interval(usecs=30_000_000))
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    rows = records(msgs)
    starts = sorted(r[2] for _op, r in rows)
    assert starts == [0, 10_000_000, 20_000_000]
    for _op, r in rows:
        assert r[3] == r[2] + 30_000_000   # window_end
        assert r[2] <= 25_000_000 < r[3]


def test_hop_window_rejects_non_divisible():
    sch = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    src = MockSource(sch, [])
    with pytest.raises(ValueError):
        HopWindowExecutor(src, 0, Interval(usecs=7_000_000),
                          Interval(usecs=30_000_000))


# -- WatermarkFilter -----------------------------------------------------


def test_watermark_filter_emits_and_drops_late():
    sch = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    store = MemoryStateStore()
    state = StateTable(41, WATERMARK_STATE_SCHEMA, [0], store)
    src = MockSource(sch, [
        barrier(1),
        chunk([100, 200], [1, 2], schema=sch),
        barrier(2),
        # late row (ts 50 < wm 200-100=100) + fresh row
        chunk([50, 300], [3, 4], schema=sch),
        barrier(3),
    ])
    ex = WatermarkFilterExecutor(src, time_col=0,
                                 delay=Interval(usecs=100), state=state)
    msgs = asyncio.run(collect_until_n_barriers(ex, 3))
    rows = [r for _op, r in records(msgs)]
    assert (50, 3) not in rows
    assert {(100, 1), (200, 2), (300, 4)} == set(rows)
    wms = [m.value for m in msgs if is_watermark(m)]
    assert wms == [100, 200]
    # watermark persisted at checkpoint
    assert state.get_row((0,))[1] == 200


def test_watermark_filter_recovers_watermark():
    sch = Schema.of(ts=DataType.TIMESTAMP, v=DataType.INT64)
    store = MemoryStateStore()

    def build():
        state = StateTable(41, WATERMARK_STATE_SCHEMA, [0], store)
        return state

    ex1 = WatermarkFilterExecutor(
        MockSource(sch, [barrier(1), chunk([500], [1], schema=sch),
                         barrier(2)]),
        time_col=0, delay=Interval(usecs=100), state=build())
    asyncio.run(collect_until_n_barriers(ex1, 2))
    ex2 = WatermarkFilterExecutor(
        MockSource(sch, [barrier(3)]),
        time_col=0, delay=Interval(usecs=100), state=build())

    async def drain():
        return [m async for m in ex2.execute()]

    # the restored watermark is emitted right after the first barrier
    # (reference recovery behavior), so drain the whole stream
    msgs = asyncio.run(drain())
    wms = [m.value for m in msgs if is_watermark(m)]
    assert wms == [400]    # restored 500-100


# -- Wrapper -------------------------------------------------------------


def test_wrapper_passes_valid_stream():
    src = MockSource(S2, [barrier(1), chunk([1], [2]), barrier(2)])
    msgs = asyncio.run(collect_until_n_barriers(WrapperExecutor(src), 2))
    assert len(records(msgs)) == 1


def test_wrapper_catches_broken_update_pair():
    bad = chunk([1, 2], [1, 2],
                ops=[Op.UPDATE_DELETE, Op.INSERT])  # U- not followed by U+
    src = MockSource(S2, [barrier(1), bad])
    with pytest.raises(SanityError):
        asyncio.run(collect_until_n_barriers(WrapperExecutor(src), 2))


def test_wrapper_catches_epoch_regression():
    src = MockSource(S2, [barrier(2), barrier(1)])
    with pytest.raises(SanityError):
        asyncio.run(collect_until_n_barriers(WrapperExecutor(src), 2))


# -- SimpleAgg -----------------------------------------------------------


def _simple_agg(script, calls, append_only=False, store=None):
    store = store or MemoryStateStore()
    src = MockSource(S2, script)
    schema, pk = simple_agg_state_schema(S2, calls)
    state = StateTable(51, schema, pk, store)
    return SimpleAggExecutor(src, calls, state,
                             append_only=append_only), store


def test_simple_agg_count_sum_first_emit_then_updates():
    calls = [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 1)]
    ex, _ = _simple_agg(
        [barrier(1), barrier(2),
         chunk([1, 2], [10, 20]), barrier(3),
         chunk([1], [10], ops=[Op.DELETE]), barrier(4)], calls)
    msgs = asyncio.run(collect_until_n_barriers(ex, 4))
    recs = records(msgs)
    # first barrier with no input emits the initial row (count 0, sum NULL)
    assert recs[0] == (Op.INSERT, (0, None))
    assert (Op.UPDATE_INSERT, (2, 30)) in recs
    assert recs[-1] == (Op.UPDATE_INSERT, (1, 20))


def test_simple_agg_max_append_only_and_recovery():
    calls = [AggCall(AggKind.MAX, 1), AggCall(AggKind.COUNT)]
    store = MemoryStateStore()
    ex1, _ = _simple_agg(
        [barrier(1), chunk([1, 2], [7, 30]), barrier(2)],
        calls, append_only=True, store=store)
    msgs = asyncio.run(collect_until_n_barriers(ex1, 2))
    assert records(msgs)[-1] == (Op.INSERT, (30, 2))
    # restart from the same store: no duplicate initial insert
    ex2, _ = _simple_agg(
        [barrier(3), chunk([5], [40]), barrier(4)],
        calls, append_only=True, store=store)
    msgs2 = asyncio.run(collect_until_n_barriers(ex2, 2))
    recs2 = records(msgs2)
    assert recs2 == [(Op.UPDATE_DELETE, (30, 2)),
                     (Op.UPDATE_INSERT, (40, 3))]


def test_simple_agg_min_retractable_rejected():
    calls = [AggCall(AggKind.MIN, 1)]
    with pytest.raises(NotImplementedError):
        _simple_agg([], calls)


def test_stateless_simple_agg_partials():
    calls = [AggCall(AggKind.COUNT), AggCall(AggKind.SUM, 1)]
    src = MockSource(S2, [barrier(1),
                          chunk([1, 2], [10, 20]),
                          chunk([3], [5], ops=[Op.DELETE]),
                          barrier(2)])
    ex = StatelessSimpleAggExecutor(src, calls)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert records(msgs) == [(Op.INSERT, (2, 30)),
                             (Op.INSERT, (-1, -5))]


# -- TopN ----------------------------------------------------------------


def _topn(script, order_by, offset, limit, group_indices=(),
          append_only=False, store=None):
    store = store or MemoryStateStore()
    src = MockSource(S2, script, pk_indices=[1])
    state = StateTable(61, S2, [0, 1] if group_indices else [1],
                       store, dist_key_indices=[])
    return GroupTopNExecutor(src, order_by, offset, limit, state,
                             group_indices=group_indices,
                             append_only=append_only)


def test_topn_basic_window_maintenance():
    # top-2 by v ascending, pk = v
    ex = _topn([barrier(1),
                chunk([1, 1, 1], [30, 10, 20]), barrier(2),
                chunk([1], [5]), barrier(3),
                chunk([1], [10], ops=[Op.DELETE]), barrier(4)],
               order_by=[(1, False)], offset=0, limit=2)
    msgs = asyncio.run(collect_until_n_barriers(ex, 4))
    assert net_view(msgs) == Counter({(1, 5): 1, (1, 20): 1})


def test_topn_offset_skips_leaders():
    ex = _topn([barrier(1), chunk([1, 1, 1], [30, 10, 20]), barrier(2)],
               order_by=[(1, False)], offset=1, limit=1)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert net_view(msgs) == Counter({(1, 20): 1})


def test_topn_descending():
    ex = _topn([barrier(1), chunk([1, 1, 1], [30, 10, 20]), barrier(2)],
               order_by=[(1, True)], offset=0, limit=2)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert net_view(msgs) == Counter({(1, 30): 1, (1, 20): 1})


def test_group_topn_per_group_windows():
    ex = _topn([barrier(1),
                chunk([1, 1, 2, 2, 2], [10, 20, 7, 5, 6]), barrier(2)],
               order_by=[(1, False)], offset=0, limit=1,
               group_indices=[0])
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert net_view(msgs) == Counter({(1, 10): 1, (2, 5): 1})


def test_topn_recovery_from_state():
    store = MemoryStateStore()
    ex1 = _topn([barrier(1), chunk([1, 1], [10, 20]), barrier(2)],
                order_by=[(1, False)], offset=0, limit=1, store=store)
    asyncio.run(collect_until_n_barriers(ex1, 2))
    # restart: a smaller row displaces the recovered leader
    ex2 = _topn([barrier(3), chunk([1], [5]), barrier(4)],
                order_by=[(1, False)], offset=0, limit=1, store=store)
    msgs = asyncio.run(collect_until_n_barriers(ex2, 2))
    assert net_view(msgs) == Counter({(1, 5): 1, (1, 10): -1})


def test_append_only_topn_prunes_state():
    store = MemoryStateStore()
    ex = _topn([barrier(1),
                chunk([1, 1, 1, 1], [40, 10, 30, 20]), barrier(2)],
               order_by=[(1, False)], offset=0, limit=2,
               append_only=True, store=store)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    assert net_view(msgs) == Counter({(1, 10): 1, (1, 20): 1})
    # managed state kept only the window
    kept = sorted(r[1] for _pk, r in ex.state.iter_rows())
    assert kept == [10, 20]


# -- DynamicFilter -------------------------------------------------------


RHS_SCHEMA = Schema.of(bound=DataType.INT64, dummy=DataType.INT64)


def _dyn(script_l, script_r, cmp):
    store = MemoryStateStore()
    lt = StateTable(71, S2, [1], store, dist_key_indices=[])
    return DynamicFilterExecutor(
        MockSource(S2, script_l, pk_indices=[1]),
        MockSource(RHS_SCHEMA, script_r),
        left_col=1, comparator=cmp, left_state=lt)


def rhs(vals, ops=None):
    return chunk(vals, [0] * len(vals), ops=ops, schema=RHS_SCHEMA)


def test_dynamic_filter_emits_on_bound_and_transitions():
    ex = _dyn(
        [barrier(1), chunk([1, 1, 1], [10, 20, 30]), barrier(2),
         chunk([1], [25]), barrier(3), barrier(4)],
        [barrier(1), rhs([15]), barrier(2), barrier(3),
         rhs([15, 28], ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
         barrier(4)],
        cmp=">")
    msgs = asyncio.run(collect_until_n_barriers(ex, 4))
    # epoch 2: bound 15 applies at barrier → stored 20,30 emitted then;
    # epoch 3: 25 passes inline; epoch 4: bound 28 retracts 20 and 25
    assert net_view(msgs) == Counter({(1, 30): 1})


def test_dynamic_filter_initial_bound_emits_backlog():
    ex = _dyn(
        [barrier(1), chunk([1, 1], [10, 30]), barrier(2), barrier(3)],
        [barrier(1), barrier(2), rhs([20]), barrier(3)],
        cmp=">=")
    msgs = asyncio.run(collect_until_n_barriers(ex, 3))
    assert net_view(msgs) == Counter({(1, 30): 1})


def test_dynamic_filter_less_than():
    ex = _dyn(
        [barrier(1), chunk([1, 1], [10, 30]), barrier(2), barrier(3)],
        [barrier(1), rhs([20]), barrier(2), barrier(3)],
        cmp="<")
    msgs = asyncio.run(collect_until_n_barriers(ex, 3))
    assert net_view(msgs) == Counter({(1, 10): 1})


def test_dynamic_filter_null_rows_never_match():
    script_l = [barrier(1),
                StreamChunk.from_pydict(S2, {"k": [1, 1],
                                             "v": [None, 50]}),
                barrier(2), barrier(3)]
    ex = _dyn(script_l,
              [barrier(1), rhs([20]), barrier(2), barrier(3)], cmp=">")
    msgs = asyncio.run(collect_until_n_barriers(ex, 3))
    assert net_view(msgs) == Counter({(1, 50): 1})


def test_append_only_dedup_first_wins_and_recovers():
    """append_only_dedup.rs: first row per key passes, duplicates drop
    (within and across chunks and across restarts)."""
    from risingwave_tpu.stream.executors.dedup import (
        AppendOnlyDedupExecutor,
    )

    store = MemoryStateStore()
    key_schema = Schema.of(k=DataType.INT64)

    def run(script, n):
        state = StateTable(70, key_schema, [0], store)
        ex = AppendOnlyDedupExecutor(
            MockSource(S2, script), [0], state)
        msgs = asyncio.run(collect_until_n_barriers(ex, n))
        return records(msgs)

    got = run([barrier(1), chunk([1, 2, 1], [10, 20, 11]),
               barrier(2), chunk([2, 3], [21, 30]), barrier(3)], 3)
    assert [r for _op, r in got] == [(1, 10), (2, 20), (3, 30)]
    # restart over the same store: keys 1-3 stay deduped
    got2 = run([barrier(4), chunk([3, 4], [31, 40]), barrier(5)], 2)
    assert [r for _op, r in got2] == [(4, 40)]
