"""RowIdGenExecutor: uniqueness across shards, epochs, and restarts."""

import asyncio

from risingwave_tpu.common.chunk import StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.stream.executors.row_id_gen import RowIdGenExecutor
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

SCHEMA = Schema.of(v=DataType.INT64)


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def _ids(shard: int, script):
    ex = RowIdGenExecutor(MockSource(SCHEMA, script), vnode_base=shard)
    msgs = asyncio.run(collect_until_n_barriers(
        ex, sum(1 for m in script if isinstance(m, Barrier))))
    out = []
    for m in msgs:
        if is_chunk(m):
            out.extend(r[-1] for r in m.to_pylist())
    return out


def _chunks(n_chunks, rows):
    return [StreamChunk.from_pydict(SCHEMA, {"v": list(range(rows))})
            for _ in range(n_chunks)]


def test_ids_unique_across_shards_same_epoch():
    # >4096 rows per epoch per shard: the 12-bit seq must carry into ms
    # bits within the shard, never into another shard's range
    script = [barrier(1)] + _chunks(3, 4096) + [barrier(2)]
    a = _ids(0, script)
    b = _ids(1, script)
    assert len(set(a)) == len(a)
    assert len(set(b)) == len(b)
    assert not (set(a) & set(b)), "shard id ranges overlap"


def test_ids_monotone_and_restart_safe():
    s1 = [barrier(1)] + _chunks(2, 128) + [barrier(2)]
    ids1 = _ids(3, s1)
    assert ids1 == sorted(ids1)
    # restart: a later epoch floor must clear all previously issued ids
    s2 = [barrier(5)] + _chunks(1, 128) + [barrier(6)]
    ids2 = _ids(3, s2)
    assert min(ids2) > max(ids1)
