"""MV-on-MV via backfill: snapshot + upstream merge vs batch recompute.

Reference parity: src/stream/src/executor/backfill/no_shuffle_backfill.rs:68,
chain.rs:28 — CREATE MV b over an ALREADY POPULATED MV a must equal the
batch recompute, stay in sync as a keeps changing, and survive restarts
(progress persistence + DDL replay).
"""

import asyncio

import pytest

from risingwave_tpu.frontend.session import Frontend
from risingwave_tpu.state.store import MemoryStateStore

SRC = ("CREATE SOURCE bid WITH (connector='nexmark', "
       "nexmark.table.type='bid', nexmark.event.num={n}, "
       "nexmark.max.chunk.size=128)")


def test_mv_on_mv_catches_up_and_stays_live():
    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(SRC.format(n=4000))
        await f.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT auction, price "
            "FROM bid WHERE price > 100")
        # populate a BEFORE b exists — b must backfill the snapshot
        for _ in range(10):
            await f.step()
        a_then = await f.execute("SELECT count(*) FROM a")
        assert a_then[0][0] > 500
        await f.execute(
            "CREATE MATERIALIZED VIEW b AS SELECT auction, count(*) "
            "AS c FROM a GROUP BY auction")
        # a keeps growing while b backfills + follows live
        for _ in range(40):
            await f.step()
        got = sorted(await f.execute("SELECT auction, c FROM b"))
        want = sorted(await f.execute(
            "SELECT auction, count(*) AS c FROM a GROUP BY auction"))
        await f.close()
        assert got == want
        assert len(got) > 10
    asyncio.run(main())


def test_mv_on_mv_restart_resumes(tmp_path):
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import LocalFsObjectStore

    root = str(tmp_path / "hummock")

    async def phase1():
        f = Frontend(HummockLite(LocalFsObjectStore(root)), rate_limit=1)
        await f.execute(SRC.format(n=1500))
        await f.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT auction, price "
            "FROM bid")
        for _ in range(4):
            await f.step()
        await f.execute(
            "CREATE MATERIALIZED VIEW b AS SELECT auction, price "
            "FROM a WHERE price > 50")
        for _ in range(3):
            await f.step()
        # crash without draining (no close): recovery must resume
        # both the source offset and the backfill progress

    async def phase2():
        f = Frontend(HummockLite(LocalFsObjectStore(root)), rate_limit=1)
        await f.recover()
        for _ in range(40):
            await f.step()
        got = sorted(await f.execute("SELECT auction, price FROM b"))
        want = sorted(await f.execute(
            "SELECT auction, price FROM a WHERE price > 50"))
        await f.close()
        return got, want

    asyncio.run(phase1())
    got, want = asyncio.run(phase2())
    assert got == want
    assert len(got) > 100


def test_drop_upstream_mv_with_dependent_is_refused():
    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(SRC.format(n=500))
        await f.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT auction FROM bid")
        await f.execute(
            "CREATE MATERIALIZED VIEW b AS SELECT auction FROM a")
        with pytest.raises(Exception, match="depended on"):
            await f.execute("DROP MATERIALIZED VIEW a")
        await f.execute("DROP MATERIALIZED VIEW b")
        await f.execute("DROP MATERIALIZED VIEW a")   # now fine
        await f.close()
    asyncio.run(main())


def test_drop_chained_mv_detaches_and_pipeline_stays_live():
    """DROP of a downstream chain MUST detach its dispatcher output —
    an orphan edge exhausts channel permits a few barriers later and
    wedges every subsequent barrier round (r3 review finding)."""
    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(SRC.format(n=100_000))
        await f.execute(
            "CREATE MATERIALIZED VIEW a AS SELECT auction FROM bid")
        await f.execute(
            "CREATE MATERIALIZED VIEW b AS SELECT auction FROM a")
        for _ in range(5):
            await f.step()
        await f.execute("DROP MATERIALIZED VIEW b")
        up = f.actors[f.catalog.mvs["a"].actor_id]
        assert up.dispatchers[0].outputs() == []   # edge detached
        # many more barriers than any channel's permit budget: would
        # hang here if the orphan edge were still attached
        for _ in range(40):
            await asyncio.wait_for(f.step(), timeout=10)
        n = (await f.execute("SELECT count(*) FROM a"))[0][0]
        assert n > 0
        await f.close()
    asyncio.run(main())
