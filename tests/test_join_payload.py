"""Device-resident join payloads (ISSUE 9): the device-emit path must
be indistinguishable from the host arena gather it replaces.

The on arm stores every device-typed payload column as HBM lanes
(ops/hash_join.py pay) and materializes matched rows from the packed
probe matrix; the off arm (``device_payload=False``) forces the
pre-existing arena gather. Both arms run the same scripts and their
EMITTED MESSAGE STREAMS must be bit-identical — not just the final
materialization — across all 8 join types, NULL-padded outer rows,
degree flips, retractions, float bit-patterns, NULL payload values,
and a varchar payload column forcing the mixed device/host emit.
Crash-recovery must rebuild the payload lanes exactly where it
rebuilds chains, and the cold tier must evict/reload a device-resident
side bit-identically.
"""

import asyncio

import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import (
    HashJoinExecutor, JoinType,
)
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

# varchar column forces the MIXED emit (device lanes for lv/lf, arena
# gather for ls); float64 column checks bit-preserving payload codecs
L = Schema.of(lk=DataType.INT64, lv=DataType.INT64,
              ls=DataType.VARCHAR, lf=DataType.FLOAT64)
R = Schema.of(rk=DataType.INT64, rv=DataType.INT64,
              rs=DataType.VARCHAR)


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


def lchunk(ks, vs, ss=None, fs=None, ops=None):
    n = len(ks)
    return StreamChunk.from_pydict(L, {
        "lk": ks, "lv": vs,
        "ls": ss if ss is not None else [f"s{v}" for v in vs],
        "lf": fs if fs is not None else [float(v) for v in vs],
    }, ops=ops)


def rchunk(ks, vs, ss=None, ops=None):
    return StreamChunk.from_pydict(R, {
        "rk": ks, "rv": vs,
        "rs": ss if ss is not None else [f"r{v}" for v in vs],
    }, ops=ops)


def records(msgs):
    out = []
    for m in msgs:
        if is_chunk(m):
            out.extend(m.to_records())
    return out


def run(jt, script_l, script_r, n_barriers, store=None, ids=(161, 162),
        device_payload=True, state_cap=None):
    store = store or MemoryStateStore()
    # state-table pks prefixed by the join key (the cold-tier contract)
    lt = StateTable(ids[0], L, [0, 1], store, dist_key_indices=[])
    rt = StateTable(ids[1], R, [0, 1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L, script_l), MockSource(R, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        join_type=jt, device_payload=device_payload,
        state_cap=state_cap)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, store


def scripts_scripted():
    """Every transition: unmatched insert, late match (0→1 flip), N:M
    growth, retraction back to unmatched (→0 flip), NULL join keys,
    NULL payload values, float bit-patterns (-0.0), update pairs."""
    script_l = [
        barrier(1),
        lchunk([1, 2, None], [10, 20, 30],
               ss=["a", None, "c"], fs=[-0.0, 1.5, float("inf")]),
        barrier(2),
        lchunk([1, 1], [10, 11], ss=["a", "a2"], fs=[-0.0, 2.5],
               ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
        barrier(3),
        lchunk([2], [20], ss=[None], fs=[1.5], ops=[Op.DELETE]),
        barrier(4),
    ]
    script_r = [
        barrier(1),
        rchunk([3, None], [90, 91], ss=[None, "x"]),
        barrier(2),
        rchunk([1, 1], [70, 71]),                # flips left 1: 0→2
        barrier(3),
        rchunk([1], [70], ops=[Op.DELETE]),      # degree 2→1 (no flip)
        barrier(4),
    ]
    return script_l, script_r, 4


ALL_TYPES = list(JoinType)


@pytest.mark.parametrize("jt", ALL_TYPES, ids=[t.value for t in ALL_TYPES])
def test_device_emit_bit_identical_scripted(jt):
    sl, sr, nb = scripts_scripted()
    on, _ = run(jt, sl, sr, nb, device_payload=True)
    sl, sr, nb = scripts_scripted()
    off, _ = run(jt, sl, sr, nb, device_payload=False)
    assert records(on) == records(off), jt


@pytest.mark.parametrize("jt", ALL_TYPES, ids=[t.value for t in ALL_TYPES])
def test_device_emit_bit_identical_random(jt):
    def scripts():
        rng = np.random.default_rng(hash(jt.value) % 2**32)
        rows = [[], []]
        script_l, script_r = [barrier(1)], [barrier(1)]
        pk = [0, 0]
        for b in range(2, 7):
            for side, script, mk in ((0, script_l, lchunk),
                                     (1, script_r, rchunk)):
                ks, vs, ops = [], [], []
                for _ in range(20):
                    if rows[side] and rng.random() < 0.3:
                        i = int(rng.integers(0, len(rows[side])))
                        k_, v_ = rows[side].pop(i)
                        ks.append(k_)
                        vs.append(v_)
                        ops.append(Op.DELETE)
                    else:
                        k_ = int(rng.integers(0, 6))
                        if rng.random() < 0.1:
                            k_ = None
                        v_ = pk[side]
                        pk[side] += 1
                        rows[side].append((k_, v_))
                        ks.append(k_)
                        vs.append(v_)
                        ops.append(Op.INSERT)
                script.append(mk(ks, vs, ops=ops))
                script.append(barrier(b))
        return script_l, script_r

    sl, sr = scripts()
    on, _ = run(jt, sl, sr, 6, device_payload=True)
    sl, sr = scripts()
    off, _ = run(jt, sl, sr, 6, device_payload=False)
    assert records(on) == records(off), jt


@pytest.mark.parametrize("jt", [JoinType.INNER, JoinType.FULL_OUTER,
                                JoinType.LEFT_ANTI],
                         ids=lambda t: t.value)
def test_recovery_rebuilds_payload_lanes(jt):
    """Kill-and-rebuild mid-stream: the fresh executor reloads the
    arena AND the device payload lanes from the state tables, and the
    resumed device-emit stream stays bit-identical to the host-gather
    arm resumed the same way."""
    def phase1():
        return ([barrier(1), lchunk([1, 2], [10, 20],
                                    ss=["a", None], fs=[-0.0, 2.5]),
                 barrier(2)],
                [barrier(1), rchunk([1], [70]), barrier(2)])

    def phase2():
        return ([barrier(3), lchunk([1], [10], ss=["a"], fs=[-0.0],
                                    ops=[Op.DELETE]), barrier(4)],
                [barrier(3), rchunk([2, 1], [80, 71], ss=[None, "z"]),
                 barrier(4)])

    streams = {}
    for arm in (True, False):
        store = MemoryStateStore()
        sl, sr = phase1()
        m1, _ = run(jt, sl, sr, 2, store=store, device_payload=arm)
        sl, sr = phase2()
        m2, _ = run(jt, sl, sr, 2, store=store, device_payload=arm)
        streams[arm] = records(m1) + records(m2)
    assert streams[True] == streams[False], jt


def test_recovery_payload_matches_arena():
    """White-box: after recovery, decoding the rebuilt device lanes by
    ref reproduces the arena columns exactly."""
    store = MemoryStateStore()
    sl = [barrier(1), lchunk([1, 2, 7], [10, 20, 30],
                             ss=["a", None, "c"],
                             fs=[-0.0, 1.25, float("-inf")]),
          barrier(2)]
    sr = [barrier(1), rchunk([1], [70]), barrier(2)]
    run(JoinType.INNER, sl, sr, 2, store=store)
    # fresh executor recovers from the tables
    lt = StateTable(161, L, [0, 1], store, dist_key_indices=[])
    rt = StateTable(162, R, [0, 1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L, [barrier(3), barrier(4)]),
        MockSource(R, [barrier(3), barrier(4)]),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)
    asyncio.run(collect_until_n_barriers(ex, 2))
    side = ex.sides[0]
    refs = np.fromiter(side.pk_to_ref.values(), dtype=np.int64,
                       count=len(side.pk_to_ref))
    assert len(refs) == 3
    want = side.payload_from_arena(refs)
    got = np.asarray(side.kernel.pay)[refs]
    assert (want == got).all(), "device payload lanes drifted from arena"


def _run_chain(ex, n_barriers):
    return records(asyncio.run(collect_until_n_barriers(ex, n_barriers)))


def _join_with_run(kind):
    """join→agg-shape pipeline whose left input is a filter+project
    run, in three arms: 'interp' (sequential executors), 'block'
    (standalone FusedFragmentExecutor — the pre-ISSUE-9 fusion shape,
    1 jit dispatch per chunk), 'join' (the run absorbed into the
    join's epoch dispatches)."""
    from risingwave_tpu.expr.expr import InputRef, Literal
    from risingwave_tpu.stream.executors.simple import (
        FilterExecutor, ProjectExecutor,
    )

    def scripts():
        sl, sr = [barrier(1)], [barrier(1)]
        for b in range(2, 8):
            ks = list(range(8))
            sl.append(lchunk(ks, [b * 100 + k for k in ks]))
            sr.append(rchunk(ks, [b * 200 + k for k in ks]))
            sl.append(barrier(b))
            sr.append(barrier(b))
        return sl, sr, 7

    sl, sr, nb = scripts()
    store = MemoryStateStore()
    src = MockSource(L, sl)
    pred = InputRef(1, DataType.INT64) >= \
        Literal(0, DataType.INT64)
    filt = FilterExecutor(src, pred)
    proj = ProjectExecutor(
        filt,
        exprs=[InputRef(0, DataType.INT64),
               InputRef(1, DataType.INT64) * Literal(2, DataType.INT64),
               InputRef(2, DataType.VARCHAR),
               InputRef(3, DataType.FLOAT64)],
        names=["lk", "lv", "ls", "lf"])
    run_top = proj
    if kind == "block":
        from risingwave_tpu.ops.fused import FusedStage, FusedStages
        from risingwave_tpu.stream.executors.fused import (
            FusedFragmentExecutor,
        )
        fs = FusedStages(L, [
            FusedStage("filter", "FilterExecutor", exprs=(pred,)),
            FusedStage("project", "ProjectExecutor",
                       exprs=tuple(proj.exprs),
                       names=("lk", "lv", "ls", "lf"))])
        run_top = FusedFragmentExecutor(src, fs)
    lt = StateTable(171, run_top.schema, [0, 1], store,
                    dist_key_indices=[])
    rt = StateTable(172, R, [0, 1], store, dist_key_indices=[])
    ex = HashJoinExecutor(run_top, MockSource(R, sr),
                          left_keys=[0], right_keys=[0],
                          left_table=lt, right_table=rt)
    if kind == "join":
        from risingwave_tpu.frontend.opt.fusion import fuse_fragments
        ex, fired, _details = fuse_fragments(ex)
        assert fired >= 1
        assert ex.sides[0].fused_input is not None, \
            "join did not absorb its input run"
    return ex, nb


def test_fused_join_dispatch_budget(dispatch_budget):
    """CI guard (ISSUE 9): absorbing a join's input run into its epoch
    dispatches must not exceed — and must beat — the standalone
    fused-block shape's dispatch count, bit-identically."""
    out_i = _run_chain(*_join_with_run("interp"))
    ex, nb = _join_with_run("block")
    out_b, d_b, rpd_b = dispatch_budget.measure(
        lambda: _run_chain(ex, nb))
    ex, nb = _join_with_run("join")
    out_j, d_j, rpd_j = dispatch_budget.measure(
        lambda: _run_chain(ex, nb))
    assert out_i == out_b == out_j and out_j
    # the absorbed run dispatches strictly less than the block shape
    # (its per-chunk chain step disappears into the epoch jits) and
    # never exceeds it (the r08-ceiling analog at test scale)
    dispatch_budget.check(d_b, rpd_b, d_j, rpd_j)
    dispatch_budget.check_ceiling(d_j, d_b, what="fused-block arm")


def test_join_kernels_steady_state_no_retrace(recompile_guard):
    """The new join epoch kernels (payload scatter, device-degree
    probe, fused-input prelude jits) stay shape-stable: uniform
    chunks after warmup must retrace nothing."""
    def phase(b0, nb):
        sl, sr = [], []
        for b in range(b0, b0 + nb):
            ks = list(range(16))
            sl.append(lchunk(ks, [b * 100 + k for k in ks]))
            sl.append(barrier(b))
            sr.append(rchunk(ks, [b * 200 + k for k in ks]))
            sr.append(barrier(b))
        return sl, sr

    store = MemoryStateStore()
    lt = StateTable(181, L, [0, 1], store, dist_key_indices=[])
    rt = StateTable(182, R, [0, 1], store, dist_key_indices=[])
    w1l, w1r = phase(2, 6)
    w2l, w2r = phase(8, 6)
    ex = HashJoinExecutor(
        MockSource(L, [barrier(1)] + w1l + w2l),
        MockSource(R, [barrier(1)] + w1r + w2r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt,
        join_type=JoinType.LEFT_OUTER)

    from risingwave_tpu.stream.message import is_barrier
    agen = ex.execute()

    async def drain(n):
        seen = 0
        while seen < n:
            if is_barrier(await agen.__anext__()):
                seen += 1

    # drive warmup + steady on ONE loop (the generator is stateful)
    loop = asyncio.new_event_loop()
    try:
        _, n_warm = recompile_guard.measure(
            lambda: loop.run_until_complete(drain(7)))
        _, n_steady = recompile_guard.measure(
            lambda: loop.run_until_complete(drain(6)))
    finally:
        loop.close()
    assert n_warm > 0, "warmup compiled nothing — dead test"
    recompile_guard.check_steady(
        n_steady, what="steady-state join epochs")


def test_cold_tier_eviction_reload_device_resident():
    """state_cap over a device-resident side: rows leave the payload
    lanes with the arena on eviction and reload together; the emitted
    stream stays bit-identical to the host-gather arm under the same
    cap, and evictions actually happened."""
    from risingwave_tpu.utils.metrics import STREAMING

    def evicted_total():
        return sum(v for _l, v in STREAMING.state_tier_evicted.series())

    def scripts():
        sl, sr = [barrier(1)], [barrier(1)]
        b = 2
        for phase in range(5):
            ks = [phase * 4 + j for j in range(4)]
            sl.append(lchunk(ks, [100 + k for k in ks]))
            sr.append(rchunk(ks, [200 + k for k in ks]))
            sl.append(barrier(b))
            sr.append(barrier(b))
            b += 1
        # revisit the OLDEST keys: forces a reload of evicted state
        sl.append(lchunk([0, 1], [900, 901]))
        sr.append(rchunk([2, 3], [902, 903]))
        sl.append(barrier(b))
        sr.append(barrier(b))
        return sl, sr, b

    streams = {}
    evicted = {}
    for arm in (True, False):
        before = evicted_total()
        sl, sr, nb = scripts()
        msgs, _ = run(JoinType.INNER, sl, sr, nb, device_payload=arm,
                      state_cap=6)
        streams[arm] = records(msgs)
        evicted[arm] = evicted_total() - before
    assert evicted[True] > 0, "cap 6 over 20 keys must evict"
    assert streams[True] == streams[False]
