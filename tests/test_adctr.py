"""ad-ctr (BASELINE.md row): Kafka-shaped sources → 3-way join →
sliding-window agg, at actor parallelism 4 — the reference's
integration_tests/ad-ctr pipeline on this framework's surface:
filelog topics stand in for Kafka, HOP windows for the sliding agg,
a temporal join against an ad dimension MV for the third side, and
a mesh session for the parallelism.
"""

import asyncio
import json
import os

import numpy as np
import pytest

N_ADS = 20
N_IMPRESSIONS = 1500
CLICK_EVERY = 3          # every 3rd impression gets a click
SLIDE_US = 2_000_000
SIZE_US = 10_000_000
# µs since epoch, large enough that the JSON parser's seconds-vs-µs
# heuristic reads it as µs (realistic 2023 wall time)
BASE_TS = 1_700_000_000_000_000


def _produce(path):
    os.makedirs(path, exist_ok=True)
    rng = np.random.default_rng(42)
    ads = rng.integers(0, N_ADS, N_IMPRESSIONS)
    with open(os.path.join(path, "impressions-0.log"), "wb") as f:
        for i in range(N_IMPRESSIONS):
            f.write(json.dumps({
                "bid_id": i, "ad_id": int(ads[i]),
                "its": BASE_TS + i * 10_000}).encode() + b"\n")
    with open(os.path.join(path, "clicks-0.log"), "wb") as f:
        for i in range(0, N_IMPRESSIONS, CLICK_EVERY):
            f.write(json.dumps({
                "cbid": i,
                "cts": BASE_TS + i * 10_000 + 500}).encode()
                + b"\n")
    return ads


def _oracle(ads):
    """Per (ad window_start): impression count + clicked count."""
    out = {}
    for i in range(N_IMPRESSIONS):
        if i % CLICK_EVERY:
            continue                      # inner join keeps clicked
        ts = BASE_TS + i * 10_000
        base = ts // SLIDE_US * SLIDE_US
        for k in range(SIZE_US // SLIDE_US):
            w = base - k * SLIDE_US
            key = (int(ads[i]), w)
            c = out.get(key, 0)
            out[key] = c + 1
    return out


def test_ad_ctr_pipeline_parallel(eight_devices, tmp_path):
    from risingwave_tpu.frontend.session import Frontend

    path = str(tmp_path)
    ads = _produce(path)

    async def run():
        fe = Frontend(rate_limit=8, min_chunks=4, parallelism=4)
        await fe.execute(
            f"CREATE SOURCE impression (bid_id BIGINT, ad_id BIGINT, "
            f"its TIMESTAMP) WITH (connector='filelog', "
            f"path='{path}', topic='impressions')")
        await fe.execute(
            f"CREATE SOURCE click (cbid BIGINT, cts TIMESTAMP) WITH "
            f"(connector='filelog', path='{path}', topic='clicks')")
        # ad dimension table (the 3rd join side): an MV the stream
        # probes temporally
        await fe.execute(
            "CREATE MATERIALIZED VIEW ad_dim AS SELECT ad_id, "
            "count(*) AS seen FROM impression GROUP BY ad_id")
        # the ad-ctr core: sliding windows over impressions, joined
        # to clicks (2nd side) and the ad dimension (3rd side),
        # aggregated per (ad, window)
        await fe.execute(
            "CREATE MATERIALIZED VIEW ad_ctr AS SELECT i.ad_id, "
            "i.window_start, count(*) AS clicked "
            "FROM HOP(impression, its, INTERVAL '2' SECOND, "
            "INTERVAL '10' SECOND) AS i "
            "JOIN click AS c ON i.bid_id = c.cbid "
            "JOIN ad_dim AS d FOR SYSTEM_TIME AS OF PROCTIME() "
            "ON i.ad_id = d.ad_id "
            "GROUP BY i.ad_id, i.window_start")
        for _ in range(40):
            await fe.step()
        rows = await fe.execute("SELECT * FROM ad_ctr")
        # CTR read: batch join of the streaming MVs' snapshots
        ctr = await fe.execute(
            "SELECT a.ad_id, a.seen FROM ad_dim AS a")
        await fe.close()
        return rows, ctr

    rows, ctr = asyncio.run(run())
    want = _oracle(ads)
    got = {(a, w): c for a, w, c in rows}
    assert got == want, (len(got), len(want))
    # dimension side saw every impression
    assert sum(s for _a, s in ctr) == N_IMPRESSIONS
