"""Foundation tests: types, chunks, hashing, epochs, config."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import (
    DataChunk, DataType, Epoch, EpochPair, Op, Schema, StreamChunk,
    VNODE_COUNT, hash_columns, vnodes_of,
)
from risingwave_tpu.common.chunk import next_pow2, ops_to_signs
from risingwave_tpu.common.hash import VnodeMapping
from risingwave_tpu.common.types import Field


def test_datatype_mapping():
    assert DataType.INT64.dtype == jnp.int64
    assert DataType.TIMESTAMP.dtype == jnp.int64
    assert DataType.VARCHAR.dtype is None
    assert not DataType.VARCHAR.is_device
    assert DataType.from_sql("BIGINT") == DataType.INT64
    assert DataType.from_sql("character varying") == DataType.VARCHAR


def test_schema():
    s = Schema.of(a=DataType.INT64, b=DataType.VARCHAR)
    assert len(s) == 2
    assert s.index_of("b") == 1
    assert s.select([1]).names() == ["b"]
    s2 = s.concat(Schema([Field("c", DataType.FLOAT64)]))
    assert s2.names() == ["a", "b", "c"]


def test_next_pow2():
    assert next_pow2(1) == 8
    assert next_pow2(8) == 8
    assert next_pow2(9) == 16
    assert next_pow2(4096) == 4096


def test_data_chunk_roundtrip():
    s = Schema.of(id=DataType.INT64, name=DataType.VARCHAR,
                  price=DataType.FLOAT64)
    c = DataChunk.from_pydict(
        s, {"id": [1, 2, 3], "name": ["a", "b", None], "price": [1.5, 2.5, 3.5]})
    assert c.capacity == 8
    assert c.cardinality() == 3
    assert c.to_pylist() == [(1, "a", 1.5), (2, "b", 2.5), (3, None, 3.5)]


def test_data_chunk_nulls_device():
    s = Schema.of(x=DataType.INT32)
    c = DataChunk.from_pydict(s, {"x": [5, None, 7]})
    assert c.to_pylist() == [(5,), (None,), (7,)]


def test_visibility_mask():
    s = Schema.of(x=DataType.INT64)
    c = DataChunk.from_pydict(s, {"x": [1, 2, 3, 4]})
    pred = c.column_values("x") % 2 == 0
    c2 = c.mask(pred)
    assert c2.to_pylist() == [(2,), (4,)]
    assert c.cardinality() == 4  # original untouched


def test_stream_chunk_ops_and_signs():
    s = Schema.of(x=DataType.INT64)
    c = StreamChunk.from_pydict(
        s, {"x": [1, 2, 2, 3]},
        ops=[Op.INSERT, Op.UPDATE_DELETE, Op.UPDATE_INSERT, Op.DELETE])
    recs = c.to_records()
    assert recs == [(Op.INSERT, (1,)), (Op.UPDATE_DELETE, (2,)),
                    (Op.UPDATE_INSERT, (2,)), (Op.DELETE, (3,))]
    signs = np.asarray(ops_to_signs(c.ops))[:4]
    assert signs.tolist() == [1, -1, 1, -1]


def test_stream_chunk_project():
    s = Schema.of(a=DataType.INT64, b=DataType.INT64)
    c = StreamChunk.from_pydict(s, {"a": [1], "b": [2]})
    p = c.project([1])
    assert p.schema.names() == ["b"]
    assert p.to_records() == [(Op.INSERT, (2,))]


def test_hash_consistency_and_spread():
    keys = jnp.arange(10_000, dtype=jnp.int64)
    h1 = hash_columns([keys])
    h2 = hash_columns([keys])
    assert np.array_equal(np.asarray(h1), np.asarray(h2))
    vn = np.asarray(vnodes_of([keys]))
    assert vn.min() >= 0 and vn.max() < VNODE_COUNT
    counts = np.bincount(vn, minlength=VNODE_COUNT)
    # roughly uniform: each vnode ~39 rows, allow wide tolerance
    assert counts.min() > 5 and counts.max() < 120


def test_hash_multi_column_and_floats():
    a = jnp.asarray([1, 1, 2], dtype=jnp.int64)
    b = jnp.asarray([1.0, 2.0, 1.0], dtype=jnp.float64)
    h = np.asarray(hash_columns([a, b]))
    assert h[0] != h[1] and h[0] != h[2]
    # -0.0 and 0.0 must hash identically
    z = np.asarray(hash_columns([jnp.asarray([0.0, -0.0])]))
    assert z[0] == z[1]


def test_vnode_mapping_uniform_and_rebalance():
    m = VnodeMapping.new_uniform(4)
    counts = np.bincount(m.owners, minlength=4)
    assert counts.tolist() == [64, 64, 64, 64]
    m2 = m.rebalance(5)
    c2 = np.bincount(m2.owners, minlength=5)
    assert sorted(c2.tolist()) == [51, 51, 51, 51, 52]
    # minimal movement: at most the vnodes needed by the new owner moved
    moved = int((m.owners != m2.owners).sum())
    assert moved == 51  # exactly the new owner's target share moved
    m3 = m2.rebalance(2)
    c3 = np.bincount(m3.owners, minlength=2)
    assert c3.tolist() == [128, 128]


def test_epoch():
    e = Epoch.from_physical(1000)
    assert e.physical_ms == 1000
    assert e.value == 1000 << 16
    e2 = e.next()
    assert e2.value > e.value
    p = EpochPair.new_initial(e)
    assert p.prev == Epoch.INVALID
    p2 = p.advance(e2)
    assert p2.prev == e and p2.curr == e2


def test_config_defaults_and_toml(tmp_path):
    from risingwave_tpu.common.config import RwConfig
    cfg = RwConfig()
    assert cfg.meta.barrier_interval_ms == 1000
    toml = tmp_path / "rw.toml"
    toml.write_text("[meta]\nbarrier_interval_ms = 250\n"
                    "[streaming]\nchunk_capacity = 1024\n")
    cfg2 = RwConfig.from_toml(str(toml),
                              overrides={"meta.checkpoint_frequency": 5})
    assert cfg2.meta.barrier_interval_ms == 250
    assert cfg2.streaming.chunk_capacity == 1024
    assert cfg2.meta.checkpoint_frequency == 5
    sp2 = cfg2.system.set("checkpoint_frequency", 10)
    assert sp2.version == cfg2.system.version + 1


def test_decimal_exact_scaled_int():
    import decimal
    from risingwave_tpu.common import DECIMAL_SCALE, decimal_to_scaled, scaled_to_decimal
    s = Schema.of(price=DataType.DECIMAL)
    c = DataChunk.from_pydict(s, {"price": ["1.01", 2, 3.555, None]})
    vals = np.asarray(c.column_values("price"))
    assert vals.dtype == np.int64
    assert vals[:3].tolist() == [10100, 20000, 35550]
    out = [r[0] for r in c.to_pylist()]
    assert out == [decimal.Decimal("1.01"), decimal.Decimal(2),
                   decimal.Decimal("3.555"), None]
    # exact money arithmetic: 0.1 + 0.2 == 0.3 (fails in float64)
    a = decimal_to_scaled("0.1") + decimal_to_scaled("0.2")
    assert scaled_to_decimal(a) == decimal.Decimal("0.3")
    assert DECIMAL_SCALE == 10_000


def test_interval_triple():
    from risingwave_tpu.common import Interval
    i = Interval(months=1, days=2, usecs=3)
    with pytest.raises(ValueError):
        i.exact_usecs()
    d = Interval.from_duration(days=1, hours=2)
    assert d.exact_usecs() == 26 * 3_600_000_000
    assert (i + Interval(months=1)).months == 2
    assert (-i).days == -2
    assert not DataType.INTERVAL.is_device
    s = Schema.of(gap=DataType.INTERVAL)
    c = DataChunk.from_pydict(s, {"gap": [d, None]})
    assert c.to_pylist() == [(d,), (None,)]


def test_hash_strings_host_vectorized():
    from risingwave_tpu.common import hash_strings_host
    vals = np.asarray(["abc", "abd", "abc", None, "", "日本語テキスト",
                       "x" * 100, "x" * 101], dtype=object)
    h = hash_strings_host(vals, 8)
    assert h.dtype == np.uint32
    assert h[0] == h[2] and h[0] != h[1]          # consistent + distinct
    assert h[3] == 0                               # null
    assert h[6] != h[7]                            # same prefix, diff length
    h2 = hash_strings_host(vals, 8)
    assert np.array_equal(h, h2)
    empty = hash_strings_host(np.asarray([], dtype=object), 0)
    assert empty.shape == (0,)


def test_column_take_host():
    s = Schema.of(x=DataType.INT64, name=DataType.VARCHAR)
    c = DataChunk.from_pydict(s, {"x": [10, 20, 30], "name": ["a", None, "c"]})
    idx = np.asarray([2, 0])
    xc = c.columns[0].take_host(idx)
    assert np.asarray(xc.values).tolist() == [30, 10]
    nc = c.columns[1].take_host(idx)
    assert np.asarray(nc.values).tolist() == ["c", "a"]


def test_from_arrays_and_empty():
    s = Schema.of(x=DataType.INT64)
    arr = jnp.arange(8, dtype=jnp.int64)
    c = DataChunk.from_arrays(s, [arr], num_rows=3)
    assert c.cardinality() == 3 and c.capacity == 8
    with pytest.raises(ValueError):
        DataChunk.from_arrays(s, [arr], num_rows=3, capacity=16)
    with pytest.raises(ValueError):
        DataChunk.from_arrays(s, [arr], num_rows=9)
    e = DataChunk.empty(s)
    assert e.cardinality() == 0
    se = StreamChunk.empty(s)
    assert isinstance(se, StreamChunk) and hasattr(se, "ops")


def test_vnode_mapping_bitmap_and_device():
    m = VnodeMapping.new_uniform(4)
    bm = m.bitmap_of(1)
    assert bm.dtype == bool and bm.sum() == 64
    assert set(np.flatnonzero(bm).tolist()) == {
        v for v in range(VNODE_COUNT) if m.owner_of(v) == 1}
    dev = m.to_device()
    assert np.array_equal(np.asarray(dev), m.owners)


def test_pluggable_clock():
    from risingwave_tpu.common import set_clock
    from risingwave_tpu.common.epoch import UNIX_RISINGWAVE_DATE_EPOCH_MS
    fixed_s = (UNIX_RISINGWAVE_DATE_EPOCH_MS + 5_000) / 1000.0
    prev = set_clock(lambda: fixed_s)
    try:
        e = Epoch.now()
        assert e.physical_ms == 5_000
        assert e.next().value == e.value + 1  # clock frozen -> seq bump
    finally:
        set_clock(prev)


def test_config_override_validation(tmp_path):
    from risingwave_tpu.common.config import RwConfig
    toml = tmp_path / "rw.toml"
    toml.write_text("")
    with pytest.raises(KeyError):
        RwConfig.from_toml(str(toml), overrides={"meta.barier_interval_ms": 1})


def test_struct_list_host_columns():
    s = Schema.of(st=DataType.STRUCT, ls=DataType.LIST)
    c = DataChunk.from_pydict(s, {"st": [(1, 2), (3, 4)],
                                  "ls": [[1], [2, 3]]})
    assert c.to_pylist() == [((1, 2), [1]), ((3, 4), [2, 3])]


def test_decimal_numpy_int_ingest_scales():
    from risingwave_tpu.common.chunk import _make_column
    col_ = _make_column(DataType.DECIMAL, np.asarray([1, 2]), 8)
    assert np.asarray(col_.values)[:2].tolist() == [10000, 20000]


# -- DECIMAL overflow detection (VERDICT r5 weak #6) ----------------------

def test_decimal_overflow_scalar_ingest():
    """decimal_to_scaled raises loudly instead of silently wrapping
    past the int64 fixed-point domain (~9.2e14 value units)."""
    import decimal

    from risingwave_tpu.common.types import (
        DecimalOverflowError, decimal_to_scaled,
    )
    assert decimal_to_scaled(9 * 10 ** 14) == 9 * 10 ** 18
    assert decimal_to_scaled(-9 * 10 ** 14) == -9 * 10 ** 18
    with pytest.raises(DecimalOverflowError, match="overflow"):
        decimal_to_scaled(10 ** 15)
    with pytest.raises(DecimalOverflowError, match="overflow"):
        decimal_to_scaled(decimal.Decimal("-1e15"))
    with pytest.raises(DecimalOverflowError, match="overflow"):
        decimal_to_scaled(1.5e15)


def test_decimal_overflow_cast_boundary():
    """Vectorized numeric→DECIMAL casts detect overflow too (the other
    ingest funnel: INSERT coercion, expression casts)."""
    from risingwave_tpu.common.types import DecimalOverflowError
    from risingwave_tpu.expr.expr import _cast_values

    ok = _cast_values(np.asarray([3, -4], dtype=np.int64),
                      DataType.INT64, DataType.DECIMAL)
    assert ok.tolist() == [30000, -40000]
    with pytest.raises(DecimalOverflowError, match="overflow"):
        _cast_values(np.asarray([10 ** 15], dtype=np.int64),
                     DataType.INT64, DataType.DECIMAL)
    with pytest.raises(DecimalOverflowError, match="overflow"):
        _cast_values(np.asarray([1e16]), DataType.FLOAT64,
                     DataType.DECIMAL)
    # non-finite floats raise too (pg: cannot convert to numeric),
    # instead of wrapping to INT64_MIN
    for v in (float("inf"), float("-inf"), float("nan")):
        with pytest.raises(DecimalOverflowError, match="overflow"):
            _cast_values(np.asarray([v]), DataType.FLOAT64,
                         DataType.DECIMAL)
    # NULL-fill zeros and ordinary floats stay fine
    assert _cast_values(np.asarray([0.0, 12.5]), DataType.FLOAT64,
                        DataType.DECIMAL).tolist() == [0, 125000]


def test_decimal_overflow_from_pydict():
    """Chunk ingest (from_pydict) funnels through decimal_to_scaled."""
    from risingwave_tpu.common.types import DecimalOverflowError
    sch = Schema.of(d=DataType.DECIMAL)
    with pytest.raises(DecimalOverflowError, match="overflow"):
        StreamChunk.from_pydict(sch, {"d": [10 ** 15]})
