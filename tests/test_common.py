"""Foundation tests: types, chunks, hashing, epochs, config."""

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common import (
    DataChunk, DataType, Epoch, EpochPair, Op, Schema, StreamChunk,
    VNODE_COUNT, hash_columns, vnodes_of,
)
from risingwave_tpu.common.chunk import next_pow2, ops_to_signs
from risingwave_tpu.common.hash import VnodeMapping
from risingwave_tpu.common.types import Field


def test_datatype_mapping():
    assert DataType.INT64.dtype == jnp.int64
    assert DataType.TIMESTAMP.dtype == jnp.int64
    assert DataType.VARCHAR.dtype is None
    assert not DataType.VARCHAR.is_device
    assert DataType.from_sql("BIGINT") == DataType.INT64
    assert DataType.from_sql("character varying") == DataType.VARCHAR


def test_schema():
    s = Schema.of(a=DataType.INT64, b=DataType.VARCHAR)
    assert len(s) == 2
    assert s.index_of("b") == 1
    assert s.select([1]).names() == ["b"]
    s2 = s.concat(Schema([Field("c", DataType.FLOAT64)]))
    assert s2.names() == ["a", "b", "c"]


def test_next_pow2():
    assert next_pow2(1) == 8
    assert next_pow2(8) == 8
    assert next_pow2(9) == 16
    assert next_pow2(4096) == 4096


def test_data_chunk_roundtrip():
    s = Schema.of(id=DataType.INT64, name=DataType.VARCHAR,
                  price=DataType.FLOAT64)
    c = DataChunk.from_pydict(
        s, {"id": [1, 2, 3], "name": ["a", "b", None], "price": [1.5, 2.5, 3.5]})
    assert c.capacity == 8
    assert c.cardinality() == 3
    assert c.to_pylist() == [(1, "a", 1.5), (2, "b", 2.5), (3, None, 3.5)]


def test_data_chunk_nulls_device():
    s = Schema.of(x=DataType.INT32)
    c = DataChunk.from_pydict(s, {"x": [5, None, 7]})
    assert c.to_pylist() == [(5,), (None,), (7,)]


def test_visibility_mask():
    s = Schema.of(x=DataType.INT64)
    c = DataChunk.from_pydict(s, {"x": [1, 2, 3, 4]})
    pred = c.column_values("x") % 2 == 0
    c2 = c.mask(pred)
    assert c2.to_pylist() == [(2,), (4,)]
    assert c.cardinality() == 4  # original untouched


def test_stream_chunk_ops_and_signs():
    s = Schema.of(x=DataType.INT64)
    c = StreamChunk.from_pydict(
        s, {"x": [1, 2, 2, 3]},
        ops=[Op.INSERT, Op.UPDATE_DELETE, Op.UPDATE_INSERT, Op.DELETE])
    recs = c.to_records()
    assert recs == [(Op.INSERT, (1,)), (Op.UPDATE_DELETE, (2,)),
                    (Op.UPDATE_INSERT, (2,)), (Op.DELETE, (3,))]
    signs = np.asarray(ops_to_signs(c.ops))[:4]
    assert signs.tolist() == [1, -1, 1, -1]


def test_stream_chunk_project():
    s = Schema.of(a=DataType.INT64, b=DataType.INT64)
    c = StreamChunk.from_pydict(s, {"a": [1], "b": [2]})
    p = c.project([1])
    assert p.schema.names() == ["b"]
    assert p.to_records() == [(Op.INSERT, (2,))]


def test_hash_consistency_and_spread():
    keys = jnp.arange(10_000, dtype=jnp.int64)
    h1 = hash_columns([keys])
    h2 = hash_columns([keys])
    assert np.array_equal(np.asarray(h1), np.asarray(h2))
    vn = np.asarray(vnodes_of([keys]))
    assert vn.min() >= 0 and vn.max() < VNODE_COUNT
    counts = np.bincount(vn, minlength=VNODE_COUNT)
    # roughly uniform: each vnode ~39 rows, allow wide tolerance
    assert counts.min() > 5 and counts.max() < 120


def test_hash_multi_column_and_floats():
    a = jnp.asarray([1, 1, 2], dtype=jnp.int64)
    b = jnp.asarray([1.0, 2.0, 1.0], dtype=jnp.float64)
    h = np.asarray(hash_columns([a, b]))
    assert h[0] != h[1] and h[0] != h[2]
    # -0.0 and 0.0 must hash identically
    z = np.asarray(hash_columns([jnp.asarray([0.0, -0.0])]))
    assert z[0] == z[1]


def test_vnode_mapping_uniform_and_rebalance():
    m = VnodeMapping.new_uniform(4)
    counts = np.bincount(m.owners, minlength=4)
    assert counts.tolist() == [64, 64, 64, 64]
    m2 = m.rebalance(5)
    c2 = np.bincount(m2.owners, minlength=5)
    assert sorted(c2.tolist()) == [51, 51, 51, 51, 52]
    # minimal movement: at most the vnodes needed by the new owner moved
    moved = int((m.owners != m2.owners).sum())
    assert moved == 51  # exactly the new owner's target share moved
    m3 = m2.rebalance(2)
    c3 = np.bincount(m3.owners, minlength=2)
    assert c3.tolist() == [128, 128]


def test_epoch():
    e = Epoch.from_physical(1000)
    assert e.physical_ms == 1000
    assert e.value == 1000 << 16
    e2 = e.next()
    assert e2.value > e.value
    p = EpochPair.new_initial(e)
    assert p.prev == Epoch.INVALID
    p2 = p.advance(e2)
    assert p2.prev == e and p2.curr == e2


def test_config_defaults_and_toml(tmp_path):
    from risingwave_tpu.common.config import RwConfig
    cfg = RwConfig()
    assert cfg.meta.barrier_interval_ms == 1000
    toml = tmp_path / "rw.toml"
    toml.write_text("[meta]\nbarrier_interval_ms = 250\n"
                    "[streaming]\nchunk_capacity = 1024\n")
    cfg2 = RwConfig.from_toml(str(toml),
                              overrides={"meta.checkpoint_frequency": 5})
    assert cfg2.meta.barrier_interval_ms == 250
    assert cfg2.streaming.chunk_capacity == 1024
    assert cfg2.meta.checkpoint_frequency == 5
    sp2 = cfg2.system.set("checkpoint_frequency", 10)
    assert sp2.version == cfg2.system.version + 1
