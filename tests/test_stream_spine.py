"""Executor-spine tests: channels, merge alignment, dispatch, actors.

Mirrors the reference's executor-test stance (SURVEY §4): MockSource feeds
hand-built chunks/barriers; outputs asserted chunk-by-chunk.
"""

import asyncio

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.expr import col, lit
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream import (
    Barrier, BarrierKind, LocalBarrierManager, MergeExecutor, StopMutation,
    Watermark, channel_for_test, is_barrier, is_chunk,
)
from risingwave_tpu.stream.actor import Actor
from risingwave_tpu.stream.dispatch import (
    HashDispatcher, Output, SimpleDispatcher,
)
from risingwave_tpu.stream.executor import ExecutorInfo
from risingwave_tpu.stream.executors import (
    FilterExecutor, MaterializeExecutor, MockSource, ProjectExecutor,
    ReceiverExecutor,
)
from risingwave_tpu.stream.executors.test_utils import collect_until_n_barriers

SCHEMA = Schema.of(k=DataType.INT64, v=DataType.INT64)


def run(coro):
    return asyncio.run(coro)


def barrier(n: int, mutation=None, kind=BarrierKind.CHECKPOINT) -> Barrier:
    curr, prev = Epoch.from_physical(n), (
        Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID)
    return Barrier(EpochPair(curr, prev), kind, mutation)


def chunk(ks, vs, ops=None) -> StreamChunk:
    return StreamChunk.from_pydict(SCHEMA, {"k": ks, "v": vs}, ops=ops)


def visible_rows(c: StreamChunk):
    return c.to_records()


# ---------------------------------------------------------------------------


def test_channel_roundtrip_and_close():
    async def go():
        tx, rx = channel_for_test()
        await tx.send(chunk([1], [2]))
        await tx.send(barrier(1))
        tx.close()
        m1 = await rx.recv()
        assert is_chunk(m1)
        m2 = await rx.recv()
        assert is_barrier(m2)
        from risingwave_tpu.stream import ChannelClosed
        with pytest.raises(ChannelClosed):
            await rx.recv()
    run(go())


def test_channel_backpressure_releases_on_recv():
    async def go():
        from risingwave_tpu.stream.exchange import channel
        tx, rx = channel(chunk_permits=16, barrier_permits=2,
                         max_chunk_cost=8)
        # each 8-capacity chunk costs 8: two fit, third must wait
        for _ in range(2):
            await tx.send(chunk([1], [2]))
        third = asyncio.ensure_future(tx.send(chunk([3], [4])))
        await asyncio.sleep(0.01)
        assert not third.done(), "third send should be blocked on permits"
        await rx.recv()
        await asyncio.wait_for(third, 1.0)
    run(go())


def test_project_filter_chain():
    async def go():
        msgs = [
            barrier(1),
            chunk([1, 2, 3, 4], [10, 20, 30, 40]),
            barrier(2),
        ]
        src = MockSource(SCHEMA, msgs)
        s = src.schema
        proj = ProjectExecutor(
            src, [col(s, "k"), col(s, "v") * lit(2)], names=["k", "v2"])
        filt = FilterExecutor(proj, col(proj.schema, "v2") > lit(40))
        out = await collect_until_n_barriers(filt, 2)
        chunks = [m for m in out if is_chunk(m)]
        assert len(chunks) == 1
        assert chunks[0].to_records() == [
            (Op.INSERT, (3, 60)), (Op.INSERT, (4, 80))]
    run(go())


def test_filter_update_pair_degradation():
    async def go():
        # pk 1: v 10 -> 60 (new half passes only) ; pk 2: v 70 -> 20 (old only)
        c = chunk([1, 1, 2, 2], [10, 60, 70, 20],
                  ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT,
                       Op.UPDATE_DELETE, Op.UPDATE_INSERT])
        src = MockSource(SCHEMA, [barrier(1), c, barrier(2)])
        filt = FilterExecutor(src, col(SCHEMA, "v") > lit(40))
        out = await collect_until_n_barriers(filt, 2)
        recs = [m for m in out if is_chunk(m)][0].to_records()
        assert recs == [(Op.INSERT, (1, 60)), (Op.DELETE, (2, 70))]
    run(go())


def test_merge_aligns_barriers():
    async def go():
        tx1, rx1 = channel_for_test()
        tx2, rx2 = channel_for_test()
        merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"), [rx1, rx2])

        async def feed():
            await tx1.send(chunk([1], [1]))
            await tx1.send(barrier(1))
            await tx1.send(chunk([3], [3]))   # epoch-2 data on input 1
            await asyncio.sleep(0.01)
            await tx2.send(chunk([2], [2]))
            await tx2.send(barrier(1))
            await tx2.send(barrier(2))        # input 2 races ahead
            await tx1.send(barrier(2))
            tx1.close()
            tx2.close()

        feeder = asyncio.ensure_future(feed())
        out = await collect_until_n_barriers(merge, 2)
        await feeder
        kinds = ["B" if is_barrier(m) else "C" for m in out]
        # both data chunks precede the first aligned barrier; the epoch-2
        # chunk comes after it
        assert kinds == ["C", "C", "B", "C", "B"]
        b1 = [m for m in out if is_barrier(m)][0]
        assert b1.epoch.curr == Epoch.from_physical(1)
    run(go())


def test_merge_blocks_fast_input_until_alignment():
    async def go():
        tx1, rx1 = channel_for_test()
        tx2, rx2 = channel_for_test()
        merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"), [rx1, rx2])
        got = []

        async def consume():
            async for m in merge.execute():
                got.append(m)
                if is_barrier(m) and m.epoch.curr == Epoch.from_physical(2):
                    return

        task = asyncio.ensure_future(consume())
        await tx1.send(barrier(1))
        # input 1 sends epoch-2 data + barrier before input 2 says anything
        await tx1.send(chunk([9], [9]))
        await tx1.send(barrier(2))
        await asyncio.sleep(0.05)
        # nothing may be emitted yet: input 2 hasn't reached barrier 1
        assert got == []
        await tx2.send(barrier(1))
        await tx2.send(barrier(2))
        await asyncio.wait_for(task, 2.0)
        kinds = ["B" if is_barrier(m) else "C" for m in got]
        assert kinds == ["B", "C", "B"]
    run(go())


def test_hash_dispatch_partition_is_exhaustive_and_consistent():
    async def go():
        n_out = 3
        chans = [channel_for_test() for _ in range(n_out)]
        outputs = [Output(i, tx) for i, (tx, _) in enumerate(chans)]
        disp = HashDispatcher(outputs, dist_key_indices=[0])
        ks = list(range(40)) * 2  # duplicate keys must route identically
        c = chunk(ks, [i * 10 for i in range(80)])
        await disp.dispatch_data(c)
        seen = {}
        total = 0
        for i, (_, rx) in enumerate(chans):
            sub = await rx.recv()
            recs = sub.to_records()
            total += len(recs)
            for _, (k, v) in recs:
                assert seen.setdefault(k, i) == i, \
                    f"key {k} routed to two outputs"
        assert total == 80
    run(go())


def test_hash_dispatch_update_pair_degraded_across_outputs():
    async def go():
        chans = [channel_for_test() for _ in range(2)]
        outputs = [Output(i, tx) for i, (tx, _) in enumerate(chans)]
        disp = HashDispatcher(outputs, dist_key_indices=[0])
        # find two keys routed to different outputs
        probe = chunk(list(range(16)), [0] * 16)
        owner = disp._route(probe)
        k_a = 0
        k_b = next(k for k in range(1, 16) if owner[k] != owner[k_a])
        c = chunk([k_a, k_b], [1, 2],
                  ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT])
        await disp.dispatch_data(c)
        recs = []
        for _, rx in chans:
            recs += (await rx.recv()).to_records()
        ops = sorted(op for op, _ in recs)
        assert ops == [Op.INSERT, Op.DELETE]  # degraded, atomic halves
    run(go())


def test_materialize_commits_on_barrier():
    async def go():
        store = MemoryStateStore()
        table = StateTable(1, SCHEMA, pk_indices=[0], store=store)
        msgs = [
            barrier(1, kind=BarrierKind.INITIAL),
            chunk([1, 2], [10, 20]),
            barrier(2),
            chunk([1], [10], ops=[Op.DELETE]),
            chunk([3], [30]),
            barrier(3),
        ]
        mat = MaterializeExecutor(MockSource(SCHEMA, msgs), table)
        await collect_until_n_barriers(mat, 3)
        store.seal_epoch(Epoch.from_physical(2).value)
        assert table.get_row((1,)) is None
        assert table.get_row((2,)) == (2, 20)
        assert table.get_row((3,)) == (3, 30)
        rows = [r for _, r in table.iter_rows()]
        assert rows == [(2, 20), (3, 30)]
    run(go())


def test_actor_reports_barrier_to_manager():
    async def go():
        mgr = LocalBarrierManager()
        tx, rx = channel_for_test()
        src = ReceiverExecutor(ExecutorInfo(SCHEMA, [], "Recv"), rx,
                               actor_id=7)
        out_tx, out_rx = channel_for_test()
        actor = Actor(7, src, [SimpleDispatcher(Output(99, out_tx))],
                      barrier_manager=mgr)
        mgr.register_sender(7, tx)
        mgr.set_expected_actors([7])
        task = actor.spawn()

        b1 = barrier(1, kind=BarrierKind.INITIAL)
        await mgr.send_barrier(b1)
        done = await asyncio.wait_for(
            mgr.await_epoch_complete(b1.epoch.curr.value), 2.0)
        assert done.epoch == b1.epoch

        b2 = barrier(2, mutation=StopMutation(frozenset({7})))
        await mgr.send_barrier(b2)
        await asyncio.wait_for(
            mgr.await_epoch_complete(b2.epoch.curr.value), 2.0)
        await asyncio.wait_for(task, 2.0)
        assert actor.failure is None
        # downstream saw both barriers
        msgs = []
        while True:
            try:
                msgs.append(await asyncio.wait_for(out_rx.recv(), 0.1))
            except Exception:
                break
        assert [m.epoch.curr.physical_ms for m in msgs if is_barrier(m)] \
            == [1, 2]
    run(go())


def test_watermark_min_alignment_in_merge():
    async def go():
        tx1, rx1 = channel_for_test()
        tx2, rx2 = channel_for_test()
        merge = MergeExecutor(ExecutorInfo(SCHEMA, [], "Merge"), [rx1, rx2])

        async def feed():
            await tx1.send(Watermark(0, DataType.INT64, 100))
            await tx2.send(Watermark(0, DataType.INT64, 50))
            await tx1.send(barrier(1))
            await tx2.send(barrier(1))
            await tx1.send(Watermark(0, DataType.INT64, 120))
            await tx2.send(Watermark(0, DataType.INT64, 110))
            await tx1.send(barrier(2))
            await tx2.send(barrier(2))
            tx1.close()
            tx2.close()

        feeder = asyncio.ensure_future(feed())
        out = await collect_until_n_barriers(merge, 2)
        await feeder
        wms = [m.value for m in out if isinstance(m, Watermark)]
        assert wms == [50, 110]  # min across inputs, monotonic
    run(go())
