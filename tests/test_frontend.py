"""SQL frontend end-to-end: DDL deploys live pipelines; SELECT reads
committed snapshots. Mirrors the reference's e2e .slt stance (SURVEY
§4) with the nexmark/datagen corpus, in-process."""

import asyncio

import pytest

from risingwave_tpu.frontend import Frontend
from risingwave_tpu.frontend.parser import ParseError, parse


# -- parser unit ----------------------------------------------------------


def test_parser_select_shapes():
    s = parse("SELECT a, b AS bb, 0.908 * price FROM bid "
              "WHERE price > 100 GROUP BY a ORDER BY a DESC LIMIT 5")
    assert len(s.projections) == 3
    assert s.projections[1][1] == "bb"
    assert s.where is not None
    assert s.order_by[0][1] is True
    assert s.limit == 5

    s = parse("SELECT window_start, MAX(price) FROM TUMBLE(bid, "
              "date_time, INTERVAL '10' SECOND) GROUP BY window_start")
    from risingwave_tpu.frontend.ast import Tumble
    assert isinstance(s.from_item, Tumble)
    assert s.from_item.window_usecs == 10_000_000

    c = parse("CREATE SOURCE b WITH (connector='nexmark', "
              "nexmark.table.type='bid', nexmark.event.num=1000)")
    assert c.options["connector"] == "nexmark"
    assert c.options["nexmark.event.num"] == "1000"

    with pytest.raises(ParseError):
        parse("SELECT FROM x")
    with pytest.raises(ParseError):
        parse("CREATE MATERIALIZED VIEW v SELECT 1")   # missing AS


# -- end-to-end -----------------------------------------------------------


NEXMARK_BID = ("CREATE SOURCE bid WITH (connector='nexmark', "
               "nexmark.table.type='bid', nexmark.event.num=20000, "
               "nexmark.max.chunk.size=1024, "
               "nexmark.min.event.gap.in.ns=100000000)")


def test_q1_shaped_mv_sql():
    async def run():
        fe = Frontend()
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE MATERIALIZED VIEW q1 AS SELECT auction, bidder, "
            "0.908 * price AS price, date_time FROM bid")
        await fe.step(6)
        rows = await fe.execute("SELECT * FROM q1")
        n = await fe.execute("SELECT COUNT(*) AS n FROM q1")
        await fe.close()
        return rows, n

    rows, n = asyncio.run(run())
    assert len(rows) > 1000
    assert n[0][0] == len(rows)
    # 0.908 * price is DECIMAL-scaled; spot-check a row's shape
    auction, bidder, price, ts = rows[0][:4]
    assert isinstance(auction, int) and isinstance(ts, int)


def test_q7_shaped_mv_sql_matches_batch_recompute():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE MATERIALIZED VIEW raw AS SELECT price, date_time "
            "FROM bid")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q7 AS SELECT window_start, "
            "MAX(price) AS max_price, COUNT(*) AS cnt FROM TUMBLE(bid, "
            "date_time, INTERVAL '10' SECOND) GROUP BY window_start")
        await fe.step(8)
        mv = await fe.execute(
            "SELECT window_start, max_price, cnt FROM q7 "
            "ORDER BY window_start")
        # batch recompute over the raw MV must agree (same snapshot)
        recompute = await fe.execute(
            "SELECT tumble_start(date_time, INTERVAL '10' SECOND) AS w, "
            "MAX(price) AS m, COUNT(*) AS c FROM raw GROUP BY "
            "tumble_start(date_time, INTERVAL '10' SECOND) ORDER BY w")
        await fe.close()
        return mv, recompute

    mv, recompute = asyncio.run(run())
    assert len(mv) >= 2
    assert mv == recompute


def test_q8_shaped_join_sql():
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            "CREATE SOURCE person WITH (connector='nexmark', "
            "nexmark.table.type='person', nexmark.event.num=20000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE SOURCE auction WITH (connector='nexmark', "
            "nexmark.table.type='auction', nexmark.event.num=20000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q8 AS SELECT p.id, p.name, "
            "a.seller FROM person AS p JOIN auction AS a "
            "ON p.id = a.seller")
        await fe.step(8)
        rows = await fe.execute("SELECT * FROM q8")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    assert len(rows) > 0
    for pid, _name, seller in {r[:3] for r in rows}:
        assert pid == seller


def test_datagen_source_and_scalar_select():
    async def run():
        fe = Frontend()
        await fe.execute(
            "CREATE SOURCE g WITH (connector='datagen', "
            "fields.id.type='bigint', fields.id.kind='sequence', "
            "fields.id.start=0, fields.id.end=1000000, "
            "fields.v.type='double', fields.v.kind='random', "
            "fields.v.min=0, fields.v.max=10, "
            "datagen.rows.per.chunk=500, datagen.event.num=2000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW dg AS SELECT id, v FROM g "
            "WHERE id % 2 = 0")
        await fe.step(6)
        cnt = await fe.execute("SELECT COUNT(*) AS n, MIN(id) AS mn, "
                               "MAX(id) AS mx FROM dg")
        scalar = await fe.execute("SELECT 1 + 2 AS three, 'x' AS s")
        shows = await fe.execute("SHOW MATERIALIZED VIEWS")
        await fe.close()
        return cnt, scalar, shows

    cnt, scalar, shows = asyncio.run(run())
    assert cnt == [(1000, 0, 1998)]
    assert scalar == [(3, "x")]
    assert shows == [("dg",)]


def test_drop_mv_stops_pipeline():
    async def run():
        fe = Frontend()
        await fe.execute(NEXMARK_BID)
        await fe.execute("CREATE MATERIALIZED VIEW m AS "
                         "SELECT auction FROM bid")
        await fe.step(2)
        await fe.execute("DROP MATERIALIZED VIEW m")
        assert await fe.execute("SHOW MATERIALIZED VIEWS") == []
        # barrier loop still healthy with zero actors? create another
        await fe.execute("CREATE MATERIALIZED VIEW m2 AS "
                         "SELECT bidder FROM bid")
        await fe.step(2)
        rows = await fe.execute("SELECT COUNT(*) AS n FROM m2")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    assert rows[0][0] > 0


def test_q0_q2_q3_shaped_queries():
    """q0 passthrough, q2 modulo filter, q3 filtered join — the rest of
    the easily-expressible nexmark corpus (e2e_test/streaming/nexmark)."""
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE SOURCE person WITH (connector='nexmark', "
            "nexmark.table.type='person', nexmark.event.num=20000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE SOURCE auction WITH (connector='nexmark', "
            "nexmark.table.type='auction', nexmark.event.num=20000, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute("CREATE MATERIALIZED VIEW q0 AS "
                         "SELECT * FROM bid")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q2 AS SELECT auction, price "
            "FROM bid WHERE auction % 123 = 0")
        await fe.execute(
            "CREATE MATERIALIZED VIEW q3 AS SELECT p.name, p.city, "
            "p.state, a.id FROM auction AS a JOIN person AS p "
            "ON a.seller = p.id "
            "WHERE a.category = 10 AND (p.state = 'OR' OR p.state = 'ID' "
            "OR p.state = 'CA')")
        await fe.step(8)
        q0 = await fe.execute("SELECT COUNT(*) AS n FROM q0")
        q2 = await fe.execute("SELECT auction, price FROM q2")
        q3 = await fe.execute("SELECT * FROM q3")
        await fe.close()
        return q0, q2, q3

    q0, q2, q3 = asyncio.run(run())
    assert q0[0][0] == 20000 * 46 // 50          # all bids materialized
    assert len(q2) > 0
    assert all(a % 123 == 0 for a, _p in q2)
    assert len(q3) > 0
    # join MVs carry trailing _row_id pk cols; state is column 2
    assert all(row[2] in ("OR", "ID", "CA") for row in q3)


def test_avg_and_topn_mv():
    """AVG (bind-time sum/count rewrite) + ORDER BY/LIMIT MVs (streaming
    TopN) — q5-ish 'hottest items' shape."""
    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE MATERIALIZED VIEW per_auction AS SELECT auction, "
            "COUNT(*) AS bids, AVG(price) AS avg_price FROM bid "
            "GROUP BY auction")
        await fe.execute(
            "CREATE MATERIALIZED VIEW top3 AS SELECT auction, "
            "COUNT(*) AS bids FROM bid GROUP BY auction "
            "ORDER BY bids DESC, auction LIMIT 3")
        await fe.step(10)
        pa = await fe.execute(
            "SELECT auction, bids, avg_price FROM per_auction")
        top3 = await fe.execute("SELECT auction, bids FROM top3 "
                                "ORDER BY bids DESC, auction")
        # batch recompute of the same top-3 over the full agg MV
        want = await fe.execute(
            "SELECT auction, bids FROM per_auction "
            "ORDER BY bids DESC, auction LIMIT 3")
        await fe.close()
        return pa, top3, want

    pa, top3, want = asyncio.run(run())
    assert len(pa) > 100
    for _a, bids, avg_price in pa[:50]:
        assert isinstance(avg_price, float) and avg_price > 0
    assert top3 == want and len(top3) == 3


def test_create_sink_to_file(tmp_path):
    """CREATE SINK AS SELECT streams a changelog to a file writer with
    epoch framing; DROP SINK stops the job."""
    import json
    path = str(tmp_path / "out.jsonl")

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(NEXMARK_BID)
        await fe.execute(
            "CREATE SINK s AS SELECT auction, price FROM bid "
            f"WHERE price > 5000000 WITH (connector='file', "
            f"path='{path}')")
        await fe.step(4)
        shows = await fe.execute("SHOW SINKS")
        await fe.execute("DROP SINK s")
        shows_after = await fe.execute("SHOW SINKS")
        await fe.close()
        return shows, shows_after

    shows, shows_after = asyncio.run(run())
    assert shows == [("s",)] and shows_after == []
    with open(path) as f:
        lines = [json.loads(x) for x in f]
    rows = [x["row"] for x in lines if "row" in x]
    epochs = [x["epoch"] for x in lines if "epoch" in x]
    assert len(rows) > 100 and len(epochs) >= 3
    assert all(r[1] > 5000000 for r in rows)


def test_failed_create_sink_does_not_wedge_barriers():
    """A CREATE SINK with a bad connector must fail cleanly BEFORE any
    barrier sender registers — an orphaned sender channel would wedge
    every later barrier once its permits ran out."""
    async def run():
        fe = Frontend(min_chunks=2)
        await fe.execute(NEXMARK_BID)
        with pytest.raises(Exception, match="unknown sink connector"):
            await fe.execute("CREATE SINK bad AS SELECT auction FROM "
                             "bid WITH (connector='kafka')")
        # cluster must still make progress: deploy a real MV and step
        # well past the 64-permit barrier budget
        await fe.execute("CREATE MATERIALIZED VIEW m AS "
                         "SELECT auction FROM bid")
        for _ in range(70):
            await fe.step(1)
        n = await fe.execute("SELECT COUNT(*) AS n FROM m")
        await fe.close()
        return n

    n = asyncio.run(run())
    assert n[0][0] > 0


def test_left_outer_join_sql():
    """LEFT OUTER JOIN through SQL: unmatched left rows appear with
    NULLs and retract when a match arrives."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(
            "CREATE SOURCE person WITH (connector='nexmark', "
            "nexmark.table.type='person', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await f.execute(
            "CREATE SOURCE auction WITH (connector='nexmark', "
            "nexmark.table.type='auction', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await f.execute(
            "CREATE MATERIALIZED VIEW lo AS SELECT p.id, a.seller "
            "FROM person AS p LEFT OUTER JOIN auction AS a "
            "ON p.id = a.seller")
        await f.execute(
            "CREATE MATERIALIZED VIEW inner_v AS SELECT p.id, a.seller "
            "FROM person AS p JOIN auction AS a ON p.id = a.seller")
        for _ in range(40):
            await f.step()
        lo = await f.execute("SELECT * FROM lo")
        iv = await f.execute("SELECT * FROM inner_v")
        await f.close()
        return lo, iv

    lo, iv = asyncio.run(main())
    from collections import Counter
    # hidden row-id pk columns differ between plans: compare the
    # SELECTed columns only, as multisets
    matched = Counter(r[:2] for r in lo if r[1] is not None)
    padded = [r[:2] for r in lo if r[1] is None]
    assert matched == Counter(r[:2] for r in iv)   # matched == inner
    assert padded                              # some persons never sold
    matched_ids = {r[0] for r in matched}
    assert all(r[0] not in matched_ids for r in padded)


def test_count_distinct_sql():
    """count(DISTINCT x) / sum(DISTINCT x) through SQL, streaming MV vs
    batch recompute over the same data (distinct.rs parity)."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def main():
        f = Frontend(rate_limit=4)
        await f.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000, "
            "nexmark.max.chunk.size=128)")
        await f.execute(
            "CREATE MATERIALIZED VIEW raw AS SELECT auction, bidder "
            "FROM bid")
        await f.execute(
            "CREATE MATERIALIZED VIEW d AS SELECT auction, "
            "count(DISTINCT bidder) AS db, count(bidder) AS b "
            "FROM bid GROUP BY auction")
        for _ in range(30):
            await f.step()
        # same committed snapshot: streaming MV vs batch recompute
        got = sorted(await f.execute("SELECT * FROM d"))
        want = sorted(await f.execute(
            "SELECT auction, count(DISTINCT bidder) AS db, "
            "count(bidder) AS b FROM raw GROUP BY auction"))
        await f.close()
        return got, want

    got, want = asyncio.run(main())
    assert got == want
    assert any(r[1] < r[2] for r in got)   # dedup actually differs


def test_failed_create_mv_leaks_nothing():
    """A CREATE whose planning fails (bind error after sources were
    registered) must not wedge later barrier rounds (r3 review)."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(NEXMARK_BID)
        with pytest.raises(Exception):
            await f.execute(
                "CREATE MATERIALIZED VIEW bad AS SELECT nonexistent "
                "FROM bid")
        # pipeline still healthy: a real MV deploys and barriers flow
        await f.execute(
            "CREATE MATERIALIZED VIEW ok AS SELECT auction FROM bid")
        for _ in range(12):
            await asyncio.wait_for(f.step(), timeout=10)
        n = (await f.execute("SELECT count(*) FROM ok"))[0][0]
        await f.close()
        return n

    assert asyncio.run(main()) > 0


def test_outer_join_where_is_not_pushed_below_padded_side():
    """WHERE on the null-padded side of a LEFT JOIN must filter AFTER
    the join (pushing it below changes results — r3 review)."""
    import asyncio

    from risingwave_tpu.frontend.session import Frontend

    async def main():
        f = Frontend(rate_limit=2)
        await f.execute(
            "CREATE SOURCE person WITH (connector='nexmark', "
            "nexmark.table.type='person', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await f.execute(
            "CREATE SOURCE auction WITH (connector='nexmark', "
            "nexmark.table.type='auction', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await f.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT p.id, a.seller "
            "FROM person AS p LEFT OUTER JOIN auction AS a "
            "ON p.id = a.seller WHERE a.seller > 0")
        for _ in range(25):
            await f.step()
        rows = await f.execute("SELECT * FROM v")
        await f.close()
        return rows

    rows = asyncio.run(main())
    # filter-after-join: NULL-padded rows fail a.seller > 0 and are
    # dropped — pushing below the join would have KEPT them
    assert rows
    assert all(r[1] is not None for r in rows)


def test_group_by_over_retracting_join_oracle():
    """GROUP BY over an OUTER join (a retraction-producing upstream)
    must be oracle-correct — the planner derives append-only-ness
    instead of assuming it (VERDICT r3 #7). The left-outer NULL-padding
    flips (padded row retracted when a match arrives) exercise DELETE
    handling plus retractable MIN/MAX via the minput path."""
    import numpy as np

    from risingwave_tpu.connectors.nexmark import gen_auctions, gen_persons, NexmarkConfig

    n_events = 20000

    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            "CREATE SOURCE person WITH (connector='nexmark', "
            f"nexmark.table.type='person', nexmark.event.num={n_events}, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE SOURCE auction WITH (connector='nexmark', "
            f"nexmark.table.type='auction', nexmark.event.num={n_events}, "
            "nexmark.min.event.gap.in.ns=100000000)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW g AS SELECT p.id, count(*) AS c, "
            "max(a.category) AS mc FROM person AS p LEFT JOIN auction "
            "AS a ON p.id = a.seller GROUP BY p.id")
        for _ in range(16):
            await fe.step()
        rows = await fe.execute("SELECT * FROM g")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    # oracle: recompute LEFT JOIN + GROUP BY from the generators
    cfg_p = NexmarkConfig(table_type="person", event_num=n_events,
                          min_event_gap_in_ns=100_000_000)
    cfg_a = NexmarkConfig(table_type="auction", event_num=n_events,
                          min_event_gap_in_ns=100_000_000)
    n_p, n_a = n_events // 50, n_events * 3 // 50
    persons = gen_persons(np.arange(n_p, dtype=np.int64), cfg_p)
    auctions = gen_auctions(np.arange(n_a, dtype=np.int64), cfg_a)
    by_seller = {}
    for s, cat in zip(auctions["seller"].tolist(),
                      auctions["category"].tolist()):
        by_seller.setdefault(s, []).append(cat)
    want = {}
    for pid in persons["id"].tolist():
        cats = by_seller.get(pid)
        if cats:
            base = want.get(pid, (0, None))
            want[pid] = (base[0] + len(cats),
                         max(cats + ([base[1]] if base[1] is not None
                                     else [])))
        else:
            c, m = want.get(pid, (0, None))
            want[pid] = (c + 1, m)
    got = {r[0]: (r[1], r[2]) for r in rows}
    assert got == want, (len(got), len(want))
    assert any(m is None for _c, m in want.values()), \
        "test needs unmatched persons to exercise NULL-padding"
    assert any(m is not None for _c, m in want.values()), \
        "test needs matched persons to exercise padded-row retraction"


def test_group_by_over_retracting_mv_histogram():
    """GROUP BY over an MV whose rows UPDATE (count histogram over a
    count MV): every upstream update retracts a real group member, so
    a hardcoded append-only agg would overcount (VERDICT r3 #7)."""
    from collections import Counter

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=6000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m1 AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        await fe.execute(
            "CREATE MATERIALIZED VIEW m2 AS SELECT c, count(*) AS n, "
            "min(auction) AS ma FROM m1 GROUP BY c")
        for _ in range(30):
            await fe.step()
        m1 = await fe.execute("SELECT * FROM m1")
        m2 = await fe.execute("SELECT * FROM m2")
        await fe.close()
        return m1, m2

    m1, m2 = asyncio.run(run())
    want_n = Counter(c for _a, c in m1)
    want_ma = {}
    for a, c in m1:
        want_ma[c] = min(want_ma.get(c, a), a)
    got = {c: (n, ma) for c, n, ma in m2}
    assert got == {c: (n, want_ma[c]) for c, n in want_n.items()}
    assert len(m1) > 100     # enough churn to have retracted members


def test_hop_window_sql_oracle():
    """HOP(...) in FROM: sliding windows from SQL (hop_window.rs via
    the SQL surface — VERDICT r3 #9: the executor existed, the parser
    could not express it)."""
    from collections import Counter

    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=3000, "
            "nexmark.max.chunk.size=256)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW h AS SELECT auction, "
            "window_start, count(*) AS c FROM HOP(bid, date_time, "
            "INTERVAL '2' SECOND, INTERVAL '10' SECOND) "
            "GROUP BY auction, window_start")
        for _ in range(10):
            await fe.step()
        rows = await fe.execute("SELECT * FROM h")
        await fe.close()
        return rows

    rows = asyncio.run(run())
    cfg = NexmarkConfig(event_num=3000, max_chunk_size=256)
    bids = gen_bids(np.arange(3000 * 46 // 50, dtype=np.int64), cfg)
    want = Counter()
    S, Z = 2_000_000, 10_000_000
    for a, t in zip(bids["auction"].tolist(),
                    bids["date_time"].tolist()):
        base = t // S * S
        for i in range(Z // S):
            want[(a, base - i * S)] += 1
    got = Counter({(a, w): c for a, w, c in rows})
    assert got == want


def test_emit_on_window_close_sql():
    """EOWC (sort_buffer.rs / AggGroup::create_eowc semantics): each
    window emits ONCE when the watermark passes it, oracle-exact, and
    never mutates after release. Watermarks come from the SQL surface
    (WITH watermark.column/watermark.delay)."""
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids

    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=4000, "
            "nexmark.max.chunk.size=256, "
            "watermark.column='date_time', "
            "watermark.delay='0 seconds')")
        await fe.execute(
            "CREATE MATERIALIZED VIEW w AS SELECT window_start, "
            "max(price) AS m, count(*) AS c FROM TUMBLE(bid, "
            "date_time, INTERVAL '100' MILLISECONDS) GROUP BY "
            "window_start EMIT ON WINDOW CLOSE")
        views = []
        for _ in range(12):
            await fe.step()
            views.append(sorted(await fe.execute("SELECT * FROM w")))
        await fe.close()
        return views

    views = asyncio.run(run())
    seen = {}
    for v in views:
        for w, m, c in v:
            assert seen.get(w, (m, c)) == (m, c), "released row mutated"
            seen[w] = (m, c)
    cfg = NexmarkConfig(event_num=4000, max_chunk_size=256)
    bids = gen_bids(np.arange(4000 * 46 // 50, dtype=np.int64), cfg)
    want = {}
    W = 100_000
    for t, p in zip(bids["date_time"].tolist(),
                    bids["price"].tolist()):
        w0 = t // W * W
        mx, c = want.get(w0, (0, 0))
        want[w0] = (max(mx, p), c + 1)
    assert all(want[w] == v for w, v in seen.items())
    # every closed window released; the open tail window is withheld
    assert len(seen) == len(want) - 1


def test_eowc_without_watermark_rejected():
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=1000)")
        with pytest.raises(Exception, match="WINDOW CLOSE"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW w AS SELECT auction FROM "
                "bid EMIT ON WINDOW CLOSE")
        await fe.close()

    asyncio.run(run())


def test_ctl_verbs(tmp_path):
    """risectl analog: offline inspection + backup ops via the CLI."""
    import subprocess
    import sys

    d = str(tmp_path / "data")
    t = str(tmp_path / "restored")

    async def build():
        from risingwave_tpu.storage.hummock import HummockLite
        from risingwave_tpu.storage.object_store import (
            LocalFsObjectStore,
        )
        fe = Frontend(HummockLite(LocalFsObjectStore(d)), rate_limit=2,
                      min_chunks=2)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=2000, "
            "nexmark.max.chunk.size=128)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW v AS SELECT auction, count(*) "
            "AS c FROM bid GROUP BY auction")
        for _ in range(4):
            await fe.step()
        await fe.close()

    asyncio.run(build())

    def ctl(*argv):
        r = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl",
             "--data-dir", d, *argv],
            capture_output=True, text=True, timeout=120,
            env={**__import__("os").environ, "JAX_PLATFORMS": "cpu"})
        assert r.returncode == 0, r.stderr[-500:]
        return r.stdout

    assert "CREATE MATERIALIZED VIEW" in ctl("meta", "catalog")
    assert '"l0"' in ctl("hummock", "version") or \
        '"l1"' in ctl("hummock", "version")
    assert ".sst" in ctl("hummock", "list-ssts")
    scan = ctl("table", "scan", "v", "-n", "5")
    assert len(scan.strip().splitlines()) == 5
    bid = ctl("backup", "create").strip()
    assert bid in ctl("backup", "list")
    ctl("backup", "restore", bid, "--target", t)
    import os
    assert os.path.exists(os.path.join(t, "meta", "ddl.json"))


def test_ctl_read_only_and_validation(tmp_path):
    import os
    import subprocess
    import sys

    env = {**os.environ, "JAX_PLATFORMS": "cpu"}

    def ctl(*argv, expect=0):
        r = subprocess.run(
            [sys.executable, "-m", "risingwave_tpu", "ctl", *argv],
            capture_output=True, text=True, timeout=120, env=env)
        assert r.returncode == expect, (r.returncode, r.stderr[-300:])
        return r

    # nonexistent data dir refused, not minted
    missing = str(tmp_path / "nope")
    r = ctl("--data-dir", missing, "meta", "catalog", expect=1)
    assert "does not exist" in r.stderr and not os.path.exists(missing)
    # malformed backup commands fail loudly
    d = str(tmp_path / "d")
    os.makedirs(d)
    ctl("--data-dir", d, "backup", "restore", "1", expect=2)
    ctl("--data-dir", d, "backup", "delete", expect=2)
    ctl("--data-dir", d, "backup", "delete", "99", expect=1)


def test_project_set_generate_series_sql():
    """Set-returning generate_series in the SELECT list (ProjectSet,
    project_set.rs parity) over a RETRACTING upstream: the counts MV
    updates as bids arrive, so every count bump retracts the old
    expansion and re-emits 1..c — final rows must equal the oracle
    expansion of the final counts."""
    import numpy as np

    from risingwave_tpu.connectors.nexmark import NexmarkConfig, gen_bids

    n_events = 4000

    async def run():
        fe = Frontend(min_chunks=8)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            f"nexmark.table.type='bid', nexmark.event.num={n_events})")
        await fe.execute(
            "CREATE MATERIALIZED VIEW g AS SELECT auction AS a, "
            "count(*) AS c FROM bid GROUP BY auction")
        await fe.execute(
            "CREATE MATERIALIZED VIEW ps AS SELECT a, "
            "generate_series(1, c) AS s FROM g")
        for _ in range(20):
            await fe.step()
        rows = await fe.execute("SELECT a, s FROM ps")
        bad = await fe.execute("SELECT s FROM ps WHERE s > 100000")
        await fe.close()
        return rows, bad

    rows, bad = asyncio.run(run())
    cfg = NexmarkConfig(event_num=n_events)
    bids = gen_bids(np.arange(n_events * 46 // 50, dtype=np.int64),
                    cfg)
    counts = {}
    for a in bids["auction"].tolist():
        counts[a] = counts.get(a, 0) + 1
    want = {(a, s) for a, c in counts.items()
            for s in range(1, c + 1)}
    assert set(map(tuple, rows)) == want, (len(rows), len(want))
    assert len(rows) == len(want)        # no duplicate survivors
    assert max(c for c in counts.values()) > 1, \
        "test needs count bumps to exercise retraction"
    assert bad == []


def test_project_set_unnest_rejected():
    async def run():
        fe = Frontend()
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=100)")
        with pytest.raises(Exception, match="unnest"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW u AS SELECT "
                "unnest(auction) FROM bid")
        await fe.close()

    asyncio.run(run())


def test_project_set_duplicate_names_and_zero_step():
    """Two unaliased series columns must keep distinct data (the
    executor builds chunks positionally), and a literal zero step is
    rejected at plan time like the batch path."""
    async def run():
        fe = Frontend(min_chunks=4)
        await fe.execute(
            "CREATE SOURCE bid WITH (connector='nexmark', "
            "nexmark.table.type='bid', nexmark.event.num=500)")
        await fe.execute(
            "CREATE MATERIALIZED VIEW g AS SELECT auction AS a, "
            "count(*) AS c FROM bid GROUP BY auction")
        with pytest.raises(Exception, match="nonzero"):
            await fe.execute(
                "CREATE MATERIALIZED VIEW z AS SELECT a, "
                "generate_series(1, 10, 0) AS s FROM g")
        await fe.execute(
            "CREATE MATERIALIZED VIEW two AS SELECT a, "
            "generate_series(1, 2), generate_series(10, 13) FROM g")
        for _ in range(8):
            await fe.step()
        g = await fe.execute("SELECT a FROM g")
        rows = await fe.execute("SELECT * FROM two")
        await fe.close()
        return [r[:3] for r in rows], len(g)

    rows, n_groups = asyncio.run(run())
    want = set()
    for (a,) in set(map(tuple, [[r[0]] for r in rows])):
        want |= {(a, 1, 10), (a, 2, 11), (a, None, 12), (a, None, 13)}
    assert set(map(tuple, rows)) == want, rows[:6]
    assert len(rows) == 4 * n_groups


def test_create_table_dml_and_mv_chain():
    """CREATE TABLE + INSERT/DELETE/UPDATE flow through the DML
    channel into the barrier pipeline; an MV over the table sees every
    delta (dml_manager.rs + handler/create_table.rs parity)."""
    async def run():
        fe = Frontend()
        await fe.execute(
            "CREATE TABLE t (a bigint, b varchar, ts timestamp)")
        r = await fe.execute(
            "INSERT INTO t VALUES (1, 'x', '2024-01-01 00:00:00'), "
            "(2, 'y', null), (3, 'z', '2024-01-02 12:30:00')")
        assert r == "INSERT 0 3"
        rows = await fe.execute("SELECT a, b FROM t")
        assert sorted(rows) == [(1, "x"), (2, "y"), (3, "z")]
        ts = await fe.execute("SELECT ts FROM t WHERE a = 2")
        assert ts == [(None,)]
        assert await fe.execute("DELETE FROM t WHERE a = 2") == \
            "DELETE 1"
        assert await fe.execute(
            "UPDATE t SET b = 'w', a = a + 10 WHERE a > 1") == \
            "UPDATE 1"
        rows = await fe.execute("SELECT a, b FROM t")
        assert sorted(rows) == [(1, "x"), (13, "w")]
        await fe.execute(
            "CREATE MATERIALIZED VIEW m AS SELECT b, count(*) AS c "
            "FROM t GROUP BY b")
        await fe.execute("INSERT INTO t VALUES (5, 'w', null)")
        rows = await fe.execute("SELECT b, c FROM m")
        assert sorted(rows) == [("w", 2), ("x", 1)]
        assert await fe.execute("DELETE FROM t") == "DELETE 3"
        assert await fe.execute("SELECT b, c FROM m") == []
        assert await fe.execute("SHOW TABLES") == [("t",)]
        assert ("t",) not in await fe.execute(
            "SHOW MATERIALIZED VIEWS")
        with pytest.raises(Exception, match="depended on"):
            await fe.execute("DROP TABLE t")
        await fe.execute("DROP MATERIALIZED VIEW m")
        await fe.execute("DROP TABLE t")
        with pytest.raises(Exception, match="not a table|unknown"):
            await fe.execute("INSERT INTO t VALUES (1, 'x', null)")
        await fe.close()

    asyncio.run(run())


def test_table_primary_key_upsert_and_recovery():
    """A PRIMARY KEY table keys its state by that column (same-pk
    insert overwrites); committed table rows survive a session crash
    and the recovered table accepts further DML with fresh row ids."""
    from risingwave_tpu.storage.hummock import HummockLite
    from risingwave_tpu.storage.object_store import MemObjectStore

    obj = MemObjectStore()

    async def phase1():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.execute(
            "CREATE TABLE kv (k bigint PRIMARY KEY, v varchar)")
        await fe.execute(
            "INSERT INTO kv VALUES (1, 'a'), (2, 'b')")
        await fe.execute("INSERT INTO kv VALUES (1, 'a2')")  # upsert
        rows = await fe.execute("SELECT k, v FROM kv")
        assert sorted(rows) == [(1, "a2"), (2, "b")]
        await fe.execute("CREATE TABLE log (msg varchar)")   # _row_id
        await fe.execute("INSERT INTO log VALUES ('m1'), ('m1')")
        assert len(await fe.execute("SELECT msg FROM log")) == 2
        # crash: NO close, no goodbye

    async def phase2():
        fe = Frontend(HummockLite(obj), min_chunks=4)
        await fe.recover()
        rows = await fe.execute("SELECT k, v FROM kv")
        assert sorted(rows) == [(1, "a2"), (2, "b")]
        await fe.execute("INSERT INTO log VALUES ('m2')")
        assert len(await fe.execute("SELECT msg FROM log")) == 3
        assert await fe.execute("DELETE FROM kv WHERE k = 1") == \
            "DELETE 1"
        assert await fe.execute("SELECT k FROM kv") == [(2,)]
        await fe.close()

    asyncio.run(phase1())
    asyncio.run(phase2())


def test_table_dml_guards():
    """The review repros: DROP MATERIALIZED VIEW on a table is
    refused, SET on the hidden _row_id is refused, and an UPDATE
    collapsing two rows onto one primary key fails the statement
    instead of killing the table's actor."""
    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE t (a bigint)")
        await fe.execute("INSERT INTO t VALUES (1), (2)")
        with pytest.raises(Exception, match="use DROP TABLE"):
            await fe.execute("DROP MATERIALIZED VIEW t")
        with pytest.raises(Exception, match="_row_id.*not found"):
            await fe.execute("UPDATE t SET _row_id = 0")
        await fe.execute(
            "CREATE TABLE kv (k bigint PRIMARY KEY, v bigint)")
        await fe.execute("INSERT INTO kv VALUES (1, 10), (2, 20)")
        with pytest.raises(Exception, match="more than one row"):
            await fe.execute("UPDATE kv SET k = 9")
        # the failed statements left the pipeline healthy
        await fe.execute("INSERT INTO kv VALUES (3, 30)")
        assert len(await fe.execute("SELECT k FROM kv")) == 3
        assert sorted(await fe.execute("SELECT a FROM t")) == \
            [(1,), (2,)]
        await fe.close()

    asyncio.run(run())


def test_insert_select():
    """INSERT INTO t SELECT … batch-evaluates over the committed
    snapshot (insert.rs analog), with column-wise coercion."""
    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE src (a bigint, b varchar)")
        await fe.execute(
            "INSERT INTO src VALUES (1, 'x'), (2, 'y'), (3, 'z')")
        await fe.execute("CREATE TABLE dst (a bigint, b varchar)")
        r = await fe.execute(
            "INSERT INTO dst SELECT a + 10, b FROM src WHERE a > 1")
        assert r == "INSERT 0 2"
        rows = await fe.execute("SELECT a, b FROM dst")
        assert sorted(rows) == [(12, "y"), (13, "z")]
        # self-insert reads the snapshot, not the in-flight writes
        r = await fe.execute("INSERT INTO src SELECT a, b FROM src")
        assert r == "INSERT 0 3"
        assert len(await fe.execute("SELECT a FROM src")) == 6
        r = await fe.execute(
            "INSERT INTO dst SELECT a, b FROM src WHERE a > 999")
        assert r == "INSERT 0 0"
        with pytest.raises(Exception, match="columns"):
            await fe.execute("INSERT INTO dst SELECT a FROM src")
        await fe.close()

    asyncio.run(run())


def test_insert_select_duplicate_output_names():
    """Duplicate SELECT output names must keep distinct data through
    the cast path (positional chunk build, not name-keyed)."""
    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE src (a bigint, b bigint)")
        await fe.execute("INSERT INTO src VALUES (1, 100), (2, 200)")
        await fe.execute("CREATE TABLE dst (x varchar, y bigint)")
        r = await fe.execute(
            "INSERT INTO dst SELECT a, b AS a FROM src")
        assert r == "INSERT 0 2"
        rows = sorted(await fe.execute("SELECT x, y FROM dst"))
        assert rows == [("1", 100), ("2", 200)], rows
        await fe.close()

    asyncio.run(run())


def test_table_decimal_roundtrip():
    """DECIMAL values survive every DML path unscaled (the physical
    scaled-int64 representation must never leak into or out of the
    DML channel): VALUES, INSERT SELECT with coercion, UPDATE, DELETE
    by value, and MV aggregation over the table."""
    from decimal import Decimal

    async def run():
        fe = Frontend()
        await fe.execute("CREATE TABLE t (d numeric, tag varchar)")
        await fe.execute(
            "INSERT INTO t VALUES (1.5, 'a'), (2.25, 'b')")
        rows = sorted(await fe.execute("SELECT d, tag FROM t"))
        assert rows == [(Decimal("1.5"), "a"),
                        (Decimal("2.25"), "b")], rows
        # coercing sibling column must not truncate the decimal
        await fe.execute("CREATE TABLE t2 (d numeric, n varchar)")
        await fe.execute("INSERT INTO t2 SELECT d, 7 FROM t")
        rows = sorted(await fe.execute("SELECT d, n FROM t2"))
        assert rows == [(Decimal("1.5"), "7"),
                        (Decimal("2.25"), "7")], rows
        # cast INTO numeric from bigint: scaled exactly once
        await fe.execute(
            "INSERT INTO t SELECT CAST(3 AS BIGINT), n FROM t2 "
            "WHERE d > 2")
        assert (Decimal("3"), "7") in await fe.execute(
            "SELECT d, tag FROM t")
        assert await fe.execute(
            "UPDATE t SET d = d + 1 WHERE tag = 'a'") == "UPDATE 1"
        assert (Decimal("2.5"), "a") in await fe.execute(
            "SELECT d, tag FROM t")
        assert await fe.execute(
            "DELETE FROM t WHERE d = 2.25") == "DELETE 1"
        s = await fe.execute("SELECT sum(d) AS s FROM t")
        assert s == [(Decimal("5.5"),)], s
        await fe.close()

    asyncio.run(run())
