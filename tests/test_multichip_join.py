"""Vnode-sharded join matcher over the 8-device virtual mesh ==
single-chip kernel results (the q8 analog of test_multichip_agg)."""

from collections import defaultdict

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

from risingwave_tpu.ops import lanes
from risingwave_tpu.ops.hash_join import JoinSideKernel
from risingwave_tpu.parallel.join import ShardedJoinKernel


def test_sharded_join_matches_single_chip(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    sharded = ShardedJoinKernel(mesh, key_width=2, key_capacity=1 << 10,
                              row_capacity=1 << 10,
                              probe_capacity=1 << 10)
    single = JoinSideKernel(key_width=2)

    rng = np.random.default_rng(11)
    next_ref = 0
    for _round in range(3):
        n = 64
        keys = rng.integers(0, 23, n).astype(np.int64) * 5_000_000_017
        hi, lo = lanes.split_i64(keys)
        kl = np.stack([hi, lo], axis=1)
        refs = np.arange(next_ref, next_ref + n, dtype=np.int32)
        next_ref += n
        vis = rng.random(n) > 0.15
        sharded.insert(kl, refs, vis)
        single.insert(jnp.asarray(kl), refs, jnp.asarray(vis))

        pk = rng.integers(0, 30, 64).astype(np.int64) * 5_000_000_017
        phi, plo = lanes.split_i64(pk)
        pkl = np.stack([phi, plo], axis=1)
        pvis = np.ones(64, dtype=bool)
        _gdeg, gp, gr = sharded.probe(pkl, pvis)
        deg, sp, sr = single.probe(jnp.asarray(pkl), jnp.asarray(pvis))

        got = defaultdict(set)
        for p, r in zip(gp.tolist(), gr.tolist()):
            got[p].add(r)
        want = defaultdict(set)
        for p, r in zip(sp.tolist(), sr.tolist()):
            want[p].add(r)
        assert got == want
        assert sum(len(v) for v in got.values()) == int(deg.sum())


def test_sharded_join_state_is_sharded(eight_devices):
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    s = ShardedJoinKernel(mesh, key_width=2, key_capacity=1 << 10)
    specs = {str(a.sharding.spec) for a in
             [s.table.keys, s.chains.head, s.chains.next]}
    assert all("'d'" in x for x in specs), specs


def test_sharded_join_recurring_keys_do_not_trip_guard(eight_devices):
    """Keys recurring across many batches must NOT hit the capacity
    guard: the bound collapses to true occupancy on overflow."""
    mesh = Mesh(np.asarray(eight_devices), ("d",))
    s = ShardedJoinKernel(mesh, key_width=2, key_capacity=256,
                        row_capacity=1 << 14)
    ref = 0
    for _ in range(40):                  # 40*64 rows, only 10 keys
        keys = (np.arange(64, dtype=np.int64) % 10) * 999_999_937
        hi, lo = lanes.split_i64(keys)
        kl = np.stack([hi, lo], axis=1)
        refs = np.arange(ref, ref + 64, dtype=np.int32)
        ref += 64
        s.insert(kl, refs, np.ones(64, dtype=bool))
    _d, gp, _gr = s.probe(kl, np.ones(64, dtype=bool))
    assert len(gp) > 0
