"""HashJoin: device matcher vs dict oracle; executor vs a changelog oracle.

Mirrors the inner-join cases of the reference's hash_join tests
(src/stream/src/executor/hash_join.rs test mod): scripted chunks on both
sides through barrier alignment, emitted changelog asserted against a
recomputed join, including retractions and N:M matches.
"""

import asyncio
from collections import Counter, defaultdict

import jax.numpy as jnp
import numpy as np
import pytest

from risingwave_tpu.common.chunk import Op, StreamChunk
from risingwave_tpu.common.epoch import Epoch, EpochPair
from risingwave_tpu.common.types import DataType, Schema
from risingwave_tpu.ops.hash_join import JoinSideKernel
from risingwave_tpu.state.state_table import StateTable
from risingwave_tpu.state.store import MemoryStateStore
from risingwave_tpu.stream.executors.hash_join import HashJoinExecutor
from risingwave_tpu.stream.executors.test_utils import (
    MockSource, collect_until_n_barriers,
)
from risingwave_tpu.stream.message import Barrier, BarrierKind, is_chunk

L_SCHEMA = Schema.of(lk=DataType.INT64, lv=DataType.INT64)
R_SCHEMA = Schema.of(rk=DataType.INT64, rv=DataType.VARCHAR)


def barrier(n: int) -> Barrier:
    prev = Epoch.from_physical(n - 1) if n > 1 else Epoch.INVALID
    return Barrier(EpochPair(Epoch.from_physical(n), prev),
                   BarrierKind.CHECKPOINT)


# -- kernel-level oracle ------------------------------------------------


def test_join_kernel_chains_and_probe():
    k = JoinSideKernel(key_width=1)
    keys = jnp.asarray([[5], [5], [7], [5]], dtype=jnp.int32)
    refs = np.asarray([0, 1, 2, 3], dtype=np.int32)
    k.insert(keys, refs, jnp.ones(4, dtype=bool))
    deg, _pidx, _prefs = k.probe(
        jnp.asarray([[5], [7], [9]], dtype=jnp.int32),
        jnp.ones(3, dtype=bool))
    assert deg.tolist() == [3, 1, 0]
    # tombstone one of the key-5 rows
    k.delete(np.asarray([1], dtype=np.int32), jnp.ones(1, dtype=bool))
    deg, _pidx, _prefs = k.probe(
        jnp.asarray([[5]], dtype=jnp.int32), jnp.ones(1, dtype=bool))
    assert deg.tolist() == [2]


def test_join_kernel_random_oracle():
    rng = np.random.default_rng(9)
    k = JoinSideKernel(key_width=1)
    oracle = defaultdict(set)       # key → set of live refs
    ref_of_row = {}
    next_ref = 0
    for _round in range(6):
        n = 64
        keys = rng.integers(0, 12, n).astype(np.int32).reshape(-1, 1)
        ins_mask = np.ones(n, dtype=bool)
        refs = np.arange(next_ref, next_ref + n, dtype=np.int32)
        next_ref += n
        k.insert(jnp.asarray(keys), refs, jnp.asarray(ins_mask))
        for i in range(n):
            oracle[int(keys[i, 0])].add(int(refs[i]))
            ref_of_row[int(refs[i])] = int(keys[i, 0])
        # random deletes
        live = [r for s in oracle.values() for r in s]
        kill = rng.choice(live, size=min(20, len(live)), replace=False)
        k.delete(np.asarray(kill, dtype=np.int32),
                 jnp.ones(len(kill), dtype=bool))
        for r in kill:
            oracle[ref_of_row[int(r)]].discard(int(r))
        probe_keys = np.arange(14, dtype=np.int32).reshape(-1, 1)
        deg, pidx, prefs = k.probe(jnp.asarray(probe_keys),
                                   jnp.ones(14, dtype=bool))
        assert deg.tolist() == [len(oracle[int(q)]) for q in range(14)]
        got = defaultdict(set)
        for p, r in zip(pidx.tolist(), prefs.tolist()):
            got[int(probe_keys[p, 0])].add(r)
        for q in range(14):
            assert got[q] == oracle[q], f"key {q}"


# -- executor-level oracle ----------------------------------------------


class JoinOracle:
    """Maintains both sides + the expected inner-join multiset."""

    def __init__(self):
        self.left = []     # (lk, lv)
        self.right = []    # (rk, rv)

    def view(self) -> Counter:
        out = Counter()
        for lk, lv in self.left:
            if lk is None:
                continue
            for rk, rv in self.right:
                if rk == lk:
                    out[(lk, lv, rk, rv)] += 1
        return out


def materialize_join(msgs) -> Counter:
    view = Counter()
    for m in msgs:
        if not is_chunk(m):
            continue
        for op, row in m.to_records():
            if op.is_insert:
                view[row] += 1
            else:
                view[row] -= 1
                assert view[row] >= 0, f"negative count for {row}"
    return +view


def run_join(script_l, script_r, n_barriers):
    store = MemoryStateStore()
    lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, script_l), MockSource(R_SCHEMA, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)
    msgs = asyncio.run(collect_until_n_barriers(ex, n_barriers))
    return msgs, (lt, rt, store)


def lchunk(ks, vs, ops=None):
    return StreamChunk.from_pydict(L_SCHEMA, {"lk": ks, "lv": vs}, ops=ops)


def rchunk(ks, vs, ops=None):
    return StreamChunk.from_pydict(R_SCHEMA, {"rk": ks, "rv": vs}, ops=ops)


def test_inner_join_basic_both_sides():
    script_l = [barrier(1), lchunk([1, 2], [10, 20]), barrier(2),
                lchunk([1], [11]), barrier(3)]
    script_r = [barrier(1), rchunk([1, 3], ["a", "c"]), barrier(2),
                rchunk([2], ["b"]), barrier(3)]
    msgs, _ = run_join(script_l, script_r, 3)
    oracle = JoinOracle()
    oracle.left = [(1, 10), (2, 20), (1, 11)]
    oracle.right = [(1, "a"), (3, "c"), (2, "b")]
    assert materialize_join(msgs) == oracle.view()


def test_inner_join_retraction():
    script_l = [barrier(1), lchunk([1, 1], [10, 11]), barrier(2),
                lchunk([1], [10], ops=[Op.DELETE]), barrier(3)]
    script_r = [barrier(1), rchunk([1], ["a"]), barrier(2),
                rchunk([], []), barrier(3)]
    msgs, _ = run_join(script_l, script_r, 3)
    view = materialize_join(msgs)
    assert view == Counter({(1, 11, 1, "a"): 1})


def test_inner_join_null_keys_never_match():
    script_l = [barrier(1),
                StreamChunk.from_pydict(
                    L_SCHEMA, {"lk": [None, 1], "lv": [1, 2]}),
                barrier(2)]
    script_r = [barrier(1),
                StreamChunk.from_pydict(
                    R_SCHEMA, {"rk": [None, 1], "rv": ["x", "y"]}),
                barrier(2)]
    msgs, _ = run_join(script_l, script_r, 2)
    assert materialize_join(msgs) == Counter({(1, 2, 1, "y"): 1})


def test_inner_join_random_stream_oracle():
    rng = np.random.default_rng(17)
    oracle = JoinOracle()
    script_l, script_r = [barrier(1)], [barrier(1)]
    b = 2
    lpk, rpk = 0, 0
    for _ in range(6):
        # left chunk: inserts + deletes of existing rows
        ks, vs, ops = [], [], []
        for _ in range(24):
            if oracle.left and rng.random() < 0.35:
                i = int(rng.integers(0, len(oracle.left)))
                k_, v_ = oracle.left.pop(i)
                ks.append(k_)
                vs.append(v_)
                ops.append(Op.DELETE)
            else:
                k_, v_ = int(rng.integers(0, 8)), lpk
                lpk += 1
                oracle.left.append((k_, v_))
                ks.append(k_)
                vs.append(v_)
                ops.append(Op.INSERT)
        script_l.append(lchunk(ks, vs, ops=ops))
        ks, vs, ops = [], [], []
        for _ in range(16):
            if oracle.right and rng.random() < 0.35:
                i = int(rng.integers(0, len(oracle.right)))
                k_, v_ = oracle.right.pop(i)
                ks.append(k_)
                vs.append(v_)
                ops.append(Op.DELETE)
            else:
                k_, v_ = int(rng.integers(0, 8)), f"r{rpk}"
                rpk += 1
                oracle.right.append((k_, v_))
                ks.append(k_)
                vs.append(v_)
                ops.append(Op.INSERT)
        script_r.append(rchunk(ks, vs, ops=ops))
        script_l.append(barrier(b))
        script_r.append(barrier(b))
        b += 1
    msgs, _ = run_join(script_l, script_r, b - 1)
    assert materialize_join(msgs) == oracle.view()


def test_inner_join_update_pair_same_pk_one_chunk():
    """An update pair [U-, U+] sharing a pk inside ONE chunk must
    retract the old row and register the new one (regression: inserts
    applied before deletes corrupted the pk→ref map)."""
    script_l = [barrier(1), lchunk([1], [10]), barrier(2),
                lchunk([1, 2], [10, 10],
                       ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]),
                barrier(3),
                # post-update probes: key 1 must be gone, key 2 must hit
                lchunk([], []), barrier(4)]
    script_r = [barrier(1), rchunk([1], ["a"]), barrier(2),
                rchunk([], []), barrier(3),
                rchunk([1, 2], ["a2", "b2"]), barrier(4)]
    msgs, _ = run_join(script_l, script_r, 4)
    assert materialize_join(msgs) == Counter({(2, 10, 2, "b2"): 1})


def test_join_forwards_key_watermarks_and_expires_state():
    """hash_join.rs:860-945: join-key watermarks forward as the min
    across sides (for BOTH output key columns) and expire stored rows
    below the combined watermark at the barrier."""
    from risingwave_tpu.stream.message import Watermark, is_watermark

    wm = lambda v: Watermark(0, DataType.INT64, v)  # noqa: E731
    script_l = [barrier(1),
                lchunk([1, 5, 9], [10, 50, 90]), wm(6),
                barrier(2), barrier(3)]
    script_r = [barrier(1),
                rchunk([1, 5, 9], ["a", "e", "i"]), wm(8),
                barrier(2), barrier(3)]
    msgs, (lt, rt, _store) = run_join(script_l, script_r, 3)
    wms = [m for m in msgs if is_watermark(m)]
    # combined = min(6, 8) = 6, emitted for left col 0 and right col 2
    assert {(m.col_idx, m.value) for m in wms} == {(0, 6), (2, 6)}
    # rows with key < 6 expired from both state tables at the barrier
    assert sorted(r[0] for _pk, r in lt.iter_rows()) == [9]
    assert sorted(r[0] for _pk, r in rt.iter_rows()) == [9]
    # ...and from the device matcher: a new left probe for key 1 or 5
    # finds nothing, key 9 still matches
    # (watermark semantics: those keys can no longer arrive; this just
    # verifies the matcher state is really gone)


def test_join_expiry_then_survivor_still_matches():
    from risingwave_tpu.stream.message import Watermark

    wm = lambda v: Watermark(0, DataType.INT64, v)  # noqa: E731
    script_l = [barrier(1), lchunk([1, 9], [10, 90]), wm(9),
                barrier(2),
                lchunk([9], [91]),   # second row for surviving key
                barrier(3)]
    script_r = [barrier(1), rchunk([1, 9], ["a", "i"]), wm(9),
                barrier(2), barrier(3)]
    msgs, _tables = run_join(script_l, script_r, 3)
    got = materialize_join(msgs)
    # key 1 joined before expiry (epoch 2 emission), key 9 both rows
    assert got == Counter({(1, 10, 1, "a"): 1, (9, 90, 9, "i"): 1,
                           (9, 91, 9, "i"): 1})


def test_join_compaction_reclaims_dead_refs(monkeypatch):
    """Update churn leaves dead refs; the barrier-time compaction must
    reclaim them without changing join results."""
    from risingwave_tpu.stream.executors.hash_join import _JoinSide
    monkeypatch.setattr(_JoinSide, "COMPACT_MIN_REFS", 8)
    script_l, script_r = [barrier(1)], [barrier(1)]
    script_l.append(lchunk([0], [5]))
    script_r.append(rchunk([3], ["z"]))
    b = 2
    k_cur = 0
    for _ in range(20):   # 20 update pairs → 21 refs, ≥10 dead
        script_l.append(barrier(b))
        script_r.append(barrier(b))
        b += 1
        k_new = (k_cur + 1) % 4
        script_l.append(lchunk([k_cur, k_new], [5, 5],
                               ops=[Op.UPDATE_DELETE, Op.UPDATE_INSERT]))
        k_cur = k_new
    script_l.append(barrier(b))
    script_r.append(barrier(b))
    store = MemoryStateStore()
    lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
    rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(L_SCHEMA, script_l), MockSource(R_SCHEMA, script_r),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)
    msgs = asyncio.run(collect_until_n_barriers(ex, b))
    view = materialize_join(msgs)
    expect = Counter({(k_cur, 5, 3, "z"): 1}) if k_cur == 3 else Counter()
    assert view == expect
    left = ex.sides[0]
    # 21 refs were allocated over the run; compaction must have rebuilt
    # to ~1 live row (plus post-compaction churn), not 21
    assert left.next_ref < 21
    assert len(left.free) < left.next_ref


def test_join_recovery_resumes():
    store = MemoryStateStore()

    def build(sl, sr):
        lt = StateTable(21, L_SCHEMA, [1], store, dist_key_indices=[])
        rt = StateTable(22, R_SCHEMA, [1], store, dist_key_indices=[])
        return HashJoinExecutor(
            MockSource(L_SCHEMA, sl), MockSource(R_SCHEMA, sr),
            left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)

    ex1 = build([barrier(1), lchunk([1], [10]), barrier(2)],
                [barrier(1), rchunk([1], ["a"]), barrier(2)])
    asyncio.run(collect_until_n_barriers(ex1, 2))
    # restart: right side gets a new matching row — the recovered left
    # row must produce the match
    ex2 = build([barrier(3), barrier(4)],
                [barrier(3), rchunk([1], ["b"]), barrier(4)])
    msgs = asyncio.run(collect_until_n_barriers(ex2, 2))
    assert materialize_join(msgs) == Counter({(1, 10, 1, "b"): 1})


def test_probe_pair_buffer_overflow_retries():
    """probe_capacity=1 forces the pair-buffer double/retry path."""
    k = JoinSideKernel(key_width=1, probe_capacity=1)
    keys = jnp.asarray([[3]] * 9 + [[4]] * 7, dtype=jnp.int32)
    refs = np.arange(16, dtype=np.int32)
    k.insert(keys, refs, jnp.ones(16, dtype=bool))
    deg, pidx, prefs = k.probe(
        jnp.asarray([[3], [4], [5]], dtype=jnp.int32),
        jnp.ones(3, dtype=bool))
    assert deg.tolist() == [9, 7, 0]
    assert k._probe_cap >= 16
    assert {int(r) for p, r in zip(pidx, prefs) if p == 0} == set(range(9))
    assert {int(r) for p, r in zip(pidx, prefs) if p == 1} == \
        set(range(9, 16))


def test_varchar_join_keys_exact_equality():
    """Varchar join keys through the SHARED interning codec (VERDICT r2
    #5): equal strings match across sides, distinct strings never merge,
    NULL keys never match, recovery reintern-rebuilds."""
    S_L = Schema.of(name=DataType.VARCHAR, lv=DataType.INT64)
    S_R = Schema.of(rname=DataType.VARCHAR, rv=DataType.INT64)

    def lc(names, vs, ops=None):
        return StreamChunk.from_pydict(S_L, {"name": names, "lv": vs},
                                       ops=ops)

    def rc(names, vs, ops=None):
        return StreamChunk.from_pydict(S_R, {"rname": names, "rv": vs},
                                       ops=ops)

    store = MemoryStateStore()
    lt = StateTable(41, S_L, [1], store, dist_key_indices=[])
    rt = StateTable(42, S_R, [1], store, dist_key_indices=[])
    ex = HashJoinExecutor(
        MockSource(S_L, [barrier(1),
                         lc(["apple", "pear", None, "plum"],
                            [1, 2, 3, 4]),
                         barrier(2)]),
        MockSource(S_R, [barrier(1),
                         rc(["pear", "apple", "apple", None],
                            [10, 20, 21, 30]),
                         barrier(2)]),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)
    msgs = asyncio.run(collect_until_n_barriers(ex, 2))
    got = Counter(tuple(r) for m in msgs if is_chunk(m)
                  for _op, r in m.to_records())
    assert got == Counter({("apple", 1, "apple", 20): 1,
                           ("apple", 1, "apple", 21): 1,
                           ("pear", 2, "pear", 10): 1})

    # recovery: fresh executor over the same tables, new rows still join
    ex2 = HashJoinExecutor(
        MockSource(S_L, [barrier(3), lc(["apple"], [5]), barrier(4)]),
        MockSource(S_R, [barrier(3), barrier(4)]),
        left_keys=[0], right_keys=[0], left_table=lt, right_table=rt)
    msgs2 = asyncio.run(collect_until_n_barriers(ex2, 2))
    got2 = Counter(tuple(r) for m in msgs2 if is_chunk(m)
                   for _op, r in m.to_records())
    assert got2 == Counter({("apple", 5, "apple", 20): 1,
                            ("apple", 5, "apple", 21): 1})
